"""Deterministic fault injection for the resilience layer.

Production grids die in ways unit asserts never exercise: a node loss
mid-checkpoint leaves a torn file, a flaky disk flips a payload bit, a
too-large dispatch hits XLA ``RESOURCE_EXHAUSTED``, a probe into a dead
device tunnel hangs forever, and a numerical blow-up writes NaN into a
field with nobody watching. This module makes every one of those
failures reproducible on demand so the recovery paths in
:mod:`dccrg_tpu.resilience` are *tested*, not hoped for.

A :class:`FaultPlan` is a seedable, deterministic schedule of faults,
installed as the process-wide active plan via context manager::

    plan = FaultPlan(seed=7)
    plan.io_error(times=1)                   # first checkpoint write fails
    plan.nan_poison("density", step=13)      # NaN lands after step 13
    plan.resource_exhausted(times=1)         # first step dispatch OOMs
    with plan:
        runner.run(50)
    assert plan.fired("step.poison")

Instrumented call sites (in resilience.py / checkpoint.py) consult the
active plan through the module hooks:

- :func:`fire` — raise a scheduled exception at a named site
  (``checkpoint.write`` transient I/O errors, ``checkpoint.chunk``
  mid-stream write failures — delta saves stream through the same
  site, so a torn delta write is the same rule, ``checkpoint.mp``
  two-phase multi-process save phases incl.
  :meth:`~FaultPlan.rank_death` — delta saves commit through the same
  phases, ``checkpoint.gc`` retention-GC unlinks
  (:meth:`~FaultPlan.gc_error`), ``step.dispatch`` simulated
  ``RESOURCE_EXHAUSTED``, ``device.probe`` hung-probe timeouts,
  ``coord.barrier`` / ``coord.init`` coordination faults).
- :func:`take_delta_parent_corrupt` — non-raising query the delta
  save uses to land a corrupted parent digest in a delta sidecar
  (:meth:`~FaultPlan.delta_parent_corrupt`), so chain verification
  and prefix-fallback resume are what get exercised.
- :func:`take_barrier_hang` — non-raising query coord.barrier uses to
  turn a scheduled :meth:`~FaultPlan.barrier_hang` into a simulated
  lost-rank hang inside its watchdog thread.
- ``amr.propose`` / ``amr.resolve`` / ``amr.install`` (phases
  ``prepare`` / ``commit``) — the distributed-AMR commit's named fault
  points (dccrg_tpu/distamr.py), one per protocol phase. Three
  variants: :meth:`~FaultPlan.amr_error` raises at the phase (the
  cross-rank transaction must roll this rank back bitwise and post the
  abort marker its peers fast-abort on), :meth:`~FaultPlan.amr_hang`
  stalls the rank inside the phase (queried via :func:`take_amr_hang`
  — the SIGSTOP-zombie / wedged-KV class; peers' deadline-bounded
  collects must abort typed, never block), and
  :meth:`~FaultPlan.amr_torn_record` makes the rank store its sealed
  proposal with a corrupted tail (queried via
  :func:`take_torn_record`; readers must convict it as
  :class:`~dccrg_tpu.coord.TornRecordError`).
  :meth:`~FaultPlan.rank_death` at the same sites kills the rank
  mid-phase (the mp harness maps it to a real ``kill -9``).
- :func:`take_preempt` / :func:`take_step_hang` — non-raising queries
  the run-supervision layer (:mod:`dccrg_tpu.supervise`) uses to turn
  a scheduled :meth:`~FaultPlan.preempt_signal` into a delivered
  preemption flag at a step boundary, and a
  :meth:`~FaultPlan.step_hang` into a wedged dispatch inside the step
  watchdog's worker thread. ``supervise.dispatch`` fires transient
  :class:`InjectedDispatchError` the supervisor must retry through.
- :func:`corrupt_file` — mutate a file that was just written
  (truncation / torn tail, single bit flips), simulating post-write
  disk corruption the CRC sidecar must catch.
- :func:`poison_step` — write NaN into a field after a given step,
  the silent numerics failure the watchdog must trip on.
- :func:`flip_step` / :func:`flip_fleet` — land a FINITE bit-flip
  (:meth:`~FaultPlan.silent_flip`) in a field / a fleet batch slot:
  the silent-data-corruption class, deliberately invisible to the
  finiteness watchdog — only the integrity layer
  (:mod:`dccrg_tpu.integrity`) can convict it.

When no plan is installed every hook is a no-op, so the hooks cost one
``is None`` check on hot paths. All randomness (which byte to flip)
comes from the plan's seeded generator — two runs with the same seed
inject byte-identical faults. The standalone helpers
(:func:`flip_bit`, :func:`truncate_file`) are also used directly by
the checkpoint-integrity tests.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field

import numpy as np


class SimulatedResourceExhausted(RuntimeError):
    """Injected stand-in for an XLA device OOM. The message carries the
    literal ``RESOURCE_EXHAUSTED`` marker so handlers that match real
    XlaRuntimeError text treat both identically."""

    def __init__(self, detail: str = ""):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected device OOM {detail}".rstrip()
        )


class InjectedIOError(OSError):
    """Injected transient I/O failure (checkpoint writes)."""


class InjectedProbeHang(TimeoutError):
    """Injected device-probe timeout (a dead accelerator tunnel)."""


class InjectedDispatchError(RuntimeError):
    """Injected TRANSIENT step-dispatch failure — the ``UNAVAILABLE`` /
    ``DEADLINE_EXCEEDED`` class of XLA runtime errors a flaky
    host-to-accelerator link produces. The message carries the literal
    ``UNAVAILABLE`` marker so handlers that match real XlaRuntimeError
    text treat both identically; the supervision layer must retry it
    with backoff instead of tripping a rollback."""

    def __init__(self, detail: str = ""):
        super().__init__(
            f"UNAVAILABLE: injected transient dispatch error {detail}".rstrip()
        )


class InjectedMutationError(RuntimeError):
    """Injected failure inside a structural mutation (AMR commit, load
    balance, plan rebuild). The transactional layer in txn.py must
    catch it, roll the grid back to the pre-mutation snapshot and
    re-raise as MutationAbortedError — the atomicity tests pin that."""


class InjectedRankDeath(RuntimeError):
    """Injected death of this rank at an instrumented multi-process
    point (the two-phase checkpoint phases, coord barriers). The faked
    test harness catches it at the per-rank pass boundary and asserts
    the surviving protocol state (old checkpoint intact, commit
    aborted); the REAL harness (tests/mp_harness.py) lets it propagate
    out of the child's main and exits the OS process — an actual dead
    rank, whose peers must then hit their barrier timeouts."""


@dataclass
class _Rule:
    site: str
    kind: str
    times: float  # math.inf = every time
    params: dict = field(default_factory=dict)
    fired: int = 0

    def matches(self, site: str, ctx: dict) -> bool:
        if self.site != site or self.fired >= self.times:
            return False
        for key in ("mode", "step", "phase", "tag", "rank", "job",
                    "tick", "key", "op"):
            want = self.params.get(key)
            if want is None:
                continue
            have = ctx.get(key)
            if key == "tag":
                # barrier tags carry protocol suffixes (the two-phase
                # save appends `#<attempt>`): a rule tag is a PREFIX
                if not (isinstance(have, str) and have.startswith(want)):
                    return False
            elif have != want:
                return False
        return True


# Canonical (site, phase) fault points of the transactional mutation
# paths, grouped by the mutation that reaches them — THE single table
# the fuzzer (fuzz._FAULT_SITES) and the per-point atomicity tests
# (tests/test_txn.py) both consume, so a newly instrumented
# ``fire(site, phase=...)`` call only needs registering here to be
# exercised everywhere.
MUTATION_FAULT_SITES = {
    "adapt": (
        ("adapt.commit", "resolve"), ("adapt.commit", "resolved"),
        ("adapt.commit", "preserved"), ("adapt.resolve", "pins"),
        ("grid.restructure", "planned"), ("grid.restructure", "moved"),
        ("hybrid.recommit", "classified"), ("hybrid.recommit", "cached"),
        ("hybrid.recommit", "tables"),
    ),
    "balance": (
        ("partition.compute", None), ("balance.commit", "partition"),
        ("balance.commit", "stage"), ("balance.commit", "finish"),
        ("balance.commit", "land"), ("grid.restructure", "planned"),
        ("grid.restructure", "moved"),
        # a balance on a REFINED grid rebuilds through the hybrid
        # builder too — its fault points are reachable from both paths
        ("hybrid.recommit", "classified"), ("hybrid.recommit", "cached"),
        ("hybrid.recommit", "tables"),
    ),
}

# Canonical (site, phase) fault points of the DISTRIBUTED AMR commit
# (dccrg_tpu/distamr.py), one per protocol phase — consumed by the
# distributed fuzz leg (fuzz.distributed_amr_case) and
# tests/test_distamr.py. Deliberately NOT in MUTATION_FAULT_SITES:
# these fire only when an AmrCommitGroup drives the commit, so the
# single-grid fuzzer would wait forever for them.
DIST_AMR_FAULT_SITES = (
    ("amr.propose", None),
    ("amr.resolve", None),
    ("amr.install", "prepare"),
    ("amr.install", "commit"),
)

# streaming-intake fault sites (dccrg_tpu/intake.py): the spool
# submission/scan/read points plus the claim->add exactly-once
# admission window. Fire only when a StreamIntake drives admission,
# so — like DIST_AMR_FAULT_SITES — they are deliberately NOT in
# MUTATION_FAULT_SITES (the single-grid fuzzer would wait forever).
INTAKE_FAULT_SITES = (
    ("intake.spool.write.torn", None),
    ("intake.spool.rename.torn", None),
    ("intake.spool.scan", None),
    ("intake.spool.read", None),
    ("intake.claim", None),
)

# warm-start cache fault sites (dccrg_tpu/warmstart.py): the persisted
# compile-cache manifest's torn/corrupt/stale-version write faults,
# cache-dir I/O errors and a rank death mid-prewarm. Each must degrade
# to a COLD compile with a typed error + quarantined entry — never a
# wrong program. Fire only when a WarmPool drives a cache, so — like
# DIST_AMR_FAULT_SITES / INTAKE_FAULT_SITES — they are deliberately
# NOT in MUTATION_FAULT_SITES (the single-grid fuzzer would wait
# forever for them).
WARMSTART_FAULT_SITES = (
    ("warm.manifest.write.torn", None),
    ("warm.manifest.write.corrupt", None),
    ("warm.manifest.write.stale", None),
    ("warm.cache.io", None),
    ("warm.prewarm", None),
)

_active: "FaultPlan | None" = None


class FaultPlan:
    """A deterministic, seedable schedule of injected faults.

    Rules are added with the ``*_`` convenience methods below and fire
    at the instrumented sites while the plan is installed (``with
    plan:``). Each rule fires at most ``times`` times (default once);
    ``times=math.inf`` fires forever. ``plan.log`` records every
    firing as ``(site, kind, detail)`` for test assertions."""

    def __init__(self, seed: int = 0):
        self.rng = np.random.default_rng(seed)
        self.rules: list[_Rule] = []
        self.log: list[tuple[str, str, dict]] = []

    # -- schedule builders --------------------------------------------

    def _add(self, site, kind, times, **params):
        self.rules.append(_Rule(site, kind, times, params))
        return self

    def io_error(self, times=1, site="checkpoint.write", phase=None,
                 rank=None):
        """Transient I/O error during a checkpoint write (before the
        atomic rename — the previous checkpoint must survive).
        ``phase``/``rank`` narrow multi-phase sites (e.g. the two-phase
        save's ``checkpoint.mp``) to one instrumented point."""
        return self._add(site, "io", times, phase=phase, rank=rank)

    def chunk_io_error(self, times=1):
        """I/O error mid payload stream (a torn temp file)."""
        return self._add("checkpoint.chunk", "io", times)

    def truncate(self, times=1, drop_bytes=None):
        """Truncate a just-written checkpoint file (torn/partial
        write reaching the final name). ``drop_bytes=None`` drops a
        seeded random amount of the tail."""
        return self._add("checkpoint.file", "truncate", times,
                         drop_bytes=drop_bytes)

    def bit_flip(self, times=1, byte_index=None, bit=None):
        """Flip one bit of a just-written checkpoint file (silent disk
        corruption). Position defaults to a seeded random payload
        byte."""
        return self._add("checkpoint.file", "bitflip", times,
                         byte_index=byte_index, bit=bit)

    def resource_exhausted(self, times=1, mode=None, job=None):
        """Simulated XLA RESOURCE_EXHAUSTED at step dispatch. With
        ``mode`` the rule fires only for that gather mode (e.g. only
        the dense path OOMs; the slot-wise fallback fits). With
        ``job`` the rule fires only for that fleet job's dispatch
        (the fleet layer fires ``step.dispatch`` per admitted job, so
        chaos tests can OOM exactly one batch slot — its neighbors'
        bits must not move)."""
        return self._add("step.dispatch", "oom", times, mode=mode, job=job)

    def nan_poison(self, fld, step, cells=None, value=float("nan"),
                   times=1, job=None):
        """Write ``value`` into ``fld`` for ``cells`` (default: one
        seeded local cell) after step ``step`` completes. ``times > 1``
        re-poisons on every replay of that step (a deterministic
        blow-up the rollback cannot outrun — the retry-bound test).
        With ``job`` the poison targets ONE fleet batch slot (consumed
        via :func:`poison_fleet` by the fleet layer; job-scoped rules
        never fire at the plain per-grid ``poison_step`` site)."""
        return self._add("step.poison", "nan", times, field=fld, step=step,
                         cells=cells, value=value, job=job)

    def probe_hang(self, times=1):
        """Device probe times out (dead accelerator tunnel)."""
        return self._add("device.probe", "hang", times)

    def barrier_hang(self, tag=None, times=1, hang_s=None):
        """A coordination barrier never completes — the signature of a
        LOST RANK on a multi-process mesh. ``coord.barrier``'s watchdog
        must raise :class:`~dccrg_tpu.coord.BarrierTimeoutError` naming
        the tag within its bound. ``tag`` narrows to one barrier by
        PREFIX (None: the next one) — the two-phase save suffixes its
        tags with ``#<attempt>``, so ``tag="save_commit:a.dc"`` hits
        every attempt; a finite ``hang_s`` below the barrier timeout
        models a slow-but-alive peer instead (the barrier completes)."""
        return self._add("coord.barrier_hang", "hang", times, tag=tag,
                         hang_s=hang_s)

    def preempt_signal(self, step=None, times=1):
        """A preemption signal (the scheduler's SIGTERM) 'arrives': the
        supervision layer's step-boundary poll observes it right after
        step ``step`` completes (None: the next boundary), exactly as
        if a real signal handler had set the preempt flag mid-step.
        Queried — not raised — through :func:`take_preempt`, so the
        whole emergency-checkpoint/resumable-exit machinery of
        :class:`dccrg_tpu.supervise.SupervisedRunner` is what gets
        exercised (tier-1's stand-in for the REAL ``kill -TERM`` the
        mp harness delivers)."""
        return self._add("supervise.preempt", "preempt", times, step=step)

    def step_hang(self, step=None, times=1, hang_s=None):
        """The dispatched step wedges — a hung collective or a dead
        accelerator tunnel mid-dispatch. Queried by the supervision
        layer's deadline watchdog (:func:`take_step_hang`): the hang
        replaces the dispatch inside the watchdog's worker thread, so
        the timeout machinery itself is what gets exercised
        (:class:`~dccrg_tpu.supervise.StepTimeoutError` within the
        bound, never a block-forever). A finite ``hang_s`` below the
        step deadline models a slow-but-alive step that completes."""
        return self._add("supervise.hang", "hang", times, step=step,
                         hang_s=hang_s)

    def silent_flip(self, fld, step, cells=None, bit=23, times=1,
                    job=None):
        """Land a FINITE bit-flip in ``fld`` after step ``step`` — the
        silent-data-corruption fault class. Unlike
        :meth:`nan_poison`, the corrupted value stays finite and
        plausible by construction (``bit`` defaults to the float32
        exponent LSB: the value halves or doubles; a flip that would
        land non-finite falls back to a finite wrong value instead),
        so ``comm.all_finite`` / ``GridBatch.finite_slots`` pass and
        only the integrity layer (:mod:`dccrg_tpu.integrity`:
        in-program fingerprints, conservation drift, shadow audits)
        can see it. ``cells=None`` picks one seeded local cell.
        With ``job`` the flip targets ONE fleet batch slot (consumed
        via :func:`flip_fleet`; job-scoped rules never fire at the
        per-grid :func:`flip_step` site)."""
        return self._add("step.flip", "flip", times, field=fld,
                         step=step, cells=cells, bit=bit, job=job)

    def dispatch_error(self, times=1, step=None, job=None):
        """Transient dispatch failure (:class:`InjectedDispatchError`,
        the UNAVAILABLE class) at step dispatch. The supervision layer
        must retry with bounded backoff and succeed WITHOUT tripping a
        rollback. With ``job`` the rule fires only for that fleet
        job's dispatch (the fleet retries just that job's quantum)."""
        return self._add("supervise.dispatch", "dispatch", times, step=step,
                         job=job)

    def delta_parent_corrupt(self, times=1):
        """Corrupt the parent content digest an incremental (delta)
        checkpoint records in its sidecar — the parent-link corruption
        class. Queried — not raised — by
        :func:`dccrg_tpu.resilience.save_delta_checkpoint` via
        :func:`take_delta_parent_corrupt`: the save completes with a
        wrong link, chain verification must then name the broken link
        and ``resume_latest`` must fall back to the last verifying
        prefix."""
        return self._add("checkpoint.delta", "parent_corrupt", times)

    def telemetry_io_error(self, times=1):
        """I/O error at a telemetry exporter write (``telemetry.export``
        — trace JSONL flushes and metrics-file exposition dumps).
        Telemetry is strictly best-effort: the write is dropped and
        counted, and the observed run must proceed with ZERO trips or
        rollbacks (pinned by tests/test_telemetry.py)."""
        return self._add("telemetry.export", "io", times)

    def gc_error(self, times=1):
        """I/O error mid retention-GC prune (``checkpoint.gc``, fired
        before an unlink). The chain-aware deletion order — deltas
        newest-first, keyframe last — must leave NO orphaned delta
        behind, whichever unlink the fault lands on."""
        return self._add("checkpoint.gc", "io", times)

    def rank_death(self, site="checkpoint.mp", phase=None, rank=None,
                   times=1):
        """This rank dies at an instrumented multi-process point
        (raises :class:`InjectedRankDeath`). Phases of the two-phase
        checkpoint save (``site="checkpoint.mp"``): ``meta`` (before
        the meta/offset-table prepare), ``slice`` (mid payload-run
        write), ``written`` (slice complete, before the commit
        barrier), ``commit`` (on the committing rank, before
        verify+rename), ``publish`` (after the rename, before the
        sidecar lands). ``rank`` narrows to one rank's pass."""
        return self._add(site, "rank_death", times, phase=phase, rank=rank)

    def host_death(self, rank=None, at_tick=None, times=1):
        """This HOST dies at a fleet-scheduler tick boundary — the
        elastic-fleet fault class (whole-rank loss mid-serve, outside
        any checkpoint barrier). Queried — not raised — through
        :func:`take_host_death` by
        :class:`~dccrg_tpu.scheduler.FleetScheduler`, which raises
        :class:`InjectedRankDeath` when it fires: in-process tests
        catch it at the loop boundary and drive the SURVIVOR
        scheduler's lease-expiry reclaim; the REAL harness
        (tests/mp_harness.py ``host_death``) instead delivers an
        actual ``kill -9`` to the worker rank's OS process — same
        recovery contract, real corpse. ``rank``/``at_tick`` narrow
        to one rank's pass / one tick boundary."""
        return self._add("fleet.host", "host_death", times, rank=rank,
                         tick=at_tick)

    def mutation_error(self, site="adapt.commit", times=1, phase=None):
        """Fault inside a structural mutation. Sites (each names where
        in the commit the failure lands; ``phase`` narrows to one):

        - ``adapt.commit``     — stop_refining (phases ``resolve``,
                                 ``resolved``, ``preserved``)
        - ``adapt.resolve``    — end of resolve_adaptation, after the
                                 pins/weights inheritance (phase ``pins``)
        - ``grid.restructure`` — plan rebuild + data move, shared by
                                 adapt and balance (phases ``planned``,
                                 ``moved``)
        - ``balance.commit``   — balance_load stages (phases
                                 ``partition``, ``stage``, ``finish``,
                                 ``land``)
        - ``hybrid.recommit``  — the hybrid plan builder for refined
                                 grids (phases ``classified``, ``cached``)
        - ``partition.compute``— inside the SFC partitioner
        """
        return self._add(site, "mutation", times, phase=phase)

    def amr_error(self, site="amr.propose", phase=None, rank=None,
                  times=1):
        """Raise (:class:`InjectedMutationError`) at a distributed-AMR
        commit phase — sites ``amr.propose`` / ``amr.resolve`` /
        ``amr.install`` (phases ``prepare``, ``commit``), the named
        fault points of dccrg_tpu/distamr.py. The cross-rank
        transaction must roll this rank back bitwise, restore its
        request sets, and post the abort marker every peer fast-aborts
        on; the fleet keeps serving the OLD plan. ``rank`` narrows to
        one rank's pass (faked in-process groups carry real rank
        ids)."""
        return self._add(site, "mutation", times, phase=phase, rank=rank)

    def amr_hang(self, site="amr.resolve", hang_s=None, phase=None,
                 rank=None, times=1):
        """This rank STALLS inside a distributed-AMR commit phase — the
        SIGSTOP-zombie / wedged-KV fault class. Queried — not raised —
        through :func:`take_amr_hang` (site suffixed ``.hang``, same
        discipline as :meth:`barrier_hang`): the stall replaces the
        phase work, so the PEERS' deadline-bounded proposal collects
        and fenced barriers are what get exercised — they must abort
        typed within their bound, and a commit the survivors re-form
        afterwards advances the fence so the woken zombie loses
        (:class:`~dccrg_tpu.coord.StaleFenceError`). ``hang_s=None``
        stalls past any deadline (``math.inf``)."""
        return self._add(site + ".hang", "hang", times, phase=phase,
                         rank=rank, hang_s=hang_s)

    def amr_torn_record(self, site="amr.propose", rank=None, times=1):
        """This rank stores its sealed proposal/commit record with a
        corrupted tail — the half-written KV record of a rank that died
        mid-write. Queried — not raised — through
        :func:`take_torn_record` by the record WRITER (site suffixed
        ``.torn``), so the damage lands in the store and every READER's
        CRC frame check (:func:`~dccrg_tpu.coord.unseal_record`) is
        what gets exercised: conviction as
        :class:`~dccrg_tpu.coord.TornRecordError` and a collective
        abort, never action on the torn payload."""
        return self._add(site + ".torn", "torn", times, rank=rank)

    # -- streaming-intake spool faults (dccrg_tpu/intake.py) ----------

    def spool_torn_write(self, times=1, job=None):
        """A submitter dies mid spec write: the spool file LANDS with
        a truncated sealed frame (a partial spec write reaching the
        final name). Queried — not raised — through
        :func:`take_spool_torn` by :func:`intake.submit`, so the torn
        bytes are durable and the intake reader's CRC conviction
        (:class:`~dccrg_tpu.coord.TornRecordError`), bounded retries
        and poison-job quarantine are what get exercised."""
        return self._add("intake.spool.write.torn", "torn", times,
                         job=job)

    def spool_torn_rename(self, times=1, job=None):
        """A submitter dies BETWEEN the temp write and the atomic
        rename-in: the spec stays in the temp directory and never
        becomes visible (the other half of the torn-submission fault
        class). Queried — not raised — through
        :func:`take_spool_torn_rename` by :func:`intake.submit`; the
        stream must simply never see the job (durable-spool contract:
        visibility IS the rename)."""
        return self._add("intake.spool.rename.torn", "torn", times,
                         job=job)

    def spool_delay(self, times=1, rank=None):
        """Delayed directory visibility: one spool scan fails to see
        the newest not-yet-tracked entry (an NFS-ish lagging readdir).
        Queried — not raised — through :func:`take_spool_delay` by the
        intake scanner; the entry must be admitted by a LATER scan,
        never lost."""
        return self._add("intake.spool.scan", "delay", times,
                         rank=rank)

    def spool_io_error(self, times=1, job=None, rank=None):
        """Transient I/O error reading a spool spec file (site
        ``intake.spool.read``) — the retry/backoff envelope's bread
        and butter: under ``times < K`` retries the job must still
        admit; at ``times >= K`` it must quarantine with a structured
        reason instead of wedging the stream."""
        return self._add("intake.spool.read", "io", times, job=job,
                         rank=rank)

    def intake_death(self, rank=None, times=1, job=None):
        """This rank dies BETWEEN the spool claim (intake lease
        acquired, journal record written) and the scheduler add —
        the exactly-once admission window. Raised at site
        ``intake.claim`` as :class:`InjectedRankDeath`: in-process
        tests catch it and drive a survivor intake's lease-expiry
        reclaim; the REAL harness (tests/mp_harness.py
        ``intake_kill``) hard-exits the OS process, and the surviving
        fleet must re-admit from the journal record exactly once."""
        return self._add("intake.claim", "rank_death", times,
                         rank=rank, job=job)

    # -- warm-start cache faults (dccrg_tpu/warmstart.py) -------------

    def warm_torn_manifest(self, times=1, key=None):
        """A manifest writer dies mid-write: the per-key record LANDS
        at its final name with a truncated sealed frame. Queried — not
        raised — through :func:`take_warm_torn` by the warmstart
        manifest writer, so the torn bytes are durable and every
        loader's CRC conviction (:class:`~dccrg_tpu.coord
        .TornRecordError` -> typed ``WarmCacheError``, entry
        quarantined, cold compile) is what gets exercised."""
        return self._add("warm.manifest.write.torn", "torn", times,
                         key=key)

    def warm_corrupt_entry(self, times=1, key=None):
        """Silent corruption of a landed manifest entry's payload
        bytes (one flipped byte INSIDE the sealed frame — the CRC
        still reads as a frame, the payload no longer matches it).
        Queried through :func:`take_warm_corrupt` by the writer; the
        loader must convict, quarantine and fall cold."""
        return self._add("warm.manifest.write.corrupt", "corrupt",
                         times, key=key)

    def warm_stale_epoch(self, times=1, key=None):
        """A manifest entry lands stamped with a DIFFERENT cache
        epoch (the record of a run on older jax/jaxlib/package
        versions). Queried through :func:`take_warm_stale` by the
        writer; the loader must REJECT it to cold compile — a drifted
        cache is never trusted."""
        return self._add("warm.manifest.write.stale", "stale", times,
                         key=key)

    def warm_io_error(self, times=1, op=None):
        """Transient I/O error at a warm-cache dir operation (site
        ``warm.cache.io``; ``op`` narrows to ``read``/``write``/
        ``scan``/``gc``). The pool must degrade that one entry (or
        pass) to cold compile and keep serving — telemetry-discipline
        best-effort, never a crash."""
        return self._add("warm.cache.io", "io", times, op=op)

    def warm_prewarm_death(self, times=1, rank=None):
        """This rank dies mid-prewarm (site ``warm.prewarm``, raised
        as :class:`InjectedRankDeath` between two background
        pre-compiles): the manifest and cache dir must stay
        loadable — the next boot simply re-warms — and an in-process
        caller sees the typed death, not a wedged pool."""
        return self._add("warm.prewarm", "rank_death", times,
                         rank=rank)

    # -- installation -------------------------------------------------

    def __enter__(self):
        global _active
        if _active is not None:
            raise RuntimeError("a FaultPlan is already active")
        _active = self
        return self

    def __exit__(self, *exc):
        global _active
        _active = None
        return False

    def fired(self, site: str) -> int:
        """How many injections have fired at ``site``."""
        return sum(1 for s, _k, _d in self.log if s == site)

    # -- firing (internal) --------------------------------------------

    def _take(self, site: str, ctx: dict) -> "_Rule | None":
        for r in self.rules:
            if r.matches(site, ctx):
                r.fired += 1
                return r
        return None


def active() -> "FaultPlan | None":
    return _active


def fire(site: str, **ctx) -> None:
    """Raise the scheduled exception for ``site``, if any. Called from
    the instrumented sites; no-op without an active plan."""
    plan = _active
    if plan is None:
        return
    rule = plan._take(site, ctx)
    if rule is None:
        return
    plan.log.append((site, rule.kind, dict(ctx)))
    if rule.kind == "io":
        raise InjectedIOError(f"injected I/O error at {site}")
    if rule.kind == "oom":
        raise SimulatedResourceExhausted(f"at {site} {ctx}")
    if rule.kind == "hang":
        raise InjectedProbeHang(f"injected probe timeout at {site}")
    if rule.kind == "mutation":
        raise InjectedMutationError(
            f"injected mutation fault at {site} {ctx}".rstrip())
    if rule.kind == "rank_death":
        raise InjectedRankDeath(
            f"injected rank death at {site} {ctx}".rstrip())
    if rule.kind == "dispatch":
        raise InjectedDispatchError(f"at {site} {ctx}".rstrip())
    raise AssertionError(f"rule kind {rule.kind!r} cannot fire at {site}")


def take_barrier_hang(tag: str):
    """Consume a scheduled barrier hang for ``tag``; returns the hang
    duration in seconds (math.inf for a dead rank) or None. Queried —
    not raised — by coord.barrier: the hang replaces the sync inside
    the watchdog thread, so the timeout machinery itself is what gets
    exercised."""
    plan = _active
    if plan is None:
        return None
    rule = plan._take("coord.barrier_hang", {"tag": tag})
    if rule is None:
        return None
    plan.log.append(("coord.barrier_hang", "hang", {"tag": tag}))
    hang = rule.params.get("hang_s")
    return math.inf if hang is None else float(hang)


def take_amr_hang(site: str, phase=None, rank=None):
    """Consume a scheduled :meth:`~FaultPlan.amr_hang` for this rank's
    distributed-AMR phase; returns the stall duration in seconds
    (math.inf for a frozen-forever rank) or None. Queried — not raised
    — by distamr so the stall happens INSIDE the phase: the peers'
    deadline machinery is what gets exercised."""
    plan = _active
    if plan is None:
        return None
    ctx = {"phase": phase, "rank": rank}
    rule = plan._take(site + ".hang", ctx)
    if rule is None:
        return None
    plan.log.append((site + ".hang", "hang", dict(ctx)))
    hang = rule.params.get("hang_s")
    return math.inf if hang is None else float(hang)


def take_torn_record(site: str, rank=None) -> bool:
    """Consume a scheduled :meth:`~FaultPlan.amr_torn_record` for this
    rank's record write; True when one fired. Queried — not raised —
    by the record writer so the torn bytes LAND in the KV and the
    readers' CRC conviction is what gets exercised."""
    plan = _active
    if plan is None:
        return False
    ctx = {"rank": rank}
    rule = plan._take(site + ".torn", ctx)
    if rule is None:
        return False
    plan.log.append((site + ".torn", "torn", dict(ctx)))
    return True


def take_spool_torn(job=None) -> bool:
    """Consume a scheduled :meth:`~FaultPlan.spool_torn_write` for
    this submission; True when one fired (the submitter then lands a
    truncated sealed frame at the FINAL spool name)."""
    plan = _active
    if plan is None:
        return False
    ctx = {"job": job}
    rule = plan._take("intake.spool.write.torn", ctx)
    if rule is None:
        return False
    plan.log.append(("intake.spool.write.torn", "torn", dict(ctx)))
    return True


def take_spool_torn_rename(job=None) -> bool:
    """Consume a scheduled :meth:`~FaultPlan.spool_torn_rename`; True
    when one fired (the submitter then leaves the spec in the temp
    directory — it never becomes visible)."""
    plan = _active
    if plan is None:
        return False
    ctx = {"job": job}
    rule = plan._take("intake.spool.rename.torn", ctx)
    if rule is None:
        return False
    plan.log.append(("intake.spool.rename.torn", "torn", dict(ctx)))
    return True


def take_spool_delay(rank=None) -> bool:
    """Consume a scheduled :meth:`~FaultPlan.spool_delay` for this
    spool scan; True when one fired (the scanner then hides the
    newest not-yet-tracked entry until a later scan)."""
    plan = _active
    if plan is None:
        return False
    ctx = {"rank": rank}
    rule = plan._take("intake.spool.scan", ctx)
    if rule is None:
        return False
    plan.log.append(("intake.spool.scan", "delay", dict(ctx)))
    return True


def _take_query(site: str, kind: str, ctx: dict) -> bool:
    """Shared body of the queried (not raised) fault consumers."""
    plan = _active
    if plan is None:
        return False
    rule = plan._take(site, ctx)
    if rule is None:
        return False
    plan.log.append((site, kind, dict(ctx)))
    return True


def take_warm_torn(key=None) -> bool:
    """Consume a scheduled :meth:`~FaultPlan.warm_torn_manifest` for
    this manifest write; True when one fired (the writer then lands a
    truncated sealed frame at the final record name)."""
    return _take_query("warm.manifest.write.torn", "torn",
                       {"key": key})


def take_warm_corrupt(key=None) -> bool:
    """Consume a scheduled :meth:`~FaultPlan.warm_corrupt_entry`;
    True when one fired (the writer then lands a payload-corrupted
    sealed frame — the loader's CRC conviction is exercised)."""
    return _take_query("warm.manifest.write.corrupt", "corrupt",
                       {"key": key})


def take_warm_stale(key=None) -> bool:
    """Consume a scheduled :meth:`~FaultPlan.warm_stale_epoch`; True
    when one fired (the writer then stamps a drifted cache epoch —
    the loader's version-rejection is exercised)."""
    return _take_query("warm.manifest.write.stale", "stale",
                       {"key": key})


def take_host_death(rank: int, tick: int) -> bool:
    """Consume a scheduled :meth:`~FaultPlan.host_death` for this
    rank's tick boundary; True when one fired. Queried — not raised —
    by the fleet scheduler so the caller decides how to die (raise
    :class:`InjectedRankDeath` in-process; the mp harness maps it to a
    hard OS exit)."""
    plan = _active
    if plan is None:
        return False
    rule = plan._take("fleet.host", {"rank": rank, "tick": tick})
    if rule is None:
        return False
    plan.log.append(("fleet.host", "host_death",
                     {"rank": rank, "tick": tick}))
    return True


def take_delta_parent_corrupt() -> bool:
    """Consume a scheduled :meth:`~FaultPlan.delta_parent_corrupt`;
    True when one fired. Queried — not raised — by the delta save so
    the corrupted link LANDS in the sidecar and the chain-verification
    machinery is what gets exercised."""
    plan = _active
    if plan is None:
        return False
    rule = plan._take("checkpoint.delta", {})
    if rule is None:
        return False
    plan.log.append(("checkpoint.delta", "parent_corrupt", {}))
    return True


def take_preempt(step: int) -> bool:
    """Consume a scheduled :meth:`~FaultPlan.preempt_signal` for the
    boundary after ``step``; True when one fired. Queried — not raised
    — by the supervision layer's step-boundary poll: the fake sets the
    SAME preempt flag a real signal handler would, so everything
    downstream (trip consensus, emergency checkpoint, resumable exit)
    is the production path."""
    plan = _active
    if plan is None:
        return False
    rule = plan._take("supervise.preempt", {"step": step})
    if rule is None:
        return False
    plan.log.append(("supervise.preempt", "preempt", {"step": step}))
    return True


def take_step_hang(step: int):
    """Consume a scheduled :meth:`~FaultPlan.step_hang` for ``step``;
    returns the hang duration in seconds (math.inf for a wedged-forever
    dispatch) or None. The hang replaces the dispatch inside the
    supervision watchdog's worker thread — same discipline as
    :func:`take_barrier_hang`."""
    plan = _active
    if plan is None:
        return None
    rule = plan._take("supervise.hang", {"step": step})
    if rule is None:
        return None
    plan.log.append(("supervise.hang", "hang", {"step": step}))
    hang = rule.params.get("hang_s")
    return math.inf if hang is None else float(hang)


def corrupt_file(path: str) -> list:
    """Apply scheduled file corruptions (truncate / bit flips) to a
    just-written file; returns what was applied. Called after the
    atomic save (file AND sidecar complete), simulating corruption at
    rest — exactly what the CRC verification exists to catch."""
    plan = _active
    applied = []
    if plan is None:
        return applied
    while True:
        rule = plan._take("checkpoint.file", {"path": path})
        if rule is None:
            return applied
        size = os.path.getsize(path)
        if rule.kind == "truncate":
            drop = rule.params.get("drop_bytes")
            if drop is None:
                drop = int(plan.rng.integers(1, max(2, size // 4)))
            detail = {"path": path, "drop_bytes": drop}
            truncate_file(path, drop)
        elif rule.kind == "bitflip":
            byte = rule.params.get("byte_index")
            if byte is None:
                byte = int(plan.rng.integers(0, size))
            bit = rule.params.get("bit")
            if bit is None:
                bit = int(plan.rng.integers(0, 8))
            detail = {"path": path, "byte_index": byte, "bit": bit}
            flip_bit(path, byte, bit)
        else:
            raise AssertionError(f"rule kind {rule.kind!r} is not a "
                                 "file corruption")
        plan.log.append(("checkpoint.file", rule.kind, detail))
        applied.append((rule.kind, detail))


def poison_step(grid, step: int) -> list:
    """Apply scheduled NaN poisonings for ``step`` to ``grid``'s
    fields; returns the poisoned (field, cells) pairs. Each matching
    rule fires at most ONCE per call (= per visit of the step), so a
    rule with ``times=k`` re-poisons the first k replays."""
    plan = _active
    applied = []
    if plan is None:
        return applied
    ctx = {"step": step}
    for rule in [r for r in plan.rules if r.matches("step.poison", ctx)]:
        rule.fired += 1
        name = rule.params["field"]
        cells = rule.params["cells"]
        if cells is None:
            local = np.asarray(grid.get_cells())
            pick = int(plan.rng.integers(0, len(local)))
            cells = np.asarray([local[pick]], dtype=np.uint64)
        cells = np.atleast_1d(np.asarray(cells, dtype=np.uint64))
        shape, dtype = grid.fields[name]
        vals = np.full((len(cells),) + shape, rule.params["value"],
                       dtype=dtype)
        grid.set(name, cells, vals)
        plan.log.append(("step.poison", "nan",
                         {"step": step, "field": name,
                          "cells": cells.tolist()}))
        applied.append((name, cells))
    return applied


def flip_values(vals: np.ndarray, bit: int) -> np.ndarray:
    """XOR ``bit`` into each element's raw bits, guaranteed FINITE:
    an element whose flip would land inf/NaN (exponent saturation)
    takes a finite wrong value (``1.5 * v + 1``) instead — silent
    corruption must stay invisible to the finiteness watchdog, that
    is the entire point of the fault class."""
    vals = np.ascontiguousarray(vals)
    kind = vals.dtype.kind
    u = vals.view(f"u{vals.dtype.itemsize}")
    flipped = (u ^ (np.array(1, dtype=u.dtype) << int(bit))).view(
        vals.dtype)
    if kind == "f":
        bad = ~np.isfinite(flipped)
        if bad.any():
            # the fallback must itself be finite for EVERY finite
            # input: halving never overflows (unlike 1.5*v + 1, which
            # is inf for |v| > ~2.26e38 float32), and the +1 branch
            # below |v| < 2 dodges the map's only fixed point at 0
            with np.errstate(over="ignore", invalid="ignore"):
                safe = np.where(np.abs(vals) >= 2.0, vals * 0.5,
                                vals * 0.5 + 1.0).astype(vals.dtype)
            flipped = np.where(bad, safe, flipped)
    return flipped


def flip_step(grid, step: int) -> list:
    """Apply scheduled silent bit-flips for ``step`` to ``grid``'s
    fields (the per-grid site, mirroring :func:`poison_step`); returns
    the flipped ``(field, cells)`` pairs. Job-scoped rules (fleet
    slots) never fire here."""
    plan = _active
    applied = []
    if plan is None:
        return applied
    ctx = {"step": step}
    for rule in [r for r in plan.rules
                 if r.site == "step.flip" and r.matches("step.flip", ctx)
                 and r.params.get("job") is None]:
        rule.fired += 1
        name = rule.params["field"]
        cells = rule.params["cells"]
        if cells is None:
            local = np.asarray(grid.get_cells())
            pick = int(plan.rng.integers(0, len(local)))
            cells = np.asarray([local[pick]], dtype=np.uint64)
        cells = np.atleast_1d(np.asarray(cells, dtype=np.uint64))
        vals = np.asarray(grid.get(name, cells))
        grid.set(name, cells, flip_values(vals, rule.params["bit"]))
        plan.log.append(("step.flip", "flip",
                         {"step": step, "field": name,
                          "cells": cells.tolist(),
                          "bit": int(rule.params["bit"])}))
        applied.append((name, cells))
    return applied


def flip_fleet(job: str, after_step: int, through_step: int) -> list:
    """Consume scheduled silent bit-flips targeting fleet job ``job``
    whose step falls in ``(after_step, through_step]`` — same window
    discipline as :func:`poison_fleet`. Returns ``[(field, cells,
    bit, step)]``; the fleet layer lands the flip in the job's batch
    slot itself (:meth:`dccrg_tpu.fleet.GridBatch.flip`)."""
    plan = _active
    out = []
    if plan is None:
        return out
    for rule in plan.rules:
        if rule.site != "step.flip" or rule.fired >= rule.times:
            continue
        want_job = rule.params.get("job")
        if want_job is not None and want_job != job:
            continue
        step = rule.params.get("step")
        if step is None or not after_step < step <= through_step:
            continue
        rule.fired += 1
        plan.log.append(("step.flip", "flip",
                         {"step": step, "job": job,
                          "field": rule.params["field"],
                          "bit": int(rule.params["bit"])}))
        out.append((rule.params["field"], rule.params["cells"],
                    int(rule.params["bit"]), int(step)))
    return out


def poison_fleet(job: str, after_step: int, through_step: int) -> list:
    """Consume scheduled NaN poisonings targeting fleet job ``job``
    whose step falls in ``(after_step, through_step]`` — the window
    one batched quantum advanced that job through. Returns
    ``[(field, cells, value, step)]``; the FLEET layer writes the
    poison into the job's batch slot itself (a slot is not a grid, so
    :func:`poison_step` cannot). Rules with ``job=None`` keep wildcard
    semantics and match whichever job is polled first; job-scoped
    rules fire only for their job."""
    plan = _active
    out = []
    if plan is None:
        return out
    for rule in plan.rules:
        if rule.site != "step.poison" or rule.fired >= rule.times:
            continue
        want_job = rule.params.get("job")
        if want_job is not None and want_job != job:
            continue
        step = rule.params.get("step")
        if step is None or not after_step < step <= through_step:
            continue
        rule.fired += 1
        plan.log.append(("step.poison", "nan",
                         {"step": step, "job": job,
                          "field": rule.params["field"]}))
        out.append((rule.params["field"], rule.params["cells"],
                    rule.params["value"], int(step)))
    return out


# -- standalone corruption helpers (also used directly by tests) ------

def flip_bit(path: str, byte_index: int, bit: int = 0) -> None:
    """Flip one bit of ``path`` in place."""
    with open(path, "r+b") as f:
        f.seek(byte_index)
        (b,) = f.read(1)
        f.seek(byte_index)
        f.write(bytes([b ^ (1 << bit)]))


def truncate_file(path: str, drop_bytes: int) -> None:
    """Drop the last ``drop_bytes`` bytes of ``path``."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(0, size - int(drop_bytes)))


EVERY = math.inf  # times=EVERY: the rule never exhausts
