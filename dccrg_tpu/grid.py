"""The distributed grid runtime.

TPU-native equivalent of the reference's ``class Dccrg``
(dccrg.hpp:151-13042), re-architected for JAX/XLA:

- **Structure is replicated host state** (the reference replicates its
  ``cell_process`` map on every rank too, dccrg.hpp:7311): the sorted
  cell list, owners, neighbor lists, and halo plans are numpy arrays
  rebuilt at structure-change events (AMR commit, load balance).
- **Data is sharded device state**: each user-declared per-cell field
  is one JAX array of shape ``[n_dev, R, ...]`` sharded over a 1-D
  device mesh; rows of a device's slice are
  ``[inner cells | outer cells | pad | ghost copies | pad | zero row]``
  (the reference's iteration-cache ordering, dccrg.hpp:11453-11767).
- **Halo exchange is one XLA collective**: the per-peer send/receive
  lists (dccrg.hpp:8729-8891) become static gather/scatter index
  tables, and ``update_copies_of_remote_neighbors()`` lowers to a
  single ``lax.all_to_all`` under ``shard_map``
  (vs per-peer MPI_Isend/Irecv, dccrg.hpp:10703-11209).
- **Stencils are gather-based**: neighbor resolution
  (dccrg.hpp:4375-4897) is precomputed into padded per-cell gather
  tables; ``apply_stencil`` hands kernels dense ``[L, S, ...]``
  neighbor blocks so XLA can fuse and vectorize — no per-cell loops.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field as dataclass_field
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import shard_map as _shard_map

from . import background
from . import faults
from . import telemetry
from .geometry import CartesianGeometry, NoGeometry, StretchedCartesianGeometry
from .mapping import Mapping
from .neighbors import (
    build_neighbor_lists,
    find_neighbors_of,
    find_neighbors_to_subset,
    make_neighborhood,
    validate_neighborhood,
    verify_tiling,
)
from .partition import (
    PARTITION_METHODS,
    partition_cells,
    partition_cells_hierarchical,
)
from .topology import GridTopology
from .txn import grid_transaction
from .types import ERROR_CELL
from . import uniform as uniform_mod

logger = logging.getLogger("dccrg_tpu.grid")

# Parity with the reference's default neighborhood id (dccrg.hpp:99).
DEFAULT_NEIGHBORHOOD_ID = -0xDCC

_allocator_tuned = False
_libc = None  # set by _tune_allocator; None = opted out / unavailable


def _tune_allocator():
    """Raise glibc's mmap/trim thresholds before the first large plan
    build: big numpy temporaries otherwise go through mmap and pay a
    page fault per 4K page on every rebuild (~2x on 128^3 structure
    builds on a quiet host). Applied lazily so merely importing the
    package leaves process-global malloc behavior untouched; opt out
    entirely with DCCRG_NO_MALLOPT=1."""
    global _allocator_tuned, _libc
    if _allocator_tuned:
        return
    _allocator_tuned = True

    if os.environ.get("DCCRG_NO_MALLOPT") == "1":
        return
    try:
        import ctypes

        libc = ctypes.CDLL("libc.so.6")
        libc.mallopt(-3, 1 << 30)  # M_MMAP_THRESHOLD
        libc.mallopt(-1, 1 << 30)  # M_TRIM_THRESHOLD
        _libc = libc
    except Exception:
        pass


def _trim_allocator():
    """Return freed heap to the OS after a large plan build: the raised
    M_TRIM_THRESHOLD means free() alone never trims, so long-running
    host applications embedding the library would otherwise keep the
    build's peak RSS. One explicit malloc_trim after each large rebuild
    keeps the build-speed win without the RSS cost. Must run after the
    build's temporaries are actually dead (i.e. after _build_plan
    returns), not inside it."""
    if _libc is None:
        return
    try:
        _libc.malloc_trim(0)
    except Exception:
        pass


# fixed tier for small host get/set transfers: one compiled
# gather/scatter program per field shape regardless of query-size drift
_GATHER_TIER = 4096


def bucket_capacity(n: int) -> int:
    """Round a capacity up to a quarter-power-of-two bucket (16, 20,
    24, 28, 32, 40, ...): structure changes that stay within a bucket
    keep every array shape identical, so the jitted exchange/stencil/
    step-loop programs (keyed by shape, not epoch) are reused instead
    of recompiled — the difference between an O(ms) and an O(30 s)
    AMR epoch on TPU. Waste is bounded at 25%."""
    n = int(n)
    if n <= 16:
        return 16
    step = 1 << max(max(n - 1, 1).bit_length() - 3, 0)
    return ((n + step - 1) // step) * step




def _synth_key(cf):
    """Static cache-key component for a closed-form plan (None when
    the plan has dense tables)."""
    if cf is None:
        return None
    return (cf["dims"], cf["periodic"], cf["n0"],
            tuple(map(tuple, cf["offsets"])), bool(cf.get("multi")))


def _synth_prep(synth, L, row_gidx=None):
    """(grid index, base validity) per row for closed-form mask
    synthesis: from the row index alone on single-device plans (rows
    ARE grid order), or from the per-row grid index array on
    multi-device closed-form plans (rows are [inner|outer] per device;
    ``row_gidx`` is ``device_row_ids[:L]`` for this device's shard,
    -1 on pad rows)."""
    n0_ = synth[2]
    if row_gidx is None:
        gidx = jnp.arange(L, dtype=jnp.int32)
        base_valid = (gidx < n0_) if L > n0_ else jnp.ones((L,), bool)
    else:
        base_valid = row_gidx >= 0
        gidx = jnp.maximum(row_gidx, 0)
    return gidx, base_valid


def _synth_col(synth, gidx, base_valid, j):
    """One [L] validity column of the closed-form mask (stencil slot
    ``j``) — lets slot-wise kernels avoid materializing the [L, S]
    stack."""
    (nx_, ny_, nz_), per_, _n0, offs_cells, *_ = synth
    xc = gidx % nx_
    yc = (gidx // nx_) % ny_
    zc = gidx // (nx_ * ny_)
    ox, oy, oz = offs_cells[j]
    v = base_valid
    for coord, o, nd, per in ((xc, ox, nx_, per_[0]),
                              (yc, oy, ny_, per_[1]),
                              (zc, oz, nz_, per_[2])):
        if o != 0 and not per:
            t = coord + o
            v = v & (t >= 0) & (t < nd)
    return v


def _synth_mask(synth, L, row_gidx=None):
    """Closed-form [L, S] validity mask (stack of _synth_col)."""
    gidx, base_valid = _synth_prep(synth, L, row_gidx)
    offs_cells = synth[3]
    return jnp.stack(
        [_synth_col(synth, gidx, base_valid, j)
         for j in range(len(offs_cells))], axis=1)



def _halo_send(fl, sr, delta, axis, n_dev):
    """One halo send: gather the send rows and move them — a compact
    per-peer ppermute when ``delta`` is given, the dense tiled
    all_to_all otherwise."""
    buf = fl[jnp.clip(sr, 0)]
    if delta is None:
        return jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
    perm = [(p, (p + delta) % n_dev) for p in range(n_dev)]
    return jax.lax.ppermute(buf, axis, perm)


def _halo_scatter(fl, rv, payload, R):
    """Scatter a received payload into ghost rows (-1 slots drop)."""
    rr = jnp.where(rv >= 0, rv, R - 1).reshape(-1)
    return fl.at[rr].set(payload.reshape((-1,) + fl.shape[1:]), mode="drop")


def put_sharded(host_array, sharding):
    """Host -> device upload of a replicatedly-computed array onto a
    (possibly multi-process) sharding: each process serves only the
    shards it can address (``jax.make_array_from_callback``), so the
    same call works on a single controller and under
    ``jax.distributed`` SPMD — the analogue of every MPI rank uploading
    its slice of the replicated structure (dccrg.hpp:7738-7803)."""
    arr = np.asarray(host_array)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def _make_nbr_gather(use_roll, r_shifts, L, nrows, nmask, wr, ws):
    """Per-device neighbor gather for stencil bodies: a table gather,
    or S sequential rolls + a sparse fixup scatter when the table is
    affine (see _HoodPlan.roll_plan). Shared by apply_stencil and the
    fused step loop."""
    if not use_roll:
        return lambda fl: fl[nrows]

    def gather(fl):
        cols = [jnp.roll(fl[:L], -s, axis=0) for s in r_shifts]
        st = jnp.stack(cols, axis=1)  # [L, S, ...]
        rows_flat = wr.reshape(-1)
        slots_flat = jnp.repeat(
            jnp.arange(len(r_shifts), dtype=jnp.int32), wr.shape[1]
        )
        st = st.at[rows_flat, slots_flat].set(fl[ws.reshape(-1)], mode="drop")
        mexp = nmask.reshape(nmask.shape + (1,) * (st.ndim - 2))
        return jnp.where(mexp, st, jnp.zeros((), st.dtype))

    return gather


def _make_nbr_slot_gather(use_roll, r_shifts, L, nrows, wr, ws):
    """Column-``j`` neighbor gather for slot-wise stencils:
    ``gather(fl, j, mask_j) -> [L, ...]``, one stencil slot at a time,
    so the [L, S] neighbor stack (whose O(L*S) HBM residency drove the
    512^3 OOM) is never materialized as a single array — though the
    scheduler can still co-locate several slot temporaries; see
    _run_slotwise. Roll mode zeroes masked
    slots (the rolled values there are junk); table mode returns the
    raw gather like the dense table path (masked slots point at
    zeroed pad rows; kernels gate on the mask either way)."""
    if not use_roll:
        return lambda fl, j, mask_j: fl[nrows[:, j]]

    def gather(fl, j, mask_j):
        col = jnp.roll(fl[:L], -r_shifts[j], axis=0)
        col = col.at[wr[j]].set(fl[ws[j]], mode="drop")
        mexp = mask_j.reshape(mask_j.shape + (1,) * (col.ndim - 1))
        return jnp.where(mexp, col, jnp.zeros((), col.dtype))

    return gather


def _make_roll3d_gather(synth, L):
    """Single-device closed-form slot gather: reshape the flat field to
    the 3-D grid and ``jnp.roll`` — pure slices/concats, NO
    scatter/gather ops. TPU executes dynamic scatters orders of
    magnitude slower than shifts (the round-5 chip A/B at 128^3:
    1.7e8 updates/s for roll-with-fixup-scatter vs 2.5e6/s for table
    gathers, against a 7.6e10/s Pallas bound), so the flat roll plan's
    wrap fixups are replaced by exact 3-D periodic rolls — rows ARE
    grid order on single-device closed-form plans. Non-periodic wraps
    carry junk and are zeroed through the slot mask, exactly like the
    fixup path."""
    (nx, ny, nz), _per, n0, offs_cells, *_ = synth

    def gather(fl, j, mask_j):
        ox, oy, oz = offs_cells[j]
        g3 = fl[:n0].reshape((nz, ny, nx) + fl.shape[1:])
        g3 = jnp.roll(g3, shift=(-oz, -oy, -ox), axis=(0, 1, 2))
        col = g3.reshape((n0,) + fl.shape[1:])
        if L > n0:
            col = jnp.pad(col, [(0, L - n0)] + [(0, 0)] * (col.ndim - 1))
        mexp = mask_j.reshape(mask_j.shape + (1,) * (col.ndim - 1))
        return jnp.where(mexp, col, jnp.zeros((), col.dtype))

    return gather


def _make_offs_col(uniform_offs, noffs, sc0):
    """Per-slot offsets closure shared by the stencil bodies and the
    dense adapter: raw (NOT premasked — kernels gate on the mask),
    ``[3]`` for uniform plans, ``[L, 3]`` when scaled (``sc0`` is the
    per-row size factor) or table-driven."""
    if uniform_offs:
        if sc0 is not None:
            return lambda j: noffs[j][None, :] * sc0[:, None]
        return lambda j: noffs[j]
    return lambda j: noffs[:, j]


def _run_slotwise(kernel, cell_fields, fields, gather, offs_col, mask_col,
                  n_slots, extra):
    """The one slot loop every slot-wise call site shares:
    init -> slot per stencil leg -> finish. ``fields`` maps name ->
    backing array, ``gather(arr, j, mask_j)`` produces slot j's
    neighbor column. Between slots the carry and the backing arrays
    thread through ``optimization_barrier``: the per-slot gathers have
    no data dependency on each other, so without the barrier XLA's
    scheduler hoists ALL slots' rolls to the front and every column is
    live at once. NOTE the barrier is necessary but — per the measured
    chip artifact (bench/chip_results/bench_main_slotwise.out) — not
    sufficient at the largest sizes: the 512^3 roll-mode run still
    kept ~9 co-resident 512 MB roll temps and OOM'd (~0.3 GB over a
    16 GB budget at 50% fragmentation). Peak HBM is REDUCED versus the
    dense [L, S] contract, not hard-bounded at O(cells); forcing full
    sequencing (lax.scan over slots / donated carry) is the open
    follow-up if 512^3-on-one-chip matters. On an OOM at dispatch the
    resilience layer (resilience.guarded_step) degrades to the next
    gather mode instead of crashing the run."""
    carry = kernel.init(cell_fields, *extra)
    names = list(fields)
    vals = [fields[n] for n in names]
    for j in range(n_slots):
        mj = mask_col(j)
        nbr_j = {n: gather(v, j, mj) for n, v in zip(names, vals)}
        carry = kernel.slot(carry, cell_fields, nbr_j, offs_col(j), mj,
                            *extra)
        if j + 1 < n_slots:
            carry, vals_t = jax.lax.optimization_barrier(
                (carry, tuple(vals)))
            vals = list(vals_t)
    return kernel.finish(carry, cell_fields, *extra)


class SlotwiseKernel:
    """Memory-lean stencil kernel: the bulk pass feeds it one neighbor
    slot (stencil leg) at a time, avoiding the dense contract's
    O(cells * slots) neighbor stack. Measured effect on chip
    (bench/chip_results/bench_main_slotwise.out): peak HBM drops
    substantially, but XLA's scheduler still co-locates several slot
    temporaries, so 512^3 remained slightly over a single chip's HBM
    budget in roll mode — treat this as *reduced*, not O(cells), peak
    HBM until a passing 512^3 run exists. Three callables:

    - ``init(cell_fields, *extra) -> carry``
    - ``slot(carry, cell_fields, nbr_j, offs_j, mask_j, *extra) ->
      carry`` — ``nbr_j[name]`` is ``[L, ...]`` (slot j's neighbor
      values), ``offs_j`` is ``[3]`` / ``[L, 3]`` and is NOT
      pre-masked (gate on ``mask_j``, shape ``[L]``)
    - ``finish(carry, cell_fields, *extra) -> {name: [L, ...]}``

    Instances are also plain dense kernels (``__call__`` loops the
    slots over axis 1), so the surface-sized passes — hard rows near
    refinement, the overlap outer re-pass — and the CPU path use the
    same object unchanged. The slots accumulate sequentially, so
    results match the dense contract's axis-1 reduction only to
    float re-association.

    ``ghost_deps`` optionally declares per-output ghost dependencies
    (``{out_field: (in_fields whose NEIGHBOR values the computation
    of out_field reads)}``) — the per-field ghost-split contract (see
    :func:`ghost_split_enabled`). A missing output defaults to "all
    of fields_in" (the conservative full re-pass)."""

    def __init__(self, init, slot, finish, ghost_deps=None):
        self.init = init
        self.slot = slot
        self.finish = finish
        if ghost_deps is not None:
            self.ghost_deps = {k: tuple(v)
                               for k, v in dict(ghost_deps).items()}

    def __call__(self, cell_fields, nbr_fields, offs, mask, *extra):
        return _run_slotwise(
            self, cell_fields, nbr_fields,
            lambda v, j, mj: v[:, j],
            (lambda j: offs[:, j]) if offs.ndim == 3 else
            (lambda j: offs[j]),
            lambda j: mask[..., j], mask.shape[-1], extra)


def ghost_split_enabled(default: bool = True) -> bool:
    """The ``DCCRG_GHOST_SPLIT`` env knob: per-field ghost-split for
    the overlapped step's outer re-pass (default on). A kernel that
    declares ``ghost_deps`` then re-runs only the outer rows feeding
    the fields that actually exchanged, and scatters only the output
    fields whose declared ghost reads intersect the exchanged set.
    ``0`` compiles the pre-split program bit-identically (the
    negative pin — same discipline as ``DCCRG_INTEGRITY=0``); kernels
    without a declaration are never split either way."""
    v = os.environ.get("DCCRG_GHOST_SPLIT", "")
    if v == "":
        return default
    return v not in ("0", "off", "false", "no")


def default_mesh(devices=None) -> Mesh:
    """1-D device mesh over all (or given) devices, axis name 'dev'."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.array(devices), ("dev",))


@dataclass
class CellView:
    """A set of cells exposed for iteration (reference ``cells`` /
    ``inner_cells()`` etc. views, dccrg.hpp:7547-7718)."""

    ids: np.ndarray  # uint64 cell ids
    owner: np.ndarray  # device index per cell

    def __len__(self):
        return len(self.ids)

    def __iter__(self):
        return iter(self.ids)


class _HoodPlan:
    """Per-neighborhood static tables (one structure epoch).

    ``lists`` (the flat host-side neighbor-entry stream for queries)
    and the neighbors_to gather tables may be passed as zero-arg
    callables: they are built on first access. The uniform fast path
    (uniform.py) uses this so a 256^3 init never materializes the
    ~0.5G-entry stream unless a query API actually needs it.
    """

    def __init__(self, offsets, nbr_rows, nbr_offs, nbr_mask,
                 send_rows=None, recv_rows=None, n_inner=None, lists=None,
                 to_tables=None, to_rows=None, to_offs=None, to_mask=None,
                 offs_const=None, hard_rows=None, hard_nbr_rows=None,
                 hard_offs=None, hard_mask=None, scale_rows=None,
                 closed_form=None, pair_compact=None):
        self.offsets = offsets  # [K, 3] neighborhood items
        # stencil gather tables, per device, padded. May be ONE thunk
        # (returning (rows, mask)) for closed-form plans, materialized
        # only if a host introspection path asks:
        self._nbr_rows = nbr_rows  # [n_dev, L, S] int32 row (pad: zero row)
        self._nbr_offs = nbr_offs  # [n_dev, L, S, 3] int32 offsets, or thunk
        self._nbr_mask = nbr_mask  # [n_dev, L, S] bool
        # closed-form single-device uniform plans: stencils synthesize
        # the mask from the row index and roll shifts arithmetically —
        # no dense tables exist unless forced (dict with dims/periodic/
        # offsets/n0)
        self.closed_form = closed_form
        # when slot offsets are per-slot constants (uniform grids),
        # stencils synthesize noffs = mask * offs_const on device and
        # the full nbr_offs array is only built if a host query asks
        self.offs_const = offs_const  # [S, 3] int32 or None
        # hybrid plans (split tables): cells near refinement hold up to
        # ~8x more neighbor entries than the uniform bulk, so they get
        # their own compact tables and stencils run a second gather
        # over just those rows instead of padding every row to the
        # hard width
        self.hard_rows = hard_rows  # [n_dev, H] int32 (pad: L) or None
        self.hard_nbr_rows = hard_nbr_rows  # [n_dev, H, Sh] int32
        self.hard_offs = hard_offs  # [n_dev, H, Sh, 3] int32
        self.hard_mask = hard_mask  # [n_dev, H, Sh] bool
        # hybrid plans: offs_const is in CELL units; per-row cell size
        # (index units) scales it on device (far/easy rows only)
        self.scale_rows = scale_rows  # [n_dev, L] int32 or None
        # halo exchange lists: the COMPACT per-entry record
        # (uniform.build_pair_tables) is the primary store — O(ghosts)
        # memory; the dense [n_dev, n_dev, M] views are materialized
        # lazily (all_to_all fallback + host introspection only), so
        # pod-scale meshes never pay the n_dev^2 arrays on the
        # per-delta ppermute path
        self._pair_compact = pair_compact
        self._send_rows = send_rows  # [n_dev(src), n_dev(dst), M] or -1
        self._recv_rows = recv_rows  # [n_dev(dst), n_dev(src), M] or -1
        self.n_inner = n_inner  # [n_dev] rows [0, n_inner) have no remote deps
        self._lists = lists  # NeighborLists or thunk
        if to_tables is None and to_rows is not None:
            to_tables = (to_rows, to_offs, to_mask)
        self._to = to_tables  # (rows, offs, mask) or thunk
        self._roll_plan = None  # computed on demand by roll_plan()
        # per-epoch memo of device uploads (tables as jit ARGUMENTS:
        # programs are shape-keyed and reused across structure epochs,
        # only the table values re-upload)
        self._dev = {}
        self._pair_host = {}  # field -> predicate-filtered pair tables

    @property
    def pair_compact(self):
        return self._pair_compact

    def _dense_pairs(self):
        if self._send_rows is None:
            from . import uniform as uniform_mod

            self._send_rows, self._recv_rows = uniform_mod.dense_pair_tables(
                self._pair_compact)
        return self._send_rows, self._recv_rows

    @property
    def send_rows(self):
        return self._dense_pairs()[0]

    @property
    def recv_rows(self):
        return self._dense_pairs()[1]

    @property
    def lists(self):
        if callable(self._lists):
            self._lists = self._lists()
        return self._lists

    @property
    def nbr_offs(self):
        if callable(self._nbr_offs):
            self._nbr_offs = self._nbr_offs()
        return self._nbr_offs

    def _to_tables(self):
        if callable(self._to):
            self._to = self._to()
        return self._to

    @property
    def nbr_rows(self):
        if callable(self._nbr_rows):
            self._nbr_rows, self._nbr_mask = self._nbr_rows()
        return self._nbr_rows

    @property
    def nbr_mask(self):
        if callable(self._nbr_mask):
            self._nbr_rows, self._nbr_mask = self._nbr_mask()
        return self._nbr_mask

    def dev(self, name, host_array, sharding=None):
        """Memoized device upload of a named table (replicated when
        no sharding is given)."""
        hit = self._dev.get(name)
        if hit is None:
            hit = (jnp.asarray(host_array) if sharding is None
                   else put_sharded(host_array, sharding))
            self._dev[name] = hit
        return hit

    def roll_plan(self, L: int, cap=bucket_capacity):
        """Affine decomposition of the of-gather: if (almost) every
        masked slot entry satisfies ``row == r + shift_j``, the [L, S]
        neighbor gather lowers to S jnp.rolls (sequential HBM traffic,
        cheap on TPU where arbitrary gathers are slow) plus a sparse
        fixup scatter for the non-affine entries (wrap rows, block
        boundaries, rows near refinement). Returns
        ``(shifts [S], wrong_rows [n_dev, S, W], wrong_src [n_dev, S, W])``
        or None when the tables aren't affine enough to pay off.
        Computed once per structure epoch (cached)."""
        if getattr(self, "_roll_plan", None) is not None:
            return self._roll_plan if self._roll_plan != () else None
        rows = np.asarray(self.nbr_rows, dtype=np.int64)
        mask = np.asarray(self.nbr_mask)
        n_dev, Lr, S = rows.shape
        base = np.arange(Lr, dtype=np.int64)[None, :]
        shifts = np.zeros(S, dtype=np.int64)
        wrong_sets = []
        n_masked = n_wrong = 0
        for j in range(S):
            mj = mask[:, :, j]
            dj = rows[:, :, j] - base
            local = rows[:, :, j] < L  # rolls only cover local rows
            dm = dj[mj & local]
            if len(dm):
                vals, counts = np.unique(dm, return_counts=True)
                shifts[j] = vals[np.argmax(counts)]
            # ghost reads (row >= L) can coincidentally equal r + shift
            # but the roll never sees them: always fix them up
            wrong = mj & ((dj != shifts[j]) | ~local)
            n_masked += int(mj.sum())
            n_wrong += int(wrong.sum())
            wrong_sets.append([np.nonzero(wrong[d])[0] for d in range(n_dev)])
        if n_masked == 0 or n_wrong / n_masked > 0.25:
            self._roll_plan = ()
            return None
        W = cap(max(1, max(len(w) for per in wrong_sets for w in per)))
        wrong_rows = np.full((n_dev, S, W), L, dtype=np.int32)  # pad: dropped
        wrong_src = np.zeros((n_dev, S, W), dtype=np.int32)
        for j, per in enumerate(wrong_sets):
            for d, w in enumerate(per):
                wrong_rows[d, j, : len(w)] = w
                wrong_src[d, j, : len(w)] = rows[d, w, j]
        self._roll_plan = (shifts, wrong_rows, wrong_src)
        return self._roll_plan

    def merged_of_tables(self, pad_row):
        """Dense [n_dev, L, S] (rows, offs, mask) merging the far and
        hard pieces of a split-table plan — the include_to fallback and
        table-introspection view. Plain plans return their own arrays.
        ``pad_row`` is the zero pad row index (plan.R - 1)."""
        if self.hard_nbr_rows is None:
            return np.asarray(self.nbr_rows), np.asarray(self.nbr_offs), np.asarray(self.nbr_mask)
        n_dev, L, k = self.nbr_rows.shape
        Sh = self.hard_nbr_rows.shape[2]
        S = max(k, Sh)
        rows = np.full((n_dev, L, S), pad_row, dtype=np.int32)
        offs = np.zeros((n_dev, L, S, 3), dtype=np.int32)
        mask = np.zeros((n_dev, L, S), dtype=bool)
        rows[:, :, :k] = self.nbr_rows
        mask[:, :, :k] = self.nbr_mask
        offs[:, :, :k] = self.nbr_mask[..., None] * np.asarray(self.offs_const)[None, None, :, :]
        if self.scale_rows is not None:
            offs[:, :, :k] *= np.asarray(self.scale_rows)[:, :, None, None]
        for d in range(n_dev):
            hr = np.asarray(self.hard_rows[d])
            real = hr < L
            # hard rows have no far entries: overwrite the full row
            rows[d, hr[real]] = pad_row
            mask[d, hr[real]] = False
            offs[d, hr[real]] = 0
            rows[d, hr[real], :Sh] = self.hard_nbr_rows[d, real]
            mask[d, hr[real], :Sh] = self.hard_mask[d, real]
            offs[d, hr[real], :Sh] = self.hard_offs[d, real]
        return rows, offs, mask

    @property
    def to_rows(self):  # [n_dev, L, T] int32 neighbors_to gather table
        return self._to_tables()[0]

    @property
    def to_offs(self):  # [n_dev, L, T, 3] int32
        return self._to_tables()[1]

    @property
    def to_mask(self):  # [n_dev, L, T] bool
        return self._to_tables()[2]


@dataclass
class _Plan:
    """Full structure epoch: row layout + per-neighborhood tables."""

    cells: np.ndarray  # sorted uint64, all cells (replicated)
    owner: np.ndarray  # int32 per cell
    n_dev: int
    L: int  # local-row capacity
    R: int  # total rows per device (L + ghost cap + 1 zero row)
    n_local: np.ndarray  # [n_dev]
    local_ids: list  # per device: uint64 ids in row order [inner|outer]
    row_of_pos: np.ndarray  # int32 [n_cells]: row on the OWNER device
    ghost_ids: list  # per device: uint64 ids in ghost-row order
    hoods: dict = dataclass_field(default_factory=dict)  # hood id -> _HoodPlan
    epoch: int = 0


class Grid:
    """Distributed cartesian cell-refinable grid on a TPU mesh.

    Mirrors the reference's fluent construction protocol
    (dccrg.hpp:8242-8357):

        grid = (Grid(cell_data={"density": jnp.float32})
                .set_initial_length((64, 64, 64))
                .set_periodic(True, True, True)
                .set_maximum_refinement_level(2)
                .set_neighborhood_length(1)
                .initialize(mesh))
    """

    def __init__(self, cell_data=None, dtype=None):
        # field spec: name -> (shape tuple, dtype). ``dtype`` is the
        # grid-wide storage override: every FLOATING field is re-typed
        # to it (bfloat16 halves the state's HBM residency and
        # exchange/checkpoint bytes; the weakly-typed flux kernels keep
        # computing in float32). float32 stays the default; integer/
        # bool fields keep their declared types either way.
        self.fields = {}
        self.state_dtype = None if dtype is None else jnp.dtype(dtype)
        for name, spec in (cell_data or {}).items():
            if isinstance(spec, tuple):
                shape, fdt = spec
            else:
                shape, fdt = (), spec
            fdt = jnp.dtype(fdt)
            if self.state_dtype is not None and jnp.issubdtype(
                    fdt, jnp.floating):
                fdt = self.state_dtype
            self.fields[name] = (tuple(shape), fdt)
        self._length = (1, 1, 1)
        self._max_ref_lvl = 0
        self._periodic = (False, False, False)
        self._hood_len = 1
        self._lb_method = "morton"
        self._geometry_kind = ("none", {})
        self.initialized = False
        # AMR request state
        self._refines = set()
        self._unrefines = set()
        self._dont_refines = set()
        self._dont_unrefines = set()
        self._removed_cells = np.empty(0, np.uint64)
        self._removed_data = {}
        self._new_cells = np.empty(0, np.uint64)
        # load balancing state
        self._staged_balance = {}
        self._pins = {}
        self._weights = {}
        self._partitioning_options = {}
        self._partitioning_levels = []  # hierarchical partitioning
        # per-field transfer predicates (receiver-dependent payloads)
        self._transfer_predicates = {}
        # capacity hysteresis memo (see _sticky_cap)
        self._cap_memo = {}
        # compiled-program cache, keyed by the STATIC shape signature
        # (L, R, flags, kernel, ...) — never invalidated by structure
        # epochs: with bucketed capacities (bucket_capacity) a rebuild
        # that lands in the same buckets reuses every compiled program
        self._program_cache = {}
        self._pending = {}
        self._txn_depth = 0  # reentrancy counter (txn.grid_transaction)
        # delta-checkpoint dirty tracking (resilience/supervise delta
        # saves): fields whose SAVED bytes may differ from the last
        # checkpoint baseline (None = everything — the conservative
        # state every wholesale load or structural rebuild resets to),
        # and the structure epoch deltas are only valid within (any
        # cell-set or partition change bumps it and forces a keyframe)
        self._ckpt_dirty = None
        self._ckpt_epoch = 0
        self._debug = os.environ.get("DCCRG_DEBUG") == "1"
        # extensible iteration-cache items (dccrg.hpp:7404-7518)
        self._cell_items = {}
        self._cell_item_values = {}
        self._neighbor_items = {}
        self._neighbor_item_values = {}

    # -- fluent pre-initialize setters (dccrg.hpp:8242-8357) ----------

    def _require_uninitialized(self):
        if self.initialized:
            raise RuntimeError("must be called before initialize()")

    def set_initial_length(self, length):
        self._require_uninitialized()
        self._length = tuple(int(v) for v in length)
        return self

    def set_maximum_refinement_level(self, lvl: int):
        """Negative means the maximum possible (dccrg.hpp:8264)."""
        self._require_uninitialized()
        self._max_ref_lvl = int(lvl)
        return self

    def set_periodic(self, x: bool, y: bool, z: bool):
        self._require_uninitialized()
        self._periodic = (bool(x), bool(y), bool(z))
        return self

    def set_neighborhood_length(self, n: int):
        self._require_uninitialized()
        if n < 0:
            raise ValueError("neighborhood length must be >= 0")
        self._hood_len = int(n)
        return self

    def set_load_balancing_method(self, method: str):
        if method not in PARTITION_METHODS:
            raise ValueError(f"unknown method {method!r}, have {PARTITION_METHODS}")
        self._lb_method = method
        return self

    def set_geometry(self, kind="cartesian", **params):
        """kind: 'none' | 'cartesian' (start, level_0_cell_length) |
        'stretched' (coordinates)."""
        self._require_uninitialized()
        if kind not in ("none", "cartesian", "stretched"):
            raise ValueError(f"unknown geometry kind {kind!r}")
        self._geometry_kind = (kind, params)
        return self

    # -- initialization (dccrg.hpp:480-562) ---------------------------

    def initialize(self, mesh: Mesh | None = None, partition: str | None = None):
        self._require_uninitialized()
        self.mesh = mesh if mesh is not None else default_mesh()
        if len(self.mesh.axis_names) != 1:
            raise ValueError("Grid needs a 1-D mesh (axis 'dev')")
        # Multi-process (jax.distributed) meshes are supported: every
        # process runs the same program over the same replicated inputs,
        # so each computes the SAME plan (all partitioners are
        # deterministic numpy) — exactly how every MPI rank in the
        # reference holds the same cell_process map
        # (dccrg.hpp:7311, 7738-7803). What changes per process is only
        # which shards the HOST paths may touch: uploads go through
        # put_sharded (each process serves its addressable shards),
        # get/set are restricted to cells on addressable devices (the
        # reference's rank-local access semantics), and checkpoint I/O
        # writes per-process slices. Collectives (ppermute halo
        # exchange, psum reductions) are mesh-shape agnostic.
        self._proc_local_dev = np.fromiter(
            (d.process_index == jax.process_index()
             for d in self.mesh.devices.flat),
            dtype=bool, count=self.mesh.devices.size,
        )
        # checkpoint-coordination identity: None = use
        # jax.process_index(); the faked test splits pin a per-pass
        # rank here (coord.process_rank, checkpoint._save_process_slice)
        self._ckpt_rank = None
        self.axis = self.mesh.axis_names[0]
        self.n_dev = self.mesh.devices.size

        self.mapping = Mapping(self._length)
        if self._max_ref_lvl < 0:
            self.mapping.set_maximum_refinement_level(
                self.mapping.get_maximum_possible_refinement_level()
            )
        elif not self.mapping.set_maximum_refinement_level(self._max_ref_lvl):
            raise ValueError(
                f"maximum refinement level {self._max_ref_lvl} not possible "
                f"for grid {self._length}"
            )
        self.topology = GridTopology(self._periodic)
        kind, params = self._geometry_kind
        if kind == "none":
            self.geometry = NoGeometry(self.mapping, self.topology)
        elif kind == "cartesian":
            self.geometry = CartesianGeometry(self.mapping, self.topology, **params)
        else:
            self.geometry = StretchedCartesianGeometry(self.mapping, self.topology, **params)

        self.neighborhoods = {DEFAULT_NEIGHBORHOOD_ID: make_neighborhood(self._hood_len)}

        # level-0 cells, partitioned (create_level_0_cells, dccrg.hpp:8089)
        n0 = self.mapping.length.total_level0_cells
        cells = np.arange(1, n0 + 1, dtype=np.uint64)
        owner = partition_cells(
            self.mapping, cells, self.n_dev, partition or self._lb_method,
            pins=self._pins or None,
        )
        self.initialized = True
        self._build_plan(cells, owner)
        self._allocate_fields()
        if self._debug:
            from . import verify as _verify

            _verify.pin_requests_succeeded(self)
        return self

    def clone(self, cell_data=None) -> "Grid":
        """New grid with identical structure (cells, owners, neighbor
        tables, neighborhoods, pins, weights) but its own — default-
        initialized — cell data, optionally of a different schema: the
        reference's cross-Cell_Data copy constructor (dccrg.hpp:344-446).
        """
        if not self.initialized:
            raise RuntimeError("clone() requires an initialized grid")
        spec = cell_data if cell_data is not None else {
            name: (shape, dtype) for name, (shape, dtype) in self.fields.items()
        }
        other = Grid(cell_data=spec)
        other._length = self._length
        other._max_ref_lvl = self._max_ref_lvl
        other._periodic = self._periodic
        other._hood_len = self._hood_len
        other._lb_method = self._lb_method
        other._geometry_kind = self._geometry_kind
        other._pins = dict(self._pins)
        other._weights = dict(self._weights)
        other._partitioning_options = dict(self._partitioning_options)
        other._partitioning_levels = [dict(lv) for lv in self._partitioning_levels]
        other.mesh = self.mesh
        other.axis = self.axis
        other.n_dev = self.n_dev
        other._proc_local_dev = self._proc_local_dev.copy()
        other._ckpt_rank = self._ckpt_rank
        other.mapping = Mapping(
            tuple(int(v) for v in self.mapping.length.get()),
            self.mapping.max_refinement_level,
        )
        other.topology = GridTopology(self._periodic)
        kind, params = self._geometry_kind
        if kind == "none":
            other.geometry = NoGeometry(other.mapping, other.topology)
        elif kind == "cartesian":
            other.geometry = CartesianGeometry(other.mapping, other.topology, **params)
        else:
            other.geometry = StretchedCartesianGeometry(other.mapping, other.topology, **params)
        other.neighborhoods = {hid: offs.copy() for hid, offs in self.neighborhoods.items()}
        other.initialized = True
        other._build_plan(self.plan.cells.copy(), self.plan.owner.copy())
        other._allocate_fields()
        return other

    def neighbor_devices(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID) -> np.ndarray:
        """[n_dev, n_dev] bool: entry [q, p] true when device q receives
        halo data from device p under the neighborhood — the peer sets
        the reference's Some_Reduce reduces over (its process-boundary
        peers, dccrg_mpi_support.hpp:285-380)."""
        c = self.plan.hoods[neighborhood_id].pair_compact
        out = np.zeros((self.n_dev, self.n_dev), dtype=bool)
        out[c["q"], c["p"]] = True
        return out

    # capacities whose arrays are small but whose need varies a lot
    # epoch-to-epoch (hard-shell sizes, pair lists, fixup widths):
    # give them a 2x band so shapes virtually never change
    _WIDE_CAPS = ("G", "M", "S", "S_hard", "Hmax", "T_hard", "rollW", "removed")

    def _sticky_cap(self, name, needed: int) -> int:
        """Capacity with hysteresis: grow in buckets with headroom,
        keep the previous capacity while the need still fits, shrink
        only once the need drops well below it — epoch-to-epoch
        structural churn then keeps array shapes identical, so the
        shape-keyed compiled programs are reused instead of
        recompiled."""
        needed = int(needed)
        base = name[0] if isinstance(name, tuple) else name
        wide = base in self._WIDE_CAPS
        prev = self._cap_memo.get(name)
        if prev is not None and needed <= prev and base == "removed":
            return prev  # tiny index buffer: never shrink
        if prev is not None and prev // (4 if wide else 2) <= needed <= prev:
            return prev
        if prev is None:
            # first build: exact bucket (a static grid should not pay
            # growth headroom it will never use)
            cap = bucket_capacity(needed)
        else:
            # headroom absorbs drift (a refined region that wanders
            # grows some devices' loads a little every epoch); the big
            # L arrays get 25%, the small high-variance ones 2x
            cap = bucket_capacity(needed * 2 if wide else needed + needed // 4)
        self._cap_memo[name] = cap
        return cap

    # -- structure plan building --------------------------------------

    def _build_plan(self, cells: np.ndarray, owner: np.ndarray,
                    changed_hint=None):
        """Rebuild all derived structure: the equivalent of the
        reference's initialize_neighbors + update_remote_neighbor_info +
        recalculate_neighbor_update_send_receive_lists +
        update_cell_pointers pipeline (dccrg.hpp:8371-8420).
        ``changed_hint`` is ``(prev_cells, changed_ids)`` from a
        structure mutation that knows its own dirty set (see
        hybrid.build_hybrid_plan); only the hybrid path consumes it."""
        self._finish_plan(self._construct_plan(cells, owner, changed_hint))

    def _construct_plan(self, cells: np.ndarray, owner: np.ndarray,
                        changed_hint=None):
        """Build a complete structure plan for ``(cells, owner)``
        WITHOUT installing it — the pure half of a rebuild, safe to run
        on a background worker thread while the step loop keeps
        dispatching against the live plan (DCCRG_BG_RECOMMIT; see
        dccrg_tpu.background.PlanBuildWorker). Reads only structural
        inputs and the build caches (capacity memo, hybrid stream-reuse
        cache, plan arena — never the field data), and builds are
        serialized per grid, so the result is bitwise identical to the
        synchronous path's."""
        plan = self._build_plan_impl(cells, owner, changed_hint)
        # the builder's large temporaries are dead only once the impl
        # frame is gone; trim here so malloc_trim can actually return
        # the build's peak to the OS (the arena-held tables stay
        # resident — that is the point)
        if len(cells) > 1 << 20:
            _trim_allocator()
        return plan

    def _build_plan_impl(self, cells: np.ndarray, owner: np.ndarray,
                         changed_hint=None):
        _tune_allocator()
        n_dev = self.n_dev
        if len(cells) > 1 and not np.all(cells[:-1] < cells[1:]):
            order = np.argsort(cells, kind="stable")
            cells = cells[order]
            owner = np.asarray(owner, dtype=np.int32)[order]
        else:  # already sorted (every initialize(); most rebuilds)
            owner = np.asarray(owner, dtype=np.int32)

        # all-level-0 grids take the closed-form fast path (uniform.py):
        # identical tables, no entry stream, bounded temporaries. Both
        # its native and numpy builders index cells with int32, so the
        # fast path is gated at 2^31 cells (the generic path below and
        # the reference's uint64 ids have no such bound).
        n0 = self.mapping.length.total_level0_cells
        if uniform_mod.is_uniform(cells, n0) and n0 < 2**31 - 2:
            return self._build_plan_uniform(cells, owner)

        # refined grids take the hybrid path (hybrid.py): closed-form
        # tables away from refinement, generic engine for the hard
        # subset near it — O(refinement surface), not O(grid)
        if n0 < 2**31 - 2 and os.environ.get("DCCRG_FORCE_GENERIC") != "1":
            return self._build_plan_hybrid(cells, owner, changed_hint)

        # per-hood neighbor lists (host), with neighbor positions in the
        # sorted cell array resolved once per hood (reused everywhere)
        hood_lists = {
            hid: build_neighbor_lists(self.mapping, self.topology, cells, offs)
            for hid, offs in self.neighborhoods.items()
        }
        hood_gidx = {
            hid: (np.searchsorted(cells, hl.of_neighbor),
                  np.searchsorted(cells, hl.to_neighbor))
            for hid, hl in hood_lists.items()
        }

        # remote-dependency classification against the union of hoods
        # (the reference tracks boundary cells per neighborhood;
        # rows are ordered by the default hood's classification)
        nl = hood_lists[DEFAULT_NEIGHBORHOOD_ID]
        nbr_idx, to_nbr_idx = hood_gidx[DEFAULT_NEIGHBORHOOD_ID]
        src_owner = owner[nl.of_source]
        nbr_owner = owner[nbr_idx]
        remote_edge = src_owner != nbr_owner
        # outer: local cell with a remote neighbor in of- or to-lists
        outer_flag = np.zeros(len(cells), dtype=bool)
        np.add.at(outer_flag, nl.of_source[remote_edge], True)
        remote_to = owner[nl.to_source] != owner[to_nbr_idx]
        np.add.at(outer_flag, nl.to_source[remote_to], True)

        local_ids, ghost_ids, n_inner_arr = [], [], np.zeros(n_dev, np.int64)
        for d in range(n_dev):
            mine = owner == d
            inner = cells[mine & ~outer_flag]
            outer = cells[mine & outer_flag]
            local_ids.append(np.concatenate([inner, outer]))
            n_inner_arr[d] = len(inner)
            # ghosts: remote cells this device reads (neighbors_of of its
            # cells) or must send to (covered by send lists); ghost rows
            # only store copies we receive -> remote neighbors_of plus
            # remote neighbors_to sources we *read* in to-gathers.
            gh = []
            for hid, hl in hood_lists.items():
                of_g, to_g = hood_gidx[hid]
                m = (owner[hl.of_source] == d) & (owner[of_g] != d)
                gh.append(hl.of_neighbor[m])
                m2 = (owner[hl.to_source] == d) & (owner[to_g] != d)
                gh.append(hl.to_neighbor[m2])
            ghost_ids.append(np.unique(np.concatenate(gh)) if gh else
                             np.empty(0, np.uint64))

        n_local = np.array([len(x) for x in local_ids], dtype=np.int64)
        n_ghost = np.array([len(x) for x in ghost_ids], dtype=np.int64)
        L = self._sticky_cap("L", max(1, int(n_local.max())))
        G = int(n_ghost.max()) if n_dev > 1 else 0
        G = self._sticky_cap("G", G) if G else 0
        R = L + G + 1  # final row = permanent zero pad

        # row lookups: row_by_gidx[d][global cell index] -> row on
        # device d (or -1), used by the table builders; row_of_pos is
        # the owner-device row per cell (host get/set lookups).
        row_by_gidx = np.full((n_dev, len(cells)), -1, dtype=np.int32)
        row_of_pos = np.full(len(cells), -1, dtype=np.int32)
        for d in range(n_dev):
            lpos = np.searchsorted(cells, local_ids[d])
            lrows = np.arange(len(local_ids[d]), dtype=np.int32)
            row_by_gidx[d, lpos] = lrows
            row_of_pos[lpos] = lrows
            if len(ghost_ids[d]):
                row_by_gidx[d, np.searchsorted(cells, ghost_ids[d])] = L + np.arange(
                    len(ghost_ids[d]), dtype=np.int32
                )

        plan = _Plan(
            cells=cells,
            owner=owner,
            n_dev=n_dev,
            L=L,
            R=R,
            n_local=n_local,
            local_ids=local_ids,
            row_of_pos=row_of_pos,
            ghost_ids=ghost_ids,
        )

        for hid, offs in self.neighborhoods.items():
            plan.hoods[hid] = self._build_hood_plan(
                plan, hood_lists[hid], offs,
                n_inner_arr if hid == DEFAULT_NEIGHBORHOOD_ID else None,
                hood_gidx[hid], row_by_gidx, hid,
            )
        return plan

    def _build_plan_uniform(self, cells: np.ndarray, owner: np.ndarray):
        """Closed-form plan construction for all-level-0 grids
        (uniform.py): same layout and tables as the generic path, no
        neighbor-entry stream, bounded temporaries."""
        layout, hood_data = uniform_mod.build_uniform_plan(
            self.mapping, self.topology, self.neighborhoods, cells, owner,
            self.n_dev, cap=self._sticky_cap,
        )
        plan = _Plan(
            cells=cells,
            owner=owner,
            n_dev=self.n_dev,
            L=layout["L"],
            R=layout["R"],
            n_local=layout["n_local"],
            local_ids=layout["local_ids"],
            row_of_pos=layout["row_of_pos"],
            ghost_ids=layout["ghost_ids"],
        )
        mapping, topology = self.mapping, self.topology
        for hid, offs in self.neighborhoods.items():
            hd = hood_data[hid]

            def lists_thunk(offs=offs):
                return build_neighbor_lists(mapping, topology, cells, offs)

            closed = "closed_form" in hd
            hood = _HoodPlan(
                offsets=offs,
                nbr_rows=hd["tables_thunk"] if closed else hd["nbr_rows"],
                nbr_offs=hd["nbr_offs"],
                nbr_mask=hd["tables_thunk"] if closed else hd["nbr_mask"],
                offs_const=hd["offs_const"],
                closed_form=hd.get("closed_form"),
                to_tables=hd["to_thunk"],
                pair_compact=hd["pair_compact"],
                n_inner=(layout["n_inner"]
                         if hid == DEFAULT_NEIGHBORHOOD_ID else None),
                lists=lists_thunk,
            )
            if closed:
                # roll shifts + wrap fixups were computed arithmetically
                hood._roll_plan = hd["roll_plan"]
            plan.hoods[hid] = hood
        return plan

    def _build_plan_hybrid(self, cells: np.ndarray, owner: np.ndarray,
                           changed_hint=None):
        """Plan construction for refined grids (hybrid.py): closed-form
        lattice tables for level-0 cells away from refinement, generic
        engine only for the hard subset near it. Same layout and
        semantics as the generic builder."""
        from . import hybrid as hybrid_mod

        if getattr(self, "_hybrid_reuse", None) is None:
            # epoch-to-epoch cache of the hard-shell neighbor streams
            # (see hybrid.py): only the dirty region reruns the engine
            self._hybrid_reuse = {}
        if getattr(self, "_plan_arena", None) is None:
            # pooled backing stores of the big plan tables, reused
            # across structure epochs so a recommit never faults in
            # multi-GB fresh pages (see hybrid.PlanArena)
            self._plan_arena = hybrid_mod.PlanArena()
        arena = self._plan_arena
        # the live plan and the active transaction's rollback snapshot
        # keep their buffers; everything older is recycled — an aborted
        # build can never have scribbled on a plan a rollback restores
        arena.begin(protect=(getattr(self, "plan", None),
                             getattr(self, "_txn_plan", None)))
        layout, hood_data = hybrid_mod.build_hybrid_plan(
            self.mapping, self.topology, self.neighborhoods, cells, owner,
            self.n_dev, cap=self._sticky_cap, reuse=self._hybrid_reuse,
            arena=arena, changed_hint=changed_hint,
        )
        plan = _Plan(
            cells=cells,
            owner=owner,
            n_dev=self.n_dev,
            L=layout["L"],
            R=layout["R"],
            n_local=layout["n_local"],
            local_ids=layout["local_ids"],
            row_of_pos=layout["row_of_pos"],
            ghost_ids=layout["ghost_ids"],
        )
        arena.bind(plan)
        mapping, topology = self.mapping, self.topology
        for hid, offs in self.neighborhoods.items():
            hd = hood_data[hid]

            def lists_thunk(offs=offs):
                return build_neighbor_lists(mapping, topology, cells, offs)

            plan.hoods[hid] = _HoodPlan(
                offsets=offs,
                nbr_rows=hd["nbr_rows"],
                nbr_offs=hd["nbr_offs"],
                nbr_mask=hd["nbr_mask"],
                offs_const=hd["offs_const"],
                hard_rows=hd["hard_rows"],
                hard_nbr_rows=hd["hard_nbr_rows"],
                hard_offs=hd["hard_offs"],
                hard_mask=hd["hard_mask"],
                scale_rows=layout["scale_rows"],
                to_tables=hd["to_thunk"],
                pair_compact=hd["pair_compact"],
                n_inner=(layout["n_inner"]
                         if hid == DEFAULT_NEIGHBORHOOD_ID else None),
                lists=lists_thunk,
            )
        return plan

    def _finish_plan(self, plan: _Plan):
        plan.epoch = getattr(self, "plan", None).epoch + 1 if getattr(self, "plan", None) else 0
        self.plan = plan
        # any rebuild invalidates a gather mode forced by the OOM
        # fallback (resilience._apply_mode re-pins and re-marks it)
        self._plan_gather_mode = None
        # compiled programs are shape-keyed and survive the epoch; the
        # per-epoch device tables live on the (replaced) hood plans

        self._update_data_items()

        # continuous self-checking, like the reference's DEBUG builds
        # (dccrg.hpp:12454-13036). User data is still mid-migration at
        # this point; _restructure/_allocate_fields check it after.
        # Inside a transaction the post-commit verify_all covers these
        # same checks (and more) on the final state — skip the
        # mid-commit pass rather than paying the O(grid) neighbor
        # recompute twice per mutation.
        if self._debug and not getattr(self, "_txn_depth", 0):
            from . import verify as _verify

            _verify.is_consistent(self)
            _verify.verify_neighbors(self)
            _verify.verify_remote_neighbor_info(self)
            # pin placement is checked where pins are APPLIED
            # (initialize / balance_load / load_cells): a pin made
            # between balance_loads only takes effect at the next one
            # (dccrg.hpp:5913-6139)

    def _build_hood_plan(self, plan: _Plan, nl, offsets, n_inner_arr, gidx,
                         row_by_gidx, hid):
        n_dev, L, R = plan.n_dev, plan.L, plan.R
        cells, owner = plan.cells, plan.owner

        def build_table(src_gidx, nbr_gidx, offs_arr):
            """Pad ragged per-cell entries into [n_dev, L, S] tables —
            fully vectorized (the entry stream is already ordered by
            source cell, so a stable sort by (device, source row) keeps
            each cell's neighborhood-item order)."""
            entry_dev = owner[src_gidx].astype(np.int64)
            src_rows = row_by_gidx[entry_dev, src_gidx].astype(np.int64)
            nrows = row_by_gidx[entry_dev, nbr_gidx]
            # every neighbor must have a row (local or ghost) on the
            # source's device — -1 would silently alias the pad row
            if len(nrows) and int(nrows.min()) < 0:
                raise AssertionError(
                    "ghost coverage bug: neighbor without a row on its "
                    "reader's device"
                )
            key = entry_dev * L + src_rows
            order = np.argsort(key, kind="stable")
            ksort = key[order]
            n = len(ksort)
            if n == 0:
                S = 1
                return (
                    np.full((n_dev, L, S), R - 1, dtype=np.int32),
                    np.zeros((n_dev, L, S, 3), dtype=np.int32),
                    np.zeros((n_dev, L, S), dtype=bool),
                )
            # slot = rank of the entry within its (device, row) group
            change = np.empty(n, dtype=bool)
            change[0] = True
            change[1:] = ksort[1:] != ksort[:-1]
            group_start = np.maximum.accumulate(
                np.where(change, np.arange(n), 0)
            )
            slot = np.arange(n) - group_start
            S = self._sticky_cap(("S", hid), max(1, int(slot.max()) + 1))
            rows = np.full((n_dev * L * S,), R - 1, dtype=np.int32)
            offs = np.zeros((n_dev * L * S, 3), dtype=np.int32)
            mask = np.zeros((n_dev * L * S,), dtype=bool)
            flat = ksort * S + slot
            rows[flat] = nrows[order]
            offs[flat] = offs_arr[order]
            mask[flat] = True
            return (
                rows.reshape(n_dev, L, S),
                offs.reshape(n_dev, L, S, 3),
                mask.reshape(n_dev, L, S),
            )

        nbr_rows, nbr_offs, nbr_mask = build_table(
            nl.of_source, gidx[0], nl.of_offset
        )

        def to_tables():
            return build_table(nl.to_source, gidx[1], nl.to_offset)

        # --- halo send/receive lists (dccrg.hpp:8729-8891) ---
        # device q receives every remote neighbor it reads; sender p is
        # that cell's owner. Lists sorted by cell id. Keys are cell
        # POSITIONS (ids are sorted, so position order == id order);
        # the shared lexsort-grouping construction lives in uniform.py.
        ghost_pos = [np.searchsorted(cells, plan.ghost_ids[q])
                     for q in range(n_dev)]
        pair_compact = uniform_mod.build_pair_tables(
            ghost_pos, n_dev,
            lambda keys: owner[keys],
            lambda p_s, keys: row_by_gidx[p_s, keys],
            lambda q_s, keys, gpos: row_by_gidx[q_s, keys],
            lambda needed: self._sticky_cap(("M", hid), needed),
        )

        return _HoodPlan(
            offsets=offsets,
            nbr_rows=nbr_rows,
            nbr_offs=nbr_offs,
            nbr_mask=nbr_mask,
            to_tables=to_tables,
            pair_compact=pair_compact,
            n_inner=(n_inner_arr if n_inner_arr is not None else None),
            lists=nl,
        )

    # -- field storage -------------------------------------------------

    def _sharding(self):
        return NamedSharding(self.mesh, P(self.axis))

    @property
    def _multiproc(self) -> bool:
        """True when the mesh spans processes this controller cannot
        address (jax.distributed SPMD, or a test faking it)."""
        return not bool(self._proc_local_dev.all())

    def _require_local(self, dev, what):
        """Multi-process host access is rank-local, as in the
        reference: a process touches only cells on its own devices
        (dccrg.hpp operator[] is valid for local cells)."""
        if self._multiproc and not self._proc_local_dev[dev].all():
            raise KeyError(
                f"{what}: cell(s) live on devices owned by another "
                "process; host access is process-local on multi-process "
                "meshes (like the reference's rank-local operator[])"
            )

    def _shard_read(self, field, dev, rows):
        """Host read via per-device addressable shards — no collective,
        valid under multi-process for process-local cells. Rows are
        sliced ON the device shard before the host copy, so a few-cell
        read transfers only those rows, not the whole shard."""
        arr = self.data[field]
        by_dev = {}
        for s in arr.addressable_shards:
            by_dev[s.index[0].start] = s.data
        out = np.empty((len(dev),) + arr.shape[2:], dtype=arr.dtype)
        for d in np.unique(dev):
            m = dev == d
            out[m] = np.asarray(by_dev[int(d)][0, rows[m]])
        return out

    def _allocate_fields(self):
        self.data = {}
        sh = self._sharding()
        for name, (shape, dtype) in self.fields.items():
            full = (self.n_dev, self.plan.R) + shape
            # jit-produced zeros (not a host transfer): valid on
            # multi-process meshes where device_put of host zeros isn't
            key = ("zeros", full, str(dtype))
            fn = self._program_cache.get(key)
            if fn is None:
                fn = jax.jit(partial(jnp.zeros, full, dtype),
                             out_shardings=sh)
                self._program_cache[key] = fn
            self.data[name] = fn()
        self._mark_ckpt_dirty()

    def _mark_ckpt_dirty(self, fields=None) -> None:
        """Record fields whose saved bytes may have changed since the
        last delta-checkpoint baseline (consumed by the incremental
        save path in :mod:`dccrg_tpu.supervise` / resilience).
        ``None`` marks everything dirty. Ghost-only writes (halo
        exchanges) never call this: checkpoints serialize owned rows
        only, so ghost refreshes cannot change the saved bytes."""
        if fields is None:
            self._ckpt_dirty = None
        elif getattr(self, "_ckpt_dirty", None) is not None:
            self._ckpt_dirty.update(fields)

    def device_row_ids(self) -> "jnp.ndarray":
        """Sharded ``[n_dev, R] int32`` array of ``cell id - 1`` per
        row (``-1`` on pad rows) — the device-side mirror of
        ``plan.local_ids``/``ghost_ids``, for initializing fields ON
        device instead of staging host arrays (on uniform grids the
        geometry center is affine in this index, so e.g. a 512^3 field
        init needs no host f64 centers at all; the reference
        initializes in one pass over already-resident memory,
        tests/advection/initialize.hpp:36-80). Cached per structure
        epoch. On a complete single-device level-0 grid the array is
        synthesized from an iota without any host staging."""
        plan = self.plan
        cached = getattr(plan, "_row_ids_dev", None)
        if cached is not None:
            return cached
        n0 = self.mapping.length.total_level0_cells
        if (self.n_dev == 1 and len(plan.cells) == n0
                and int(plan.cells[-1]) == n0):
            # complete level-0 grid, one device: rows are id order
            idx = jnp.arange(plan.R, dtype=jnp.int32)[None, :]
            arr = jnp.where(idx < n0, idx, jnp.int32(-1))
            arr = jax.device_put(arr, self._sharding())
        else:
            # int64 rows when ids exceed int32 (deeply refined AMR
            # grids): the closed-form multi stencil path can never get
            # here (build_uniform_plan is gated at < 2^31 cells), so
            # only field-init consumers see the wide dtype. Without
            # x64, jnp.asarray would silently WRAP int64 to int32 —
            # keep the loud failure in that configuration.
            wide = bool(len(plan.cells)
                        and int(plan.cells[-1]) > np.iinfo(np.int32).max)
            if wide and not jax.config.jax_enable_x64:
                raise ValueError(
                    "cell ids exceed int32 and JAX x64 is disabled; "
                    "enable jax_enable_x64 for device_row_ids() on "
                    "deeply refined grids, or initialize via set_many"
                )
            host = np.full((self.n_dev, plan.R), -1,
                           dtype=np.int64 if wide else np.int32)
            for d in range(self.n_dev):
                nl = int(plan.n_local[d])
                host[d, :nl] = plan.local_ids[d].astype(np.int64) - 1
                ng = len(plan.ghost_ids[d])
                if ng:  # ghost rows sit at [L, L+ng) (see hybrid.py)
                    host[d, plan.L : plan.L + ng] = (
                        plan.ghost_ids[d].astype(np.int64) - 1
                    )
            arr = put_sharded(host, self._sharding())
        plan._row_ids_dev = arr
        return arr

    def local_row_mask(self) -> "jnp.ndarray":
        """Sharded ``[n_dev, R] float32`` mask: 1 on local rows, 0 on
        ghost and pad rows — the device-side reduction mask (masked
        sums / dots over owned cells only). Built on device from an
        iota and cached per structure epoch (on the plan object, so a
        same-bucket repartition that keeps array shapes still
        invalidates it)."""
        plan = self.plan
        cached = getattr(plan, "_local_mask_dev", None)
        if cached is not None:
            return cached
        fn = getattr(self, "_local_mask_fn", None)
        if fn is None:
            @partial(jax.jit, static_argnames=("shape",),
                     out_shardings=self._sharding())
            def fn(nl, shape):
                rows = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
                return (rows < nl).astype(jnp.float32)

            self._local_mask_fn = fn
        nl = jnp.asarray(np.asarray(plan.n_local)[:, None].astype(np.int32))
        arr = fn(nl, shape=(self.n_dev, plan.R))
        plan._local_mask_dev = arr
        return arr

    def _host_rows(self, ids):
        """(device, row) for each cell id (host lookup)."""
        ids = np.atleast_1d(np.asarray(ids, dtype=np.uint64))
        if ids is self.plan.cells or (
            len(ids) == len(self.plan.cells) and ids[0] == self.plan.cells[0]
            and ids[-1] == self.plan.cells[-1]
            and np.array_equal(ids, self.plan.cells)
        ):
            # whole-grid access (init paths): skip the binary search
            return self.plan.owner.copy(), self.plan.row_of_pos.astype(np.int64)
        pos = np.searchsorted(self.plan.cells, ids)
        if np.any(pos >= len(self.plan.cells)) or np.any(self.plan.cells[np.minimum(pos, len(self.plan.cells)-1)] != ids):
            if getattr(self, "_bg_build", None) is not None:
                # a deferred recommit (DCCRG_BG_RECOMMIT) may hold the
                # epoch these ids belong to — the adapt-then-project
                # pattern reads/writes new children right after
                # stop_refining. A data access that NEEDS the new
                # epoch IS a boundary: install (blocking) and retry,
                # so apps stay oblivious while accesses the live epoch
                # can serve keep costing nothing.
                self.bg_install(wait=True)
                return self._host_rows(ids)
            raise KeyError("unknown cell id(s)")
        dev = self.plan.owner[pos]
        rows = self.plan.row_of_pos[pos].astype(np.int64)
        return dev, rows

    def get(self, field: str, ids) -> np.ndarray:
        """Host read of per-cell data (reference operator[] access).
        Small queries gather ON device and pull only the requested
        rows (a full 512^3 field is half a GB; a few cells should not
        cost a whole-array transfer); large/whole-grid reads pull the
        array once."""
        scalar = np.isscalar(ids) or np.asarray(ids).ndim == 0
        dev, rows = self._host_rows(ids)
        if self._multiproc:
            # rank-local access, via addressable shards (no collective:
            # other processes may be get()ing different cells)
            self._require_local(dev, "get")
            out = self._shard_read(field, dev, rows)
        elif (0 < len(rows) <= _GATHER_TIER
                and len(rows) < len(self.plan.cells) // 4):
            out = self._device_gather(field, dev, rows)
        else:
            host = np.asarray(self.data[field])
            out = host[dev, rows]
        return out[0] if scalar else out

    def _device_gather(self, name, dev, rows, cap=None):
        """Compact device-side gather of rows ``(dev, rows)`` of field
        ``name``: indices pad to a fixed tier (pad reads hit the zero
        pad row), every device extracts its own rows under shard_map,
        a psum merges them, and only [cap] rows cross to the host.
        One compiled program per (shape, dtype, R)."""
        shape, dtype = self.fields[name]
        n = len(rows)
        if cap is None:
            cap = _GATHER_TIER if n <= _GATHER_TIER else bucket_capacity(n)
        R = self.plan.R
        dev_p = np.zeros(cap, dtype=np.int32)
        row_p = np.full(cap, R - 1, dtype=np.int32)
        dev_p[:n] = dev
        row_p[:n] = rows
        key = ("devgather", shape, str(dtype), cap, R)
        fn = self._program_cache.get(key)
        if fn is None:
            mesh, axis = self.mesh, self.axis

            def body(arr, dv, rw):
                mine = dv == jax.lax.axis_index(axis)
                r = jnp.where(mine, rw, R - 1)  # zero pad row
                vals = arr[0, r]
                mexp = mine.reshape(mine.shape + (1,) * len(shape))
                vals = jnp.where(mexp, vals, jnp.zeros((), arr.dtype))
                return jax.lax.psum(vals, axis)

            fn = jax.jit(_shard_map(
                body, mesh=mesh,
                in_specs=(P(self.axis), P(), P()),
                out_specs=P(),
            ))
            self._program_cache[key] = fn
        from . import comm

        # the psum replicates the result on every device; pull through
        # comm so real multi-process meshes (not fully addressable
        # from one controller) read their local copy
        out = comm.pull_replicated(fn(self.data[name], jnp.asarray(dev_p),
                                      jnp.asarray(row_p)))
        # psum promotes bool to int; keep the field dtype for both paths
        return out[:n].astype(dtype, copy=False)

    def set(self, field: str, ids, values) -> None:
        """Host write of per-cell data (init / tests / boundary setup)."""
        self.set_many(ids, {field: values})

    def set_many(self, ids, values_by_field, preserve_ghosts=True) -> None:
        """Host write of several fields for the same cell set in one
        pass (the row resolution happens once). With
        ``preserve_ghosts=False`` and ``ids`` covering every cell, the
        old device arrays are not read back at all — ghost rows read
        zero until the next halo exchange refreshes them (the pattern
        of per-epoch static-field initialization)."""
        self._mark_ckpt_dirty(values_by_field)
        dev, rows = self._host_rows(ids)
        fresh = (not preserve_ghosts
                 and len(np.atleast_1d(np.asarray(ids))) == len(self.plan.cells))
        # single-device full-cover writes: with no ghosts there is no
        # inner/outer reorder, so rows are the identity and the scatter
        # is a contiguous copy
        identity = fresh and self.n_dev == 1 and len(rows) == len(self.plan.cells)
        # partial writes scatter ON DEVICE: only the written rows cross
        # the host boundary, instead of a full array pull + re-upload
        # per field (the staged-balance landing path and every host
        # set() ride this). On multi-process meshes every non-full
        # write rides this tier: the scatter has no collective and each
        # device applies only its own process's writes (rank-local set,
        # like the reference's operator[] assignment)
        # a TRUE cover (every cell exactly once) — a same-length list
        # with duplicates must not take the zero-filled merge below, or
        # the missed cell's data would be silently zeroed. The sort
        # only runs in the rare multi-process full-length case.
        full_cover = (
            self._multiproc and not fresh
            and len(np.atleast_1d(np.asarray(ids))) == len(self.plan.cells)
            and np.array_equal(
                np.sort(np.atleast_1d(np.asarray(ids, dtype=np.uint64))),
                self.plan.cells)
        )
        if full_cover:
            # replicated full-cover write with ghost preservation:
            # upload the new values (put_sharded serves local shards),
            # then merge ON DEVICE so old ghost rows survive — no
            # foreign-shard host read needed
            mask = self.local_row_mask() > 0
            sh = self._sharding()
            for name, values in values_by_field.items():
                shape, dtype = self.fields[name]
                host = np.zeros((self.n_dev, self.plan.R) + shape,
                                dtype=dtype)
                host[dev, rows] = values
                new = put_sharded(host, sh)
                key = ("covermerge", shape, str(dtype))
                fn = self._program_cache.get(key)
                if fn is None:
                    def _merge(old, nw, m, _nd=len(shape)):
                        mx = m.reshape(m.shape + (1,) * _nd)
                        return jnp.where(mx, nw, old)
                    fn = jax.jit(_merge, out_shardings=sh)
                    self._program_cache[key] = fn
                self.data[name] = fn(self.data[name], new, mask)
            return
        partial = ((not fresh) and len(rows) < len(self.plan.cells)
                   ) or (self._multiproc and not fresh)
        if self._multiproc and not fresh:
            self._require_local(dev, "set")
        for name, values in values_by_field.items():
            shape, dtype = self.fields[name]
            if fresh:
                # full-cover init: values are replicated across
                # processes (every process passes the whole grid's
                # values), so each process uploads its own shards
                host = np.zeros((self.n_dev, self.plan.R) + shape, dtype=dtype)
                if identity:
                    host[0, : len(rows)] = np.asarray(values, dtype=dtype)
                    self.data[name] = put_sharded(host, self._sharding())
                    continue
            elif partial:
                self.data[name] = self._device_scatter(
                    name, dev, rows, np.asarray(values, dtype=dtype))
                continue
            else:
                host = np.asarray(self.data[name]).copy()
            host[dev, rows] = values
            self.data[name] = put_sharded(host, self._sharding())

    def _device_scatter(self, name, dev, rows, values):
        """Masked per-device scatter of ``values`` into rows
        ``(dev, rows)`` of field ``name``: indices and values are
        padded to a bucketed capacity (pad writes land as zeros on the
        permanent zero pad row), broadcast to every device, and each
        device applies only its own writes under shard_map — no
        collective and no full-array host round trip."""
        shape, dtype = self.fields[name]
        # duplicate targets in one set_many: keep the LAST write, the
        # host path's (numpy) semantics — XLA scatter leaves the winner
        # among duplicate indices unspecified
        flat = dev.astype(np.int64) * self.plan.R + rows
        if len(np.unique(flat)) != len(flat):
            _, last_rev = np.unique(flat[::-1], return_index=True)
            keep = np.sort(len(flat) - 1 - last_rev)
            dev, rows = dev[keep], rows[keep]
            values = np.broadcast_to(
                values, (len(flat),) + self.fields[name][0])[keep]
        n = len(rows)
        # fixed small tier, then buckets: adapt-epoch projection writes
        # (new children / unrefined parents, surface-sized) all land in
        # ONE program per field regardless of their per-epoch drift
        # (the zero-new-programs invariant, test_advection_amr); only
        # rare large landings (balance restructure) take bucketed caps
        cap = _GATHER_TIER if n <= _GATHER_TIER else bucket_capacity(n)
        R = self.plan.R
        dev_p = np.zeros(cap, dtype=np.int32)
        row_p = np.full(cap, R - 1, dtype=np.int32)
        val_p = np.zeros((cap,) + shape, dtype=dtype)
        dev_p[:n] = dev
        row_p[:n] = rows
        if n:
            val_p[:n] = np.broadcast_to(values, (n,) + shape)
        # keyed by (shape, dtype), not field name: same-shaped fields
        # share one compiled scatter
        key = ("devscatter", shape, str(dtype), cap, R)
        fn = self._program_cache.get(key)
        if fn is None:
            mesh, axis = self.mesh, self.axis

            def body(arr, dv, rw, vl):
                mine = dv == jax.lax.axis_index(axis)
                r = jnp.where(mine, rw, R - 1)
                mexp = mine.reshape(mine.shape + (1,) * len(shape))
                safe = jnp.where(mexp, vl, jnp.zeros((), arr.dtype))
                return arr.at[0, r].set(safe, mode="drop")

            fn = jax.jit(_shard_map(
                body, mesh=mesh,
                in_specs=(P(self.axis), P(), P(), P()),
                out_specs=P(self.axis),
            ))
            self._program_cache[key] = fn
        return fn(self.data[name], jnp.asarray(dev_p), jnp.asarray(row_p),
                  jnp.asarray(val_p))

    # -- iteration views (dccrg.hpp:7594-7718) -------------------------

    # neighbor-type bitmask constants (dccrg.hpp:91-148)
    HAS_NO_NEIGHBOR = 0
    HAS_LOCAL_NEIGHBOR_OF = 1 << 0
    HAS_LOCAL_NEIGHBOR_TO = 1 << 1
    HAS_REMOTE_NEIGHBOR_OF = 1 << 2
    HAS_REMOTE_NEIGHBOR_TO = 1 << 3
    HAS_LOCAL_NEIGHBOR_BOTH = HAS_LOCAL_NEIGHBOR_OF | HAS_LOCAL_NEIGHBOR_TO
    HAS_REMOTE_NEIGHBOR_BOTH = HAS_REMOTE_NEIGHBOR_OF | HAS_REMOTE_NEIGHBOR_TO

    def neighbor_type_masks(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID) -> np.ndarray:
        """Per-cell neighbor-type bitmask in plan.cells order: which of
        each cell's neighbors_of / neighbors_to live on its own device
        ("local") vs another device (reference is_neighbor_type_match,
        dccrg.hpp:2968-3075)."""
        plan = self.plan
        nl = plan.hoods[neighborhood_id].lists
        masks = np.zeros(len(plan.cells), dtype=np.int32)
        of_nbr_owner = plan.owner[np.searchsorted(plan.cells, nl.of_neighbor)]
        same = plan.owner[nl.of_source] == of_nbr_owner
        np.bitwise_or.at(masks, nl.of_source[same], self.HAS_LOCAL_NEIGHBOR_OF)
        np.bitwise_or.at(masks, nl.of_source[~same], self.HAS_REMOTE_NEIGHBOR_OF)
        to_nbr_owner = plan.owner[np.searchsorted(plan.cells, nl.to_neighbor)]
        same_to = plan.owner[nl.to_source] == to_nbr_owner
        np.bitwise_or.at(masks, nl.to_source[same_to], self.HAS_LOCAL_NEIGHBOR_TO)
        np.bitwise_or.at(masks, nl.to_source[~same_to], self.HAS_REMOTE_NEIGHBOR_TO)
        return masks

    def get_cells(
        self,
        criteria=None,
        exact_match: bool = False,
        neighborhood_id=DEFAULT_NEIGHBORHOOD_ID,
    ) -> np.ndarray:
        """Cell ids, optionally filtered by neighbor-type criteria
        (reference get_cells, dccrg.hpp:661-753). Without criteria:
        every cell. With criteria: cells whose neighbor-type bitmask
        matches any criterion — equality under ``exact_match``,
        otherwise a non-empty intersection with the merged criteria.
        Always id-sorted (the reference's ``sorted`` flag exists because
        its hash-map iteration order is arbitrary; here there is only
        one order)."""
        if neighborhood_id not in self.plan.hoods:
            return np.empty(0, np.uint64)
        cells = self.plan.cells.copy()
        if criteria is None:
            return cells
        criteria = [int(c) for c in np.atleast_1d(criteria)]
        masks = self.neighbor_type_masks(neighborhood_id)
        if exact_match:
            keep = np.isin(masks, criteria)
        else:
            merged = 0
            for c in criteria:
                merged |= c
            keep = (masks & merged) > 0
        return cells[keep]

    # -- extensible iteration-cache items ------------------------------
    # (reference Additional_Cell_Items / Additional_Neighbor_Items,
    # dccrg.hpp:7404-7518: user mixins whose update() runs at cache
    # rebuild; e.g. Is_Local / Center in tests/advection/cell.hpp).
    # Here an item is a vectorized function evaluated over the whole
    # cell (or neighbor-entry) set at every structure rebuild.

    def add_cell_data_item(self, name: str, fn) -> None:
        """Register ``fn(grid, ids) -> array`` recomputed at every
        structure rebuild and cached for the epoch."""
        self._cell_items[name] = fn
        if self.initialized:
            self._cell_item_values[name] = np.asarray(fn(self, self.plan.cells))

    def remove_cell_data_item(self, name: str) -> None:
        self._cell_items.pop(name, None)
        self._cell_item_values.pop(name, None)

    def cell_data_item(self, name: str, ids=None) -> np.ndarray:
        """The cached item values, for all cells (plan order) or the
        given ids."""
        vals = self._cell_item_values[name]
        if ids is None:
            return vals.copy()
        scalar = np.isscalar(ids) or np.asarray(ids).ndim == 0
        ids = np.atleast_1d(np.asarray(ids, dtype=np.uint64))
        pos = np.searchsorted(self.plan.cells, ids)
        if np.any(pos >= len(self.plan.cells)) or np.any(self.plan.cells[pos] != ids):
            raise KeyError("unknown cell id(s)")
        out = vals[pos]
        return out[0] if scalar else out

    def add_neighbor_data_item(self, name: str, fn,
                               neighborhood_id=DEFAULT_NEIGHBORHOOD_ID) -> None:
        """Register ``fn(grid, src_ids, nbr_ids, offsets) -> array``
        over the neighborhood's flat neighbor entries, recomputed at
        every structure rebuild."""
        self._neighbor_items[name] = (fn, neighborhood_id)
        if self.initialized:
            nl = self.plan.hoods[neighborhood_id].lists
            self._neighbor_item_values[name] = np.asarray(
                fn(self, self.plan.cells[nl.of_source], nl.of_neighbor, nl.of_offset)
            )

    def remove_neighbor_data_item(self, name: str) -> None:
        self._neighbor_items.pop(name, None)
        self._neighbor_item_values.pop(name, None)

    def neighbor_data_item(self, name: str, cell=None) -> np.ndarray:
        """Item values for all neighbor entries, or one cell's."""
        vals = self._neighbor_item_values[name]
        if cell is None:
            return vals.copy()
        _, hid = self._neighbor_items[name]
        nl = self.plan.hoods[hid].lists
        pos = self._cell_pos(cell)
        if pos is None:
            raise ValueError(f"unknown cell {cell}")
        return vals[nl.of_source == pos]

    def _update_data_items(self) -> None:
        for name, fn in self._cell_items.items():
            self._cell_item_values[name] = np.asarray(fn(self, self.plan.cells))
        # drop items whose neighborhood has been removed
        for name in [n for n, (_, hid) in self._neighbor_items.items()
                     if hid not in self.plan.hoods]:
            self.remove_neighbor_data_item(name)
        for name, (fn, hid) in self._neighbor_items.items():
            nl = self.plan.hoods[hid].lists
            self._neighbor_item_values[name] = np.asarray(
                fn(self, self.plan.cells[nl.of_source], nl.of_neighbor, nl.of_offset)
            )

    def is_inner(self, cell) -> bool:
        """True when no neighbor relation of the cell crosses a device
        boundary (dccrg_iterator_support.hpp:33-56)."""
        pos = self._cell_pos(cell)
        if pos is None:
            raise ValueError(f"unknown cell {cell}")
        d = int(self.plan.owner[pos])
        row = int(self.plan.row_of_pos[pos])
        return row < self._n_inner(d)

    def is_outer(self, cell) -> bool:
        return not self.is_inner(cell)

    def local_cells(self) -> CellView:
        return CellView(self.plan.cells.copy(), self.plan.owner.copy())

    def inner_cells(self) -> CellView:
        ids = np.concatenate(
            [self.plan.local_ids[d][: self._n_inner(d)] for d in range(self.n_dev)]
        ) if self.n_dev else np.empty(0, np.uint64)
        return self._view_of(ids)

    def outer_cells(self) -> CellView:
        ids = np.concatenate(
            [
                self.plan.local_ids[d][self._n_inner(d): self.plan.n_local[d]]
                for d in range(self.n_dev)
            ]
        )
        return self._view_of(ids)

    def remote_cells(self) -> CellView:
        """Cells with copies on some device that doesn't own them."""
        ids = np.unique(np.concatenate([g for g in self.plan.ghost_ids if len(g)]) if any(
            len(g) for g in self.plan.ghost_ids) else np.empty(0, np.uint64))
        return self._view_of(ids)

    def all_cells(self) -> CellView:
        return self.local_cells()

    def _n_inner(self, d):
        return int(self.plan.hoods[DEFAULT_NEIGHBORHOOD_ID].n_inner[d])

    def _view_of(self, ids):
        ids = np.sort(ids)
        pos = np.searchsorted(self.plan.cells, ids)
        return CellView(ids, self.plan.owner[pos])

    # -- neighbor queries (dccrg.hpp:831-3236) -------------------------

    def _cell_pos(self, cell):
        """Index of ``cell`` in the sorted replicated cell list, or
        None for an unknown id (the reference's cell_process lookup)."""
        pos = int(np.searchsorted(self.plan.cells, np.uint64(cell)))
        if pos >= len(self.plan.cells) or self.plan.cells[pos] != np.uint64(cell):
            return None
        return pos

    def _cell_neighbors_of(self, pos, hood):
        """(neighbor ids, offsets) of one cell. When the flat entry
        stream is already materialized it is the fastest lookup; on the
        uniform fast path (lazy stream) a single-cell find_neighbors_of
        answers in O(K log n) instead of forcing the multi-GB stream
        build the fast path exists to avoid."""
        if callable(hood._lists):
            src, nbr, off, _item = find_neighbors_of(
                self.mapping, self.topology, self.plan.cells,
                self.plan.cells[pos : pos + 1], hood.offsets,
            )
            return nbr, off
        nl = hood.lists
        m = nl.of_source == pos
        return nl.of_neighbor[m], nl.of_offset[m]

    def _cell_neighbors_to(self, pos, hood):
        """(ids, offsets) of cells that consider this cell a neighbor.
        Direct subset query when the entry stream is lazy (uniform and
        hybrid fast paths), entry stream otherwise."""
        if callable(hood._lists):
            _qi, src, off = find_neighbors_to_subset(
                self.mapping, self.topology, self.plan.cells,
                self.plan.cells[pos : pos + 1], hood.offsets,
            )
            return src, off
        nl = hood.lists
        m = nl.to_source == pos
        return nl.to_neighbor[m], nl.to_offset[m]

    def get_neighbors_of(self, cell, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID):
        """[(neighbor id, (dx, dy, dz))] in neighborhood-item order."""
        pos = self._cell_pos(cell)
        if pos is None:
            raise ValueError(f"unknown cell {cell}")
        nbrs, offs = self._cell_neighbors_of(pos, self.plan.hoods[neighborhood_id])
        return list(zip(nbrs.tolist(), map(tuple, offs)))

    def get_neighbors_to(self, cell, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID):
        pos = self._cell_pos(cell)
        if pos is None:
            raise ValueError(f"unknown cell {cell}")
        nbrs, offs = self._cell_neighbors_to(pos, self.plan.hoods[neighborhood_id])
        return list(zip(nbrs.tolist(), map(tuple, offs)))

    def get_face_neighbors_of(self, cell):
        """[(neighbor id, direction)] with directions +-1/2/3 as in the
        reference (dccrg.hpp:2828-2955): +-1 = x, +-2 = y, +-3 = z."""
        out = []
        size = int(self.mapping.get_cell_length_in_indices(np.uint64(cell)))
        for nid, off in self.get_neighbors_of(cell):
            nsize = int(self.mapping.get_cell_length_in_indices(np.uint64(nid)))
            for dim in range(3):
                lo, hi = off[dim], off[dim] + nsize
                other = [d for d in range(3) if d != dim]
                if all(off[d] < size and off[d] + nsize > 0 for d in other):
                    if hi == 0:
                        out.append((nid, -(dim + 1)))
                    elif lo == size:
                        out.append((nid, dim + 1))
        return out

    def get_neighbors_of_at_offset(self, cell, x, y, z,
                                   neighborhood_id=DEFAULT_NEIGHBORHOOD_ID):
        """Neighbors of ``cell`` inside the neighborhood window at
        offset (x, y, z) — [(id, (dx, dy, dz))], empty for the zero
        offset, an offset outside the neighborhood, or an unknown cell
        (reference get_neighbors_of_at_offset, dccrg.hpp:3110-3160).

        Matches by window intersection, so a coarser neighbor covering
        several windows is returned at each of them (as the reference's
        index matching does), even though the stored neighbor list
        holds it only once."""
        if (x, y, z) == (0, 0, 0):
            return []
        hood = self.plan.hoods.get(neighborhood_id)
        if hood is None:
            return []
        if not np.any(np.all(hood.offsets == np.array([x, y, z]), axis=1)):
            return []
        pos = self._cell_pos(cell)
        if pos is None:
            return []
        nbrs, offs = self._cell_neighbors_of(pos, hood)
        if len(nbrs) == 0:
            return []
        size = int(self.mapping.get_cell_length_in_indices(np.uint64(cell)))
        win = self.mapping.get_indices(np.uint64(cell)).astype(np.int64)
        win += np.array([x, y, z], dtype=np.int64) * size
        il = self.mapping.get_index_length().astype(np.int64)
        for d in range(3):
            if self.topology.is_periodic(d):
                win[d] %= il[d]
            elif not 0 <= win[d] < il[d]:
                return []
        nidx = self.mapping.get_indices(nbrs).astype(np.int64)
        nsize = self.mapping.get_cell_length_in_indices(nbrs).astype(np.int64)
        hit = np.ones(len(nbrs), dtype=bool)
        for d in range(3):
            if self.topology.is_periodic(d):
                h = np.zeros(len(nbrs), dtype=bool)
                for shift in (-il[d], 0, il[d]):
                    h |= (nidx[:, d] + shift < win[d] + size) & (
                        nidx[:, d] + nsize + shift > win[d]
                    )
                hit &= h
            else:
                hit &= (nidx[:, d] < win[d] + size) & (nidx[:, d] + nsize > win[d])
        return list(zip(nbrs[hit].tolist(), map(tuple, offs[hit])))

    def get_remote_neighbors_of(self, cell,
                                neighborhood_id=DEFAULT_NEIGHBORHOOD_ID,
                                sorted: bool = False):
        """Neighbors of ``cell`` owned by a different device than the
        cell itself (reference get_remote_neighbors_of,
        dccrg.hpp:3175-3234)."""
        return self._remote_neighbors(cell, neighborhood_id, sorted, to=False)

    def get_remote_neighbors_to(self, cell,
                                neighborhood_id=DEFAULT_NEIGHBORHOOD_ID,
                                sorted: bool = False):
        """Cells considering ``cell`` a neighbor that live on a
        different device (reference get_remote_neighbors_to,
        dccrg.hpp:3236-3296)."""
        return self._remote_neighbors(cell, neighborhood_id, sorted, to=True)

    def _remote_neighbors(self, cell, neighborhood_id, sorted, to):
        hood = self.plan.hoods.get(neighborhood_id)
        if hood is None:
            return np.empty(0, np.uint64)
        pos = self._cell_pos(cell)
        if pos is None:
            return np.empty(0, np.uint64)
        if to:
            nbrs, _ = self._cell_neighbors_to(pos, hood)
        else:
            nbrs, _ = self._cell_neighbors_of(pos, hood)
        own = int(self.plan.owner[pos])
        nbr_owner = self.plan.owner[np.searchsorted(self.plan.cells, nbrs)]
        out = nbrs[nbr_owner != own]
        return np.sort(out) if sorted else out

    def find_cells(self, indices_min, indices_max,
                   minimum_refinement_level: int = 0,
                   maximum_refinement_level: int | None = None) -> np.ndarray:
        """Existing cells whose index volume overlaps the inclusive box
        [indices_min, indices_max] and whose refinement level is within
        the given range (reference find_cells, dccrg.hpp:4908-5030).
        Indices are in smallest-possible-cell units; result id-sorted."""
        if maximum_refinement_level is None:
            maximum_refinement_level = self.mapping.max_refinement_level
        if minimum_refinement_level > maximum_refinement_level:
            raise ValueError("minimum refinement level > maximum")
        if maximum_refinement_level > self.mapping.max_refinement_level:
            raise ValueError("maximum refinement level too large")
        lo = np.asarray(indices_min, dtype=np.int64)
        hi = np.asarray(indices_max, dtype=np.int64)
        if np.any(lo > hi):
            raise ValueError("minimum index > maximum index")
        cells = self.plan.cells
        lvl = self.mapping.get_refinement_level(cells)
        keep = (lvl >= minimum_refinement_level) & (lvl <= maximum_refinement_level)
        idx = self.mapping.get_indices(cells).astype(np.int64)
        size = self.mapping.get_cell_length_in_indices(cells).astype(np.int64)
        overlap = np.all((idx <= hi) & (idx + size[:, None] - 1 >= lo), axis=1)
        return cells[keep & overlap]

    # -- user neighborhoods (dccrg.hpp:6491-6681) ----------------------

    def add_neighborhood(self, neighborhood_id, offsets) -> bool:
        if neighborhood_id in self.neighborhoods:
            return False
        offsets = validate_neighborhood(offsets, self._hood_len)
        self.neighborhoods[neighborhood_id] = offsets
        if self.initialized:
            self._build_plan(self.plan.cells, self.plan.owner)
        return True

    def remove_neighborhood(self, neighborhood_id) -> None:
        if neighborhood_id == DEFAULT_NEIGHBORHOOD_ID:
            raise ValueError("cannot remove the default neighborhood")
        self.neighborhoods.pop(neighborhood_id, None)
        if self.initialized:
            self._build_plan(self.plan.cells, self.plan.owner)

    # -- halo exchange (dccrg.hpp:978-1014, 5046-5413) -----------------

    def set_transfer_predicate(self, field: str, fn) -> None:
        """Per-peer, per-neighborhood selection of what a cell sends —
        the TPU counterpart of the reference's 5-argument
        ``get_mpi_datatype(cell, sender, receiver, receiving, hood)``
        (dccrg_get_cell_datatype.hpp:48-213), where a cell may expose
        different data to different peers.

        ``fn(cell_ids, sender, receiver, neighborhood_id) -> bool
        array`` is evaluated at plan time per device pair; a False
        entry drops that cell's ``field`` payload for that pair (both
        sides skip it — the symmetric equivalent of the reference's
        requirement that sender and receiver datatypes agree). Pass
        ``None`` to clear.

        Predicates are sampled into cached pair tables when set; a
        closure whose behavior changes later must be re-registered via
        this setter to invalidate those caches."""
        if not self.initialized:
            raise RuntimeError(
                "set_transfer_predicate() requires initialize() first "
                "(predicates are sampled against the built plan)")
        if fn is None:
            self._transfer_predicates.pop(field, None)
        else:
            if field not in self.fields:
                raise KeyError(f"unknown field {field!r}")
            self._transfer_predicates[field] = fn
        # pair tables are runtime arguments of the compiled programs;
        # only the cached (host + device) tables need rebuilding
        for hood in self.plan.hoods.values():
            hood._pair_host.clear()
            stale = [k for k in hood._dev
                     if isinstance(k, tuple) and k[0] in ("pair", "peer")]
            for k in stale:
                del hood._dev[k]

    @staticmethod
    def _pair_groups(c):
        """(starts, ends) of the (sender, receiver) groups in a compact
        pair record (entries are sorted by (p, q))."""
        pq = c["p"] * np.int64(c["n_dev"]) + c["q"]
        starts = np.r_[0, np.flatnonzero(np.diff(pq)) + 1] \
            if len(pq) else np.empty(0, np.int64)
        ends = np.r_[starts[1:], len(pq)] if len(pq) else starts
        return starts.astype(np.int64), ends.astype(np.int64)

    def _field_pair_compact(self, neighborhood_id, field):
        """The hood's compact pair record, filtered by the field's
        transfer predicate if set (dropped entries removed; surviving
        entries KEEP their slot positions, so holes mirror the dense
        tables' -1 slots)."""
        hood = self.plan.hoods[neighborhood_id]
        c = hood.pair_compact
        fn = self._transfer_predicates.get(field)
        if fn is None:
            return c
        cached = hood._pair_host.get(("c", field))
        if cached is not None:
            return cached
        keep = np.ones(len(c["p"]), dtype=bool)
        starts, ends = self._pair_groups(c)
        # the predicate contract is per-(sender, receiver): each live
        # pair gets its own call (O(devices x peers) calls)
        for s, e in zip(starts, ends):
            p0, q0 = int(c["p"][s]), int(c["q"][s])
            ids = self.plan.local_ids[p0][c["srow"][s:e]]
            k = np.asarray(fn(ids, p0, q0, neighborhood_id), dtype=bool)
            if k.shape != ids.shape:
                raise ValueError(
                    "transfer predicate must return one bool per cell"
                )
            keep[s:e] = k
        out = dict(c)
        for key in ("p", "q", "pos", "srow", "rrow"):
            out[key] = c[key][keep]
        hood._pair_host[("c", field)] = out
        return out

    def _field_pair_tables(self, neighborhood_id, field):
        """(send_rows, recv_rows) DENSE views for one field — the
        all_to_all fallback and host introspection format; the
        per-delta ppermute path uses _field_pair_compact and never
        materializes these."""
        hood = self.plan.hoods[neighborhood_id]
        if self._transfer_predicates.get(field) is None:
            return hood.send_rows, hood.recv_rows
        cached = hood._pair_host.get(field)
        if cached is not None:
            return cached
        out = uniform_mod.dense_pair_tables(self._field_pair_compact(
            neighborhood_id, field))
        hood._pair_host[field] = out
        return out

    # halo exchanges with at most this many peer offsets use one
    # ppermute per offset instead of a dense all_to_all: each device
    # typically talks to ~2 neighbors, so the all_to_all's [n_dev, M]
    # buffer wastes ~n_dev/peers of the interconnect bandwidth
    _MAX_PEER_OFFSETS = 8

    def _peer_deltas(self, neighborhood_id):
        """Sorted device-offset set {(q-p) mod n_dev} with halo
        traffic, or None when the all_to_all fallback should be used
        (too many distinct offsets)."""
        hood = self.plan.hoods[neighborhood_id]
        if ("deltas",) in hood._dev:
            return hood._dev[("deltas",)]
        c = hood.pair_compact
        deltas = tuple(sorted(set(
            np.unique((c["q"] - c["p"]) % self.n_dev).tolist())))
        if len(deltas) > self._MAX_PEER_OFFSETS:
            deltas = None  # all_to_all fallback (memoized as None too)
        hood._dev[("deltas",)] = deltas
        return deltas

    def _pair_tables_device(self, neighborhood_id, field_names):
        """Per-field (send, recv) device tables, hood-memoized.

        With a small peer-offset set, tables are per-delta compact
        slices ``[n_dev, M_delta]`` (one ppermute each); otherwise the
        dense ``[n_dev, n_dev, M]`` all_to_all tables."""
        hood = self.plan.hoods[neighborhood_id]
        sh = self._sharding()
        deltas = self._peer_deltas(neighborhood_id)
        sends, recvs = [], []
        for n in field_names:
            if deltas is None:
                s, r = self._field_pair_tables(neighborhood_id, n)
                sends.append(hood.dev(("pair", n, "s"), s, sh))
                recvs.append(hood.dev(("pair", n, "r"), r, sh))
                continue
            # per-delta compact tables straight from the compact pair
            # record — the dense [n_dev, n_dev, M] arrays are never
            # touched on this path (pod-scale memory stays linear);
            # fc/dvec are only computed when some delta's tables are
            # not yet cached (the warm path is dictionary hits)
            fc = dvec = None
            for d in deltas:
                key_s, key_r = ("peer", n, d, "s"), ("peer", n, d, "r")
                if key_s not in hood._dev:
                    if fc is None:
                        fc = self._field_pair_compact(neighborhood_id, n)
                        dvec = (fc["q"] - fc["p"]) % self.n_dev
                    sel = dvec == d
                    # shrink to this delta's own (sticky) width; slots
                    # may have predicate holes, so cover the LAST valid
                    # slot, not the count
                    need = (int(fc["pos"][sel].max()) + 1
                            if sel.any() else 1)
                    Md = self._sticky_cap(("Md", neighborhood_id, d), need)
                    Md = min(Md, fc["M"])
                    sd = np.full((self.n_dev, Md), -1, dtype=np.int32)
                    rd = np.full((self.n_dev, Md), -1, dtype=np.int32)
                    inw = sel & (fc["pos"] < Md)
                    # device p SENDS to p+d; device q RECEIVES from q-d
                    # — both tables sharded by the acting device
                    sd[fc["p"][inw], fc["pos"][inw]] = fc["srow"][inw]
                    rd[fc["q"][inw], fc["pos"][inw]] = fc["rrow"][inw]
                    hood.dev(key_s, sd, sh)
                    hood.dev(key_r, rd, sh)
                sends.append(hood._dev[key_s])
                recvs.append(hood._dev[key_r])
        return tuple(sends), tuple(recvs)

    def _exchange_programs(self, neighborhood_id, n_f):
        """(start, finish, fused, n_t) jitted exchange programs for n_f
        fields — tables and field arrays are arguments, so one program
        serves every epoch whose (bucketed) shapes match.

        With a small peer-offset set the collective is one
        ``lax.ppermute`` per offset over compact [n_dev, M_delta]
        buffers (each device talks to its ~2 neighbors; a dense
        all_to_all would move n_dev/peers times the bytes); otherwise
        it falls back to the all_to_all over [n_dev, M]. ``n_t`` is
        the number of table slots per field per direction."""
        deltas = self._peer_deltas(neighborhood_id)
        n_dev = self.n_dev
        key = ("exchange", n_f, self.plan.R, deltas, n_dev)
        hit = self._program_cache.get(key)
        if hit is not None:
            return hit
        R = self.plan.R
        axis = self.axis
        mesh = self.mesh
        n_t = 1 if deltas is None else len(deltas)

        def start_body(*args):
            sends = args[: n_f * n_t]
            fields = args[n_f * n_t :]
            outs = []
            for i, f in enumerate(fields):
                fl = f[0]
                for j in range(n_t):
                    sr = sends[i * n_t + j][0]
                    dlt = None if deltas is None else deltas[j]
                    outs.append(_halo_send(fl, sr, dlt, axis, n_dev)[None])
            return tuple(outs)

        def finish_body(*args):
            recvs = args[: n_f * n_t]
            bufs = args[n_f * n_t : 2 * n_f * n_t]
            fields = args[2 * n_f * n_t :]
            outs = []
            for i, f in enumerate(fields):
                fl = f[0]
                for j in range(n_t):
                    fl = _halo_scatter(fl, recvs[i * n_t + j][0],
                                       bufs[i * n_t + j][0], R)
                fl = fl.at[R - 1].set(0)  # keep the zero pad row zero
                outs.append(fl[None])
            return tuple(outs)

        start_mapped = _shard_map(
            start_body,
            mesh=mesh,
            in_specs=(P(axis),) * (n_f * n_t + n_f),
            out_specs=(P(axis),) * (n_f * n_t),
        )
        finish_mapped = _shard_map(
            finish_body,
            mesh=mesh,
            in_specs=(P(axis),) * (2 * n_f * n_t + n_f),
            out_specs=(P(axis),) * n_f,
        )

        start = jax.jit(lambda *a: start_mapped(*a))
        finish = jax.jit(lambda *a: finish_mapped(*a))

        @jax.jit
        def fused(*args):
            sends = args[: n_f * n_t]
            recvs = args[n_f * n_t : 2 * n_f * n_t]
            fields = args[2 * n_f * n_t :]
            bufs = start_mapped(*sends, *fields)
            return finish_mapped(*recvs, *bufs, *fields)

        hit = (start, finish, fused, n_t)
        self._program_cache[key] = hit
        return hit

    def _exchange_split_fns(self, neighborhood_id, field_names):
        """Split-phase halo exchange: ``start`` runs the all_to_all and
        returns only the received ghost payload; ``finish`` scatters
        that payload into the *current* field arrays, touching ghost
        rows only — the reference's receives write ``remote_neighbors``
        exclusively (dccrg.hpp:10726-10935), so user writes to local
        rows between start and wait must survive. Returns callables
        bound to this epoch's pair tables; the underlying compiled
        programs are shared across epochs."""
        start_j, finish_j, _fused, _n_t = self._exchange_programs(
            neighborhood_id, len(field_names))
        sends, recvs = self._pair_tables_device(neighborhood_id, field_names)

        def start(*fields):
            return start_j(*sends, *fields)

        def finish(*bufs_and_fields):
            return finish_j(*recvs, *bufs_and_fields)

        return start, finish

    def update_copies_of_remote_neighbors(
        self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID, fields=None
    ) -> None:
        """Refresh ghost copies of remote neighbors: the reference's
        update_copies_of_remote_neighbors() (dccrg.hpp:978), one fused
        all_to_all. ``fields`` selects which per-cell fields move (the
        get_mpi_datatype() / transfer_switch boundary)."""
        self._check_not_in_flight(neighborhood_id)
        if self.n_dev == 1:
            return
        with telemetry.span("grid.exchange"):
            names = tuple(sorted(fields)) if fields is not None else tuple(sorted(self.fields))
            _start, _finish, fused, _n_t = self._exchange_programs(
                neighborhood_id, len(names))
            sends, recvs = self._pair_tables_device(neighborhood_id, names)
            out = fused(*sends, *recvs, *(self.data[n] for n in names))
            for n, arr in zip(names, out):
                self.data[n] = arr

    def _check_not_in_flight(self, neighborhood_id):
        entry = self._pending.get(neighborhood_id)
        if entry is not None and entry[0] == self.plan.epoch:
            raise RuntimeError(
                f"neighborhood {neighborhood_id} already has an in-flight halo "
                "update; call wait_remote_neighbor_copy_updates first"
            )
        if entry is not None:
            # orphaned by a structure rebuild: its wait would raise
            # anyway, and this fresh update supersedes it
            del self._pending[neighborhood_id]

    # split-phase parity API (dccrg.hpp:5046-5413). Dispatch is async
    # in JAX, so start returns immediately; wait scatters ONLY the
    # received ghost rows into the then-current arrays — local-row
    # writes made between start and wait survive, matching the
    # reference's receives-touch-remote_neighbors-only semantics
    # (dccrg.hpp:10726-10935).
    def start_remote_neighbor_copy_updates(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID, fields=None):
        self._check_not_in_flight(neighborhood_id)
        names = tuple(sorted(fields)) if fields is not None else tuple(sorted(self.fields))
        if self.n_dev == 1:
            self._pending[neighborhood_id] = (self.plan.epoch, names, None, None)
            return
        with telemetry.span("grid.exchange.start"):
            start, finish = self._exchange_split_fns(neighborhood_id, names)
            bufs = start(*(self.data[n] for n in names))
        self._pending[neighborhood_id] = (self.plan.epoch, names, finish, bufs)

    def wait_remote_neighbor_copy_updates(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID) -> None:
        if neighborhood_id not in self._pending:
            return
        epoch, names, finish, bufs = self._pending.pop(neighborhood_id)
        if epoch != self.plan.epoch:
            raise RuntimeError(
                "grid structure changed between start_remote_neighbor_copy_updates "
                "and wait_remote_neighbor_copy_updates; the in-flight halo payload "
                "is stale"
            )
        if finish is None:  # single-device: nothing was exchanged
            return
        with telemetry.span("grid.exchange.wait"):
            out = finish(*bufs, *(self.data[n] for n in names))
            for n, arr in zip(names, out):
                self.data[n] = arr

    def wait_remote_neighbor_copy_update_receives(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID) -> None:
        self.wait_remote_neighbor_copy_updates(neighborhood_id)

    def wait_remote_neighbor_copy_update_sends(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID) -> None:
        pass

    def get_number_of_update_send_cells(
        self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID, field: str | None = None
    ) -> int:
        """Total cells sent per halo update (dccrg.hpp:5428); with
        ``field``, the count after that field's transfer predicate."""
        if field is None:
            return len(self.plan.hoods[neighborhood_id].pair_compact["p"])
        return len(self._field_pair_compact(neighborhood_id, field)["p"])

    def get_number_of_update_receive_cells(
        self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID, field: str | None = None
    ) -> int:
        if field is None:
            return len(self.plan.hoods[neighborhood_id].pair_compact["q"])
        return len(self._field_pair_compact(neighborhood_id, field)["q"])

    # -- stencil execution ---------------------------------------------

    def apply_stencil(
        self,
        kernel,
        fields_in,
        fields_out,
        neighborhood_id=DEFAULT_NEIGHBORHOOD_ID,
        include_to=False,
        extra_args=(),
    ):
        """Run a gather-based stencil kernel over all local cells.

        ``kernel(cell_fields, nbr_fields, offs, mask, *extra)`` receives
        per-device blocks: ``cell_fields[name]`` is ``[L, ...]``,
        ``nbr_fields[name]`` is ``[L, S, ...]`` (neighbors gathered,
        zeros at padding), ``offs`` is ``[L, S, 3]`` and ``mask``
        ``[L, S]``. With ``include_to=True`` a second
        (nbr_to_fields, to_offs, to_mask) triple follows. Must return a
        dict name -> [L, ...] for every name in ``fields_out``.

        The updated field rows are written back; ghost copies are NOT
        refreshed (call update_copies_of_remote_neighbors).
        """
        fields_in = tuple(fields_in)
        fields_out = tuple(fields_out)
        fn, tables = self._make_stencil(
            kernel, fields_in, fields_out, neighborhood_id, include_to,
            n_extra=len(extra_args),
        )
        out = fn(*tables, *(self.data[n] for n in fields_in),
                 *(self.data[n] for n in fields_out), *extra_args)
        for n, arr in zip(fields_out, out):
            self.data[n] = arr
        self._mark_ckpt_dirty(fields_out)


    def _on_accelerator(self) -> bool:
        return self.mesh.devices.flat[0].platform not in ("cpu",)

    def _use_roll_gather(self) -> bool:
        """Roll-decomposed gathers trade a dense random gather for S
        sequential rolls + a sparse fixup: a clear win on TPU (random
        gathers crawl), a small loss on the CPU backend (caches absorb
        the near-sequential gather, the stack materialization doesn't
        pay). Default: on for accelerators, off for CPU; override with
        DCCRG_ROLL_STENCIL=0/1."""
        env = os.environ.get("DCCRG_ROLL_STENCIL")
        if env in ("0", "1"):
            return env == "1"
        return self._on_accelerator()

    def _use_overlap(self) -> bool:
        """Overlapped fused steps: start the halo collectives, run the
        bulk kernel on pre-exchange state (inner rows' results are
        final — they read no ghosts), then redo just the outer rows
        after the scatter. Removes the collective -> kernel dependency
        so XLA's async collective-permute runs under the MXU work —
        the reference's solve-inner-while-messages-fly
        (dccrg.hpp:5046-5413, tests/advection/2d.cpp:327-343). Costs a
        surface-sized second kernel pass, so default on for
        accelerators only — the CPU backend has no async
        collective-permute to hide and the measured CPU A/B is 0.89x
        (PERF.md); override with DCCRG_OVERLAP=0/1."""
        env = os.environ.get("DCCRG_OVERLAP")
        if env in ("0", "1"):
            return env == "1"
        return self._on_accelerator()

    def _outer_tables(self, neighborhood_id, hood, use_roll, r_shifts, roll):
        """Host tables for the overlapped step's outer re-pass:
        ``(outer_rows [n_dev, Wo] int32, pad R-1;
        outer_nbr_rows [n_dev, Wo, S] int32)`` — the rows
        [n_inner, n_local) per device and their neighbor rows in the
        full (local+ghost) array. None when overlap can't pay: no
        outer rows, or outer is the majority of the grid (the re-pass
        would cost more than the hidden collective). Memoized on the
        hood (one structure epoch); capacity is sticky-bucketed so the
        compiled program survives epochs."""
        if getattr(hood, "_outer_skip", False):
            return None
        cached = getattr(hood, "_outer_host", None)
        if cached is not None:
            return cached
        plan = self.plan
        L, R = plan.L, plan.R
        n_inner = np.asarray(hood.n_inner, dtype=np.int64)
        n_local = np.asarray(plan.n_local, dtype=np.int64)
        n_out_d = n_local - n_inner
        if int(n_out_d.max(initial=0)) == 0 or (
                2 * int(n_out_d.sum()) > int(n_local.sum())):
            hood._outer_skip = True
            return None
        W = self._sticky_cap(("outerW", neighborhood_id), int(n_out_d.max()))
        orow = np.full((self.n_dev, W), R - 1, dtype=np.int32)
        for d in range(self.n_dev):
            k = int(n_out_d[d])
            orow[d, :k] = np.arange(n_inner[d], n_local[d], dtype=np.int32)
        if use_roll:
            # neighbor row = row + shift_j, overridden by the roll
            # plan's fixups (ghost reads are always fixups); masked
            # slots may hold junk — the outer gather re-applies the
            # mask exactly as _make_nbr_gather does
            shifts = np.asarray(r_shifts, dtype=np.int64)
            S = len(shifts)
            onr64 = orow.astype(np.int64)[:, :, None] + shifts[None, None, :]
            wr = np.asarray(roll[1])
            ws = np.asarray(roll[2])
            for d in range(self.n_dev):
                lo, hi = int(n_inner[d]), int(n_local[d])
                for j in range(S):
                    wrow = wr[d, j]
                    sel = (wrow >= lo) & (wrow < hi)
                    onr64[d, wrow[sel] - lo, j] = ws[d, j][sel]
            onr = np.clip(onr64, 0, R - 1).astype(np.int32)
            for d in range(self.n_dev):
                onr[d, int(n_out_d[d]):] = R - 1
        else:
            nbr = np.asarray(hood.nbr_rows)
            S = nbr.shape[2]
            onr = np.full((self.n_dev, W, S), R - 1, dtype=np.int32)
            for d in range(self.n_dev):
                k = int(n_out_d[d])
                onr[d, :k] = nbr[d, orow[d, :k]]
        hood._outer_host = (orow, onr)
        return hood._outer_host

    def _refreshed_ghost_mask(self, neighborhood_id, names):
        """``[n_dev, R]`` bool: ghost rows that RECEIVE fresh bytes
        when ``names`` exchange — per-field post-transfer-predicate
        receive rows. The zero pad row is excluded (the exchange
        rewrites it to the 0 it already holds)."""
        R = self.plan.R
        m = np.zeros((self.n_dev, R), dtype=bool)
        for n in names:
            c = self._field_pair_compact(neighborhood_id, n)
            m[c["q"], c["rrow"]] = True
        m[:, R - 1] = False
        return m

    def _split_outer_tables(self, neighborhood_id, hood, use_roll,
                            r_shifts, roll, relevant):
        """Ghost-split outer tables: like :meth:`_outer_tables` but
        restricted to the local rows whose gather actually READS a
        ghost row refreshed by exchanging ``relevant`` — the rows a
        step exchanging only those fields can invalidate. Rows that
        are outer only through the to-lists, rows whose ghost
        neighbors are all transfer-predicate-filtered, and (on AMR
        hybrid plans) rows whose ghost reads ride the hard tables'
        own unconditional re-pass never qualify. Returns ``(orow
        [n_dev, W], onr [n_dev, W, S], rows_total)`` or None when no
        row qualifies; memoized per ``relevant`` on the hood."""
        cache = getattr(hood, "_split_outer", None)
        if cache is None:
            cache = hood._split_outer = {}
        # the gather mode is part of the key: roll callers (the step
        # loop on accelerators) and table callers (_make_outer_repass)
        # build format-incompatible onr tables for the same rows
        key = (bool(use_roll), tuple(relevant))
        if key in cache:
            return cache[key]
        plan = self.plan
        L, R = plan.L, plan.R
        n_local = np.asarray(plan.n_local, dtype=np.int64)
        refreshed = self._refreshed_ghost_mask(neighborhood_id, relevant)
        row_sets = []
        if use_roll:
            # ghost reads are always roll-plan fixups (the shifts only
            # reach local rows), so membership falls out of the fixup
            # tables alone; pad fixup entries are (0, 0) — row 0 is
            # local, never a refreshed ghost, so pads never select
            wr = np.asarray(roll[1])
            ws = np.asarray(roll[2])
            for d in range(self.n_dev):
                sel = refreshed[d][ws[d]]
                rows = np.unique(wr[d][sel]).astype(np.int64)
                row_sets.append(rows[rows < n_local[d]])
        else:
            nbr = np.asarray(hood.nbr_rows)
            msk = np.asarray(hood.nbr_mask)
            for d in range(self.n_dev):
                k = int(n_local[d])
                hit = (msk[d, :k] & refreshed[d][nbr[d, :k]]).any(axis=1)
                row_sets.append(np.nonzero(hit)[0].astype(np.int64))
        rows_total = int(sum(len(r) for r in row_sets))
        if rows_total == 0:
            cache[key] = None
            return None
        W = self._sticky_cap(("gsplitW", neighborhood_id, key),
                             int(max(len(r) for r in row_sets)))
        orow = np.full((self.n_dev, W), R - 1, dtype=np.int32)
        for d, rows in enumerate(row_sets):
            orow[d, :len(rows)] = rows
        if use_roll:
            shifts = np.asarray(r_shifts, dtype=np.int64)
            S = len(shifts)
            onr64 = orow.astype(np.int64)[:, :, None] + shifts[None, None, :]
            wr = np.asarray(roll[1])
            ws = np.asarray(roll[2])
            for d, rows in enumerate(row_sets):
                if not len(rows):
                    continue
                for j in range(S):
                    wrow = wr[d, j]
                    pos = np.searchsorted(rows, wrow)
                    sel = (pos < len(rows)) & (
                        rows[np.minimum(pos, len(rows) - 1)] == wrow)
                    onr64[d, pos[sel], j] = ws[d, j][sel]
            onr = np.clip(onr64, 0, R - 1).astype(np.int32)
            for d, rows in enumerate(row_sets):
                onr[d, len(rows):] = R - 1
        else:
            nbr = np.asarray(hood.nbr_rows)
            S = nbr.shape[2]
            onr = np.full((self.n_dev, W, S), R - 1, dtype=np.int32)
            for d, rows in enumerate(row_sets):
                onr[d, :len(rows)] = nbr[d, rows]
        cache[key] = (orow, onr, rows_total)
        return cache[key]

    def _make_outer_repass(self, kernel, fields_in, fields_out,
                           neighborhood_id, exchange_names):
        """A compiled fix-the-refreshed-rows pass for split-overlap
        treatments of stencils OUTSIDE the fused step loop (the
        Poisson fused-CG matvec): recomputes ``kernel`` at exactly the
        local rows whose gather reads a ghost row refreshed by
        exchanging ``exchange_names``, scattering the results into
        already-computed bulk outputs. The caller runs the bulk
        stencil on PRE-exchange state (rows not returned here read no
        refreshed ghosts, so their bulk results are final), lands the
        halos, then calls this pass.

        Returns ``(fn, tables)`` with ``out = fn(*tables,
        *fields_in_arrays, *bulk_out_arrays)`` (full ``[n_dev, R,
        ...]`` arrays in and out), or None when the plan is
        unsupported (AMR hybrid hard tables — those rows ride their
        own unconditional re-pass) or no row qualifies."""
        hood = self.plan.hoods[neighborhood_id]
        if hood.hard_nbr_rows is not None:
            return None
        try:
            msk = np.asarray(hood.nbr_mask)
        except Exception:  # noqa: BLE001 - table-free plan shapes
            return None
        if msk is None or getattr(msk, "ndim", 0) != 3:
            return None
        exch = tuple(sorted(exchange_names))
        st = self._split_outer_tables(neighborhood_id, hood, False,
                                      None, None, exch)
        if st is None:
            return None
        orow_h, onr_h, _rows = st
        L, R = self.plan.L, self.plan.R
        n_dev, W = orow_h.shape
        S = onr_h.shape[2]
        n_local = np.asarray(self.plan.n_local, dtype=np.int64)
        omask_h = np.zeros((n_dev, W, S), dtype=bool)
        kper = []
        for d in range(n_dev):
            rows = orow_h[d][orow_h[d] < n_local[d]]
            kper.append(rows)
            omask_h[d, :len(rows)] = msk[d, rows]
        if hood.offs_const is not None:
            off = np.asarray(hood.offs_const)
            ooffs_h = (omask_h[..., None]
                       * off[None, None, :, :]).astype(np.int32)
            if hood.scale_rows is not None:
                sc = np.asarray(hood.scale_rows)
                scw = np.ones((n_dev, W), dtype=sc.dtype)
                for d, rows in enumerate(kper):
                    scw[d, :len(rows)] = sc[d, rows]
                ooffs_h = ooffs_h * scw[:, :, None, None]
        else:
            offs_all = np.asarray(hood.nbr_offs)
            ooffs_h = np.zeros((n_dev, W, S, 3), dtype=offs_all.dtype)
            for d, rows in enumerate(kper):
                ooffs_h[d, :len(rows)] = offs_all[d, rows]
        sh = self._sharding()
        tables = [hood.dev(("orp", exch, "rows"), orow_h, sh),
                  hood.dev(("orp", exch, "nbr"), onr_h, sh),
                  hood.dev(("orp", exch, "mask"), omask_h, sh),
                  hood.dev(("orp", exch, "offs"), ooffs_h, sh)]
        fields_in = tuple(fields_in)
        fields_out = tuple(fields_out)
        key = ("outer_repass", kernel, fields_in, fields_out,
               neighborhood_id, exch, L, R)
        fn = self._program_cache.get(key)
        if fn is not None:
            return fn, tables
        axis, mesh = self.axis, self.mesh
        nin, nout = len(fields_in), len(fields_out)

        def body(orow, onr, omask, ooffs, *args):
            orow, onr = orow[0], onr[0]
            omask, ooffs = omask[0], ooffs[0]
            orc = jnp.minimum(orow, L - 1)
            fins = {n: a[0] for n, a in zip(fields_in, args[:nin])}
            bulk = [a[0] for a in args[nin:nin + nout]]
            cell = {n: fins[n][:L][orc] for n in fields_in}
            nbr = {n: fins[n][onr] for n in fields_in}
            res = kernel(cell, nbr, ooffs, omask)
            outs = []
            for n, b in zip(fields_out, bulk):
                fixed = b[:L].at[orow].set(res[n].astype(b.dtype),
                                           mode="drop")
                outs.append(b.at[:L].set(fixed)[None])
            return tuple(outs)

        mapped = _shard_map(
            body, mesh=mesh,
            in_specs=(P(axis),) * (4 + nin + nout),
            out_specs=(P(axis),) * nout, check_vma=False)
        fn = jax.jit(lambda *a: mapped(*a))
        self._program_cache[key] = fn
        return fn, tables

    def _make_stencil(self, kernel, fields_in, fields_out, neighborhood_id, include_to,
                      n_extra=0):
        """(program, bound tables) for a gather stencil. The jitted
        program takes every table as an argument and is cached by its
        STATIC signature (capacities, flags, kernel) — bucketed plan
        rebuilds reuse it; only the table values re-upload."""
        hood = self.plan.hoods[neighborhood_id]
        L, R = self.plan.L, self.plan.R
        sh = self._sharding()
        split = hood.hard_nbr_rows is not None and not include_to
        merged = include_to and hood.hard_nbr_rows is not None
        roll = None
        cf = None
        if merged:
            uniform_offs = False
            if "m_rows" not in hood._dev:
                m_rows, m_offs, m_mask = hood.merged_of_tables(R - 1)
                hood.dev("m_rows", m_rows, sh)
                hood.dev("m_offs", m_offs, sh)
                hood.dev("m_mask", m_mask, sh)
            tables = [hood._dev["m_rows"], hood._dev["m_offs"],
                      hood._dev["m_mask"]]
        else:
            uniform_offs = hood.offs_const is not None
            cf = hood.closed_form if not include_to else None
            # affine tables lower the gather to rolls + sparse fixups;
            # closed-form plans HAVE no tables, so they always roll and
            # additionally synthesize the mask in-body
            if cf is not None:
                roll = hood.roll_plan(L)
            elif uniform_offs and not include_to and self._use_roll_gather():
                roll = hood.roll_plan(
                    L, cap=lambda n: self._sticky_cap(("rollW", neighborhood_id), n))
            else:
                roll = None
            if roll is not None:
                tables = [hood.dev("roll_dummy",
                                   np.zeros((self.n_dev, 1, 1), np.int32), sh)]
            else:
                tables = [hood.dev("nbr_rows", hood.nbr_rows, sh)]
            if uniform_offs:
                # per-slot constant offsets: synthesized in-body from
                # the mask instead of storing [n_dev, L, S, 3] in HBM
                tables.append(hood.dev("offs_const", hood.offs_const))
            else:
                tables.append(hood.dev("nbr_offs", hood.nbr_offs, sh))
            if cf is not None:
                if cf.get("multi"):
                    # multi-device closed-form: the mask is synthesized
                    # from the per-row grid index (rows are NOT grid
                    # order), shipped in the mask slot
                    tables.append(self.device_row_ids())
                else:
                    tables.append(hood.dev("mask_dummy",
                                           np.zeros((self.n_dev, 1, 1), bool),
                                           sh))
            else:
                tables.append(hood.dev("nbr_mask", hood.nbr_mask, sh))
        r_shifts = tuple(int(s) for s in roll[0]) if roll is not None else None
        if roll is not None:
            tables.append(hood.dev("roll_wr", roll[1], sh))
            tables.append(hood.dev("roll_ws", roll[2], sh))
        scaled = uniform_offs and hood.scale_rows is not None
        if scaled:
            tables.append(hood.dev("scale_rows", hood.scale_rows, sh))
        if split:
            tables.append(hood.dev("hard_rows", hood.hard_rows, sh))
            tables.append(hood.dev("hard_nbr_rows", hood.hard_nbr_rows, sh))
            tables.append(hood.dev("hard_offs", hood.hard_offs, sh))
            tables.append(hood.dev("hard_mask", hood.hard_mask, sh))
        if include_to:
            tables.append(hood.dev("to_rows", hood.to_rows, sh))
            tables.append(hood.dev("to_offs", hood.to_offs, sh))
            tables.append(hood.dev("to_mask", hood.to_mask, sh))

        synth = _synth_key(cf)
        key = ("stencil", kernel, fields_in, fields_out, include_to, n_extra,
               L, R, uniform_offs, scaled, split, merged, r_shifts, synth)
        fn = self._program_cache.get(key)
        if fn is not None:
            return fn, tables

        n_in, n_out = len(fields_in), len(fields_out)
        axis, mesh = self.axis, self.mesh
        use_roll = r_shifts is not None
        if isinstance(kernel, SlotwiseKernel) and include_to:
            raise ValueError("SlotwiseKernel does not support include_to")
        slotwise = isinstance(kernel, SlotwiseKernel)

        def body(nrows, noffs, nmask, *args):
            nrows = nrows[0]
            row_gidx = None
            if synth is not None:
                row_gidx = nmask[0][:L] if synth[4] else None
                nmask = None  # synthesized on demand (dense) / per-slot
            else:
                nmask = nmask[0]
            if use_roll:
                wr, ws, *args = args
                wr, ws = wr[0], ws[0]
            if scaled:
                sc, *args = args
                sc0 = sc[0]
            if not uniform_offs:
                noffs = noffs[0]
            if split:
                hr, hnr, hof, hm, *args = args
                hr, hnr, hof, hm = hr[0], hnr[0], hof[0], hm[0]
            if include_to:
                trows, toffs, tmask, *args = args
                trows, toffs, tmask = trows[0], toffs[0], tmask[0]
            ins = args[:n_in]
            outs_cur = args[n_in: n_in + n_out]
            extra = args[n_in + n_out:]
            cell_fields = {n: f[0][:L] for n, f in zip(fields_in, ins)}
            if slotwise:
                # per-slot gather + accumulate: the [L, S] neighbor
                # stack (and [L, S, 3] offsets) never materialize
                if synth is not None:
                    sgidx, sbase = _synth_prep(synth, L, row_gidx=row_gidx)
                    mask_col = lambda j: _synth_col(synth, sgidx, sbase, j)
                else:
                    mask_col = lambda j: nmask[:, j]
                n_slots = len(r_shifts) if use_roll else nrows.shape[1]
                if synth is not None and not synth[4]:
                    slot_gather = _make_roll3d_gather(synth, L)
                else:
                    slot_gather = _make_nbr_slot_gather(
                        use_roll, r_shifts, L, nrows,
                        wr if use_roll else None, ws if use_roll else None,
                    )
                result = _run_slotwise(
                    kernel, cell_fields,
                    {n: f[0] for n, f in zip(fields_in, ins)}, slot_gather,
                    _make_offs_col(uniform_offs, noffs,
                                   sc0 if scaled else None),
                    mask_col, n_slots, extra)
            else:
                if nmask is None:
                    nmask = _synth_mask(synth, L, row_gidx=row_gidx)
                if uniform_offs:
                    noffs = nmask[:, :, None] * noffs[None, :, :]
                    if scaled:
                        # offs_const is in cell units; scale by per-row
                        # size
                        noffs = noffs * sc0[:, None, None]
                gather_nbr = _make_nbr_gather(
                    use_roll, r_shifts, L, nrows, nmask,
                    wr if use_roll else None, ws if use_roll else None,
                )
                nbr_fields = {n: gather_nbr(f[0])
                              for n, f in zip(fields_in, ins)}
                if include_to:
                    to_fields = {n: f[0][trows]
                                 for n, f in zip(fields_in, ins)}
                    result = kernel(
                        cell_fields, nbr_fields, noffs, nmask, to_fields,
                        toffs, tmask, *extra,
                    )
                else:
                    result = kernel(cell_fields, nbr_fields, noffs, nmask,
                                    *extra)
            if split:
                # second pass over the hard rows (near refinement) with
                # their own, wider gather tables; results scattered over
                # the far pass's output (pad index L drops)
                hrc = jnp.minimum(hr, L - 1)
                h_cell = {n: cell_fields[n][hrc] for n in fields_in}
                h_nbr = {n: f[0][hnr] for n, f in zip(fields_in, ins)}
                h_result = kernel(h_cell, h_nbr, hof, hm, *extra)
                for n in fields_out:
                    result[n] = result[n].at[hr].set(
                        h_result[n].astype(result[n].dtype), mode="drop"
                    )
            outs = []
            for n, cur in zip(fields_out, outs_cur):
                fl = cur[0]
                fl = fl.at[:L].set(result[n].astype(fl.dtype))
                outs.append(fl[None])
            return tuple(outs)

        mapped = _shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P() if uniform_offs else P(axis), P(axis))
            + ((P(axis), P(axis)) if use_roll else ())
            + ((P(axis),) if scaled else ())
            + ((P(axis),) * 4 if split else ())
            + ((P(axis), P(axis), P(axis)) if include_to else ())
            + (P(axis),) * (n_in + n_out) + (P(),) * n_extra,
            out_specs=(P(axis),) * n_out,
            check_vma=False,
        )

        fn = jax.jit(lambda *a: mapped(*a))
        self._program_cache[key] = fn
        return fn, tables

    # -- fused multi-step execution ------------------------------------

    def compile_step_loop(
        self,
        kernel,
        fields_in,
        fields_out,
        exchange_fields=None,
        neighborhood_id=DEFAULT_NEIGHBORHOOD_ID,
        n_extra=0,
    ):
        """One jitted program running ``n_steps`` time steps on device.

        Each iteration refreshes ghost rows of ``exchange_fields``
        (an all_to_all, as update_copies_of_remote_neighbors), gathers
        neighbors and runs ``kernel`` (same signature as apply_stencil's),
        and writes the result into ``fields_out`` — the whole time loop
        is a single XLA program (lax.fori_loop), so exchange, stencil
        and apply fuse with no host round-trips. This is the TPU answer
        to the reference's start/solve-inner/wait/solve-outer overlap
        (dccrg.hpp:5046-5413, tests/advection/2d.cpp:327-343): XLA
        overlaps the collective with independent compute inside one
        program instead of split-phase host calls.

        ``exchange_fields`` must be a subset of ``fields_out`` (fields
        that change per step); static fields' ghosts are assumed valid
        for the whole epoch. Returns ``(fn, tables, static_in)`` where
        ``fn(n_steps, *tables, *in, *out, *extra) -> out arrays`` with
        dynamic ``n_steps``; the program is cached by its static shape
        signature and survives (bucketed) structure epochs. Use
        :meth:`run_steps` for the stateful wrapper.
        """
        fields_in = tuple(fields_in)
        fields_out = tuple(fields_out)
        if exchange_fields is None:
            exchange_fields = fields_out
        exchange_fields = tuple(exchange_fields)
        if not set(exchange_fields) <= set(fields_out):
            raise ValueError(
                "exchange_fields must be a subset of fields_out; static "
                "fields' ghosts are refreshed once per structure epoch"
            )
        # DCCRG_BULK=pallas: the roll-plan-driven Pallas bulk executor
        # (ops/roll_executor.py) replaces the XLA roll path where the
        # plan is eligible (single-device closed-form, scalar fields,
        # SlotwiseKernel); anything else falls through. With the env
        # unset (or =xla) this branch is never entered and the
        # pre-executor program compiles bit-identically — the negative
        # pin, same discipline as DCCRG_INTEGRITY=0.
        if os.environ.get("DCCRG_BULK", "").strip().lower() == "pallas":
            from .ops import roll_executor

            built = roll_executor.compile_bulk_step_loop(
                self, kernel, fields_in, fields_out, exchange_fields,
                neighborhood_id, n_extra)
            if built is not None:
                return built
        hood = self.plan.hoods[neighborhood_id]
        L, R = self.plan.L, self.plan.R
        sh = self._sharding()
        uniform_offs = hood.offs_const is not None
        split = hood.hard_nbr_rows is not None
        cf = hood.closed_form
        if cf is not None:
            roll = hood.roll_plan(L)  # table-free plans always roll
        elif uniform_offs and self._use_roll_gather():
            roll = hood.roll_plan(
                L, cap=lambda n: self._sticky_cap(("rollW", neighborhood_id), n))
        else:
            roll = None
        r_shifts = tuple(int(s) for s in roll[0]) if roll is not None else None
        use_roll = r_shifts is not None
        static_in = tuple(n for n in fields_in if n not in fields_out)
        n_static, n_out = len(static_in), len(fields_out)
        exch_idx = tuple(fields_out.index(n) for n in exchange_fields)
        n_x = len(exch_idx)

        tables = []
        if use_roll:
            tables.append(hood.dev("roll_dummy",
                                   np.zeros((self.n_dev, 1, 1), np.int32), sh))
        else:
            tables.append(hood.dev("nbr_rows", hood.nbr_rows, sh))
        if uniform_offs:
            tables.append(hood.dev("offs_const", hood.offs_const))
        else:
            tables.append(hood.dev("nbr_offs", hood.nbr_offs, sh))
        if cf is not None:
            if cf.get("multi"):
                tables.append(self.device_row_ids())
            else:
                tables.append(hood.dev("mask_dummy",
                                       np.zeros((self.n_dev, 1, 1), bool),
                                       sh))
        else:
            tables.append(hood.dev("nbr_mask", hood.nbr_mask, sh))
        sends, recvs = self._pair_tables_device(
            neighborhood_id, tuple(fields_out[j] for j in exch_idx)
        )
        deltas = self._peer_deltas(neighborhood_id)
        n_t = 1 if deltas is None else len(deltas)
        tables.extend(sends)
        tables.extend(recvs)
        if use_roll:
            tables.append(hood.dev("roll_wr", roll[1], sh))
            tables.append(hood.dev("roll_ws", roll[2], sh))
        scaled = uniform_offs and hood.scale_rows is not None
        if scaled:
            tables.append(hood.dev("scale_rows", hood.scale_rows, sh))
        if split:
            tables.append(hood.dev("hard_rows", hood.hard_rows, sh))
            tables.append(hood.dev("hard_nbr_rows", hood.hard_nbr_rows, sh))
            tables.append(hood.dev("hard_offs", hood.hard_offs, sh))
            tables.append(hood.dev("hard_mask", hood.hard_mask, sh))
        overlap = (self.n_dev > 1 and hood.n_inner is not None
                   and n_x > 0 and self._use_overlap())
        # per-field ghost split (DCCRG_GHOST_SPLIT, default on): a
        # kernel declaring ghost_deps re-runs only the outer rows
        # feeding the fields that actually exchanged, and scatters
        # only the outputs whose declared ghost reads intersect the
        # exchanged set. Without a declaration (or with the knob off)
        # the pre-split program compiles bit-identically below.
        deps = getattr(kernel, "ghost_deps", None)
        o_mode = None          # "full" | "split" | "none" once engaged
        repass = fields_out    # outputs the outer re-pass scatters
        rows_full = rows_split = 0
        if overlap:
            rows_full = int((np.asarray(self.plan.n_local)
                             - np.asarray(hood.n_inner)).sum())
        if overlap and deps is not None and ghost_split_enabled():
            xn = tuple(fields_out[j] for j in exch_idx)
            repass = tuple(F for F in fields_out
                           if set(deps.get(F, fields_in)) & set(xn))
            relevant = tuple(sorted(set().union(set(), *(
                set(deps.get(F, fields_in)) & set(xn)
                for F in repass))))
            st = (self._split_outer_tables(
                neighborhood_id, hood, use_roll, r_shifts, roll,
                relevant) if repass else None)
            if st is None:
                # nothing needs a re-pass: overlap with the re-pass
                # elided entirely (the exchanged ghosts feed no output
                # this kernel computes, or no local row reads them)
                o_mode, repass = "none", ()
            elif repass == fields_out and st[2] >= rows_full:
                # the split saves nothing over the full re-pass: fall
                # through to the pre-split program (same key, same
                # tables — the shared compile IS the negative pin)
                o_mode, repass = None, fields_out
            elif 2 * st[2] > int(np.asarray(self.plan.n_local).sum()):
                overlap = False  # the re-pass outweighs the hidden
                repass = fields_out  # collective even split
            else:
                o_mode, rows_split = "split", st[2]
                # use_roll in the upload keys: the OOM fallback chain
                # (guarded_step) can compile roll AND table programs
                # over one hood, and their onr formats differ
                tables.append(hood.dev(
                    ("gsplit_rows", use_roll) + tuple(relevant),
                    st[0], sh))
                tables.append(hood.dev(
                    ("gsplit_nbr", use_roll) + tuple(relevant),
                    st[1], sh))
        if overlap and o_mode is None:
            ot = self._outer_tables(neighborhood_id, hood, use_roll,
                                    r_shifts, roll)
            if ot is None:
                overlap = False
            else:
                o_mode = "full"
                rows_split = rows_full
                tables.append(hood.dev("outer_rows", ot[0], sh))
                tables.append(hood.dev("outer_nbr_rows", ot[1], sh))
        o_tabs = o_mode in ("full", "split")
        self.last_overlap = {
            "mode": o_mode or "off",
            "rows_full": rows_full * n_out if overlap else 0,
            "rows_split": (rows_split * len(repass) if o_tabs
                           else 0) if overlap else 0,
            "repass_fields": repass if overlap else fields_out,
        }

        synth = _synth_key(cf)
        key = ("steploop", kernel, fields_in, fields_out, exch_idx, n_extra,
               L, R, uniform_offs, scaled, split, r_shifts, synth, deltas,
               overlap) + ((("gsplit", o_mode, repass),)
                           if o_mode in ("split", "none") else ())
        fn = self._program_cache.get(key)
        if fn is not None:
            return fn, tables, static_in

        axis, mesh, n_dev = self.axis, self.mesh, self.n_dev
        slotwise = isinstance(kernel, SlotwiseKernel)

        def body(n_steps, nrows, noffs, nmask, *args):
            send_rs = [a[0] for a in args[: n_x * n_t]]
            recv_rs = [a[0] for a in args[n_x * n_t : 2 * n_x * n_t]]
            args = args[2 * n_x * n_t:]
            nrows = nrows[0]
            row_gidx = None
            if synth is not None:
                row_gidx = nmask[0][:L] if synth[4] else None
                nmask = None  # synthesized on demand (dense) / per-slot
            else:
                nmask = nmask[0]
            if use_roll:
                wr, ws, *args = args
                wr, ws = wr[0], ws[0]
            if scaled:
                sc, *args = args
                sc0 = sc[0]
            if not uniform_offs:
                noffs = noffs[0]
            if split:
                hr, hnr, hof, hm, *args = args
                hr, hnr, hof, hm = hr[0], hnr[0], hof[0], hm[0]
                hrc = jnp.minimum(hr, L - 1)
            if o_tabs:
                orow_t, onr_t, *args = args
                orow, onr = orow_t[0], onr_t[0]
                orc = jnp.minimum(orow, L - 1)
            def exchange_one(fl, xi):
                # per-peer-offset ppermutes of compact buffers, or the
                # dense all_to_all fallback (see _exchange_programs)
                for j in range(n_t):
                    dlt = None if deltas is None else deltas[j]
                    payload = _halo_send(fl, send_rs[xi * n_t + j], dlt,
                                         axis, n_dev)
                    fl = _halo_scatter(fl, recv_rs[xi * n_t + j], payload, R)
                return fl.at[R - 1].set(0)
            if slotwise:
                n_slots = len(r_shifts) if use_roll else nrows.shape[1]
                if synth is not None:
                    sgidx, sbase = _synth_prep(synth, L, row_gidx=row_gidx)
                    mask_col = lambda j: _synth_col(synth, sgidx, sbase, j)

                    def mask_rows(rows):
                        g, b = sgidx[rows], sbase[rows]
                        return jnp.stack(
                            [_synth_col(synth, g, b, j)
                             for j in range(n_slots)], axis=1)
                else:
                    mask_col = lambda j: nmask[:, j]
                    mask_rows = lambda rows: nmask[rows]
                if synth is not None and not synth[4]:
                    slot_gather = _make_roll3d_gather(synth, L)
                else:
                    slot_gather = _make_nbr_slot_gather(
                        use_roll, r_shifts, L, nrows,
                        wr if use_roll else None, ws if use_roll else None,
                    )

                def offs_rows(rows, m):
                    # dense offsets for a surface-sized row subset,
                    # premasked like the dense path's uniform offsets
                    if uniform_offs:
                        o = m[:, :, None] * noffs[None, :, :]
                        if scaled:
                            o = o * sc0[rows][:, None, None]
                        return o
                    return noffs[rows]

                def run_bulk(full, cell_fields, extra):
                    return _run_slotwise(
                        kernel, cell_fields,
                        {n: full[n] for n in fields_in}, slot_gather,
                        _make_offs_col(uniform_offs, noffs,
                                       sc0 if scaled else None),
                        mask_col, n_slots, extra)
            else:
                if nmask is None:
                    nmask = _synth_mask(synth, L, row_gidx=row_gidx)
                if uniform_offs:
                    noffs = nmask[:, :, None] * noffs[None, :, :]
                    if scaled:
                        noffs = noffs * sc0[:, None, None]
                gather_nbr = _make_nbr_gather(
                    use_roll, r_shifts, L, nrows, nmask,
                    wr if use_roll else None, ws if use_roll else None,
                )

            statics = {n: a[0] for n, a in zip(static_in, args[:n_static])}
            state0 = tuple(a[0] for a in args[n_static:n_static + n_out])
            extra = args[n_static + n_out:]

            def step(_, state):
                state = list(state)
                if overlap:
                    # sends read only local rows: every round's
                    # collective starts BEFORE the bulk kernel, with no
                    # data dependency between them, so the scheduler
                    # can fly the halos under the stencil compute
                    # (async collective-permute) — the reference's
                    # solve-inner-while-messages-fly overlap
                    # (dccrg.hpp:5046-5413, 2d.cpp:327-343)
                    payloads = [
                        _halo_send(state[j], send_rs[xi * n_t + t],
                                   None if deltas is None else deltas[t],
                                   axis, n_dev)
                        for xi, j in enumerate(exch_idx)
                        for t in range(n_t)
                    ]
                    # bulk pass on pre-exchange state: rows
                    # [0, n_inner) read no ghosts, so their results
                    # are final; outer rows are redone below
                    full = dict(statics)
                    full.update(zip(fields_out, state))
                    cell_fields = {n: full[n][:L] for n in fields_in}
                    if slotwise:
                        result = run_bulk(full, cell_fields, extra)
                    else:
                        nbr_fields = {n: gather_nbr(full[n])
                                      for n in fields_in}
                        result = kernel(cell_fields, nbr_fields, noffs,
                                        nmask, *extra)
                    # land the halos, then redo just the outer rows
                    # (with ghost-split, only the rows feeding the
                    # exchanged fields, scattering only the outputs
                    # whose declared ghost reads those fields)
                    for xi, j in enumerate(exch_idx):
                        fl = state[j]
                        for t in range(n_t):
                            fl = _halo_scatter(fl, recv_rs[xi * n_t + t],
                                               payloads[xi * n_t + t], R)
                        state[j] = fl.at[R - 1].set(0)
                    if o_tabs:
                        full = dict(statics)
                        full.update(zip(fields_out, state))
                        cell_fields = {n: full[n][:L] for n in fields_in}
                        om = mask_rows(orc) if slotwise else nmask[orc]
                        o_cell = {n: cell_fields[n][orc]
                                  for n in fields_in}
                        o_nbr = {}
                        for n in fields_in:
                            g = full[n][onr]
                            if use_roll:
                                # mirror _make_nbr_gather's mask-zeroing
                                mexp = om.reshape(om.shape
                                                  + (1,) * (g.ndim - 2))
                                g = jnp.where(mexp, g,
                                              jnp.zeros((), g.dtype))
                            o_nbr[n] = g
                        o_offs = (offs_rows(orc, om) if slotwise
                                  else noffs[orc])
                        o_res = kernel(o_cell, o_nbr, o_offs, om, *extra)
                        for n in repass:
                            result[n] = result[n].at[orow].set(
                                o_res[n].astype(result[n].dtype),
                                mode="drop")
                else:
                    if n_dev > 1:
                        for xi, j in enumerate(exch_idx):
                            state[j] = exchange_one(state[j], xi)
                    full = dict(statics)
                    full.update(zip(fields_out, state))
                    cell_fields = {n: full[n][:L] for n in fields_in}
                    if slotwise:
                        result = run_bulk(full, cell_fields, extra)
                    else:
                        nbr_fields = {n: gather_nbr(full[n])
                                      for n in fields_in}
                        result = kernel(cell_fields, nbr_fields, noffs,
                                        nmask, *extra)
                if split:
                    h_cell = {n: cell_fields[n][hrc] for n in fields_in}
                    h_nbr = {n: full[n][hnr] for n in fields_in}
                    h_result = kernel(h_cell, h_nbr, hof, hm, *extra)
                    for n in fields_out:
                        result[n] = result[n].at[hr].set(
                            h_result[n].astype(result[n].dtype), mode="drop"
                        )
                for j, n in enumerate(fields_out):
                    state[j] = state[j].at[:L].set(result[n].astype(state[j].dtype))
                return tuple(state)

            out = jax.lax.fori_loop(0, n_steps, step, state0)
            return tuple(o[None] for o in out)

        mapped = _shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(axis),
                      P() if uniform_offs else P(axis), P(axis))
            + (P(axis),) * (2 * n_x * n_t)
            + ((P(axis), P(axis)) if use_roll else ())
            + ((P(axis),) if scaled else ())
            + ((P(axis),) * 4 if split else ())
            + ((P(axis), P(axis)) if o_tabs else ())
            + (P(axis),) * (n_static + n_out) + (P(),) * n_extra,
            out_specs=(P(axis),) * n_out,
            check_vma=False,
        )

        fn = jax.jit(lambda *a: mapped(*a))
        self._program_cache[key] = fn
        return fn, tables, static_in

    def run_steps(
        self,
        kernel,
        fields_in,
        fields_out,
        n_steps,
        exchange_fields=None,
        neighborhood_id=DEFAULT_NEIGHBORHOOD_ID,
        extra_args=(),
    ) -> None:
        """Run ``n_steps`` fused exchange+stencil steps and install the
        results (see compile_step_loop)."""
        # the background-recommit swap point: a FINISHED plan installs
        # here, at a step boundary, before this dispatch compiles
        # against the (then previous) epoch; an unfinished build keeps
        # the loop on the live plan — zero stall (DCCRG_BG_RECOMMIT)
        if getattr(self, "_bg_build", None) is not None:
            self.bg_install()
        fields_in = tuple(fields_in)
        fields_out = tuple(fields_out)
        with telemetry.span("grid.step"):
            fn, tables, static_in = self.compile_step_loop(
                kernel, fields_in, fields_out, exchange_fields,
                neighborhood_id, n_extra=len(extra_args),
            )
            ov = getattr(self, "last_overlap", None)
            if ov is not None and ov["mode"] != "off":
                # the ghost-split measuring stick: outer-re-pass row
                # slots actually recomputed vs the full re-pass's
                telemetry.inc("dccrg_outer_repass_rows_total",
                              ov["rows_split"] * int(n_steps),
                              mode=ov["mode"])
                telemetry.inc("dccrg_outer_repass_rows_full_total",
                              ov["rows_full"] * int(n_steps))
            out = fn(
                jnp.int32(n_steps),
                *tables,
                *(self.data[n] for n in static_in),
                *(self.data[n] for n in fields_out),
                *extra_args,
            )
            for n, arr in zip(fields_out, out):
                self.data[n] = arr
        self._mark_ckpt_dirty(fields_out)
        # DCCRG_WATCHDOG=N: self-check the stepped fields for NaN/Inf
        # every ~N steps (one device-side scalar; see resilience.py) —
        # a silent blow-up surfaces as NumericsError instead of
        # garbage physics hours later
        from . import resilience

        wd = resilience.watchdog_interval()
        if wd > 0:
            self._watchdog_accum = getattr(self, "_watchdog_accum", 0) \
                + int(n_steps)
            if self._watchdog_accum >= wd:
                self._watchdog_accum = 0
                resilience.assert_finite(self, fields_out)

    def run_steps_guarded(
        self,
        kernel,
        fields_in,
        fields_out,
        n_steps,
        exchange_fields=None,
        neighborhood_id=DEFAULT_NEIGHBORHOOD_ID,
        extra_args=(),
    ) -> str:
        """:meth:`run_steps` with graceful OOM degradation: on XLA
        ``RESOURCE_EXHAUSTED`` the dispatch walks the gather-mode
        fallback chain (current -> slot-wise roll -> dense tables),
        logging each downgrade. Returns the mode that completed
        (see resilience.guarded_step)."""
        from . import resilience

        return resilience.guarded_step(
            self, kernel, fields_in, fields_out, n_steps,
            exchange_fields=exchange_fields,
            neighborhood_id=neighborhood_id, extra_args=extra_args,
        )

    # -- load balancing (dccrg.hpp:1046-1064, 3770-4182, 8482-8720) ----

    def balance_load(self, use_zoltan: bool = True) -> None:
        """Repartition cells over devices and move their data: the
        reference's balance_load (dccrg.hpp:1046). ``use_zoltan=False``
        keeps the partition from pin requests only (parity with the
        reference's flag).

        Atomic: the three stages run in ONE transaction — a failure
        in any of them rolls the whole balance back
        (:class:`~dccrg_tpu.txn.MutationAbortedError`) and the grid
        keeps its previous partition, data placement and staging."""
        with telemetry.span("grid.balance"), \
                grid_transaction(self, op="balance_load"):
            self.initialize_balance_load(use_zoltan)
            self.continue_balance_load()
            self.finish_balance_load()

    def initialize_balance_load(self, use_zoltan: bool = True) -> None:
        """Stage 1: compute the new partition (dccrg.hpp:3770-3909).
        SFC partitioning with weights replaces Zoltan_LB_Balance;
        pin requests are merged in afterwards, as the reference merges
        pins with Zoltan output (dccrg.hpp:8552-8576)."""
        if getattr(self, "_pending_owner", None) is not None:
            raise RuntimeError("balance_load already initialized")
        with grid_transaction(self, op="initialize_balance_load"):
            self._initialize_balance_load_impl(use_zoltan)

    def _initialize_balance_load_impl(self, use_zoltan: bool) -> None:
        self._staged_balance = {}
        cells = self.plan.cells
        if use_zoltan:
            weights = None
            if self._weights:
                weights = np.ones(len(cells), dtype=np.float64)
                for cid, w in self._weights.items():
                    pos = np.searchsorted(cells, np.uint64(cid))
                    if pos < len(cells) and cells[pos] == np.uint64(cid):
                        weights[pos] = w
            # connectivity edges for the "cut" method (the role of
            # Zoltan's graph callbacks, dccrg.hpp:12091-12252). On
            # closed-form plans the of-lists are a lazy thunk whose
            # first build is O(grid); the edge arrays only depend on
            # the CELL SET (not the partition), so they are cached on
            # the grid and survive repeated balances until an AMR
            # commit changes the cells.
            edges = None
            methods = [lv.get("method") for lv in self._partitioning_levels]
            if self._lb_method == "cut" or "cut" in methods:
                # keyed on the grid's cell-set epoch (bumped by
                # _restructure whenever the cell set changes) — a
                # content fingerprint could collide across AMR commits
                ck = getattr(self, "_cells_epoch", 0)
                cached = getattr(self, "_cut_edges", None)
                if cached is not None and cached[0] == ck:
                    edges = cached[1]
                else:
                    nl = self.plan.hoods[DEFAULT_NEIGHBORHOOD_ID].lists
                    edges = (nl.of_source.astype(np.int64),
                             np.searchsorted(cells, nl.of_neighbor))
                    self._cut_edges = (ck, edges)
            if self._partitioning_levels:
                new_owner = partition_cells_hierarchical(
                    self.mapping, cells, self.n_dev,
                    self._partitioning_levels,
                    weights=weights, pins=self._pins or None, edges=edges,
                )
            else:
                new_owner = partition_cells(
                    self.mapping, cells, self.n_dev, self._lb_method,
                    weights=weights, pins=self._pins or None, edges=edges,
                )
        else:
            new_owner = self.plan.owner.copy()
            for cid, dest in self._pins.items():
                pos = np.searchsorted(cells, np.uint64(cid))
                if pos < len(cells) and cells[pos] == np.uint64(cid):
                    new_owner[pos] = dest
        faults.fire("balance.commit", phase="partition")
        self._pending_owner = new_owner

    def continue_balance_load(self, fields=None) -> None:
        """Stage 2: transfer the data of cells that change owner, for
        the given field group (dccrg.hpp:3932-3964). Callable
        repeatedly with different ``fields`` — the reference's
        multi-stage protocol for ragged payloads
        (tests/load_balancing/multi_stage_load_balancing.cpp): a field
        group captured here is what arrives at the destination at
        finish_balance_load, even if the source data (or another
        field's capacity) changes between stages. Fields never staged
        by any continue call move atomically at finish."""
        if getattr(self, "_pending_owner", None) is None:
            raise RuntimeError("initialize_balance_load not called")
        names = list(fields) if fields is not None else list(self.fields)
        for n in names:
            if n not in self.fields:
                raise KeyError(f"unknown field {n!r}")
        # validate=False: staging only captures snapshot references in
        # _staged_balance — no structure the verifiers check can change,
        # so the (repeatable) stage skips the O(grid) debug validation
        with grid_transaction(self, op="continue_balance_load",
                              validate=False):
            faults.fire("balance.commit", phase="stage")
            moving = self.plan.cells[self._pending_owner != self.plan.owner]
            for n in names:
                # DEVICE-side staging: jax arrays are immutable, so the
                # stage is a zero-copy snapshot reference — the captured
                # version survives later set()s (which install new arrays)
                # and the landing at finish is an on-device gather; moved
                # payloads never leave HBM (the reference moves balance
                # payloads rank-to-rank, dccrg.hpp:3932-3964)
                self._staged_balance[n] = (
                    moving.copy(), self.data[n] if len(moving) else None
                )

    def staged_balance_data(self, field: str):
        """(moving cell ids, values) captured by continue_balance_load
        for a field — the receiver-side peek between stages (the
        reference's receivers see arrived data in their cell_data
        before finish)."""
        ids, snap = self._staged_balance[field]
        if snap is None:
            return ids.copy(), None
        dev, rows = self._host_rows(ids)  # plan unchanged since staging
        if self._multiproc:
            # rank-local peek: only this process's moving cells, read
            # from addressable shards of the snapshot (no collective)
            lm = self._proc_local_dev[dev]
            by_dev = {s.index[0].start: s.data
                      for s in snap.addressable_shards}
            out = np.empty((int(lm.sum()),) + snap.shape[2:],
                           dtype=snap.dtype)
            ldev, lrows = dev[lm], rows[lm]
            for d in np.unique(ldev):
                m = ldev == d
                out[m] = np.asarray(by_dev[int(d)][0, lrows[m]])
            return ids[lm].copy(), out
        return ids.copy(), np.asarray(snap[dev, rows])

    def finish_balance_load(self) -> None:
        """Stage 3: install the new partition, rebuild all derived
        structure (dccrg.hpp:3980-4182), and land the staged field
        groups at their destinations. Atomic: a failure rolls back to
        the staged (post-continue) state, so finish can be retried."""
        if getattr(self, "_pending_owner", None) is None:
            raise RuntimeError("initialize_balance_load not called")
        with grid_transaction(self, op="finish_balance_load"):
            self._finish_balance_load_impl()

    def _finish_balance_load_impl(self) -> None:
        new_owner = self._pending_owner
        faults.fire("balance.commit", phase="finish")
        moved = self.plan.cells[new_owner != self.plan.owner]
        # per-device view of the movement (reference
        # get_cells_added/removed_by_balance_load, dccrg.hpp)
        self._balance_added = {
            d: moved[new_owner[np.searchsorted(self.plan.cells, moved)] == d]
            for d in range(self.n_dev)
        }
        self._balance_removed = {
            d: moved[self.plan.owner[np.searchsorted(self.plan.cells, moved)] == d]
            for d in range(self.n_dev)
        }
        self._pending_owner = None
        staged = self._staged_balance
        self._staged_balance = {}
        # old row positions of every staged group, before the plan is
        # rebuilt: the landing gathers straight from the device
        # snapshots (no host copy of moved payloads; the reference
        # moves them rank-to-rank, dccrg.hpp:3932-3964)
        old_pos = {n: self._host_rows(ids)
                   for n, (ids, snap) in staged.items() if snap is not None}
        old_R = self.plan.R
        self._restructure(self.plan.cells.copy(), new_owner)
        faults.fire("balance.commit", phase="land")
        if self._debug:
            from . import verify as _verify

            _verify.pin_requests_succeeded(self)
        sh = self._sharding()
        # all staged groups share one moving-id set per balance: build
        # the relocation index tables once, not once per field
        tbl_ids, src_dev, mask_dev = None, None, None
        for n, (ids, snap) in staged.items():
            if snap is None or n not in self.fields:
                continue
            shape, dtype = self.fields[n]
            if tbl_ids is None or not np.array_equal(ids, tbl_ids):
                od, orw = old_pos[n]
                nd, nrw = self._host_rows(ids)
                src = np.full(self.n_dev * self.plan.R, -1, dtype=np.int64)
                src[nd.astype(np.int64) * self.plan.R + nrw] = (
                    od.astype(np.int64) * old_R + orw)
                src2 = src.reshape(self.n_dev, self.plan.R)
                src_dev = put_sharded(src2, sh)
                mask_dev = put_sharded(src2 >= 0, sh)
                tbl_ids = ids
            snap_shape = tuple(snap.shape[2:])
            key = ("balance_land", snap_shape, shape, str(dtype))
            fn = self._program_cache.get(key)
            if fn is None:
                @partial(jax.jit, out_shardings=sh)
                def fn(cur, snp, srcs, mask, _ss=snap_shape, _ts=shape):
                    flat = snp.reshape((-1,) + snp.shape[2:])
                    g = flat[jnp.clip(srcs, 0)]
                    if _ss != _ts:
                        # a stage in between grew/shrank the field (the
                        # particles resize-by-count flow): pad/truncate
                        # the staged rows to the current capacity
                        fixed = jnp.zeros(g.shape[:2] + _ts, g.dtype)
                        sl = tuple(slice(0, min(a, b))
                                   for a, b in zip(_ss, _ts))
                        ix = (slice(None), slice(None)) + sl
                        g = fixed.at[ix].set(g[ix])
                    mexp = mask.reshape(mask.shape + (1,) * len(_ts))
                    return jnp.where(mexp, g.astype(cur.dtype), cur)
                self._program_cache[key] = fn
            self.data[n] = fn(self.data[n], snap, src_dev, mask_dev)

    def get_cells_added_by_balance_load(self, device: int | None = None):
        """Cells the last balance_load moved ONTO a device (all moved
        cells when device is None) — reference
        get_cells_added_by_balance_load."""
        added = getattr(self, "_balance_added", {})
        if device is not None:
            return added.get(int(device), np.empty(0, np.uint64)).copy()
        return (np.sort(np.concatenate(list(added.values())))
                if added else np.empty(0, np.uint64))

    def get_cells_removed_by_balance_load(self, device: int | None = None):
        """Cells the last balance_load moved OFF a device."""
        removed = getattr(self, "_balance_removed", {})
        if device is not None:
            return removed.get(int(device), np.empty(0, np.uint64)).copy()
        return (np.sort(np.concatenate(list(removed.values())))
                if removed else np.empty(0, np.uint64))

    def get_cells_to_send(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID):
        """{(sender, receiver): cell ids} of one halo update — the
        reference's per-peer send lists (dccrg.hpp get_cells_to_send)."""
        c = self.plan.hoods[neighborhood_id].pair_compact
        starts, ends = self._pair_groups(c)
        out = {}
        for s, e in zip(starts, ends):
            p0, q0 = int(c["p"][s]), int(c["q"][s])
            out[(p0, q0)] = self.plan.local_ids[p0][c["srow"][s:e]]
        return out

    def get_cells_to_receive(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID):
        """{(sender, receiver): cell ids} computed from the RECEIVE
        rows (ghost rows on the receiver), independently of
        get_cells_to_send's sender rows — the two must agree, and tests
        cross-check them (reference get_cells_to_receive)."""
        c = self.plan.hoods[neighborhood_id].pair_compact
        starts, ends = self._pair_groups(c)
        L = self.plan.L
        out = {}
        for s, e in zip(starts, ends):
            p0, q0 = int(c["p"][s]), int(c["q"][s])
            out[(p0, q0)] = self.plan.ghost_ids[q0][c["rrow"][s:e] - L]
        return out

    def get_neighborhood_of(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID):
        """The neighborhood's offset list (reference
        get_neighborhood_of)."""
        return np.asarray(self.neighborhoods[neighborhood_id]).copy()

    def get_neighborhood_to(self, neighborhood_id=DEFAULT_NEIGHBORHOOD_ID):
        """Negated offsets (the to-direction items)."""
        return -self.get_neighborhood_of(neighborhood_id)

    def get_pin_requests(self) -> dict:
        """Current pin requests {cell id: device} (reference
        get_pin_requests; the new/committed distinction collapses on a
        single controller)."""
        return dict(self._pins)

    # pinning (dccrg.hpp:5913-6139)

    def pin(self, cell, process: int) -> bool:
        """Force a cell onto a device across future balance_loads."""
        if not self.is_local(cell) or not 0 <= int(process) < self.n_dev:
            return False
        self._pins[int(cell)] = int(process)
        return True

    def unpin(self, cell) -> bool:
        return self._pins.pop(int(cell), None) is not None

    def unpin_local_cells(self, device: int | None = None) -> None:
        """Remove pins of cells owned by the given device (all, when
        None — host code sees every device)."""
        for cid in list(self._pins):
            if not self.is_local(cid):  # stale pin (cell gone): prune
                del self._pins[cid]
            elif device is None or self.get_process(cid) == device:
                del self._pins[cid]

    def unpin_all_cells(self) -> None:
        self._pins.clear()

    # cell weights (dccrg.hpp:6318-6380)

    def set_cell_weight(self, cell, weight: float) -> bool:
        if not self.is_local(cell):
            return False
        if weight < 0:
            return False
        self._weights[int(cell)] = float(weight)
        return True

    def get_cell_weight(self, cell) -> float:
        return self._weights.get(int(cell), 1.0)

    # partitioning options (dccrg.hpp:5590-5880). The SFC partitioner
    # has no Zoltan parameter space; options are recorded for parity
    # and 'method'/'LB_METHOD' selects the curve.

    def set_partitioning_option(self, name: str, value) -> None:
        if name.upper() in ("LB_METHOD", "METHOD"):
            self.set_load_balancing_method(str(value))
        self._partitioning_options[name] = value

    def get_partitioning_options(self, hierarchial_partitioning_level: int | None = None):
        """Flat options dict, or (with a level argument) that hierarchy
        level's option names (dccrg.hpp:5814)."""
        if hierarchial_partitioning_level is None:
            return dict(self._partitioning_options)
        lv = self._hierarchy_level(hierarchial_partitioning_level)
        return [k for k in lv if k not in ("processes", "method")]

    # hierarchical partitioning (Zoltan hierarchical replacement,
    # dccrg.hpp:5629-5880): levels group devices, e.g. (host, chip)

    def _hierarchy_level(self, level: int) -> dict:
        if not 0 <= int(level) < len(self._partitioning_levels):
            raise IndexError(
                f"no hierarchial partitioning level {level} "
                f"(have {len(self._partitioning_levels)})"
            )
        return self._partitioning_levels[int(level)]

    def add_partitioning_level(self, processes: int):
        """Append a hierarchy level whose parts hold ``processes``
        devices each (dccrg.hpp:5634). On TPU a natural two-level
        hierarchy is (devices-per-host, 1)."""
        if int(processes) < 1:
            raise ValueError("processes per part must be >= 1")
        self._partitioning_levels.append({"processes": int(processes)})
        return self

    def remove_partitioning_level(self, hierarchial_partitioning_level: int):
        self._hierarchy_level(hierarchial_partitioning_level)
        del self._partitioning_levels[int(hierarchial_partitioning_level)]
        return self

    def add_partitioning_option(self, level: int, name: str, value):
        """Set an option on a hierarchy level (dccrg.hpp:5731);
        'LB_METHOD'/'method' selects the curve for that level's split."""
        lv = self._hierarchy_level(level)
        lv[name] = value
        if name.upper() in ("LB_METHOD", "METHOD"):
            method = str(value).lower()
            if method not in PARTITION_METHODS:
                raise ValueError(
                    f"unknown method {value!r} for level {level}, have {PARTITION_METHODS}"
                )
            lv["method"] = method  # validated lowercase wins over the raw value
        return self

    def remove_partitioning_option(self, level: int, name: str):
        lv = self._hierarchy_level(level)
        lv.pop(name, None)
        if name.upper() in ("LB_METHOD", "METHOD"):
            lv.pop("method", None)
        return self

    def get_partitioning_option_value(self, level: int, name: str):
        return self._hierarchy_level(level).get(name)

    # -- adaptive mesh refinement (dccrg.hpp:2456-3507, 9730-10693) ----

    def refine_completely(self, cell) -> bool:
        """Request refinement of a cell into its 8 children
        (dccrg.hpp:2456). Committed by stop_refining()."""
        if not self.is_local(cell):
            return False
        if self.mapping.get_refinement_level(np.uint64(cell)) >= self.mapping.max_refinement_level:
            return False
        self._refines.add(int(cell))
        # a refine overrides pending unrefines of the sibling groups it
        # touches (dccrg.hpp:2517-2551); resolved again at commit
        self._unrefines.discard(int(cell))
        return True

    def unrefine_completely(self, cell) -> bool:
        """Request removal of the cell's sibling group, replaced by the
        parent (dccrg.hpp:2582)."""
        if not self.is_local(cell):
            return False
        if self.mapping.get_refinement_level(np.uint64(cell)) == 0:
            return False
        if int(cell) in self._refines:
            return False
        self._unrefines.add(int(cell))
        return True

    def dont_refine(self, cell) -> bool:
        """Forbid refinement (incl. induced) of the cell (dccrg.hpp:2766)."""
        if not self.is_local(cell):
            return False
        self._dont_refines.add(int(cell))
        return True

    def dont_unrefine(self, cell) -> bool:
        """Forbid unrefinement of the cell's sibling group (dccrg.hpp:2701)."""
        if not self.is_local(cell):
            return False
        self._dont_unrefines.add(int(cell))
        return True

    def refine_completely_at(self, coordinate) -> bool:
        """Coordinate variant (dccrg.hpp:3401-3470)."""
        c = self.get_existing_cell(coordinate)
        return bool(c != ERROR_CELL) and self.refine_completely(c)

    def unrefine_completely_at(self, coordinate) -> bool:
        c = self.get_existing_cell(coordinate)
        return bool(c != ERROR_CELL) and self.unrefine_completely(c)

    def dont_refine_at(self, coordinate) -> bool:
        c = self.get_existing_cell(coordinate)
        return bool(c != ERROR_CELL) and self.dont_refine(c)

    def dont_unrefine_at(self, coordinate) -> bool:
        c = self.get_existing_cell(coordinate)
        return bool(c != ERROR_CELL) and self.dont_unrefine(c)

    def enable_distributed_amr(self, *, kv=None, rank=None,
                               n_ranks=None, membership=None,
                               prefix="dccrg/amr", timeout=None):
        """Route this grid's adapt epochs through the fleet-wide,
        crash-consistent commit protocol (dccrg_tpu/distamr.py):
        ``stop_refining`` becomes an epoch-fenced collective install
        coordinated over the KV, every rank's local requests merged by
        a deadline-bounded proposal exchange. Returns the installed
        :class:`~dccrg_tpu.distamr.AmrCommitGroup`. A ``membership``
        lease view lets a retry after a rank death re-form the
        collective over the survivors."""
        from . import distamr

        self._amr_group = distamr.AmrCommitGroup(
            self, kv=kv, rank=rank, n_ranks=n_ranks,
            membership=membership, prefix=prefix, timeout=timeout)
        return self._amr_group

    def disable_distributed_amr(self) -> None:
        """Drop the commit group: ``stop_refining`` reverts to the
        single-controller path."""
        self._amr_group = None

    def stop_refining(self) -> np.ndarray:
        """Commit all refinement requests; returns the created cells
        (dccrg.hpp:3483-3507). Data of refined parents and removed
        cells stays readable through get_old_data() until
        clear_refined_unrefined_data().

        Atomic: a failure anywhere inside the commit (including
        injected faults) rolls the grid — requests included — back to
        its pre-commit state and re-raises as
        :class:`~dccrg_tpu.txn.MutationAbortedError`; retrying the
        commit is then safe. With ``DCCRG_DEBUG=1`` the committed
        state is verified and rolled back on a broken invariant
        (:class:`~dccrg_tpu.txn.GridInvariantError`).

        With an :meth:`enable_distributed_amr` group installed the
        commit instead runs the fleet-wide fenced protocol — same
        return value, same atomicity per rank, plus the distributed
        rollback/fencing guarantees documented in
        dccrg_tpu/distamr.py. Without one, this is byte-for-byte the
        single-controller commit."""
        group = getattr(self, "_amr_group", None)
        if group is not None:
            from . import distamr

            return distamr.distributed_stop_refining(self, group)
        return self._stop_refining_local()

    def _stop_refining_local(self) -> np.ndarray:
        from .amr import resolve_adaptation

        with telemetry.span("grid.adapt"), \
                grid_transaction(self, op="stop_refining"):
            faults.fire("adapt.commit", phase="resolve")
            res = resolve_adaptation(
                self.mapping,
                self.plan.cells,
                self.plan.owner,
                self.neighborhoods[DEFAULT_NEIGHBORHOOD_ID],
                self._refines,
                self._unrefines,
                self._dont_refines,
                self._dont_unrefines,
                pins=self._pins,
                weights=self._weights,
                topology=self.topology,
                hood_len=self._hood_len,
            )
            faults.fire("adapt.commit", phase="resolved")
            self._refines.clear()
            self._unrefines.clear()
            self._dont_refines.clear()
            self._dont_unrefines.clear()

            # preserve data of disappearing cells for the app's projection
            old_ids = np.concatenate([res.refined_parents, res.removed_cells])
            self._removed_data = {}
            if len(old_ids):
                # gather the disappearing cells' rows ON DEVICE and pull
                # only that slice (not every field's full array), through
                # the psum gather whose replicated (structure-derived) args
                # make it consistent across processes too; the sticky cap
                # keeps the program from retracing per epoch
                dev, rows = self._host_rows(old_ids)
                capn = self._sticky_cap("removed", len(old_ids))
                for name in self.fields:
                    self._removed_data[name] = (
                        old_ids, self._device_gather(name, dev, rows, cap=capn)
                    )
            else:
                self._removed_data = {name: (old_ids, None) for name in self.fields}
            faults.fire("adapt.commit", phase="preserved")
            self._removed_cells = res.removed_cells
            self._new_cells = res.new_cells
            self._unrefined_parents = res.unrefined_parents

            # dirty-set propagation into the hybrid recommit: the ids
            # that appear in exactly one of the pre/post cell lists
            self._pending_changed_cells = res.changed_cells
            self._restructure(res.cells, res.owner, defer_ok=True)
            return res.new_cells.copy()

    def _restructure(self, new_cells, new_owner, defer_ok=False):
        with telemetry.span("grid.recommit"):
            return self._restructure_impl(new_cells, new_owner,
                                          defer_ok=defer_ok)

    def _restructure_impl(self, new_cells, new_owner, defer_ok=False):
        """Rebuild the plan for a new cell set, carrying over the data
        of surviving cells (the reference's rebuild at
        dccrg.hpp:10642-10690, with data movement folded in).

        With ``DCCRG_BG_RECOMMIT=1`` and ``defer_ok`` (the
        ``stop_refining`` commit — a balance must land its staged data
        on the new plan immediately, so it never defers), the plan
        build runs on a background worker while stepping continues on
        the live plan; :meth:`run_steps` (and ``GridBatch.step``)
        installs the finished plan at the next step boundary via
        :meth:`bg_install`. Until the swap, queries and checkpoints
        reflect the previous (consistent) structure epoch.

        Data moves entirely on device: each surviving cell's (old dev,
        old row) -> (new dev, new row) relocation is ONE sharded gather
        per field (XLA inserts the cross-device collective), instead of
        pulling every field to host and re-uploading."""
        # builds are serialized per grid: a still-pending background
        # plan installs (or inline-rebuilds) before a new one starts
        self.bg_install(wait=True)
        old_plan = self.plan

        # dirty-set hint for the hybrid recommit: stop_refining knows
        # exactly which ids changed; an owner-only restructure (a
        # repartition) changes none. The hint is keyed on the previous
        # plan's cell array OBJECT so a stale hint can never alias a
        # different epoch (hybrid.build_hybrid_plan verifies identity).
        pending = getattr(self, "_pending_changed_cells", None)
        self._pending_changed_cells = None
        same_cells = (len(new_cells) == len(old_plan.cells)
                      and np.array_equal(new_cells, old_plan.cells))
        if same_cells:
            changed_hint = (old_plan.cells, np.empty(0, dtype=np.uint64))
        elif pending is not None:
            changed_hint = (old_plan.cells, pending)
        else:
            changed_hint = None

        if (defer_ok and background.bg_recommit_enabled()
                and not self._multiproc):
            self._bg_build = background.PlanBuildWorker(
                self, new_cells, new_owner, changed_hint).start()
            return

        plan = self._construct_plan(new_cells, new_owner, changed_hint)
        self._install_plan(plan, same_cells=same_cells)

    def _install_plan(self, plan, same_cells=None):
        """Install a constructed plan as the live structure epoch and
        relocate the surviving cells' data — the impure half of a
        restructure, always on the thread that owns the grid (the
        step-boundary swap point for background builds)."""
        old_plan = self.plan
        old_R = old_plan.R
        # any restructure (cell-set change OR repartition) ends the
        # delta-checkpoint structure epoch: the offset table and the
        # per-rank slice layout both derive from cells/owners, so the
        # next periodic save must be a full keyframe (the AMR commit's
        # AmrResult.changed_cells dirty seed feeds the plan rebuild;
        # for checkpointing the whole payload is conservatively dirty)
        self._ckpt_epoch = getattr(self, "_ckpt_epoch", 0) + 1
        self._mark_ckpt_dirty()
        new_cells = plan.cells
        if same_cells is None:
            same_cells = (len(new_cells) == len(old_plan.cells)
                          and np.array_equal(new_cells, old_plan.cells))
        if not same_cells:
            # cell-set epoch: caches keyed on the cell SET (not the
            # partition) — e.g. the cut partitioner's edge arrays —
            # invalidate here and nowhere else
            self._cells_epoch = getattr(self, "_cells_epoch", 0) + 1
        surviving = new_cells[np.isin(new_cells, old_plan.cells)]
        old_dev, old_rows = self._host_rows(surviving)
        old_flat = old_dev.astype(np.int64) * old_R + old_rows

        self._finish_plan(plan)
        faults.fire("grid.restructure", phase="planned")
        new_dev, new_rows = self._host_rows(surviving)
        new_flat = new_dev.astype(np.int64) * self.plan.R + new_rows

        src = np.full(self.n_dev * self.plan.R, -1, dtype=np.int64)
        src[new_flat] = old_flat
        sh = self._sharding()
        # On accelerators every host round-trip crosses the interconnect
        # — move data with an on-device gather. On the CPU backend the
        # "transfer" is a memcpy and the host scatter is cheaper than
        # compiling a per-epoch-shape gather program.
        if (self._on_accelerator() or self._multiproc
                or os.environ.get("DCCRG_DEVICE_RESTRUCTURE") == "1"):
            src2 = src.reshape(self.n_dev, self.plan.R)
            src_dev = put_sharded(src2, sh)
            mask_dev = put_sharded(src2 >= 0, sh)
            n_dev = self.n_dev

            def move_for(n_extra_dims):
                key = ("restructure_move", n_extra_dims)
                fn = self._program_cache.get(key)
                if fn is None:
                    @partial(jax.jit, out_shardings=sh)
                    def fn(old, srcs, mask):
                        flat = old.reshape((-1,) + old.shape[2:])
                        g = flat[jnp.clip(srcs, 0)]
                        return jnp.where(
                            mask.reshape(mask.shape + (1,) * n_extra_dims), g, 0
                        )
                    self._program_cache[key] = fn
                return fn

            for name, (shape, dtype) in self.fields.items():
                self.data[name] = move_for(len(shape))(
                    self.data[name], src_dev, mask_dev
                )
        else:
            keep = src >= 0
            srcc = np.clip(src, 0, None)
            for name, (shape, dtype) in self.fields.items():
                old_host = np.asarray(self.data[name]).reshape(
                    (self.n_dev * old_R,) + shape
                )
                arr = np.where(
                    keep.reshape((-1,) + (1,) * len(shape)), old_host[srcc], 0
                ).astype(dtype, copy=False)
                self.data[name] = jnp.asarray(
                    arr.reshape((self.n_dev, self.plan.R) + shape), device=sh
                )
        faults.fire("grid.restructure", phase="moved")

        # covered by the transaction's post-commit verify_all when one
        # is active (every mutation path); kept for direct callers
        if self._debug and not getattr(self, "_txn_depth", 0):
            from . import verify as _verify

            _verify.verify_user_data(self)

    # -- background recommit (DCCRG_BG_RECOMMIT; see background.py) ----

    def bg_pending(self) -> bool:
        """True while a background plan build is in flight or awaiting
        its step-boundary swap."""
        return getattr(self, "_bg_build", None) is not None

    def bg_install(self, wait: bool = False) -> bool:
        """The step-boundary swap point: install the background-built
        plan if one is finished (``wait=True`` blocks for it — the
        residual stall lands in ``dccrg_recommit_stall_seconds``) and
        relocate the surviving cells' data, exactly as the synchronous
        restructure would have. A worker crash falls back to the
        inline rebuild here. The install runs inside its own
        transaction, so a failure mid-swap (injected faults included)
        rolls back to the live pre-swap epoch and surfaces as
        MutationAbortedError. Returns True when a plan was installed."""
        bg = getattr(self, "_bg_build", None)
        if bg is None:
            return False
        if not bg.ready() and not wait:
            return False
        bg.wait()
        # consumed BEFORE the swap transaction: its entry barrier (and
        # any nested mutation) must not re-enter this install
        self._bg_build = None
        t0 = time.perf_counter()
        with telemetry.span("grid.recommit.swap"), \
                grid_transaction(self, op="bg_recommit_swap"):
            if bg.error is not None:
                logger.warning(
                    "background recommit worker failed (%s: %s); "
                    "rebuilding inline", type(bg.error).__name__, bg.error)
                plan = self._construct_plan(bg.cells, bg.owner,
                                            bg.changed_hint)
            else:
                plan = bg.plan
            self._install_plan(plan)
        telemetry.observe("dccrg_recommit_stall_seconds",
                          time.perf_counter() - t0, where="swap")
        return True

    def bg_discard(self) -> None:
        """Drop a pending background build without installing it (the
        transaction-rollback path: an aborted mutation must leave the
        live plan AND the snapshot plan exactly as they were). Blocks
        until the worker thread has actually stopped touching the
        arena; the orphaned build generation's buffers are reclaimed
        by the next build's ``arena.begin`` (it is never protected)."""
        bg = getattr(self, "_bg_build", None)
        if bg is None:
            return
        bg.done.wait()
        self._bg_build = None

    def _prewarm_plan(self, plan) -> None:
        """Pre-materialize the lazily-derived per-hood tables the first
        post-swap dispatch would otherwise compute on the step loop
        (the roll-plan affine decomposition — an O(L*S) numpy pass),
        with the same capacity function the compile path passes. Runs
        on the background worker; best-effort (a failure here simply
        re-surfaces at compile time)."""
        try:
            for hid, hood in plan.hoods.items():
                if hood.closed_form is not None:
                    hood.roll_plan(plan.L)
                elif hood.offs_const is not None and self._use_roll_gather():
                    hood.roll_plan(plan.L, cap=lambda n, hid=hid:
                                   self._sticky_cap(("rollW", hid), n))
        except Exception:  # noqa: BLE001 - prewarm must never kill a build
            logger.debug("plan prewarm failed", exc_info=True)

    def get_removed_cells(self) -> np.ndarray:
        """Cells removed by the last stop_refining (dccrg.hpp:3519)."""
        return self._removed_cells.copy()

    def get_old_data(self, field, ids):
        """Data of cells that disappeared in the last stop_refining
        (refined parents and removed children) — the reference keeps
        these reachable via grid[cell] until clear (dccrg.hpp:10355)."""
        ids = np.atleast_1d(np.asarray(ids, dtype=np.uint64))
        stored_ids, values = self._removed_data[field]
        order = np.argsort(stored_ids, kind="stable")
        sorted_ids = stored_ids[order]
        pos = np.searchsorted(sorted_ids, ids)
        if np.any(pos >= len(sorted_ids)) or np.any(sorted_ids[np.minimum(pos, len(sorted_ids) - 1)] != ids):
            raise KeyError("cell not among refined/removed cells")
        return values[order][pos]

    def clear_refined_unrefined_data(self) -> None:
        """Drop the preserved old data (dccrg.hpp:5550)."""
        self._removed_data = {}
        self._removed_cells = np.empty(0, np.uint64)
        self._new_cells = np.empty(0, np.uint64)

    # vectorized projection helpers (the idiomatic TPU versions of the
    # per-cell loops in tests/advection/adapter.hpp:229-301)

    def _owned_subset(self, ids):
        """The subset of ``ids`` on this process's devices — the
        projection helpers write rank-locally on multi-process meshes
        (the reference projects each process's own cells; under
        distributed AMR the commit's ``_new_cells``/parents span the
        whole fleet, and each peer projects its own share)."""
        if len(ids) == 0 or not self._multiproc:
            return ids
        dev, _rows = self._host_rows(ids)
        return ids[self._proc_local_dev[dev]]

    def assign_children_from_parents(self, fields=None) -> None:
        """Copy each new child's value from its refined parent
        (process-local on multi-process meshes)."""
        new = self._owned_subset(self._new_cells)
        if len(new) == 0:
            return
        parents = self.mapping.get_parent(new)
        for name in fields if fields is not None else self.fields:
            self.set(name, new, self.get_old_data(name, parents))

    def average_parents_from_children(self, fields=None) -> None:
        """Set each unrefined parent to the mean of its removed
        children (process-local on multi-process meshes)."""
        if len(self._removed_cells) == 0:
            return
        parents = self._owned_subset(self._unrefined_parents)
        if len(parents) == 0:
            return
        kids = self.mapping.get_all_children(parents)  # [n, 8]
        for name in fields if fields is not None else self.fields:
            vals = self.get_old_data(name, kids.reshape(-1))
            fshape = vals.shape[1:]
            vals = vals.reshape((len(parents), 8) + fshape).mean(axis=1)
            self.set(name, parents, vals)

    def load_cells(self, cells) -> None:
        """Replace the grid structure with an arbitrary valid cell set
        (the reference's load_cells, dccrg.hpp:3669-3738); data of all
        cells is reset."""
        from .neighbors import verify_tiling
        from .partition import partition_cells

        cells = np.sort(np.asarray(cells, dtype=np.uint64))
        verify_tiling(self.mapping, cells)
        with grid_transaction(self, op="load_cells"):
            owner = partition_cells(
                self.mapping, cells, self.n_dev, self._lb_method,
                pins=self._pins or None
            )
            self._cells_epoch = getattr(self, "_cells_epoch", 0) + 1
            self._ckpt_epoch = getattr(self, "_ckpt_epoch", 0) + 1
            self._build_plan(cells, owner)
            self._allocate_fields()
            if self._debug:
                from . import verify as _verify

                _verify.pin_requests_succeeded(self)

    # -- VTK output (dccrg.hpp:3320-3392) ------------------------------

    def write_vtk_file(self, filename: str, fields=None) -> None:
        from .utils.vtk import write_vtk_file

        write_vtk_file(self, filename, fields=fields)

    # -- checkpoint / restart (dccrg.hpp:1109-2426) --------------------

    def save_grid_data(self, filename: str, header: bytes = b"",
                       variable=None, *, sidecar: bool = False,
                       sidecar_chunk_bytes: int | None = None) -> None:
        """Write the pinned ``.dc`` bytes. On multi-process meshes the
        write is a TWO-PHASE COMMIT (slices into ``<file>.mp-tmp``,
        CRC exchange at a timeout-guarded barrier, verify + atomic
        rename by the committing rank); ``sidecar=True`` has that rank
        also write the resilience CRC32 sidecar with the per-rank
        slice table. Single-controller saves ignore the sidecar kwargs
        (use :meth:`save_checkpoint`)."""
        from .checkpoint import save_grid_data

        save_grid_data(self, filename, header, variable=variable,
                       sidecar=sidecar,
                       sidecar_chunk_bytes=sidecar_chunk_bytes)

    def load_grid_data(self, filename: str, header_size: int = 0,
                       variable=None) -> bytes:
        from .checkpoint import load_grid_data

        return load_grid_data(self, filename, header_size, variable=variable)

    @classmethod
    def from_file(cls, filename: str, cell_data, mesh: Mesh | None = None,
                  header_size: int = 0, variable=None):
        """Restart from nothing but a .dc file: reconstructs mapping,
        topology, geometry and the AMR cell set from the file metadata
        (the reference's load_grid_data, dccrg.hpp:1815-2105), then
        streams the payloads. Returns ``(grid, header)``."""
        from .checkpoint import load_grid

        return load_grid(filename, cell_data, mesh=mesh,
                         header_size=header_size, variable=variable)

    def save_checkpoint(self, filename: str, header: bytes = b"",
                        variable=None) -> str:
        """Atomic, checksummed checkpoint: the pinned ``.dc`` bytes
        (identical to :meth:`save_grid_data`) written via temp file +
        fsync + rename, with a per-chunk CRC32 sidecar ``<file>.crc``
        (see resilience.save_checkpoint)."""
        from . import resilience

        return resilience.save_checkpoint(self, filename, header=header,
                                          variable=variable)

    @classmethod
    def load_checkpoint(cls, filename: str, cell_data, mesh: Mesh | None = None,
                        header_size: int = 0, variable=None,
                        strict: bool = True):
        """Restart from a checkpoint with integrity verification:
        ``(grid, header, report)``; corrupt chunks raise (strict) or
        are salvaged (see resilience.load_checkpoint)."""
        from . import resilience

        return resilience.load_checkpoint(
            filename, cell_data, mesh=mesh, header_size=header_size,
            variable=variable, strict=strict)

    # -- misc parity ---------------------------------------------------

    def get_comm_size(self) -> int:
        """Device count (the reference's MPI communicator size)."""
        return self.n_dev

    def get_number_of_cells(self) -> int:
        return len(self.plan.cells)

    def get_existing_cell_from_indices(self, indices,
                                       minimum_refinement_level: int = 0,
                                       maximum_refinement_level: int | None = None):
        """Smallest existing cell containing the given smallest-cell
        indices within a refinement-level range (reference
        get_existing_cell(indices, min, max), dccrg.hpp:11414-11447)."""
        if maximum_refinement_level is None:
            maximum_refinement_level = self.mapping.max_refinement_level
        idx = np.asarray(indices, dtype=np.uint64)
        if np.any(idx >= self.mapping.get_index_length()):
            return ERROR_CELL
        for lvl in range(maximum_refinement_level,
                         minimum_refinement_level - 1, -1):
            c = self.mapping.get_cell_from_indices(idx, lvl)
            if c != ERROR_CELL and self._cell_pos(c) is not None:
                return np.uint64(c)
        return ERROR_CELL

    def get_existing_cell(self, coordinate):
        """Smallest existing cell containing a coordinate (reference
        get_existing_cell, dccrg.hpp:11414-11447)."""
        for lvl in range(self.mapping.max_refinement_level, -1, -1):
            c = self.geometry.get_cell(lvl, coordinate)
            if c != ERROR_CELL:
                pos = np.searchsorted(self.plan.cells, c)
                if pos < len(self.plan.cells) and self.plan.cells[pos] == c:
                    return np.uint64(c)
        return ERROR_CELL

    def get_maximum_refinement_level_difference(self) -> int:
        """Parity with dccrg.hpp:6752."""
        return 1

    def is_local(self, cell, device=None) -> bool:
        """Whether ``cell`` is owned by ``device``.

        The reference's ``is_local`` means "owned by *this* process"
        (its cell_process lookup against its own rank). Here host code
        is a single controller that sees every device, so there is no
        implicit "this device": with ``device=None`` the host-global
        view applies and every *existing* cell is local (False only for
        unknown ids). That is deliberate — the reference uses is_local
        to gate per-rank request APIs (refine_completely, pin, ...); on
        the single-controller model the host is allowed to request
        changes to any cell, so those guards only reject unknown ids.
        Pass an explicit ``device`` for the reference's owned-by-rank
        meaning."""
        pos = self._cell_pos(cell)
        if pos is None:
            return False
        if device is None:
            return True
        return int(self.plan.owner[pos]) == int(device)

    def get_process(self, cell) -> int:
        """Owning device of a cell (reference cell_process lookup)."""
        pos = self._cell_pos(cell)
        if pos is None:
            raise ValueError(f"unknown cell {cell}")
        return int(self.plan.owner[pos])
