"""Primitive types and constants.

Semantics follow the reference's L0 layer (dccrg_types.hpp:60,84):
indices are triples of unsigned 64-bit integers measured in units of the
*smallest possible* cell in the grid (i.e. a cell at the maximum
refinement level has extent 1 in indices); a neighborhood is a list of
integer offset triples, in units of a cell's *own* size.

All host-side structure code is vectorized numpy over uint64/int64;
device-side tables are int32 (a single device never addresses more than
2**31 local+ghost cells).
"""

from __future__ import annotations

import numpy as np

# Invalid cell id (reference: dccrg_mapping.hpp:38). Cell numbering is
# 1-based, so 0 is free to mean "no cell".
ERROR_CELL = np.uint64(0)

# Invalid index (reference: dccrg_mapping.hpp:41).
ERROR_INDEX = np.uint64(0xFFFFFFFFFFFFFFFF)


def as_cell_array(cells) -> np.ndarray:
    """Coerce a scalar/list of cell ids to a uint64 numpy array.

    Out-of-range values (negative, or >= 2**64) become ERROR_CELL rather
    than raising, preserving the error-value convention for callers that
    produce ids from signed arithmetic.
    """
    arr = np.asarray(cells)
    if arr.dtype == np.uint64:
        return np.atleast_1d(arr)
    if np.issubdtype(arr.dtype, np.unsignedinteger):
        return np.atleast_1d(arr.astype(np.uint64))
    if np.issubdtype(arr.dtype, np.signedinteger):
        a = np.atleast_1d(arr)
        return np.where(a < 0, 0, a).astype(np.uint64)
    if np.issubdtype(arr.dtype, np.floating):
        a = np.atleast_1d(arr)
        bad = ~np.isfinite(a) | (a < 0) | (a >= 2.0**64)
        return np.where(bad, 0.0, a).astype(np.uint64)
    # object dtype: python ints possibly outside int64/uint64 range
    a = np.atleast_1d(arr)
    out = np.zeros(a.shape, dtype=np.uint64)
    flat, oflat = a.reshape(-1), out.reshape(-1)
    for i, v in enumerate(flat):
        iv = int(v)
        if 0 <= iv < 2**64:
            oflat[i] = iv
    return out


def as_index_array(indices) -> np.ndarray:
    """Coerce indices to a (..., 3) uint64 array."""
    arr = np.asarray(indices, dtype=np.uint64)
    if arr.shape[-1] != 3:
        raise ValueError(f"indices must have trailing dim 3, got {arr.shape}")
    return arr
