"""Auxiliary subsystems: observability, VTK dumps, profiling."""

from .profiling import PhaseTimer
from .vtk import write_vtk_file

__all__ = ["PhaseTimer", "write_vtk_file"]
