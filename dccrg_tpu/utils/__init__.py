"""Auxiliary subsystems: observability, VTK dumps, profiling."""

from .profiling import PhaseTimer
from .vtk import dc_to_vtk, write_vtk_file

__all__ = ["PhaseTimer", "dc_to_vtk", "write_vtk_file"]
