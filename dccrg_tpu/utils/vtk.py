"""VTK output for visual inspection.

Equivalent of the reference's ``write_vtk_file`` (dccrg.hpp:3320-3392)
and the dc2vtk converters: an ASCII unstructured-grid dump of the leaf
cells, one hexahedron (VTK_VOXEL) per cell, with optional per-cell
scalar fields appended as CELL_DATA.
"""

from __future__ import annotations

import numpy as np


def write_vtk_file(grid, filename: str, fields=None, title: str = "dccrg_tpu") -> None:
    """Write all cells (the reference writes each rank's local cells to
    its own file; host code here sees the whole grid)."""
    cells = grid.get_cells()
    mins = grid.geometry.get_min(cells)
    maxs = grid.geometry.get_max(cells)
    n = len(cells)

    # 8 corners per cell in VTK_VOXEL order (x fastest, then y, then z)
    corners = np.empty((n, 8, 3))
    k = np.arange(8)
    cx = (k & 1).astype(bool)
    cy = ((k >> 1) & 1).astype(bool)
    cz = ((k >> 2) & 1).astype(bool)
    for d, flags in enumerate((cx, cy, cz)):
        corners[:, :, d] = np.where(flags[None, :], maxs[:, d : d + 1], mins[:, d : d + 1])

    with open(filename, "w") as f:
        f.write("# vtk DataFile Version 2.0\n")
        f.write(f"{title}\n")
        f.write("ASCII\nDATASET UNSTRUCTURED_GRID\n")
        f.write(f"POINTS {8 * n} float\n")
        np.savetxt(f, corners.reshape(-1, 3), fmt="%.9g")
        f.write(f"CELLS {n} {9 * n}\n")
        conn = np.column_stack(
            [np.full(n, 8, dtype=np.int64), np.arange(8 * n).reshape(n, 8)]
        )
        np.savetxt(f, conn, fmt="%d")
        f.write(f"CELL_TYPES {n}\n")
        np.savetxt(f, np.full(n, 11, dtype=np.int64), fmt="%d")  # VTK_VOXEL

        names = list(fields) if fields else []
        if names:
            f.write(f"CELL_DATA {n}\n")
            # cell ids first, like the reference's dc2vtk output
            f.write("SCALARS cell_id double 1\nLOOKUP_TABLE default\n")
            np.savetxt(f, cells.astype(np.float64), fmt="%.9g")
            for name in names:
                vals = np.asarray(grid.get(name, cells), dtype=np.float64).reshape(n, -1)
                if vals.shape[1] != 1:
                    continue  # only scalar fields in v1
                f.write(f"SCALARS {name} double 1\nLOOKUP_TABLE default\n")
                np.savetxt(f, vals[:, 0], fmt="%.9g")
