"""VTK output for visual inspection.

Equivalent of the reference's ``write_vtk_file`` (dccrg.hpp:3320-3392)
and the dc2vtk converters (examples/dc2vtk.cpp,
tests/advection/dc2vtk.cpp): an ASCII unstructured-grid dump of the
leaf cells, one hexahedron (VTK_VOXEL) per cell, with optional per-cell
scalar fields appended as CELL_DATA.
"""

from __future__ import annotations

import numpy as np


def _write_vtk(filename, cells, mins, maxs, scalar_fields, title,
               cell_data=None):
    """Core writer: cells as VTK_VOXELs + named per-cell scalars.
    ``cell_data`` forces the CELL_DATA/cell_id block even when every
    requested field was filtered out (vector fields)."""
    if cell_data is None:
        cell_data = bool(scalar_fields)
    n = len(cells)
    # 8 corners per cell in VTK_VOXEL order (x fastest, then y, then z)
    corners = np.empty((n, 8, 3))
    k = np.arange(8)
    cx = (k & 1).astype(bool)
    cy = ((k >> 1) & 1).astype(bool)
    cz = ((k >> 2) & 1).astype(bool)
    for d, flags in enumerate((cx, cy, cz)):
        corners[:, :, d] = np.where(flags[None, :], maxs[:, d : d + 1], mins[:, d : d + 1])

    with open(filename, "w") as f:
        f.write("# vtk DataFile Version 2.0\n")
        f.write(f"{title}\n")
        f.write("ASCII\nDATASET UNSTRUCTURED_GRID\n")
        f.write(f"POINTS {8 * n} float\n")
        np.savetxt(f, corners.reshape(-1, 3), fmt="%.9g")
        f.write(f"CELLS {n} {9 * n}\n")
        conn = np.column_stack(
            [np.full(n, 8, dtype=np.int64), np.arange(8 * n).reshape(n, 8)]
        )
        np.savetxt(f, conn, fmt="%d")
        f.write(f"CELL_TYPES {n}\n")
        np.savetxt(f, np.full(n, 11, dtype=np.int64), fmt="%d")  # VTK_VOXEL

        if cell_data:
            f.write(f"CELL_DATA {n}\n")
            # cell ids first, like the reference's dc2vtk output
            f.write("SCALARS cell_id double 1\nLOOKUP_TABLE default\n")
            np.savetxt(f, cells.astype(np.float64), fmt="%.9g")
            for name, vals in scalar_fields:
                vals = np.asarray(vals, dtype=np.float64).reshape(n, -1)
                if vals.shape[1] != 1:
                    continue  # only scalar fields in v1
                f.write(f"SCALARS {name} double 1\nLOOKUP_TABLE default\n")
                np.savetxt(f, vals[:, 0], fmt="%.9g")


def write_vtk_file(grid, filename: str, fields=None, title: str = "dccrg_tpu") -> None:
    """Write all cells (the reference writes each rank's local cells to
    its own file; host code here sees the whole grid)."""
    cells = grid.get_cells()
    mins = grid.geometry.get_min(cells)
    maxs = grid.geometry.get_max(cells)
    names = list(fields) if fields else []
    scalars = [(name, grid.get(name, cells)) for name in names]
    _write_vtk(filename, cells, mins, maxs, scalars, title,
               cell_data=bool(names))


def dc_to_vtk(dc_filename: str, vtk_filename: str, fields,
              header_size: int = 0, title: str = "dccrg_tpu") -> np.ndarray:
    """Standalone .dc -> .vtk converter: parses a checkpoint file
    written by ``save_grid_data`` without a live grid (the reference's
    dc2vtk programs, examples/dc2vtk.cpp and tests/advection/dc2vtk.cpp,
    each knowing their app's cell layout).

    ``fields`` is the saved grid's field spec ``{name: (shape, dtype)}``
    — the same role as the per-app cell struct in the reference's
    converters. Returns the cell ids written.
    """
    from ..checkpoint import _payload_spec_of, parse_metadata

    with open(dc_filename, "rb") as f:
        data = f.read()

    _, _, _, geometry, cells, offsets, _ = parse_metadata(data, header_size)
    offsets = offsets.astype(np.int64)
    spec, _, _ = _payload_spec_of(fields)

    # gather only the scalar columns (skip vector fields the converter
    # doesn't plot) — avoids materializing the full payload matrix
    raw = np.frombuffer(data, dtype=np.uint8)
    scalars = []
    col = 0
    for name, shape, dtype, nbytes in spec:
        n_lanes = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if n_lanes == 1:
            idx = offsets[:, None] + (col + np.arange(nbytes, dtype=np.int64))[None, :]
            vals = raw[idx].copy().view(dtype).reshape(len(cells))
            scalars.append((name, vals))
        col += nbytes

    mins = geometry.get_min(cells)
    maxs = geometry.get_max(cells)
    _write_vtk(vtk_filename, cells, mins, maxs, scalars, title,
               cell_data=bool(spec))
    return cells


def _parse_field_spec(spec_strs):
    """CLI field specs: ``name:dtype`` or ``name:dtype:d0xd1`` (e.g.
    ``density:float32`` or ``pos:float32:16x3``)."""
    fields = {}
    for s in spec_strs:
        parts = s.split(":")
        if len(parts) == 2:
            name, dt = parts
            fields[name] = ((), np.dtype(dt))
        elif len(parts) == 3:
            name, dt, shp = parts
            shape = tuple(int(v) for v in shp.split("x"))
            fields[name] = (shape, np.dtype(dt))
        else:
            raise SystemExit(f"bad field spec {s!r}: use name:dtype[:d0xd1]")
    return fields


def main(argv=None):
    """``python -m dccrg_tpu.utils.vtk`` — the reference's dc2vtk
    converters (examples/dc2vtk.cpp, tests/advection/dc2vtk.cpp) as one
    CLI taking the field schema on the command line."""
    import argparse

    ap = argparse.ArgumentParser(
        description="Convert a .dc checkpoint to an unstructured-grid "
        ".vtk file (scalar fields only)")
    ap.add_argument("dc_file")
    ap.add_argument("vtk_file")
    ap.add_argument("--field", action="append", required=True,
                    dest="fields", metavar="NAME:DTYPE[:SHAPE]",
                    help="cell field, repeatable, in the saved schema")
    ap.add_argument("--header-size", type=int, default=0)
    ap.add_argument("--title", default="dccrg_tpu grid")
    args = ap.parse_args(argv)
    cells = dc_to_vtk(args.dc_file, args.vtk_file,
                      _parse_field_spec(args.fields),
                      header_size=args.header_size, title=args.title)
    print(f"wrote {args.vtk_file}: {len(cells)} cells")


if __name__ == "__main__":
    main()
