"""Per-phase timing and transfer accounting.

The reference has no profiling subsystem; apps time phases with
MPI_Wtime and reduce min/avg/max over ranks
(tests/advection/2d.cpp:330-340, 453-503) and compute halo bandwidth
from the grid's transfer counters (2d.cpp:345-350). This module gives
the same measurements a home: ``PhaseTimer`` accumulates named phase
durations (synchronizing the device so numbers mean something), and
``halo_bytes_per_update`` mirrors the B/s accounting.

For deep kernel analysis use ``jax.profiler`` traces; this is the
lightweight always-on layer.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager

import numpy as np


class PhaseTimer:
    def __init__(self, sync=None):
        """``sync``: optional callable blocking until device work
        finishes (e.g. ``lambda: jax.block_until_ready(arr)``)."""
        self._sync = sync
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextmanager
    def phase(self, name: str):
        if self._sync:
            self._sync()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if self._sync:
                self._sync()
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()

    def report(self) -> dict:
        """{phase: {total, count, mean}} — the avg the reference prints
        per rank; min/max over ranks is meaningless on one host."""
        return {
            k: {"total": self.totals[k], "count": self.counts[k],
                "mean": self.totals[k] / max(self.counts[k], 1)}
            for k in self.totals
        }

    def __repr__(self):
        rows = [
            f"{k}: {v['total']:.4f}s / {v['count']} = {v['mean'] * 1e3:.2f}ms"
            for k, v in sorted(self.report().items())
        ]
        return "PhaseTimer(" + "; ".join(rows) + ")"


def halo_bytes_per_update(grid, neighborhood_id=None, fields=None) -> int:
    """Bytes moved by one update_copies_of_remote_neighbors call (the
    reference's get_number_of_update_send_cells x payload size,
    tests/advection/2d.cpp:345-350)."""
    from ..grid import DEFAULT_NEIGHBORHOOD_ID

    hood_id = neighborhood_id if neighborhood_id is not None else DEFAULT_NEIGHBORHOOD_ID
    names = fields if fields is not None else list(grid.fields)
    total = 0
    for name in names:
        shape, dtype = grid.fields[name]
        per_cell = int(np.prod(shape, dtype=np.int64) if shape else 1) * dtype.itemsize
        # per-field count: a transfer predicate may thin this field's list
        total += grid.get_number_of_update_send_cells(hood_id, field=name) * per_cell
    return total
