"""Silent-data-corruption (SDC) defense: in-program integrity
invariants and the fingerprint primitives the audit layers share.

Every robustness layer so far defends against *detectable* faults:
NaN/Inf trips the numerics watchdog, OOM walks the gather fallback
chain, rank death times out a barrier, a torn write fails its CRC.
None of them can see a fault that lands **finite, plausible, wrong
bits** in device state — ``comm.all_finite`` passes, the checkpoint
CRC faithfully seals the corrupted bytes, and the fleet serves a
silently wrong answer. At fleet scale this is the dominant unhandled
failure mode ("Cores that don't count", Hochschild et al., HotOS'21;
"Silent Data Corruptions at Scale", Dixit et al., arXiv:2102.11245).

Three defense layers, cheapest first (all off with
``DCCRG_INTEGRITY=0`` — the fleet step program is then bitwise
unchanged, pinned by the negative tests):

1. **In-program invariants** (this module + the fleet quantum program,
   :meth:`dccrg_tpu.fleet.GridBatch._programs`): the device computes
   its own per-slot *fingerprint* — an order-independent
   Fletcher-style pair of uint32 sums over the owned rows — of both
   the input and the output state **in the same HBM pass as the
   step**, plus per-field conservation sums for kernels registered
   conservative. The host compares exactly (integer fingerprints are
   order-independent and therefore bit-reproducible across programs)
   or against the expected drift (float conservation sums, tolerance
   :func:`sum_tolerance`). Catches corruption of resident state
   between dispatches and gross in-compute corruption, every quantum,
   at near-zero cost.
2. **Shadow-execution audits** (:mod:`dccrg_tpu.scheduler`): at a
   sampled cadence (``DCCRG_AUDIT_EVERY``) the last quantum is
   re-executed from the pre-quantum state in a spare fleet slot (or
   the solo path) and the results are compared bitwise — catches
   *any* divergence, including in-compute corruption of
   non-conservative kernels, and attributes it to a slot/device.
   ``FleetJob(redundancy=2)`` is the always-on variant (DMR): two
   slots step the same job and their digests are compared at every
   quantum boundary.
3. **Containment**: a corrupt verdict is a *recoverable trip*
   (``resilience._TRIP_CORRUPT``, between the numerics and OOM
   classes) — the victim rolls back from its own checkpoint chain and
   replays, bounded retries, exactly mirroring the NaN path; repeat
   offenders quarantine their device
   (``DCCRG_QUARANTINE_AFTER``, :class:`~dccrg_tpu.scheduler
   .FleetScheduler`) with bit-exact survivor migration.

The fingerprint is also recorded in every checkpoint's CRC sidecar
(single-controller saves) so ``python -m dccrg_tpu.resilience audit
<ckpt>`` can re-derive it from the file's payload bytes offline: a
checkpoint whose CRCs verify but whose payload no longer matches the
fingerprint taken from live device state at save time is at-rest SDC
under an intact-looking CRC epoch.

Why Fletcher-*style*: a real Fletcher checksum is positional; these
pairs are ``(sum(x), sum((lo16(x)+1)*(hi16(x)+1)))`` over uint32
words in wrapping uint32 arithmetic — commutative and associative
EXACTLY, so device reductions (any order XLA picks), host numpy
reductions and file-payload reductions all agree bit-for-bit on
equal bytes, while compensating multi-word changes that preserve the
linear sum still shift the nonlinear one.
"""

from __future__ import annotations

import os

import numpy as np

from .resilience import ResilienceExhaustedError

logger = __import__("logging").getLogger("dccrg_tpu.integrity")


class IntegrityError(ResilienceExhaustedError):
    """CORRUPT trips exhausted their bounded retries: device state
    repeatedly failed its own fingerprint/conservation invariants
    while every cheaper detector (finiteness, CRCs) passed — the
    persistent silent-data-corruption signature, most likely a
    defective device rather than a transient upset. Raised by
    :class:`~dccrg_tpu.resilience.ResilientRunner` in place of the
    plain :class:`~dccrg_tpu.resilience.ResilienceExhaustedError`
    (which it subclasses, so generic handlers keep working).
    ``details`` maps invariant name -> a short description of the
    mismatch."""

    def __init__(self, msg, details=None):
        super().__init__(msg)
        self.details = dict(details or {})


# ---------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------

def integrity_enabled(default: bool = True) -> bool:
    """The ``DCCRG_INTEGRITY`` env knob: in-program integrity
    invariants on (default) or off. Off means *no program change at
    all* — the fleet quantum program compiles to exactly the
    pre-integrity bytes (the negative pin), not a cheaper check."""
    v = os.environ.get("DCCRG_INTEGRITY", "")
    if v == "":
        return default
    return v not in ("0", "off", "false", "no")


def audit_every_default(default: int = 0) -> int:
    """The ``DCCRG_AUDIT_EVERY`` env knob: run a shadow-execution
    audit every N scheduler ticks (0 = audits off). Each audit
    re-executes ONE slot's last quantum from its pre-quantum state and
    compares bitwise."""
    try:
        return max(0, int(os.environ.get("DCCRG_AUDIT_EVERY", "")
                          or default))
    except ValueError:
        return default


def quarantine_after_default(default: int = 3) -> int:
    """The ``DCCRG_QUARANTINE_AFTER`` env knob: corrupt verdicts
    attributed to one device lane before the scheduler quarantines it
    and migrates the survivors (0 = never quarantine)."""
    try:
        return max(0, int(os.environ.get("DCCRG_QUARANTINE_AFTER", "")
                          or default))
    except ValueError:
        return default


def integrity_rtol(default: float = 1e-4) -> float:
    """The ``DCCRG_INTEGRITY_RTOL`` env knob: relative tolerance for
    conservation-sum drift (float reductions are inexact; the
    fingerprints are the exact layer)."""
    try:
        return float(os.environ.get("DCCRG_INTEGRITY_RTOL", "")
                     or default)
    except ValueError:
        return default


def note_suspect(lane: int, count: int,
                 quarantined: bool = False) -> None:
    """Export one device lane's suspect accounting as live gauges
    (``dccrg_lane_suspects{lane}`` / ``dccrg_lane_quarantined{lane}``)
    — a first-class controller input for the autopilot's audit-cadence
    rule and the operator's dashboard, useful with the autopilot off
    too."""
    from . import telemetry

    telemetry.set_gauge("dccrg_lane_suspects", int(count),
                        lane=str(int(lane)))
    telemetry.set_gauge("dccrg_lane_quarantined",
                        1 if quarantined else 0, lane=str(int(lane)))


def sum_tolerance(base, n_elements: int, steps: int = 1) -> float:
    """Allowed |drift| of a conservation sum over ``steps`` steps of a
    conservative kernel: rounding accumulates ~eps per element-update,
    so the bound scales with the magnitude of the sum, sqrt of the
    element count, and the step count — while a single corrupted cell
    moves the sum by O(cell value) = O(|sum| / n), far above it for
    any practically sized grid."""
    scale = abs(float(base)) + float(n_elements)
    return integrity_rtol() * scale * max(1.0, float(steps)) ** 0.5


# ---------------------------------------------------------------------
# conservation registry: which kernels conserve which fields
# ---------------------------------------------------------------------

# kernel registry name -> (fields, axes that must be periodic for the
# conservation to hold; None = any periodicity)
_CONSERVED: dict = {}


def register_conserved(kernel_name: str, fields, periodic_axes=None):
    """Declare that the registered fleet kernel ``kernel_name``
    conserves the total of ``fields`` (exactly, in real arithmetic),
    provided every axis in ``periodic_axes`` is periodic. The fleet
    layer then checks per-quantum conservation drift for those fields
    when integrity is enabled."""
    _CONSERVED[str(kernel_name)] = (tuple(fields),
                                    None if periodic_axes is None
                                    else tuple(periodic_axes))


# the built-in kernels: diffusion redistributes over a symmetric
# neighbor relation (conserves under any periodicity); upwind
# advection conserves only when the transport axis wraps
register_conserved("diffuse", ("rho",))
register_conserved("advect_x", ("rho",), periodic_axes=(0,))


def conserved_fields(kernel, periodic, fields_out) -> tuple:
    """The fields a job's kernel provably conserves under its
    periodicity — the per-quantum conservation-check set. Callable
    kernels (no registry entry) conserve nothing we can assume."""
    if callable(kernel):
        return ()
    entry = _CONSERVED.get(str(kernel))
    if entry is None:
        return ()
    fields, axes = entry
    if axes is not None and not all(bool(periodic[a]) for a in axes):
        return ()
    return tuple(n for n in fields if n in tuple(fields_out))


# ---------------------------------------------------------------------
# fingerprints: order-independent exact uint32 pairs
# ---------------------------------------------------------------------

def _row_words(arr) -> np.ndarray:
    """``[n, k]`` uint32 word view of per-cell rows: each cell's field
    bytes, zero-padded per row to a multiple of 4. Padding per ROW
    (not per column) keeps the words cell-aligned, so the same cells
    in any order produce the same word multiset — the property the
    order-independent sums need."""
    a = np.ascontiguousarray(arr)
    n = a.shape[0] if a.ndim else 1
    b = a.reshape(n, -1).view(np.uint8)
    pad = (-b.shape[1]) % 4
    if pad:
        b = np.concatenate(
            [b, np.zeros((n, pad), dtype=np.uint8)], axis=1)
    return b.view(np.uint32)


def fingerprint_rows(arr) -> tuple:
    """The ``(s1, s2)`` fingerprint of per-cell rows ``arr`` (leading
    axis = cells): wrapping-uint32 ``sum(x)`` plus a nonlinear second
    sum ``sum((lo16(x)+1) * (hi16(x)+1))`` over the word view. Exact,
    order-independent, and reproduced identically by the device-side
    program (:func:`device_fingerprint`) and the file-payload
    recompute (:func:`file_fingerprint`). The second sum is a
    half-word product rather than ``x*x`` because float bit patterns
    routinely carry 16+ trailing zeros, making plain squares collapse
    to 0 mod 2^32."""
    w = _row_words(arr)
    s1 = int(np.sum(w, dtype=np.uint32))
    lo = (w & np.uint32(0xFFFF)) + np.uint32(1)
    hi = (w >> np.uint32(16)) + np.uint32(1)
    s2 = int(np.sum(lo * hi, dtype=np.uint32))
    return s1, s2


def device_fingerprint(x, n_own: int):
    """jnp body computing the ``(s1, s2)`` pair of one field's owned
    rows ``x[:n_own]`` inside a jitted program — the fused in-program
    invariant. 32-bit element types bitcast losslessly on every
    backend; 16-bit types (bfloat16 state) bitcast to uint16 and widen
    each element to its OWN uint32 word — which equals the host
    packer's padded-row words only for one-element rows, so the fleet
    restricts 16-bit device fingerprints to scalar-shaped fields (the
    host helpers handle any dtype)."""
    import jax
    import jax.numpy as jnp

    v = x[:n_own]
    if v.dtype.itemsize == 2:
        v = jax.lax.bitcast_convert_type(v, jnp.uint16).astype(jnp.uint32)
    elif v.dtype.itemsize != 4:
        raise TypeError(
            f"device fingerprints need a 16- or 32-bit element type, "
            f"got {v.dtype}")
    w = jax.lax.bitcast_convert_type(v, jnp.uint32)
    s1 = jnp.sum(w, dtype=jnp.uint32)
    lo = (w & jnp.uint32(0xFFFF)) + jnp.uint32(1)
    hi = (w >> jnp.uint32(16)) + jnp.uint32(1)
    s2 = jnp.sum(lo * hi, dtype=jnp.uint32)
    return jnp.stack([s1, s2])


def grid_fingerprint(grid, fields=None) -> dict:
    """``{field: (s1, s2)}`` over the grid's OWNED cell bytes — the
    same rows :func:`dccrg_tpu.checkpoint.state_digest` hashes, so two
    grids with equal owned bytes fingerprint equal. Host-side and
    dtype-agnostic; process-local on multi-process meshes (uint32 sums
    combine across ranks by wrapping addition, but the sidecar record
    is only written by single-controller saves)."""
    out = {}
    names = sorted(fields if fields is not None else grid.fields)
    for name in names:
        s1 = s2 = 0
        arr = grid.data[name]
        if isinstance(arr, np.ndarray):
            # a frozen host snapshot (background.freeze_grid): the
            # async-save writer must never touch jax, and the pulled
            # [n_dev, R, ...] array carries the same owned rows the
            # shard walk below reads — bitwise the same fingerprint
            for d in range(grid.n_dev):
                a, b = fingerprint_rows(arr[d, : int(grid.plan.n_local[d])])
                s1 = (s1 + a) & 0xFFFFFFFF
                s2 = (s2 + b) & 0xFFFFFFFF
            out[name] = (s1, s2)
            continue
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        for s in shards:
            d = s.index[0].start or 0
            n_own = int(grid.plan.n_local[d])
            a, b = fingerprint_rows(np.asarray(s.data)[0, :n_own])
            s1 = (s1 + a) & 0xFFFFFFFF
            s2 = (s2 + b) & 0xFFFFFFFF
        out[name] = (s1, s2)
    return out


def file_fingerprint(path: str, cell_data, header_size: int = 0,
                     variable=None) -> dict:
    """Recompute the ``{field: (s1, s2)}`` fingerprint from a
    checkpoint file's payload bytes — the offline half of the at-rest
    SDC audit (``python -m dccrg_tpu.resilience audit``). Only fixed
    (non-ragged) fields fingerprint; ragged fields are skipped (their
    per-cell extents make the column walk ambiguous under
    corruption)."""
    from . import checkpoint as checkpoint_mod

    raw = np.memmap(path, dtype=np.uint8, mode="r")
    try:
        meta = checkpoint_mod.parse_metadata(raw, header_size)
        fields = _normalize_fields(cell_data)
        cols = checkpoint_mod.payload_columns(
            raw, meta, fields, variable=variable)
        return {name: fingerprint_rows(col)
                for name, col in cols.items()}
    finally:
        del raw


def _normalize_fields(cell_data) -> dict:
    out = {}
    for name, spec in cell_data.items():
        if isinstance(spec, tuple):
            shape, dtype = spec
        else:
            shape, dtype = (), spec
        out[name] = (tuple(shape), np.dtype(dtype))
    return out


# ---------------------------------------------------------------------
# conservation sums: device-side collective (the solo-grid check)
# ---------------------------------------------------------------------

def conservation_sums(grid, fields) -> np.ndarray:
    """Global per-field sums over the grid's owned cells, computed
    device-side and psum-reduced across the mesh in ONE cached
    program (:func:`dccrg_tpu.comm.field_sums`, the same discipline as
    ``resilience.check_finite``): every rank pulls the identical
    replicated value, so the drift verdict agrees across ranks by
    construction. Returns ``[len(fields)]`` float64 (host)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from . import comm
    from .compat import shard_map

    names = tuple(fields)
    if not names:
        return np.zeros(0, dtype=np.float64)
    key = ("integrity_sums", names,
           tuple(tuple(grid.fields[n][0]) for n in names))
    fn = grid._program_cache.get(key)
    if fn is None:
        axis, mesh = grid.axis, grid.mesh
        n_own = np.asarray(grid.plan.n_local, dtype=np.int32)

        def body(dev_row, *arrs):
            d = dev_row[0, 0]
            # mask ghost/pad rows: only rows < n_local[d] are owned
            rows = np.arange(int(grid.plan.R))
            import jax.numpy as jnp

            own = jnp.asarray(rows)[None] < jnp.asarray(n_own)[d]
            masked = []
            for a in arrs:
                v = a[0]
                m = own.reshape((v.shape[0],) + (1,) * (v.ndim - 1))
                masked.append(jnp.where(m, v, 0))
            return comm.field_sums(masked, axis)[None]

        dev_ids = np.arange(grid.n_dev, dtype=np.int32)[:, None]
        mapped = shard_map(
            body, mesh=mesh, in_specs=(P(axis),) * (1 + len(names)),
            out_specs=P(axis), check_vma=False)
        fn = jax.jit(mapped)
        grid._program_cache[key] = fn
        grid._program_cache[key + ("dev_ids",)] = dev_ids
    dev_ids = grid._program_cache[key + ("dev_ids",)]
    out = fn(dev_ids, *(grid.data[n] for n in names))
    return np.asarray(comm.pull_replicated(out),
                      dtype=np.float64).reshape(-1)[:len(names)]
