"""Lagrangian particle tracking on the distributed grid.

Equivalent of the reference's tests/particles apps: each cell owns a
variable-size list of 3-D particle coordinates
(tests/particles/cell.hpp:37-84) that moves between cells as particles
advect, with a two-phase MPI transfer (counts first, then resize, then
coordinates, cell.hpp:50-84).

TPU-native ragged-payload design: per-cell particle storage is a
fixed-capacity padded buffer — fields ``pos [capacity, 3]`` and
``count`` — so a halo update moves both in one phase (static shapes
replace the resize handshake; the reference's README itself frames the
two-phase dance as an artifact of dynamic buffers). Capacity overflow
is detected on device and handled as a host replanning event
(``ensure_capacity``), the same epoch mechanism as AMR/load balance.

Migration is gather-based like every other stencil here: each cell
collects, from itself and all neighbors (both neighbor directions, so
any particle that leaves a cell is picked up by whoever contains it
now), the particles whose positions fall inside its bounds — the
vectorized form of the per-cell loops in tests/particles/simple.cpp:62-97.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..grid import Grid


class ParticleModel:
    """``velocity_fn(pos [., 3]) -> [., 3]`` is fixed at construction so
    the migration kernels compile once per structure epoch."""

    def __init__(self, velocity_fn, length=(4, 4, 4), capacity=16, mesh=None,
                 periodic=(False, False, False)):
        self.velocity_fn = velocity_fn
        self.capacity = int(capacity)
        self.grid = (
            Grid(
                cell_data={
                    "pos": ((capacity, 3), jnp.float32),
                    "count": jnp.int32,
                    "overflow": jnp.int32,
                    # cell bounds stored per cell (the reference's apps
                    # cache geometry in the cell, tests/advection/cell.hpp)
                    "cell_min": ((3,), jnp.float32),
                    "cell_max": ((3,), jnp.float32),
                }
            )
            .set_initial_length(length)
            .set_periodic(*periodic)
            .set_neighborhood_length(1)
            .initialize(mesh)
        )
        self._refresh_bounds()

    def _refresh_bounds(self) -> None:
        cells = self.grid.get_cells()
        self.grid.set("cell_min", cells, self.grid.geometry.get_min(cells).astype(np.float32))
        self.grid.set("cell_max", cells, self.grid.geometry.get_max(cells).astype(np.float32))

    # -- population ----------------------------------------------------

    def add_particles(self, coordinates) -> int:
        """Host-side seeding: assign each coordinate to its cell.
        Returns the number of particles placed (drops those outside
        the grid or beyond a cell's capacity)."""
        coords = np.atleast_2d(np.asarray(coordinates, dtype=np.float32))
        cells = self.grid.get_cells()
        placed = 0
        by_cell = {}
        for c in coords:
            cid = self.grid.get_existing_cell(c)
            if cid == 0:
                continue
            by_cell.setdefault(int(cid), []).append(c)
        ids = np.array(sorted(by_cell), dtype=np.uint64)
        if len(ids) == 0:
            return 0
        pos = np.array(self.grid.get("pos", ids))
        cnt = np.array(self.grid.get("count", ids))
        for i, cid in enumerate(ids):
            for c in by_cell[int(cid)]:
                if cnt[i] < self.capacity:
                    pos[i, cnt[i]] = c
                    cnt[i] += 1
                    placed += 1
        self.grid.set("pos", ids, pos)
        self.grid.set("count", ids, cnt)
        return placed

    def particles(self) -> np.ndarray:
        """All particle coordinates, gathered to host."""
        cells = self.grid.get_cells()
        pos = np.array(self.grid.get("pos", cells))
        cnt = np.array(self.grid.get("count", cells))
        out = [pos[i, : cnt[i]] for i in range(len(cells)) if cnt[i]]
        return np.concatenate(out) if out else np.empty((0, 3), np.float32)

    def counts(self) -> np.ndarray:
        return np.array(self.grid.get("count", self.grid.get_cells()))

    # -- the step -------------------------------------------------------

    def _move_kernel(self, cell, nbr, offs, mask, dt):
        pos = cell["pos"]
        cap = pos.shape[1]
        k = jnp.arange(cap)[None, :]
        alive = k < cell["count"][:, None]
        vel = self.velocity_fn(pos.reshape(-1, 3)).reshape(pos.shape)
        newpos = pos + dt * vel
        # wrap positions through periodic boundaries so the collection
        # phase finds them in the wrapped cell
        start = jnp.asarray(self.grid.geometry.get_start(), jnp.float32)
        end = jnp.asarray(self.grid.geometry.get_end(), jnp.float32)
        extent = end - start
        wrapped = start + jnp.mod(newpos - start, extent)
        periodic = jnp.asarray(self.grid.topology.periodic, bool)
        newpos = jnp.where(periodic[None, None, :], wrapped, newpos)
        return {"pos": jnp.where(alive[..., None], newpos, pos)}

    def step(self, dt: float) -> None:
        """Advance positions, then migrate particles to their new cells
        via neighbor gathers."""
        cap = self.capacity
        g = self.grid

        # phase 1: move (pure elementwise on device)
        g.apply_stencil(
            self._move_kernel, ["pos", "count"], ["pos"],
            extra_args=(jnp.float32(dt),),
        )

        # phase 2: exchange buffers, then each cell collects what's inside
        # it. The radius-1 neighbors_of list contains every touching
        # cell (adjacency is symmetric for radius-1 windows), and each
        # exactly once on uniform grids — including neighbors_to as
        # well would double-collect under a symmetric neighborhood.
        #
        # Capacity overflow is the resize() moment of the reference's
        # two-phase transfer: snapshot the buffers first, and if any
        # cell overflows, roll back, grow capacity to what the counts
        # demanded, and redo the collect — no particle is ever dropped.
        snap_pos, snap_cnt = g.data["pos"], g.data["count"]
        g.update_copies_of_remote_neighbors(fields=["pos", "count"])
        g.apply_stencil(
            self._collect_kernel,
            ["pos", "count", "cell_min", "cell_max"],
            ["pos", "count", "overflow"],
        )
        max_over = int(jnp.max(g.data["overflow"]))
        if max_over > 0:
            g.data["pos"], g.data["count"] = snap_pos, snap_cnt
            self.ensure_capacity(self.capacity + max_over)
            g.update_copies_of_remote_neighbors(fields=["pos", "count"])
            g.apply_stencil(
                self._collect_kernel,
                ["pos", "count", "cell_min", "cell_max"],
                ["pos", "count", "overflow"],
            )

    def _collect_kernel(self, cell, nbr, offs, mask):
        """Each cell keeps its still-inside particles and adopts those
        of any touching neighbor that now fall in its bounds.
        Particles that cross more than one cell per step are lost —
        the same constraint as the reference's neighbor-list transfer
        (tests/particles/simple.cpp). Uniform grids only for now: under
        AMR a coarse neighbor satisfies several offset items and would
        need dedup before collection."""
        cap = self.capacity
        own_pos = cell["pos"]  # [L, cap, 3]
        own_cnt = cell["count"]

        def flat(p, c, m):
            # [L, X, cap, 3] + counts [L, X] -> flat candidates + validity
            L, X = c.shape
            k = jnp.arange(cap)[None, None, :]
            valid = (k < c[:, :, None]) & m[:, :, None]
            return p.reshape(L, X * cap, 3), valid.reshape(L, X * cap)

        nbr_p, nbr_v = flat(nbr["pos"], nbr["count"], mask)
        own_valid = jnp.arange(cap)[None, :] < own_cnt[:, None]
        cand = jnp.concatenate([own_pos, nbr_p], axis=1)  # [L, M, 3]
        valid = jnp.concatenate([own_valid, nbr_v], axis=1)

        lo = cell["cell_min"][:, None, :]
        hi = cell["cell_max"][:, None, :]
        inside = jnp.all((cand >= lo) & (cand < hi), axis=-1) & valid
        # compact: stable order, keepers first
        order = jnp.argsort(~inside, axis=1, stable=True)
        take = order[:, :cap]
        picked = jnp.take_along_axis(cand, take[..., None], axis=1)
        picked_ok = jnp.take_along_axis(inside, take, axis=1)
        count = jnp.sum(inside, axis=1).astype(jnp.int32)
        overflow = jnp.maximum(count - cap, 0)
        count = jnp.minimum(count, cap)
        newpos = jnp.where(picked_ok[..., None], picked, 0.0)
        return {"pos": newpos, "count": count, "overflow": overflow}

    def ensure_capacity(self, new_capacity: int) -> None:
        """Grow the per-cell particle buffers (the resize() phase of the
        reference's two-phase transfer, as a structure epoch)."""
        if new_capacity <= self.capacity:
            return
        g = self.grid
        cells = g.get_cells()
        old_pos = np.array(g.get("pos", cells))
        cnt = np.array(g.get("count", cells))
        self.capacity = int(new_capacity)
        g.fields["pos"] = ((self.capacity, 3), jnp.dtype(jnp.float32))
        g.data["pos"] = jnp.zeros(
            (g.n_dev, g.plan.R, self.capacity, 3), dtype=jnp.float32, device=g._sharding()
        )
        pad = np.zeros((len(cells), self.capacity, 3), np.float32)
        pad[:, : old_pos.shape[1]] = old_pos
        g.set("pos", cells, pad)
        g.set("count", cells, cnt)
        # compiled programs are shape-keyed: the new capacity simply
        # retraces; no cache invalidation needed