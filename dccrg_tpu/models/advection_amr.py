"""Adaptive advection: the reference advection test's full loop —
upwind finite-volume fluxes over AMR face neighbors, the relative-
density-difference adaptation criterion, and periodic load balancing
(tests/advection/2d.cpp:321-442, solve.hpp:44-333, adapter.hpp:47-311)
— on the general distributed grid.

TPU-first formulation: the reference's per-cell scatter loop (visit
each face once, update both sides, solve.hpp:166-234) becomes a
*gather* kernel — every cell accumulates its own flux from all of its
face neighbors, so each face is evaluated twice (once per side) with
identical face velocity / area / upwind density, which keeps the scheme
conservative while staying embarrassingly parallel for the MXU/VPU.
Face detection is the reference's offset arithmetic
(solve.hpp:76-120): a neighbor at logical offset ``o`` with index
length ``nl`` is a face neighbor in dimension d when ``o_d`` equals the
cell's index length (+d side) or ``-nl`` (-d side) and the windows
overlap in both other dimensions.

Static per-cell quantities (edge lengths, velocities at the center,
index length) are fields refreshed once per structure epoch and halo-
exchanged once, so the per-step exchange only moves density (the
reference's transfer-count trick, tests/advection/cell.hpp:31-55).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..grid import Grid
from ..neighbors import face_masks

STATIC_FIELDS = ("vx", "vy", "vz", "lx", "ly", "lz", "ilen")


def velocity(centers: np.ndarray) -> np.ndarray:
    """Solid-body rotation about (0.5, 0.5) (solve.hpp:339-346)."""
    v = np.zeros_like(centers)
    v[:, 0] = 0.5 - centers[:, 1]
    v[:, 1] = centers[:, 0] - 0.5
    return v


def hump(centers: np.ndarray, x0=0.25, y0=0.5, radius=0.15) -> np.ndarray:
    """Cosine hump initial density (tests/advection/initialize.hpp:54-66)."""
    r = np.minimum(
        np.sqrt((centers[:, 0] - x0) ** 2 + (centers[:, 1] - y0) ** 2), radius
    ) / radius
    return (1.0 + np.cos(np.pi * r)) / 4


def make_flux_kernel():
    """The upwind flux gather kernel (solve.hpp:44-266)."""

    def kernel(cell, nbr, offs, mask, dt):
        rho_c = cell["density"][:, None]
        rho_n = nbr["density"]
        ilen_c = cell["ilen"]
        ilen_n = nbr["ilen"]
        lens_c = [cell["lx"][:, None], cell["ly"][:, None], cell["lz"][:, None]]
        lens_n = [nbr["lx"], nbr["ly"], nbr["lz"]]
        vels_c = [cell["vx"][:, None], cell["vy"][:, None], cell["vz"][:, None]]
        vels_n = [nbr["vx"], nbr["vy"], nbr["vz"]]
        vol_c = (cell["lx"] * cell["ly"] * cell["lz"])[:, None]

        faces = face_masks(ilen_c[:, None], ilen_n, offs, mask)
        flux = jnp.zeros_like(rho_n)
        for d, (face_pos, face_neg) in enumerate(faces):
            # velocity interpolated to the shared face (solve.hpp:168-175)
            v = (lens_c[d] * vels_n[d] + lens_n[d] * vels_c[d]) / (
                lens_c[d] + lens_n[d] + 1e-30
            )
            o1, o2 = [e for e in range(3) if e != d]
            area = jnp.minimum(lens_c[o1] * lens_c[o2], lens_n[o1] * lens_n[o2])
            # +d face: positive v carries cell density out (solve.hpp:180-234)
            up_pos = jnp.where(v >= 0, rho_c, rho_n)
            up_neg = jnp.where(v >= 0, rho_n, rho_c)
            m = dt * v * area / vol_c
            flux = flux - jnp.where(face_pos, up_pos * m, 0.0)
            flux = flux + jnp.where(face_neg, up_neg * m, 0.0)
        return {"flux": jnp.sum(flux, axis=1)}

    return kernel


def make_fused_step_kernel():
    """Flux + apply in one kernel for the fused multi-step loop:
    returns the post-step density directly (solve.hpp:272-279 folded
    into the flux gather), so exchange+flux+apply is one XLA program
    per step under Grid.run_steps."""
    base = make_flux_kernel()

    def kernel(cell, nbr, offs, mask, dt):
        r = base(cell, nbr, offs, mask, dt)
        return {"density": cell["density"] + r["flux"]}

    return kernel


def make_diff_kernel(diff_threshold: float):
    """Max relative density difference over face neighbors
    (adapter.hpp:110-131)."""

    def kernel(cell, nbr, offs, mask):
        rho_c = cell["density"][:, None]
        rho_n = nbr["density"]
        faces = face_masks(cell["ilen"][:, None], nbr["ilen"], offs, mask)
        is_face = jnp.zeros(mask.shape, dtype=bool)
        for fp, fn in faces:
            is_face = is_face | fp | fn
        diff = jnp.abs(rho_c - rho_n) / (jnp.minimum(rho_c, rho_n) + diff_threshold)
        return {"max_diff": jnp.max(jnp.where(is_face, diff, 0.0), axis=1)}

    return kernel


class AmrAdvection:
    """The reference test's main program (tests/advection/2d.cpp):
    solve / adapt every ``adapt_n`` / balance every ``balance_n``."""

    def __init__(self, length=(32, 32, 1), max_refinement_level=1, mesh=None,
                 cfl=0.5, diff_increase=0.02, diff_threshold=0.025,
                 unrefine_sensitivity=0.5, partition=None):
        self.cfl = cfl
        self.diff_increase = diff_increase
        self.diff_threshold = diff_threshold
        self.unrefine_sensitivity = unrefine_sensitivity
        cell_len = tuple(1.0 / n for n in length)
        self.grid = (
            Grid(cell_data={
                "density": jnp.float32, "flux": jnp.float32,
                "max_diff": jnp.float32,
                "vx": jnp.float32, "vy": jnp.float32, "vz": jnp.float32,
                "lx": jnp.float32, "ly": jnp.float32, "lz": jnp.float32,
                "ilen": jnp.int32,
            })
            .set_initial_length(length)
            .set_maximum_refinement_level(max_refinement_level)
            .set_neighborhood_length(1)
            .set_geometry("cartesian", start=(0.0, 0.0, 0.0),
                          level_0_cell_length=cell_len)
            .initialize(mesh, partition=partition)
        )
        self._flux_kernel = make_flux_kernel()
        self._fused_kernel = make_fused_step_kernel()
        self._diff_kernel = make_diff_kernel(diff_threshold)
        self._refresh_static()
        cells = self.grid.get_cells()
        self.grid.set("density", cells,
                      hump(self.grid.geometry.get_center(cells)).astype(np.float32))
        self.time = 0.0

    @classmethod
    def from_grid(cls, grid, cfl=0.5, diff_increase=0.02,
                  diff_threshold=0.025, unrefine_sensitivity=0.5,
                  time=0.0):
        """Wrap an existing grid (e.g. one restored with
        ``Grid.from_file``) carrying this app's field schema — the
        restart path of the reference's advection test."""
        app = cls.__new__(cls)
        app.cfl = cfl
        app.diff_increase = diff_increase
        app.diff_threshold = diff_threshold
        app.unrefine_sensitivity = unrefine_sensitivity
        app.grid = grid
        app._flux_kernel = make_flux_kernel()
        app._fused_kernel = make_fused_step_kernel()
        app._diff_kernel = make_diff_kernel(diff_threshold)
        app._refresh_static()
        app.time = time
        return app

    # -- static per-epoch fields ---------------------------------------

    def _refresh_static(self) -> None:
        g = self.grid
        cells = g.get_cells()
        centers = g.geometry.get_center(cells)
        lengths = g.geometry.get_length(cells)
        v = velocity(centers)
        # one batched upload: static fields cover every cell, so the
        # old device arrays are never read back; the exchange below
        # re-fills the ghost rows for the whole epoch
        g.set_many(cells, {
            "vx": v[:, 0].astype(np.float32),
            "vy": v[:, 1].astype(np.float32),
            "vz": v[:, 2].astype(np.float32),
            "lx": lengths[:, 0].astype(np.float32),
            "ly": lengths[:, 1].astype(np.float32),
            "lz": lengths[:, 2].astype(np.float32),
            "ilen": g.mapping.get_cell_length_in_indices(cells).astype(np.int32),
        }, preserve_ghosts=False)
        g.update_copies_of_remote_neighbors(fields=list(STATIC_FIELDS))

    # -- time stepping (2d.cpp:321-343) --------------------------------

    def max_time_step(self) -> float:
        """Global CFL limit (solve.hpp:289-333). Depends only on the
        static per-epoch velocity/length fields, so it is computed once
        per structure epoch (one device reduction, one scalar pull)."""
        g = self.grid
        cached = getattr(self, "_cfl_cache", None)
        if cached is not None and cached[0] == g.plan.epoch:
            return cached[1]
        steps = []
        for lname, vname in (("lx", "vx"), ("ly", "vy"), ("lz", "vz")):
            l = g.data[lname]
            v = jnp.abs(g.data[vname])
            s = jnp.min(jnp.where(v > 0, l / jnp.maximum(v, 1e-30), jnp.inf))
            steps.append(float(s))
        dt = float(min(steps))
        self._cfl_cache = (g.plan.epoch, dt)
        return dt

    def step(self, dt: float | None = None) -> float:
        if dt is None:
            dt = self.cfl * self.max_time_step()
        g = self.grid
        g.update_copies_of_remote_neighbors(fields=["density"])
        g.apply_stencil(
            self._flux_kernel,
            ["density", "vx", "vy", "vz", "lx", "ly", "lz", "ilen"],
            ["flux"],
            extra_args=(jnp.float32(dt),),
        )
        # apply_fluxes (solve.hpp:272-279)
        g.data["density"] = g.data["density"] + g.data["flux"]
        g.data["flux"] = jnp.zeros_like(g.data["flux"])
        self.time += dt
        return dt

    def run_fused(self, n_steps: int, dt: float | None = None) -> float:
        """``n_steps`` advection steps as ONE jitted device program
        (exchange + flux + apply per step inside lax.fori_loop) — the
        hot path between structure events. dt is constant across the
        segment: the CFL limit depends only on the static per-epoch
        velocity/length fields (solve.hpp:289-333)."""
        if dt is None:
            dt = self.cfl * self.max_time_step()
        self.grid.run_steps(
            self._fused_kernel,
            ["density", "vx", "vy", "vz", "lx", "ly", "lz", "ilen"],
            ["density"],
            n_steps,
            extra_args=(jnp.float32(dt),),
        )
        self.time += n_steps * dt
        return dt

    # -- adaptation (adapter.hpp:47-311) -------------------------------

    def _flagged_cells(self) -> tuple:
        """Device-side adaptation criterion (adapter.hpp:47-178 runs it
        rank-locally; here it is one threshold reduction ON device): a
        per-row decision code is computed from max_diff and the level
        (recovered from ilen = 2^(max_lvl - lvl)) in one jitted
        program, and only the compact int8 code array crosses to the
        host — 1 byte/row instead of the f64 max_diff pull plus host
        level recomputation (VERDICT r3 item 5; a device-side
        ``jnp.nonzero(size=...)`` compaction was measured 3.4 s/call
        on the CPU mesh against <0.1 s for the int8 pull, so the
        host does the final nonzero on the byte array). Returns
        (ids, codes) with code 1=refine, 2=dont_unrefine,
        3=unrefine."""
        g = self.grid
        max_lvl = g.mapping.max_refinement_level
        if not hasattr(self, "_code_fn"):
            @jax.jit
            def _codes(diff, ilen, nl, inc, sens):
                rows = jax.lax.broadcasted_iota(jnp.int32, diff.shape, 1)
                local = rows < nl
                lvl = jnp.int32(max_lvl) - jnp.round(
                    jnp.log2(jnp.maximum(ilen, 1).astype(jnp.float32))
                ).astype(jnp.int32)
                refine_t = (lvl + 1).astype(jnp.float32) * inc
                unref_t = sens * refine_t
                code = jnp.where(
                    (diff > refine_t) & (lvl < max_lvl), 1,
                    jnp.where(
                        (diff < unref_t) & (lvl > 0), 3,
                        jnp.where(
                            (diff <= refine_t) & (diff >= unref_t)
                            & (lvl > 0), 2, 0),
                    ),
                )
                return jnp.where(local, code, 0).astype(jnp.int8)

            self._code_fn = _codes
        nl = jnp.asarray(np.asarray(g.plan.n_local)[:, None].astype(np.int32))
        code = np.asarray(self._code_fn(
            g.data["max_diff"], g.data["ilen"], nl,
            jnp.float32(self.diff_increase),
            jnp.float32(self.unrefine_sensitivity),
        ))
        d, row = np.nonzero(code)
        if len(d) == 0:
            return np.empty(0, np.uint64), np.empty(0, np.int8)
        codes = code[d, row]
        ids = np.empty(len(d), dtype=np.uint64)
        for dev in range(g.n_dev):
            m = d == dev
            if m.any():
                ids[m] = g.plan.local_ids[dev][row[m]]
        return ids, codes

    def adapt(self) -> tuple:
        """check_for_adaptation + adapt_grid: returns (created, removed)."""
        g = self.grid
        if g.mapping.max_refinement_level == 0:
            return (np.empty(0, np.uint64), np.empty(0, np.uint64))
        g.update_copies_of_remote_neighbors(fields=["density"])
        g.apply_stencil(
            self._diff_kernel, ["density", "ilen"], ["max_diff"]
        )
        ids, codes = self._flagged_cells()
        to_refine = ids[codes == 1]
        keep = ids[codes == 2]
        to_unrefine = ids[codes == 3]
        # conflict resolution between siblings is the grid's job
        # (refine_completely overrides sibling unrefines, dccrg.hpp:2517)
        for c in to_refine:
            g.refine_completely(c)
        for c in keep:
            g.dont_unrefine(c)
        for c in to_unrefine:
            g.unrefine_completely(c)
        created = g.stop_refining()
        removed = g.get_removed_cells()
        # project data across the structure change (adapter.hpp:229-301)
        g.assign_children_from_parents(fields=["density"])
        g.average_parents_from_children(fields=["density"])
        g.clear_refined_unrefined_data()
        self._refresh_static()
        g.data["flux"] = jnp.zeros_like(g.data["flux"])
        return created, removed

    # -- load balancing (2d.cpp:425-438) -------------------------------

    def balance(self) -> None:
        self.grid.balance_load()
        self._refresh_static()

    # -- diagnostics ---------------------------------------------------

    def total_mass(self) -> float:
        g = self.grid
        cells = g.get_cells()
        rho = g.get("density", cells).astype(np.float64)
        vol = np.prod(g.geometry.get_length(cells), axis=1)
        return float(np.sum(rho * vol))

    def run(self, steps: int, adapt_n: int = 0, balance_n: int = 0,
            fused: bool = True) -> None:
        """The main loop (2d.cpp:321-442). With ``fused`` (default) the
        steps between structure events run as one device program each
        (run_fused); otherwise one dispatch pair per step."""
        i = 0
        while i < steps:
            # next structure event bounds the fused segment
            nexts = [steps - i]
            if adapt_n:
                nexts.append(adapt_n - i % adapt_n)
            if balance_n:
                nexts.append(balance_n - i % balance_n)
            seg = min(nexts)
            if fused:
                self.run_fused(seg)
                i += seg
            else:
                self.step()
                i += 1
            if adapt_n and i % adapt_n == 0:
                self.adapt()
            if balance_n and i % balance_n == 0:
                self.balance()
