"""Finite-volume upwind advection — the north-star benchmark workload.

Re-implements the reference advection test's math
(tests/advection/solve.hpp:44-333, initialize.hpp:36-80) on the dense
fast path: solid-body rotation velocity field (vx = 0.5 - y,
vy = x - 0.5, vz = 0; solve.hpp:339-346), cosine-hump initial density
(radius 0.15 at (0.25, 0.5), initialize.hpp:54-66), first-order upwind
fluxes with face-interpolated velocities, CFL-limited global step
(solve.hpp:289-333).

The per-cell neighbor loop of the reference becomes a fused shifted-
array computation on halo-padded local blocks; the halo exchange is
DenseGrid.pad_with_halo (ppermute slabs). One jitted step does
exchange + flux + apply (the reference's start/solve-inner/wait/
solve-outer/apply sequence collapses into a single XLA program whose
scheduler overlaps the collectives with independent compute).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..dense import AXES, DenseGrid
from ..grid import SlotwiseKernel

HUMP_X0, HUMP_Y0, HUMP_RADIUS = 0.25, 0.5, 0.15


def hump_density(x, y):
    """Cosine hump (initialize.hpp:54-66)."""
    r = jnp.minimum(jnp.sqrt((x - HUMP_X0) ** 2 + (y - HUMP_Y0) ** 2), HUMP_RADIUS) / HUMP_RADIUS
    return 0.25 * (1.0 + jnp.cos(jnp.pi * r))


def analytic_density(x, y, t):
    """Exact solution: the hump rotated by angle t about (0.5, 0.5)."""
    xc, yc = x - 0.5, y - 0.5
    c, s = jnp.cos(-t), jnp.sin(-t)
    x0, y0 = xc * c - yc * s + 0.5, xc * s + yc * c + 0.5
    return hump_density(x0, y0)


class PallasRotationAdvection:
    """Single-chip fast path: the Pallas temporal-blocked kernel
    (ops/advection_kernel.py) on the benchmark's separable rotation
    field. Produces bit-identical physics to AdvectionSolver's general
    dense path (cross-checked in tests), at HBM-bandwidth-limited
    throughput."""

    def __init__(self, n=512, nz=None, dtype=jnp.float32, cfl=0.5, steps_per_pass=7,
                 tile=(32, 128), interpret=False):
        from ..ops.advection_kernel import make_rotation_step

        nz = nz if nz is not None else n
        self.n, self.nz, self.cfl = n, nz, cfl
        self.steps_per_pass = steps_per_pass
        dx = 1.0 / n
        self.dx = dx
        x = (np.arange(n) + 0.5) * dx
        self.rho = jnp.asarray(
            np.asarray(hump_density(x[:, None, None], x[None, :, None])) * np.ones((1, 1, nz)),
            dtype=dtype,
        )
        self.vx_face = jnp.asarray((0.5 - x).astype(np.float32)[None, :])
        vy = (x - 0.5).astype(np.float32)
        # 8-row wrap margin on each side (kernel docstring)
        self.vy_face = jnp.asarray(np.concatenate([vy[-8:], vy, vy[:8]])[:, None])
        self._step = make_rotation_step(
            (n, n, nz), dtype=dtype, tile=tile, steps_per_pass=steps_per_pass,
            cell_length=(dx, dx, 1.0 / nz), interpret=interpret,
        )
        self.time = 0.0

    def max_time_step(self) -> float:
        vmax = float(np.abs(np.asarray(self.vx_face)).max())
        vmax = max(vmax, float(np.abs(np.asarray(self.vy_face)).max()))
        return self.dx / vmax

    def step(self, dt: float | None = None) -> float:
        """One kernel pass = ``steps_per_pass`` time steps."""
        if dt is None:
            dt = self.cfl * self.max_time_step()
        self.rho = self._step(self.rho, self.vx_face, self.vy_face, jnp.float32(dt))
        self.time += float(dt) * self.steps_per_pass
        return float(dt)


def make_uniform_flux_kernel(cell_length):
    """Upwind flux kernel for the general-Grid gather path on a uniform
    (max_refinement_level=0) grid with in-plane velocities: same math
    as AdvectionSolver._kernel (solve.hpp:44-279) expressed over
    face-neighbor gather tables (offsets in index units, cell size 1).
    Arithmetic is always float32: narrow-storage fields (bfloat16 HBM
    residency, the TPU bandwidth lever) are widened on read and the
    fused loop's writeback narrows the result — no-op casts when the
    fields are float32 already."""
    inv = [1.0 / float(cell_length[d]) for d in range(3)]
    f32 = jnp.float32

    def init(cell, dt):
        return jnp.zeros(cell["density"].shape, f32)

    def slot(acc, cell, nbr, offs, mask, dt):
        # one stencil leg: nbr[name] is [L], offs [3] or [L, 3] (raw,
        # gated by mask), mask [L] — the SlotwiseKernel contract keeps
        # peak HBM at O(cells); dense callers reach this through the
        # __call__ adapter one slot at a time
        rho_c = cell["density"].astype(f32)
        rho_n = nbr["density"].astype(f32)
        for d, vname in ((0, "vx"), (1, "vy")):
            v = 0.5 * (cell[vname].astype(f32) + nbr[vname].astype(f32))
            up_pos = jnp.where(v >= 0, rho_c, rho_n)
            up_neg = jnp.where(v >= 0, rho_n, rho_c)
            face_pos = mask & (offs[..., d] == 1)
            face_neg = mask & (offs[..., d] == -1)
            m = v * (dt * inv[d])
            acc = acc - jnp.where(face_pos, up_pos * m, 0.0)
            acc = acc + jnp.where(face_neg, up_neg * m, 0.0)
        return acc

    def finish(acc, cell, dt):
        return {"density": cell["density"].astype(f32) + acc}

    return SlotwiseKernel(init, slot, finish)


class GridAdvection:
    """The north-star benchmark on the general ``Grid`` runtime: the
    same solid-body-rotation advection as AdvectionSolver, but running
    through the framework's gather tables and the fused
    ``Grid.run_steps`` loop (exchange + stencil + apply per step inside
    one XLA program) instead of the dense fast path. Face-neighbor
    neighborhood (set_neighborhood_length(0), dccrg.hpp:8015-8076)."""

    def __init__(self, n=256, nz=None, mesh=None, cfl=0.5,
                 dtype=jnp.float32):
        from ..grid import Grid

        nz = nz if nz is not None else n
        self.n, self.nz, self.cfl = n, nz, cfl
        self.dtype = jnp.dtype(dtype)
        dx = 1.0 / n
        self.dx = dx
        self.grid = (
            # grid-wide storage dtype: bfloat16 here halves the
            # state's HBM residency and exchange/checkpoint bytes
            # end-to-end; the flux kernel computes in float32 either
            # way (weakly-typed arithmetic, pinned in
            # tests/test_pallas_kernel.py)
            Grid(cell_data={"density": jnp.float32, "vx": jnp.float32,
                            "vy": jnp.float32}, dtype=self.dtype)
            .set_initial_length((n, n, nz))
            .set_periodic(True, True, False)
            .set_maximum_refinement_level(0)
            .set_neighborhood_length(0)
            .set_geometry("cartesian", start=(0.0, 0.0, 0.0),
                          level_0_cell_length=(dx, dx, 1.0 / nz))
            # block partition: contiguous slabs take the closed-form
            # multi-device plan (no dense gather tables) and the
            # compact +-1-peer ppermute exchange
            .initialize(mesh, partition="block")
        )
        # init entirely ON device: the cell index is affine in the
        # geometry center on this uniform grid, so density/vx/vy are
        # computed from the sharded row-id array — no host f64 centers,
        # no host trig, no bulk uploads (the reference initializes in
        # one pass over resident memory, initialize.hpp:36-80; at 512^3
        # this path took ~66 s through the host, VERDICT r3)
        ridx = self.grid.device_row_ids()
        nx = np.int32(n)

        fdt = self.dtype

        @partial(jax.jit, out_shardings=self.grid._sharding())
        def _init_fields(ridx):
            valid = ridx >= 0
            xi = jnp.where(valid, ridx, 0) % nx
            yi = (jnp.where(valid, ridx, 0) // nx) % nx
            x = (xi.astype(jnp.float32) + 0.5) * jnp.float32(dx)
            y = (yi.astype(jnp.float32) + 0.5) * jnp.float32(dx)
            zero = jnp.float32(0.0)
            return (
                jnp.where(valid, hump_density(x, y).astype(jnp.float32),
                          zero).astype(fdt),
                jnp.where(valid, jnp.float32(0.5) - y, zero).astype(fdt),
                jnp.where(valid, x - jnp.float32(0.5), zero).astype(fdt),
            )

        rho, vx, vy = _init_fields(ridx)
        self.grid.data["density"] = rho
        self.grid.data["vx"] = vx
        self.grid.data["vy"] = vy
        self._kernel = make_uniform_flux_kernel((dx, dx, 1.0 / nz))
        self.time = 0.0

    def max_time_step(self) -> float:
        # centers span [dx/2, 1-dx/2], so max |v| over cell centers is
        # 0.5 - dx/2 exactly — no host center arrays needed
        return self.dx / (0.5 - 0.5 * self.dx)

    def run(self, n_steps: int, dt: float | None = None) -> float:
        if dt is None:
            dt = self.cfl * self.max_time_step()
        self.grid.run_steps(
            self._kernel, ["density", "vx", "vy"], ["density"], n_steps,
            extra_args=(jnp.float32(dt),),
        )
        self.time += n_steps * dt
        return dt

    def density(self) -> np.ndarray:
        return self.grid.get("density", self.grid.plan.cells)

    def checksum(self) -> float:
        """Forced scalar readback: sums the sharded density over LOCAL
        rows only (ghost and pad rows masked out, so this is the true
        total density — usable as a mass probe at unit cell volume) and
        pulls ONE scalar — a synchronization point that cannot
        under-report elapsed time the way block_until_ready can when
        dispatch is remote."""
        return float(jnp.sum(self.grid.data["density"] * self.grid.local_row_mask()))

    def l2_error(self) -> float:
        """L2 error vs the rotated analytic hump (BASELINE.json's
        parity metric; same math as AdvectionSolver.l2_error), computed
        on device over local rows (XLA's tree reduction keeps the f32
        sum well-conditioned; no host center arrays at 512^3)."""
        g = self.grid
        if not hasattr(self, "_sq_err_fn"):
            nx = np.int32(self.n)
            dx = jnp.float32(self.dx)

            @jax.jit
            def _sq_err(rho, ridx, mask, t):
                valid = ridx >= 0
                xi = jnp.where(valid, ridx, 0) % nx
                yi = (jnp.where(valid, ridx, 0) // nx) % nx
                x = (xi.astype(jnp.float32) + 0.5) * dx
                y = (yi.astype(jnp.float32) + 0.5) * dx
                exact = analytic_density(x, y, t).astype(jnp.float32)
                return jnp.sum((rho - exact) ** 2 * mask)

            self._sq_err_fn = _sq_err
        sq = self._sq_err_fn(g.data["density"], g.device_row_ids(),
                             g.local_row_mask(), jnp.float32(self.time))
        vol = self.dx * self.dx * (1.0 / self.nz)
        return float(np.sqrt(float(sq) * vol))


class AdvectionSolver:
    """Dense-path advection on [0,1]^3.

    Mirrors tests/advection/2d.cpp's configuration for normal dimension
    z: grid (n, n, nz), periodic in x and y (2d.cpp:237), velocities in
    the x-y plane. ``nz > 1`` replicates the 2-D problem along z — the
    3-D 512^3 benchmark configuration of BASELINE.json.
    """

    def __init__(self, n=64, nz=None, mesh=None, dtype=jnp.float32, cfl=0.5):
        nz = nz if nz is not None else 1
        self.n = n
        self.cfl = cfl
        self.grid = DenseGrid(
            (n, n, nz),
            {"rho": dtype, "vx": dtype, "vy": dtype, "vz": dtype},
            mesh=mesh,
            periodic=(True, True, False),
            start=(0.0, 0.0, 0.0),
            cell_length=(1.0 / n, 1.0 / n, 1.0 / nz),
        )
        self.grid.init_fields(
            lambda x, y, z: {
                "rho": hump_density(x, y) + 0.0 * z,
                "vx": 0.5 - y + 0.0 * x + 0.0 * z,
                "vy": x - 0.5 + 0.0 * y + 0.0 * z,
                "vz": jnp.zeros_like(x + y + z),
            }
        )
        # velocities are constant in time: halo-pad them ONCE and pass
        # the padded blocks into every step, so each step exchanges only
        # rho (4x less ppermute traffic than re-padding all four fields)
        import jax
        from ..dense import _shard_map

        pad1 = _shard_map(
            lambda b: self.grid.pad_with_halo(b, 1),
            mesh=self.grid.mesh,
            in_specs=P(*AXES),
            out_specs=P(*AXES),
        )
        self._vel_padded = tuple(
            jax.jit(pad1)(self.grid.arrays[n]) for n in ("vx", "vy", "vz")
        )
        self._step = self.grid.make_step(
            self._kernel, ("rho",), ("rho",), halo=1,
            extra_specs=(P(*AXES), P(*AXES), P(*AXES), P()),
        )
        self.time = 0.0

    # -- CFL (solve.hpp:289-333) --------------------------------------

    def max_time_step(self) -> float:
        """Largest stable dt: min over cells of length/|v| per dim
        (global psum-free reduction; jnp.min over the sharded arrays)."""
        steps = []
        for d, name in enumerate(("vx", "vy", "vz")):
            v = self.grid.arrays[name]
            dlen = self.grid.cell_length[d]
            m = jnp.min(jnp.where(jnp.abs(v) > 0, dlen / jnp.abs(v), jnp.inf))
            steps.append(m)
        return float(jnp.minimum(jnp.minimum(steps[0], steps[1]), steps[2]))

    # -- the fused step (solve.hpp:44-279) ----------------------------

    def _kernel(self, b, vxp, vyp, vzp, dt):
        rho = b["rho"]
        vel = (vxp, vyp, vzp)
        lens = self.grid.cell_length
        nloc = tuple(s - 2 for s in rho.shape)  # interior block extent

        def interior_shift(a, d, off):
            idx = tuple(
                slice(1 + (off if dd == d else 0), a.shape[dd] - 1 + (off if dd == d else 0))
                for dd in range(3)
            )
            return a[idx]

        rho_c = interior_shift(rho, 0, 0)
        out = rho_c
        for d in range(3):
            v = vel[d]
            v_c = interior_shift(v, d, 0)
            v_p = interior_shift(v, d, +1)
            v_m = interior_shift(v, d, -1)
            rho_p = interior_shift(rho, d, +1)
            rho_m = interior_shift(rho, d, -1)
            # velocity interpolated to the shared face (equal-size cells
            # reduce solve.hpp:169-176 to the average)
            vface_hi = 0.5 * (v_c + v_p)
            vface_lo = 0.5 * (v_m + v_c)
            # upwind donor density (solve.hpp:178-226)
            up_hi = jnp.where(vface_hi >= 0, rho_c, rho_p)
            up_lo = jnp.where(vface_lo >= 0, rho_m, rho_c)
            flux_hi = vface_hi * up_hi
            flux_lo = vface_lo * up_lo
            if not self.grid.periodic[d]:
                # missing neighbor => no flux through that face (the
                # reference simply has no face neighbor there)
                pos = lax.axis_index(AXES[d])
                glob = pos * nloc[d] + lax.broadcasted_iota(jnp.int32, nloc, d)
                flux_hi = jnp.where(glob < self.grid.length[d] - 1, flux_hi, 0.0)
                flux_lo = jnp.where(glob > 0, flux_lo, 0.0)
            out = out + (flux_lo - flux_hi) * (dt / lens[d])
        return {"rho": out}

    def step(self, dt: float | None = None) -> float:
        if dt is None:
            dt = self.cfl * self.max_time_step()
        self.grid.arrays = self._step(self.grid.arrays, *self._vel_padded, jnp.asarray(dt))
        self.time += float(dt)
        return float(dt)

    # -- diagnostics ---------------------------------------------------

    def total_mass(self) -> float:
        # f64 accumulation on host (x64 is disabled on-device)
        vol = float(np.prod(self.grid.cell_length))
        return float(np.sum(self.grid.to_host("rho"), dtype=np.float64)) * vol

    def l2_error(self) -> float:
        """L2 error against the rotated analytic hump (the parity
        metric of BASELINE.json)."""
        g = self.grid
        x = np.asarray(g.cell_centers(0))[:, None, None]
        y = np.asarray(g.cell_centers(1))[None, :, None]
        exact = np.asarray(analytic_density(x, y, self.time))
        diff = g.to_host("rho").astype(np.float64) - exact
        vol = float(np.prod(g.cell_length))
        return float(np.sqrt(np.sum(diff**2) * vol))
