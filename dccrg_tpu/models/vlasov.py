"""Vlasov-style workload: wide per-cell velocity-space payloads where
only reduced moments exchange as ghosts.

The reference dccrg's home domain is Vlasiator-style hybrid-Vlasov
simulation (Palmroth et al. 2018): each spatial cell carries a WIDE
velocity-space distribution (the ragged ``Cell_Data`` shape — here a
fixed ``[Nv]`` vector field ``f``), while the MPI ghost traffic moves
only small reduced quantities. This model reproduces exactly that
transfer shape on the batched runtime:

- ``f`` (``[n_cells, Nv]`` float32) is advected in **velocity space**
  by a self-consistent-field sketch (acceleration from the neighbor
  density gradient — an electrostatic-force proxy) with zero-flux
  velocity boundaries, then relaxed BGK-style toward a discrete
  Maxwellian built from the **neighbor-averaged** moments — the
  configuration-space coupling;
- only the reduced moments ``rho`` and ``ux`` (recomputed from ``f``
  every step) are read from neighbors, so ``run_steps`` exchanges
  ``("rho", "ux")`` — a proper subset of ``fields_out`` — and the
  wide payload NEVER moves over the interconnect
  (:class:`GridVlasov` pins the stale-ghost bytes).

Conservation: the velocity advection is flux-form with zero boundary
fluxes (per-cell mass exact in real arithmetic), and the BGK target
is normalized so its moment equals the neighbor-averaged density —
doubly stochastic over the face relation under full periodicity —
so total mass (``sum rho``) is conserved;
``integrity.register_conserved("vlasov", ("rho",))`` wires it into
the SDC defense.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..grid import Grid

NV_DEFAULT = 16
VMAX = 1.0      # velocity-grid half-extent
VT = 0.4        # thermal width of the BGK target
NU = 0.5        # BGK relaxation rate
KFIELD = 0.05   # density-gradient force coefficient
RHO_FLOOR = 1.0e-6

VLASOV_FIELDS = ("f", "rho", "ux")
VLASOV_EXCHANGE = ("rho", "ux")

_f32 = jnp.float32


def vlasov_cell_data(nv: int = NV_DEFAULT, dtype=jnp.float32) -> dict:
    """The schema: the wide ``[Nv]`` payload plus its two moments."""
    return {"f": ((int(nv),), dtype), "rho": dtype, "ux": dtype}


def _v_grid(nv: int):
    v = jnp.linspace(-VMAX, VMAX, nv, dtype=_f32)
    dv = _f32(2.0 * VMAX / (nv - 1))
    return v, dv


def _moments(f, v, dv):
    rho = jnp.sum(f, axis=-1) * dv
    ux = jnp.sum(f * v, axis=-1) * dv / jnp.maximum(rho, _f32(RHO_FLOOR))
    return rho, ux


def make_vlasov_kernel():
    """The fleet kernel (registry name ``"vlasov"``), one parameter
    ``dt``. ``Nv`` is read off the field shape, so one kernel serves
    every payload width. Declares that EVERY output's ghost reads are
    the two moments — the wide ``f`` is never read from neighbors."""

    def kernel(cell, nbr, offs, mask, dt):
        f = cell["f"].astype(_f32)               # [L, Nv]
        nv = f.shape[-1]
        v, dv = _v_grid(nv)
        dt = _f32(dt)
        face = mask & (jnp.sum(jnp.abs(offs), axis=-1) == 1)
        rho_n = nbr["rho"].astype(_f32)          # [L, S]
        ux_n = nbr["ux"].astype(_f32)
        deg = jnp.maximum(jnp.sum(face, axis=1), 1).astype(_f32)
        rho_bar = jnp.sum(jnp.where(face, rho_n, 0.0), axis=1) / deg
        ux_bar = jnp.sum(jnp.where(face, ux_n, 0.0), axis=1) / deg
        # electrostatic-force proxy: the x-gradient of the neighbor
        # density (the only other ghost read)
        gx = jnp.sum(jnp.where(face & (offs[..., 0] != 0),
                               offs[..., 0].astype(_f32) * rho_n, 0.0),
                     axis=1)
        a = -_f32(KFIELD) * gx                   # [L]
        # velocity-space upwind advection, flux form, zero-flux ends:
        # interior edge fluxes [L, Nv-1], per-cell mass telescopes
        ap = jnp.maximum(a, 0.0)[:, None]
        am = jnp.minimum(a, 0.0)[:, None]
        flux = ap * f[:, :-1] + am * f[:, 1:]
        z1 = jnp.zeros(f.shape[:-1] + (1,), _f32)
        f1 = f - (dt / dv) * (jnp.concatenate([flux, z1], axis=-1)
                              - jnp.concatenate([z1, flux], axis=-1))
        # BGK relaxation toward the neighbor-moment Maxwellian,
        # normalized so its density moment is exactly rho_bar
        w = jnp.exp(-((v[None, :] - ux_bar[:, None]) / _f32(VT)) ** 2)
        g = (rho_bar[:, None] * w
             / (jnp.sum(w, axis=-1, keepdims=True) * dv))
        f2 = f1 + dt * _f32(NU) * (g - f1)
        rho2, ux2 = _moments(f2, v, dv)
        return {"f": f2, "rho": rho2, "ux": ux2}

    kernel.ghost_deps = {n: VLASOV_EXCHANGE for n in VLASOV_FIELDS}
    return kernel


def vlasov_default_init(grid, seed: int) -> None:
    """Seeded default init for ``"vlasov"`` jobs: a positive random
    distribution with SELF-CONSISTENT moments (rho/ux recomputed from
    f exactly as the kernel does). Byte-identical fleet vs solo."""
    rng = np.random.default_rng(seed)
    cells = grid.plan.cells
    nv = int(grid.fields["f"][0][0])
    f = (0.1 + rng.random((len(cells), nv))).astype(np.float32)
    _set_with_moments(grid, cells, f)


def _set_with_moments(grid, cells, f) -> None:
    v = np.linspace(-VMAX, VMAX, f.shape[-1], dtype=np.float32)
    dv = np.float32(2.0 * VMAX / (f.shape[-1] - 1))
    rho = (f.sum(axis=-1, dtype=np.float32) * dv).astype(np.float32)
    ux = ((f * v).sum(axis=-1, dtype=np.float32) * dv
          / np.maximum(rho, np.float32(RHO_FLOOR))).astype(np.float32)
    grid.set("f", cells, f)
    grid.set("rho", cells, rho)
    grid.set("ux", cells, ux)


class GridVlasov:
    """The multi-device Vlasov model: a drifting density bump whose
    wide velocity payload stays device-local — every ``run_steps``
    call exchanges only the two moments."""

    def __init__(self, n=8, nz=None, nv=NV_DEFAULT, mesh=None,
                 partition="block", seed=0):
        nz = nz if nz is not None else n
        self.n, self.nz, self.nv = n, nz, int(nv)
        self.grid = (
            Grid(cell_data=vlasov_cell_data(nv))
            .set_initial_length((n, n, nz))
            .set_periodic(True, True, True)
            .set_maximum_refinement_level(0)
            .set_neighborhood_length(0)
            .initialize(mesh, partition=partition)
        )
        cells = self.grid.plan.cells
        idx = self.grid.mapping.get_indices(np.asarray(cells, np.uint64))
        x = (idx[:, 0].astype(np.float64) + 0.5) / n
        bump = (1.0 + 0.5 * np.cos(2.0 * np.pi * x)).astype(np.float32)
        v = np.linspace(-VMAX, VMAX, self.nv, dtype=np.float32)
        f = (bump[:, None]
             * np.exp(-((v[None, :] - 0.2) / VT) ** 2)).astype(np.float32)
        rng = np.random.default_rng(seed)
        f = f + (0.01 * rng.random(f.shape)).astype(np.float32)
        _set_with_moments(self.grid, cells, f)
        self.grid.update_copies_of_remote_neighbors()
        self._kernel = make_vlasov_kernel()
        self.time = 0.0

    def run(self, n_steps: int, dt: float = 0.05) -> float:
        self.grid.run_steps(
            self._kernel, VLASOV_FIELDS, VLASOV_FIELDS, n_steps,
            exchange_fields=VLASOV_EXCHANGE,
            extra_args=(jnp.float32(dt),))
        self.time += n_steps * dt
        return dt

    def total_mass(self) -> float:
        g = self.grid
        return float(np.sum(np.asarray(g.get("rho", g.plan.cells),
                                       np.float64)))


def register() -> None:
    """Register the zoo entries: the ``"vlasov"`` fleet kernel (with
    the wide-payload schema defaults and seeded init) and the mass
    invariant for the SDC defense. Idempotent."""
    from .. import fleet, integrity

    fleet.register_kernel("vlasov", make_vlasov_kernel())
    fleet.register_kernel_spec(
        "vlasov", cell_data=vlasov_cell_data(NV_DEFAULT),
        fields_in=VLASOV_FIELDS, fields_out=VLASOV_FIELDS,
        params=(0.05,), init=vlasov_default_init)
    integrity.register_conserved("vlasov", ("rho",),
                                 periodic_axes=(0, 1, 2))


ZOO_INFO = {
    "kernel": "vlasov",
    "fields": VLASOV_FIELDS,
    "ghost_deps": {n: VLASOV_EXCHANGE for n in VLASOV_FIELDS},
    "conserved": ("rho",),
    "model": "GridVlasov",
    "description": ("hybrid-Vlasov-style: wide [Nv] per-cell velocity "
                    "payload advected locally; only the (rho, ux) "
                    "moments exchange as ghosts"),
}
