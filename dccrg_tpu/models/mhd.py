"""Finite-volume ideal-MHD-style solver — the model zoo's multi-field
workload.

Eight coupled per-cell fields (the Vlasiator/dccrg shape: density,
momentum x3, total energy, magnetic field x3) advanced by two
operator-split passes with **different ghost dependencies**:

- the **hydro flux pass** — first-order Rusanov (local Lax-Friedrichs)
  fluxes of the Euler subsystem over face neighbors — reads ONLY the
  hydro fields' ghosts;
- the **CT/divergence-cleaning pass** — a conservative resistive
  smoothing of B over face neighbors (the diffusive limit of
  constrained-transport cleaning) — reads ONLY the B fields' ghosts.

That split is exactly what the per-field ghost-split overlap
(``DCCRG_GHOST_SPLIT``, grid.py) consumes: each pass declares
``ghost_deps`` and exchanges only its own subsystem, so the overlap
outer re-pass recomputes the subsystem's rows instead of every outer
row x every field (counted by ``Grid.last_overlap`` /
``dccrg_outer_repass_rows_total``; bench/models_bench.py's
``outer_repass_rows_{full,split}`` keys).

Modeling notes (honest simplifications):

- The Lorentz back-reaction on the momentum/energy equations is
  omitted and the induction stretching term is folded into the
  cleaning diffusivity, so each subsystem is EXACTLY conservative in
  real arithmetic — mass, momentum x3, energy and B x3 under periodic
  BCs — which is precisely the invariant surface the SDC defense
  consumes (``integrity.register_conserved("mhd", ...)``).
- Face fluxes are written so the two sides of a face compute
  bit-identical values (commutative-add flux averages, shared
  ``U_right - U_left`` dissipation term, symmetric ``max`` wave
  speed): the pairwise cancellation is exact, and the conservation
  sums drift only by reduction rounding — inside
  ``integrity.sum_tolerance`` by construction.
- Pressure and density are floored (``P_FLOOR``/``RHO_FLOOR``) inside
  the flux evaluation only: the update stays flux-form, so the floors
  never break conservation, they only keep the wave-speed finite on
  rough states (the fleet's seeded random inits).

The single fused kernel (:func:`make_mhd_kernel` — hydro AND cleaning
every step) is registered as the fleet kernel ``"mhd"``; the
two-pass form (:func:`make_mhd_pass_kernels`) drives
:class:`GridMHD`, the multi-device model class.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..grid import Grid

GAMMA = 5.0 / 3.0
ETA = 0.08          # B cleaning diffusivity (stability: lam*ETA*6 < 1)
P_FLOOR = 1.0e-6
RHO_FLOOR = 1.0e-3

MHD_HYDRO = ("rho", "mx", "my", "mz", "en")
MHD_BFIELD = ("bx", "by", "bz")
MHD_ALL = MHD_HYDRO + MHD_BFIELD

_f32 = jnp.float32


def mhd_cell_data(dtype=jnp.float32) -> dict:
    """The 8-field MHD schema (every field a scalar per cell)."""
    return {n: dtype for n in MHD_ALL}


def _widen(fields, names):
    return {n: fields[n].astype(_f32) for n in names}


def _euler_flux(U, d):
    """Euler flux along axis ``d`` plus the local max wave speed
    ``|v_d| + c``. Shapes follow the inputs ([L] cells or [L, S]
    neighbors)."""
    rho = jnp.maximum(U["rho"], _f32(RHO_FLOOR))
    inv = 1.0 / rho
    vx, vy, vz = U["mx"] * inv, U["my"] * inv, U["mz"] * inv
    ke = 0.5 * (U["mx"] * vx + U["my"] * vy + U["mz"] * vz)
    p = jnp.maximum(_f32(GAMMA - 1.0) * (U["en"] - ke), _f32(P_FLOOR))
    vd = (vx, vy, vz)[d]
    F = {
        "rho": U[("mx", "my", "mz")[d]],
        "mx": vd * U["mx"],
        "my": vd * U["my"],
        "mz": vd * U["mz"],
        "en": vd * (U["en"] + p),
    }
    md = ("mx", "my", "mz")[d]
    F[md] = F[md] + p
    speed = jnp.abs(vd) + jnp.sqrt(_f32(GAMMA) * p * inv)
    return F, speed


def _hydro_update(cell, nbr, offs, mask, lam):
    """One Rusanov step of the hydro subsystem: ``U += lam * sum of
    face fluxes`` with ``lam = dt/dx``. Reads hydro neighbor (ghost)
    values only."""
    U_c = _widen(cell, MHD_HYDRO)
    U_n = _widen(nbr, MHD_HYDRO)
    lam = _f32(lam)
    acc = {n: jnp.zeros_like(U_c[n]) for n in MHD_HYDRO}
    unit = jnp.sum(jnp.abs(offs), axis=-1) == 1
    for d in range(3):
        Fc, sc = _euler_flux(U_c, d)
        Fn, sn = _euler_flux(U_n, d)
        pos = mask & unit & (offs[..., d] == 1)
        neg = mask & unit & (offs[..., d] == -1)
        # the two sides of a face compute bit-identical fluxes: the
        # average is x+y either way, the dissipation term is always
        # (U_right - U_left), and max(a, b) == max(b, a)
        for n in MHD_HYDRO:
            cc = U_c[n][:, None]
            f_hi = (0.5 * (Fc[n][:, None] + Fn[n])
                    - 0.5 * jnp.maximum(sc[:, None], sn)
                    * (U_n[n] - cc))
            f_lo = (0.5 * (Fn[n] + Fc[n][:, None])
                    - 0.5 * jnp.maximum(sn, sc[:, None])
                    * (cc - U_n[n]))
            acc[n] = acc[n] + (jnp.sum(jnp.where(neg, f_lo, 0.0), axis=1)
                               - jnp.sum(jnp.where(pos, f_hi, 0.0),
                                         axis=1))
    return {n: U_c[n] + lam * acc[n] for n in MHD_HYDRO}


def _b_update(cell, nbr, offs, mask, lam):
    """One cleaning step of the B subsystem: conservative face
    smoothing ``B += lam * ETA * sum_faces (B_nbr - B)``. Reads B
    neighbor (ghost) values only."""
    lam = _f32(lam)
    unit = jnp.sum(jnp.abs(offs), axis=-1) == 1
    face = mask & unit
    out = {}
    for n in MHD_BFIELD:
        b_c = cell[n].astype(_f32)
        b_n = nbr[n].astype(_f32)
        s = jnp.sum(jnp.where(face, b_n - b_c[:, None], 0.0), axis=1)
        out[n] = b_c + lam * _f32(ETA) * s
    return out


def make_mhd_kernel():
    """The fused fleet kernel (registry name ``"mhd"``): hydro flux
    AND B cleaning every step, one parameter ``lam = dt/dx``.
    Declares the per-field ghost split: hydro outputs read hydro
    ghosts, B outputs read B ghosts."""

    def kernel(cell, nbr, offs, mask, lam):
        out = _hydro_update(cell, nbr, offs, mask, lam)
        out.update(_b_update(cell, nbr, offs, mask, lam))
        return out

    kernel.ghost_deps = {**{n: MHD_HYDRO for n in MHD_HYDRO},
                         **{n: MHD_BFIELD for n in MHD_BFIELD}}
    return kernel


def make_mhd_pass_kernels():
    """The operator-split pair ``(hydro_pass, b_pass)`` driving
    :class:`GridMHD`: each pass updates its subsystem and passes the
    other through IDENTITY, so a ``run_steps`` call exchanges only
    the subsystem that changes — a proper subset of ``fields_out``,
    which is what lets the ghost-split outer re-pass skip the frozen
    subsystem's rows entirely."""

    def hydro_pass(cell, nbr, offs, mask, lam):
        out = _hydro_update(cell, nbr, offs, mask, lam)
        out.update({n: cell[n] for n in MHD_BFIELD})
        return out

    hydro_pass.ghost_deps = {**{n: MHD_HYDRO for n in MHD_HYDRO},
                             **{n: () for n in MHD_BFIELD}}

    def b_pass(cell, nbr, offs, mask, lam):
        out = {n: cell[n] for n in MHD_HYDRO}
        out.update(_b_update(cell, nbr, offs, mask, lam))
        return out

    b_pass.ghost_deps = {**{n: () for n in MHD_HYDRO},
                         **{n: MHD_BFIELD for n in MHD_BFIELD}}
    return hydro_pass, b_pass


def mhd_default_init(grid, seed: int) -> None:
    """The fleet's seeded default init for ``"mhd"`` jobs: a smooth
    random state with positive density and pressure (the plain
    uniform-random fill of the generic default would start with
    supersonic noise and negative pressures). Deterministic in
    (cell count, seed); byte-identical fleet vs solo."""
    rng = np.random.default_rng(seed)
    cells = grid.plan.cells
    nc = len(cells)
    rho = (1.0 + 0.5 * rng.random(nc)).astype(np.float32)
    mom = {n: (0.2 * (rng.random(nc) - 0.5)).astype(np.float32)
           for n in ("mx", "my", "mz")}
    p = (0.5 + 0.5 * rng.random(nc)).astype(np.float32)
    ke = 0.5 * (mom["mx"] ** 2 + mom["my"] ** 2 + mom["mz"] ** 2) / rho
    en = (p / np.float32(GAMMA - 1.0) + ke).astype(np.float32)
    grid.set("rho", cells, rho)
    for n, v in mom.items():
        grid.set(n, cells, v)
    grid.set("en", cells, en)
    for n in MHD_BFIELD:
        grid.set(n, cells, (0.3 * (rng.random(nc) - 0.5))
                 .astype(np.float32))


class GridMHD:
    """The multi-device MHD model on the general ``Grid`` runtime:
    a blast-wave setup advanced by the two-pass operator splitting
    (hydro^n then cleaning^n per :meth:`run` call) through the fused
    ``Grid.run_steps`` loop, each pass exchanging only its own
    subsystem's ghosts."""

    def __init__(self, n=16, nz=None, mesh=None, dtype=jnp.float32,
                 partition="block", profile="blast", seed=0):
        nz = nz if nz is not None else n
        self.n, self.nz = n, nz
        dx = 1.0 / n
        self.dx = dx
        self.grid = (
            Grid(cell_data=mhd_cell_data(jnp.float32), dtype=dtype)
            .set_initial_length((n, n, nz))
            .set_periodic(True, True, True)
            .set_maximum_refinement_level(0)
            .set_neighborhood_length(0)
            .set_geometry("cartesian", start=(0.0, 0.0, 0.0),
                          level_0_cell_length=(dx, dx, 1.0 / nz))
            .initialize(mesh, partition=partition)
        )
        cells = self.grid.plan.cells
        if profile == "blast":
            self._init_blast(cells)
        else:
            mhd_default_init(self.grid, seed)
        self.grid.update_copies_of_remote_neighbors()
        self._hydro, self._bpass = make_mhd_pass_kernels()
        self.time = 0.0

    def _init_blast(self, cells):
        """Sedov-style pressure blast in a uniform magnetized medium
        (the reference test-zoo's classic)."""
        g = self.grid
        idx = g.mapping.get_indices(np.asarray(cells, np.uint64))
        x = (idx[:, 0].astype(np.float64) + 0.5) * self.dx
        y = (idx[:, 1].astype(np.float64) + 0.5) * self.dx
        z = (idx[:, 2].astype(np.float64) + 0.5) / self.nz
        r2 = (x - 0.5) ** 2 + (y - 0.5) ** 2 + (z - 0.5) ** 2
        p = np.where(r2 < 0.1 ** 2, 10.0, 0.1).astype(np.float32)
        nc = len(cells)
        g.set("rho", cells, np.ones(nc, np.float32))
        for nme in ("mx", "my", "mz"):
            g.set(nme, cells, np.zeros(nc, np.float32))
        g.set("en", cells, (p / np.float32(GAMMA - 1.0)))
        g.set("bx", cells, np.full(nc, 0.2, np.float32))
        g.set("by", cells, np.zeros(nc, np.float32))
        g.set("bz", cells, np.zeros(nc, np.float32))

    def max_time_step(self) -> float:
        """CFL bound from the current state (host reduction)."""
        g = self.grid
        rho = np.maximum(np.asarray(g.get("rho", g.plan.cells),
                                    np.float64), RHO_FLOOR)
        vmax = 0.0
        ke = np.zeros_like(rho)
        for nme in ("mx", "my", "mz"):
            m = np.asarray(g.get(nme, g.plan.cells), np.float64)
            vmax = max(vmax, float(np.abs(m / rho).max()))
            ke += 0.5 * m * m / rho
        en = np.asarray(g.get("en", g.plan.cells), np.float64)
        p = np.maximum((GAMMA - 1.0) * (en - ke), P_FLOOR)
        c = float(np.sqrt(GAMMA * p / rho).max())
        return self.dx / max(vmax + c, ETA * 6.0, 1e-12)

    def run(self, n_steps: int, dt: float | None = None,
            cfl: float = 0.4) -> float:
        """``n_steps`` hydro steps then ``n_steps`` cleaning steps
        (coarse operator splitting — each pass is one fused device
        loop exchanging only its own subsystem)."""
        if dt is None:
            dt = cfl * self.max_time_step()
        lam = jnp.float32(dt / self.dx)
        self.grid.run_steps(self._hydro, MHD_ALL, MHD_ALL, n_steps,
                            exchange_fields=MHD_HYDRO,
                            extra_args=(lam,))
        self.grid.run_steps(self._bpass, MHD_ALL, MHD_ALL, n_steps,
                            exchange_fields=MHD_BFIELD,
                            extra_args=(lam,))
        self.time += n_steps * dt
        return dt

    def conserved_sums(self) -> dict:
        """Host-f64 global sums of every conserved field — the
        conservation diagnostic the tests pin."""
        g = self.grid
        return {n: float(np.sum(np.asarray(g.get(n, g.plan.cells),
                                           np.float64)))
                for n in MHD_ALL}


def register() -> None:
    """Register the zoo entries: the ``"mhd"`` fleet kernel (with its
    schema defaults and seeded init) and the conservation invariants
    the SDC defense checks. Idempotent."""
    from .. import fleet, integrity

    fleet.register_kernel("mhd", make_mhd_kernel())
    fleet.register_kernel_spec(
        "mhd", cell_data=mhd_cell_data(jnp.float32),
        fields_in=MHD_ALL, fields_out=MHD_ALL, params=(0.05,),
        init=mhd_default_init)
    integrity.register_conserved("mhd", MHD_ALL, periodic_axes=(0, 1, 2))


ZOO_INFO = {
    "kernel": "mhd",
    "fields": MHD_ALL,
    "ghost_deps": {**{n: MHD_HYDRO for n in MHD_HYDRO},
                   **{n: MHD_BFIELD for n in MHD_BFIELD}},
    "conserved": MHD_ALL,
    "model": "GridMHD",
    "description": ("finite-volume ideal-MHD-style: Rusanov hydro "
                    "fluxes (hydro ghosts) + conservative B cleaning "
                    "(B ghosts)"),
}
