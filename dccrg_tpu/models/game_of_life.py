"""Conway's game of life on the distributed grid.

The reference's minimal stencil application
(examples/simple_game_of_life.cpp: cell struct :20-32, main loop
:91-159): each cell counts live neighbors over the radius-1 cube
neighborhood and applies the standard rules. Used as the end-to-end
proof of mapping + partition + halo exchange + stencil iteration.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..grid import Grid


def life_kernel(cell, nbr, offs, mask):
    """Count live neighbors and apply the rules (the loop at
    examples/simple_game_of_life.cpp:103-120, as one gather)."""
    total = jnp.sum(jnp.where(mask, nbr["live"], 0), axis=1)
    live = jnp.where((total == 3) | ((cell["live"] > 0) & (total == 2)), 1, 0)
    return {"live": live, "total": total}


class GameOfLife:
    def __init__(self, length=(10, 10, 1), periodic=(False, False, False), mesh=None,
                 partition=None, max_refinement_level=0):
        """``max_refinement_level > 0`` allows running the game on a
        refined grid (the reference's refined GoL variants,
        tests/game_of_life/refined.cpp, refined2d.cpp): live counting
        runs over the AMR neighbor lists unchanged."""
        self.grid = (
            Grid(cell_data={"live": jnp.int32, "total": jnp.int32})
            .set_initial_length(length)
            .set_periodic(*periodic)
            .set_maximum_refinement_level(max_refinement_level)
            .set_neighborhood_length(1)
            .initialize(mesh, partition=partition)
        )

    def refine(self, ids) -> None:
        """Refine the given cells and commit; new children inherit the
        parent's live state (refined.cpp re-initializes equivalently)."""
        for c in np.atleast_1d(ids):
            self.grid.refine_completely(c)
        self.grid.stop_refining()
        self.grid.assign_children_from_parents(fields=["live"])
        self.grid.clear_refined_unrefined_data()

    def set_alive(self, ids) -> None:
        self.grid.set("live", np.asarray(ids, dtype=np.uint64),
                      np.ones(len(ids), dtype=np.int32))

    def alive_cells(self) -> np.ndarray:
        cells = self.grid.get_cells()
        live = self.grid.get("live", cells)
        return cells[live > 0]

    def step(self) -> None:
        self.grid.update_copies_of_remote_neighbors(fields=["live"])
        self.grid.apply_stencil(life_kernel, ["live"], ["live", "total"])

    def run(self, n_steps: int) -> None:
        """``n_steps`` generations as ONE device program: exchange +
        rules per generation inside the fused step loop (the TPU form
        of the reference's overlapped main loop,
        examples/game_of_life.cpp)."""
        self.grid.run_steps(life_kernel, ["live"], ["live", "total"],
                            n_steps, exchange_fields=["live"])
