"""Conway's game of life on the distributed grid.

The reference's minimal stencil application
(examples/simple_game_of_life.cpp: cell struct :20-32, main loop
:91-159): each cell counts live neighbors over the radius-1 cube
neighborhood and applies the standard rules. Used as the end-to-end
proof of mapping + partition + halo exchange + stencil iteration.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..grid import Grid


def life_kernel(cell, nbr, offs, mask):
    """Count live neighbors and apply the rules (the loop at
    examples/simple_game_of_life.cpp:103-120, as one gather)."""
    total = jnp.sum(jnp.where(mask, nbr["live"], 0), axis=1)
    live = jnp.where((total == 3) | ((cell["live"] > 0) & (total == 2)), 1, 0)
    return {"live": live, "total": total}


class GameOfLife:
    def __init__(self, length=(10, 10, 1), periodic=(False, False, False), mesh=None,
                 partition=None):
        self.grid = (
            Grid(cell_data={"live": jnp.int32, "total": jnp.int32})
            .set_initial_length(length)
            .set_periodic(*periodic)
            .set_neighborhood_length(1)
            .initialize(mesh, partition=partition)
        )

    def set_alive(self, ids) -> None:
        self.grid.set("live", np.asarray(ids, dtype=np.uint64),
                      np.ones(len(ids), dtype=np.int32))

    def alive_cells(self) -> np.ndarray:
        cells = self.grid.get_cells()
        live = self.grid.get("live", cells)
        return cells[live > 0]

    def step(self) -> None:
        self.grid.update_copies_of_remote_neighbors(fields=["live"])
        self.grid.apply_stencil(life_kernel, ["live"], ["live", "total"])
