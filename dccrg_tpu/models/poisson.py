"""Poisson solver on the distributed grid.

Equivalent of the reference's tests/poisson solver family
(tests/poisson/poisson_solve.hpp): the Numerical-Recipes 2.7.6
biconjugate scheme over grid cells, with per-cell per-direction
geometry factors so the same solver covers uniform, AMR, and stretched
grids, plus boundary (Dirichlet) cells and skipped cells
(poisson_solve.hpp:222-258's cells / cells_to_skip / boundary
classification).

Fidelity notes:

- Geometry factors: per direction, the offset to the face neighbor's
  center is half_own + half_neighbor (missing or skipped neighbors act
  as equal-size cells with no coupling); f_dir = ±2/(offset · total)
  and the diagonal is -Σf (set_scaling_factor,
  poisson_solve.hpp:691-830). A direction with 4 finer face neighbors
  applies f/4 to each (:332-338).
- The matrix is asymmetric under AMR, so the solve iterates both A·p0
  and transpose(A)·p1 — the transpose using the *neighbor's* factor of
  the opposite direction (:422-466).
- The reference iterates `update_copies_of_remote_neighbors` on a
  sub-selection of fields chosen by ``Poisson_Cell::transfer_switch``
  (poisson_solve.hpp:47-141); here that boundary is the ``fields``
  argument of the halo update — each iteration moves only p0/p1,
  factors move once at preparation (the GEOMETRY transfer, :968-970).
- Global dot products (MPI_Allreduce, :341-349) are jnp reductions
  over the sharded fields: XLA inserts the all-reduce.
- Cells neither solved nor skipped are boundary cells: their solution
  feeds the initial residual (Dirichlet data, initialize_solver
  :986-1041) and is never changed.

``DensePoissonSolver`` is the uniform fast path on DenseGrid for
large problems (the serial reference solver's role,
tests/poisson/reference_poisson_solve.hpp, doubles as the parity
check).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..grid import DEFAULT_NEIGHBORHOOD_ID, Grid
from ..dense import DenseGrid
from ..neighbors import face_masks, make_neighborhood

POISSON_NEIGHBORHOOD_ID = 0xB01550

# cell_type values (poisson_solve.hpp:143-149)
SOLVE_CELL, BOUNDARY_CELL, SKIP_CELL = 1, 0, -1

def poisson_fields(dtype=jnp.float32):
    """The solver's field spec at a given float width. The reference
    solver is double-precision throughout (poisson_solve.hpp:47-141);
    ``poisson_fields(jnp.float64)`` is the parity mode (CPU: tests run
    with JAX_ENABLE_X64). TPU runs use float32: expect the residual
    floor near 1e-6 relative instead of 1e-12 — see
    tests/test_poisson.py::test_f64_parity_mode for the measured
    budget."""
    f = jnp.dtype(dtype)
    return {
        "rhs": f, "solution": f,
        "r0": f, "r1": f,
        "p0": f, "p1": f, "Ap0": f,
        "fxp": f, "fxn": f,
        "fyp": f, "fyn": f,
        "fzp": f, "fzn": f,
        "scale": f, "ctype": jnp.int32, "ilen": jnp.int32,
    }


POISSON_FIELDS = poisson_fields(jnp.float32)

_F_NAMES = (("fxp", "fxn"), ("fyp", "fyn"), ("fzp", "fzn"))
_GEOMETRY_FIELDS = [n for pair in _F_NAMES for n in pair] + ["scale", "ctype", "ilen"]


def _matvec_kernel(transpose: bool):
    """A·p (or transpose(A)·p) over face neighbors
    (poisson_solve.hpp:296-338 forward, :422-466 transpose)."""
    src = "p1" if transpose else "p0"

    def kernel(cell, nbr, offs, mask):
        p_c = cell[src]
        p_n = nbr[src]
        faces = face_masks(cell["ilen"][:, None], nbr["ilen"], offs, mask)
        if transpose:
            # transpose reads A[n, c]: the /4 averaging applies when
            # THIS cell is the finer side of n's face (:463-466)
            finer = cell["ilen"][:, None] < nbr["ilen"]
        else:
            # finer face neighbors: 4 per direction, each weighted f/4
            finer = nbr["ilen"] < cell["ilen"][:, None]
        w = jnp.where(finer, 0.25, 1.0) * (nbr["ctype"] != SKIP_CELL)
        acc = cell["scale"] * p_c
        for d, (face_pos, face_neg) in enumerate(faces):
            if transpose:
                # neighbor's factor of the opposite direction (:436-455)
                m_pos = nbr[_F_NAMES[d][1]]
                m_neg = nbr[_F_NAMES[d][0]]
            else:
                m_pos = cell[_F_NAMES[d][0]][:, None]
                m_neg = cell[_F_NAMES[d][1]][:, None]
            acc = acc + jnp.sum(jnp.where(face_pos, m_pos * w * p_n, 0.0), axis=1)
            acc = acc + jnp.sum(jnp.where(face_neg, m_neg * w * p_n, 0.0), axis=1)
        return {"out": acc}

    def wrapped(cell, nbr, offs, mask):
        out = kernel(cell, nbr, offs, mask)
        # only solve cells carry the result; others stay 0
        return {("r1" if transpose else "Ap0"):
                jnp.where(cell["ctype"] == SOLVE_CELL, out["out"], 0.0)}

    return wrapped


class PoissonSolver:
    """Biconjugate Poisson solve on the general (AMR-capable) grid.

    Either wraps an existing grid declared with POISSON_FIELDS (the
    reference solver is grid-agnostic the same way,
    poisson_solve.hpp:252-258) or builds a uniform one from ``length``.
    """

    def __init__(self, length=None, mesh=None, periodic=(True, True, True),
                 dtype=jnp.float32, grid: Grid | None = None,
                 max_refinement_level: int = 0):
        if grid is not None:
            self.grid = grid
        else:
            self.grid = (
                Grid(cell_data=poisson_fields(dtype))
                .set_initial_length(length)
                .set_periodic(*periodic)
                .set_maximum_refinement_level(max_refinement_level)
                .set_neighborhood_length(1)
                .initialize(mesh)
            )
        missing = [n for n in POISSON_FIELDS if n not in self.grid.fields]
        if missing:
            raise ValueError(f"grid lacks Poisson fields {missing}")
        self.dtype = self.grid.fields["solution"][1]
        self._np_dtype = np.dtype(self.dtype)
        if POISSON_NEIGHBORHOOD_ID not in self.grid.neighborhoods:
            self.grid.add_neighborhood(POISSON_NEIGHBORHOOD_ID, make_neighborhood(0))
        self._fwd = _matvec_kernel(transpose=False)
        self._tr = _matvec_kernel(transpose=True)
        self._prepared_epoch = None
        self._solve_mask = None

    def _cache_key(self, cells_to_solve, cells_to_skip):
        return (
            self.grid.plan.epoch,
            None if cells_to_solve is None
            else np.asarray(cells_to_solve, np.uint64).tobytes(),
            None if cells_to_skip is None
            else np.asarray(cells_to_skip, np.uint64).tobytes(),
        )

    # -- field setup ---------------------------------------------------

    def set_rhs(self, values) -> None:
        cells = self.grid.get_cells()
        self.grid.set("rhs", cells, np.asarray(values, dtype=self._np_dtype))

    def set_rhs_from(self, fn) -> None:
        """rhs from a function of cell centers."""
        cells = self.grid.get_cells()
        centers = self.grid.geometry.get_center(cells)
        self.set_rhs(fn(centers[:, 0], centers[:, 1], centers[:, 2]))

    def solution(self) -> np.ndarray:
        return self.grid.get("solution", self.grid.get_cells())

    # -- preparation (cache_system_info, poisson_solve.hpp:838-970) ----

    def prepare(self, cells_to_solve=None, cells_to_skip=None) -> None:
        """Classify cells and compute geometry factors for the current
        structure epoch."""
        g = self.grid
        cells = g.get_cells()
        n = len(cells)

        def positions(ids, what):
            ids = np.asarray(ids, dtype=np.uint64)
            pos = np.searchsorted(cells, ids)
            bad = (pos >= n) | (cells[np.minimum(pos, n - 1)] != ids)
            if bad.any():
                raise ValueError(f"{what} contains unknown cell id(s): "
                                 f"{ids[bad][:5].tolist()}")
            return pos

        ctype = np.full(n, BOUNDARY_CELL, dtype=np.int32)
        if cells_to_solve is None:
            ctype[:] = SOLVE_CELL
        else:
            ctype[positions(cells_to_solve, "cells_to_solve")] = SOLVE_CELL
        if cells_to_skip is not None:
            pos = positions(cells_to_skip, "cells_to_skip")
            # solve wins over skip (poisson_solve.hpp:230-233)
            ctype[pos[ctype[pos] != SOLVE_CELL]] = SKIP_CELL

        lengths = g.geometry.get_length(cells).astype(np.float64)
        half = lengths / 2.0
        ilen = g.mapping.get_cell_length_in_indices(cells).astype(np.int64)

        # host face classification over the face-hood neighbor lists
        nl = g.plan.hoods[POISSON_NEIGHBORHOOD_ID].lists
        src, nbr_pos = nl.of_source, np.searchsorted(cells, nl.of_neighbor)
        offs = nl.of_offset
        ok = ctype[nbr_pos] != SKIP_CELL
        faces = face_masks(ilen[src], ilen[nbr_pos], offs, ok)
        # per (cell, direction, sign): non-skip face neighbor half size
        has = np.zeros((n, 3, 2), dtype=bool)
        nbr_half = np.zeros((n, 3, 2), dtype=np.float64)
        for d in range(3):
            for s, mm in enumerate(faces[d]):
                has[src[mm], d, s] = True
                nbr_half[src[mm], d, s] = half[nbr_pos[mm], d]

        # offsets to neighbor centers; missing/skipped neighbors act as
        # equal-size cells (poisson_solve.hpp:716-723)
        pos_off = half + np.where(has[:, :, 0], nbr_half[:, :, 0], half)
        neg_off = half + np.where(has[:, :, 1], nbr_half[:, :, 1], half)
        tot = pos_off + neg_off
        f_pos = np.where(has[:, :, 0], 2.0 / (pos_off * tot), 0.0)
        f_neg = np.where(has[:, :, 1], 2.0 / (neg_off * tot), 0.0)
        scale = -(f_pos.sum(axis=1) + f_neg.sum(axis=1))

        for d in range(3):
            g.set(_F_NAMES[d][0], cells, f_pos[:, d].astype(self._np_dtype))
            g.set(_F_NAMES[d][1], cells, f_neg[:, d].astype(self._np_dtype))
        g.set("scale", cells, scale.astype(self._np_dtype))
        g.set("ctype", cells, ctype)
        g.set("ilen", cells, ilen.astype(np.int32))
        # the GEOMETRY transfer: factors valid for the whole epoch
        g.update_copies_of_remote_neighbors(
            neighborhood_id=POISSON_NEIGHBORHOOD_ID, fields=_GEOMETRY_FIELDS
        )

        self._solve_mask = g.local_row_mask().astype(
            jnp.dtype(self._np_dtype)
        ) * (g.data["ctype"] == SOLVE_CELL)
        self._prepared_epoch = self._cache_key(cells_to_solve, cells_to_skip)

    # -- reductions ----------------------------------------------------

    def _dot(self, a: str, b: str) -> float:
        return float(jnp.sum(self.grid.data[a] * self.grid.data[b] * self._solve_mask))

    def _exchange_p(self, fields) -> None:
        self.grid.update_copies_of_remote_neighbors(
            neighborhood_id=POISSON_NEIGHBORHOOD_ID, fields=fields
        )

    def _apply(self, transpose: bool) -> None:
        fields_in = ["p1" if transpose else "p0", "ilen", "ctype", "scale"] + [
            n for pair in _F_NAMES for n in pair
        ]
        self.grid.apply_stencil(
            self._tr if transpose else self._fwd,
            fields_in,
            ["r1" if transpose else "Ap0"],
            neighborhood_id=POISSON_NEIGHBORHOOD_ID,
        )

    # -- solve (poisson_solve.hpp:252-523) -----------------------------

    def _fused_solve_fn(self):
        """The ENTIRE biconjugate solve as one XLA program: initial
        residual, then a lax.while_loop whose body fuses the p0/p1
        halo exchange, both matvecs, the three global dots (XLA
        all-reduces — the reference pays an MPI_Allreduce per
        iteration, poisson_solve.hpp:341-349) and the vector updates.
        No host round-trips until the result is read.

        Tables, static fields and the solve mask are ARGUMENTS of the
        compiled program (cached in the grid's shape-keyed program
        cache), so bucket-stable structure epochs reuse it instead of
        recompiling."""
        g = self.grid
        fields_in_fwd = ["p0", "ilen", "ctype", "scale"] + [
            n for pair in _F_NAMES for n in pair
        ]
        fields_in_tr = ["p1"] + fields_in_fwd[1:]
        fwd_fn, fwd_tables = g._make_stencil(
            self._fwd, tuple(fields_in_fwd), ("Ap0",),
            POISSON_NEIGHBORHOOD_ID, False)
        tr_fn, tr_tables = g._make_stencil(
            self._tr, tuple(fields_in_tr), ("r1",),
            POISSON_NEIGHBORHOOD_ID, False)
        _s1, _f1, fused1, _nt1 = g._exchange_programs(POISSON_NEIGHBORHOOD_ID, 1)
        sx1, rx1 = g._pair_tables_device(POISSON_NEIGHBORHOOD_ID, ("p0",))
        start2_j, finish2_j, fused2, _nt2 = g._exchange_programs(
            POISSON_NEIGHBORHOOD_ID, 2)
        sx2, rx2 = g._pair_tables_device(POISSON_NEIGHBORHOOD_ID, ("p0", "p1"))
        statics = tuple(g.data[n] for n in fields_in_fwd[1:])
        mask = self._solve_mask
        single = g.n_dev == 1
        # the split-overlap treatment of the per-iteration matvecs
        # (the step loop's DCCRG_GHOST_SPLIT discipline): start the
        # p0/p1 halo collective, run both matvecs on PRE-exchange
        # state — rows whose gather reads no refreshed ghost are
        # final — land the halos, then re-run ONLY the rows feeding
        # the exchanged field (grid._make_outer_repass). Accelerator-
        # default like the step overlap (DCCRG_OVERLAP), ghost-split
        # opt-out shared (DCCRG_GHOST_SPLIT=0 = this pre-PR program)
        from ..grid import ghost_split_enabled

        rp_fwd = rp_tr = None
        if not single and g._use_overlap() and ghost_split_enabled():
            rp_fwd = g._make_outer_repass(
                self._fwd, tuple(fields_in_fwd), ("Ap0",),
                POISSON_NEIGHBORHOOD_ID, ("p0",))
            rp_tr = g._make_outer_repass(
                self._tr, tuple(fields_in_tr), ("r1",),
                POISSON_NEIGHBORHOOD_ID, ("p1",))
        overlap = rp_fwd is not None and rp_tr is not None
        rpf_fn, rpf_t = rp_fwd if overlap else (None, ())
        rpt_fn, rpt_t = rp_tr if overlap else (None, ())
        nf, nt = len(fwd_tables), len(tr_tables)
        n1, n2 = len(sx1) + len(rx1), len(sx2) + len(rx2)
        n_sx2 = len(sx2)
        nrf, nrt = len(rpf_t), len(rpt_t)
        ns = len(statics)
        bindings = (*fwd_tables, *tr_tables, *sx1, *rx1, *sx2, *rx2,
                    *rpf_t, *rpt_t, mask, *statics)
        key = ("poisson_fused", self._fwd, self._tr, single,
               nf, nt, n1, n2, ns, g.plan.L, g.plan.R,
               overlap)
        prog = g._program_cache.get(key)
        if prog is not None:
            return lambda *state: prog(*state, *bindings)

        def run(solution, rhs, scratch, rtol, max_iterations, *rest):
            fwd_t = rest[:nf]
            tr_t = rest[nf:nf + nt]
            ex1 = rest[nf + nt:nf + nt + n1]
            ex2 = rest[nf + nt + n1:nf + nt + n1 + n2]
            base = nf + nt + n1 + n2
            rpf_tables = rest[base:base + nrf]
            rpt_tables = rest[base + nrf:base + nrf + nrt]
            mask = rest[base + nrf + nrt]
            statics = rest[base + nrf + nrt + 1:]

            def fwd(*args):
                return fwd_fn(*fwd_t, *args)

            def tr(*args):
                return tr_fn(*tr_t, *args)

            def exchange1(p0):
                return fused1(*ex1, p0)

            def exchange2(p0, p1):
                return fused2(*ex2, p0, p1)

            def exchange2_start(p0, p1):
                return start2_j(*ex2[:n_sx2], p0, p1)

            def exchange2_finish(bufs, p0, p1):
                return finish2_j(*ex2[n_sx2:], *bufs, p0, p1)

            def dot(a, b):
                return jnp.sum(a * b * mask)

            # initial residual (initialize_solver, :986-1041)
            p0 = solution
            if not single:
                (p0,) = exchange1(p0)
            (Ap0,) = fwd(p0, *statics, scratch)
            r0 = (rhs - Ap0) * mask
            dot_r0 = dot(r0, r0)
            b2 = dot(rhs, rhs)
            target = jnp.maximum(
                rtol * rtol * jnp.maximum(jnp.maximum(b2, dot_r0), 1e-30),
                1e-30,
            )

            def cond(s):
                return s["go"] & (s["residual"] > target) & (
                    s["it"] < max_iterations
                )

            def body(s):
                p0, p1 = s["p0"], s["p1"]
                if overlap:
                    # sends read local rows only: the collective flies
                    # under both bulk matvecs, then only the refreshed
                    # rows are redone (the ghost-split overlap)
                    bufs = exchange2_start(p0, p1)
                    (Ap0,) = fwd(p0, *statics, s["Ap0"])
                    (Atp1,) = tr(p1, *statics, s["r1"])
                    p0, p1 = exchange2_finish(bufs, p0, p1)
                    (Ap0,) = rpf_fn(*rpf_tables, p0, *statics, Ap0)
                    (Atp1,) = rpt_fn(*rpt_tables, p1, *statics, Atp1)
                else:
                    if not single:
                        p0, p1 = exchange2(p0, p1)
                    (Ap0,) = fwd(p0, *statics, s["Ap0"])
                    (Atp1,) = tr(p1, *statics, s["r1"])
                dot_p = dot(p1, Ap0)
                go = (dot_p != 0) & (s["dot_r"] != 0)
                safe_p = jnp.where(dot_p == 0, 1, dot_p)
                alpha = jnp.where(go, s["dot_r"] / safe_p, 0.0)
                solution = s["solution"] + alpha * p0 * mask
                r0 = s["r0"] - alpha * Ap0 * mask
                r1 = s["r1"] - alpha * Atp1 * mask
                new_dot_r = dot(r0, r1)
                safe_r = jnp.where(s["dot_r"] == 0, 1, s["dot_r"])
                beta = jnp.where(go, new_dot_r / safe_r, 0.0)
                p0 = (r0 + beta * p0) * mask
                p1 = (r1 + beta * p1) * mask
                return {
                    "solution": jnp.where(go, solution, s["solution"]),
                    "r0": jnp.where(go, r0, s["r0"]),
                    "r1": jnp.where(go, r1, s["r1"]),
                    "p0": jnp.where(go, p0, s["p0"]),
                    "p1": jnp.where(go, p1, s["p1"]),
                    "Ap0": Ap0,
                    "dot_r": jnp.where(go, new_dot_r, s["dot_r"]),
                    "residual": jnp.where(go, dot(r0, r0), s["residual"]),
                    "it": s["it"] + jnp.where(go, 1, 0),
                    "go": go,
                }

            init = {
                "solution": solution, "r0": r0, "r1": r0, "p0": r0,
                "p1": r0, "Ap0": Ap0, "dot_r": dot_r0, "residual": dot_r0,
                "it": jnp.int32(0), "go": jnp.bool_(True),
            }
            out = jax.lax.while_loop(cond, body, init)
            return out["solution"], out["it"], out["residual"]

        prog = jax.jit(run)
        g._program_cache[key] = prog
        return lambda *state: prog(*state, *bindings)

    def solve(self, rtol: float = 1e-5, max_iterations: int = 1000,
              cells_to_solve=None, cells_to_skip=None,
              cache_is_up_to_date: bool = False, fused: bool = True) -> dict:
        g = self.grid
        # re-prepare only when the structure epoch or the cell
        # classification changed (the reference's cache_is_up_to_date
        # flag, poisson_solve.hpp:241-245, made automatic: the key
        # includes plan.epoch, which changes on refine/balance)
        del cache_is_up_to_date
        if self._cache_key(cells_to_solve, cells_to_skip) != self._prepared_epoch:
            self.prepare(cells_to_solve, cells_to_skip)
        mask = self._solve_mask
        # with no Dirichlet classification every boundary closure —
        # periodic wrap or missing-neighbor zero flux alike — is
        # Neumann, so the operator always has the constant nullspace
        singular = cells_to_solve is None and cells_to_skip is None
        if singular:
            self._remove_mean("rhs")

        if fused:
            run = self._fused_solve_fn()
            sol, it, residual = run(
                self.grid.data["solution"], self.grid.data["rhs"],
                self.grid.data["Ap0"],
                jnp.asarray(rtol, dtype=self.dtype),
                jnp.int32(max_iterations),
            )
            self.grid.data["solution"] = sol
            if singular:
                self._remove_mean("solution")
            return {"iterations": int(it),
                    "residual": float(np.sqrt(max(float(residual), 0.0)))}

        # r0 = rhs - A·solution, with boundary cells' solution as data
        # (initialize_solver, poisson_solve.hpp:986-1041)
        g.data["p0"] = g.data["solution"]
        self._exchange_p(["p0"])
        self._apply(transpose=False)
        g.data["r0"] = (g.data["rhs"] - g.data["Ap0"]) * mask
        g.data["r1"] = g.data["r0"]
        g.data["p0"] = g.data["r0"]
        g.data["p1"] = g.data["r0"]

        # r1 == r0 here, so one reduction serves all three initial dots
        dot_r = residual = r2_0 = self._dot("r0", "r0")
        b2 = self._dot("rhs", "rhs")
        # pure-Dirichlet/Laplace problems have zero rhs on solve cells;
        # fall back to the initial residual so rtol still applies
        target = max(rtol * rtol * max(b2, r2_0, 1e-30), 1e-30)
        iterations = 0
        while residual > target and iterations < max_iterations:
            self._exchange_p(["p0", "p1"])
            self._apply(transpose=False)
            dot_p = self._dot("p1", "Ap0")
            if dot_p == 0.0 or dot_r == 0.0:
                break
            alpha = dot_r / dot_p
            g.data["solution"] = g.data["solution"] + alpha * g.data["p0"] * mask
            g.data["r0"] = g.data["r0"] - alpha * g.data["Ap0"] * mask
            # r1 -= alpha · transpose(A)·p1 (:415-470); the kernel
            # writes A^T p1 into r1's slot, so stash r1 first
            r1_old = g.data["r1"]
            self._apply(transpose=True)
            g.data["r1"] = r1_old - alpha * g.data["r1"] * mask
            new_dot_r = self._dot("r0", "r1")
            beta = new_dot_r / dot_r
            g.data["p0"] = (g.data["r0"] + beta * g.data["p0"]) * mask
            g.data["p1"] = (g.data["r1"] + beta * g.data["p1"]) * mask
            dot_r = new_dot_r
            residual = self._dot("r0", "r0")
            iterations += 1
        if singular:
            self._remove_mean("solution")
        return {"iterations": iterations, "residual": float(np.sqrt(max(residual, 0.0)))}

    def _remove_mean(self, field: str) -> None:
        total = float(jnp.sum(self.grid.data[field] * self._solve_mask))
        cnt = float(jnp.sum(self._solve_mask))
        self.grid.data[field] = (
            self.grid.data[field] - (total / max(cnt, 1.0)) * self._solve_mask
        )


class DensePoissonSolver:
    """CG on the dense fast path (uniform grids, big problems)."""

    def __init__(self, length, mesh=None, periodic=(True, True, True), dtype=jnp.float32):
        self.grid = DenseGrid(
            length,
            {"p": dtype, "Ap": dtype},
            mesh=mesh,
            periodic=periodic,
            cell_length=tuple(1.0 / l for l in length),
        )
        self.periodic = tuple(periodic)
        self.dtype = jnp.dtype(dtype)
        rdx2 = (1.0 / np.asarray(self.grid.cell_length) ** 2).astype(self.dtype)
        grid = self.grid

        def lap_kernel(b):
            from jax import lax
            from ..dense import AXES

            p = b["p"]
            core = tuple(slice(1, s - 1) for s in p.shape)
            nloc = tuple(s - 2 for s in p.shape)
            out = jnp.zeros_like(p[core])
            for d in range(3):
                lo = tuple(
                    slice(0 if dd == d else 1, (s - 2 if dd == d else s - 1))
                    for dd, s in enumerate(p.shape)
                )
                hi = tuple(
                    slice(2 if dd == d else 1, (s if dd == d else s - 1))
                    for dd, s in enumerate(p.shape)
                )
                t_lo = p[lo] - p[core]
                t_hi = p[hi] - p[core]
                if not grid.periodic[d]:
                    # homogeneous Neumann: drop missing-neighbor terms,
                    # matching PoissonSolver's masked stencil
                    pos = lax.axis_index(AXES[d])
                    g = pos * nloc[d] + lax.broadcasted_iota(jnp.int32, nloc, d)
                    t_lo = jnp.where(g > 0, t_lo, 0.0)
                    t_hi = jnp.where(g < grid.length[d] - 1, t_hi, 0.0)
                out = out + rdx2[d] * (t_lo + t_hi)
            return {"Ap": out}

        self._matvec = self.grid.make_step(lap_kernel, ("p",), ("Ap",), halo=1)

    def solve(self, rhs, rtol=1e-5, max_iterations=1000):
        def mv(p):
            arrays = {"p": p, "Ap": p}
            return self._matvec(arrays)["Ap"]

        return cg_solve(mv, rhs, singular=all(self.periodic),
                        dtype=self.dtype, rtol=rtol,
                        max_iterations=max_iterations)


def cg_solve(matvec, rhs, singular, dtype, rtol=1e-5, max_iterations=1000):
    """Plain conjugate gradients over an SPD ``matvec`` callable —
    shared by DensePoissonSolver (XLA dense step) and
    PallasPoissonSolver (Pallas kernel matvec). ``singular`` removes
    the constant null space (all-periodic Laplacian): the RHS and the
    solution are projected to zero mean."""
    rhs = jnp.asarray(rhs, dtype=dtype)
    if singular:
        rhs = rhs - jnp.mean(rhs)
    x = jnp.zeros_like(rhs)
    r = rhs
    p = r
    rs = float(jnp.sum(r * r))
    target = max(rtol * rtol * float(jnp.sum(rhs * rhs)), 1e-30)
    it = 0
    while rs > target and it < max_iterations:
        Ap = matvec(p)
        pAp = float(jnp.sum(p * Ap))
        if pAp == 0.0:
            break
        alpha = rs / pAp
        x = x + alpha * p
        r = r - alpha * Ap
        rs_new = float(jnp.sum(r * r))
        p = r + (rs_new / rs) * p
        rs = rs_new
        it += 1
    if singular:
        x = x - jnp.mean(x)
    return x, {"iterations": it, "residual": float(np.sqrt(max(rs, 0.0)))}
