"""Poisson solver on the distributed grid.

Equivalent of the reference's tests/poisson solver family
(tests/poisson/poisson_solve.hpp): an iterative Krylov solve of
nabla^2 u = rhs over grid cells, where each iteration updates ghost
copies of the search direction and forms the 7-point Laplacian matvec
from face neighbors.

Fidelity notes:

- The reference iterates its Numerical-Recipes biconjugate scheme with
  ``update_copies_of_remote_neighbors`` on a *sub-selection of cell
  fields* chosen by ``Poisson_Cell::transfer_switch``
  (poisson_solve.hpp:47-141): only the field needed per phase crosses
  the network. Here that boundary is the ``fields=[...]`` argument of
  the halo update — each CG iteration moves only ``p``.
- Global dot products (MPI_Allreduce at poisson_solve.hpp:278-360) are
  jnp reductions over the sharded field arrays: XLA inserts the
  all-reduce.
- The matvec runs through the gather-based stencil engine over a
  user-declared face-only neighborhood (``add_neighborhood``), the
  same mechanism apps use for custom stencils (dccrg.hpp:6491-6663).
- Missing face neighbors (non-periodic boundaries) contribute no flux
  (homogeneous Neumann); periodic problems project out the constant
  nullspace, like the reference's failure_* handling of the singular
  system.

``DensePoissonSolver`` is the uniform fast path on DenseGrid for
large problems.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..grid import DEFAULT_NEIGHBORHOOD_ID, Grid
from ..dense import DenseGrid
from ..neighbors import make_neighborhood

POISSON_NEIGHBORHOOD_ID = 0xB01550


class PoissonSolver:
    """CG Poisson solve on the general (AMR-capable) grid.

    v1 restriction: refinement level 0 (the reference's uniform
    variants; its AMR poisson uses per-direction geometry factors,
    planned for the general path later).
    """

    def __init__(self, length, mesh=None, periodic=(True, True, True), dtype=jnp.float32):
        self.grid = (
            Grid(cell_data={"rhs": dtype, "solution": dtype, "r": dtype, "p": dtype, "Ap": dtype})
            .set_initial_length(length)
            .set_periodic(*periodic)
            .set_neighborhood_length(1)
            .initialize(mesh)
        )
        self.grid.add_neighborhood(POISSON_NEIGHBORHOOD_ID, make_neighborhood(0))
        self.periodic = tuple(periodic)
        # uniform level-0 cell lengths
        self.dx = self.grid.geometry.get_length(np.uint64(1))
        rdx2 = (1.0 / self.dx**2).astype(np.float32)
        self._rdx2 = jnp.asarray(rdx2)
        # local-row validity mask for global reductions
        mask = np.zeros((self.grid.n_dev, self.grid.plan.R), dtype=np.float32)
        for d in range(self.grid.n_dev):
            mask[d, : self.grid.plan.n_local[d]] = 1.0
        self._mask = jax.device_put(jnp.asarray(mask), self.grid._sharding())
        self._matvec_kernel = self._make_matvec()

    def _make_matvec(self):
        rdx2 = self._rdx2

        def kernel(cell, nbr, offs, mask):
            p_c = cell["p"]
            p_n = nbr["p"]
            # per-slot 1/dx^2 by face axis (offset is nonzero along
            # exactly one axis for the face neighborhood)
            fac = jnp.sum(jnp.where(offs != 0, rdx2[None, None, :], 0.0), axis=-1)
            terms = jnp.where(mask, fac * (p_n - p_c[:, None]), 0.0)
            return {"Ap": jnp.sum(terms, axis=1)}

        return kernel

    # -- field setup ---------------------------------------------------

    def set_rhs(self, values) -> None:
        cells = self.grid.get_cells()
        self.grid.set("rhs", cells, np.asarray(values, dtype=np.float32))

    def set_rhs_from(self, fn) -> None:
        """rhs from a function of cell centers."""
        cells = self.grid.get_cells()
        centers = self.grid.geometry.get_center(cells)
        self.set_rhs(fn(centers[:, 0], centers[:, 1], centers[:, 2]))

    def solution(self) -> np.ndarray:
        return self.grid.get("solution", self.grid.get_cells())

    # -- reductions ----------------------------------------------------

    def _dot(self, a: str, b: str) -> float:
        return float(jnp.sum(self.grid.data[a] * self.grid.data[b] * self._mask))

    def _matvec(self) -> None:
        """Ap <- A p: ghost update of p only, then the face stencil."""
        self.grid.update_copies_of_remote_neighbors(
            neighborhood_id=POISSON_NEIGHBORHOOD_ID, fields=["p"]
        )
        self.grid.apply_stencil(
            self._matvec_kernel, ["p"], ["Ap"], neighborhood_id=POISSON_NEIGHBORHOOD_ID
        )

    def _remove_mean(self, field: str) -> None:
        total = float(jnp.sum(self.grid.data[field] * self._mask))
        n = float(np.sum(self.grid.plan.n_local))
        self.grid.data[field] = self.grid.data[field] - (total / n) * self._mask

    # -- CG (the reference's iteration at poisson_solve.hpp:278-360) ---

    def solve(self, rtol: float = 1e-5, max_iterations: int = 1000) -> dict:
        g = self.grid
        singular = all(self.periodic)
        if singular:
            self._remove_mean("rhs")
        # r = rhs - A x ; start from x = 0 unless a warm start is set
        g.data["p"] = g.data["solution"]
        self._matvec()
        g.data["r"] = (g.data["rhs"] - g.data["Ap"]) * self._mask
        g.data["p"] = g.data["r"]
        rs = self._dot("r", "r")
        b2 = self._dot("rhs", "rhs")
        target = max(rtol * rtol * max(b2, 1e-30), 1e-30)
        iterations = 0
        while rs > target and iterations < max_iterations:
            self._matvec()
            pAp = self._dot("p", "Ap")
            if pAp == 0.0:
                break
            alpha = rs / pAp
            g.data["solution"] = g.data["solution"] + alpha * g.data["p"] * self._mask
            g.data["r"] = g.data["r"] - alpha * g.data["Ap"] * self._mask
            rs_new = self._dot("r", "r")
            beta = rs_new / rs
            g.data["p"] = (g.data["r"] + beta * g.data["p"]) * self._mask
            rs = rs_new
            iterations += 1
        if singular:
            self._remove_mean("solution")
        return {"iterations": iterations, "residual": float(np.sqrt(max(rs, 0.0)))}


class DensePoissonSolver:
    """CG on the dense fast path (uniform grids, big problems)."""

    def __init__(self, length, mesh=None, periodic=(True, True, True), dtype=jnp.float32):
        self.grid = DenseGrid(
            length,
            {"p": dtype, "Ap": dtype},
            mesh=mesh,
            periodic=periodic,
            cell_length=tuple(1.0 / l for l in length),
        )
        self.periodic = tuple(periodic)
        rdx2 = (1.0 / np.asarray(self.grid.cell_length) ** 2).astype(np.float32)
        grid = self.grid

        def lap_kernel(b):
            from jax import lax
            from ..dense import AXES

            p = b["p"]
            core = tuple(slice(1, s - 1) for s in p.shape)
            nloc = tuple(s - 2 for s in p.shape)
            out = jnp.zeros_like(p[core])
            for d in range(3):
                lo = tuple(
                    slice(0 if dd == d else 1, (s - 2 if dd == d else s - 1))
                    for dd, s in enumerate(p.shape)
                )
                hi = tuple(
                    slice(2 if dd == d else 1, (s if dd == d else s - 1))
                    for dd, s in enumerate(p.shape)
                )
                t_lo = p[lo] - p[core]
                t_hi = p[hi] - p[core]
                if not grid.periodic[d]:
                    # homogeneous Neumann: drop missing-neighbor terms,
                    # matching PoissonSolver's masked stencil
                    pos = lax.axis_index(AXES[d])
                    g = pos * nloc[d] + lax.broadcasted_iota(jnp.int32, nloc, d)
                    t_lo = jnp.where(g > 0, t_lo, 0.0)
                    t_hi = jnp.where(g < grid.length[d] - 1, t_hi, 0.0)
                out = out + rdx2[d] * (t_lo + t_hi)
            return {"Ap": out}

        self._matvec = self.grid.make_step(lap_kernel, ("p",), ("Ap",), halo=1)

    def solve(self, rhs, rtol=1e-5, max_iterations=1000):
        singular = all(self.periodic)
        rhs = jnp.asarray(rhs, dtype=jnp.float32)
        if singular:
            rhs = rhs - jnp.mean(rhs)
        x = jnp.zeros_like(rhs)
        arrays = {"p": x, "Ap": x}  # working set for the matvec step
        r = rhs
        p = r
        rs = float(jnp.sum(r * r))
        target = max(rtol * rtol * float(jnp.sum(rhs * rhs)), 1e-30)
        it = 0
        while rs > target and it < max_iterations:
            arrays["p"] = p
            arrays = self._matvec(arrays)
            Ap = arrays["Ap"]
            pAp = float(jnp.sum(p * Ap))
            if pAp == 0.0:
                break
            alpha = rs / pAp
            x = x + alpha * p
            r = r - alpha * Ap
            rs_new = float(jnp.sum(r * r))
            p = r + (rs_new / rs) * p
            rs = rs_new
            it += 1
        if singular:
            x = x - jnp.mean(x)
        return x, {"iterations": it, "residual": float(np.sqrt(max(rs, 0.0)))}
