"""Application layer: the reference's examples/tests solvers as JAX
programs on top of the grid (SURVEY.md section L6)."""
