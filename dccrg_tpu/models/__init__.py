"""Application layer: the reference's examples/tests solvers as JAX
programs on top of the grid (SURVEY.md section L6) — and the model
zoo's registry surface.

Importing this package registers every zoo kernel with the fleet
(``"mhd"``, ``"vlasov"`` — schemas, seeded default inits and
conservation invariants included), so ``python -m dccrg_tpu.fleet``
job files and :class:`~dccrg_tpu.fleet.FleetJob` constructions can
name any zoo kernel without further setup; the fleet layer lazy-
imports this package on an unknown kernel name for the same effect.
The classic solver classes (``GridAdvection``, ``AdvectionSolver``,
``PoissonSolver``, ``GridMHD``, ``GridVlasov``, ...) stay LAZY — the
heavier submodules only import when an attribute is first touched.
"""

from __future__ import annotations

from . import mhd, vlasov

# kernel registration happens at package import (the zoo contract the
# fleet CLI depends on); both calls are idempotent
mhd.register()
vlasov.register()

#: the zoo table: fleet-kernel name -> structured info (fields, ghost
#: dependencies, conserved quantities, the model class name)
MODEL_ZOO = {
    "mhd": dict(mhd.ZOO_INFO),
    "vlasov": dict(vlasov.ZOO_INFO),
    "diffuse": {
        "kernel": "diffuse", "fields": ("rho",),
        "ghost_deps": None, "conserved": ("rho",),
        "model": None,
        "description": "neighbor-coupling relaxation (fleet workhorse)",
    },
    "advect_x": {
        "kernel": "advect_x", "fields": ("rho",),
        "ghost_deps": None, "conserved": ("rho",),
        "model": None,
        "description": "first-order upwind advection along +x",
    },
}

# attribute -> (submodule, attr) for the lazy classic solvers
_LAZY = {
    "AdvectionSolver": ("advection", "AdvectionSolver"),
    "GridAdvection": ("advection", "GridAdvection"),
    "PallasRotationAdvection": ("advection", "PallasRotationAdvection"),
    "PoissonSolver": ("poisson", "PoissonSolver"),
    "DensePoissonSolver": ("poisson", "DensePoissonSolver"),
    "GridMHD": ("mhd", "GridMHD"),
    "GridVlasov": ("vlasov", "GridVlasov"),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(f".{entry[0]}", __name__)
    val = getattr(mod, entry[1])
    globals()[name] = val
    return val


def ensure_registered() -> None:
    """Idempotent zoo registration hook (registration already ran at
    package import; this is the explicit spelling for lazy callers)."""
    mhd.register()
    vlasov.register()


def available_models() -> list:
    """The zoo, one dict per registered kernel: ``name``, ``fields``,
    ``ghost_deps`` (None = undeclared/conservative), ``conserved``
    fields, the multi-device ``model`` class name and a one-line
    description — the README model table's source of truth."""
    return [dict(info, name=name)
            for name, info in sorted(MODEL_ZOO.items())]
