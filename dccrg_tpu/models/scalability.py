"""Synthetic weak/strong-scaling harness.

Equivalent of the reference's scalability suite
(tests/scalability/scalability.cpp:39-160): a configurable cost model —
bytes transferred per cell and artificial compute per cell — measuring
solve time vs halo-exchange time per step, plus a sweep driver over
parallelism (tests/scalability/run_tests.py:28-39 sweeps MPI process
counts; here the sweep varies device-mesh size).

The per-cell payload is ``floats_per_cell`` f32 lanes (the reference's
``bytes_per_cell`` knob); the solve does ``work_iters`` dependent
fused multiply-adds per lane inside ``lax.fori_loop`` (the reference's
busy-wait ``solution_time`` knob, :61-75 — a compute knob XLA cannot
constant-fold because each iteration depends on the previous).
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..grid import Grid
from ..utils import PhaseTimer
from ..utils.profiling import halo_bytes_per_update


class ScalabilityModel:
    def __init__(self, length=(16, 16, 16), floats_per_cell: int = 8,
                 work_iters: int = 64, mesh=None, partition=None,
                 neighborhood_length: int = 1):
        self.floats_per_cell = int(floats_per_cell)
        self.work_iters = int(work_iters)
        self.grid = (
            Grid(cell_data={"payload": ((self.floats_per_cell,), jnp.float32)})
            .set_initial_length(length)
            .set_periodic(True, True, True)
            .set_neighborhood_length(neighborhood_length)
            .initialize(mesh, partition=partition)
        )
        cells = self.grid.get_cells()
        rng = np.random.default_rng(0)
        self.grid.set(
            "payload", cells,
            rng.standard_normal((len(cells), self.floats_per_cell)).astype(np.float32),
        )
        self.timer = PhaseTimer()
        iters = self.work_iters

        def kernel(cell, nbr, offs, mask):
            # average of neighbors (consumes the halo) ...
            cnt = jnp.maximum(jnp.sum(mask, axis=1), 1)
            avg = jnp.sum(jnp.where(mask[..., None], nbr["payload"], 0.0), axis=1)
            avg = avg / cnt[:, None].astype(jnp.float32)
            # ... then a dependent FMA chain per lane: the tunable
            # compute cost (scalability.cpp:61-75's busy loop)
            def body(_, v):
                return v * jnp.float32(1.0000001) + jnp.float32(1e-7)
            out = lax.fori_loop(0, iters, body, 0.5 * (cell["payload"] + avg))
            return {"payload": out}

        self._kernel = kernel

    def step(self) -> None:
        """One timed step: halo exchange then synthetic solve (the
        reference times these phases separately, scalability.cpp:124-160)."""
        g = self.grid
        with self.timer.phase("halo"):
            g.update_copies_of_remote_neighbors(fields=["payload"])
            jax.block_until_ready(g.data["payload"])
        with self.timer.phase("solve"):
            g.apply_stencil(self._kernel, ["payload"], ["payload"])
            jax.block_until_ready(g.data["payload"])

    def run(self, steps: int = 10, warmup: int = 2) -> dict:
        """Report per-step timings + transfer volume, the reference's
        printed metrics (scalability.cpp:124-160)."""
        for _ in range(warmup):
            self.step()
        self.timer.reset()
        t0 = time.perf_counter()
        for _ in range(steps):
            self.step()
        total = time.perf_counter() - t0
        rep = self.timer.report()
        n_cells = len(self.grid.get_cells())
        return {
            "n_devices": self.grid.n_dev,
            "n_cells": n_cells,
            "steps": steps,
            "solve_s_per_step": rep["solve"]["total"] / steps,
            "halo_s_per_step": rep["halo"]["total"] / steps,
            "total_s_per_step": total / steps,
            "cell_updates_per_sec": n_cells * steps / total,
            "halo_bytes_per_step": halo_bytes_per_update(self.grid),
        }


def run_sweep(device_counts=None, length=(16, 16, 16), floats_per_cell: int = 8,
              work_iters: int = 64, steps: int = 10, weak: bool = False) -> list:
    """Strong (fixed size) or weak (size grows with devices in x)
    scaling sweep over device counts — the run_tests.py driver."""
    from jax.sharding import Mesh

    devices = jax.devices()
    if device_counts is None:
        device_counts = [n for n in (1, 2, 4, 8) if n <= len(devices)]
    dropped = [n for n in device_counts if n > len(devices)]
    if dropped:
        import sys
        print(f"skipping device counts {dropped}: only {len(devices)} "
              f"device(s) available", file=sys.stderr)
        device_counts = [n for n in device_counts if n <= len(devices)]
    results = []
    for n in device_counts:
        dims = (length[0] * n, length[1], length[2]) if weak else length
        mesh = Mesh(np.array(devices[:n]), ("dev",))
        model = ScalabilityModel(
            dims, floats_per_cell=floats_per_cell, work_iters=work_iters, mesh=mesh
        )
        results.append(model.run(steps=steps))
    return results


if __name__ == "__main__":
    import argparse
    import json
    import os

    # the image's site hook pre-sets JAX_PLATFORMS=axon at interpreter
    # startup; honor an explicit CPU request (virtual multi-device mesh)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--length", type=int, nargs=3, default=[16, 16, 16])
    p.add_argument("--floats-per-cell", type=int, default=8)
    p.add_argument("--work-iters", type=int, default=64)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--weak", action="store_true")
    p.add_argument("--devices", type=int, nargs="*", default=None)
    a = p.parse_args()
    for row in run_sweep(a.devices, tuple(a.length), a.floats_per_cell,
                         a.work_iters, a.steps, a.weak):
        print(json.dumps(row))
