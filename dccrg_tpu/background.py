"""Zero-stall serving primitives: background work that used to block
the step loop.

Two multi-second host-side pauses sit directly on the serving path:

- an AMR recommit epoch (the structure-plan rebuild after
  ``stop_refining``) blocks the step loop for its whole build — 9-12 s
  at 192^3 even on the native fast path — although the *build* is pure
  host/ctypes work that releases the GIL and depends only on the new
  (cells, owner) structure, never on the field bytes the loop keeps
  advancing;
- a periodic checkpoint save serializes the CRC + fsync + rename file
  work against the next quantum's dispatch, although the payload
  snapshot is a set of immutable jax array references the moment it is
  taken.

This module holds the two pieces of machinery that take both off the
critical path, opt-in via environment flags and bitwise-neutral when
off:

:class:`PlanBuildWorker` (``DCCRG_BG_RECOMMIT=1``) builds the *next*
structure epoch's plan on a worker thread against a third
:class:`~dccrg_tpu.hybrid.PlanArena` generation (live plan and the
transaction rollback snapshot stay protected) while stepping continues
on the live plan; :meth:`Grid.run_steps
<dccrg_tpu.grid.Grid.run_steps>` / ``GridBatch.step`` install the
finished plan at the next step/quantum boundary (`grid.bg_install`),
bitwise-identical to the synchronous build. Grown arena tables are
prefaulted inside the worker, so the shape-transition cold-first-touch
stall never hits the step loop either.

:class:`AsyncSaver` (``DCCRG_ASYNC_SAVE=1``) runs a single-controller
checkpoint write (temp stream + CRC sidecar + fsync + rename) on a
writer thread against a :func:`freeze_grid` snapshot, overlapped with
the next quantum's dispatch; :meth:`AsyncSaver.drain` is the barrier
every store reader (rollback, resume, GC, emergency save) takes before
trusting the directory. Multi-process saves overlap too, through
:func:`freeze_grid_mp`: the two-phase commit's barriers are pure
coordination-service gRPC (thread-safe off the main thread), and the
snapshot replaces the save path's two device touch points — shard
reads become host-copy reads, and the commit-time CRC exchange rides
the coordination KV instead of a device all-gather — so the writer
thread never dispatches jax work.
"""

from __future__ import annotations

import copy
import os
import threading
import time

import numpy as np

from . import telemetry


def bg_recommit_enabled() -> bool:
    """``DCCRG_BG_RECOMMIT=1``: defer AMR recommits to a background
    plan-build worker, swapping at the next step/quantum boundary."""
    return os.environ.get("DCCRG_BG_RECOMMIT") == "1"


def async_save_enabled() -> bool:
    """``DCCRG_ASYNC_SAVE=1``: overlap single-controller checkpoint
    writes with the next quantum's dispatch."""
    return os.environ.get("DCCRG_ASYNC_SAVE") == "1"


# ---------------------------------------------------------------------
# background plan builds
# ---------------------------------------------------------------------

class PlanBuildWorker:
    """One in-flight background structure-plan build for a grid.

    The worker runs ``grid._construct_plan`` — the pure build half of a
    restructure, no install — on a daemon thread. The build reads only
    structural inputs captured at submit time (the new cells/owners and
    the dirty-set hint) plus the grid's build caches (sticky capacity
    memo, hybrid stream-reuse cache, plan arena), none of which the
    step loop touches; the arena itself is lock-protected because the
    LIVE plan's lazy table thunks may take buffers concurrently. At
    most one build is in flight per grid (submission is serialized by
    the grid's drain discipline), so the caches see the same ordered
    build sequence as the synchronous path and the resulting plan is
    bitwise identical to it (pinned by tests/test_bgrecommit.py).

    A worker crash (any exception, injected faults included) is
    captured, not raised: the swap point falls back to the inline
    rebuild."""

    def __init__(self, grid, cells, owner, changed_hint):
        self.cells = cells
        self.owner = owner
        self.changed_hint = changed_hint
        self.plan = None
        self.error = None
        self.done = threading.Event()
        self._grid = grid
        self.thread = threading.Thread(
            target=self._work, name="dccrg-bg-recommit", daemon=True)

    def start(self) -> "PlanBuildWorker":
        telemetry.inc("dccrg_recommit_bg_builds_total")
        self.thread.start()
        return self

    def _work(self) -> None:
        grid = self._grid
        arena = getattr(grid, "_plan_arena", None)
        t0 = time.perf_counter()
        try:
            # fresh arena allocations fault their pages INSIDE the
            # worker (prefault=True touches them at take time even for
            # sparsely-written tables), so a shape transition's
            # cold-first-touch cost never lands on the step loop
            if arena is not None:
                arena.prefault = True
            self.plan = grid._construct_plan(
                self.cells, self.owner, self.changed_hint)
            # derive the lazy post-swap tables (roll plans) here too,
            # so the first dispatch after the swap pays nothing
            grid._prewarm_plan(self.plan)
        except BaseException as e:  # noqa: BLE001 - surfaced at swap
            self.error = e
            telemetry.inc("dccrg_recommit_bg_errors_total")
        finally:
            if arena is not None:
                arena.prefault = False
            telemetry.record_span("recommit.bg", time.perf_counter() - t0)
            telemetry.observe("dccrg_recommit_bg_build_seconds",
                              time.perf_counter() - t0)
            self.done.set()

    def ready(self) -> bool:
        return self.done.is_set()

    def wait(self, timeout=None) -> bool:
        """Block until the build finishes; the blocked time is the
        step loop's residual stall and lands in the stall histogram."""
        if not self.done.is_set():
            t0 = time.perf_counter()
            self.done.wait(timeout)
            telemetry.observe("dccrg_recommit_stall_seconds",
                              time.perf_counter() - t0)
        return self.done.is_set()


# ---------------------------------------------------------------------
# async checkpoint writes
# ---------------------------------------------------------------------

def freeze_grid(grid, fields=None):
    """An immutable snapshot of ``grid`` for a background checkpoint
    writer: a shallow copy whose field data is pulled to HOST numpy
    arrays here, on the caller's thread — the device-side extraction
    is the save's one synchronization point with dispatch, and once
    extracted the payload bytes are immutable. The writer thread then
    does pure numpy + file I/O and NEVER dispatches jax work: two
    threads tracing/dispatching concurrently can deadlock the runtime
    (observed on this jax: a background ``Array.__getitem__`` against
    the main thread's ``block_until_ready``), and the device queue is
    the serving path's, not the writer's. Plans are rebuilt wholesale
    and mapping/topology/geometry/fields never change after
    initialize, so everything else shallow-shares — the writer
    serializes exactly the bytes a synchronous save at the submit
    point would have (the bitwise pin in tests/test_bgrecommit.py).
    ``fields`` restricts the pull to a save's sub-schema (the delta
    path: a static field's bytes never cross for a dirty-field
    save)."""
    snap = copy.copy(grid)
    names = sorted(grid.data) if fields is None else sorted(fields)
    snap.data = {n: np.asarray(grid.data[n]) for n in names}
    # the plan object itself is never edited in place, but a HYBRID
    # plan's row_of_pos is an arena-held view: once the live grid
    # restructures twice, the frozen plan is no longer in any
    # arena.begin protect set and its buffers recycle under the
    # writer — which would gather wrong rows into a checkpoint whose
    # CRC sidecar (computed from the written bytes) still verifies.
    # Pin the one layout array the save path reads through
    # _host_rows with a private copy; cells/owner/n_local are plain
    # per-epoch arrays and the rest of the layout is never touched
    # by a save.
    snap.plan = copy.copy(grid.plan)
    snap.plan.row_of_pos = np.array(grid.plan.row_of_pos, copy=True)
    dirty = getattr(grid, "_ckpt_dirty", None)
    snap._ckpt_dirty = set(dirty) if isinstance(dirty, set) else dirty
    # the snapshot must never alias live background machinery: a save
    # of the frozen copy may not drain/install the real grid's builds
    snap._bg_build = None
    return snap


def freeze_grid_mp(grid, fields=None, variable=None):
    """A :func:`freeze_grid` analogue for MULTI-PROCESS grids, so the
    two-phase-commit save can run on an :class:`AsyncSaver` writer
    thread. The mp save path touches devices in exactly two places,
    and the snapshot removes both on the caller's thread:

    - payload reads go through ``grid._shard_read`` (per-device
      addressable-shard pulls): the snapshot pulls every LOCAL device's
      shard to host numpy here and overrides ``_shard_read`` with a
      host-copy reader;
    - variable-field counts go through ``checkpoint._replicated_pull``
      (a chunked psum device gather — an XLA collective): the snapshot
      precomputes the pull for every count field of ``variable`` into
      ``_frozen_pulls``, which ``_replicated_pull`` serves first.

    The remaining cross-rank traffic — the prepare/commit/done
    barriers and the commit-time CRC exchange — is coordination-service
    gRPC: the snapshot sets ``_ckpt_crc_via_kv`` so the CRC table
    crosses through KV records posted before the commit barrier
    (:func:`~dccrg_tpu.checkpoint._post_run_crcs_kv`) instead of
    ``comm.host_all_gather``. Collective discipline is unchanged:
    EVERY rank must freeze and submit the same save (the barriers
    still rendezvous, just on writer threads), and the save-attempt
    epoch advances on the SOURCE grid through ``_mp_epoch_src`` so a
    later save never reuses a barrier tag. Field arrays are immutable
    jax values, so the frozen bytes are exactly what a synchronous
    save at the freeze point would write."""
    snap = copy.copy(grid)
    names = sorted(grid.data) if fields is None else sorted(fields)
    host: dict = {}
    for n in names:
        arr = grid.data[n]
        by_dev = {}
        for s in arr.addressable_shards:
            d = int(s.index[0].start or 0)
            if grid._proc_local_dev[d]:
                by_dev[d] = np.asarray(s.data)[0]
        host[n] = by_dev

    def _frozen_shard_read(field, dev, rows, _host=host):
        by_dev = _host[field]
        sample = next(iter(by_dev.values()))
        out = np.empty((len(dev),) + sample.shape[1:],
                       dtype=sample.dtype)
        for d in np.unique(dev):
            m = dev == d
            out[m] = by_dev[int(d)][rows[m]]
        return out

    snap._shard_read = _frozen_shard_read
    snap._frozen_pulls = {}
    if variable:
        from . import checkpoint as checkpoint_mod
        cells = np.asarray(grid.get_cells())
        for cf in sorted(set(variable.values())):
            snap._frozen_pulls[cf] = checkpoint_mod._replicated_pull(
                grid, cf, cells)
    snap._ckpt_crc_via_kv = True
    snap._mp_epoch_src = grid
    # same layout pin as freeze_grid: the save reads row_of_pos
    # through _host_rows, and an arena recycle must not rot it
    snap.plan = copy.copy(grid.plan)
    snap.plan.row_of_pos = np.array(grid.plan.row_of_pos, copy=True)
    dirty = getattr(grid, "_ckpt_dirty", None)
    snap._ckpt_dirty = set(dirty) if isinstance(dirty, set) else dirty
    snap._bg_build = None
    return snap


class AsyncSaver:
    """At most one checkpoint write in flight, with a drain barrier.

    ``submit(fn)`` drains any previous write (surfacing its failure at
    this save boundary — the async analogue of a synchronous save
    raising in place), then runs ``fn`` on a fresh daemon thread under
    a ``ckpt.async`` span. ``drain()`` joins the writer and re-raises
    its exception after invoking the submitter's ``on_fail`` hook
    (which un-publishes speculative bookkeeping: the rollback target /
    delta parent must fall back to the last DURABLE checkpoint). The
    time a drain actually blocks is the checkpoint stall that survived
    overlapping and lands in ``dccrg_ckpt_stall_seconds``."""

    def __init__(self):
        self._thread = None
        self._box = None

    def submit(self, fn, on_fail=None, label="") -> None:
        self.drain()
        telemetry.inc("dccrg_ckpt_async_saves_total")
        box = {"error": None, "label": label,
               "on_fail": [on_fail] if on_fail is not None else []}

        def work():
            t0 = time.perf_counter()
            try:
                with telemetry.span("ckpt.async", tags={"path": label}):
                    fn()
            except BaseException as e:  # noqa: BLE001 - rethrown at drain
                box["error"] = e
            finally:
                # the write's true wall, measured ON the writer — what
                # the overlap benches compare dispatch windows against
                telemetry.observe("dccrg_ckpt_async_write_seconds",
                                  time.perf_counter() - t0)

        t = threading.Thread(target=work, name="dccrg-async-save",
                             daemon=True)
        self._thread, self._box = t, box
        t.start()

    def add_on_fail(self, cb) -> None:
        """Chain another failure hook onto the in-flight write (the
        runner adds its rollback-target restore after the store
        recorded its own parent/dirty reset). No-op when nothing is
        pending."""
        if self._box is not None:
            self._box["on_fail"].append(cb)

    def pending(self) -> bool:
        return self._thread is not None

    def drain(self) -> None:
        """The store-reader barrier: returns only once no write is in
        flight, re-raising a captured writer failure (after its
        ``on_fail`` bookkeeping rollbacks ran, oldest first). Called
        from the writer thread itself (post-save work chained onto the
        same submission, e.g. retention GC) it is a no-op — that work
        is already ordered after the write."""
        t, box = self._thread, self._box
        if t is None or t is threading.current_thread():
            return
        t0 = time.perf_counter()
        t.join()
        stall = time.perf_counter() - t0
        if stall > 0:
            telemetry.observe("dccrg_ckpt_stall_seconds", stall)
        self._thread = self._box = None
        err = box["error"]
        if err is not None:
            telemetry.inc("dccrg_ckpt_async_errors_total")
            for cb in box["on_fail"]:
                cb(err)
            raise err


# ---------------------------------------------------------------------
# warm-start pre-compiles
# ---------------------------------------------------------------------

class PrewarmWorker:
    """One abortable background pre-compile sweep (the warm-start
    pool's thread; see dccrg_tpu/warmstart.py).

    Same discipline as :class:`PlanBuildWorker`: a daemon thread whose
    failure is captured, never raised into the serving path, and whose
    work is bitwise-neutral to live dispatches — ``fn(abort)`` must
    only *compile* (``jit.lower(...).compile()`` traces and compiles
    without allocating state buffers or dispatching device work, so it
    never contends with the main thread's ``block_until_ready`` — the
    deadlock class the PR-13 writer-thread rule exists for). ``fn``
    checks ``abort`` between items; :meth:`stop` sets it and joins, so
    a scheduler teardown (or a GC pass that must not race an in-flight
    compile) has a bounded wait."""

    def __init__(self, fn, name: str = "dccrg-warm-prewarm"):
        self.fn = fn
        self.error = None
        self.done = threading.Event()
        self.abort = threading.Event()
        self.thread = threading.Thread(target=self._work, name=name,
                                       daemon=True)

    def start(self) -> "PrewarmWorker":
        self.thread.start()
        return self

    def _work(self) -> None:
        t0 = time.perf_counter()
        try:
            self.fn(self.abort)
        except BaseException as e:  # noqa: BLE001 - surfaced via .error
            self.error = e
            telemetry.inc("dccrg_prewarm_errors_total")
        finally:
            telemetry.observe("dccrg_prewarm_seconds",
                              time.perf_counter() - t0)
            self.done.set()

    def ready(self) -> bool:
        return self.done.is_set()

    def wait(self, timeout=None) -> bool:
        self.done.wait(timeout)
        return self.done.is_set()

    def stop(self, timeout=5.0) -> bool:
        """Abort and join (bounded). Returns whether the thread
        actually finished — a straggler mid-XLA-compile is left to
        die with the process (daemon), never blocked on forever."""
        self.abort.set()
        return self.wait(timeout)
