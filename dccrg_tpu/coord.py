"""Distributed coordination: timeout-guarded barriers, guarded
``jax.distributed`` bring-up, and cross-rank trip consensus.

The reference dccrg leans on MPI's collective semantics: a rank that
dies makes the next collective fail *somewhere*, and the job scheduler
reaps the rest. JAX multi-controller gives no such courtesy —
``sync_global_devices`` simply never returns if a participant is gone,
and a checkpoint save that died on one rank leaves every other rank
blocked forever with a half-written file on disk. This module is the
coordination layer the multi-process paths (checkpoint two-phase
commit, :class:`~dccrg_tpu.resilience.ResilientRunner`) thread their
rank synchronization through:

- :func:`barrier` — a tagged, timeout-guarded barrier. Real meshes go
  through the ``jax.distributed`` coordination-service barrier (which
  has a deadline) when available, else ``sync_global_devices`` under a
  watchdog thread. Either way a lost rank surfaces as a typed
  :class:`BarrierTimeoutError` *naming the tag* within the configured
  bound (``DCCRG_BARRIER_TIMEOUT``, default 120 s) instead of hanging
  the job. Fault injection (:meth:`~dccrg_tpu.faults.FaultPlan
  .barrier_hang`) exercises the watchdog deterministically on a single
  controller.
- :func:`distributed_init` — ``jax.distributed.initialize`` with
  bounded retry + exponential backoff for the transient failures of
  real cluster bring-up (coordination service not listening yet, port
  races), raising :class:`DistributedInitError` when the budget is
  spent.
- :func:`trip_consensus` — all-reduces a per-rank trip code over the
  mesh (max), so rollback decisions that originate on ONE host (a
  ``MutationAbortedError``, an OOM, a watchdog hook) are taken by
  EVERY rank together: all ranks roll back to the same checkpoint
  instead of deadlocking in a barrier half of them never reach.
  :func:`broadcast_fatal` is its deadline-bounded best-effort variant
  for a rank that is about to die and must not hang while saying so.
- :class:`CheckpointCommitError` — the abort signal of the two-phase
  multi-process checkpoint commit (checkpoint._save_process_slice):
  raised by the committing rank when a slice is missing or fails its
  CRC, with the previous checkpoint still intact under the final name.
- :func:`seal_record` / :func:`unseal_record` / :func:`kv_barrier` —
  the primitives the distributed-AMR commit (dccrg_tpu/distamr.py)
  rides: CRC-framed KV records (a torn write convicts as
  :class:`TornRecordError`, never acts), and a presence-key barrier
  with an EXPLICIT participant set that doubles as a small all-gather,
  watches an epoch fence (:class:`StaleFenceError` — a SIGSTOP zombie
  that wakes after the fleet moved on must lose) and a peer abort
  marker (:class:`RemoteAbortError` — distributed rollback propagates
  faster than a timeout), and upgrades expiry to
  :class:`PeerDeadError` under a membership lease view.
- :class:`Membership` — elastic fleet membership: every rank writes a
  heartbeat lease into the coordination KV store
  (``DCCRG_HEARTBEAT_S`` cadence), and peers classify each other
  live/suspect/dead from the OBSERVED lease age (the observer's own
  clock ages a value it saw stop changing — no cross-host clock
  comparison, ``DCCRG_LEASE_S`` is the death bound).
  :meth:`Membership.poll` / :meth:`Membership.detect_dead_ranks` are
  deadline-bounded through :func:`run_with_deadline` so a wedged KV
  read can never block the step loop — on expiry the caller keeps the
  last view. A :class:`Membership` registered via
  :func:`set_membership` upgrades barrier timeouts: a barrier whose
  peer is DEAD by lease raises :class:`PeerDeadError` *naming the
  rank* (a :class:`BarrierTimeoutError` subclass, so every existing
  handler keeps working) instead of timing out and blaming a tag.
- :class:`InMemoryKV` / :class:`CoordKV` — the KV store the
  membership leases and the scheduler's job leases ride.
  ``create()`` is first-writer-wins (the coordination service's
  ``allow_overwrite=False`` IS a compare-and-set), which is what
  makes a double-reclaim race resolve to exactly one winner.

Everything degrades to a no-op on a single controller, so
single-process code pays one ``process_count()`` check per call.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np

from . import faults

logger = logging.getLogger("dccrg_tpu.coord")

DEFAULT_BARRIER_TIMEOUT = 120.0

# Barrier ids must be unique AND align across ranks. A PER-TAG counter
# (not one global sequence) keeps them aligned even when ranks' barrier
# histories diverge on OTHER tags — e.g. a save that failed mid-protocol
# on one rank consumed that save's tags only, so an unrelated barrier
# still matches. Within one tag the contract is: every rank calls it the
# same number of times; protocols that can fail asymmetrically BETWEEN
# calls of the same tag must fold an attempt epoch into the tag itself
# (the two-phase checkpoint save tags carry `#<attempt>` for exactly
# this — a collective retry re-aligns by construction).
_tag_seq: dict = {}


def _next_seq(tag: str) -> int:
    seq = _tag_seq.get(tag, 0)
    _tag_seq[tag] = seq + 1
    return seq


class BarrierTimeoutError(RuntimeError):
    """A tagged barrier did not complete within its bound: a
    participating rank is gone (process death, hung collective, dead
    accelerator tunnel). ``tag``/``timeout`` carry the details."""

    def __init__(self, tag: str, timeout: float):
        super().__init__(
            f"barrier {tag!r} did not complete within {timeout:g}s: a "
            "participating rank is unreachable (process death, hung "
            "collective, or dead accelerator tunnel)")
        self.tag = tag
        self.timeout = timeout


class DistributedInitError(RuntimeError):
    """``jax.distributed.initialize`` failed after every bounded
    retry."""


class CheckpointCommitError(RuntimeError):
    """The two-phase multi-process checkpoint commit aborted: one or
    more ranks' slices are missing or fail their CRC32, so the new file
    was NOT published and the previous checkpoint stays bitwise intact
    under the final name. ``ranks`` names the writers whose slices
    failed (the dead/torn ranks)."""

    def __init__(self, msg, ranks=()):
        super().__init__(msg)
        self.ranks = sorted({int(r) for r in ranks})


class TornRecordError(RuntimeError):
    """A sealed coordination record (:func:`seal_record`) failed its
    CRC32 frame — the half-written KV record of a writer that died (or
    was SIGKILLed) mid-write. The reader must treat the record as
    absent-and-poisoned: abort the protocol round, never act on the
    payload. ``key`` names the record when known."""

    def __init__(self, key: str = "", detail: str = ""):
        super().__init__(
            f"coordination record {key!r} is torn (CRC mismatch"
            f"{': ' + detail if detail else ''})")
        self.key = key


class StaleFenceError(RuntimeError):
    """An epoch-fenced coordination point observed the fence move past
    the epoch this participant entered under: this process is a ZOMBIE
    — it was stopped (SIGSTOP, GC pause, swapped host) while the
    surviving ranks completed (or re-formed) the protocol round and
    advanced the fence. The only safe move is a full local rollback to
    the pre-round state; rejoining happens at the NEW fence through the
    fleet layer, never by finishing the stale round."""

    def __init__(self, tag: str, expected, observed):
        super().__init__(
            f"fenced point {tag!r}: fence moved {expected!r} -> "
            f"{observed!r} while this rank was inside the round — this "
            "rank is a zombie; rolling back to the pre-round state")
        self.tag = tag
        self.expected = expected
        self.observed = observed


class RemoteAbortError(RuntimeError):
    """A PEER rank aborted the distributed transaction this rank is
    inside and posted an abort marker — the distributed-rollback fast
    path: every waiting participant raises this immediately instead of
    burning its barrier timeout. ``rank`` names the aborter (-1 when
    the marker was unreadable), ``reason`` its message."""

    def __init__(self, tag: str, rank: int = -1, reason: str = ""):
        super().__init__(
            f"distributed commit {tag!r}: peer rank {rank} aborted"
            f"{' (' + reason + ')' if reason else ''} — rolling back")
        self.tag = tag
        self.rank = int(rank)
        self.reason = reason


class PeerDeadError(BarrierTimeoutError):
    """A coordination point failed because one or more PEER RANKS are
    dead by membership lease (no heartbeat within ``DCCRG_LEASE_S``) —
    the detecting side of a host failure. Subclasses
    :class:`BarrierTimeoutError` so every existing timeout handler
    keeps working, but ``ranks`` names the culprits instead of the
    barrier tag having to take the blame."""

    def __init__(self, tag: str, timeout: float, ranks, lease_s=None):
        ranks = sorted({int(r) for r in ranks})
        lease = "" if lease_s is None else f" within {lease_s:g}s"
        RuntimeError.__init__(
            self,
            f"barrier {tag!r}: peer rank(s) {ranks} are DEAD by "
            f"membership lease (no heartbeat observed{lease}); their "
            "jobs are reclaimable by the survivors")
        self.tag = tag
        self.timeout = timeout
        self.ranks = ranks


def barrier_timeout(default: float = DEFAULT_BARRIER_TIMEOUT) -> float:
    """The ``DCCRG_BARRIER_TIMEOUT`` env knob: seconds before a
    coordination barrier gives up on its peers."""
    try:
        return float(os.environ.get("DCCRG_BARRIER_TIMEOUT", "") or default)
    except ValueError:
        return default


def run_with_deadline(fn, timeout: float, name: str = "deadline"):
    """Run ``fn()`` on a daemon worker thread bounded by ``timeout``
    seconds — the shared watchdog primitive behind the barrier sync,
    the fatal-trip broadcast and the supervision layer's step/save
    deadlines. Returns ``(finished, result, error)``; on expiry the
    worker is abandoned (``finished=False``) — a wedged callee cannot
    be cancelled, only reported — and the caller decides whether that
    is a typed error or a logged shrug."""
    box, err = [], []
    done = threading.Event()

    def _work():
        try:
            box.append(fn())
        except BaseException as e:  # noqa: BLE001 - caller's to re-raise
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_work, daemon=True, name=f"dccrg-{name}")
    t.start()
    if not done.wait(float(timeout)):
        return False, None, None
    return True, (box[0] if box else None), (err[0] if err else None)


def _coordination_client():
    """The jax.distributed coordination-service client, or None (not
    initialized, or jax internals drifted)."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals drift
        return None


def barrier(tag: str, timeout: float | None = None) -> None:
    """Synchronize every process at a tagged point, or raise
    :class:`BarrierTimeoutError` naming the tag within ``timeout``
    seconds (default: :func:`barrier_timeout`).

    Single-controller meshes return immediately. Real multi-process
    meshes prefer the coordination-service barrier (deadline built in);
    when only ``sync_global_devices`` is available it runs on a daemon
    watchdog thread so the caller can never block past the bound (the
    hung thread is abandoned — a barrier that lost a rank is not
    recoverable anyway, only reportable). An injected
    :meth:`~dccrg_tpu.faults.FaultPlan.barrier_hang` replaces the sync
    with a sleep, exercising the watchdog machinery deterministically
    without a cluster."""
    timeout = barrier_timeout() if timeout is None else float(timeout)
    faults.fire("coord.barrier", tag=tag)
    hang = faults.take_barrier_hang(tag)
    import jax

    # the membership fast path: a peer the heartbeat leases already
    # declared dead will never reach this barrier — raise the typed
    # error NAMING the rank now instead of burning the full timeout
    # (in-process fleets register a membership too, so the check
    # precedes the single-controller early return)
    _raise_if_peer_dead(tag, timeout, poll=False)
    real = jax.process_count() > 1
    if not real and hang is None:
        return
    seq = _next_seq(tag)
    if hang is None:
        client = _coordination_client()
        if client is not None:
            try:
                client.wait_at_barrier(f"dccrg:{tag}:{seq}",
                                       int(timeout * 1000))
                return
            except Exception as e:
                # the service reports a lost rank either as our
                # deadline expiring or as the peer's task failing its
                # heartbeat — both mean the same thing to the caller
                msg = str(e)
                if ("DEADLINE_EXCEEDED" in msg or "Barrier failed" in msg
                        or "heartbeat timeout" in msg):
                    _raise_if_peer_dead(tag, timeout, poll=True)
                    raise BarrierTimeoutError(tag, timeout) from e
                raise

    # watchdog-thread path: sync_global_devices has no deadline of its
    # own, and the injected hang must exercise this same machinery
    def _sync():
        if hang is not None:
            # a simulated lost rank: the sync never happens; a
            # finite hang_s below the timeout models a slow-but-
            # alive peer the barrier should survive
            time.sleep(min(hang, timeout + 30.0))
        elif real:  # pragma: no cover - needs a real cluster
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"dccrg:{tag}:{seq}")

    finished, _res, err = run_with_deadline(_sync, timeout,
                                            f"barrier:{tag}")
    if not finished:
        _raise_if_peer_dead(tag, timeout, poll=True)
        raise BarrierTimeoutError(tag, timeout)
    if err is not None:
        raise err


def distributed_init(coordinator_address=None, num_processes=None,
                     process_id=None, *, retries: int = 3,
                     backoff: float = 0.5, **kwargs) -> None:
    """``jax.distributed.initialize`` with bounded retry + exponential
    backoff: real cluster bring-up fails transiently (the coordinator
    is not listening yet, a port race, a slow DNS answer) and the raw
    call just dies. Raises :class:`DistributedInitError` with the last
    failure chained once the budget is spent."""
    import jax

    last: Exception | None = None
    for attempt in range(retries + 1):
        try:
            faults.fire("coord.init", attempt=attempt)
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                **kwargs)
            return
        except Exception as e:  # noqa: BLE001 - retried, then surfaced
            last = e
            if attempt < retries:
                delay = backoff * (2 ** attempt)
                logger.warning(
                    "distributed init failed (%s); retry %d/%d in %.1fs",
                    e, attempt + 1, retries, delay)
                time.sleep(delay)
    raise DistributedInitError(
        f"jax.distributed.initialize failed after {retries + 1} "
        f"attempt(s): {last}") from last


def process_rank(grid) -> int:
    """This controller's rank for checkpoint coordination:
    ``jax.process_index()``, or the per-pass rank a faked test split
    pinned on the grid (``grid._ckpt_rank``)."""
    r = getattr(grid, "_ckpt_rank", None)
    if r is not None:
        return int(r)
    import jax

    return int(jax.process_index())


def trip_consensus(grid, code: int) -> int:
    """All-reduce (max) a per-rank trip code across the mesh.

    :class:`~dccrg_tpu.resilience.ResilientRunner` calls this every
    step so trip/rollback decisions that originate host-side on ONE
    rank (``MutationAbortedError`` from a failed adapt, an OOM, the
    watchdog hook inside ``run_steps``) are taken by EVERY rank: all
    ranks roll back to the same checkpoint together instead of the
    tripped rank abandoning a collective its peers are still waiting
    in. Codes are small ints ordered by priority (0 = no trip;
    resilience._TRIP_INTERRUPT = a consensus-agreed step-boundary
    interrupt, e.g. a preemption signal — outranked by any real trip;
    recoverable trips — every rank rolls back together; >=
    resilience._TRIP_FATAL marks a non-recoverable failure — every
    rank raises in sync); the max across ranks wins.
    Single-controller grids return ``code`` unchanged — the reduction
    (a cached compiled collective, see comm._mesh_map) only runs on
    multi-process meshes."""
    code = int(code)
    if not grid._multiproc:
        return code
    from . import comm

    flags = np.zeros(grid.n_dev, dtype=np.int32)
    flags[grid._proc_local_dev] = np.int32(code)
    return int(comm.host_all_reduce(grid.mesh, flags, "max"))


def broadcast_fatal(grid, code: int, timeout: float | None = None) -> None:
    """Best-effort, deadline-bounded :func:`trip_consensus` broadcast
    for a rank on its way out of a non-recoverable error. The mesh may
    be the very thing that is broken (a wedged collective is exactly
    what :class:`~dccrg_tpu.supervise.StepTimeoutError` reports), so
    the courtesy broadcast runs on a daemon watchdog thread and is
    abandoned after ``timeout`` seconds (default:
    :func:`barrier_timeout`) — telling the peers must never keep the
    dying rank alive. Exceptions are swallowed: the caller is about to
    re-raise the error that actually matters."""
    timeout = barrier_timeout() if timeout is None else float(timeout)

    def _send():
        try:
            trip_consensus(grid, code)
        except Exception:  # noqa: BLE001 - the original error outranks it
            pass

    finished, _res, _err = run_with_deadline(_send, timeout,
                                             "fatal-broadcast")
    if not finished:  # pragma: no cover - needs a wedged mesh
        logger.warning(
            "fatal trip code %d could not be broadcast within %.0fs "
            "(the mesh itself is unreachable); peers must rely on "
            "their own barrier timeouts", code, timeout)


# ---------------------------------------------------------------------
# elastic membership: heartbeat leases over the coordination KV store
# ---------------------------------------------------------------------

DEFAULT_HEARTBEAT_S = 2.0
DEFAULT_LEASE_S = 8.0


def heartbeat_seconds(default: float = DEFAULT_HEARTBEAT_S) -> float:
    """The ``DCCRG_HEARTBEAT_S`` env knob: seconds between a rank's
    heartbeat-lease renewals in the coordination KV store."""
    try:
        v = float(os.environ.get("DCCRG_HEARTBEAT_S", "") or default)
    except ValueError:
        v = default
    return max(0.01, v)


def lease_seconds(default: float | None = None) -> float:
    """The ``DCCRG_LEASE_S`` env knob: seconds without an observed
    heartbeat before a peer rank is declared DEAD (and its job leases
    reclaimable). Clamped to at least two heartbeats — a lease shorter
    than that would flap on ordinary scheduling jitter."""
    hb = heartbeat_seconds()
    fallback = DEFAULT_LEASE_S if default is None else float(default)
    try:
        v = float(os.environ.get("DCCRG_LEASE_S", "") or fallback)
    except ValueError:
        v = fallback
    return max(2.0 * hb, v)


class InMemoryKV:
    """Process-local KV store with the coordination service's
    compare-and-set semantics (:meth:`create` is first-writer-wins).
    The single-process default, and the store the fake-clock
    lease/fencing tests share between in-process 'ranks'."""

    def __init__(self):
        self._data: dict = {}
        self._lock = threading.Lock()

    def set(self, key: str, value: str) -> None:
        with self._lock:
            self._data[str(key)] = str(value)

    def create(self, key: str, value: str) -> bool:
        """Create ``key`` iff absent; False when another writer won
        the race (THE compare-and-set the lease fencing rides)."""
        with self._lock:
            if str(key) in self._data:
                return False
            self._data[str(key)] = str(value)
            return True

    def get(self, key: str):
        with self._lock:
            return self._data.get(str(key))

    def dir_get(self, prefix: str):
        """Every ``(key, value)`` under ``prefix`` as a dict — the
        one-call census the lease machinery prefers over per-key
        reads (an ABSENT key costs a full blocking-get timeout on the
        real service; a prefix listing only returns what exists)."""
        prefix = str(prefix)
        with self._lock:
            return {k: v for k, v in self._data.items()
                    if k.startswith(prefix)}

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(str(key), None)


class CoordKV:
    """The real ``jax.distributed`` coordination-service KV store.
    ``create()`` maps to ``key_value_set`` WITHOUT overwrite — the
    service rejects an existing key, which is the first-writer-wins
    compare-and-set exactly one reclaimer may win. Reads use a short
    blocking get (this jaxlib has no try-get); every operation
    swallows service errors into None/False — a dying coordination
    service must degrade into observed staleness (the failure mode
    the lease machinery already handles), never a crash."""

    #: how long a read waits for a key that may simply not exist yet
    GET_TIMEOUT_MS = 100

    def __init__(self, client):
        self._client = client

    def set(self, key: str, value: str) -> None:
        try:
            self._client.key_value_set(str(key), str(value),
                                       allow_overwrite=True)
        except TypeError:  # pragma: no cover - older jaxlib signature
            try:
                self._client.key_value_delete(str(key))
            except Exception:  # noqa: BLE001 - best effort
                pass
            try:
                self._client.key_value_set(str(key), str(value))
            except Exception:  # noqa: BLE001 - best effort
                pass
        except Exception:  # noqa: BLE001 - degrade to staleness
            pass

    def create(self, key: str, value: str) -> bool:
        try:
            # no allow_overwrite: the service refuses an existing key
            self._client.key_value_set(str(key), str(value))
            return True
        except Exception:  # noqa: BLE001 - lost the CAS (or no service)
            return False

    def get(self, key: str):
        try:
            return self._client.blocking_key_value_get(
                str(key), self.GET_TIMEOUT_MS)
        except Exception:  # noqa: BLE001 - absent key / dead service
            return None

    def dir_get(self, prefix: str):
        """One prefix listing instead of N blocking gets (an absent
        key costs the full GET_TIMEOUT_MS; a listing returns only
        what exists). None on service error — the caller falls back
        to per-key reads."""
        try:
            return dict(self._client.key_value_dir_get(str(prefix)))
        except Exception:  # noqa: BLE001 - degrade to per-key reads
            return None

    def delete(self, key: str) -> None:
        try:
            self._client.key_value_delete(str(key))
        except Exception:  # noqa: BLE001 - best effort
            pass


_LOCAL_KV: "InMemoryKV | None" = None


def default_kv():
    """The KV store leases ride: the coordination service's when
    ``jax.distributed`` is initialized, else one process-global
    :class:`InMemoryKV` (single-host serving needs no coordination,
    but the code paths stay identical)."""
    client = _coordination_client()
    if client is not None:
        return CoordKV(client)
    global _LOCAL_KV
    if _LOCAL_KV is None:
        _LOCAL_KV = InMemoryKV()
    return _LOCAL_KV


def prefix_census(kv, prefix: str):
    """One-call ``{full_key: value}`` snapshot of every key under
    ``prefix``, or None when the KV cannot list (callers then fall
    back to per-key reads). On the real coordination service an
    ABSENT key costs a full blocking-get timeout, so every tick-path
    consumer (job leases, the streaming-intake front door) reads one
    census instead of per-key; the service may list RELATIVE child
    names, which are normalized back to full keys so lookups are
    uniform across KV implementations."""
    dir_get = getattr(kv, "dir_get", None)
    if dir_get is None:
        return None
    raw = dir_get(str(prefix))
    if raw is None:
        return None
    p = str(prefix).rstrip("/") + "/"
    return {(str(k) if str(k).startswith(p) else p + str(k)): v
            for k, v in raw.items()}


# ---------------------------------------------------------------------
# sealed records + fenced KV barrier (the distributed-AMR commit rides
# these; see dccrg_tpu/distamr.py)
# ---------------------------------------------------------------------

def seal_record(payload: str) -> str:
    """Frame ``payload`` with its CRC32 (``crc:length:payload``) for a
    KV write that may be observed half-done: the coordination service
    itself writes atomically, but a writer can die BETWEEN composing a
    record and meaning it, and fault injection deliberately stores torn
    tails — the frame lets every reader convict a damaged record
    instead of acting on it."""
    import zlib

    data = str(payload)
    raw = data.encode("utf-8")
    return f"{zlib.crc32(raw) & 0xFFFFFFFF:08x}:{len(raw)}:{data}"


def unseal_record(record: str, key: str = "") -> str:
    """Verify and strip a :func:`seal_record` frame; raises
    :class:`TornRecordError` naming ``key`` when the CRC or length
    does not match the payload."""
    import zlib

    try:
        crc_hex, length, data = str(record).split(":", 2)
        want_crc = int(crc_hex, 16)
        want_len = int(length)
    except (ValueError, AttributeError):
        raise TornRecordError(key, "unparseable frame") from None
    raw = data.encode("utf-8")
    if len(raw) != want_len:
        raise TornRecordError(key, f"length {len(raw)} != {want_len}")
    if (zlib.crc32(raw) & 0xFFFFFFFF) != want_crc:
        raise TornRecordError(key, "payload CRC mismatch")
    return data


def atomic_file_write(path: str, data: str, *, tmp_dir=None) -> str:
    """Durably land a small file: write to a temp sibling (or
    ``tmp_dir``), fsync, then atomically ``os.replace`` onto ``path``
    — the intake-spool discipline, shared by every small on-disk
    record in the package (a crashed writer leaves either the old
    complete file or an invisible temp, never a torn visible one).
    The temp name carries the writer's pid so crash litter is
    attributable (swept by the stale-temp GC patterns)."""
    d = tmp_dir if tmp_dir is not None else (os.path.dirname(path)
                                             or ".")
    tmp = os.path.join(
        str(d), f".{os.path.basename(path)}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def write_sealed_file(path: str, payload: str, *, tmp_dir=None) -> str:
    """:func:`seal_record` + :func:`atomic_file_write`: a CRC-framed
    durable small-file record any reader can convict instead of
    trusting (the warm-start manifest entries ride this)."""
    return atomic_file_write(path, seal_record(payload),
                             tmp_dir=tmp_dir)


def read_sealed_file(path: str, key: str = "") -> str:
    """Read and verify a :func:`write_sealed_file` record; raises
    :class:`TornRecordError` (naming ``key``, default the path) on a
    damaged frame. OSErrors propagate — absent and unreadable are the
    caller's distinction to make."""
    with open(path) as f:
        raw = f.read()
    return unseal_record(raw, key or str(path))


def kv_barrier(kv, tag: str, rank: int, ranks, timeout=None, *,
               value: str = "1", poll_s: float = 0.02, fence=None,
               abort_key=None, membership=None) -> dict:
    """Presence-key barrier over the coordination KV: each participant
    writes ``<tag>/<rank> = value`` and polls until every rank in
    ``ranks`` has arrived, then returns ``{rank: value}`` — the barrier
    doubles as an all-gather of one small record per rank (the
    distributed-AMR commit meets at it with structure digests as the
    values, so agreement checking costs no extra round).

    Unlike the coordination-service barrier this one takes an EXPLICIT
    participant set, so a collective that lost a rank can re-form over
    the survivors, and in-process fake ranks (tests) can meet at it.
    While polling it watches for the two conditions that must abort a
    distributed round faster than a timeout:

    - ``fence=(key, expected)``: raises :class:`StaleFenceError` the
      moment the fence key moves off ``expected`` — a stopped rank that
      wakes after the fleet committed without it must lose, not finish.
      The first element may also be a zero-arg callable returning the
      current fence (the distributed-AMR group's monotonic epoch read)
      instead of a KV key.
    - ``abort_key``: raises :class:`RemoteAbortError` the moment a peer
      posts an abort marker there (the distributed-rollback fast path).
      The marker also VETOES completion: arrival keys are monotonic
      within a round, so a peer that arrived and later aborted (a
      deeper-phase failure, a commit-wait timeout) leaves its arrivals
      behind as ghosts — a slow rank waking into a "complete" barrier
      of an aborted round must abort with the fleet, not finish alone.

    On expiry, a ``membership`` whose lease view declares a missing
    peer DEAD upgrades the timeout to :class:`PeerDeadError` naming the
    rank; otherwise :class:`BarrierTimeoutError` blames the tag. An
    injected :meth:`~dccrg_tpu.faults.FaultPlan.barrier_hang` for the
    tag replaces this rank's arrival with a sleep, exercising the
    peers' timeout machinery deterministically."""
    timeout = barrier_timeout() if timeout is None else float(timeout)
    expected = sorted({int(r) for r in ranks})
    faults.fire("coord.barrier", tag=tag)
    hang = faults.take_barrier_hang(tag)
    deadline = time.monotonic() + timeout
    if hang is not None:
        # simulate a lost/slow rank: never (or late) post the arrival
        time.sleep(min(float(hang), max(0.0, deadline - time.monotonic())))
    kv.set(f"{tag}/{int(rank)}", str(value))

    def _arrivals() -> dict:
        got = kv.dir_get(f"{tag}/")
        if got is None:  # service hiccup: degrade to per-key reads
            got = {}
            for r in expected:
                v = kv.get(f"{tag}/{r}")
                if v is not None:
                    got[f"{tag}/{r}"] = v
        arrived = {}
        for k, v in got.items():
            tail = k.rsplit("/", 1)[-1]
            try:
                arrived[int(tail)] = v
            except ValueError:
                continue
        return arrived

    def _abort_marker():
        """Read the abort marker cheaply: a prefix listing returns
        only keys that EXIST, where the real service's get blocks
        ~100 ms on an absent one — this probe runs every poll and on
        every successful exit. The listing targets the marker's PARENT
        directory (the real service's dir-get only returns keys UNDER
        the prefix, never the prefix itself), then picks the exact
        key — which also keeps attempt 1 from shadowing attempt 10."""
        got = kv.dir_get(abort_key.rsplit("/", 1)[0] + "/")
        if got is not None:
            return got.get(abort_key)
        return kv.get(abort_key)

    def _finish(arrived: dict) -> dict:
        """Success-path exit: every expected rank arrived. An abort
        marker still vetoes completion (see docstring) — the arrival
        keys may be ghosts of a round the peers already rolled back."""
        if abort_key is not None:
            marker = _abort_marker()
            if marker is not None:
                raise _remote_abort(tag, abort_key, marker)
        return {r: arrived[r] for r in expected}

    last_live_check = 0.0
    while True:
        # completion is checked before the FENCE: presence keys are
        # monotonic within a round, so once any rank observed all
        # arrivals, every rank will — a fence bump the winner performs
        # right after passing must never strand a slower participant
        # that the barrier already counted. The ABORT marker is the
        # one thing that outranks completion (checked in _finish).
        arrived = _arrivals()
        if all(r in arrived for r in expected):
            return _finish(arrived)
        if fence is not None:
            fkey, fexp = fence
            cur = fkey() if callable(fkey) else kv.get(fkey)
            if cur is not None and str(cur) != str(fexp):
                # the real service's get BLOCKS briefly on an absent
                # key, so a bump landing during this very check can be
                # observed BEFORE the arrival that justified it was
                # re-read — re-sample the arrivals once: a barrier the
                # winner already counted this rank through must return
                # success, not convict a live participant as a zombie
                arrived = _arrivals()
                if all(r in arrived for r in expected):
                    return _finish(arrived)
                raise StaleFenceError(tag, fexp, cur)
        if abort_key is not None:
            marker = _abort_marker()
            if marker is not None:
                raise _remote_abort(tag, abort_key, marker)
        now = time.monotonic()
        if membership is not None and now - last_live_check > 0.25:
            last_live_check = now
            try:
                dead = set(membership.detect_dead_ranks())
            except Exception:  # noqa: BLE001 - view refresh is best-effort
                dead = set()
            missing_dead = [r for r in expected
                            if r not in arrived and r in dead]
            if missing_dead:
                raise PeerDeadError(tag, timeout, missing_dead,
                                    lease_s=membership.lease_s)
        if now >= deadline:
            raise BarrierTimeoutError(tag, timeout)
        time.sleep(poll_s)


def _remote_abort(tag: str, key: str, marker) -> RemoteAbortError:
    """Decode an abort marker into the typed error (tolerating a torn
    marker: an unreadable abort is still an abort)."""
    import json

    try:
        info = json.loads(unseal_record(marker, key))
        return RemoteAbortError(tag, rank=int(info.get("rank", -1)),
                                reason=str(info.get("reason", "")))
    except Exception:  # noqa: BLE001 - torn marker: abort anonymously
        return RemoteAbortError(tag, rank=-1, reason="torn abort marker")


class Membership:
    """Elastic fleet membership over heartbeat leases.

    Every rank :meth:`heartbeat`\\ s a monotonically bumped counter
    into the KV under ``<prefix>/<rank>`` at the ``heartbeat_s``
    cadence. :meth:`poll` reads every peer's key under a deadline
    (:func:`run_with_deadline` — a wedged KV read keeps the LAST view
    instead of blocking the step loop) and classifies each peer by
    how long ago the OBSERVER saw its value change:

    - ``live``    — changed within ``suspect_s`` (2 heartbeats);
    - ``suspect`` — stale past ``suspect_s`` but short of the lease;
    - ``dead``    — stale for ``lease_s`` or more: the rank's job
      leases are reclaimable, and barriers involving it raise
      :class:`PeerDeadError` instead of blaming a tag.

    Aging is strictly observer-clock (no cross-host clock
    comparison), ``clock`` is injectable (the fake-clock tests), and
    a peer that starts heartbeating again flips back to live — the
    elastic-regrow half of the contract. Every poll exports
    ``dccrg_fleet_membership{state}`` gauges and logs state
    transitions."""

    LIVE, SUSPECT, DEAD = "live", "suspect", "dead"

    def __init__(self, rank: int, n_ranks: int, *, kv=None,
                 heartbeat_s=None, lease_s=None, clock=time.monotonic,
                 prefix: str = "dccrg/hb"):
        self.rank = int(rank)
        self.n_ranks = max(1, int(n_ranks))
        self.kv = kv if kv is not None else default_kv()
        self.heartbeat_s = (heartbeat_seconds() if heartbeat_s is None
                            else max(0.01, float(heartbeat_s)))
        self.lease_s = max(2.0 * self.heartbeat_s,
                           lease_seconds() if lease_s is None
                           else float(lease_s))
        self.suspect_s = min(2.0 * self.heartbeat_s, self.lease_s / 2.0)
        self.clock = clock
        self.prefix = str(prefix)
        self._beat = 0
        self._last_beat_t = None
        self._auto = None
        now = self.clock()
        # a peer that has NEVER heartbeat gets the same full-lease
        # grace from construction as one that just stopped — a slow
        # starter is not a corpse
        self._seen = {r: [None, now] for r in range(self.n_ranks)
                      if r != self.rank}
        self._state = {r: self.LIVE for r in self._seen}

    def _key(self, rank: int) -> str:
        return f"{self.prefix}/{int(rank)}"

    def heartbeat(self, force: bool = False) -> bool:
        """Renew this rank's lease (throttled to ``heartbeat_s``
        unless ``force``); returns whether a write happened."""
        now = self.clock()
        if (not force and self._last_beat_t is not None
                and now - self._last_beat_t < self.heartbeat_s):
            return False
        self._beat += 1
        self.kv.set(self._key(self.rank), f"{self._beat}")
        self._last_beat_t = now
        return True

    def start_auto(self) -> None:
        """Start the daemon heartbeat thread (idempotent): liveness
        must not ride the serving loop's stalls — an XLA compile
        blocks a tick for seconds, and a compile is not a death. A
        SIGSTOP/SIGKILL freezes/kills this thread with the process,
        so the beats stop exactly when the host actually stops. Only
        meaningful under a real clock (fake-clock tests drive
        :meth:`heartbeat` by hand and never call this)."""
        if self._auto is not None:
            return
        stop = threading.Event()

        def _beat():
            while not stop.wait(self.heartbeat_s):
                try:
                    self.heartbeat(force=True)
                except Exception:  # noqa: BLE001 - beats are best-effort
                    pass

        t = threading.Thread(target=_beat, daemon=True,
                             name="dccrg-heartbeat")
        t.start()
        self._auto = (t, stop)

    def stop_auto(self) -> None:
        if self._auto is not None:
            self._auto[1].set()
            self._auto = None

    def _classify(self, age: float) -> str:
        if age >= self.lease_s:
            return self.DEAD
        if age > self.suspect_s:
            return self.SUSPECT
        return self.LIVE

    def poll(self, timeout: float | None = None) -> dict:
        """One deadline-bounded membership scan; returns
        ``{rank: state}`` for every peer. The KV reads run under
        :func:`run_with_deadline` (budget: ``timeout``, default one
        heartbeat, floor 50 ms) — on expiry the previous observations
        stand and keep aging, so a wedged store reads as staleness,
        never as a blocked step loop."""
        from . import telemetry

        budget = (max(0.05, self.heartbeat_s) if timeout is None
                  else max(0.01, float(timeout)))
        peers = list(self._seen)

        def _read():
            return [self.kv.get(self._key(r)) for r in peers]

        finished, vals, err = run_with_deadline(_read, budget,
                                                "membership-poll")
        now = self.clock()
        if finished and err is None and vals is not None:
            for r, v in zip(peers, vals):
                rec = self._seen[r]
                if v is not None and v != rec[0]:
                    rec[0], rec[1] = v, now
        else:
            telemetry.inc("dccrg_membership_poll_failures_total")
        for r, rec in self._seen.items():
            st = self._classify(now - rec[1])
            if st != self._state[r]:
                logger.warning(
                    "fleet membership: rank %d %s -> %s (lease age "
                    "%.2fs, lease bound %.2fs)", r, self._state[r], st,
                    now - rec[1], self.lease_s)
                telemetry.inc("dccrg_fleet_membership_transitions_total",
                              rank=str(r), state=st)
                self._state[r] = st
        counts = {self.LIVE: 1, self.SUSPECT: 0, self.DEAD: 0}  # self
        for st in self._state.values():
            counts[st] += 1
        for st, n in counts.items():
            telemetry.set_gauge("dccrg_fleet_membership", n, state=st)
        return dict(self._state)

    def detect_dead_ranks(self, timeout: float | None = None) -> list:
        """Deadline-bounded refresh + the ranks currently DEAD by
        lease. Never blocks past the poll budget."""
        self.poll(timeout=timeout)
        return self.dead_ranks()

    def state(self, rank: int) -> str:
        """``live``/``suspect``/``dead`` (self is always live)."""
        if int(rank) == self.rank:
            return self.LIVE
        return self._state.get(int(rank), self.DEAD)

    def lease_age(self, rank: int) -> float:
        """Seconds since this observer saw ``rank``'s lease change."""
        rec = self._seen.get(int(rank))
        return 0.0 if rec is None else self.clock() - rec[1]

    def dead_ranks(self) -> list:
        return sorted(r for r, s in self._state.items()
                      if s == self.DEAD)

    def live_ranks(self) -> list:
        """Every rank not currently dead, self included — the rank
        set the rank-aware scheduler partitions work over."""
        return sorted([self.rank] + [r for r, s in self._state.items()
                                     if s != self.DEAD])


#: the process-wide membership barrier timeouts consult — None (the
#: default) changes nothing anywhere
_MEMBERSHIP: list = [None]


def set_membership(m: "Membership | None") -> "Membership | None":
    """Register (or clear) the process-wide :class:`Membership` the
    barrier path consults; returns the previous one. With a
    registered membership, a barrier whose peer is DEAD by lease
    raises :class:`PeerDeadError` naming the rank instead of a bare
    :class:`BarrierTimeoutError` blaming the tag."""
    prev = _MEMBERSHIP[0]
    _MEMBERSHIP[0] = m
    return prev


def get_membership() -> "Membership | None":
    return _MEMBERSHIP[0]


def _raise_if_peer_dead(tag: str, timeout: float, poll: bool) -> None:
    """Raise :class:`PeerDeadError` when the registered membership
    (if any) knows of dead peers. ``poll=True`` refreshes the view
    first (bounded — this runs on the timeout path, where the barrier
    budget is already spent)."""
    m = _MEMBERSHIP[0]
    if m is None:
        return
    dead = (m.detect_dead_ranks(timeout=min(2.0, m.heartbeat_s * 2))
            if poll else m.dead_ranks())
    if dead:
        raise PeerDeadError(tag, timeout, dead, lease_s=m.lease_s)
