"""Distributed coordination: timeout-guarded barriers, guarded
``jax.distributed`` bring-up, and cross-rank trip consensus.

The reference dccrg leans on MPI's collective semantics: a rank that
dies makes the next collective fail *somewhere*, and the job scheduler
reaps the rest. JAX multi-controller gives no such courtesy —
``sync_global_devices`` simply never returns if a participant is gone,
and a checkpoint save that died on one rank leaves every other rank
blocked forever with a half-written file on disk. This module is the
coordination layer the multi-process paths (checkpoint two-phase
commit, :class:`~dccrg_tpu.resilience.ResilientRunner`) thread their
rank synchronization through:

- :func:`barrier` — a tagged, timeout-guarded barrier. Real meshes go
  through the ``jax.distributed`` coordination-service barrier (which
  has a deadline) when available, else ``sync_global_devices`` under a
  watchdog thread. Either way a lost rank surfaces as a typed
  :class:`BarrierTimeoutError` *naming the tag* within the configured
  bound (``DCCRG_BARRIER_TIMEOUT``, default 120 s) instead of hanging
  the job. Fault injection (:meth:`~dccrg_tpu.faults.FaultPlan
  .barrier_hang`) exercises the watchdog deterministically on a single
  controller.
- :func:`distributed_init` — ``jax.distributed.initialize`` with
  bounded retry + exponential backoff for the transient failures of
  real cluster bring-up (coordination service not listening yet, port
  races), raising :class:`DistributedInitError` when the budget is
  spent.
- :func:`trip_consensus` — all-reduces a per-rank trip code over the
  mesh (max), so rollback decisions that originate on ONE host (a
  ``MutationAbortedError``, an OOM, a watchdog hook) are taken by
  EVERY rank together: all ranks roll back to the same checkpoint
  instead of deadlocking in a barrier half of them never reach.
  :func:`broadcast_fatal` is its deadline-bounded best-effort variant
  for a rank that is about to die and must not hang while saying so.
- :class:`CheckpointCommitError` — the abort signal of the two-phase
  multi-process checkpoint commit (checkpoint._save_process_slice):
  raised by the committing rank when a slice is missing or fails its
  CRC, with the previous checkpoint still intact under the final name.

Everything degrades to a no-op on a single controller, so
single-process code pays one ``process_count()`` check per call.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np

from . import faults

logger = logging.getLogger("dccrg_tpu.coord")

DEFAULT_BARRIER_TIMEOUT = 120.0

# Barrier ids must be unique AND align across ranks. A PER-TAG counter
# (not one global sequence) keeps them aligned even when ranks' barrier
# histories diverge on OTHER tags — e.g. a save that failed mid-protocol
# on one rank consumed that save's tags only, so an unrelated barrier
# still matches. Within one tag the contract is: every rank calls it the
# same number of times; protocols that can fail asymmetrically BETWEEN
# calls of the same tag must fold an attempt epoch into the tag itself
# (the two-phase checkpoint save tags carry `#<attempt>` for exactly
# this — a collective retry re-aligns by construction).
_tag_seq: dict = {}


def _next_seq(tag: str) -> int:
    seq = _tag_seq.get(tag, 0)
    _tag_seq[tag] = seq + 1
    return seq


class BarrierTimeoutError(RuntimeError):
    """A tagged barrier did not complete within its bound: a
    participating rank is gone (process death, hung collective, dead
    accelerator tunnel). ``tag``/``timeout`` carry the details."""

    def __init__(self, tag: str, timeout: float):
        super().__init__(
            f"barrier {tag!r} did not complete within {timeout:g}s: a "
            "participating rank is unreachable (process death, hung "
            "collective, or dead accelerator tunnel)")
        self.tag = tag
        self.timeout = timeout


class DistributedInitError(RuntimeError):
    """``jax.distributed.initialize`` failed after every bounded
    retry."""


class CheckpointCommitError(RuntimeError):
    """The two-phase multi-process checkpoint commit aborted: one or
    more ranks' slices are missing or fail their CRC32, so the new file
    was NOT published and the previous checkpoint stays bitwise intact
    under the final name. ``ranks`` names the writers whose slices
    failed (the dead/torn ranks)."""

    def __init__(self, msg, ranks=()):
        super().__init__(msg)
        self.ranks = sorted({int(r) for r in ranks})


def barrier_timeout(default: float = DEFAULT_BARRIER_TIMEOUT) -> float:
    """The ``DCCRG_BARRIER_TIMEOUT`` env knob: seconds before a
    coordination barrier gives up on its peers."""
    try:
        return float(os.environ.get("DCCRG_BARRIER_TIMEOUT", "") or default)
    except ValueError:
        return default


def run_with_deadline(fn, timeout: float, name: str = "deadline"):
    """Run ``fn()`` on a daemon worker thread bounded by ``timeout``
    seconds — the shared watchdog primitive behind the barrier sync,
    the fatal-trip broadcast and the supervision layer's step/save
    deadlines. Returns ``(finished, result, error)``; on expiry the
    worker is abandoned (``finished=False``) — a wedged callee cannot
    be cancelled, only reported — and the caller decides whether that
    is a typed error or a logged shrug."""
    box, err = [], []
    done = threading.Event()

    def _work():
        try:
            box.append(fn())
        except BaseException as e:  # noqa: BLE001 - caller's to re-raise
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_work, daemon=True, name=f"dccrg-{name}")
    t.start()
    if not done.wait(float(timeout)):
        return False, None, None
    return True, (box[0] if box else None), (err[0] if err else None)


def _coordination_client():
    """The jax.distributed coordination-service client, or None (not
    initialized, or jax internals drifted)."""
    try:
        from jax._src import distributed

        return distributed.global_state.client
    except Exception:  # pragma: no cover - jax internals drift
        return None


def barrier(tag: str, timeout: float | None = None) -> None:
    """Synchronize every process at a tagged point, or raise
    :class:`BarrierTimeoutError` naming the tag within ``timeout``
    seconds (default: :func:`barrier_timeout`).

    Single-controller meshes return immediately. Real multi-process
    meshes prefer the coordination-service barrier (deadline built in);
    when only ``sync_global_devices`` is available it runs on a daemon
    watchdog thread so the caller can never block past the bound (the
    hung thread is abandoned — a barrier that lost a rank is not
    recoverable anyway, only reportable). An injected
    :meth:`~dccrg_tpu.faults.FaultPlan.barrier_hang` replaces the sync
    with a sleep, exercising the watchdog machinery deterministically
    without a cluster."""
    timeout = barrier_timeout() if timeout is None else float(timeout)
    faults.fire("coord.barrier", tag=tag)
    hang = faults.take_barrier_hang(tag)
    import jax

    real = jax.process_count() > 1
    if not real and hang is None:
        return
    seq = _next_seq(tag)
    if hang is None:
        client = _coordination_client()
        if client is not None:
            try:
                client.wait_at_barrier(f"dccrg:{tag}:{seq}",
                                       int(timeout * 1000))
                return
            except Exception as e:
                # the service reports a lost rank either as our
                # deadline expiring or as the peer's task failing its
                # heartbeat — both mean the same thing to the caller
                msg = str(e)
                if ("DEADLINE_EXCEEDED" in msg or "Barrier failed" in msg
                        or "heartbeat timeout" in msg):
                    raise BarrierTimeoutError(tag, timeout) from e
                raise

    # watchdog-thread path: sync_global_devices has no deadline of its
    # own, and the injected hang must exercise this same machinery
    def _sync():
        if hang is not None:
            # a simulated lost rank: the sync never happens; a
            # finite hang_s below the timeout models a slow-but-
            # alive peer the barrier should survive
            time.sleep(min(hang, timeout + 30.0))
        elif real:  # pragma: no cover - needs a real cluster
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"dccrg:{tag}:{seq}")

    finished, _res, err = run_with_deadline(_sync, timeout,
                                            f"barrier:{tag}")
    if not finished:
        raise BarrierTimeoutError(tag, timeout)
    if err is not None:
        raise err


def distributed_init(coordinator_address=None, num_processes=None,
                     process_id=None, *, retries: int = 3,
                     backoff: float = 0.5, **kwargs) -> None:
    """``jax.distributed.initialize`` with bounded retry + exponential
    backoff: real cluster bring-up fails transiently (the coordinator
    is not listening yet, a port race, a slow DNS answer) and the raw
    call just dies. Raises :class:`DistributedInitError` with the last
    failure chained once the budget is spent."""
    import jax

    last: Exception | None = None
    for attempt in range(retries + 1):
        try:
            faults.fire("coord.init", attempt=attempt)
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                **kwargs)
            return
        except Exception as e:  # noqa: BLE001 - retried, then surfaced
            last = e
            if attempt < retries:
                delay = backoff * (2 ** attempt)
                logger.warning(
                    "distributed init failed (%s); retry %d/%d in %.1fs",
                    e, attempt + 1, retries, delay)
                time.sleep(delay)
    raise DistributedInitError(
        f"jax.distributed.initialize failed after {retries + 1} "
        f"attempt(s): {last}") from last


def process_rank(grid) -> int:
    """This controller's rank for checkpoint coordination:
    ``jax.process_index()``, or the per-pass rank a faked test split
    pinned on the grid (``grid._ckpt_rank``)."""
    r = getattr(grid, "_ckpt_rank", None)
    if r is not None:
        return int(r)
    import jax

    return int(jax.process_index())


def trip_consensus(grid, code: int) -> int:
    """All-reduce (max) a per-rank trip code across the mesh.

    :class:`~dccrg_tpu.resilience.ResilientRunner` calls this every
    step so trip/rollback decisions that originate host-side on ONE
    rank (``MutationAbortedError`` from a failed adapt, an OOM, the
    watchdog hook inside ``run_steps``) are taken by EVERY rank: all
    ranks roll back to the same checkpoint together instead of the
    tripped rank abandoning a collective its peers are still waiting
    in. Codes are small ints ordered by priority (0 = no trip;
    resilience._TRIP_INTERRUPT = a consensus-agreed step-boundary
    interrupt, e.g. a preemption signal — outranked by any real trip;
    recoverable trips — every rank rolls back together; >=
    resilience._TRIP_FATAL marks a non-recoverable failure — every
    rank raises in sync); the max across ranks wins.
    Single-controller grids return ``code`` unchanged — the reduction
    (a cached compiled collective, see comm._mesh_map) only runs on
    multi-process meshes."""
    code = int(code)
    if not grid._multiproc:
        return code
    from . import comm

    flags = np.zeros(grid.n_dev, dtype=np.int32)
    flags[grid._proc_local_dev] = np.int32(code)
    return int(comm.host_all_reduce(grid.mesh, flags, "max"))


def broadcast_fatal(grid, code: int, timeout: float | None = None) -> None:
    """Best-effort, deadline-bounded :func:`trip_consensus` broadcast
    for a rank on its way out of a non-recoverable error. The mesh may
    be the very thing that is broken (a wedged collective is exactly
    what :class:`~dccrg_tpu.supervise.StepTimeoutError` reports), so
    the courtesy broadcast runs on a daemon watchdog thread and is
    abandoned after ``timeout`` seconds (default:
    :func:`barrier_timeout`) — telling the peers must never keep the
    dying rank alive. Exceptions are swallowed: the caller is about to
    re-raise the error that actually matters."""
    timeout = barrier_timeout() if timeout is None else float(timeout)

    def _send():
        try:
            trip_consensus(grid, code)
        except Exception:  # noqa: BLE001 - the original error outranks it
            pass

    finished, _res, _err = run_with_deadline(_send, timeout,
                                             "fatal-broadcast")
    if not finished:  # pragma: no cover - needs a wedged mesh
        logger.warning(
            "fatal trip code %d could not be broadcast within %.0fs "
            "(the mesh itself is unreachable); peers must rely on "
            "their own barrier timeouts", code, timeout)
