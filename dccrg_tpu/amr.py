"""Adaptive mesh refinement: the request/commit algorithms.

Host-side equivalents of the reference's AMR commit pipeline
(dccrg.hpp:3483-3507 ``stop_refining`` = override_refines ->
induce_refines -> override_unrefines -> execute_refines,
:9730-10693). The reference runs iterated global collectives until
quiescence because each rank only sees parts of the structure; here
structure is replicated, so the same fixpoints run as vectorized numpy
set iterations over the full neighbor lists.

Semantics preserved:

- Refining a cell forces every coarser cell in its neighborhood (both
  directions of the neighbor relation) to refine too — induced
  refinement, iterated to a fixpoint (dccrg.hpp:9730-9906).
- ``dont_refine`` spreads: a cell that must not refine blocks the
  refinement of finer neighbors, recursively (dccrg.hpp:10130-10233).
- Unrefinement applies to whole sibling groups; it is cancelled when a
  sibling is refined, marked dont_unrefine, or when a cell too fine to
  be the parent's neighbor exists nearby, evaluated against
  post-refinement levels (dccrg.hpp:9935-10124).
- New children live on their parent's device, inheriting pins and
  weights; an unrefined parent lands on the owner of the first child
  (dccrg.hpp:10362-10399, :10437).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from . import faults
from .mapping import Mapping
from .topology import GridTopology


@dataclass
class AmrResult:
    """Outcome of an AMR commit."""

    cells: np.ndarray  # new sorted cell list
    owner: np.ndarray  # owners aligned with cells
    new_cells: np.ndarray  # created children (sorted)
    removed_cells: np.ndarray  # removed leaves (children of unrefined groups)
    refined_parents: np.ndarray  # cells that were replaced by children
    unrefined_parents: np.ndarray  # cells created by unrefinement

    @property
    def changed_cells(self) -> np.ndarray:
        """Every id in exactly one of the pre/post cell lists — the
        commit's exact dirty seed. stop_refining hands this to the
        hybrid plan rebuild, which dilates it by the search radius on
        the level-0 lattice instead of recomputing the symmetric
        difference of two full cell lists (hybrid.build_hybrid_plan's
        reuse branch)."""
        return np.concatenate([
            np.asarray(self.new_cells, dtype=np.uint64),
            np.asarray(self.removed_cells, dtype=np.uint64),
            np.asarray(self.refined_parents, dtype=np.uint64),
            np.asarray(self.unrefined_parents, dtype=np.uint64),
        ])


# bins above which the vectorized-lattice unrefine check falls back to
# the per-parent loop (deeply refined grids have huge fine lattices)
_LATTICE_MAX_BINS = 1 << 24


def _shift_bool(a: np.ndarray, shift: int, axis: int, periodic: bool) -> np.ndarray:
    """Boolean array shifted along ``axis``; wraps when periodic, else
    shifts in zeros."""
    if periodic:
        return np.roll(a, shift, axis=axis)
    out = np.zeros_like(a)
    n = a.shape[axis]
    if abs(shift) >= n:
        return out
    src = [slice(None)] * 3
    dst = [slice(None)] * 3
    if shift > 0:
        src[axis] = slice(0, n - shift)
        dst[axis] = slice(shift, n)
    else:
        src[axis] = slice(-shift, n)
        dst[axis] = slice(0, n + shift)
    out[tuple(dst)] = a[tuple(src)]
    return out


def _box_dilate(a: np.ndarray, radius, periodic) -> np.ndarray:
    """Chebyshev-ball (box) dilation of a 3-D bool lattice, separable
    per axis. ``radius`` is a scalar or a per-axis sequence; ``periodic``
    a per-axis sequence (both in the array's axis order)."""
    if np.isscalar(radius):
        radius = (radius,) * 3
    for d in range(3):
        acc = a.copy()
        for s in range(1, int(radius[d]) + 1):
            acc |= _shift_bool(a, s, d, periodic[d])
            acc |= _shift_bool(a, -s, d, periodic[d])
        a = acc
    return a


class _FrontierEdges:
    """Incrementally discovered neighbor edges for the commit fixpoints.

    The reference's override/induce phases propagate flags along
    neighbor links, iterated to a global fixpoint (dccrg.hpp:9730-10233).
    Propagation only ever leaves *flagged* cells, so instead of building
    the full O(all cells) of/to streams, edges are fetched on demand for
    the flagged frontier: neighbors_of via the generic engine,
    neighbors_to via the direct subset query — O(touched cells), not
    O(grid)."""

    def __init__(self, mapping, topology, cells, offsets):
        self.mapping = mapping
        self.topology = topology
        self.cells = cells
        self.offsets = offsets
        n = len(cells)
        self._expanded = np.zeros(n, dtype=bool)
        self.src = np.empty(0, dtype=np.int64)
        self.nbr = np.empty(0, dtype=np.int64)

    def expand(self, flag: np.ndarray) -> None:
        """Ensure edges of every flagged position are loaded."""
        from .neighbors import find_neighbors_of, find_neighbors_to_subset

        new = np.nonzero(flag & ~self._expanded)[0]
        if len(new) == 0:
            return
        self._expanded[new] = True
        q = self.cells[new]
        src, nbr, _off, _item = find_neighbors_of(
            self.mapping, self.topology, self.cells, q, self.offsets
        )
        qi, to_src, _off2 = find_neighbors_to_subset(
            self.mapping, self.topology, self.cells, q, self.offsets
        )
        self.src = np.concatenate([
            self.src, new[src], new[qi]
        ])
        self.nbr = np.concatenate([
            self.nbr,
            np.searchsorted(self.cells, nbr),
            np.searchsorted(self.cells, to_src),
        ])


def resolve_adaptation(
    mapping: Mapping,
    cells: np.ndarray,
    owner: np.ndarray,
    offsets: np.ndarray,
    refines: set,
    unrefines: set,
    dont_refines: set,
    dont_unrefines: set,
    pins: dict | None = None,
    weights: dict | None = None,
    topology=None,
    hood_len: int = 1,
) -> AmrResult:
    """Run the full commit pipeline on the replicated structure.

    ``offsets`` is the default neighborhood's offset list (the
    reference's commit propagates along the default neighborhood,
    dccrg.hpp:9730-9906)."""
    n = len(cells)
    lvl = mapping.get_refinement_level(cells)
    if topology is None:
        topology = GridTopology((False, False, False))

    def positions(id_set):
        """Positions of the ids that exist in the cell list."""
        if not id_set:
            return np.empty(0, dtype=np.int64)
        ids = np.fromiter((int(c) for c in id_set), dtype=np.uint64,
                          count=len(id_set))
        pos = np.minimum(np.searchsorted(cells, ids), n - 1)
        return pos[cells[pos] == ids].astype(np.int64)

    edges = _FrontierEdges(mapping, topology, cells, offsets)

    refine_flag = np.zeros(n, dtype=bool)
    rp = positions(refines)
    refine_flag[rp[lvl[rp] < mapping.max_refinement_level]] = True

    # --- override_refines: spread dont_refine to finer neighbors ------
    # (dccrg.hpp:10130-10233) a blocked cell also blocks the refinement
    # of any strictly finer neighbor, recursively.
    blocked = np.zeros(n, dtype=bool)
    blocked[positions(dont_refines)] = True
    while True:
        edges.expand(blocked)
        # finer neighbors of blocked cells become blocked
        m = blocked[edges.src] & (lvl[edges.nbr] > lvl[edges.src])
        new = np.zeros(n, dtype=bool)
        new[edges.nbr[m]] = True
        new &= ~blocked
        if not new.any():
            break
        blocked |= new
    refine_flag &= ~blocked

    # --- induce_refines (dccrg.hpp:9730-9906) --------------------------
    # refining a cell forces every coarser neighbor to refine
    while True:
        edges.expand(refine_flag)
        m = refine_flag[edges.src] & (lvl[edges.nbr] < lvl[edges.src])
        cand = np.zeros(n, dtype=bool)
        cand[edges.nbr[m]] = True
        cand &= ~refine_flag & ~blocked & (lvl < mapping.max_refinement_level)
        # note: a coarser cell that is blocked cannot be forced; the
        # reference guarantees this cannot happen because the spread
        # phase already removed the inducing refine. Keep the guard for
        # safety (blocked cells simply don't refine).
        if not cand.any():
            break
        refine_flag |= cand

    final_lvl = lvl + refine_flag.astype(np.int64)

    # --- unrefines: expand to sibling groups ---------------------------
    up = positions(unrefines)
    up = up[lvl[up] > 0]
    unref_parent = (
        np.unique(mapping.get_parent(cells[up])) if len(up)
        else np.empty(0, np.uint64)
    )

    dont_unref = np.zeros(n, dtype=bool)
    dont_unref[positions(dont_unrefines)] = True

    # --- override_unrefines (dccrg.hpp:9935-10124) ---------------------
    # The reference walks the neighborhood AROUND THE PARENT (BFS over
    # neighbors_, :10019-10124): the parent's neighborhood window has
    # the parent's own edge length as its radius unit — twice the
    # children's — so a cell just outside the children's windows can
    # still violate the <=1-level rule against the new parent. Check
    # cells intersecting the parent's would-be window directly: the
    # window is exactly the (2r+1)^3 parent-size-aligned bins around
    # the parent, so the check vectorizes as a box-dilated occupancy
    # lattice of too-fine cells (per-parent interval loop as fallback
    # for deeply refined grids whose bin lattice would be huge).
    accepted_parents = np.empty(0, np.uint64)
    cand_parents = np.empty(0, np.uint64)
    cand_kpos = np.empty((0, 8), np.int64)
    if len(unref_parent):
        idx_all = mapping.get_indices(cells).astype(np.int64)
        size_all = (1 << (mapping.max_refinement_level - lvl)).astype(np.int64)
        index_length = mapping.get_index_length().astype(np.int64)
        radius = max(int(hood_len), 1)
        periodic = np.array([topology.is_periodic(d) for d in range(3)])

        # sibling-group screening, vectorized over candidates: all 8
        # children must be leaves, none refining or marked dont_unrefine
        kids = mapping.get_all_children(unref_parent)  # [P, 8]
        kpos = np.minimum(np.searchsorted(cells, kids), n - 1)
        kid_ok = cells[kpos] == kids
        group_ok = kid_ok.all(axis=1)
        group_ok &= ~(refine_flag[kpos] & kid_ok).any(axis=1)
        group_ok &= ~(dont_unref[kpos] & kid_ok).any(axis=1)
        cand_parents = unref_parent[group_ok]
        cand_kpos = kpos[group_ok].astype(np.int64)

    if len(cand_parents):
        child_lvls = lvl[cand_kpos[:, 0]]
        accepted = np.zeros(len(cand_parents), dtype=bool)
        for child_lvl in np.unique(child_lvls):
            sel = np.nonzero(child_lvls == child_lvl)[0]
            s_c = 1 << (mapping.max_refinement_level - int(child_lvl))
            s_p = 2 * s_c  # parent size; divides the extent (child_lvl >= 1)
            fine = final_lvl > child_lvl
            # parent min corner = first child's
            parent_base = idx_all[cand_kpos[sel, 0]]
            if not fine.any():
                accepted[sel] = True
                continue
            bins = index_length // s_p
            if float(np.prod(bins.astype(np.float64))) <= _LATTICE_MAX_BINS:
                # too-fine cells (size < s_p, aligned) occupy exactly
                # one s_p bin each; a parent is rejected iff any lies
                # within Chebyshev radius of its window
                occ = np.zeros(tuple(bins), dtype=bool)
                fb = idx_all[fine] // s_p
                occ[fb[:, 0], fb[:, 1], fb[:, 2]] = True
                occ = _box_dilate(occ, radius, periodic)
                pb = parent_base // s_p
                accepted[sel] = ~occ[pb[:, 0], pb[:, 1], pb[:, 2]]
            else:
                fi, fs = idx_all[fine], size_all[fine]
                for k, base in zip(sel, parent_base):
                    lo = base - radius * s_p
                    hi = base + (radius + 1) * s_p  # exclusive
                    hit = np.ones(len(fi), dtype=bool)
                    for d in range(3):
                        if periodic[d]:
                            span = index_length[d]
                            h = np.zeros(len(fi), dtype=bool)
                            for shift in (-span, 0, span):
                                h |= (fi[:, d] + shift < hi[d]) & (
                                    fi[:, d] + fs + shift > lo[d]
                                )
                            hit &= h
                        else:
                            hit &= (fi[:, d] < hi[d]) & (fi[:, d] + fs > lo[d])
                    accepted[k] = not hit.any()
        accepted_parents = cand_parents[accepted]
        accepted_kpos = cand_kpos[accepted]

    # --- execute (dccrg.hpp:10243-10693) -------------------------------
    refined_idx = np.nonzero(refine_flag)[0]
    refined_parents = cells[refined_idx]
    children = (
        mapping.get_all_children(refined_parents).reshape(-1)
        if len(refined_idx)
        else np.empty(0, np.uint64)
    )
    child_owner = np.repeat(owner[refined_idx], 8) if len(refined_idx) else np.empty(0, np.int32)

    if len(accepted_parents):
        removed = mapping.get_all_children(accepted_parents).reshape(-1)
        new_parents = accepted_parents
        # parent owned by owner of first child (dccrg.hpp:10437)
        new_parent_owner = owner[accepted_kpos[:, 0]].astype(np.int32)
    else:
        removed = np.empty(0, np.uint64)
        new_parents = np.empty(0, np.uint64)
        new_parent_owner = np.empty(0, np.int32)

    # assemble the new cell list
    drop = np.zeros(n, dtype=bool)
    drop[refined_idx] = True
    drop[np.searchsorted(cells, removed)] = True
    keep_cells = cells[~drop]
    keep_owner = owner[~drop]
    new_cells_all = np.concatenate([keep_cells, children, new_parents])
    new_owner_all = np.concatenate([keep_owner, child_owner, new_parent_owner])
    order = np.argsort(new_cells_all, kind="stable")

    # inherit pins and weights (dccrg.hpp:10379-10399)
    if pins is not None:
        for p, ch in zip(refined_parents, np.reshape(children, (-1, 8)) if len(children) else []):
            if int(p) in pins:
                dest = pins.pop(int(p))
                for k in ch:
                    pins[int(k)] = dest
        for parent, kids0 in zip(new_parents, removed.reshape(-1, 8) if len(removed) else []):
            for k in kids0:
                pins.pop(int(k), None)
    if weights is not None:
        for p, ch in zip(refined_parents, np.reshape(children, (-1, 8)) if len(children) else []):
            if int(p) in weights:
                w = weights.pop(int(p))
                for k in ch:
                    weights[int(k)] = w
        for kids0 in removed.reshape(-1, 8) if len(removed) else []:
            for k in kids0:
                weights.pop(int(k), None)

    # the pins/weights dicts were just mutated IN PLACE (inheritance):
    # a fault here pins that the transaction snapshot restores them
    faults.fire("adapt.resolve", phase="pins")

    return AmrResult(
        cells=new_cells_all[order],
        owner=new_owner_all[order],
        new_cells=np.sort(children),
        removed_cells=np.sort(removed),
        refined_parents=np.sort(refined_parents),
        unrefined_parents=np.sort(new_parents),
    )


def frontier_induced_refines(
    mapping: Mapping,
    cells: np.ndarray,
    owner: np.ndarray,
    offsets: np.ndarray,
    refines: set,
    local_devs,
    topology=None,
) -> np.ndarray:
    """The FIRST induction wave a rank's local refines push across its
    ownership boundary: every refinable coarser neighbor of a directly
    requested refine that is NOT owned by ``local_devs``.

    This is the partial-view half of the distributed commit
    (dccrg_tpu/distamr.py): each rank declares this wave in its sealed
    proposal, computed from nothing but its OWN request set and the
    replicated structure. Because the wave depends only on (requests,
    structure), every peer can recompute it from the proposal against
    its own replicated structure — a mismatch convicts the proposer of
    resolving against a DIFFERENT structure epoch (a zombie whose plan
    is stale, a torn-but-CRC-passing payload) before any merge
    happens. It is deliberately ONE wave, not the fixpoint: the merged
    :func:`resolve_adaptation` runs the real fixpoint over the union
    of requests, and its digest is what the ranks compare at the
    resolve barrier; the frontier is the per-proposal integrity check
    that makes a bad proposal fail CLOSED at collect time."""
    n = len(cells)
    if topology is None:
        topology = GridTopology((False, False, False))
    lvl = mapping.get_refinement_level(cells)

    flag = np.zeros(n, dtype=bool)
    if refines:
        ids = np.fromiter((int(c) for c in refines), dtype=np.uint64,
                          count=len(refines))
        pos = np.minimum(np.searchsorted(cells, ids), n - 1)
        pos = pos[cells[pos] == ids].astype(np.int64)
        flag[pos[lvl[pos] < mapping.max_refinement_level]] = True
    if not flag.any():
        return np.empty(0, dtype=np.uint64)

    edges = _FrontierEdges(mapping, topology, cells, offsets)
    edges.expand(flag)
    m = flag[edges.src] & (lvl[edges.nbr] < lvl[edges.src])
    cand = np.zeros(n, dtype=bool)
    cand[edges.nbr[m]] = True
    cand &= ~flag & (lvl < mapping.max_refinement_level)
    local = np.isin(owner, np.asarray(sorted(int(d) for d in local_devs),
                                      dtype=np.asarray(owner).dtype))
    return np.sort(cells[cand & ~local].astype(np.uint64))
