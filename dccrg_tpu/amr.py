"""Adaptive mesh refinement: the request/commit algorithms.

Host-side equivalents of the reference's AMR commit pipeline
(dccrg.hpp:3483-3507 ``stop_refining`` = override_refines ->
induce_refines -> override_unrefines -> execute_refines,
:9730-10693). The reference runs iterated global collectives until
quiescence because each rank only sees parts of the structure; here
structure is replicated, so the same fixpoints run as vectorized numpy
set iterations over the full neighbor lists.

Semantics preserved:

- Refining a cell forces every coarser cell in its neighborhood (both
  directions of the neighbor relation) to refine too — induced
  refinement, iterated to a fixpoint (dccrg.hpp:9730-9906).
- ``dont_refine`` spreads: a cell that must not refine blocks the
  refinement of finer neighbors, recursively (dccrg.hpp:10130-10233).
- Unrefinement applies to whole sibling groups; it is cancelled when a
  sibling is refined, marked dont_unrefine, or when a cell too fine to
  be the parent's neighbor exists nearby, evaluated against
  post-refinement levels (dccrg.hpp:9935-10124).
- New children live on their parent's device, inheriting pins and
  weights; an unrefined parent lands on the owner of the first child
  (dccrg.hpp:10362-10399, :10437).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .mapping import Mapping
from .neighbors import NeighborLists


@dataclass
class AmrResult:
    """Outcome of an AMR commit."""

    cells: np.ndarray  # new sorted cell list
    owner: np.ndarray  # owners aligned with cells
    new_cells: np.ndarray  # created children (sorted)
    removed_cells: np.ndarray  # removed leaves (children of unrefined groups)
    refined_parents: np.ndarray  # cells that were replaced by children
    unrefined_parents: np.ndarray  # cells created by unrefinement


def _neighbor_pairs(lists: NeighborLists, n_cells: int):
    """Symmetric (a, b) neighbor index pairs from the of/to lists."""
    a = np.concatenate([lists.of_source, lists.to_source])
    b_ids = np.concatenate([lists.of_neighbor, lists.to_neighbor])
    return a, b_ids


def resolve_adaptation(
    mapping: Mapping,
    cells: np.ndarray,
    owner: np.ndarray,
    lists: NeighborLists,
    refines: set,
    unrefines: set,
    dont_refines: set,
    dont_unrefines: set,
    pins: dict | None = None,
    weights: dict | None = None,
    topology=None,
    hood_len: int = 1,
) -> AmrResult:
    """Run the full commit pipeline on the replicated structure."""
    n = len(cells)
    lvl = mapping.get_refinement_level(cells)
    pos_of = {int(c): i for i, c in enumerate(cells)}

    pair_src, pair_nbr_ids = _neighbor_pairs(lists, n)
    pair_nbr = np.searchsorted(cells, pair_nbr_ids)

    refine_flag = np.zeros(n, dtype=bool)
    for c in refines:
        i = pos_of.get(int(c))
        if i is not None and lvl[i] < mapping.max_refinement_level:
            refine_flag[i] = True

    # --- override_refines: spread dont_refine to finer neighbors ------
    # (dccrg.hpp:10130-10233) a blocked cell also blocks the refinement
    # of any strictly finer neighbor, recursively.
    blocked = np.zeros(n, dtype=bool)
    for c in dont_refines:
        i = pos_of.get(int(c))
        if i is not None:
            blocked[i] = True
    while True:
        # finer neighbors of blocked cells become blocked
        m = blocked[pair_src] & (lvl[pair_nbr] > lvl[pair_src])
        new = np.zeros(n, dtype=bool)
        new[pair_nbr[m]] = True
        new &= ~blocked
        if not new.any():
            break
        blocked |= new
    refine_flag &= ~blocked

    # --- induce_refines (dccrg.hpp:9730-9906) --------------------------
    # refining a cell forces every coarser neighbor to refine
    while True:
        m = refine_flag[pair_src] & (lvl[pair_nbr] < lvl[pair_src])
        cand = np.zeros(n, dtype=bool)
        cand[pair_nbr[m]] = True
        cand &= ~refine_flag & ~blocked & (lvl < mapping.max_refinement_level)
        # note: a coarser cell that is blocked cannot be forced; the
        # reference guarantees this cannot happen because the spread
        # phase already removed the inducing refine. Keep the guard for
        # safety (blocked cells simply don't refine).
        if not cand.any():
            break
        refine_flag |= cand

    final_lvl = lvl + refine_flag.astype(np.int64)

    # --- unrefines: expand to sibling groups ---------------------------
    unref_parent = {}  # parent id -> True (candidate sibling group)
    for c in unrefines:
        i = pos_of.get(int(c))
        if i is None or lvl[i] == 0:
            continue
        unref_parent[int(mapping.get_parent(np.uint64(c)))] = True

    dont_unref = np.zeros(n, dtype=bool)
    for c in dont_unrefines:
        i = pos_of.get(int(c))
        if i is not None:
            dont_unref[i] = True

    # --- override_unrefines (dccrg.hpp:9935-10124) ---------------------
    # The reference walks the neighborhood AROUND THE PARENT (BFS over
    # neighbors_, :10019-10124): the parent's neighborhood window has
    # the parent's own edge length as its radius unit — twice the
    # children's — so a cell just outside the children's windows can
    # still violate the <=1-level rule against the new parent. Check
    # cells intersecting the parent's would-be window directly.
    accepted_parents = []
    if len(unref_parent):
        # geometry of potential violators: anything whose
        # post-refinement level exceeds the candidate's children
        idx_all = mapping.get_indices(cells).astype(np.int64)
        size_all = (1 << (mapping.max_refinement_level - lvl)).astype(np.int64)
        index_length = mapping.get_index_length().astype(np.int64)
        radius = max(int(hood_len), 1)
        periodic = np.array(
            [topology.is_periodic(d) if topology is not None else False
             for d in range(3)]
        )
        # per child level, the (indices, sizes) of all finer-than-child
        # cells — shared by every candidate at that level
        fine_by_lvl = {}

        def fine_cells_at(child_lvl):
            if child_lvl not in fine_by_lvl:
                fine = final_lvl > child_lvl
                fine_by_lvl[child_lvl] = (idx_all[fine], size_all[fine])
            return fine_by_lvl[child_lvl]

    for parent in sorted(unref_parent):
        kids = mapping.get_all_children(np.uint64(parent))
        kid_idx = []
        ok = True
        for k in kids:
            i = pos_of.get(int(k))
            if i is None:  # a sibling is not a leaf (refined deeper)
                ok = False
                break
            kid_idx.append(i)
        if not ok:
            continue
        kid_idx = np.array(kid_idx)
        if refine_flag[kid_idx].any() or dont_unref[kid_idx].any():
            continue
        # parent (level child-1) must stay within 1 level of everything
        # in ITS neighborhood: no cell with final level > child level
        # may intersect the parent's window
        child_lvl = lvl[kid_idx[0]]
        fi, fs = fine_cells_at(child_lvl)
        if len(fi) == 0:
            accepted_parents.append(parent)
            continue
        s_p = 2 * size_all[kid_idx[0]]
        base = idx_all[kid_idx[0]]  # parent min corner = first child's
        lo = base - radius * s_p
        hi = base + (radius + 1) * s_p  # exclusive
        hit = np.ones(len(fi), dtype=bool)
        for d in range(3):
            if periodic[d]:
                span = index_length[d]
                h = np.zeros(len(fi), dtype=bool)
                for shift in (-span, 0, span):
                    h |= (fi[:, d] + shift < hi[d]) & (fi[:, d] + fs + shift > lo[d])
                hit &= h
            else:
                hit &= (fi[:, d] < hi[d]) & (fi[:, d] + fs > lo[d])
        if hit.any():
            continue
        accepted_parents.append(parent)

    # --- execute (dccrg.hpp:10243-10693) -------------------------------
    refined_idx = np.nonzero(refine_flag)[0]
    refined_parents = cells[refined_idx]
    children = (
        mapping.get_all_children(refined_parents).reshape(-1)
        if len(refined_idx)
        else np.empty(0, np.uint64)
    )
    child_owner = np.repeat(owner[refined_idx], 8) if len(refined_idx) else np.empty(0, np.int32)

    removed = []
    removed_owner = []
    new_parents = []
    new_parent_owner = []
    for parent in accepted_parents:
        kids = mapping.get_all_children(np.uint64(parent))
        idx = np.array([pos_of[int(k)] for k in kids])
        removed.append(kids)
        removed_owner.append(owner[idx])
        new_parents.append(parent)
        # parent owned by owner of first child (dccrg.hpp:10437)
        new_parent_owner.append(owner[idx[0]])
    removed = np.concatenate(removed) if removed else np.empty(0, np.uint64)
    new_parents = np.array(new_parents, dtype=np.uint64)
    new_parent_owner = np.array(new_parent_owner, dtype=np.int32)

    # assemble the new cell list
    drop = np.zeros(n, dtype=bool)
    drop[refined_idx] = True
    drop[np.searchsorted(cells, removed)] = True
    keep_cells = cells[~drop]
    keep_owner = owner[~drop]
    new_cells_all = np.concatenate([keep_cells, children, new_parents])
    new_owner_all = np.concatenate([keep_owner, child_owner, new_parent_owner])
    order = np.argsort(new_cells_all, kind="stable")

    # inherit pins and weights (dccrg.hpp:10379-10399)
    if pins is not None:
        for p, ch in zip(refined_parents, np.reshape(children, (-1, 8)) if len(children) else []):
            if int(p) in pins:
                dest = pins.pop(int(p))
                for k in ch:
                    pins[int(k)] = dest
        for parent, kids0 in zip(new_parents, removed.reshape(-1, 8) if len(removed) else []):
            for k in kids0:
                pins.pop(int(k), None)
    if weights is not None:
        for p, ch in zip(refined_parents, np.reshape(children, (-1, 8)) if len(children) else []):
            if int(p) in weights:
                w = weights.pop(int(p))
                for k in ch:
                    weights[int(k)] = w
        for kids0 in removed.reshape(-1, 8) if len(removed) else []:
            for k in kids0:
                weights.pop(int(k), None)

    return AmrResult(
        cells=new_cells_all[order],
        owner=new_owner_all[order],
        new_cells=np.sort(children),
        removed_cells=np.sort(removed),
        refined_parents=np.sort(refined_parents),
        unrefined_parents=np.sort(new_parents),
    )
