"""Warm-start resilience: a crash-consistent persistent compile cache
and pre-warmed bucket program pools.

The elastic fleet (PR 14) survives host death and the durable intake
(PR 17) survives a crashed front door — but a fresh or rejoining host
still pays a full XLA compile storm before its first dispatch, and
churn puts that storm exactly where the fleet is weakest. This module
closes the cold-start half of the streaming front door:

**Persistent compile cache** — ``DCCRG_COMPILE_CACHE=<dir>`` points
jax's persistent compilation cache at ``<dir>/xla`` (via the
:func:`~dccrg_tpu.compat.enable_persistent_cache` drift shim) and
keeps our own **program-key manifest** next to it: one CRC-framed
record per (shape, periodicity, schema, kernel, dtype, capacity,
integrity-flag) bucket key ever compiled, written with the intake
spool's durability discipline (temp sibling + fsync + atomic rename —
:func:`dccrg_tpu.coord.write_sealed_file`), so two ranks on one host
race safely (last complete writer wins) and a crashed writer leaves
either the old intact record or invisible temp litter, never a torn
visible one. Every record is stamped with a **cache epoch** derived
from the jax/jaxlib/package versions: a drifted cache is *rejected to
cold compile*, never trusted. A torn or corrupt record is convicted by
its CRC frame (typed :class:`WarmCacheError`), quarantined under
``<dir>/quarantine/`` and degraded to cold — no crash, no wrong
program, no silent warm claim.

**Warm bucket pools** — at boot (and on a PR-14 elastic rejoin) a
:class:`WarmPool` replays the manifest most-recently-served first and
pre-compiles each known bucket program on a background thread
(:class:`dccrg_tpu.background.PrewarmWorker`: abortable, bitwise-
neutral, compile-only — ``jit.lower(...).compile()`` allocates no
state buffers and dispatches nothing, so it never contends with a
live dispatch). A pre-compiled program is the EXACT executable the
jit path would build (bitwise pin in tests/test_warmstart.py); the
fleet's program cache consults :func:`take_prewarmed` before
building, so a warm host's first dispatch skips trace + compile
entirely. :class:`~dccrg_tpu.scheduler.SLOPolicy` consults
:meth:`WarmPool.projection_cost` so an un-warmed bucket's projected
completion is charged its measured cold-compile cost up front instead
of discovering it mid-tick.

Every warm/cold/reject/quarantine decision is journaled through the
autopilot (``warmstart.cache`` / ``warmstart.gc`` rules) and
replayable via ``python -m dccrg_tpu.autopilot explain``. Retention
GC (``python -m dccrg_tpu.warmstart gc``, dry-run by default) prunes
least-recently-hit entries under size/age bounds, sweeps dead-pid
temp litter (the ``checkpoint.stale_temp_files`` pattern) and never
touches a key currently being pre-warmed.

OFF by default: with ``DCCRG_COMPILE_CACHE`` unset nothing here is
constructed and the serving stack is bitwise identical to before (the
negative pin). ``DCCRG_WARM_POOL=0`` keeps the persistent disk cache
but disables the background pre-compile pool.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time

from . import background, compat, coord, faults, telemetry
from .autopilot import key_id

logger = logging.getLogger("dccrg_tpu.warmstart")

#: manifest record layout version — part of the cache epoch, so a
#: layout change rejects old records instead of misreading them
MANIFEST_SCHEMA = 1

MANIFEST_DIR = "manifest"
XLA_DIR = "xla"
QUARANTINE_DIR = "quarantine"
RECORD_SUFFIX = ".rec"


class WarmCacheError(RuntimeError):
    """A persisted warm-start artifact could not be trusted (torn or
    corrupt manifest record, cache-epoch drift, registry drift, I/O
    failure). Always degrades to a cold compile — the error names the
    convicted entry and why; it is never allowed to take serving
    down."""

    def __init__(self, key: str, detail: str):
        super().__init__(f"warm cache entry {key!r}: {detail}")
        self.key = str(key)
        self.detail = str(detail)


# ---------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------

def cache_dir_default():
    """``DCCRG_COMPILE_CACHE``: the persistent cache directory, or
    None (the negative pin: unset means nothing here exists)."""
    v = os.environ.get("DCCRG_COMPILE_CACHE", "").strip()
    return v or None


def warm_pool_default(default: bool = True) -> bool:
    """``DCCRG_WARM_POOL``: whether an attached pool starts the
    background pre-compile sweep (default on when a cache dir is
    configured; ``0`` keeps the disk cache only)."""
    v = os.environ.get("DCCRG_WARM_POOL", "").strip().lower()
    if v in ("0", "off", "false", "no"):
        return False
    if v in ("1", "on", "true", "yes"):
        return True
    return default


def gc_max_bytes_default():
    """``DCCRG_WARM_GC_BYTES``: retention size bound (0/unset =
    unbounded)."""
    try:
        v = int(os.environ.get("DCCRG_WARM_GC_BYTES", "0"))
    except ValueError:
        return None
    return v if v > 0 else None


def gc_max_age_default():
    """``DCCRG_WARM_GC_AGE_S``: retention age bound in seconds
    (0/unset = unbounded)."""
    try:
        v = float(os.environ.get("DCCRG_WARM_GC_AGE_S", "0"))
    except ValueError:
        return None
    return v if v > 0 else None


def cache_epoch() -> str:
    """The version fingerprint every manifest record is stamped with.
    Any drift — jax, jaxlib, the package, the record layout — changes
    the epoch, and a record from another epoch is REJECTED to cold
    compile: a persisted program key must never vouch for bytes a
    different compiler stack wrote."""
    import hashlib

    try:
        import jax

        jv = str(jax.__version__)
    except Exception:  # noqa: BLE001 - epoch must never raise
        jv = "?"
    try:
        import jaxlib

        jlv = str(jaxlib.__version__)
    except Exception:  # noqa: BLE001
        jlv = "?"
    pkg = sys.modules.get(__package__)
    pv = str(getattr(pkg, "__version__", "0"))
    seed = f"jax={jv}:jaxlib={jlv}:pkg={pv}:schema={MANIFEST_SCHEMA}"
    return hashlib.sha1(seed.encode("utf-8")).hexdigest()[:16]


# ---------------------------------------------------------------------
# bucket-key (de)serialization
# ---------------------------------------------------------------------

def bucket_payload(bucket_key):
    """The JSON-faithful form of a :meth:`~dccrg_tpu.fleet.FleetJob
    .bucket_key`, or None for a callable kernel (identity-bucketed
    callables cannot survive a process restart, so they are never
    manifested — they simply stay cold)."""
    (length, periodic, hood_len, schema, kernel,
     fin, fout, n_params) = bucket_key
    if callable(kernel):
        return None
    return {
        "length": [int(v) for v in length],
        "periodic": [bool(v) for v in periodic],
        "hood_len": int(hood_len),
        "schema": [[str(n), [int(s) for s in shape], str(d)]
                   for n, shape, d in schema],
        "kernel": str(kernel),
        "fields_in": [str(f) for f in fin],
        "fields_out": [str(f) for f in fout],
        "n_params": int(n_params),
    }


def bucket_from_payload(p) -> tuple:
    """Invert :func:`bucket_payload` back to the hashable tuple form
    (raises KeyError/TypeError on a malformed payload — the loader
    maps those to :class:`WarmCacheError`)."""
    return (
        tuple(int(v) for v in p["length"]),
        tuple(bool(v) for v in p["periodic"]),
        int(p["hood_len"]),
        tuple(sorted((str(n), tuple(int(s) for s in shape), str(d))
                     for n, shape, d in p["schema"])),
        str(p["kernel"]),
        tuple(str(f) for f in p["fields_in"]),
        tuple(str(f) for f in p["fields_out"]),
        int(p["n_params"]),
    )


def job_for_bucket(bucket_key):
    """Reconstruct a prototype :class:`~dccrg_tpu.fleet.FleetJob`
    from a manifested bucket key, and PROVE the reconstruction by
    round-tripping its own ``bucket_key()`` — if the kernel-spec
    registry drifted since the record was written (renamed kernel,
    changed schema), the mismatch is a typed :class:`WarmCacheError`
    and the key falls cold instead of pre-compiling a wrong
    program."""
    from . import fleet

    (length, periodic, hood_len, schema, kernel,
     fin, fout, n_params) = bucket_key
    cell_data = {n: (tuple(shape), d) for n, shape, d in schema}
    try:
        job = fleet.FleetJob(
            "_warm", length=length, kernel=kernel,
            cell_data=cell_data, fields_in=fin, fields_out=fout,
            params=(0.0,) * int(n_params), periodic=periodic,
            hood_len=hood_len, n_steps=0)
        job.resolved_kernel()  # an unknown kernel name fails HERE
    except Exception as e:  # noqa: BLE001 - registry drift
        raise WarmCacheError(str(kernel),
                             f"job reconstruction failed: {e}") from e
    if job.bucket_key() != bucket_key:
        raise WarmCacheError(
            str(kernel),
            "kernel registry drift: reconstructed bucket key differs")
    return job


# ---------------------------------------------------------------------
# the manifest (per-entry sealed records, atomic rename)
# ---------------------------------------------------------------------

def ensure_cache(directory: str) -> str:
    """Create the cache directory tree (idempotent) and point jax's
    persistent compilation cache at its ``xla/`` half."""
    directory = str(directory)
    for d in ("", MANIFEST_DIR, QUARANTINE_DIR, XLA_DIR):
        os.makedirs(os.path.join(directory, d), exist_ok=True)
    compat.enable_persistent_cache(os.path.join(directory, XLA_DIR))
    return directory


def entry_path(directory: str, kid: str) -> str:
    return os.path.join(directory, MANIFEST_DIR, kid + RECORD_SUFFIX)


def write_entry(directory: str, kid: str, entry: dict) -> str:
    """Durably land one manifest record (concurrent writers safe: the
    per-entry atomic rename makes the last COMPLETE writer win). The
    injected fault sites land the three damage classes a reader must
    convict — a torn frame, a corrupted payload byte, a drifted
    epoch."""
    faults.fire("warm.cache.io", op="write")
    rec = dict(entry)
    rec.setdefault("epoch", cache_epoch())
    if faults.take_warm_stale(key=kid):
        rec["epoch"] = "0" * 16  # a run on some other compiler stack
    sealed = coord.seal_record(json.dumps(rec, sort_keys=True))
    if faults.take_warm_torn(key=kid):
        sealed = sealed[:max(1, len(sealed) // 2)]
    elif faults.take_warm_corrupt(key=kid):
        # flip one payload byte INSIDE the frame: the record still
        # parses as crc:len:payload, the CRC no longer matches
        b = bytearray(sealed.encode("utf-8"))
        b[-1] ^= 0x01
        sealed = b.decode("utf-8", errors="replace")
    path = entry_path(directory, kid)
    return coord.atomic_file_write(
        path, sealed, tmp_dir=os.path.dirname(path))


def read_entry(path: str) -> dict:
    """Read + verify one manifest record; raises
    :class:`WarmCacheError` naming the record for every way it can be
    untrustworthy (torn frame, bad JSON, epoch drift, malformed
    key)."""
    kid = os.path.basename(path)
    if kid.endswith(RECORD_SUFFIX):
        kid = kid[:-len(RECORD_SUFFIX)]
    faults.fire("warm.cache.io", op="read", key=kid)
    try:
        payload = coord.read_sealed_file(path, key=kid)
    except coord.TornRecordError as e:
        raise WarmCacheError(kid, f"torn record ({e})") from e
    try:
        rec = json.loads(payload)
    except ValueError as e:
        raise WarmCacheError(kid, f"undecodable payload ({e})") from e
    if rec.get("epoch") != cache_epoch():
        raise WarmCacheError(
            kid, f"cache epoch drift ({rec.get('epoch')!r} != "
                 f"{cache_epoch()!r})")
    try:
        rec["_bucket"] = bucket_from_payload(rec["key"])
        rec["capacity"] = int(rec["capacity"])
    except (KeyError, TypeError, ValueError) as e:
        raise WarmCacheError(kid, f"malformed key ({e})") from e
    rec["_kid"] = kid
    return rec


def quarantine_entry(directory: str, path: str, err) -> str:
    """Move a convicted record out of the manifest (best-effort: a
    second rank may have quarantined it first). Returns the
    quarantine path."""
    dst = os.path.join(directory, QUARANTINE_DIR,
                       os.path.basename(path))
    try:
        os.replace(path, dst)
    except OSError:
        pass
    telemetry.inc("dccrg_warm_quarantined_total")
    logger.warning("warmstart: quarantined %s (%s)", path, err)
    return dst


def load_manifest(directory: str):
    """Load every trustworthy manifest record. Returns ``(entries,
    rejects)``: ``entries`` maps kid -> record, ``rejects`` is
    ``[(path, WarmCacheError)]`` for every record that was convicted
    (the caller quarantines + journals them — the load itself never
    raises on damage, only on a missing directory)."""
    entries, rejects = {}, []
    mdir = os.path.join(str(directory), MANIFEST_DIR)
    try:
        faults.fire("warm.cache.io", op="scan")
        names = sorted(os.listdir(mdir))
    except OSError as e:
        return {}, [(mdir, WarmCacheError(mdir, f"scan failed: {e}"))]
    for name in names:
        if not name.endswith(RECORD_SUFFIX):
            continue
        path = os.path.join(mdir, name)
        try:
            rec = read_entry(path)
        except WarmCacheError as e:
            rejects.append((path, e))
            continue
        except OSError as e:
            rejects.append((path, WarmCacheError(
                name, f"unreadable ({e})")))
            continue
        entries[rec["_kid"]] = rec
    return entries, rejects


# ---------------------------------------------------------------------
# the active pool (consulted by fleet.GridBatch._programs)
# ---------------------------------------------------------------------

_POOL: "WarmPool | None" = None


def active() -> "WarmPool | None":
    return _POOL


def activate(pool) -> None:
    global _POOL
    _POOL = pool


def deactivate(pool=None) -> None:
    """Clear the active pool (idempotent; with ``pool`` given, only
    if it is still the active one — a newer pool wins)."""
    global _POOL
    if pool is None or _POOL is pool:
        _POOL = None


def take_prewarmed(prog_key, device=None):
    """The fleet program cache's warm lookup: the pre-compiled
    program-tuple for ``prog_key`` (exactly what
    ``GridBatch._build_programs`` would return, with the compile
    already done), or None — no pool, key not warmed yet, or a device
    mismatch (an AOT executable is bound to the device it compiled
    for). Zero branches beyond a module-global None check when no
    cache is configured."""
    pool = _POOL
    if pool is None:
        return None
    return pool.take(prog_key, device=device)


class WarmPool:
    """The warm bucket pool over one persistent cache directory.

    Lifecycle: construct (loads + convicts the manifest),
    :meth:`attach` to a scheduler (adopts its autopilot/device, hooks
    the SLO policy's cold-cost projection, activates the module-level
    lookup and starts the background pre-compile sweep), serve. All
    shared state is lock-guarded: the prewarm thread publishes
    finished programs while the serving thread takes them."""

    def __init__(self, directory, *, device=None, autopilot=None,
                 start_pool=None):
        self.dir = ensure_cache(directory)
        self.device = device
        self.autopilot = autopilot
        self.start_pool = (warm_pool_default() if start_pool is None
                           else bool(start_pool))
        self.t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._ready: dict = {}    # program key -> program tuple
        self._warm_buckets: set = set()  # bucket keys with a ready program
        self._served: set = set()        # program keys served warm
        self._inflight: set = set()      # kids being pre-compiled (GC guard)
        self._worker = None
        self._first_ready = None
        self.errors: list = []    # [(kid, WarmCacheError)] degradations
        self.entries: dict = {}
        self._queue: list = []    # prewarm order (kids, front first)
        self._load()

    # -- construction -------------------------------------------------

    @staticmethod
    def from_env():
        """A pool over ``DCCRG_COMPILE_CACHE``, or None when unset —
        the negative pin: no env, no pool, no new branches
        anywhere."""
        d = cache_dir_default()
        return WarmPool(d) if d else None

    def _load(self) -> None:
        self.entries, rejects = load_manifest(self.dir)
        for path, err in rejects:
            self._degrade(err, path=path)
        # most-recently-served first: the keys live traffic needed
        # last are the ones a rejoining host needs first
        self._queue = [kid for kid, _e in sorted(
            self.entries.items(),
            key=lambda kv: (-float(kv[1].get("last_hit", 0.0)),
                            -int(kv[1].get("hits", 0)), kv[0]))]

    def attach(self, sched) -> None:
        """Adopt the scheduler's autopilot (one journal) and first
        device lane, charge un-warmed buckets their cold cost in its
        SLO projection, and start the pre-compile sweep."""
        if self.autopilot is None:
            self.autopilot = sched.autopilot
        if self.device is None and sched.devices:
            self.device = sched.devices[0]
        sched.slo.warm_cost = self.projection_cost
        activate(self)
        if self.start_pool:
            self.prewarm()

    def close(self) -> None:
        """Abort the prewarm sweep and release the module-level
        lookup (tests construct many pools; the last closed must not
        leak into the next scheduler)."""
        if self._worker is not None:
            self._worker.stop()
        deactivate(self)

    # -- degradation + journaling -------------------------------------

    def _journal(self, decision: str, kid: str, **inputs) -> None:
        telemetry.inc("dccrg_warm_decisions_total", decision=decision)
        if self.autopilot is not None:
            self.autopilot.record_warm(decision, kid, inputs)

    def _degrade(self, err, *, path=None, kid=None) -> None:
        """A warm artifact could not be trusted: quarantine it (when
        it is a file), journal the decision, count it, keep serving —
        the typed error is recorded on :attr:`errors`, never
        raised."""
        kid = kid or getattr(err, "key", "?")
        telemetry.inc("dccrg_warm_cache_errors_total")
        self.errors.append((kid, err))
        decision = "quarantine" if path is not None else "reject"
        if path is not None:
            quarantine_entry(self.dir, path, err)
        self._journal(decision, kid,
                      error=type(err).__name__,
                      detail=str(getattr(err, "detail", err))[:200])

    # -- the prewarm sweep --------------------------------------------

    def prewarm(self, block: bool = False):
        """Pre-compile every manifested bucket program, most recently
        served first. ``block=True`` runs inline (tests, CLI) and
        propagates an injected rank death; the default starts one
        abortable :class:`~dccrg_tpu.background.PrewarmWorker`."""
        if block:
            self._prewarm_run(threading.Event())
            return None
        if self._worker is not None and not self._worker.ready():
            return self._worker
        self._worker = background.PrewarmWorker(self._prewarm_run)
        return self._worker.start()

    def _prewarm_run(self, abort) -> None:
        while True:
            with self._lock:
                if abort.is_set() or not self._queue:
                    return
                kid = self._queue.pop(0)
                entry = self.entries.get(kid)
            if entry is None:
                continue
            # a real death window between two pre-compiles: the
            # manifest + cache dir must stay loadable for the NEXT
            # boot (InjectedRankDeath propagates; everything else
            # degrades this one key to cold)
            faults.fire("warm.prewarm", key=kid)
            t0 = time.perf_counter()
            try:
                self._compile_one(kid, entry)
            except faults.InjectedRankDeath:
                raise
            except WarmCacheError as e:
                self._degrade(e, kid=kid)
                continue
            except Exception as e:  # noqa: BLE001 - degrade, never crash
                self._degrade(WarmCacheError(kid, f"prewarm failed: "
                                                  f"{e}"), kid=kid)
                continue
            telemetry.observe("dccrg_prewarm_seconds",
                              time.perf_counter() - t0, key=kid)

    def _compile_one(self, kid: str, entry: dict) -> None:
        from . import fleet

        with self._lock:
            self._inflight.add(kid)
        try:
            job = job_for_bucket(entry["_bucket"])
            # a skeleton batch: program-construction inputs only
            # (plan tables, schema) — no [capacity, R, ...] state
            # allocation, nothing dispatched
            batch = fleet.GridBatch(job, entry["capacity"],
                                    self.device, skeleton=True)
            key = batch._program_key()
            with self._lock:
                if key in self._ready:
                    return
            programs = self._aot_compile(batch, key)
            with self._lock:
                self._ready[key] = programs
                self._warm_buckets.add(batch.key)
            telemetry.inc("dccrg_warm_prewarmed_total")
        finally:
            with self._lock:
                self._inflight.discard(kid)

    def _aot_compile(self, batch, prog_key):
        """Lower + compile the bucket's programs ahead of time
        against abstract inputs — the exact avals ``GridBatch.step``
        dispatches with — and wrap each executable with a lazy jit
        fallback (an aval mismatch falls back to the ordinary compile
        path; execution errors like a real OOM pass through
        untouched, the scheduler's OOM handling owns those)."""
        import jax
        import numpy as np

        run_j, finite_j, fp_j, bulk = batch._build_programs(prog_key)
        state = {n: jax.ShapeDtypeStruct(
            (batch.capacity, batch.R) + shape, dtype)
            for n, (shape, dtype) in batch.schema.items()}
        extras = jax.ShapeDtypeStruct(
            (batch.capacity, batch.n_extra), np.float32)
        budget = jax.ShapeDtypeStruct((batch.capacity,), np.int32)
        q = jax.ShapeDtypeStruct((), np.int32)
        run_c = run_j.lower(state, extras, budget, q).compile()
        finite_c = finite_j.lower(state).compile()
        fp_c = None if fp_j is None else fp_j.lower(state).compile()
        return (_with_fallback(run_c, run_j),
                _with_fallback(finite_c, finite_j),
                None if fp_j is None else _with_fallback(fp_c, fp_j),
                bulk)

    # -- serving-side hooks -------------------------------------------

    def take(self, prog_key, device=None):
        with self._lock:
            hit = self._ready.get(prog_key)
        if hit is None:
            return None
        if (device is not None and self.device is not None
                and device != self.device):
            return None
        with self._lock:
            self._served.add(prog_key)
        return hit

    def warm_ready(self, bucket_key) -> bool:
        """Whether a pre-compiled program exists for this bucket key
        (any capacity variant) — the scheduler-admission signal."""
        with self._lock:
            return bucket_key in self._warm_buckets

    def projection_cost(self, bucket_key) -> float:
        """The :class:`~dccrg_tpu.scheduler.SLOPolicy` hook: the
        extra seconds a job of this bucket key should be charged up
        front — 0.0 once a warm program is ready (or for a key the
        manifest has never measured), else the recorded cold-compile
        cost."""
        if self.warm_ready(bucket_key):
            return 0.0
        best = 0.0
        with self._lock:
            for e in self.entries.values():
                if e.get("_bucket") == bucket_key:
                    best = max(best, float(e.get("compile_s", 0.0)))
        return best

    def note_incoming(self, bucket_key) -> None:
        """An intake admission saw this bucket key: move its
        manifest entries to the FRONT of the prewarm queue — the
        stream knows better than the hit counters what is about to
        dispatch."""
        payload = bucket_payload(bucket_key)
        if payload is None:
            return
        with self._lock:
            front = [kid for kid in self._queue
                     if self.entries.get(kid, {}).get("_bucket")
                     == bucket_key]
            if front:
                rest = [kid for kid in self._queue
                        if kid not in front]
                self._queue = front + rest

    def note_dispatch(self, batch, seconds: float) -> None:
        """The scheduler's first-dispatch hook for a batch instance:
        classify it warm (a pre-compiled program was served — the
        dispatch paid no compile) or cold (measured ``seconds``
        carries the compile), journal the decision, publish the
        first-dispatch-ready gauge and upsert the manifest record —
        all best-effort: a failing cache/manifest write leaves
        serving at zero trips (the telemetry-exporter discipline)."""
        prog_key = batch._program_key()
        warm = prog_key in self._served
        kid = key_id((batch.key, batch.capacity))
        telemetry.inc("dccrg_warm_hits_total" if warm
                      else "dccrg_warm_misses_total")
        if self._first_ready is None:
            self._first_ready = time.perf_counter() - self.t0
            telemetry.set_gauge(
                "dccrg_warm_first_dispatch_ready_seconds",
                self._first_ready)
        self._journal("warm" if warm else "cold", kid,
                      seconds=round(float(seconds), 6),
                      capacity=int(batch.capacity))
        payload = bucket_payload(batch.key)
        if payload is None:
            return  # identity-bucketed callable: never manifested
        try:
            with self._lock:
                old = self.entries.get(kid, {})
                entry = {
                    "epoch": cache_epoch(),
                    "key": payload,
                    "capacity": int(batch.capacity),
                    "integrity": bool(prog_key[2]),
                    "bulk": bool(prog_key[3]),
                    "hits": int(old.get("hits", 0)) + 1,
                    "last_hit": round(time.time(), 3),
                    "compile_s": (float(old.get("compile_s", 0.0))
                                  if warm else round(float(seconds),
                                                     6)),
                }
                write_entry(self.dir, kid, entry)
                entry["_bucket"] = batch.key
                entry["_kid"] = kid
                self.entries[kid] = entry
        except (OSError, faults.InjectedIOError) as e:
            self._degrade(WarmCacheError(kid, f"manifest write "
                                              f"failed: {e}"),
                          kid=kid)

    # -- retention ----------------------------------------------------

    def gc(self, *, max_bytes=None, max_age_s=None, dry_run=True):
        """Size/age-bounded retention over this pool's cache dir.
        Keys currently being pre-warmed (or queued for it) are
        protected; applied prunes are journaled through the
        ``warmstart.gc`` rule."""
        with self._lock:
            protect = set(self._inflight) | set(self._queue)
        report = gc(self.dir, max_bytes=max_bytes,
                    max_age_s=max_age_s, dry_run=dry_run,
                    protect=protect)
        pruned = report["pruned"]
        if not dry_run:
            with self._lock:
                for kid in report["pruned_kids"]:
                    self.entries.pop(kid, None)
            if pruned and self.autopilot is not None:
                self.autopilot.record_warm_gc(
                    pruned, {"bytes_before": report["bytes_before"],
                             "bytes_after": report["bytes_after"]})
        return report


def _with_fallback(compiled, jitted):
    """Serve the AOT executable; an input/aval mismatch (TypeError /
    ValueError at the call boundary, raised before anything executes)
    falls back to the jit path — which compiles through the same
    persistent disk cache, so even the fallback is warmer than cold.
    Execution failures (OOM and friends) propagate untouched."""
    def call(*args):
        try:
            return compiled(*args)
        except (TypeError, ValueError):
            telemetry.inc("dccrg_warm_misses_total",
                          where="aot_fallback")
            return jitted(*args)
    return call


# ---------------------------------------------------------------------
# retention GC
# ---------------------------------------------------------------------

def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True
    return True


def stale_temp_files(directory: str) -> list:
    """Dead-pid temp litter under the manifest dir — the
    ``checkpoint.stale_temp_files`` pattern applied to the
    :func:`~dccrg_tpu.coord.atomic_file_write` temp names
    (``.<name>.tmp.<pid>``): a writer that died between write and
    rename. Never matches a landed record."""
    out = []
    mdir = os.path.join(str(directory), MANIFEST_DIR)
    try:
        names = sorted(os.listdir(mdir))
    except OSError:
        return out
    for name in names:
        idx = name.rfind(".tmp.")
        if idx < 0:
            continue
        pid = name[idx + len(".tmp."):]
        if pid.isdigit() and not _pid_alive(int(pid)):
            out.append(os.path.join(mdir, name))
    return out


def _dir_bytes(paths) -> int:
    total = 0
    for p in paths:
        try:
            total += os.path.getsize(p)
        except OSError:
            pass
    return total


def gc(directory, *, max_bytes=None, max_age_s=None, dry_run=True,
       protect=(), now=None):
    """Retention GC over one cache directory: sweep dead-pid temp
    litter, then prune manifest records least-recently-hit first (and
    the ``xla/`` cache files oldest first) until the age bound
    (``max_age_s`` since last hit / mtime) and size bound
    (``max_bytes`` across manifest + xla) hold. ``dry_run=True`` (the
    default) only reports. ``protect`` is a set of kids that must
    never prune (the pool passes its in-flight prewarm keys).
    Returns a report dict; damage encountered while scanning is
    skipped, never raised."""
    directory = str(directory)
    now = time.time() if now is None else float(now)
    protect = set(protect)
    report = {"dry_run": bool(dry_run), "pruned": [],
              "pruned_kids": [], "swept_tmp": [], "kept": 0,
              "bytes_before": 0, "bytes_after": 0}
    try:
        faults.fire("warm.cache.io", op="gc")
    except OSError as e:
        # a cache-dir I/O failure degrades the GC pass to a no-op
        # report — retention is best-effort, never a crash
        telemetry.inc("dccrg_warm_cache_errors_total")
        report["error"] = str(e)
        return report
    for tmp in stale_temp_files(directory):
        report["swept_tmp"].append(tmp)
        if not dry_run:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    mdir = os.path.join(directory, MANIFEST_DIR)
    xdir = os.path.join(directory, XLA_DIR)
    recs = []  # (last_hit, path, kid, bytes)
    try:
        names = sorted(os.listdir(mdir))
    except OSError:
        names = []
    for name in names:
        if not name.endswith(RECORD_SUFFIX):
            continue
        path = os.path.join(mdir, name)
        kid = name[:-len(RECORD_SUFFIX)]
        last = 0.0
        try:
            rec = read_entry(path)
            last = float(rec.get("last_hit", 0.0))
        except (WarmCacheError, OSError):
            pass  # unreadable records sort oldest: pruned first
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        recs.append((last, path, kid, size))
    xla = []  # (mtime, path, bytes)
    try:
        for name in sorted(os.listdir(xdir)):
            path = os.path.join(xdir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            if os.path.isfile(path):
                xla.append((st.st_mtime, path, st.st_size))
    except OSError:
        pass
    recs.sort()
    xla.sort()
    total = sum(s for _t, _p, _k, s in recs) + sum(
        s for _t, _p, s in xla)
    report["bytes_before"] = total

    def prune(path, kid=None):
        report["pruned"].append(path)
        if kid is not None:
            report["pruned_kids"].append(kid)
        if not dry_run:
            try:
                os.unlink(path)
            except OSError:
                pass

    keep_recs = []
    for last, path, kid, size in recs:
        aged = (max_age_s is not None and now - last > max_age_s)
        if aged and kid not in protect:
            prune(path, kid)
            total -= size
        else:
            keep_recs.append((last, path, kid, size))
    keep_xla = []
    for mtime, path, size in xla:
        if max_age_s is not None and now - mtime > max_age_s:
            prune(path)
            total -= size
        else:
            keep_xla.append((mtime, path, size))
    if max_bytes is not None:
        # least-recently-hit records (with the oldest xla files
        # interleaved by time) go first until the budget holds
        pool = ([("rec", t, p, k, s) for t, p, k, s in keep_recs]
                + [("xla", t, p, None, s) for t, p, s in keep_xla])
        pool.sort(key=lambda e: e[1])
        for kind, _t, path, kid, size in pool:
            if total <= max_bytes:
                break
            if kind == "rec" and kid in protect:
                continue
            prune(path, kid)
            total -= size
    report["bytes_after"] = total
    report["kept"] = (len(keep_recs) + len(keep_xla)
                      - len(report["pruned"]))
    return report


# ---------------------------------------------------------------------
# CLI: python -m dccrg_tpu.warmstart list|gc
# ---------------------------------------------------------------------

def _main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m dccrg_tpu.warmstart",
        description="warm-start cache inspection + retention GC")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_list = sub.add_parser("list", help="list manifest entries")
    p_gc = sub.add_parser("gc", help="retention GC (dry-run unless "
                                     "--apply)")
    for p in (p_list, p_gc):
        p.add_argument("--dir", default=None,
                       help="cache dir (default: "
                            "$DCCRG_COMPILE_CACHE)")
    p_gc.add_argument("--max-bytes", type=int,
                      default=None, help="size bound (default: "
                                         "$DCCRG_WARM_GC_BYTES)")
    p_gc.add_argument("--max-age-s", type=float,
                      default=None, help="age bound (default: "
                                         "$DCCRG_WARM_GC_AGE_S)")
    p_gc.add_argument("--apply", action="store_true",
                      help="actually prune (default: dry-run)")
    args = ap.parse_args(argv)
    d = args.dir or cache_dir_default()
    if not d:
        print("no cache dir (set DCCRG_COMPILE_CACHE or pass --dir)")
        return 2
    if args.cmd == "list":
        entries, rejects = load_manifest(d)
        for kid, e in sorted(entries.items()):
            k = e["key"]
            print(f"{kid}  {k['kernel']:<12} "
                  f"{'x'.join(str(v) for v in k['length']):<12} "
                  f"cap={e['capacity']:<4} hits={e.get('hits', 0):<5} "
                  f"compile_s={e.get('compile_s', 0.0):.3f}")
        for path, err in rejects:
            print(f"REJECT {path}: {err}")
        print(f"{len(entries)} entries, {len(rejects)} rejected, "
              f"epoch {cache_epoch()}")
        return 0
    mb = (args.max_bytes if args.max_bytes is not None
          else gc_max_bytes_default())
    ma = (args.max_age_s if args.max_age_s is not None
          else gc_max_age_default())
    report = gc(d, max_bytes=mb, max_age_s=ma,
                dry_run=not args.apply)
    verb = "pruned" if args.apply else "would prune"
    print(f"{verb} {len(report['pruned'])} file(s), swept "
          f"{len(report['swept_tmp'])} stale temp(s), "
          f"{report['bytes_before']} -> {report['bytes_after']} "
          f"bytes{' (dry-run)' if report['dry_run'] else ''}")
    for p in report["pruned"]:
        print(f"  {verb}: {p}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(_main())
