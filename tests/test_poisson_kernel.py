"""Pallas Poisson (7-point Laplacian) kernel tests.

Same contract as test_pallas_kernel.py: on TPU the kernel runs
natively; on the CPU mesh it runs under Pallas's interpret mode, so CI
exercises the real kernel body (DMAs, semaphores, grid pipeline), not
only a numpy mirror."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dccrg_tpu.ops.poisson_kernel import (
    PallasPoissonSolver, make_laplacian_matvec,
)


def on_tpu():
    return jax.devices()[0].platform == "tpu"


def reference_laplacian(p, periodic, cell_length):
    rd = [1.0 / c**2 for c in cell_length]
    want = np.zeros_like(p)
    for d in range(3):
        for sgn in (-1, 1):
            t = np.roll(p, -sgn, axis=d) - p
            if not periodic[d]:
                idx = np.arange(p.shape[d])
                edge = (idx == p.shape[d] - 1) if sgn > 0 else (idx == 0)
                shape = [-1 if dd == d else 1 for dd in range(3)]
                t = np.where(edge.reshape(shape), 0.0, t)
            want += rd[d] * t
    return want


@pytest.mark.parametrize("periodic", [
    (True, True, True), (False, True, True), (False, False, False),
])
def test_matvec_matches_reference(periodic):
    X, Y, Z = (32, 16, 256) if on_tpu() else (16, 8, 128)
    rng = np.random.default_rng(3)
    p = rng.random((X, Y, Z)).astype(np.float32)
    mv = make_laplacian_matvec((X, Y, Z), periodic=periodic,
                               interpret=not on_tpu())
    got = np.asarray(mv(p))
    want = reference_laplacian(
        p, periodic, (1.0 / X, 1.0 / Y, 1.0 / Z)).astype(np.float32)
    scale = max(1.0, np.abs(want).max())
    np.testing.assert_allclose(got / scale, want / scale, atol=5e-6)


def test_cg_solve_matches_dense_path():
    """Full CG through the Pallas matvec lands on the same solution as
    DensePoissonSolver (the XLA dense path) on a manufactured RHS."""
    from dccrg_tpu.models.poisson import DensePoissonSolver

    X, Y, Z = 16, 8, 128
    rng = np.random.default_rng(5)
    rhs = rng.random((X, Y, Z)).astype(np.float32)
    rhs -= rhs.mean()
    pal = PallasPoissonSolver((X, Y, Z), interpret=not on_tpu())
    xs, info = pal.solve(rhs, rtol=1e-5)
    dense = DensePoissonSolver((X, Y, Z))
    xd, info_d = dense.solve(rhs, rtol=1e-5)
    assert info["iterations"] > 0
    # both solve the same SPD system to the same tolerance: compare
    # against each other after gauge fixing (both are zero-mean)
    na = np.asarray(xs, dtype=np.float64)
    nb = np.asarray(xd, dtype=np.float64)
    denom = max(np.abs(nb).max(), 1e-9)
    np.testing.assert_allclose(na / denom, nb / denom, atol=5e-4)
