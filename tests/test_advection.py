"""Advection solver tests (the reference's tests/advection workload)."""

import numpy as np
import pytest

import jax
from dccrg_tpu.dense import dense_mesh
from dccrg_tpu.models.advection import AdvectionSolver, analytic_density, hump_density


def mesh3(shape):
    n = int(np.prod(shape))
    return dense_mesh(jax.devices()[:n], shape)


def test_mass_conservation():
    s = AdvectionSolver(n=32, mesh=mesh3((2, 2, 1)))
    m0 = s.total_mass()
    for _ in range(20):
        s.step()
    assert abs(s.total_mass() - m0) < 1e-6 * max(m0, 1.0)


def test_density_bounds_and_positivity():
    s = AdvectionSolver(n=32, mesh=mesh3((2, 2, 1)))
    for _ in range(20):
        s.step()
    rho = s.grid.to_host("rho")
    assert rho.min() >= -1e-6
    assert rho.max() <= 0.5 + 1e-5  # first-order upwind never overshoots


def test_l2_error_small_after_rotation():
    # quarter rotation on 64^2: first-order upwind is diffusive but the
    # error must stay moderate and the hump must actually move
    s = AdvectionSolver(n=64, mesh=mesh3((4, 2, 1)))
    t_target = np.pi / 2
    while s.time < t_target:
        s.step(min(s.cfl * s.max_time_step(), t_target - s.time))
    err = s.l2_error()
    assert err < 0.05, err
    # hump moved: density peak now near (0.5, 0.25) (rotated -90deg...
    # velocity (0.5-y, x-0.5) rotates counterclockwise: (0.25,0.5)->(0.5,0.25)
    rho = s.grid.to_host("rho")[:, :, 0]
    i, j = np.unravel_index(np.argmax(rho), rho.shape)
    x, y = (i + 0.5) / 64, (j + 0.5) / 64
    assert abs(x - 0.5) < 0.1 and abs(y - 0.25) < 0.1, (x, y)


def test_convergence_with_resolution():
    errs = []
    for n in (32, 64):
        s = AdvectionSolver(n=n, mesh=mesh3((1, 1, 1)))
        t_target = np.pi / 8
        while s.time < t_target:
            s.step(min(s.cfl * s.max_time_step(), t_target - s.time))
        errs.append(s.l2_error())
    assert errs[1] < errs[0]  # finer grid -> smaller error


def test_device_invariance():
    """Identical results on 1 device and on a 2x2x2 mesh."""
    results = []
    for shape in ((1, 1, 1), (2, 2, 2)):
        s = AdvectionSolver(n=16, nz=8, mesh=mesh3(shape))
        for _ in range(10):
            s.step(0.4 * s.max_time_step())
        results.append(s.grid.to_host("rho"))
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6, atol=1e-7)


def test_3d_replicates_2d_along_z():
    s = AdvectionSolver(n=16, nz=4, mesh=mesh3((2, 1, 2)))
    for _ in range(5):
        s.step()
    rho = s.grid.to_host("rho")
    for k in range(1, 4):
        np.testing.assert_allclose(rho[:, :, k], rho[:, :, 0], rtol=1e-6, atol=1e-7)


def test_max_time_step_matches_cfl():
    s = AdvectionSolver(n=32, mesh=mesh3((1, 1, 1)))
    # max |v| on the grid is at the domain corners: sqrt(2)*~0.5 per axis;
    # dt = min over dims of dx/|v|
    vx = s.grid.to_host("vx")
    vy = s.grid.to_host("vy")
    expect = min(
        (1 / 32) / np.abs(vx)[np.abs(vx) > 0].max(),
        (1 / 32) / np.abs(vy)[np.abs(vy) > 0].max(),
    )
    assert np.isclose(s.max_time_step(), expect, rtol=1e-6)


def test_grid_path_matches_dense_path():
    """GridAdvection (general gather tables + run_steps) must produce
    the same density field as the dense fast path, cell for cell."""
    from dccrg_tpu.models.advection import GridAdvection
    from jax.sharding import Mesh
    import jax

    n, nz = 16, 4
    dense = AdvectionSolver(n=n, nz=nz, mesh=mesh3((1, 1, 1)))
    gridp = GridAdvection(n=n, nz=nz,
                          mesh=Mesh(np.array(jax.devices()[:4]), ("dev",)))
    dt = 0.4 * dense.max_time_step()
    assert np.isclose(gridp.max_time_step(), dense.max_time_step(), rtol=1e-6)
    for _ in range(8):
        dense.step(dt)
    gridp.run(8, dt)
    want = dense.grid.to_host("rho")  # [nx, ny, nz]
    got = gridp.density()  # cells sorted by id: x fastest, then y, z
    got3 = got.reshape(nz, n, n).transpose(2, 1, 0)
    np.testing.assert_allclose(got3, want, rtol=2e-5, atol=1e-6)
    assert abs(gridp.l2_error() - dense.l2_error()) < 1e-6
    assert np.isfinite(gridp.checksum())


def test_grid_path_convergence_with_resolution():
    """First-order upwind on the general Grid path: L2 error vs the
    analytic rotated hump decreases with resolution (the reference's
    convergence expectation for its scheme)."""
    from dccrg_tpu.models.advection import GridAdvection
    from jax.sharding import Mesh
    import jax

    errs = []
    for n in (24, 48):
        s = GridAdvection(n=n, nz=1,
                          mesh=Mesh(np.array(jax.devices()[:4]), ("dev",)))
        dt = 0.4 * s.max_time_step()
        s.run(12, dt)
        errs.append(s.l2_error())
    assert errs[1] < 0.75 * errs[0], errs


def test_checksum_stable_across_balance():
    """local_row_mask is cached per plan epoch: a repartition that
    stays inside the same capacity bucket (identical array shapes)
    must still refresh the mask, so checksum (= total density over
    local rows) is unchanged by load balancing."""
    from dccrg_tpu.models.advection import GridAdvection
    from jax.sharding import Mesh
    import jax

    a = GridAdvection(n=8, nz=4,
                      mesh=Mesh(np.array(jax.devices()[:4]), ("dev",)))
    c0 = a.checksum()
    a.grid.set_partitioning_option("method", "rcb")
    a.grid.balance_load()
    c1 = a.checksum()
    assert np.isclose(c0, c1, rtol=1e-6), (c0, c1)


def test_grid_advection_bf16_storage():
    """bfloat16 field storage (the TPU HBM-bandwidth lever): compute
    stays float32, storage narrows. The first-order scheme's physics
    must survive — mass approximately conserved and the solution close
    to the float32 run."""
    import jax.numpy as jnp
    from dccrg_tpu.models.advection import GridAdvection

    runs = {}
    for dt_ in ("f32", "bf16"):
        s = GridAdvection(
            n=32, nz=8,
            dtype=jnp.float32 if dt_ == "f32" else jnp.bfloat16)
        m0 = s.checksum()
        step = 0.5 * s.max_time_step()
        s.run(12, step)
        if dt_ == "bf16":
            # storage stayed narrow THROUGH the fused loop's writeback
            assert s.grid.data["density"].dtype == jnp.bfloat16
        runs[dt_] = (m0, s.checksum(), s.l2_error())
    m0, m1, l2_bf = runs["bf16"]
    assert abs(m1 - m0) < 2e-2 * max(m0, 1.0)  # bf16 writeback rounding
    _, _, l2_f32 = runs["f32"]
    assert l2_bf < 3.0 * max(l2_f32, 1e-3)
