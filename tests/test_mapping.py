"""Mapping unit tests.

Mirrors the reference's addressing semantics (dccrg_mapping.hpp) and its
tests (tests/get_neighbors_, tests/mapping usage in dccrg tests): cell
ids are 1-based and level-major, indices are in smallest-cell units,
children enumerate in z-order with x fastest.
"""

import numpy as np
import pytest

from dccrg_tpu import ERROR_CELL, ERROR_INDEX, Mapping


def test_level0_ids_x_fastest():
    m = Mapping((4, 3, 2))
    # 1-based, x fastest: cell at (i,j,k) = 1 + i + j*4 + k*12
    assert m.get_cell_from_indices((0, 0, 0), 0) == 1
    assert m.get_cell_from_indices((1, 0, 0), 0) == 2
    assert m.get_cell_from_indices((0, 1, 0), 0) == 5
    assert m.get_cell_from_indices((0, 0, 1), 0) == 13
    assert m.get_cell_from_indices((3, 2, 1), 0) == 24
    assert m.get_last_cell() == 24


def test_roundtrip_level0():
    m = Mapping((5, 7, 3))
    cells = np.arange(1, 5 * 7 * 3 + 1, dtype=np.uint64)
    idx = m.get_indices(cells)
    back = m.get_cell_from_indices(idx, np.zeros(len(cells), dtype=np.int64))
    np.testing.assert_array_equal(back, cells)


def test_refined_id_ranges():
    m = Mapping((2, 2, 2), maximum_refinement_level=2)
    # level 0: ids 1..8; level 1: 9..72 (8*8); level 2: 73..584 (8*64)
    assert m.get_refinement_level(1) == 0
    assert m.get_refinement_level(8) == 0
    assert m.get_refinement_level(9) == 1
    assert m.get_refinement_level(72) == 1
    assert m.get_refinement_level(73) == 2
    assert m.get_last_cell() == 8 + 64 + 512
    assert m.get_refinement_level(int(m.get_last_cell())) == 2
    assert m.get_refinement_level(int(m.get_last_cell()) + 1) == -1
    assert m.get_refinement_level(0) == -1


def test_indices_scaling_with_refinement():
    m = Mapping((2, 1, 1), maximum_refinement_level=1)
    # level-0 cell 2 is at level-0 index (1,0,0) -> smallest-unit (2,0,0)
    np.testing.assert_array_equal(m.get_indices(np.uint64(2)), [2, 0, 0])
    assert m.get_cell_length_in_indices(np.uint64(1)) == 2
    # first level-1 cell is id 3, at indices (0,0,0), length 1
    assert m.get_refinement_level(3) == 1
    np.testing.assert_array_equal(m.get_indices(np.uint64(3)), [0, 0, 0])
    assert m.get_cell_length_in_indices(np.uint64(3)) == 1


def test_children_z_order():
    m = Mapping((2, 1, 1), maximum_refinement_level=1)
    kids = m.get_all_children(np.uint64(1))
    # children of cell 1: level-1 cells in z-order, x fastest
    # level-1 grid is 4x2x2; first child at (0,0,0) -> id 3
    assert kids[0] == 3
    assert kids[1] == 4  # +x
    assert kids[2] == 7  # +y (level-1 x-extent 4)
    assert kids[3] == 8
    assert kids[4] == 11  # +z (4*2 = 8 per z-layer)
    assert kids[5] == 12
    assert kids[7] == 16
    # all children's parent is cell 1
    np.testing.assert_array_equal(m.get_parent(kids), np.full(8, 1, dtype=np.uint64))


def test_parent_child_identity_cases():
    m = Mapping((2, 2, 2), maximum_refinement_level=1)
    # level-0 cell: parent is itself
    assert m.get_parent(np.uint64(5)) == 5
    # max-level cell: child is itself
    last = m.get_last_cell()
    assert m.get_child(last) == last
    # invalid
    assert m.get_parent(np.uint64(0)) == ERROR_CELL
    assert m.get_child(np.uint64(0)) == ERROR_CELL


def test_siblings():
    m = Mapping((2, 1, 1), maximum_refinement_level=1)
    kids = m.get_all_children(np.uint64(2))
    sibs = m.get_siblings(kids[3])
    np.testing.assert_array_equal(np.sort(sibs), np.sort(kids))
    # level-0 cell: itself + 7 error cells
    s0 = m.get_siblings(np.uint64(1))
    assert s0[0] == 1
    assert np.all(s0[1:] == ERROR_CELL)


def test_level_0_parent():
    m = Mapping((2, 2, 1), maximum_refinement_level=2)
    c = m.get_all_children(np.uint64(3))[5]
    g = m.get_all_children(c)[2]
    assert m.get_level_0_parent(g) == 3
    assert m.get_level_0_parent(np.uint64(3)) == 3


def test_out_of_range_indices():
    m = Mapping((4, 4, 4), maximum_refinement_level=1)
    assert m.get_cell_from_indices((8, 0, 0), 0) == ERROR_CELL
    assert m.get_cell_from_indices((0, 0, 0), 2) == ERROR_CELL
    assert m.get_cell_from_indices((0, 0, 0), -1) == ERROR_CELL
    np.testing.assert_array_equal(m.get_indices(np.uint64(0)), [ERROR_INDEX] * 3)


def test_max_possible_refinement_level():
    m = Mapping((1, 1, 1))
    # sum_{i=0..21} 8^i <= 2^64-1 < sum_{i=0..22} 8^i
    assert m.get_maximum_possible_refinement_level() == 21
    assert m.set_maximum_refinement_level(21)
    assert not m.set_maximum_refinement_level(22)
    big = Mapping((1000, 1000, 1000))
    # 1e9 * (8^L sum) must fit
    lvl = big.get_maximum_possible_refinement_level()
    total = sum(10**9 * 8**i for i in range(lvl + 1))
    assert total <= 2**64 - 1
    assert sum(10**9 * 8**i for i in range(lvl + 2)) > 2**64 - 1


def test_file_roundtrip():
    m = Mapping((6, 5, 4), maximum_refinement_level=3)
    m2 = Mapping.from_bytes(m.to_bytes())
    assert m == m2
    assert m2.get_last_cell() == m.get_last_cell()


def test_vectorized_matches_scalar():
    m = Mapping((3, 4, 5), maximum_refinement_level=2)
    rng = np.random.default_rng(0)
    cells = rng.integers(1, int(m.get_last_cell()) + 1, size=200, dtype=np.uint64)
    idx = m.get_indices(cells)
    lvl = m.get_refinement_level(cells)
    for i in range(0, 200, 17):
        c = np.uint64(cells[i])
        np.testing.assert_array_equal(m.get_indices(c), idx[i])
        assert m.get_refinement_level(c) == lvl[i]
        assert m.get_cell_from_indices(idx[i], int(lvl[i])) == c


def test_set_length_rejects_incompatible_max_level():
    m = Mapping((1, 1, 1), maximum_refinement_level=21)
    assert not m.set_length((1000, 1000, 1000))
    # unchanged on failure
    np.testing.assert_array_equal(m.length.get(), [1, 1, 1])
    assert m.get_refinement_level(1) == 0
    m2 = Mapping((1, 1, 1))
    assert m2.set_length((1000, 1000, 1000))


def test_huge_grid_construction():
    m = Mapping((2**32 - 1, 2**16, 2**16))
    assert m.get_last_cell() == (2**32 - 1) * 2**32
    assert m.get_refinement_level(int(m.get_last_cell())) == 0


def test_negative_ids_are_error_values():
    m = Mapping((4, 4, 4))
    assert m.get_refinement_level(-1) == -1
    assert m.get_parent(-5) == ERROR_CELL
    lvls = m.get_refinement_level(np.array([-1, 1, 2**70], dtype=object))
    np.testing.assert_array_equal(lvls, [-1, 0, -1])


def test_scalar_out_convention():
    m = Mapping((2, 2, 2), maximum_refinement_level=1)
    assert np.isscalar(m.get_refinement_level(1)) or np.ndim(m.get_refinement_level(1)) == 0
    assert np.ndim(m.get_parent(np.uint64(9))) == 0
    assert np.ndim(m.get_child(np.uint64(1))) == 0
    assert np.ndim(m.get_cell_length_in_indices(np.uint64(1))) == 0
    assert m.get_all_children(np.uint64(1)).shape == (8,)
    assert m.get_siblings(np.uint64(9)).shape == (8,)
    assert m.get_parent(np.array([9, 10], dtype=np.uint64)).shape == (2,)
