"""Multi-process (jax.distributed) lifting, validated by FAKING the
process split on the single-controller test mesh.

A multi-process mesh differs from a single-controller one only in
which shards the host may touch: uploads go through put_sharded (each
process serves its addressable shards), get/set become rank-local
(the reference's operator[] semantics, dccrg.hpp:7738-7803), and
checkpoint I/O writes per-process slices through the TWO-PHASE COMMIT
protocol (slices into ``<file>.mp-tmp`` with per-run CRC32s, commit
barrier, verify + atomic rename by the committing rank — hardening
the reference's collective MPI-IO write, dccrg.hpp:1594-1659, against
rank death). Faking ``grid._proc_local_dev`` (+ a per-pass
``_ckpt_rank``) exercises exactly those code paths; the shards stay
addressable underneath, so the restriction logic and the
slice-merging can be verified byte-for-byte against the
single-controller result — two faked processes writing one file must
reproduce the single-save file exactly, and a rank killed at ANY save
phase must leave the previous checkpoint bitwise intact. The REAL
(multi-OS-process, jax.distributed) version of these scenarios runs
in tests/mp_harness.py.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dccrg_tpu import coord, faults, resilience
from dccrg_tpu import checkpoint as checkpoint_mod
from dccrg_tpu.grid import Grid


def _mk(fields=None, n=(8, 8, 8)):
    g = (
        Grid(cell_data=fields or {"v": jnp.float32})
        .set_initial_length(n)
        .set_periodic(True, True, False)
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .initialize(partition="block")
    )
    return g


def _fake_split(g, local_devs, rank=None):
    g._proc_local_dev = np.array(
        [d in set(local_devs) for d in range(g.n_dev)], dtype=bool)
    g._ckpt_rank = rank


def _unfake(g):
    g._proc_local_dev = np.ones(g.n_dev, dtype=bool)
    g._ckpt_rank = None
    for attr in ("_ckpt_writes_meta", "_ckpt_commits"):
        if hasattr(g, attr):
            delattr(g, attr)


@pytest.fixture(autouse=True)
def _clean_mp_state():
    """Rank-death tests abort mid-protocol; never leak staged CRCs or
    temp files into the next test."""
    yield
    checkpoint_mod._MP_CRC_STAGE.clear()


def _rank_pass(g, rank, fn, **save_kwargs):
    """One fake rank's pass of the two-phase save protocol: rank 0 is
    the meta writer, the LAST rank (1 of 2 here) commits."""
    half = g.n_dev // 2
    _fake_split(g, range(half) if rank == 0 else range(half, g.n_dev),
                rank=rank)
    g._ckpt_writes_meta = rank == 0
    g._ckpt_commits = rank == 1
    g.save_grid_data(str(fn), **save_kwargs)


def _two_pass_save(g, fn, **save_kwargs):
    for rank in (0, 1):
        _rank_pass(g, rank, fn, **save_kwargs)
    _unfake(g)


def test_get_set_are_rank_local():
    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(11)).astype(np.float32))
    half = list(range(g.n_dev // 2))
    _fake_split(g, half)
    assert g._multiproc
    local = np.isin(g.plan.owner, half)
    my, foreign = cells[local], cells[~local]

    # local reads work and match the single-controller values
    got = g.get("v", my[:100])
    np.testing.assert_array_equal(
        got, (my[:100] % np.uint64(11)).astype(np.float32))

    # foreign access fails loudly (reference: operator[] is rank-local)
    with pytest.raises(KeyError, match="process-local"):
        g.get("v", foreign[:3])
    with pytest.raises(KeyError, match="process-local"):
        g.set("v", foreign[:3], np.zeros(3, np.float32))

    # local writes land (verified through the unfaked full view)
    g.set("v", my[:5], np.full(5, 99.0, np.float32))
    _unfake(g)
    np.testing.assert_array_equal(g.get("v", my[:5]),
                                  np.full(5, 99.0, np.float32))


def test_collective_paths_unchanged_under_split():
    """Halo exchange + fused steps use replicated tables and
    collectives only — a faked process split must not change them."""
    def kern(cell, nbr, offs, mask):
        return {"v": 0.5 * cell["v"] + 0.125 * jnp.sum(
            jnp.where(mask, nbr["v"], 0.0), axis=1)}

    res = []
    for split in (False, True):
        g = _mk()
        cells = g.plan.cells
        g.set("v", cells, (cells % np.uint64(7)).astype(np.float32))
        g.update_copies_of_remote_neighbors()
        if split:
            _fake_split(g, range(g.n_dev // 2))
        g.run_steps(kern, ["v"], ["v"], 3)
        _unfake(g)
        res.append(g.get("v", cells))
    np.testing.assert_array_equal(res[0], res[1])


def _single_vs_split_save(make_grid, tmp_path, **save_kwargs):
    """Save an identically-built grid once single-controller and once
    as two faked processes running the TWO-PHASE protocol into one
    file; return both byte strings. The protocol under test: rank 0
    writes meta + its slice runs into the .mp-tmp, rank 1
    (_ckpt_writes_meta=False, _ckpt_commits=True) fills its own runs,
    verifies every slice CRC and atomically publishes."""
    files = {}
    for mode in ("single", "split"):
        g = make_grid()
        fn = tmp_path / f"{mode}.dc"
        if mode == "single":
            g.save_grid_data(str(fn), **save_kwargs)
        else:
            _two_pass_save(g, fn, **save_kwargs)
        files[mode] = fn.read_bytes()
    return files["single"], files["split"]


def test_two_process_checkpoint_slices_merge_exactly(tmp_path):
    """Two faked processes filling one file == the single-save file."""
    def make():
        g = _mk({"v": jnp.float32, "w": jnp.int32})
        cells = g.plan.cells
        rng = np.random.default_rng(3)
        g.set("v", cells, rng.random(len(cells)).astype(np.float32))
        g.set("w", cells, (cells % np.uint64(5)).astype(np.int32))
        return g

    single, split = _single_vs_split_save(make, tmp_path, header=b"HDR!")
    assert single == split


def test_two_process_ragged_checkpoint(tmp_path):
    """Variable-size payloads: counts ride the replicated device
    gather, ragged rows ride per-process shard reads."""
    cap = 4

    def make():
        g = _mk({"n": jnp.int32, "p": ((cap, 2), jnp.float32)})
        cells = g.plan.cells
        rng = np.random.default_rng(5)
        g.set("n", cells,
              rng.integers(0, cap + 1, len(cells)).astype(np.int32))
        g.set("p", cells, rng.random((len(cells), cap, 2)).astype(np.float32))
        return g

    single, split = _single_vs_split_save(make, tmp_path,
                                          variable={"p": "n"})
    assert single == split


def test_two_process_slices_on_refined_morton_grid(tmp_path):
    """Fragmented ownership (morton partition + refinement): the
    per-process payload runs are many and interleaved; the merged file
    must still be byte-identical to the single-controller save."""
    def make():
        g = (
            Grid(cell_data={"v": jnp.float32})
            .set_initial_length((6, 6, 4))
            .set_maximum_refinement_level(1)
            .set_neighborhood_length(1)
            .initialize(partition="morton")
        )
        for cid in g.local_cells().ids[::17]:
            g.refine_completely(int(cid))
        g.stop_refining()
        cells = g.plan.cells
        g.set("v", cells, (cells % np.uint64(19)).astype(np.float32))
        return g

    single, split = _single_vs_split_save(make, tmp_path)
    assert single == split


def test_process_local_load(tmp_path):
    """Each process scatters only its cells; foreign rows stay zero
    (their real shards are served by the owning process)."""
    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(13)).astype(np.float32))
    fn = str(tmp_path / "a.dc")
    g.save_grid_data(fn)

    g2 = _mk()
    half = list(range(g2.n_dev // 2))
    _fake_split(g2, half)
    g2.load_grid_data(fn)
    _unfake(g2)
    local = np.isin(g2.plan.owner, half)
    np.testing.assert_array_equal(
        g2.get("v", cells[local]),
        (cells[local] % np.uint64(13)).astype(np.float32))
    assert not np.any(g2.get("v", cells[~local]))


def test_full_cover_set_preserving_ghosts_under_split():
    """A replicated full-cover set() with preserve_ghosts=True (the
    standard init idiom) must work on a multi-process mesh: new values
    ride put_sharded, ghost rows keep their old values via an
    on-device merge."""
    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, np.ones(len(cells), np.float32))
    g.update_copies_of_remote_neighbors()  # ghosts now 1.0
    _fake_split(g, range(g.n_dev // 2))
    g.set("v", cells, np.full(len(cells), 2.0, np.float32))  # full cover
    _unfake(g)
    np.testing.assert_array_equal(
        g.get("v", cells), np.full(len(cells), 2.0, np.float32))
    # ghost rows were preserved (still 1.0, not zeroed): check one
    # device's ghost block directly
    host = np.asarray(g.data["v"])
    L = g.plan.L
    for d in range(g.n_dev):
        ng = len(g.plan.ghost_ids[d])
        if ng:
            np.testing.assert_array_equal(host[d, L:L + ng],
                                          np.ones(ng, np.float32))


def test_amr_commit_and_balance_under_split():
    """The whole AMR pipeline — refine, commit, projection, balance —
    must produce the same structure and data under a faked process
    split as single-controller (the structure decisions are replicated;
    data movement is device-side)."""
    results = {}
    for split in (False, True):
        g = (
            Grid(cell_data={"v": jnp.float32})
            .set_initial_length((8, 8, 4))
            .set_periodic(True, True, False)
            .set_maximum_refinement_level(1)
            .set_neighborhood_length(1)
            .initialize(partition="block")
        )
        cells = g.plan.cells
        g.set("v", cells, (cells % np.uint64(23)).astype(np.float32))
        if split:
            _fake_split(g, range(g.n_dev // 2))
        for cid in g.plan.cells[:12:3]:
            g.refine_completely(int(cid))
        g.stop_refining()
        g.assign_children_from_parents(fields=["v"])
        g.clear_refined_unrefined_data()
        g.set_partitioning_option("method", "morton")
        g.balance_load()
        g.update_copies_of_remote_neighbors()
        _unfake(g)
        results[split] = (g.plan.cells.copy(), g.plan.owner.copy(),
                          g.get("v", g.plan.cells))
    np.testing.assert_array_equal(results[False][0], results[True][0])
    np.testing.assert_array_equal(results[False][1], results[True][1])
    np.testing.assert_array_equal(results[False][2], results[True][2])


def test_staged_balance_peek_is_rank_local():
    """staged_balance_data under a process split returns only this
    process's moving cells, read from addressable shards."""
    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(9)).astype(np.float32))
    for c in cells[:6]:
        g.pin(int(c), (g.get_process(int(c)) + 1) % g.n_dev)
    g.initialize_balance_load(use_zoltan=False)
    g.continue_balance_load()
    all_ids, all_vals = g.staged_balance_data("v")
    half = list(range(g.n_dev // 2))
    _fake_split(g, half)
    ids, vals = g.staged_balance_data("v")
    _unfake(g)
    dev, _ = g._host_rows(ids)
    assert np.isin(dev, half).all()
    sel = np.isin(all_ids, ids)
    np.testing.assert_array_equal(all_vals[sel], vals)
    g.finish_balance_load()  # leave the grid consistent


def test_ppermute_exchange_never_materializes_dense_pair_tables():
    """Pod-scale memory: the per-delta ppermute exchange works from
    the compact O(ghosts) pair record; the dense [n_dev, n_dev, M]
    arrays must stay unmaterialized unless the all_to_all fallback or
    a host introspection API asks for them."""
    from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID

    if os.environ.get("DCCRG_DEBUG") == "1":
        pytest.skip("DEBUG verifiers materialize the dense pair tables "
                    "by design (verify_remote_neighbor_info reads "
                    "send_rows/recv_rows)")

    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(7)).astype(np.float32))
    g.update_copies_of_remote_neighbors()
    g.run_steps(lambda c, n, o, m: {"v": c["v"]}, ["v"], ["v"], 2)
    hood = g.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    assert hood._send_rows is None and hood._recv_rows is None
    # introspection still works, via lazy materialization
    sends = g.get_cells_to_send()
    assert sends and hood._send_rows is None  # compact-backed
    _ = hood.send_rows
    assert hood._send_rows is not None


# -- two-phase-commit save: atomicity under rank death ----------------

def _value_grid(val=None):
    g = _mk()
    cells = g.plan.cells
    if val is None:
        g.set("v", cells, (cells % np.uint64(11)).astype(np.float32))
    else:
        g.set("v", cells, np.full(len(cells), val, np.float32))
    return g


def test_two_phase_publishes_only_at_commit(tmp_path):
    """Nothing appears under the final name until the committing rank
    has verified every slice: after rank 0's pass only the .mp-tmp
    exists; after rank 1's commit the final file exists, the temp is
    gone, and the bytes equal the single-controller save."""
    fn = tmp_path / "a.dc"
    single = tmp_path / "s.dc"
    _value_grid().save_grid_data(str(single))

    g = _value_grid()
    _rank_pass(g, 0, fn, sidecar=True)
    assert not fn.exists()
    assert os.path.exists(str(fn) + checkpoint_mod.MP_TMP_SUFFIX)
    _rank_pass(g, 1, fn, sidecar=True)
    assert fn.exists()
    assert not os.path.exists(str(fn) + checkpoint_mod.MP_TMP_SUFFIX)
    assert fn.read_bytes() == single.read_bytes()
    # the committing rank wrote the sidecar, extended with the
    # per-rank slice table, and it verifies clean
    rec = resilience.read_sidecar(str(fn))
    assert rec["slices"] and all(len(s) == 5 for s in rec["slices"])
    assert {s[1] for s in rec["slices"]} == {0, 1}
    assert resilience.verify_checkpoint(str(fn)) == []


@pytest.mark.faultinject
@pytest.mark.parametrize("rank,phase", [
    (0, "meta"), (0, "slice"), (0, "written"),
    (1, "slice"), (1, "written"), (1, "commit"),
])
def test_rank_death_at_every_phase_preserves_old_checkpoint(
        tmp_path, rank, phase):
    """Kill one fake rank at each instrumented save phase: the
    surviving protocol must never publish a torn file — the previous
    checkpoint stays bitwise intact, still verifies against its
    sidecar, and still loads."""
    fn = tmp_path / "ck.dc"
    _two_pass_save(_value_grid(), fn, sidecar=True)
    good = fn.read_bytes()
    good_side = (tmp_path / "ck.dc.crc").read_bytes()

    g = _value_grid(7.0)  # new state that must NOT reach the file
    plan = faults.FaultPlan(seed=11)
    plan.rank_death(phase=phase, rank=rank)
    outcomes = []
    with plan:
        for r in (0, 1):
            try:
                _rank_pass(g, r, fn, sidecar=True)
            except Exception as e:  # noqa: BLE001 - recorded + asserted
                outcomes.append((r, type(e)))
    assert (rank, faults.InjectedRankDeath) in outcomes
    if rank == 0:
        # the survivor is the committer: it must have ABORTED (missing
        # or unverifiable slices), loudly, not published garbage
        assert any(issubclass(t, (coord.CheckpointCommitError,
                                  OSError))
                   for r, t in outcomes if r == 1)
    assert fn.read_bytes() == good
    assert (tmp_path / "ck.dc.crc").read_bytes() == good_side
    assert resilience.verify_checkpoint(str(fn)) == []
    grid, _hdr, rep = resilience.load_checkpoint(str(fn),
                                                 {"v": jnp.float32})
    assert rep.clean
    cells = grid.plan.cells
    np.testing.assert_array_equal(
        grid.get("v", cells), (cells % np.uint64(11)).astype(np.float32))


@pytest.mark.faultinject
def test_rank_death_after_publish_leaves_new_checkpoint(tmp_path):
    """Death between the rename and the sidecar write: the NEW bytes
    are published whole (the rename already happened) with no sidecar
    — strict load refuses conservatively, salvage load returns the new
    state. 'Either the old or the new checkpoint intact' — this is the
    'new' arm."""
    fn = tmp_path / "p.dc"
    _two_pass_save(_value_grid(), fn, sidecar=True)

    g = _value_grid(5.0)
    plan = faults.FaultPlan()
    plan.rank_death(phase="publish", rank=1)
    with plan:
        _rank_pass(g, 0, fn, sidecar=True)
        with pytest.raises(faults.InjectedRankDeath):
            _rank_pass(g, 1, fn, sidecar=True)
    single = tmp_path / "s.dc"
    _value_grid(5.0).save_grid_data(str(single))
    assert fn.read_bytes() == single.read_bytes()  # new bytes, whole
    with pytest.raises(resilience.CheckpointCorruptionError,
                       match="no checksum sidecar"):
        resilience.load_checkpoint(str(fn), {"v": jnp.float32})
    grid, _hdr, rep = resilience.load_checkpoint(
        str(fn), {"v": jnp.float32}, strict=False)
    assert rep.sidecar_missing
    np.testing.assert_array_equal(
        grid.get("v", grid.plan.cells),
        np.full(len(grid.plan.cells), 5.0, np.float32))


@pytest.mark.faultinject
def test_commit_verify_catches_torn_slice_and_metadata(tmp_path):
    """Bytes torn in the temp file AFTER a rank wrote them (its death
    mid-pwrite, a flaky disk): the committing rank's verification pass
    catches both a torn payload slice (naming the writer rank) and a
    torn metadata block, and never publishes."""
    fn = tmp_path / "t.dc"
    tmp = str(fn) + checkpoint_mod.MP_TMP_SUFFIX
    # torn payload slice of rank 0
    g = _value_grid()
    _rank_pass(g, 0, fn, sidecar=True)
    ps = resilience._sidecar_record(tmp)["payload_start"]
    faults.flip_bit(tmp, ps + 3, 1)
    with pytest.raises(coord.CheckpointCommitError) as ei:
        _rank_pass(g, 1, fn, sidecar=True)
    assert ei.value.ranks == [0]
    assert not fn.exists()
    checkpoint_mod._MP_CRC_STAGE.clear()
    # torn metadata (offset table) — replicated bytes, verified
    # without any CRC exchange
    g = _value_grid()
    _rank_pass(g, 0, fn, sidecar=True)
    faults.flip_bit(tmp, 100, 1)
    with pytest.raises(coord.CheckpointCommitError, match="metadata"):
        _rank_pass(g, 1, fn, sidecar=True)
    assert not fn.exists()


@pytest.mark.faultinject
def test_injected_io_fault_mid_slice_never_tears_final(tmp_path):
    """A transient I/O error during one rank's slice stream aborts that
    rank's pass; the final name is never touched."""
    fn = tmp_path / "io.dc"
    _two_pass_save(_value_grid(), fn, sidecar=True)
    good = fn.read_bytes()
    g = _value_grid(9.0)
    plan = faults.FaultPlan()
    plan.io_error(site="checkpoint.mp", phase="slice", rank=1)
    with plan:
        _rank_pass(g, 0, fn, sidecar=True)
        with pytest.raises(faults.InjectedIOError):
            _rank_pass(g, 1, fn, sidecar=True)
    assert fn.read_bytes() == good
    assert resilience.verify_checkpoint(str(fn)) == []


@pytest.mark.faultinject
def test_barrier_hang_during_save_times_out_not_hangs(tmp_path):
    """A lost rank at the commit barrier surfaces as a typed
    BarrierTimeoutError naming the tag, within the configured bound —
    never an infinite hang — and nothing is published."""
    import time

    fn = tmp_path / "h.dc"
    g = _value_grid()
    plan = faults.FaultPlan()
    plan.barrier_hang(tag="save_commit:h.dc")
    t0 = time.monotonic()
    with plan, pytest.raises(coord.BarrierTimeoutError,
                             match="save_commit"):
        g_ = g
        half = g_.n_dev // 2
        _fake_split(g_, range(half), rank=0)
        g_._ckpt_writes_meta, g_._ckpt_commits = True, False
        os.environ["DCCRG_BARRIER_TIMEOUT"] = "0.3"
        try:
            g_.save_grid_data(str(fn))
        finally:
            del os.environ["DCCRG_BARRIER_TIMEOUT"]
    assert time.monotonic() - t0 < 5.0
    assert not fn.exists()


@pytest.mark.faultinject
def test_save_barrier_tags_carry_attempt_epoch(tmp_path):
    """Every save's barrier tags embed a per-grid attempt epoch
    (`#<n>`), so a collective retry after an asymmetric mid-protocol
    failure re-aligns the ranks' barrier ids by construction. Pinned
    via the fault log: hangs pinned to the tag PREFIX fire on distinct
    full tags across saves."""
    fn = tmp_path / "e.dc"
    g = _value_grid()
    plan = faults.FaultPlan()
    plan.barrier_hang(tag="save_prepare:e.dc", times=2, hang_s=0.01)
    with plan:
        _two_pass_save(g, fn)
        _two_pass_save(g, fn)
    tags = [d["tag"] for s, _k, d in plan.log
            if s == "coord.barrier_hang"]
    assert len(tags) == 2
    assert all(t.startswith("save_prepare:e.dc#") for t in tags)
    assert tags[0] != tags[1]


@pytest.mark.faultinject
def test_salvage_load_names_dead_ranks_cells(tmp_path):
    """At-rest corruption inside one rank's slice: strict load names
    the writer rank; salvage returns the intact cells and reports
    dead_ranks + the zeroed cells (which belong to that rank)."""
    fn = tmp_path / "sv.dc"
    g = _value_grid()
    _two_pass_save(g, fn, sidecar=True, sidecar_chunk_bytes=256)
    rec = resilience.read_sidecar(str(fn))
    sl = next(s for s in rec["slices"] if s[1] == 1)
    faults.flip_bit(str(fn), sl[2] + 5, 2)

    with pytest.raises(resilience.CheckpointCorruptionError,
                       match=r"rank\(s\) \[1\]"):
        resilience.load_checkpoint(str(fn), {"v": jnp.float32})
    grid, _hdr, rep = resilience.load_checkpoint(
        str(fn), {"v": jnp.float32}, strict=False)
    assert rep.dead_ranks == [1]
    assert len(rep.bad_slices) == 1
    assert len(rep.corrupt_cells)
    # every zeroed cell belongs to a device the dead rank wrote
    pos = np.searchsorted(grid.plan.cells, rep.corrupt_cells)
    rank1_devs = set(range(g.n_dev // 2, g.n_dev))
    assert set(grid.plan.owner[pos].tolist()) <= rank1_devs
    # the surviving rank's cells are intact
    ok = ~np.isin(grid.plan.cells, rep.corrupt_cells)
    cells = grid.plan.cells[ok]
    np.testing.assert_array_equal(
        grid.get("v", cells), (cells % np.uint64(11)).astype(np.float32))


def test_save_checkpoint_routes_multiproc_through_two_phase(tmp_path):
    """resilience.save_checkpoint on a multi-process grid delegates to
    the two-phase save (the single-controller tmp.pid protocol cannot
    work across ranks) and still produces a verifying sidecar."""
    fn = tmp_path / "rc.dc"
    g = _value_grid()
    half = g.n_dev // 2
    _fake_split(g, range(half), rank=0)
    g._ckpt_writes_meta, g._ckpt_commits = True, False
    resilience.save_checkpoint(g, str(fn))
    assert not fn.exists()  # two-phase: nothing published yet
    _fake_split(g, range(half, g.n_dev), rank=1)
    g._ckpt_writes_meta, g._ckpt_commits = False, True
    resilience.save_checkpoint(g, str(fn))
    _unfake(g)
    assert resilience.verify_checkpoint(str(fn)) == []
    single = tmp_path / "s.dc"
    _value_grid().save_grid_data(str(single))
    assert fn.read_bytes() == single.read_bytes()


def test_initialize_accepts_foreign_process_mesh_structurally():
    """initialize() no longer refuses multi-process meshes; the plan it
    builds is pure replicated host structure, identical to the
    single-controller one (every process computes the same plan)."""
    g1 = _mk()
    g2 = _mk()
    _fake_split(g2, range(g2.n_dev // 2))
    assert np.array_equal(g1.plan.cells, g2.plan.cells)
    assert np.array_equal(g1.plan.owner, g2.plan.owner)


# ---------------------------------------------------------------------
# async (writer-thread) two-phase saves: background.freeze_grid_mp
# ---------------------------------------------------------------------

def test_async_mp_save_is_bitwise_and_snapshot_consistent(tmp_path):
    """The mp save run from freeze_grid_mp snapshots on AsyncSaver
    writer threads produces the byte-identical file of the synchronous
    two-pass save — even when the LIVE grid is mutated between the
    freeze and the write (the snapshot pulled every local shard to
    host at freeze time)."""
    from dccrg_tpu import background

    fn_sync = tmp_path / "sync.dc"
    _two_pass_save(_value_grid(), fn_sync, header=b"HDR!")

    g = _value_grid()
    fn = tmp_path / "async.dc"
    frozen = {}
    half = g.n_dev // 2
    for rank in (0, 1):  # collective discipline: EVERY rank freezes
        _fake_split(g, range(half) if rank == 0 else range(half, g.n_dev),
                    rank=rank)
        g._ckpt_writes_meta = rank == 0
        g._ckpt_commits = rank == 1
        frozen[rank] = background.freeze_grid_mp(g)
    _unfake(g)
    # live mutation AFTER the freeze: must not reach the files
    g.set("v", g.plan.cells, np.full(len(g.plan.cells), -9.0, np.float32))

    saver = background.AsyncSaver()
    for rank in (0, 1):  # faked split: barriers no-op, passes sequence
        fr = frozen[rank]
        saver.submit(lambda fr=fr: fr.save_grid_data(str(fn),
                                                     header=b"HDR!"))
        saver.drain()
    assert fn.read_bytes() == fn_sync.read_bytes()
    # and the attempt epoch advanced on the SOURCE grid (_mp_epoch_src),
    # so the NEXT save never reuses a barrier tag
    assert getattr(g, "_mp_save_epoch", 0) >= 2


@pytest.mark.faultinject
def test_async_mp_save_rank_death_aborts_cleanly(tmp_path):
    """A rank death inside an async writer thread surfaces typed at
    drain() (the async analogue of the synchronous save raising in
    place); nothing is published and a fresh save retries clean."""
    from dccrg_tpu import background

    fn = tmp_path / "ad.dc"
    _two_pass_save(_value_grid(), fn, sidecar=True)
    good = fn.read_bytes()

    g = _value_grid(7.0)
    half = g.n_dev // 2
    frozen = {}
    for rank in (0, 1):
        _fake_split(g, range(half) if rank == 0 else range(half, g.n_dev),
                    rank=rank)
        g._ckpt_writes_meta = rank == 0
        g._ckpt_commits = rank == 1
        frozen[rank] = background.freeze_grid_mp(g)
    _unfake(g)

    saver = background.AsyncSaver()
    failures = []
    plan = faults.FaultPlan()
    plan.rank_death(phase="slice", rank=1)
    with plan:
        for rank in (0, 1):
            fr = frozen[rank]
            saver.submit(lambda fr=fr: fr.save_grid_data(str(fn),
                                                         sidecar=True),
                         on_fail=lambda e: failures.append(e))
            if rank == 0:
                saver.drain()
            else:
                with pytest.raises(faults.InjectedRankDeath):
                    saver.drain()
    assert len(failures) == 1
    assert fn.read_bytes() == good  # old checkpoint bitwise intact
    assert resilience.verify_checkpoint(str(fn)) == []

    # the epoch is retryable: a fresh synchronous save publishes
    _two_pass_save(_value_grid(7.0), fn, sidecar=True)
    single = tmp_path / "s.dc"
    _value_grid(7.0).save_grid_data(str(single), sidecar=True)
    assert fn.read_bytes() == single.read_bytes()


def test_supervise_store_routes_multiproc_async_through_freeze_mp(
        tmp_path, monkeypatch):
    """With DCCRG_ASYNC_SAVE=1 a multi-process CheckpointStore.save
    freezes through freeze_grid_mp (not the single-controller
    freeze_grid) and the published bytes equal the synchronous save's
    — the PR-13 follow-up: mp saves no longer block dispatch."""
    from dccrg_tpu import background, supervise

    monkeypatch.setenv("DCCRG_ASYNC_SAVE", "1")
    frozen_kinds = []
    real_freeze_mp = background.freeze_grid_mp

    def spy(grid, fields=None, variable=None):
        frozen_kinds.append("mp")
        return real_freeze_mp(grid, fields=fields, variable=variable)

    monkeypatch.setattr(background, "freeze_grid_mp", spy)
    store_dir = tmp_path / "store"
    store = supervise.CheckpointStore(str(store_dir), stem="as")
    g = _value_grid()
    half = g.n_dev // 2
    for rank in (0, 1):
        _fake_split(g, range(half) if rank == 0 else range(half, g.n_dev),
                    rank=rank)
        g._ckpt_writes_meta = rank == 0
        g._ckpt_commits = rank == 1
        store.save(g, step=4)
        store.drain()
    _unfake(g)
    assert frozen_kinds == ["mp", "mp"]
    entries = supervise.list_checkpoints(str(store_dir), stem="as")
    assert entries, "async mp store save never published"

    sync_dir = tmp_path / "sync_store"
    monkeypatch.setenv("DCCRG_ASYNC_SAVE", "0")
    store2 = supervise.CheckpointStore(str(sync_dir), stem="as")
    g2 = _value_grid()
    for rank in (0, 1):
        _fake_split(g2, range(half) if rank == 0 else range(half, g2.n_dev),
                    rank=rank)
        g2._ckpt_writes_meta = rank == 0
        g2._ckpt_commits = rank == 1
        store2.save(g2, step=4)
    _unfake(g2)
    a = entries[0][1]
    b = supervise.list_checkpoints(str(sync_dir), stem="as")[0][1]
    assert open(a, "rb").read() == open(b, "rb").read()
