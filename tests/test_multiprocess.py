"""Multi-process (jax.distributed) lifting, validated by FAKING the
process split on the single-controller test mesh.

A multi-process mesh differs from a single-controller one only in
which shards the host may touch: uploads go through put_sharded (each
process serves its addressable shards), get/set become rank-local
(the reference's operator[] semantics, dccrg.hpp:7738-7803), and
checkpoint I/O writes per-process slices (the reference's collective
MPI-IO with per-rank file views, dccrg.hpp:1594-1659). Faking
``grid._proc_local_dev`` exercises exactly those code paths; the
shards stay addressable underneath, so the restriction logic and the
slice-merging can be verified byte-for-byte against the
single-controller result — two faked processes writing one file must
reproduce the single-save file exactly.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dccrg_tpu.grid import Grid


def _mk(fields=None, n=(8, 8, 8)):
    g = (
        Grid(cell_data=fields or {"v": jnp.float32})
        .set_initial_length(n)
        .set_periodic(True, True, False)
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .initialize(partition="block")
    )
    return g


def _fake_split(g, local_devs):
    g._proc_local_dev = np.array(
        [d in set(local_devs) for d in range(g.n_dev)], dtype=bool)


def _unfake(g):
    g._proc_local_dev = np.ones(g.n_dev, dtype=bool)


def test_get_set_are_rank_local():
    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(11)).astype(np.float32))
    half = list(range(g.n_dev // 2))
    _fake_split(g, half)
    assert g._multiproc
    local = np.isin(g.plan.owner, half)
    my, foreign = cells[local], cells[~local]

    # local reads work and match the single-controller values
    got = g.get("v", my[:100])
    np.testing.assert_array_equal(
        got, (my[:100] % np.uint64(11)).astype(np.float32))

    # foreign access fails loudly (reference: operator[] is rank-local)
    with pytest.raises(KeyError, match="process-local"):
        g.get("v", foreign[:3])
    with pytest.raises(KeyError, match="process-local"):
        g.set("v", foreign[:3], np.zeros(3, np.float32))

    # local writes land (verified through the unfaked full view)
    g.set("v", my[:5], np.full(5, 99.0, np.float32))
    _unfake(g)
    np.testing.assert_array_equal(g.get("v", my[:5]),
                                  np.full(5, 99.0, np.float32))


def test_collective_paths_unchanged_under_split():
    """Halo exchange + fused steps use replicated tables and
    collectives only — a faked process split must not change them."""
    def kern(cell, nbr, offs, mask):
        return {"v": 0.5 * cell["v"] + 0.125 * jnp.sum(
            jnp.where(mask, nbr["v"], 0.0), axis=1)}

    res = []
    for split in (False, True):
        g = _mk()
        cells = g.plan.cells
        g.set("v", cells, (cells % np.uint64(7)).astype(np.float32))
        g.update_copies_of_remote_neighbors()
        if split:
            _fake_split(g, range(g.n_dev // 2))
        g.run_steps(kern, ["v"], ["v"], 3)
        _unfake(g)
        res.append(g.get("v", cells))
    np.testing.assert_array_equal(res[0], res[1])


def _single_vs_split_save(make_grid, tmp_path, **save_kwargs):
    """Save an identically-built grid once single-controller and once
    as two faked processes filling one file; return both byte strings.
    The protocol under test: proc 0 writes meta + its slice, proc 1
    (_ckpt_writes_meta=False) fills its own payload runs."""
    files = {}
    for mode in ("single", "split"):
        g = make_grid()
        fn = tmp_path / f"{mode}.dc"
        if mode == "single":
            g.save_grid_data(str(fn), **save_kwargs)
        else:
            half = g.n_dev // 2
            _fake_split(g, range(half))
            g.save_grid_data(str(fn), **save_kwargs)
            _fake_split(g, range(half, g.n_dev))
            g._ckpt_writes_meta = False
            g.save_grid_data(str(fn), **save_kwargs)
        files[mode] = fn.read_bytes()
    return files["single"], files["split"]


def test_two_process_checkpoint_slices_merge_exactly(tmp_path):
    """Two faked processes filling one file == the single-save file."""
    def make():
        g = _mk({"v": jnp.float32, "w": jnp.int32})
        cells = g.plan.cells
        rng = np.random.default_rng(3)
        g.set("v", cells, rng.random(len(cells)).astype(np.float32))
        g.set("w", cells, (cells % np.uint64(5)).astype(np.int32))
        return g

    single, split = _single_vs_split_save(make, tmp_path, header=b"HDR!")
    assert single == split


def test_two_process_ragged_checkpoint(tmp_path):
    """Variable-size payloads: counts ride the replicated device
    gather, ragged rows ride per-process shard reads."""
    cap = 4

    def make():
        g = _mk({"n": jnp.int32, "p": ((cap, 2), jnp.float32)})
        cells = g.plan.cells
        rng = np.random.default_rng(5)
        g.set("n", cells,
              rng.integers(0, cap + 1, len(cells)).astype(np.int32))
        g.set("p", cells, rng.random((len(cells), cap, 2)).astype(np.float32))
        return g

    single, split = _single_vs_split_save(make, tmp_path,
                                          variable={"p": "n"})
    assert single == split


def test_two_process_slices_on_refined_morton_grid(tmp_path):
    """Fragmented ownership (morton partition + refinement): the
    per-process payload runs are many and interleaved; the merged file
    must still be byte-identical to the single-controller save."""
    def make():
        g = (
            Grid(cell_data={"v": jnp.float32})
            .set_initial_length((6, 6, 4))
            .set_maximum_refinement_level(1)
            .set_neighborhood_length(1)
            .initialize(partition="morton")
        )
        for cid in g.local_cells().ids[::17]:
            g.refine_completely(int(cid))
        g.stop_refining()
        cells = g.plan.cells
        g.set("v", cells, (cells % np.uint64(19)).astype(np.float32))
        return g

    single, split = _single_vs_split_save(make, tmp_path)
    assert single == split


def test_process_local_load(tmp_path):
    """Each process scatters only its cells; foreign rows stay zero
    (their real shards are served by the owning process)."""
    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(13)).astype(np.float32))
    fn = str(tmp_path / "a.dc")
    g.save_grid_data(fn)

    g2 = _mk()
    half = list(range(g2.n_dev // 2))
    _fake_split(g2, half)
    g2.load_grid_data(fn)
    _unfake(g2)
    local = np.isin(g2.plan.owner, half)
    np.testing.assert_array_equal(
        g2.get("v", cells[local]),
        (cells[local] % np.uint64(13)).astype(np.float32))
    assert not np.any(g2.get("v", cells[~local]))


def test_full_cover_set_preserving_ghosts_under_split():
    """A replicated full-cover set() with preserve_ghosts=True (the
    standard init idiom) must work on a multi-process mesh: new values
    ride put_sharded, ghost rows keep their old values via an
    on-device merge."""
    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, np.ones(len(cells), np.float32))
    g.update_copies_of_remote_neighbors()  # ghosts now 1.0
    _fake_split(g, range(g.n_dev // 2))
    g.set("v", cells, np.full(len(cells), 2.0, np.float32))  # full cover
    _unfake(g)
    np.testing.assert_array_equal(
        g.get("v", cells), np.full(len(cells), 2.0, np.float32))
    # ghost rows were preserved (still 1.0, not zeroed): check one
    # device's ghost block directly
    host = np.asarray(g.data["v"])
    L = g.plan.L
    for d in range(g.n_dev):
        ng = len(g.plan.ghost_ids[d])
        if ng:
            np.testing.assert_array_equal(host[d, L:L + ng],
                                          np.ones(ng, np.float32))


def test_amr_commit_and_balance_under_split():
    """The whole AMR pipeline — refine, commit, projection, balance —
    must produce the same structure and data under a faked process
    split as single-controller (the structure decisions are replicated;
    data movement is device-side)."""
    results = {}
    for split in (False, True):
        g = (
            Grid(cell_data={"v": jnp.float32})
            .set_initial_length((8, 8, 4))
            .set_periodic(True, True, False)
            .set_maximum_refinement_level(1)
            .set_neighborhood_length(1)
            .initialize(partition="block")
        )
        cells = g.plan.cells
        g.set("v", cells, (cells % np.uint64(23)).astype(np.float32))
        if split:
            _fake_split(g, range(g.n_dev // 2))
        for cid in g.plan.cells[:12:3]:
            g.refine_completely(int(cid))
        g.stop_refining()
        g.assign_children_from_parents(fields=["v"])
        g.clear_refined_unrefined_data()
        g.set_partitioning_option("method", "morton")
        g.balance_load()
        g.update_copies_of_remote_neighbors()
        _unfake(g)
        results[split] = (g.plan.cells.copy(), g.plan.owner.copy(),
                          g.get("v", g.plan.cells))
    np.testing.assert_array_equal(results[False][0], results[True][0])
    np.testing.assert_array_equal(results[False][1], results[True][1])
    np.testing.assert_array_equal(results[False][2], results[True][2])


def test_staged_balance_peek_is_rank_local():
    """staged_balance_data under a process split returns only this
    process's moving cells, read from addressable shards."""
    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(9)).astype(np.float32))
    for c in cells[:6]:
        g.pin(int(c), (g.get_process(int(c)) + 1) % g.n_dev)
    g.initialize_balance_load(use_zoltan=False)
    g.continue_balance_load()
    all_ids, all_vals = g.staged_balance_data("v")
    half = list(range(g.n_dev // 2))
    _fake_split(g, half)
    ids, vals = g.staged_balance_data("v")
    _unfake(g)
    dev, _ = g._host_rows(ids)
    assert np.isin(dev, half).all()
    sel = np.isin(all_ids, ids)
    np.testing.assert_array_equal(all_vals[sel], vals)
    g.finish_balance_load()  # leave the grid consistent


def test_ppermute_exchange_never_materializes_dense_pair_tables():
    """Pod-scale memory: the per-delta ppermute exchange works from
    the compact O(ghosts) pair record; the dense [n_dev, n_dev, M]
    arrays must stay unmaterialized unless the all_to_all fallback or
    a host introspection API asks for them."""
    from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID

    if os.environ.get("DCCRG_DEBUG") == "1":
        pytest.skip("DEBUG verifiers materialize the dense pair tables "
                    "by design (verify_remote_neighbor_info reads "
                    "send_rows/recv_rows)")

    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(7)).astype(np.float32))
    g.update_copies_of_remote_neighbors()
    g.run_steps(lambda c, n, o, m: {"v": c["v"]}, ["v"], ["v"], 2)
    hood = g.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    assert hood._send_rows is None and hood._recv_rows is None
    # introspection still works, via lazy materialization
    sends = g.get_cells_to_send()
    assert sends and hood._send_rows is None  # compact-backed
    _ = hood.send_rows
    assert hood._send_rows is not None


def test_initialize_accepts_foreign_process_mesh_structurally():
    """initialize() no longer refuses multi-process meshes; the plan it
    builds is pure replicated host structure, identical to the
    single-controller one (every process computes the same plan)."""
    g1 = _mk()
    g2 = _mk()
    _fake_split(g2, range(g2.n_dev // 2))
    assert np.array_equal(g1.plan.cells, g2.plan.cells)
    assert np.array_equal(g1.plan.owner, g2.plan.owner)
