"""Scalability harness tests (reference tests/scalability)."""

import numpy as np

import jax
from jax.sharding import Mesh

from dccrg_tpu.models.scalability import ScalabilityModel, run_sweep


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def test_model_runs_and_reports():
    model = ScalabilityModel((8, 8, 8), floats_per_cell=4, work_iters=8,
                             mesh=mesh_of(4))
    rep = model.run(steps=3, warmup=1)
    assert rep["n_devices"] == 4
    assert rep["n_cells"] == 512
    assert rep["solve_s_per_step"] > 0
    assert rep["halo_s_per_step"] > 0
    assert rep["cell_updates_per_sec"] > 0
    # 4 f32 lanes per ghost cell
    assert rep["halo_bytes_per_step"] == 16 * model.grid.get_number_of_update_receive_cells()


def test_solve_preserves_determinism():
    """Same step on 1 vs 8 devices gives identical payloads (the
    reference requires any-process-count equivalence, tests/README:5-6)."""
    out = []
    for n in (1, 8):
        m = ScalabilityModel((4, 4, 4), floats_per_cell=2, work_iters=4,
                             mesh=mesh_of(n))
        m.step()
        out.append(np.asarray(m.grid.get("payload", m.grid.get_cells())))
    # summation order over gathered neighbors differs with the mesh
    # size; tolerance covers f32 reassociation noise only
    np.testing.assert_allclose(out[0], out[1], rtol=1e-5, atol=1e-6)


def test_sweep_driver():
    rows = run_sweep(device_counts=[1, 2], length=(4, 4, 4),
                     floats_per_cell=2, work_iters=2, steps=2)
    assert [r["n_devices"] for r in rows] == [1, 2]
    rows_weak = run_sweep(device_counts=[1, 2], length=(4, 4, 4),
                          floats_per_cell=2, work_iters=2, steps=2, weak=True)
    assert rows_weak[1]["n_cells"] == 2 * rows_weak[0]["n_cells"]
