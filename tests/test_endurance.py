"""End-to-end endurance scenario: the reference's production pattern —
long advection run with periodic adaptation, load balancing, and a
mid-run checkpoint/restart — all through the public API, with physics
invariants checked throughout (tests/advection + tests/restart
combined)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dccrg_tpu.grid import Grid
from dccrg_tpu.models.advection_amr import AmrAdvection


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def test_long_run_with_adapt_balance_restart(tmp_path):
    app = AmrAdvection(length=(24, 24, 1), max_refinement_level=2,
                       mesh=mesh_of(8))
    m0 = app.total_mass()

    # phase 1: 12 steps with adaptation every 3, balancing every 6
    app.run(12, adapt_n=3, balance_n=6)
    assert app.total_mass() == pytest.approx(m0, rel=1e-4)
    lvl = app.grid.mapping.get_refinement_level(app.grid.get_cells())
    assert lvl.max() >= 1  # the hump edge refined

    # phase 2: checkpoint, keep running the original
    fn = str(tmp_path / "mid.dc")
    app.grid.save_grid_data(fn)
    t_mid = app.time
    app.run(9, adapt_n=3)
    want = app.grid.get("density", app.grid.get_cells())
    want_cells = app.grid.get_cells()

    # phase 3: restart from nothing but the file; same trajectory
    grid2, _ = Grid.from_file(fn, dict(app.grid.fields), mesh=mesh_of(8))
    app2 = AmrAdvection.from_grid(grid2, time=t_mid)
    app2.run(9, adapt_n=3)
    np.testing.assert_array_equal(app2.grid.get_cells(), want_cells)
    np.testing.assert_allclose(
        app2.grid.get("density", want_cells), want, rtol=1e-5, atol=1e-6,
    )
    assert app2.total_mass() == pytest.approx(m0, rel=1e-4)

    # phase 4: density stays physical through it all
    rho = app.grid.get("density", app.grid.get_cells())
    assert rho.min() >= -1e-5 and rho.max() <= 0.55
