"""Transactional mutation atomicity (txn.py).

A FaultPlan fault injected at EVERY fault point inside the adapt
commit and balance_load must leave the grid bitwise identical to its
pre-mutation snapshot (checkpoint-bytes comparison), pass verify_all,
and allow the same mutation to be retried successfully — the
reference's all-or-nothing structure-rebuild discipline.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu import (FaultPlan, Grid, GridInvariantError,
                       MutationAbortedError, VerificationError, verify_all)
from dccrg_tpu import verify as V
from dccrg_tpu.faults import InjectedMutationError
from dccrg_tpu.txn import grid_state_bytes, grid_transaction

pytestmark = pytest.mark.faultinject


def make_grid(n_dev=4, length=(4, 4, 2), max_lvl=2, refined=True):
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("dev",))
    g = (
        Grid(cell_data={"rho": jnp.float32, "mom": ((3,), jnp.float32)})
        .set_initial_length(length)
        .set_maximum_refinement_level(max_lvl)
        .set_periodic(True, True, True)
        .set_neighborhood_length(1)
        .initialize(mesh)
    )
    if refined:
        g.refine_completely(int(g.get_cells()[0]))
        g.stop_refining()
    rng = np.random.default_rng(7)
    cells = g.get_cells()
    g.set("rho", cells, rng.random(len(cells)).astype(np.float32))
    g.set("mom", cells, rng.random((len(cells), 3)).astype(np.float32))
    g.pin(int(cells[-1]), 1)
    g.set_cell_weight(int(cells[0]), 2.0)
    g.balance_load()  # apply the pin so full verify_all holds
    return g


# every fault point on each mutation path, from the canonical table
# next to the fire() sites — a newly instrumented fault point only
# needs registering there to gain a parametrized atomicity test here
from dccrg_tpu.faults import MUTATION_FAULT_SITES

ADAPT_SITES = MUTATION_FAULT_SITES["adapt"]
BALANCE_SITES = MUTATION_FAULT_SITES["balance"]


@pytest.mark.parametrize("site,phase", ADAPT_SITES)
def test_adapt_fault_rolls_back_bitwise(site, phase):
    g = make_grid()
    pins_before = g.get_pin_requests()
    weights_before = dict(g._weights)
    target = int(g.get_cells()[3])
    assert g.refine_completely(target)
    before = grid_state_bytes(g)

    plan = FaultPlan(seed=1)
    plan.mutation_error(site=site, times=1, phase=phase)
    with plan:
        with pytest.raises(MutationAbortedError) as ei:
            g.stop_refining()
    assert plan.fired(site) == 1
    assert isinstance(ei.value.__cause__, InjectedMutationError)

    # bitwise rollback: structure AND every field payload
    assert grid_state_bytes(g) == before
    assert g.get_pin_requests() == pins_before
    assert g._weights == weights_before
    verify_all(g)

    # the refine request survived the rollback: the retry commits
    new = g.stop_refining()
    assert len(new) >= 8
    assert np.isin(
        g.mapping.get_all_children(np.uint64(target)), g.get_cells()
    ).all()
    verify_all(g)


@pytest.mark.parametrize("site,phase", BALANCE_SITES)
def test_balance_fault_rolls_back_bitwise(site, phase):
    g = make_grid()
    before = grid_state_bytes(g)
    owner_before = g.plan.owner.copy()

    plan = FaultPlan(seed=2)
    plan.mutation_error(site=site, times=1, phase=phase)
    with plan:
        with pytest.raises(MutationAbortedError):
            g.balance_load()
    assert plan.fired(site) == 1

    assert grid_state_bytes(g) == before
    assert np.array_equal(g.plan.owner, owner_before)
    # the half-applied balance left NO pending stage behind
    assert getattr(g, "_pending_owner", None) is None
    assert g._staged_balance == {}
    verify_all(g)

    g.balance_load()  # retry succeeds
    verify_all(g)


def test_staged_finish_fault_preserves_staging():
    """The staged multi-phase API: a fault inside finish_balance_load
    rolls back to the post-continue state (staging intact), so finish
    alone can be retried."""
    g = make_grid()
    g.initialize_balance_load()
    g.continue_balance_load()
    pending = g._pending_owner.copy()

    plan = FaultPlan(seed=3)
    plan.mutation_error(site="balance.commit", times=1, phase="land")
    with plan:
        with pytest.raises(MutationAbortedError):
            g.finish_balance_load()

    # staging survived the rollback
    assert np.array_equal(g._pending_owner, pending)
    assert set(g._staged_balance) == set(g.fields)
    g.finish_balance_load()
    verify_all(g)


def test_unrefine_fault_rolls_back(tmp_path):
    g = make_grid()
    lvl1 = [int(c) for c in g.get_cells()
            if g.mapping.get_refinement_level(np.uint64(c)) == 1]
    assert g.unrefine_completely(lvl1[0])
    before = grid_state_bytes(g)

    plan = FaultPlan(seed=4)
    plan.mutation_error(site="adapt.commit", times=1, phase="preserved")
    with plan:
        with pytest.raises(MutationAbortedError):
            g.stop_refining()
    assert grid_state_bytes(g) == before
    g.stop_refining()
    verify_all(g)


def test_post_commit_validation_rolls_back(monkeypatch):
    """GridInvariantError: a broken invariant detected by the
    post-commit verify_all rolls the commit back and names the cells."""
    g = make_grid()
    g._debug = True  # transactional post-commit validation on
    before = grid_state_bytes(g)

    def planted(grid):
        raise VerificationError("planted invariant break", cells=(17, 23))

    # patch a checker only the transaction-level verify_all runs (the
    # per-rebuild DEBUG hooks inside _finish_plan run the others)
    monkeypatch.setattr(V, "verify_partition_coverage", planted)
    assert g.refine_completely(int(g.get_cells()[2]))
    with pytest.raises(GridInvariantError) as ei:
        g.stop_refining()
    assert ei.value.cells == (17, 23)
    assert "17" in str(ei.value)

    monkeypatch.undo()
    assert grid_state_bytes(g) == before
    verify_all(g)
    # rolled back including the request sets: retry succeeds
    assert len(g.stop_refining()) >= 8


def test_post_commit_validator_crash_rolls_back(monkeypatch):
    """A verifier CRASHING (raw exception, not VerificationError) on
    the committed state is the same verdict with less detail — the
    commit must still roll back under the typed error."""
    g = make_grid()
    g._debug = True
    before = grid_state_bytes(g)

    def crashing(grid):
        raise ValueError("verifier blew up on malformed state")

    monkeypatch.setattr(V, "verify_partition_coverage", crashing)
    assert g.refine_completely(int(g.get_cells()[2]))
    with pytest.raises(GridInvariantError) as ei:
        g.stop_refining()
    assert isinstance(ei.value.__cause__, ValueError)

    monkeypatch.undo()
    assert grid_state_bytes(g) == before
    verify_all(g)


def test_nested_transaction_joins_outer():
    """A transaction opened inside another must not commit or roll
    back on its own — rollback belongs to the outermost."""
    g = make_grid(refined=False)
    before = grid_state_bytes(g)
    with pytest.raises(MutationAbortedError):
        with grid_transaction(g, op="outer"):
            with grid_transaction(g, op="inner"):
                g.balance_load()  # joins too (depth 3)
            raise RuntimeError("outer failure after inner success")
    assert grid_state_bytes(g) == before
    assert g._txn_depth == 0
    verify_all(g)


def test_transaction_errors_are_typed():
    g = make_grid(refined=False)
    with pytest.raises(RuntimeError):  # the hierarchy stays a RuntimeError
        with grid_transaction(g, op="noop"):
            raise ValueError("boom")
    try:
        with grid_transaction(g, op="noop"):
            raise ValueError("boom")
    except MutationAbortedError as e:
        assert e.op == "noop"
        assert isinstance(e.__cause__, ValueError)
    else:  # pragma: no cover
        pytest.fail("MutationAbortedError not raised")


def test_fault_exhausted_plan_does_not_fire():
    """A rule with times=1 must not abort the retry."""
    g = make_grid(refined=False)
    plan = FaultPlan(seed=5)
    plan.mutation_error(site="balance.commit", times=1, phase="finish")
    with plan:
        with pytest.raises(MutationAbortedError):
            g.balance_load()
        g.balance_load()  # same plan still active: rule is exhausted
    assert plan.fired("balance.commit") == 1
    verify_all(g)
