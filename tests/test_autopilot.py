"""Production autopilot: telemetry-driven self-tuning with an
explainable decision journal.

The pins: the controller is deterministic under a fake clock and pure
rules (a journal replay re-derives every action from the recorded
inputs alone); knob convergence under injected OOM/trip/shed/suspect
histories; the hard-bound property (no decision ever leaves its
envelope); the off-by-default negative pin (with ``DCCRG_AUTOPILOT``
unset the scheduler constructs no controller and fleet results,
cadences and knobs are untouched); and the controller-input metrics
(save/rollback/audit cost histograms, per-lane suspect gauges) that
are useful observability even with the autopilot off."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dccrg_tpu import autopilot as ap_mod
from dccrg_tpu import telemetry
from dccrg_tpu.autopilot import (RULES, Autopilot, explain_decision,
                                 key_id, read_journal, replay)
from dccrg_tpu.faults import FaultPlan
from dccrg_tpu.fleet import FleetJob, GridBatch, run_solo
from dccrg_tpu.scheduler import FleetScheduler, SLOPolicy

pytestmark = pytest.mark.autopilot


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Autopilot off in the env, a fresh registry, and both again on
    the way out (the registry is process-global by design)."""
    for var in ("DCCRG_AUTOPILOT", "DCCRG_DECISION_FILE",
                "DCCRG_STATUS_FILE"):
        monkeypatch.delenv(var, raising=False)
    telemetry.configure(trace=False)
    telemetry.clear_trace()
    telemetry.registry().reset()
    yield
    telemetry.configure(trace=False)
    telemetry.clear_trace()
    telemetry.registry().reset()


def _jobs(count=4, steps=16, slo_ms=None, **kw):
    return [FleetJob(f"a{i:02d}", length=(8, 8, 8), n_steps=steps,
                     seed=i, params=(0.03,), checkpoint_every=4,
                     slo_ms=slo_ms, **kw)
            for i in range(count)]


def _solo(jobs):
    return {j.name: run_solo(FleetJob(
        j.name, length=(8, 8, 8), n_steps=j.n_steps, seed=j.seed,
        params=j.params)) for j in jobs}


def _sched(tmp_path, jobs, ap=None, quantum=4, **kw):
    pol = SLOPolicy(quantum=quantum, clock=lambda: 0.0)
    return FleetScheduler(str(tmp_path / "work"), jobs,
                          quantum=quantum, slo_policy=pol,
                          autopilot=ap, **kw), pol


# -- the rules: pure, deterministic, JSON-faithful --------------------

def test_rules_pure_and_json_faithful():
    """Every rule derives the same action from the same inputs, and
    from the inputs after a JSON round-trip — the property replay
    rests on (journaled inputs ARE json)."""
    cases = {
        "quantum.shorten": (8, {"slo_slack_min_s": -1.0,
                                "trip_rate": 0.0, "lo": 1, "hi": 64,
                                "streak": 1, "patience": 1}),
        "quantum.lengthen": (8, {"slo_slack_min_s": None,
                                 "quantum_latency_s": 0.001,
                                 "trip_rate": 0.0, "lo": 1, "hi": 64,
                                 "streak": 9, "patience": 4}),
        "checkpoint.retune": (32, {"save_cost_s": 0.05,
                                   "step_seconds": 0.01,
                                   "trip_rate": 0.125,
                                   "lo": 1, "hi": 256}),
        "audit.tighten": (8, {"new_suspects": 1, "hi": 16}),
        "audit.relax": (2, {"clean_streak": 9, "relax_after": 8,
                            "baseline": 8, "hi": 16}),
        "capacity.learn": (None, {"observed_capacity": 4}),
        "capacity.seed": (16, {"learned_capacity": 4, "lo": 1}),
        "capacity.probe": (4, {"clean_run": True,
                               "default_capacity": 16}),
        "quantum.learn": (None, {"final_quantum": 2,
                                 "configured": 8}),
        "quantum.warm_start": (8, {"learned_quantum": 2, "lo": 1,
                                   "hi": 64, "configured": 8}),
        "shed.cooldown": (4, {"new_sheds": 1, "lo": 1, "hi": 64,
                              "baseline": 4, "relax_after": 8,
                              "clean_streak": 0}),
        "retry.budget": (3, {"repeat_trips": 2, "recovered": 0,
                             "lo": 1, "hi": 8}),
        "fleet.reclaim": (0, {"n": 2, "jobs": ["a", "b"],
                              "dead_rank": 1, "lease_s": 8.0}),
        "intake.backpressure": (0, {"ratio": 1.5,
                                    "arrival_per_s": 3.0,
                                    "drain_per_s": 2.0,
                                    "queue_age_s": 0.5, "backlog": 6,
                                    "hi": 1.2, "lo": 0.9,
                                    "age_bound_s": 30.0}),
        "intake.shed": (0, {"n": 2, "tenant": "default",
                            "names": ["a", "b"], "backlog": 8,
                            "drain_per_s": 1.0, "age_bound_s": 4.0}),
        "intake.quarantine": (0, {"name": "j1", "tenant": "default",
                                  "attempts": 4,
                                  "error_type":
                                      "IntakeRetryExhausted"}),
        "warmstart.cache": (0, {"decision": "warm",
                                "key": "ab12cd34ef56",
                                "seconds": 0.004}),
        "warmstart.gc": (0, {"n": 2, "pruned": ["a.rec", "b.rec"],
                             "bytes_before": 4096,
                             "bytes_after": 1024}),
    }
    assert set(cases) == set(RULES)
    for rule, (before, inp) in cases.items():
        first = RULES[rule](before, inp)
        assert first is not None, rule  # the case is a firing one
        assert RULES[rule](before, inp) == first, rule
        rt = json.loads(json.dumps(inp))
        assert RULES[rule](before, rt) == first, rule


def test_checkpoint_retune_is_youngs_optimum():
    """sqrt(2 * (save_cost/step_time) / trip_rate): higher trip rate
    -> shorter cadence, a trip-free history -> the upper bound."""
    inp = {"save_cost_s": 0.05, "step_seconds": 0.01, "lo": 1,
           "hi": 256}
    calm = RULES["checkpoint.retune"](32, dict(inp, trip_rate=0.0))
    warm = RULES["checkpoint.retune"](32, dict(inp, trip_rate=0.02))
    hot = RULES["checkpoint.retune"](32, dict(inp, trip_rate=0.5))
    assert calm == 256  # no trips: saves cost, trips don't
    assert warm == round((2 * 5 / 0.02) ** 0.5)  # = 22
    assert hot < warm < calm
    # the deadband suppresses churn: a value within 25% stands
    assert RULES["checkpoint.retune"](21, dict(inp, trip_rate=0.02)) \
        is None


def test_hard_bounds_property():
    """Fuzzed inputs (extreme rates, negative slacks, huge costs):
    every rule either declines or lands inside the recorded
    envelope. The knobs can NEVER leave their bounds."""
    rng = np.random.default_rng(7)
    maybe = lambda v: None if rng.random() < 0.2 else v  # noqa: E731
    for _ in range(400):
        lo, hi = 1, int(rng.integers(2, 512))
        inp = {
            "lo": lo, "hi": hi,
            "slo_slack_min_s": maybe(float(rng.normal(0, 50))),
            "quantum_latency_s": maybe(float(abs(rng.normal(0, 10)))),
            "trip_rate": float(abs(rng.normal(0, 1))),
            "save_cost_s": maybe(float(abs(rng.normal(0, 10)))),
            "step_seconds": maybe(float(abs(rng.normal(0, 1)))),
            "new_suspects": int(rng.integers(-1, 5)),
            "clean_streak": int(rng.integers(0, 20)),
            "relax_after": 8, "baseline": int(rng.integers(0, 17)),
            "warm_start": 8, "streak": int(rng.integers(0, 10)),
            "patience": int(rng.integers(1, 5)),
            "trip_warm": 0.02, "trip_cool": 0.005,
            "slack_factor": 8.0, "deadband": 0.25,
            "observed_capacity": int(rng.integers(1, 256)),
            "learned_capacity": maybe(int(rng.integers(1, 256))),
            "rollback_s": maybe(float(abs(rng.normal(0, 10)))),
            "learned_quantum": maybe(int(rng.integers(1, 512))),
            "final_quantum": maybe(int(rng.integers(1, 512))),
            "configured": int(rng.integers(1, 512)),
        }
        before = int(rng.integers(lo, hi + 1))
        for rule in ("quantum.shorten", "quantum.lengthen",
                     "checkpoint.retune"):
            got = RULES[rule](before, inp)
            assert got is None or lo <= got <= hi, (rule, inp)
        for rule in ("audit.tighten", "audit.relax"):
            got = RULES[rule](int(rng.integers(0, hi + 1)), inp)
            # audits: {0 = off} ∪ [1, hi]
            assert got is None or got == 0 or 1 <= got <= hi, \
                (rule, inp)
        got = RULES["capacity.seed"](before, inp)
        assert got is None or lo <= got <= before, inp
        got = RULES["capacity.learn"](maybe(before), inp)
        assert got is None or got >= 1
        got = RULES["capacity.probe"](
            before, dict(inp, clean_run=bool(rng.integers(0, 2)),
                         default_capacity=maybe(int(
                             rng.integers(1, 256)))))
        assert got is None or got >= 1
        got = RULES["quantum.warm_start"](before, inp)
        assert got is None or lo <= got <= hi, inp
        got = RULES["quantum.learn"](maybe(before), inp)
        assert got is None or got >= 1
        got = RULES["shed.cooldown"](
            before, dict(inp, new_sheds=int(rng.integers(-1, 3))))
        assert got is None or lo <= got <= hi, inp
        got = RULES["retry.budget"](
            before, dict(inp, repeat_trips=int(rng.integers(0, 6)),
                         recovered=int(rng.integers(0, 6))))
        assert got is None or lo <= got <= hi, inp
        got = RULES["fleet.reclaim"](
            int(rng.integers(0, 100)),
            dict(inp, n=int(rng.integers(-1, 4)), jobs=[], dead_rank=1,
                 lease_s=8.0))
        assert got is None or got >= 0


# -- knob convergence under injected histories ------------------------

def _tick(sched, ap, n=1):
    """Drive n controller ticks without dispatching (the tests inject
    the observations by hand — the fake-clock discipline)."""
    for _ in range(n):
        ap.tick(sched)
        sched.ticks += 1


def test_quantum_shortens_under_slo_violation(tmp_path):
    """Sustained negative SLO slack halves the quantum down to the
    envelope floor — and never through it."""
    jobs = _jobs(2, slo_ms=100.0)
    ap = Autopilot(quantum=16, clock=lambda: 0.0)
    sched, pol = _sched(tmp_path, jobs, ap, quantum=16)
    sched._admit_pending()
    for j in jobs:
        j.slo_t0 = 0.0
    pol.observe(jobs[0].bucket_key(), 10.0)  # blows the 100 ms SLO
    seen = []
    _tick(sched, ap, 8)
    for rec in ap.decisions:
        seen.append((rec["rule"], rec["before"], rec["after"]))
    lo, hi = ap.bounds["quantum"]
    assert sched.quantum == lo == 1
    assert [r for r, _b, _a in seen] == ["quantum.shorten"] * 4
    assert [(b, a) for _r, b, a in seen] == [(16, 8), (8, 4), (4, 2),
                                             (2, 1)]
    # the SLO projections follow the tuned quantum
    assert pol.quantum == 1


def test_quantum_lengthens_with_comfortable_slack(tmp_path):
    """Low measured latency, no violations, cool trip rate: the
    quantum doubles (after the patience streak) up to the envelope
    ceiling — amortizing dispatch — and never through it."""
    jobs = _jobs(2)  # best-effort only: slack is None
    ap = Autopilot(quantum=4, clock=lambda: 0.0, lengthen_patience=3)
    sched, pol = _sched(tmp_path, jobs, ap, quantum=4)
    sched._admit_pending()
    pol.observe(jobs[0].bucket_key(), 1e-4)
    _tick(sched, ap, 2)
    assert sched.quantum == 4  # patience not yet reached
    _tick(sched, ap, 20)
    assert sched.quantum == ap.bounds["quantum"][1] == 32
    rules = {r["rule"] for r in ap.decisions}
    assert rules == {"quantum.lengthen"}


def test_shed_cooldown_follows_shed_churn(tmp_path):
    """The PR-12 carried item, shed half: a fresh SLO shed doubles
    the shed cooldown (damping the shed -> compile -> EWMA-poison
    feedback loop); a sustained shed-free streak halves it back to
    the configured baseline — and never past the envelope."""
    jobs = _jobs(2)
    ap = Autopilot(quantum=4, clock=lambda: 0.0, relax_after=2)
    sched, pol = _sched(tmp_path, jobs, ap, quantum=4)
    sched._admit_pending()
    assert pol.shed_cooldown == 4
    telemetry.inc("dccrg_fleet_slo_sheds_total", job="x")
    _tick(sched, ap)
    assert pol.shed_cooldown == 8
    telemetry.inc("dccrg_fleet_slo_sheds_total", job="y")
    _tick(sched, ap)
    assert pol.shed_cooldown == 16
    _tick(sched, ap, 2)  # shed-free: halve back toward the baseline
    assert pol.shed_cooldown == 8
    _tick(sched, ap, 2)
    assert pol.shed_cooldown == 4
    _tick(sched, ap, 6)
    assert pol.shed_cooldown == 4  # the baseline, never past
    assert {r["rule"] for r in ap.decisions} == {"shed.cooldown"}
    lo, hi = ap.bounds["shed_cooldown"]
    for rec in ap.decisions:
        assert lo <= rec["after"] <= hi


def test_retry_budget_follows_trip_history(tmp_path):
    """The PR-12 carried item, retry half: a job churning retries at
    the SAME step (a deterministic blow-up the rollback cannot
    outrun) gets its budget cut — fail fast — while a job whose trips
    recover earns headroom; both bounded, both event-driven (no move
    without fresh trip history)."""
    jobs = _jobs(2)
    ap = Autopilot(quantum=4, clock=lambda: 0.0)
    sched, _pol = _sched(tmp_path, jobs, ap, quantum=4)
    sched._admit_pending()
    doomed, healthy = jobs
    doomed.trips = [("nan", 5), ("nan", 5), ("nan", 5)]
    doomed.retries = 3  # the scheduler's consecutive same-step streak
    healthy.trips = [("nan", 2)]
    healthy.retries = 0  # progressed past its one trip
    _tick(sched, ap)
    assert doomed.max_retries == 2   # 3 -> 2: fail faster
    assert healthy.max_retries == 4  # 3 -> 4: headroom
    _tick(sched, ap, 4)  # no fresh history: no further moves
    assert doomed.max_retries == 2 and healthy.max_retries == 4
    for _ in range(6):  # churn on: cut to the floor, never through
        doomed.trips.append(("nan", 5))
        doomed.retries += 1
        _tick(sched, ap)
    assert doomed.max_retries == ap.bounds["max_retries"][0] == 1
    assert {r["rule"] for r in ap.decisions} == {"retry.budget"}
    # the journal replays clean (the rules are pure)
    assert replay(list(ap.decisions)) == []


def test_reclaim_records_narrate_and_replay(tmp_path):
    """Elastic-fleet reclaims are decision-journal records: explain
    names the dead rank and the reclaimed jobs from the journal
    alone, and replay re-derives the cumulative count."""
    jf = tmp_path / "rec.jsonl"
    ap = Autopilot(quantum=4, clock=lambda: 0.0,
                   decision_file=str(jf), load_history=False)
    ap.record_reclaim(1, ["jB", "jA"], 8.0)
    ap.record_reclaim(2, ["jC"], 8.0)
    assert ap.reclaims == 3
    recs = read_journal(str(jf))
    assert [r["rule"] for r in recs] == ["fleet.reclaim"] * 2
    assert recs[0]["inputs"]["jobs"] == ["jA", "jB"]
    assert recs[0]["inputs"]["dead_rank"] == 1
    assert (recs[0]["before"], recs[0]["after"]) == (0, 2)
    assert replay(recs) == []
    line = explain_decision(recs[0])
    assert "fleet.reclaim" in line and "dead_rank=1" in line


def test_checkpoint_cadence_follows_trip_history(tmp_path):
    """The acceptance pin: an injected high-trip-rate history
    measurably SHORTENS the checkpoint cadence; a trip-free history
    with the same measured save cost lengthens it to the bound."""
    jobs = _jobs(2, steps=400)
    for j in jobs:
        j.checkpoint_every = 32
    ap = Autopilot(quantum=4, clock=lambda: 0.0, adjust_every=1)
    sched, pol = _sched(tmp_path, jobs, ap, quantum=4)
    sched._admit_pending()
    pol.observe(jobs[0].bucket_key(), 0.04)  # 0.01 s/step
    # replace the admission keyframes' real timings with a fixed
    # injected save-cost history (the test is about the rule)
    telemetry.registry().reset()
    for _ in range(6):
        telemetry.observe("dccrg_ckpt_save_seconds", 0.05,
                          kind="keyframe")
    calm, tripping = jobs
    calm.steps_done = 64
    tripping.steps_done = 64
    tripping.trips = [("nan", i) for i in range(8)]  # rate 0.125
    _tick(sched, ap)
    assert calm.checkpoint_every == 256  # trip-free: the bound
    assert tripping.checkpoint_every < 32  # high trips: shortened
    # Young: sqrt(2 * (0.05/0.01) / 0.125) = sqrt(80) ~ 9
    assert tripping.checkpoint_every == 9
    knobs = {r["knob"] for r in ap.decisions}
    assert f"checkpoint_every[{calm.name}]" in knobs
    assert f"checkpoint_every[{tripping.name}]" in knobs


def test_audit_cadence_warm_then_clean(tmp_path):
    """Suspect verdicts tighten the audit cadence (halving); a clean
    streak relaxes it back to the configured baseline — and not
    past it."""
    jobs = _jobs(2)
    ap = Autopilot(quantum=4, audit_every=8, clock=lambda: 0.0,
                   relax_after=2)
    sched, _pol = _sched(tmp_path, jobs, ap, quantum=4, audit_every=8)
    sched._admit_pending()
    sched.suspects[0] = 1
    _tick(sched, ap)
    assert sched.audit_every == 4
    sched.suspects[0] = 2
    _tick(sched, ap)
    assert sched.audit_every == 2
    # clean from here: relax_after=2 clean ticks per doubling
    _tick(sched, ap, 2)
    assert sched.audit_every == 4
    _tick(sched, ap, 2)
    assert sched.audit_every == 8
    _tick(sched, ap, 6)
    assert sched.audit_every == 8  # the baseline, never past


def test_audit_cadence_switches_on_from_zero_baseline(tmp_path):
    """A baseline of 0 (audits off) still warms up under suspects —
    and a long clean streak switches audits back off."""
    jobs = _jobs(2)
    ap = Autopilot(quantum=4, audit_every=0, clock=lambda: 0.0,
                   relax_after=1)
    sched, _pol = _sched(tmp_path, jobs, ap, quantum=4, audit_every=0)
    sched._admit_pending()
    sched.suspects[0] = 1
    _tick(sched, ap)
    assert sched.audit_every == 8  # warm start: audits ON
    _tick(sched, ap)  # clean: 8 -> 16 (the envelope top)
    assert sched.audit_every == 16
    _tick(sched, ap)  # past the top with baseline 0: back OFF
    assert sched.audit_every == 0


def test_capacity_seeded_from_oom_history(tmp_path, monkeypatch):
    """THE acceptance pin: a run whose bucket had to halve to survive
    a real batch OOM journals the surviving capacity; the NEXT run
    (sharing only the journal) seeds its bucket AT that capacity
    instead of rediscovering it by halving — and every digest still
    matches solo."""
    journal = str(tmp_path / "decisions.jsonl")
    jobs = _jobs(8, steps=10)
    solo = _solo(jobs)
    real_step = GridBatch.step

    def step(self, budget):
        if self.capacity > 4:
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: out of memory (injected)")
        return real_step(self, budget)

    monkeypatch.setattr(GridBatch, "step", step)
    ap1 = Autopilot(quantum=4, clock=lambda: 0.0,
                    decision_file=journal)
    sched1, _ = _sched(tmp_path, jobs, ap1, quantum=4)
    report = sched1.run()
    assert all(r["status"] == "done" for r in report.values())
    assert {n: r["digest"] for n, r in report.items()} == solo
    kid = key_id(jobs[0].bucket_key())
    assert ap1.capacity[kid] <= 4
    assert any(r["rule"] == "capacity.learn" for r in ap1.decisions)

    # run 2: no OOM injection, fresh scheduler + controller, SAME
    # journal -> the bucket starts at the learned capacity
    monkeypatch.setattr(GridBatch, "step", real_step)
    jobs2 = _jobs(8, steps=10)
    ap2 = Autopilot(quantum=4, clock=lambda: 0.0,
                    decision_file=journal)
    assert ap2.capacity[kid] <= 4  # recovered from the journal alone
    pol2 = SLOPolicy(quantum=4, clock=lambda: 0.0)
    sched2 = FleetScheduler(str(tmp_path / "work2"), jobs2,
                            quantum=4, slo_policy=pol2, autopilot=ap2)
    sched2._admit_pending()
    caps = [b.capacity for bs in sched2.buckets.values() for b in bs]
    assert caps and all(c <= 4 for c in caps)
    assert any(r["rule"] == "capacity.seed" for r in ap2.decisions)
    report2 = sched2.run()
    assert {n: r["digest"] for n, r in report2.items()} == solo


def test_shed_history_recorded(tmp_path):
    """An SLO shed rebuild also lands in the capacity history."""
    ap = Autopilot(quantum=4, clock=lambda: 0.0)
    key = _jobs(1)[0].bucket_key()
    ap.record_shed(key, 6)
    ap.record_oom(key, 3)
    ap.record_oom(key, 5)  # never grows the learned floor mid-run
    assert ap.capacity[key_id(key)] == 3
    events = [r["inputs"]["event"] for r in ap.decisions]
    assert events == ["shed", "oom"]


def test_seed_floor_never_strips_a_dmr_shadow(tmp_path):
    """Capacity history learned from plain jobs must not disable a
    redundancy=2 job's DMR replica: the seed floors at the largest
    single job's slot demand."""
    ap = Autopilot(quantum=4, clock=lambda: 0.0)
    dmr = FleetJob("dmr0", length=(8, 8, 8), n_steps=8, seed=1,
                   params=(0.03,), checkpoint_every=4, redundancy=2)
    ap.capacity[key_id(dmr.bucket_key())] = 1  # history: plain jobs
    sched, _pol = _sched(tmp_path, [dmr], ap, quantum=4)
    sched._admit_pending()
    (batch,) = [b for bs in sched.buckets.values() for b in bs]
    assert batch.capacity >= 2
    assert batch.shadow_of  # the shadow replica was admitted
    (rec,) = [r for r in ap.decisions if r["rule"] == "capacity.seed"]
    assert rec["after"] == 2 and rec["inputs"]["lo"] == 2


def test_checkpoint_retune_uses_each_buckets_own_latency(tmp_path):
    """A heterogeneous fleet: each job's step time comes from ITS
    bucket's latency EWMA, not the slowest bucket's (which would
    over-checkpoint every fast job ~latency-ratio-fold)."""
    fast = FleetJob("fastj", length=(8, 8, 8), n_steps=400, seed=1,
                    params=(0.03,), checkpoint_every=64)
    slow = FleetJob("slowj", length=(12, 12, 12), n_steps=400, seed=2,
                    params=(0.03,), checkpoint_every=64)
    ap = Autopilot(quantum=4, clock=lambda: 0.0, adjust_every=1)
    sched, pol = _sched(tmp_path, [fast, slow], ap, quantum=4)
    sched._admit_pending()
    telemetry.registry().reset()
    telemetry.observe("dccrg_ckpt_save_seconds", 0.05,
                      kind="keyframe")
    pol.observe(fast.bucket_key(), 0.004)  # 0.001 s/step
    pol.observe(slow.bucket_key(), 0.4)    # 0.1 s/step
    for j in (fast, slow):
        j.steps_done = 64
        j.trips = [("nan", i) for i in range(8)]  # rate 0.125
    _tick(sched, ap)
    by_job = {r["knob"]: r["inputs"]["step_seconds"]
              for r in ap.decisions
              if r["rule"] == "checkpoint.retune"}
    assert len(by_job) == 2
    assert by_job["checkpoint_every[fastj]"] == pytest.approx(0.001)
    assert by_job["checkpoint_every[slowj]"] == pytest.approx(0.1)
    # Young with the SAME save cost and trip rate: the fast bucket
    # affords a longer cadence (sqrt(2*50/.125)=28), the slow one a
    # shorter (sqrt(2*0.5/.125)=3) — not one global answer
    assert fast.checkpoint_every == 28
    assert slow.checkpoint_every == 3


def test_capacity_floor_recovers_after_clean_runs(tmp_path):
    """The learned capacity is NOT a permanent ratchet: a seeded key
    that survives a whole run without OOM/shed earns a journaled
    capacity.probe doubling it back toward the configured default —
    and the journal replay reconstructs the recovery sequence."""
    journal = str(tmp_path / "j.jsonl")
    ap = Autopilot(quantum=4, clock=lambda: 0.0,
                   decision_file=journal)
    key = _jobs(1)[0].bucket_key()
    kid = key_id(key)
    ap.record_oom(key, 4)
    ap.end_of_run()  # the OOM run itself earns nothing
    assert ap.capacity[kid] == 4
    # (seeded at, recovered to): doubles per clean run, capped at
    # the configured default — after which neither rule fires
    for seeded, recovered in ((4, 8), (8, 16), (16, 16)):
        assert ap.seed_capacity(key, 16) == seeded
        ap.end_of_run()
        assert ap.capacity[kid] == recovered
    # a fresh controller replays learn AND probe records in order
    ap2 = Autopilot(quantum=4, clock=lambda: 0.0,
                    decision_file=journal)
    assert ap2.capacity[kid] == 16
    assert replay(read_journal(journal)) == []


def test_quantum_warm_starts_from_journal(tmp_path):
    """The carried-item pin: a run whose controller converged the
    QUANTUM knob journals the final value at the clean drain
    (quantum.learn), and a FRESH controller sharing only the journal
    warm-starts the next scheduler there on its first tick
    (quantum.warm_start) instead of re-halving from the configured
    default — the capacity.learn/probe discipline for the quantum.
    Replay reconstructs both runs."""
    journal = str(tmp_path / "j.jsonl")
    jobs = _jobs(2, slo_ms=100.0)
    ap = Autopilot(quantum=16, clock=lambda: 0.0,
                   decision_file=journal)
    sched, pol = _sched(tmp_path / "one", _jobs(2, slo_ms=100.0), ap,
                        quantum=16)
    sched._admit_pending()
    for _b, _s, j in sched.active_jobs():
        j.slo_t0 = 0.0
    pol.observe(jobs[0].bucket_key(), 10.0)  # blows the 100 ms SLO
    _tick(sched, ap, 8)
    assert sched.quantum == 1  # converged to the floor
    ap.end_of_run()
    learns = [r for r in ap.decisions if r["rule"] == "quantum.learn"]
    assert [(r["before"], r["after"]) for r in learns] == [(None, 1)]
    # run 2: a fresh controller + scheduler, same configured quantum,
    # sharing ONLY the journal
    ap2 = Autopilot(quantum=16, clock=lambda: 0.0,
                    decision_file=journal)
    assert ap2.learned_quantum == 1
    sched2, _pol2 = _sched(tmp_path / "two", _jobs(2), ap2, quantum=16)
    sched2._admit_pending()
    _tick(sched2, ap2, 1)
    assert sched2.quantum == 1  # warm-started, not re-halved
    warm = [r for r in ap2.decisions
            if r["rule"] == "quantum.warm_start"]
    assert [(r["before"], r["after"]) for r in warm] == [(16, 1)]
    assert replay(read_journal(journal)) == []
    # a run that never tuned (and has no prior memory) journals no
    # quantum.learn: nothing to remember
    j3 = str(tmp_path / "j3.jsonl")
    ap3 = Autopilot(quantum=16, clock=lambda: 0.0, decision_file=j3)
    ap3.end_of_run()
    assert not os.path.exists(j3)


def test_checkpoint_retune_prices_measured_rollback_cost(tmp_path):
    """The carried-item pin: the cadence rule extends Young with the
    MEASURED per-trip recovery cost (Daly's sqrt(2*C*(M+R))): with a
    recorded ``rollback_s`` the optimum lengthens exactly by the
    closed form, the live gather feeds the measured
    dccrg_rollback_seconds mean into the journaled inputs, and
    replay stays divergence-free."""
    inp = {"save_cost_s": 0.05, "step_seconds": 0.01, "lo": 1,
           "hi": 256, "trip_rate": 0.125}
    young = RULES["checkpoint.retune"](64, dict(inp))
    daly = RULES["checkpoint.retune"](64, dict(inp, rollback_s=0.4))
    assert young == round((2 * 5 / 0.125) ** 0.5)  # = 9, R absent
    # M = 8 steps, R = 0.4/0.01 = 40 steps: sqrt(2*5*48) ~ 22
    assert daly == round((2 * 5 * (8 + 40)) ** 0.5)
    assert daly > young
    # None / zero rollback history degrades to Young exactly
    assert RULES["checkpoint.retune"](
        64, dict(inp, rollback_s=None)) == young
    # live path: the measured rollback histogram lands in the inputs
    journal = str(tmp_path / "j.jsonl")
    jobs = _jobs(2, steps=400)
    sched, pol = _sched(tmp_path, jobs, None, quantum=4)
    sched._admit_pending()
    pol.observe(jobs[0].bucket_key(), 0.04)  # 0.01 s/step
    telemetry.registry().reset()
    ap = Autopilot(quantum=4, clock=lambda: 0.0, adjust_every=1,
                   decision_file=journal)
    sched.autopilot = ap
    for _ in range(6):
        telemetry.observe("dccrg_ckpt_save_seconds", 0.05,
                          kind="keyframe")
    telemetry.observe("dccrg_rollback_seconds", 0.4)
    telemetry.observe("dccrg_rollback_seconds", 0.4)
    tripping = jobs[0]
    tripping.steps_done = 64
    tripping.trips = [("nan", i) for i in range(8)]  # rate 0.125
    _tick(sched, ap)
    assert tripping.checkpoint_every == daly
    recs = [r for r in read_journal(journal)
            if r["rule"] == "checkpoint.retune"]
    assert recs and all(abs(r["inputs"]["rollback_s"] - 0.4) < 1e-9
                        for r in recs)
    assert replay(read_journal(journal)) == []


# -- the negative pin: off by default, bitwise untouched --------------

def test_off_by_default_negative_pin(tmp_path):
    """With ``DCCRG_AUTOPILOT`` unset: no controller exists, every
    knob keeps its configured value through a full serving run (trips
    included), results are bitwise the solo baselines, and no journal
    or status file appears."""
    jobs = _jobs(4)
    solo = _solo(jobs)
    plan = FaultPlan(seed=3)
    plan.nan_poison("rho", step=7, job="a01")
    sched, _pol = _sched(tmp_path, jobs, quantum=4, audit_every=2)
    assert sched.autopilot is None
    with plan:
        report = sched.run()
    assert all(r["status"] == "done" for r in report.values())
    assert {n: r["digest"] for n, r in report.items()} == solo
    assert report["a01"]["trips"] == 1
    # knobs bitwise untouched through trips, saves and audits
    assert sched.quantum == 4 and sched.audit_every == 2
    assert all(j.checkpoint_every == 4 for j in jobs)
    assert telemetry.registry().counter_total(
        "dccrg_autopilot_decisions_total") == 0
    leftovers = [f for f in os.listdir(tmp_path)
                 if "decision" in f or "status" in f]
    assert leftovers == []


def test_autopilot_on_preserves_results(tmp_path, monkeypatch):
    """The env-opt-in path: ``DCCRG_AUTOPILOT=1`` constructs the
    controller inside the scheduler, the run self-tunes (decisions
    journal), and every job's digest STILL matches its solo run —
    tuning moves cadences, never bytes."""
    journal = str(tmp_path / "decisions.jsonl")
    status = str(tmp_path / "status.txt")
    monkeypatch.setenv("DCCRG_AUTOPILOT", "1")
    monkeypatch.setenv("DCCRG_DECISION_FILE", journal)
    monkeypatch.setenv("DCCRG_STATUS_FILE", status)
    jobs = _jobs(4, steps=24)
    solo = _solo(jobs)
    plan = FaultPlan(seed=5)
    plan.nan_poison("rho", step=9, job="a02")
    sched, _pol = _sched(tmp_path, jobs, quantum=4)
    assert sched.autopilot is not None
    with plan:
        report = sched.run()
    assert all(r["status"] == "done" for r in report.values())
    assert {n: r["digest"] for n, r in report.items()} == solo
    assert os.path.exists(status)
    text = open(status).read()
    assert "quantum=" in text and "suspects:" in text \
        and "buckets:" in text
    # whatever it decided is fully re-derivable from the journal
    recs = read_journal(journal)
    assert replay(recs) == []


# -- the journal: explain + replay ------------------------------------

def _synth_journal(tmp_path, n=6):
    """A journal with real decisions, produced by the controller
    itself (fake clock, hand-fed pressure)."""
    journal = str(tmp_path / "j.jsonl")
    jobs = _jobs(2, slo_ms=100.0)
    sched, pol = _sched(tmp_path, jobs, None, quantum=16)
    sched._admit_pending()
    for j in jobs:
        j.slo_t0 = 0.0
    pol.observe(jobs[0].bucket_key(), 10.0)
    sched.suspects[0] = 1
    # the admission keyframes recorded REAL save timings: reset, then
    # construct the controller (its observation baseline anchors
    # here) and feed it a fixed history, so the journal is fully
    # deterministic
    telemetry.registry().reset()
    ap = Autopilot(quantum=16, clock=lambda: 0.0,
                   decision_file=journal)
    sched.autopilot = ap
    telemetry.observe("dccrg_ckpt_save_seconds", 0.05,
                      kind="keyframe")
    _tick(sched, ap, n)
    ap.record_oom(jobs[0].bucket_key(), 4)
    assert ap.seq >= 3
    return journal, ap


def test_journal_replay_equivalence_and_divergence(tmp_path):
    """Replay re-derives every action from the recorded inputs; a
    tampered record (or an unknown rule) is a detected divergence."""
    journal, ap = _synth_journal(tmp_path)
    recs = read_journal(journal)
    assert len(recs) == ap.seq == len(ap.decisions)
    assert replay(recs) == []
    bad = [dict(r) for r in recs]
    bad[0]["after"] = 999
    div = replay(bad)
    assert len(div) == 1 and "re-derived" in div[0][1]
    bad[1]["rule"] = "quantum.noSuchRule"
    assert len(replay(bad)) == 2


def test_journal_is_deterministic(tmp_path):
    """Two identical fake-clock runs journal identical decision
    sequences (wall-clock anchors aside) — the controller has no
    hidden nondeterministic input."""
    strip = lambda rs: [  # noqa: E731
        {k: v for k, v in r.items() if k != "ts"} for r in rs]
    j1, _ = _synth_journal(tmp_path / "one")
    j2, _ = _synth_journal(tmp_path / "two")
    assert strip(read_journal(j1)) == strip(read_journal(j2))


def test_explain_and_replay_cli(tmp_path, capsys):
    journal, ap = _synth_journal(tmp_path)
    assert ap_mod._main(["explain", journal]) == 0
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.startswith("[tick")]
    assert len(lines) == ap.seq
    assert any("quantum.shorten" in ln and "->" in ln
               and "observed:" in ln and "expected:" in ln
               for ln in lines)
    assert ap_mod._main(["replay", journal]) == 0
    out = capsys.readouterr().out
    assert json.loads(out.splitlines()[-1])["divergences"] == 0
    # tamper -> nonzero exit naming the diverged record
    recs = read_journal(journal)
    recs[-1]["after"] = -5
    broken = str(tmp_path / "broken.jsonl")
    with open(broken, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    assert ap_mod._main(["replay", broken]) == 1
    assert "DIVERGED" in capsys.readouterr().out


def test_decision_ring_bounded(tmp_path):
    ap = Autopilot(quantum=4, clock=lambda: 0.0, ring=16,
                   decision_file=None)
    key = ("k",)
    for i in range(50):
        ap._learn_capacity(key, 50 - i, "oom")  # fires every time
    assert ap.seq == 50 and len(ap.decisions) == 16
    assert ap.decisions[-1]["seq"] == 49


def test_explain_decision_names_everything():
    rec = {"seq": 0, "tick": 3, "rank": 1, "rule": "audit.tighten",
           "knob": "audit_every", "before": 8, "after": 4,
           "inputs": {"new_suspects": 2}, "expected": "x"}
    line = explain_decision(rec)
    for frag in ("tick 3", "rank 1", "audit.tighten", "8 -> 4",
                 "new_suspects=2", "expected: x"):
        assert frag in line


# -- controller-input metrics (useful with the autopilot off) ---------

def test_save_rollback_audit_metrics_and_lane_gauges(tmp_path):
    """The satellite pin: save-cost/rollback-cost/audit-cost
    histograms and per-lane suspect gauges are recorded by a plain
    (autopilot-off) fleet run with a trip, an audit cadence and a
    silent flip."""
    jobs = _jobs(4, steps=16)
    plan = FaultPlan(seed=11)
    plan.nan_poison("rho", step=6, job="a01")
    plan.silent_flip("rho", step=10, job="a03")
    sched, _pol = _sched(tmp_path, jobs, quantum=4, audit_every=2)
    with plan:
        report = sched.run()
    assert all(r["status"] == "done" for r in report.values())
    reg = telemetry.registry()
    h = reg.histogram("dccrg_ckpt_save_seconds", kind="keyframe")
    assert h is not None and h.total > 0 and h.sum_seconds > 0
    assert reg.histogram("dccrg_rollback_seconds").total >= 2
    assert reg.histogram("dccrg_audit_seconds").total >= 1
    assert reg.gauges[("dccrg_lane_suspects",
                       (("lane", "0"),))] >= 1.0
    assert ("dccrg_lane_quarantined",
            (("lane", "0"),)) in reg.gauges


def test_telemetry_summary_covers_histograms(tmp_path, capsys):
    """The satellite pin: ``python -m dccrg_tpu.telemetry summary``
    over a metrics file prints per-histogram p50/p99 — the same
    numbers the controller acts on — parsed back from the Prometheus
    exposition."""
    for v in (0.002, 0.004, 0.008, 0.3):
        telemetry.observe("dccrg_ckpt_save_seconds", v,
                          kind="keyframe")
    telemetry.observe("dccrg_fleet_quantum_seconds", 0.05, job="a")
    live = telemetry.histogram_stats()
    path = str(tmp_path / "metrics.prom")
    assert telemetry.export_metrics(path)
    hists = telemetry.parse_prometheus_histograms(open(path).read())
    offline = telemetry.histogram_stats(hists)
    key = 'dccrg_ckpt_save_seconds{kind="keyframe"}'
    assert key in offline
    assert offline[key]["count"] == 4
    assert offline[key]["p50_s"] == pytest.approx(live[key]["p50_s"])
    assert offline[key]["p99_s"] == pytest.approx(live[key]["p99_s"])
    assert telemetry._main(["summary", path]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["histograms"][key]["p99_s"] == pytest.approx(
        live[key]["p99_s"])
    assert 'dccrg_fleet_quantum_seconds{job="a"}' in out["histograms"]


def test_controller_baselines_preexisting_registry_history(tmp_path):
    """The registry outlives schedulers: a controller constructed
    after an earlier run's trips/saves must NOT inherit them as a
    phantom first-tick observation (no spurious quantum.shorten, no
    foreign save costs — and emergency saves never price the
    periodic cadence)."""
    sched, _pol = _sched(tmp_path, _jobs(2), None, quantum=8)
    sched._admit_pending()
    # foreign history lands BEFORE the controller exists...
    telemetry.inc("dccrg_fleet_trips_total", 50, job="old")
    telemetry.observe("dccrg_ckpt_save_seconds", 100.0,
                      kind="keyframe")
    ap = Autopilot(quantum=8, clock=lambda: 0.0)  # ...baseline here
    sched.autopilot = ap
    telemetry.observe("dccrg_ckpt_save_seconds", 0.25,
                      kind="delta")
    telemetry.observe("dccrg_ckpt_save_seconds", 9.0,
                      kind="emergency")  # excluded from the mean
    inp = ap.tick(sched)
    assert inp["trip_rate"] == 0.0  # the 50 old trips never count
    assert inp["save_cost_s"] == pytest.approx(0.25)
    assert not any(r["rule"] == "quantum.shorten"
                   for r in ap.decisions)


def test_injected_autopilot_never_stomps_configured_knobs(tmp_path):
    """The scheduler's live knobs are the source of truth: an
    injected controller whose constructor defaults differ writes
    nothing back unless a rule fires (every knob move is a journaled
    decision — the module's headline contract)."""
    ap = Autopilot(clock=lambda: 0.0)  # defaults: quantum=8, audit=0
    sched, pol = _sched(tmp_path, _jobs(2), ap, quantum=4,
                        audit_every=6)
    sched._admit_pending()
    _tick(sched, ap, 3)  # no pressure, no latency data: no rules
    assert ap.seq == 0
    assert sched.quantum == 4 and sched.audit_every == 6
    assert pol.quantum == 4


def test_skipped_audit_not_counted_as_performed(tmp_path,
                                                monkeypatch):
    """An audit window with no comparable re-execution path (bulk
    bucket, no spare slot) must not report a performed audit."""
    jobs = _jobs(2, steps=8)
    sched, _pol = _sched(tmp_path, jobs, quantum=4, audit_every=1)
    sched._admit_pending()
    monkeypatch.setattr(FleetScheduler, "_audit_digests",
                        lambda self, *a: None)
    report = sched.run()
    assert all(r["status"] == "done" for r in report.values())
    assert sched.audits == 0
    assert telemetry.registry().counter_total(
        "dccrg_audits_total") == 0
    assert telemetry.registry().histogram(
        "dccrg_audit_seconds") is None


def test_summary_sums_per_rank_metrics_files(tmp_path, capsys):
    """Per-rank metrics files of one run SUM per series (a plain
    dict merge would keep only the last rank)."""
    paths = []
    tricky = "a\\nb"  # literal backslash then 'n': the escape-order trap
    for rank, vals in enumerate([(0.002, 0.004), (0.004, 0.3)]):
        telemetry.registry().reset()
        for v in vals:
            telemetry.observe("dccrg_step_seconds", v)
            telemetry.observe("dccrg_fleet_quantum_seconds", v,
                              job=tricky)
        p = str(tmp_path / f"metrics_r{rank}.prom")
        assert telemetry.export_metrics(p)
        paths.append(p)
    telemetry.registry().reset()
    assert telemetry._main(["summary", *paths]) == 0
    out = json.loads(capsys.readouterr().out)
    h = out["histograms"]["dccrg_step_seconds"]
    assert h["count"] == 4
    assert h["sum_s"] == pytest.approx(0.31)
    # the merged p99 sees rank 1's tail, not just the last file
    assert h["p99_s"] >= 0.3
    # a label holding backslash-then-n round-trips the exposition
    # escaping exactly, so both ranks' series merged under ONE key
    (tricky_key,) = [k for k in out["histograms"]
                     if k.startswith("dccrg_fleet_quantum_seconds")]
    assert out["histograms"][tricky_key]["count"] == 4


def test_bench_trend_flags_regressions(tmp_path):
    """The satellite pin: bench/trend.py merges the per-round JSONs
    into one metric-keyed trajectory and flags >10% regressions vs
    the best prior round (direction-aware)."""
    rows = [
        (1, {"grid_path_updates_per_sec": 100.0, "l2_error": 1e-4,
             "parity_l2_error": 0.0, "legacy_per_sec": 100.0}),
        (2, {"grid_path_updates_per_sec": 120.0, "l2_error": 1e-4,
             "parity_l2_error": 0.0, "legacy_per_sec": 50.0}),
        (3, {"grid_path_updates_per_sec": 90.0, "l2_error": 2e-4,
             "parity_l2_error": 1e-3}),
    ]
    files = []
    for n, parsed in rows:
        p = str(tmp_path / f"BENCH_r{n:02d}.json")
        with open(p, "w") as f:
            json.dump({"n": n, "parsed": parsed}, f)
        files.append(p)
    script = os.path.join(os.path.dirname(__file__), "..", "bench",
                          "trend.py")
    out = subprocess.run(
        [sys.executable, script, *files, "--json"],
        capture_output=True, text=True)
    assert out.returncode == 0
    d = json.loads(out.stdout)
    flagged = {r["metric"] for r in d["regressions"]}
    # 90 is -25% vs best prior 120; 2e-4 doubles the error; and a
    # regression FROM a perfect 0.0 baseline (a bitwise-parity
    # metric going nonzero) flags even though no ratio exists —
    # while legacy_per_sec, regressed in r02 but ABSENT from the
    # newest round (a removed bench leg), never flags stale
    assert flagged == {"grid_path_updates_per_sec", "l2_error",
                       "parity_l2_error"}
    # within-noise rounds do not flag, and --strict gates CI
    assert subprocess.run(
        [sys.executable, script, *files[:2], "--json"],
        capture_output=True, text=True).returncode == 0
    assert subprocess.run(
        [sys.executable, script, *files, "--strict"],
        capture_output=True, text=True).returncode == 1
