"""Run supervision: emergency checkpoints on preemption, the
step-hang deadline watchdog, transient-dispatch retry, auto-resume
ordering and retention GC — every path driven deterministically by
fault injection (dccrg_tpu.faults), plus a REAL in-process SIGTERM.

The acceptance pins: a preemption signal (faked or real) produces a
CRC-verified checkpoint and a resumable exit, and `resume_latest`
reconverges bitwise with an uninterrupted same-seed run; an injected
step hang raises StepTimeoutError within the configured deadline
(never blocks); retention GC can never delete the only checkpoint
that passes verification."""

import os
import shutil
import signal
import time

import numpy as np
import pytest

import jax.numpy as jnp

from dccrg_tpu import Grid, faults, resilience, supervise
from dccrg_tpu.supervise import (
    RESUMABLE_EXIT, CheckpointStore, PreemptedError, StepTimeoutError,
    SupervisedRunner, gc_checkpoints, list_checkpoints, resume_latest,
    retention_plan)

pytestmark = pytest.mark.supervise

CELL_DATA = {"v": jnp.float32}


def _mk(seed=0):
    g = (Grid(cell_data=CELL_DATA)
         .set_initial_length((8, 8, 4))
         .set_periodic(True, True, False)
         .set_maximum_refinement_level(0)
         .set_neighborhood_length(1)
         # the METHOD: resume_latest repartitions with it, so
         # ownership stays stable across restore
         .set_load_balancing_method("block")
         .initialize())
    cells = g.plan.cells
    g.set("v", cells, ((cells.astype(np.float64) * (seed + 7) % 31) / 31)
          .astype(np.float32))
    g.update_copies_of_remote_neighbors()
    return g


def _kernel(c, nbr, offs, mask):
    return {"v": jnp.float32(0.5) * c["v"] + jnp.float32(0.125) * jnp.sum(
        jnp.where(mask, nbr["v"], jnp.float32(0)), axis=1)}


def _step_fn(grid, _i):
    grid.run_steps(_kernel, ["v"], ["v"], 1)


def _sup(tmp_path, name, grid=None, step_fn=_step_fn, **kw):
    kw.setdefault("check_every", 100)
    kw.setdefault("checkpoint_every", 3)
    kw.setdefault("backoff", 0.0)
    kw.setdefault("keep_last", 99)
    return SupervisedRunner(grid if grid is not None else _mk(), step_fn,
                            str(tmp_path / name), **kw)


def _state(sup):
    g = sup.grid
    return np.asarray(g.get("v", g.plan.cells)).tobytes()


# -- preemption -------------------------------------------------------

def test_fake_preempt_emergency_checkpoint_and_resumable_exit(tmp_path):
    """FaultPlan.preempt_signal at the boundary after step 4: the run
    stops there, the emergency checkpoint is written AND CRC-verifies,
    and the error carries the distinct resumable exit code."""
    sup = _sup(tmp_path, "pre")
    plan = faults.FaultPlan(seed=1)
    plan.preempt_signal(step=4)
    with plan, pytest.raises(PreemptedError) as ei:
        sup.run(10)
    e = ei.value
    assert plan.fired("supervise.preempt") == 1
    assert e.exit_code == RESUMABLE_EXIT == 75
    assert e.step == 5 and e.clean
    assert sup.preempted and sup.step == 5
    assert e.checkpoint == sup.store.path_for(5)
    assert resilience.verify_checkpoint(e.checkpoint) == []


def test_preempt_resume_reconverges_bitwise(tmp_path):
    """THE acceptance pin: preempt mid-run, resume_latest from the
    emergency checkpoint, run to the end — final state bitwise equals
    an uninterrupted run's."""
    ref = _sup(tmp_path, "ref")
    ref.run(12)
    want = _state(ref)

    sup = _sup(tmp_path, "pre")
    plan = faults.FaultPlan(seed=2)
    plan.preempt_signal(step=5)
    with plan, pytest.raises(PreemptedError):
        sup.run(12)

    info = resume_latest(str(tmp_path / "pre"), CELL_DATA,
                         load_balancing_method="block")
    assert info is not None and not info.salvaged
    assert info.step == 6 and info.report.clean
    info.grid.update_copies_of_remote_neighbors()
    sup2 = _sup(tmp_path, "pre", grid=info.grid, start_step=info.step)
    sup2.run(12)
    assert sup2.step == 12
    assert _state(sup2) == want


def test_real_sigterm_mid_step_preempts_at_boundary(tmp_path):
    """An actual SIGTERM delivered to this process mid-step (the
    handler is installed by the supervisor) sets the flag; the run
    stops at the NEXT boundary with the emergency checkpoint."""
    def step_fn(grid, i):
        _step_fn(grid, i)
        if i == 3:
            os.kill(os.getpid(), signal.SIGTERM)

    sup = _sup(tmp_path, "sig", step_fn=step_fn)
    with pytest.raises(PreemptedError) as ei:
        sup.run(10)
    assert ei.value.step == 4
    assert resilience.verify_checkpoint(ei.value.checkpoint) == []
    assert not supervise.preempt_requested()  # next run starts clean


def test_second_sigint_escalates_to_keyboard_interrupt(tmp_path):
    """The first ctrl-C is a graceful preemption; a second one means
    'now' and must not be swallowed by the supervision machinery."""
    def step_fn(grid, i):
        _step_fn(grid, i)
        if i == 1:
            os.kill(os.getpid(), signal.SIGINT)
            os.kill(os.getpid(), signal.SIGINT)

    sup = _sup(tmp_path, "int", step_fn=step_fn)
    with pytest.raises(KeyboardInterrupt):
        sup.run(10)
    supervise.clear_preempt()


def test_preempt_loses_consensus_to_a_real_trip(tmp_path, monkeypatch):
    """A recoverable trip elsewhere on the mesh outranks the interrupt
    code: this rank rolls back with the peers FIRST, and the still-set
    preempt flag stops the run at the next boundary."""
    from dccrg_tpu import coord

    remote = []

    def fake_consensus(grid, code):
        if code == resilience._TRIP_INTERRUPT and not remote:
            remote.append(code)
            return resilience._TRIP_NUMERICS  # a peer tripped too
        return int(code)

    monkeypatch.setattr(coord, "trip_consensus", fake_consensus)
    sup = _sup(tmp_path, "race")
    plan = faults.FaultPlan(seed=3)
    plan.preempt_signal(step=4)
    with plan, pytest.raises(PreemptedError) as ei:
        sup.run(10)
    assert remote == [resilience._TRIP_INTERRUPT]
    assert sup.rollbacks == 1  # rolled back with the peers first
    # then preempted at the first boundary after the rollback
    assert ei.value.step == 4
    assert resilience.verify_checkpoint(ei.value.checkpoint) == []


def test_preempt_never_checkpoints_poisoned_state(tmp_path):
    """The rollback-target invariant extends to the emergency save: a
    NaN produced by the very step the preemption lands on trips a
    recovery FIRST (the boundary check runs before RunInterrupted),
    and the still-pending preemption stops the run at the first clean
    boundary — the emergency checkpoint is always finite."""
    poisoned = []

    def step_fn(grid, i):
        _step_fn(grid, i)
        if i == 4 and not poisoned:
            poisoned.append(i)
            cells = grid.plan.cells
            grid.set("v", cells[:1], np.array([np.nan], np.float32))

    sup = _sup(tmp_path, "poison", step_fn=step_fn,
               fields=("v",), checkpoint_every=3)
    plan = faults.FaultPlan(seed=11)
    plan.preempt_signal(step=4)
    with plan, pytest.raises(PreemptedError) as ei:
        sup.run(10)
    assert sup.rollbacks == 1  # recovered before honoring the preempt
    assert resilience.verify_checkpoint(ei.value.checkpoint) == []
    info = resume_latest(str(tmp_path / "poison"), CELL_DATA,
                         load_balancing_method="block")
    assert info.step == ei.value.step
    assert resilience.check_finite(info.grid)  # never NaN on disk


def test_transient_error_after_state_mutation_does_not_double_apply(
        tmp_path):
    """A real transient error surfaces AFTER step_fn already advanced
    grid.data (async dispatch): the retry must rewind to the pre-step
    arrays, not re-apply the step on top of the new ones — pinned by
    bitwise agreement with an undisturbed run."""
    ref = _sup(tmp_path, "mref")
    ref.run(6)

    failed = []

    def step_fn(grid, i):
        _step_fn(grid, i)  # the mutation lands first...
        if i == 3 and not failed:
            failed.append(i)  # ...then the transient error surfaces
            raise faults.InjectedDispatchError("post-mutation")

    sup = _sup(tmp_path, "mut", step_fn=step_fn, dispatch_backoff=0.0)
    sup.run(6)
    assert sup.dispatch_retried == 1 and sup.rollbacks == 0
    assert _state(sup) == _state(ref)


def test_emergency_save_shortens_the_barrier_timeout(tmp_path,
                                                     monkeypatch):
    """During the emergency save the coord.barrier timeout is cut to a
    quarter of the grace window (so ONE dead peer cannot eat it all),
    and restored afterwards."""
    from dccrg_tpu import coord

    seen = []
    real_save = resilience.save_checkpoint

    def spy_save(grid, path, **kw):
        seen.append(coord.barrier_timeout())
        return real_save(grid, path, **kw)

    monkeypatch.setattr(resilience, "save_checkpoint", spy_save)
    monkeypatch.setenv("DCCRG_BARRIER_TIMEOUT", "120")
    sup = _sup(tmp_path, "grace", grace=8.0)
    plan = faults.FaultPlan(seed=4)
    plan.preempt_signal(step=2)
    with plan, pytest.raises(PreemptedError):
        sup.run(10)
    # periodic saves (full timeout) + the emergency one (grace / 4)
    assert seen[-1] == 2.0
    assert all(t == 120.0 for t in seen[:-1])
    assert coord.barrier_timeout() == 120.0  # restored


def test_emergency_save_failure_falls_back_to_periodic(tmp_path,
                                                       monkeypatch):
    """When the emergency save itself dies (I/O fault), the run is
    still resumable: the error points at the last periodic
    checkpoint, clean=False tells the story."""
    real_save = resilience.save_checkpoint
    calls = []

    def flaky_save(grid, path, **kw):
        calls.append(path)
        if "00000005" in path:
            raise OSError("disk gone")
        return real_save(grid, path, **kw)

    monkeypatch.setattr(resilience, "save_checkpoint", flaky_save)
    sup = _sup(tmp_path, "fb")
    plan = faults.FaultPlan(seed=5)
    plan.preempt_signal(step=4)
    with plan, pytest.raises(PreemptedError) as ei:
        sup.run(10)
    assert not ei.value.clean
    assert ei.value.checkpoint == sup.store.path_for(3)  # periodic
    assert resilience.verify_checkpoint(ei.value.checkpoint) == []


# -- step-hang watchdog + transient dispatch retry --------------------

def test_step_hang_raises_typed_timeout_within_deadline(tmp_path):
    """An injected wedged dispatch raises StepTimeoutError NAMING the
    step within the configured deadline — never a block-forever."""
    g = _mk()
    _step_fn(g, 0)  # warm the compiled step: the deadline is tight
    sup = _sup(tmp_path, "hang", grid=g, step_timeout=0.5)
    plan = faults.FaultPlan(seed=6)
    plan.step_hang(step=2)
    t0 = time.monotonic()
    with plan, pytest.raises(StepTimeoutError) as ei:
        sup.run(10)
    assert time.monotonic() - t0 < 10.0
    assert ei.value.step == 2
    assert "step 2" in str(ei.value)
    assert plan.fired("supervise.hang") == 1


def test_slow_but_alive_step_completes_under_deadline(tmp_path):
    """A finite hang below the deadline models a slow step: the run
    completes, nothing trips."""
    sup = _sup(tmp_path, "slow", step_timeout=30.0)
    plan = faults.FaultPlan(seed=7)
    plan.step_hang(step=1, hang_s=0.05)
    with plan:
        sup.run(4)
    assert sup.step == 4 and sup.rollbacks == 0


def test_step_timeout_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("DCCRG_STEP_TIMEOUT", "0.4")
    g = _mk()
    _step_fn(g, 0)  # warm the compiled step: the deadline is tight
    sup = _sup(tmp_path, "env", grid=g)
    assert sup.step_timeout == 0.4
    plan = faults.FaultPlan(seed=8)
    plan.step_hang(step=1)
    with plan, pytest.raises(StepTimeoutError):
        sup.run(4)


def test_transient_dispatch_errors_retry_without_rollback(tmp_path):
    """Two injected UNAVAILABLE dispatch errors: the step retries with
    backoff and succeeds — no trip, no rollback, and the final state
    bitwise equals an undisturbed run's."""
    ref = _sup(tmp_path, "dref")
    ref.run(6)

    sup = _sup(tmp_path, "disp", dispatch_backoff=0.0)
    plan = faults.FaultPlan(seed=9)
    plan.dispatch_error(times=2, step=3)
    with plan:
        sup.run(6)
    assert plan.fired("supervise.dispatch") == 2
    assert sup.dispatch_retried == 2
    assert sup.rollbacks == 0 and not sup.trips
    assert _state(sup) == _state(ref)


def test_persistent_dispatch_errors_exhaust_and_surface(tmp_path):
    """A dispatch error that never clears surfaces after the bounded
    retries instead of looping forever."""
    sup = _sup(tmp_path, "dead", dispatch_retries=2, dispatch_backoff=0.0)
    plan = faults.FaultPlan(seed=10)
    plan.dispatch_error(times=faults.EVERY)
    with plan, pytest.raises(faults.InjectedDispatchError):
        sup.run(6)
    assert sup.dispatch_retried == 2


# -- checkpoint store, resume ordering, retention GC ------------------

def test_store_paths_and_listing(tmp_path):
    store = CheckpointStore(tmp_path / "s", stem="run")
    assert store.path_for(7).endswith("run_00000007.dc")
    for s in (3, 11, 7):
        with open(store.path_for(s), "wb") as f:
            f.write(b"x")
    assert [s for s, _ in store.list()] == [11, 7, 3]
    # foreign stems are invisible to a stem-scoped store
    with open(os.path.join(store.dir, "other_00000099.dc"), "wb") as f:
        f.write(b"x")
    assert [s for s, _ in store.list()] == [11, 7, 3]
    assert [s for s, _ in list_checkpoints(store.dir)] == [99, 11, 7, 3]


def test_retention_plan_policy():
    keep, drop = retention_plan(range(1, 11), keep_last=2, keep_every=4)
    assert keep == [10, 9, 8, 4]
    assert drop == [7, 6, 5, 3, 2, 1]
    # keep_last clamps to 1: the pure policy can never empty a dir
    keep, drop = retention_plan([5], keep_last=0)
    assert keep == [5] and drop == []
    assert retention_plan([], 3, 0) == ([], [])


def _plant_store(tmp_path, steps, seed=0):
    """A store of REAL checkpoints: one saved grid, copied (file +
    sidecar) to every step — byte-identical, individually
    corruptible."""
    store = CheckpointStore(tmp_path / f"plant{seed}")
    g = _mk(seed)
    proto = os.path.join(store.dir, "proto.bin")
    resilience.save_checkpoint(g, proto)
    for s in steps:
        shutil.copy(proto, store.path_for(s))
        shutil.copy(resilience.sidecar_path(proto),
                    resilience.sidecar_path(store.path_for(s)))
    os.unlink(proto)
    os.unlink(resilience.sidecar_path(proto))
    return store


def _corrupt_payload(path):
    rec = resilience.read_sidecar(path)
    faults.flip_bit(path, int(rec["payload_start"]) + 5, 1)


def test_resume_ordering_prefers_newest_verified(tmp_path):
    """A directory mixing valid, corrupt and unverifiable checkpoints
    resolves to the NEWEST one that passes verification."""
    store = _plant_store(tmp_path, (2, 4, 6, 8))
    _corrupt_payload(store.path_for(8))                    # fails CRC
    os.unlink(resilience.sidecar_path(store.path_for(6)))  # unverifiable
    info = resume_latest(store.dir, CELL_DATA, stem=store.stem,
                         load_balancing_method="block")
    assert info is not None and not info.salvaged
    assert info.step == 4
    want = np.asarray(_mk(0).get("v", _mk(0).plan.cells))
    got = np.asarray(info.grid.get("v", info.grid.plan.cells))
    np.testing.assert_array_equal(got, want)


def test_resume_salvages_newest_when_nothing_verifies(tmp_path):
    store = _plant_store(tmp_path, (2, 4))
    _corrupt_payload(store.path_for(2))
    _corrupt_payload(store.path_for(4))
    info = resume_latest(store.dir, CELL_DATA, stem=store.stem,
                         load_balancing_method="block")
    assert info is not None and info.salvaged
    assert info.step == 4
    assert len(info.report.corrupt_cells)
    assert resume_latest(store.dir, CELL_DATA, stem=store.stem,
                         salvage=False) is None
    assert resume_latest(str(tmp_path / "empty"), CELL_DATA) is None


def test_gc_applies_policy_and_removes_sidecars(tmp_path):
    store = _plant_store(tmp_path, (1, 2, 3, 4, 5, 6))
    rep = store.gc(keep_last=2, keep_every=3, apply=False)
    assert [s for s, _ in rep.kept] == [6, 5, 3]
    assert os.path.exists(store.path_for(1))  # dry run touches nothing
    rep = store.gc(keep_last=2, keep_every=3, apply=True)
    assert rep.applied
    assert [s for s, _ in store.list()] == [6, 5, 3]
    for s, path in rep.dropped:
        assert not os.path.exists(path)
        assert not os.path.exists(resilience.sidecar_path(path))


def test_gc_never_deletes_the_only_verified_checkpoint(tmp_path):
    """Planted corruption: every keeper fails its CRC; the newest
    VERIFYING dropee must be rescued instead of pruned."""
    store = _plant_store(tmp_path, (1, 2, 3, 4, 5))
    for s in (4, 5):  # the keep_last=2 keepers
        _corrupt_payload(store.path_for(s))
    rep = store.gc(keep_last=2, apply=True)
    assert rep.rescued == 3
    assert [s for s, _ in store.list()] == [5, 4, 3]
    assert resilience.verify_checkpoint(store.path_for(3)) == []


def test_gc_refuses_when_nothing_verifies(tmp_path):
    store = _plant_store(tmp_path, (1, 2, 3))
    for s in (1, 2, 3):
        _corrupt_payload(store.path_for(s))
    rep = store.gc(keep_last=1, apply=True)
    assert rep.refused and not rep.dropped
    assert [s for s, _ in store.list()] == [3, 2, 1]  # all survive


def test_gc_verification_property_under_fuzzed_directories(tmp_path):
    """The acceptance property, fuzzed: whatever the step set, policy
    and corruption pattern, a prune never removes the last checkpoint
    that passes verification."""
    rng = np.random.default_rng(42)
    for trial in range(8):
        steps = sorted(rng.choice(np.arange(1, 30), replace=False,
                                  size=int(rng.integers(1, 8))).tolist())
        store = _plant_store(tmp_path / f"t{trial}", steps, seed=trial)
        corrupt = [s for s in steps if rng.random() < 0.5]
        for s in corrupt:
            _corrupt_payload(store.path_for(s))
        any_ok_before = len(corrupt) < len(steps)
        store.gc(keep_last=int(rng.integers(1, 4)),
                 keep_every=int(rng.integers(0, 6)), apply=True)
        left_ok = [s for s, p in store.list()
                   if not resilience.verify_checkpoint(p)]
        if any_ok_before:
            assert left_ok, (trial, steps, corrupt)
        else:
            assert [s for s, _ in store.list()] \
                == sorted(steps, reverse=True), (trial, steps)


def test_preempt_flag_consumed_without_signal_handlers(tmp_path):
    """install_signal_handlers=False (the non-main-thread mode): a
    honored preemption must consume the flag, or every later run in
    the process would re-preempt at its first boundary."""
    sup = _sup(tmp_path, "nohandler", install_signal_handlers=False)
    supervise.request_preempt()
    with pytest.raises(PreemptedError) as ei:
        sup.run(10)
    assert ei.value.step == 1  # honored at the first boundary
    assert not supervise.preempt_requested()
    info = resume_latest(str(tmp_path / "nohandler"), CELL_DATA,
                         load_balancing_method="block")
    info.grid.update_copies_of_remote_neighbors()
    sup2 = _sup(tmp_path, "nohandler", grid=info.grid,
                start_step=info.step, install_signal_handlers=False)
    sup2.run(10)  # makes real progress; no stale re-preempt
    assert sup2.step == 10 and not sup2.preempted


def test_gc_treats_each_stem_as_its_own_sequence(tmp_path):
    """stem=None (the CLI default) on a directory holding TWO runs'
    checkpoints: retention and the only-verifiable guard apply per
    stem — one run's corrupt files can never doom (or shadow) the
    other's."""
    a = _plant_store(tmp_path, (1, 2, 3))          # stem "ckpt"
    b = CheckpointStore(a.dir, stem="other")
    g = _mk(1)
    for s in (2, 3, 4):
        resilience.save_checkpoint(g, b.path_for(s))
    for s in (3, 4):  # ALL of stem "other"'s keepers corrupt
        _corrupt_payload(b.path_for(s))
    rep = gc_checkpoints(a.dir, keep_last=2, apply=True)
    # "ckpt" pruned by plain policy; "other" rescued its only
    # verifying file (step 2) despite sharing step numbers with "ckpt"
    assert [s for s, _ in a.list()] == [3, 2]
    assert [s for s, _ in b.list()] == [4, 3, 2]
    assert rep.rescued == 2
    assert resilience.verify_checkpoint(b.path_for(2)) == []


def test_gc_sweeps_stale_temp_files(tmp_path):
    store = _plant_store(tmp_path, (1, 2))
    mp_tmp = store.path_for(1) + ".mp-tmp"
    dead = os.path.join(store.dir, "x.dc.tmp.999999999")
    alive = os.path.join(store.dir, f"y.dc.salvage.{os.getpid()}")
    for p in (mp_tmp, dead, alive):
        with open(p, "wb") as f:
            f.write(b"t")
    rep = store.gc(keep_last=5, apply=True)
    assert sorted(rep.stale_temps) == sorted([mp_tmp, dead])
    assert not os.path.exists(mp_tmp) and not os.path.exists(dead)
    assert os.path.exists(alive)  # its owner (us) is still running


def test_runner_prunes_as_it_goes(tmp_path):
    """The supervised loop GCs after every periodic save: only the
    newest keep_last checkpoints remain at the end."""
    sup = _sup(tmp_path, "gc", keep_last=2, checkpoint_every=2)
    sup.run(10)
    assert [s for s, _ in sup.store.list()] == [10, 8]


# -- the maintenance CLI ----------------------------------------------

def test_cli_verify_and_gc(tmp_path, capsys):
    store = _plant_store(tmp_path, (1, 2, 3))
    good = store.path_for(3)
    assert resilience._main(["verify", good]) == 0
    assert "OK" in capsys.readouterr().out
    _corrupt_payload(store.path_for(2))
    assert resilience._main(["verify", store.path_for(2)]) == 1
    assert "CORRUPT" in capsys.readouterr().out

    assert resilience._main(["gc", store.dir, "--keep-last", "1"]) == 0
    out = capsys.readouterr().out
    assert "dry-run" in out and "--apply" in out
    assert [s for s, _ in store.list()] == [3, 2, 1]  # untouched
    assert resilience._main(["gc", store.dir, "--keep-last", "1",
                             "--apply"]) == 0
    assert "applied" in capsys.readouterr().out
    # step 2 is corrupt; 3 verifies and is kept, so policy prunes 1+2
    assert [s for s, _ in store.list()] == [3]


# -- wall-clock checkpoint cadence (DCCRG_CKPT_SECONDS) ---------------

def test_wall_clock_cadence_checkpoints_between_step_marks(tmp_path):
    """With a tiny wall-clock cadence and the step-count cadence
    effectively off, every (slow) step boundary becomes checkpoint-due
    — saves land at step boundaries only, numbered per step."""
    def slow_step(grid, i):
        _step_fn(grid, i)
        time.sleep(0.03)

    sup = _sup(tmp_path, "wc", step_fn=slow_step,
               checkpoint_every=10**9, checkpoint_seconds=0.02)
    sup.run(4)
    steps = sorted(s for s, _ in sup.store.list())
    # step 0's bootstrap save + every boundary after a >=0.02s step
    assert steps == [0, 1, 2, 3, 4]


def test_wall_clock_cadence_off_by_default(tmp_path, monkeypatch):
    """checkpoint_seconds defaults to DCCRG_CKPT_SECONDS (unset = 0 =
    step-count cadence only): a fast run saves on the step cadence."""
    monkeypatch.delenv("DCCRG_CKPT_SECONDS", raising=False)
    sup = _sup(tmp_path, "off", checkpoint_every=3)
    assert sup.runner.checkpoint_seconds == 0.0
    sup.run(6)
    assert sorted(s for s, _ in sup.store.list()) == [0, 3, 6]


def test_wall_clock_cadence_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("DCCRG_CKPT_SECONDS", "7.5")
    sup = _sup(tmp_path, "env")
    assert sup.runner.checkpoint_seconds == 7.5
    # an explicit kwarg beats the env
    sup2 = _sup(tmp_path, "env2", checkpoint_seconds=1.25)
    assert sup2.runner.checkpoint_seconds == 1.25


def test_wall_clock_cadence_never_saves_mid_step(tmp_path):
    """The monotonic clock is only consulted at step boundaries: a
    single long step with an expired cadence still yields exactly the
    boundary save, no mid-step one (pinned by the save count)."""
    calls = []

    def one_slow_step(grid, i):
        calls.append(i)
        time.sleep(0.05)

    sup = _sup(tmp_path, "mid", step_fn=one_slow_step,
               checkpoint_every=10**9, checkpoint_seconds=0.01)
    sup.run(1)
    assert calls == [0]
    assert sorted(s for s, _ in sup.store.list()) == [0, 1]


# -- per-step latency histogram ---------------------------------------

def test_latency_histogram_counts_every_step(tmp_path):
    sup = _sup(tmp_path, "lat")
    sup.run(5)
    buckets = sup.latency_histogram()
    assert sum(c for _lo, _hi, c in buckets) == 5
    # log-spaced edges: monotone, each bucket doubling
    los = [lo for lo, _hi, _c in buckets]
    his = [hi for _lo, hi, _c in buckets]
    assert all(a < b for a, b in zip(his, his[1:]))
    assert los[0] == 0.0 and los[1:] == his[:-1]


def test_latency_histogram_places_slow_step_right(tmp_path):
    def slow_step(grid, i):
        time.sleep(0.06)

    sup = _sup(tmp_path, "lat2", step_fn=slow_step)
    sup.run(2)
    mass = [(lo, hi, c) for lo, hi, c in sup.latency_histogram() if c]
    assert sum(c for _l, _h, c in mass) == 2
    for lo, hi, _c in mass:
        assert hi > 0.06 * 0.5  # nothing recorded implausibly fast
    assert sup._latency.quantile(0.5) >= 0.06
    assert sup._latency.max_seconds >= 0.06


def test_latency_summary_logged_on_step_timeout(tmp_path, caplog):
    """A wedged step logs the latency story so far before raising —
    the degradation trend is on record even though the run dies."""
    import logging

    g = _mk()
    _step_fn(g, 0)  # warm the compiled step: the deadline is tight
    plan = faults.FaultPlan(seed=4)
    plan.step_hang(step=2)
    sup = _sup(tmp_path, "wedge", grid=g, step_timeout=0.5)
    with caplog.at_level(logging.WARNING, logger="dccrg_tpu.supervise"):
        with plan, pytest.raises(StepTimeoutError) as ei:
            sup.run(5)
    assert ei.value.step == 2
    assert any("latency so far" in r.message for r in caplog.records)
    buckets = sup.latency_histogram()
    assert sum(c for _l, _h, c in buckets) == 3  # steps 0, 1 + the wedge
