"""VTK output and profiling utility tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu.grid import Grid
from dccrg_tpu.utils import PhaseTimer
from dccrg_tpu.utils.profiling import halo_bytes_per_update


def make_grid(length=(2, 2, 1), n_dev=2, max_lvl=0):
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("dev",))
    return (
        Grid(cell_data={"v": jnp.float32})
        .set_initial_length(length)
        .set_maximum_refinement_level(max_lvl)
        .initialize(mesh)
    )


def test_vtk_output(tmp_path):
    g = make_grid((2, 2, 1), max_lvl=1)
    g.refine_completely(1)
    g.stop_refining()
    cells = g.get_cells()
    g.set("v", cells, np.arange(len(cells), dtype=np.float32))
    fn = str(tmp_path / "out.vtk")
    g.write_vtk_file(fn, fields=["v"])
    text = open(fn).read()
    assert "UNSTRUCTURED_GRID" in text
    assert f"CELLS {len(cells)}" in text
    assert "SCALARS v double 1" in text
    # refined cell 1 is gone; its 8 children present as voxels
    assert f"POINTS {8 * len(cells)} float" in text


def test_dc_to_vtk_standalone(tmp_path):
    from dccrg_tpu.utils import dc_to_vtk

    g = make_grid((2, 2, 1), max_lvl=1)
    g.refine_completely(1)
    g.stop_refining()
    cells = g.get_cells()
    g.set("v", cells, np.arange(len(cells), dtype=np.float32))
    dc = str(tmp_path / "state.dc")
    g.save_grid_data(dc, header=b"hdr!")
    vtk = str(tmp_path / "state.vtk")
    written = dc_to_vtk(dc, vtk, fields={"v": ((), np.float32)}, header_size=4)
    np.testing.assert_array_equal(written, cells)
    text = open(vtk).read()
    assert "UNSTRUCTURED_GRID" in text
    assert "SCALARS v double 1" in text
    assert f"CELL_DATA {len(cells)}" in text


def test_phase_timer():
    t = PhaseTimer()
    with t.phase("solve"):
        sum(range(1000))
    with t.phase("solve"):
        pass
    rep = t.report()
    assert rep["solve"]["count"] == 2
    assert rep["solve"]["total"] >= 0


def test_halo_bytes_accounting():
    g = make_grid((8, 1, 1), n_dev=4)
    n = g.get_number_of_update_send_cells()
    assert halo_bytes_per_update(g) == n * 4  # one f32 field
    assert halo_bytes_per_update(g, fields=[]) == 0
