"""Tier-1 tests of the crash-safe distributed AMR commit
(dccrg_tpu/distamr.py): two faked in-process ranks (process-split
device masks over the 8 virtual CPU devices, one shared
:class:`~dccrg_tpu.coord.InMemoryKV`, one protocol thread per rank)
drive the real four-phase epoch-fenced protocol end to end.

What is pinned here:

- a fault-free two-rank commit installs the SAME structure the
  single-controller path produces from the merged request sets, and
  each rank's locally-owned payload matches it bitwise;
- an injected failure at EVERY named fault point
  (:data:`~dccrg_tpu.faults.DIST_AMR_FAULT_SITES`) aborts the round
  COLLECTIVELY — the victim by the injected error, the peer by the
  posted abort marker — with both ranks bitwise rolled back (plan,
  payload, request sets, fence) and the fault-free retry committing;
- a torn proposal record is convicted by its CRC frame, never parsed;
- a zombie proposer whose epoch fence advanced underneath it loses
  with a typed :class:`~dccrg_tpu.coord.StaleFenceError` and keeps
  serving the OLD plan bitwise;
- a peer death mid-round aborts the survivor typed
  (:class:`~dccrg_tpu.coord.PeerDeadError` through the membership
  lease view) and the retry RE-FORMS over the survivors and commits —
  the dead rank's requests are dropped, its grid stays bitwise
  pre-commit;
- ``stop_refining`` without a commit group is byte-for-byte the
  pre-refactor single-controller commit.

The REAL-process versions (actual ``kill -9`` mid-phase, a stalled
proposer fenced across OS processes) live in tests/mp_harness.py; the
random-schedule version is ``python -m dccrg_tpu.fuzz --dist-amr``.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from dccrg_tpu import amr, coord, distamr, faults, fuzz, txn
from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID, Grid

# jax dispatch is not thread-safe; the per-rank protocol threads
# serialize every device-touching call on one lock (the PlanBuildWorker
# / fuzz.dist_amr_case discipline)
JLOCK = threading.Lock()


def _mk(length=(8, 8, 4), max_lvl=1):
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length(length)
         .set_periodic(True, True, False)
         .set_maximum_refinement_level(max_lvl)
         .set_neighborhood_length(1)
         .initialize(partition="block"))
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(23)).astype(np.float32))
    return g


def _fake_split(g, rank):
    half = g.n_dev // 2
    devs = range(half) if rank == 0 else range(half, g.n_dev)
    g._proc_local_dev = np.array(
        [d in set(devs) for d in range(g.n_dev)], dtype=bool)
    g._ckpt_rank = rank
    return sorted(devs)


def _serialize_jax(g):
    ig, dg = g._install_plan, g._device_gather

    def install(plan, same_cells=None):
        with JLOCK:
            return ig(plan, same_cells=same_cells)

    def gather(name, dev, rows, cap=None):
        with JLOCK:
            return dg(name, dev, rows, cap=cap)

    g._install_plan = install
    g._device_gather = gather


def _pair(kv=None, timeout=60, membership=None):
    """Two faked ranks sharing one KV, distamr enabled; returns
    (kv, {rank: grid})."""
    kv = kv if kv is not None else coord.InMemoryKV()
    grids = {}
    with JLOCK:
        for rank in (0, 1):
            g = _mk()
            _fake_split(g, rank)
            _serialize_jax(g)
            g.enable_distributed_amr(kv=kv, rank=rank, n_ranks=2,
                                     timeout=timeout,
                                     membership=membership)
            grids[rank] = g
    return kv, grids


def _run_ranks(grids, fn, join_s=120):
    """fn(rank, grid) on one thread per rank; returns {rank: error}."""
    errs = {}

    def body(rank):
        try:
            fn(rank, grids[rank])
            errs[rank] = None
        except BaseException as e:  # noqa: BLE001 - asserted by caller
            errs[rank] = e

    ts = {r: threading.Thread(target=body, args=(r,)) for r in grids}
    for t in ts.values():
        t.start()
    for t in ts.values():
        t.join(join_s)
    assert all(not t.is_alive() for t in ts.values()), "rank wedged"
    return errs


def _digest(g):
    with JLOCK:
        return fuzz._dist_amr_digest(g)


def _local_reqs(g, rank, count=4, stride=3):
    """``count`` locally-owned level-0 cells of ``rank``, spread out."""
    half = g.n_dev // 2
    devs = range(half) if rank == 0 else range(half, g.n_dev)
    mine = g.plan.cells[np.isin(g.plan.owner, list(devs))]
    return [int(c) for c in mine[: count * stride : stride]]


def _merged_reference(reqs):
    """The single-controller commit of the MERGED request sets — what
    every rank's installed structure must equal bitwise."""
    ref = _mk()
    for r in sorted(reqs):
        for c in reqs[r]:
            ref.refine_completely(c)
    ref.stop_refining()
    ref.assign_children_from_parents(fields=["v"])
    ref.clear_refined_unrefined_data()
    return ref


def _assert_matches_reference(grids, ref):
    ref_cells = ref.plan.cells
    ref_owner = ref.plan.owner
    ref_v = ref.get("v", ref_cells)
    half = grids[0].n_dev // 2
    for rank, g in grids.items():
        np.testing.assert_array_equal(g.plan.cells, ref_cells,
                                      err_msg=f"rank {rank} cells")
        np.testing.assert_array_equal(g.plan.owner, ref_owner,
                                      err_msg=f"rank {rank} owner")
        # the faked split only materializes THIS rank's writes (the
        # foreign shards' writes happen in the other real process);
        # compare the locally-owned payload bitwise
        mine = np.isin(ref_owner, list(
            range(half) if rank == 0 else range(half, g.n_dev)))
        g._proc_local_dev = np.ones(g.n_dev, dtype=bool)
        np.testing.assert_array_equal(g.get("v", ref_cells[mine]),
                                      ref_v[mine])


def test_two_rank_commit_matches_single_controller():
    kv, grids = _pair()
    reqs = {r: _local_reqs(grids[0], r) for r in (0, 1)}
    ref = _merged_reference(reqs)

    def body(rank, g):
        for c in reqs[rank]:
            g.refine_completely(c)
        new = g.stop_refining()
        assert len(new) == 8 * len(set(reqs[0]) | set(reqs[1]))

    errs = _run_ranks(grids, body)
    assert not any(errs.values()), errs
    with JLOCK:
        for g in grids.values():
            g.assign_children_from_parents(fields=["v"])
            g.clear_refined_unrefined_data()
    assert grids[0]._amr_group.read_fence() == 1
    _assert_matches_reference(grids, ref)


@pytest.mark.parametrize("site,phase", faults.DIST_AMR_FAULT_SITES)
@pytest.mark.parametrize("victim", [0, 1])
def test_injected_abort_rolls_back_both_ranks_bitwise(site, phase,
                                                      victim):
    """An error at any named fault point aborts the round on EVERY
    rank — the victim typed by the injected fault, the peer fast-
    aborted by the posted marker — both bitwise pre-commit; the
    fault-free retry then commits the same merged structure."""
    kv, grids = _pair()
    reqs = {r: _local_reqs(grids[0], r) for r in (0, 1)}
    with JLOCK:
        for r, g in grids.items():
            for c in reqs[r]:
                g.refine_completely(c)
    before = {r: _digest(g) for r, g in grids.items()}

    plan = faults.FaultPlan().amr_error(site=site, phase=phase,
                                        rank=victim)
    with plan:
        errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    assert plan.fired(site) == 1, plan.log
    for r, e in errs.items():
        assert isinstance(e, txn.CrossRankAbortedError), (r, e)
    cause_v = errs[victim].__cause__
    cause_p = errs[1 - victim].__cause__
    assert isinstance(cause_v, faults.InjectedMutationError), cause_v
    assert isinstance(cause_p, coord.RemoteAbortError), cause_p
    assert cause_p.rank == victim
    for r, g in grids.items():
        assert _digest(g) == before[r], f"rank {r} not bitwise"

    # the epoch is collectively retryable: same requests, no fault
    ref = _merged_reference(reqs)
    errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    assert not any(errs.values()), errs
    with JLOCK:
        for g in grids.values():
            g.assign_children_from_parents(fields=["v"])
            g.clear_refined_unrefined_data()
    assert grids[0]._amr_group.read_fence() == 1
    _assert_matches_reference(grids, ref)


def test_torn_proposal_record_convicted_never_parsed():
    """A proposal whose sealed frame fails its CRC (the writer died
    mid-write) aborts the round for everyone; nobody acts on it."""
    kv, grids = _pair()
    reqs = {r: _local_reqs(grids[0], r) for r in (0, 1)}
    with JLOCK:
        for r, g in grids.items():
            for c in reqs[r]:
                g.refine_completely(c)
    before = {r: _digest(g) for r, g in grids.items()}

    plan = faults.FaultPlan().amr_torn_record(site="amr.propose",
                                              rank=0)
    with plan:
        errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    assert plan.fired("amr.propose.torn") == 1, plan.log
    for r, e in errs.items():
        assert isinstance(e, txn.CrossRankAbortedError), (r, e)
    # at least one rank convicted the frame itself; the other may have
    # been fast-aborted by the marker first — both are typed aborts
    causes = {type(e.__cause__) for e in errs.values()}
    assert coord.TornRecordError in causes, causes
    for r, g in grids.items():
        assert _digest(g) == before[r], f"rank {r} not bitwise"

    errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    assert not any(errs.values()), errs
    assert grids[0]._amr_group.read_fence() == 1


def test_zombie_proposer_loses_to_advanced_fence(monkeypatch):
    """A rank that stalls after reading the fence and wakes after the
    survivors committed a new epoch must LOSE: typed
    StaleFenceError, bitwise rollback, old plan still served."""
    kv, grids = _pair()
    g = grids[1]
    with JLOCK:
        for c in _local_reqs(g, 1):
            g.refine_completely(c)
    before = _digest(g)
    old_cells = g.plan.cells.copy()

    def probe(phase, rank):
        # the survivors re-formed and committed while this rank was
        # SIGSTOPped: the fence key moved on
        if phase == "propose":
            kv.set(g._amr_group.fence_key(), "1")

    monkeypatch.setattr(distamr, "_PHASE_PROBE", probe)
    with pytest.raises(txn.CrossRankAbortedError) as ei:
        g.stop_refining()
    assert isinstance(ei.value.__cause__, coord.StaleFenceError)
    assert _digest(g)[:-1] == before[:-1]  # all but the moved fence
    np.testing.assert_array_equal(g.plan.cells, old_cells)


class _StubMembership:
    """A lease view the test script directly: live until told dead."""

    lease_s = 1.0

    def __init__(self):
        self.live = {0, 1}

    def poll(self):
        pass

    def live_ranks(self):
        return set(self.live)

    def detect_dead_ranks(self):
        return {0, 1} - self.live


def test_peer_death_aborts_then_retry_reforms_over_survivors():
    """Rank 1 dies mid-propose (a kill -9: no abort marker). The
    survivor's barrier convicts it through the membership lease view
    (typed PeerDeadError), rolls back bitwise, and the RETRY re-forms
    the collective over the survivors alone: rank 1's requests are
    lost with it, rank 0 commits its own and the fence advances."""
    stub = _StubMembership()
    kv, grids = _pair(timeout=30, membership=stub)
    reqs = {r: _local_reqs(grids[0], r) for r in (0, 1)}
    with JLOCK:
        for r, g in grids.items():
            for c in reqs[r]:
                g.refine_completely(c)
    before = {r: _digest(g) for r, g in grids.items()}

    def probe(phase, rank):
        # by the time rank 0 proposes, the lease on rank 1 has lapsed
        # (the attempt's expected set was already formed with it in)
        if rank == 0 and phase == "propose":
            stub.live.discard(1)

    outcome = {}

    def body(rank, g):
        if rank == 1:
            g.stop_refining()  # raises InjectedRankDeath
            return
        try:
            g.stop_refining()
            outcome["first"] = "committed"
        except txn.CrossRankAbortedError as e:
            outcome["first"] = e
        outcome["mid"] = _digest(g)
        # the collective retry over the survivors ({0} alone)
        outcome["new"] = g.stop_refining()

    plan = (faults.FaultPlan()
            .rank_death(site="amr.propose", rank=1))
    old_probe = distamr._PHASE_PROBE
    distamr._PHASE_PROBE = probe
    try:
        with plan:
            errs = _run_ranks(grids, body)
    finally:
        distamr._PHASE_PROBE = old_probe
    assert isinstance(errs[1], faults.InjectedRankDeath), errs[1]
    assert errs[0] is None, errs[0]
    assert isinstance(outcome["first"], txn.CrossRankAbortedError)
    assert isinstance(outcome["first"].__cause__, coord.PeerDeadError)
    assert outcome["mid"] == before[0], "survivor not bitwise on abort"
    # the dead rank rolled back bitwise before its (injected) death
    assert _digest(grids[1])[:-1] == before[1][:-1]

    # the survivor-only commit == single-controller with ONLY rank 0's
    # requests (the dead rank's were never proposed)
    ref = _merged_reference({0: reqs[0]})
    g0 = grids[0]
    assert g0._amr_group.read_fence() == 1
    np.testing.assert_array_equal(g0.plan.cells, ref.plan.cells)
    np.testing.assert_array_equal(g0.plan.owner, ref.plan.owner)
    assert len(outcome["new"]) == 8 * len(reqs[0])


def test_slow_rank_at_commit_barrier_cannot_commit_alone():
    """The split-brain regression: rank 1 stalls just before the
    commit barrier until rank 0 has timed out, rolled back and posted
    the abort verdict + marker. Rank 1 then wakes into a barrier whose
    arrival keys are ALL present (monotonic ghosts of the aborted
    round) and must still LOSE — the abort verdict on the decision
    key vetoes completion — leaving both ranks bitwise pre-round; the
    collective retry then commits."""
    kv, grids = _pair(timeout=60)
    grids[0]._amr_group.timeout = 3  # only rank 0's commit wait
    reqs = {r: _local_reqs(grids[0], r) for r in (0, 1)}
    with JLOCK:
        for r, g in grids.items():
            for c in reqs[r]:
                g.refine_completely(c)
    before = {r: _digest(g) for r, g in grids.items()}
    abort_key = f"{grids[0]._amr_group.prefix}/abort/0#1"

    def probe(phase, rank):
        # rank 1 reaches the commit phase and stalls until rank 0 has
        # given up on it (timed out, rolled back, announced the abort)
        if rank == 1 and phase == "commit":
            deadline = time.monotonic() + 60
            while kv.get(abort_key) is None:
                assert time.monotonic() < deadline, "rank 0 never aborted"
                time.sleep(0.01)

    old_probe = distamr._PHASE_PROBE
    distamr._PHASE_PROBE = probe
    try:
        errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    finally:
        distamr._PHASE_PROBE = old_probe
    for r, e in errs.items():
        assert isinstance(e, txn.CrossRankAbortedError), (r, e)
    assert isinstance(errs[0].__cause__, coord.BarrierTimeoutError)
    # the waker: complete-looking barrier, but the round is decided
    assert isinstance(errs[1].__cause__, coord.RemoteAbortError)
    assert errs[1].__cause__.rank == 0
    for r, g in grids.items():
        assert _digest(g) == before[r], f"rank {r} not bitwise"
    assert grids[0]._amr_group.read_fence() == 0

    grids[0]._amr_group.timeout = 60
    ref = _merged_reference(reqs)
    errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    assert not any(errs.values()), errs
    with JLOCK:
        for g in grids.values():
            g.assign_children_from_parents(fields=["v"])
            g.clear_refined_unrefined_data()
    assert grids[0]._amr_group.read_fence() == 1
    _assert_matches_reference(grids, ref)


def test_commit_barrier_failure_rolls_forward_when_decided(monkeypatch):
    """2PC roll-forward: a rank whose commit barrier fails AFTER the
    round's verdict landed as COMMIT must install with the fleet —
    its abort bid loses the decision race and the recorded verdict
    overrules the local failure."""
    kv, grids = _pair()
    reqs = {r: _local_reqs(grids[0], r) for r in (0, 1)}
    with JLOCK:
        for r, g in grids.items():
            for c in reqs[r]:
                g.refine_completely(c)
    ref = _merged_reference(reqs)

    real = distamr._Attempt.barrier

    def wrapped(self, phase, value="1"):
        out = real(self, phase, value=value)
        if phase == "commit" and self.group.rank == 1:
            # wait for the fleet's verdict to land, then fail the
            # barrier locally — the narrow race the single decision
            # record exists to close
            deadline = time.monotonic() + 60
            while self.group.kv.get(self.decision_key()) is None:
                assert time.monotonic() < deadline, "no verdict landed"
                time.sleep(0.01)
            raise coord.BarrierTimeoutError(self.tag(phase), 0.0)
        return out

    monkeypatch.setattr(distamr._Attempt, "barrier", wrapped)
    errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    assert not any(errs.values()), errs  # BOTH ranks committed
    with JLOCK:
        for g in grids.values():
            g.assign_children_from_parents(fields=["v"])
            g.clear_refined_unrefined_data()
    assert grids[0]._amr_group.read_fence() == 1
    _assert_matches_reference(grids, ref)


def test_fence_advance_is_monotonic_and_zombie_proof():
    """The epoch fence can only move forward: a stalled rank's late
    re-publish of an old epoch (the blind-set regression) and a blind
    legacy write to the mirror key both leave the observed fence at
    the fleet's maximum."""
    kv, grids = _pair()
    group = grids[0]._amr_group
    assert group.read_fence() == 0
    assert group.advance_fence(1) == 1
    assert group.advance_fence(2) == 2
    # a zombie waking between decide and publish re-publishes its
    # stale target: the create-only epoch key cannot regress anything
    assert group.advance_fence(1) == 2
    assert group.read_fence() == 2
    # nor can a blind write to the mirror key drag the fence back
    kv.set(group.fence_key(), "1")
    assert group.read_fence() == 2
    # ...but raising the mirror (the zombie-fencing tests' knob, and
    # a dir_get-degraded service's only view) still counts
    kv.set(group.fence_key(), "5")
    assert group.read_fence() == 5


def test_committed_rounds_are_garbage_collected():
    """Round keys — barrier arrivals, abort markers, decision records,
    old epoch-fence keys — are deleted once the fence moves past their
    round, so the coordination KV stays bounded across adapt epochs."""
    kv, grids = _pair()
    prefix = grids[0]._amr_group.prefix
    # epoch 1: one aborted attempt, then the collective retry commits
    reqs = {r: _local_reqs(grids[0], r) for r in (0, 1)}
    with JLOCK:
        for r, g in grids.items():
            for c in reqs[r]:
                g.refine_completely(c)
    plan = faults.FaultPlan().amr_error(site="amr.resolve", rank=0)
    with plan:
        errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    assert all(isinstance(e, txn.CrossRankAbortedError)
               for e in errs.values()), errs
    errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    assert not any(errs.values()), errs
    with JLOCK:
        for g in grids.values():
            g.assign_children_from_parents(fields=["v"])
            g.clear_refined_unrefined_data()
    # round 0 just committed: its own keys must still be readable (a
    # slow peer may be mid-decision), so nothing is collected yet
    assert kv.dir_get(f"{prefix}/b/0#"), "round-0 keys collected early"
    assert kv.dir_get(f"{prefix}/abort/0#")
    # epoch 2: the commit at fence 1 sweeps everything of round 0
    with JLOCK:
        for r, g in grids.items():
            cells, owner = g.plan.cells, g.plan.owner
            lvl = g.mapping.get_refinement_level(cells)
            half = g.n_dev // 2
            devs = list(range(half) if r == 0 else range(half, g.n_dev))
            mine = cells[np.isin(owner, devs) & (lvl < 1)]
            for c in mine[:2]:
                g.refine_completely(int(c))
    errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    assert not any(errs.values()), errs
    assert grids[0]._amr_group.read_fence() == 2
    for sub in (f"{prefix}/b/0#", f"{prefix}/abort/0#",
                f"{prefix}/decision/0#"):
        assert not kv.dir_get(sub), (sub, kv.dir_get(sub))
    # the newest epoch keys survive — a fence read can never regress
    assert kv.dir_get(f"{prefix}/fence/")


def test_post_decision_install_failure_is_fatal_not_divergent(
        monkeypatch):
    """Once the verdict is COMMIT, a local install failure must not
    roll back into a diverged survivor: the rank terminates (stubbed
    here) so lease/reclaim absorbs it like the post-decision death it
    is."""
    kv, grids = _pair()
    reqs = {r: _local_reqs(grids[0], r) for r in (0, 1)}
    with JLOCK:
        for r, g in grids.items():
            for c in reqs[r]:
                g.refine_completely(c)

    died = []
    monkeypatch.setattr(distamr, "_FATAL_INSTALL", died.append)
    g1_install = grids[1]._install_plan

    def broken_install(plan, same_cells=None):
        raise RuntimeError("injected install fault")

    grids[1]._install_plan = broken_install
    errs = _run_ranks(grids, lambda _r, g: g.stop_refining())
    grids[1]._install_plan = g1_install
    assert errs[0] is None, errs[0]  # the healthy rank committed
    assert isinstance(errs[1], RuntimeError), errs[1]
    assert len(died) == 1 and isinstance(died[0], RuntimeError)
    # NOT rolled back: the broken rank did not resurrect the old plan
    # as a CrossRankAbortedError would have
    assert not isinstance(errs[1], txn.CrossRankAbortedError)
    assert grids[0]._amr_group.read_fence() == 1


def test_frontier_induced_refines_properties():
    """The proposal-integrity frontier: the one-wave coarser-neighbor
    set a rank's refines push across its ownership boundary."""
    g = _mk(max_lvl=2)
    offsets = g.neighborhoods[DEFAULT_NEIGHBORHOOD_ID]
    cells, owner = g.plan.cells, g.plan.owner

    # no requests -> no frontier; whole-grid ownership -> no frontier
    empty = amr.frontier_induced_refines(
        g.mapping, cells, owner, offsets, set(), [0],
        topology=g.topology)
    assert empty.dtype == np.uint64 and len(empty) == 0
    corner = int(cells[0])  # periodic corner: neighbors wrap far away
    assert len(amr.frontier_induced_refines(
        g.mapping, cells, owner, offsets, {corner},
        range(g.n_dev), topology=g.topology)) == 0

    # refine the corner cell, then request one of its children: every
    # coarser neighbor NOT owned by the child's rank is frontier
    g.refine_completely(corner)
    new = g.stop_refining()
    g.clear_refined_unrefined_data()
    cells, owner = g.plan.cells, g.plan.owner
    child = int(np.min(new))
    lvl = g.mapping.get_refinement_level(cells)
    f0 = amr.frontier_induced_refines(
        g.mapping, cells, owner, offsets, {child}, [0],
        topology=g.topology)
    assert len(f0), "corner child induces nothing across the boundary"
    assert np.array_equal(f0, np.sort(f0)) and f0.dtype == np.uint64
    pos = np.searchsorted(cells, f0)
    np.testing.assert_array_equal(cells[pos], f0)
    child_lvl = int(g.mapping.get_refinement_level(
        np.asarray([child], dtype=np.uint64))[0])
    assert (lvl[pos] < child_lvl).all(), "frontier must be coarser"
    assert not np.isin(owner[pos], [0]).any(), "frontier must be foreign"
    # shrinking the ownership view can only GROW the frontier
    f01 = amr.frontier_induced_refines(
        g.mapping, cells, owner, offsets, {child}, [0, 1],
        topology=g.topology)
    assert set(int(c) for c in f01) <= set(int(c) for c in f0)


def test_single_controller_path_is_unchanged():
    """Without a commit group, stop_refining IS the local commit."""
    a, b = _mk(), _mk()
    picks = [int(c) for c in a.plan.cells[:9:3]]
    for g in (a, b):
        for c in picks:
            g.refine_completely(c)
    ra = a.stop_refining()  # no group installed: routes local
    rb = b._stop_refining_local()
    np.testing.assert_array_equal(ra, rb)
    np.testing.assert_array_equal(a.plan.cells, b.plan.cells)
    np.testing.assert_array_equal(a.plan.owner, b.plan.owner)
    for g in (a, b):
        g.assign_children_from_parents(fields=["v"])
        g.clear_refined_unrefined_data()
    np.testing.assert_array_equal(a.get("v", a.plan.cells),
                                  b.get("v", b.plan.cells))
