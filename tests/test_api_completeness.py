"""Grid API surface added for reference parity: hierarchical
partitioning (dccrg.hpp:5629-5880), get_cells criteria filtering
(dccrg.hpp:661-753), collectives (dccrg_mpi_support.hpp), cross-schema
clone (dccrg.hpp:344-446), and extensible cache items
(dccrg.hpp:7404-7518 / tests/additional_cell_data)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu import Grid, comm
from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID
from dccrg_tpu.partition import partition_cells, partition_cells_hierarchical
from dccrg_tpu.mapping import Mapping


@pytest.fixture
def mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("dev",))


def make_grid(mesh, length=(4, 4, 4), max_lvl=0, hood=1):
    return (
        Grid(cell_data={"rho": np.float32})
        .set_initial_length(length)
        .set_maximum_refinement_level(max_lvl)
        .set_periodic(True, True, True)
        .set_neighborhood_length(hood)
        .initialize(mesh)
    )


# -- hierarchical partitioning ----------------------------------------

def test_hierarchical_partition_groups_devices():
    mapping = Mapping((8, 8, 8), 0)
    cells = np.arange(1, 513, dtype=np.uint64)
    owner = partition_cells_hierarchical(
        mapping, cells, 8,
        [{"processes": 4, "method": "block"}, {"processes": 1, "method": "morton"}],
    )
    # all 8 devices used, balanced to 64 cells each
    counts = np.bincount(owner, minlength=8)
    assert np.all(counts == 64)
    # level-0 split is in block (cell-id) order: first half of ids on
    # devices 0-3, second half on 4-7
    assert np.all(owner[:256] < 4) and np.all(owner[256:] >= 4)


def test_hierarchical_balance_load(mesh8):
    grid = make_grid(mesh8)
    grid.add_partitioning_level(4)
    grid.add_partitioning_option(0, "LB_METHOD", "block")
    grid.add_partitioning_level(1)
    assert grid.get_partitioning_option_value(0, "LB_METHOD") == "block"
    assert "LB_METHOD" in grid.get_partitioning_options(0)
    grid.balance_load()
    counts = np.bincount(grid.plan.owner, minlength=8)
    assert np.all(counts == 8)
    grid.remove_partitioning_option(0, "LB_METHOD")
    assert grid.get_partitioning_option_value(0, "LB_METHOD") is None
    grid.remove_partitioning_level(1)
    grid.balance_load()  # still valid with one level
    with pytest.raises(IndexError):
        grid.remove_partitioning_level(5)


def test_hierarchical_respects_weights_and_pins():
    mapping = Mapping((4, 4, 4), 0)
    cells = np.arange(1, 65, dtype=np.uint64)
    w = np.ones(64)
    w[:8] = 100.0  # heavy cells
    owner = partition_cells_hierarchical(
        mapping, cells, 4, [{"processes": 2, "method": "block"}],
        weights=w, pins={64: 0},
    )
    assert owner[-1] == 0  # pin wins
    # heavy cells spread: device 0's cell count far below 16
    assert np.sum(owner == 0) < 16


# -- get_cells criteria ------------------------------------------------

def test_get_cells_criteria_match_views(mesh8):
    grid = make_grid(mesh8)
    masks = grid.neighbor_type_masks()
    # every cell has of- and to-neighbors on a periodic uniform grid
    assert np.all(masks > 0)
    remote_bits = Grid.HAS_REMOTE_NEIGHBOR_OF | Grid.HAS_REMOTE_NEIGHBOR_TO
    outer = grid.get_cells(criteria=[remote_bits])
    np.testing.assert_array_equal(np.sort(outer), np.sort(grid.outer_cells().ids))
    exact_inner = grid.get_cells(
        criteria=[Grid.HAS_LOCAL_NEIGHBOR_BOTH], exact_match=True
    )
    np.testing.assert_array_equal(np.sort(exact_inner), np.sort(grid.inner_cells().ids))
    # unknown neighborhood -> empty (reference returns empty)
    assert len(grid.get_cells(criteria=[1], neighborhood_id=1234)) == 0
    assert len(grid.get_cells()) == 64


def test_is_inner_is_outer(mesh8):
    grid = make_grid(mesh8)
    for cid in grid.inner_cells().ids[:3]:
        assert grid.is_inner(int(cid)) and not grid.is_outer(int(cid))
    for cid in grid.outer_cells().ids[:3]:
        assert grid.is_outer(int(cid))


# -- collectives -------------------------------------------------------

def test_host_all_reduce_and_gather(mesh8):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    total = comm.host_all_reduce(mesh8, x)
    assert float(total[0]) == 28.0
    mx = comm.host_all_reduce(mesh8, x, op="max")
    assert float(mx[0]) == 7.0
    g = comm.host_all_gather(mesh8, x)
    assert g.shape == (8, 8, 1)
    for d in range(8):
        np.testing.assert_array_equal(g[d, :, 0], np.arange(8, dtype=np.float32))


def test_host_some_reduce_matches_mask(mesh8):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    mask = np.zeros((8, 8), dtype=bool)
    for q in range(8):
        mask[q, (q + 1) % 8] = True
        mask[q, (q - 1) % 8] = True
    out = comm.host_some_reduce(mesh8, x, mask)
    for q in range(8):
        want = x[(q + 1) % 8, 0] + x[(q - 1) % 8, 0]
        assert float(out[q, 0]) == want


def test_neighbor_devices_symmetry(mesh8):
    grid = make_grid(mesh8)
    peers = grid.neighbor_devices()
    assert peers.shape == (8, 8)
    # halo flows are symmetric on a symmetric stencil
    np.testing.assert_array_equal(peers, peers.T)
    assert not np.any(np.diag(peers))


# -- clone -------------------------------------------------------------

def test_clone_same_structure_new_schema(mesh8):
    grid = make_grid(mesh8, max_lvl=1)
    grid.refine_completely(int(grid.get_cells()[0]))
    grid.stop_refining()
    ids = grid.get_cells()
    grid.set("rho", ids, np.arange(len(ids), dtype=np.float32))

    other = grid.clone(cell_data={"a": np.float64, "b": ((3,), np.int32)})
    np.testing.assert_array_equal(other.plan.cells, grid.plan.cells)
    np.testing.assert_array_equal(other.plan.owner, grid.plan.owner)
    assert set(other.fields) == {"a", "b"}
    assert np.all(other.get("a", ids) == 0.0)
    # data independence: writing the clone leaves the original untouched
    other.set("a", ids[:4], np.ones(4))
    assert np.all(grid.get("rho", ids) == np.arange(len(ids), dtype=np.float32))


# -- extensible cache items -------------------------------------------

def test_cell_and_neighbor_items_recomputed(mesh8):
    grid = make_grid(mesh8, max_lvl=1)

    # Is_Local-style item (tests/advection/cell.hpp:153-173)
    grid.add_cell_data_item(
        "on_dev0", lambda g, ids: g.plan.owner[np.searchsorted(g.plan.cells, ids)] == 0
    )
    # Center-style neighbor item: offset magnitude per neighbor entry
    grid.add_neighbor_data_item(
        "dist", lambda g, src, nbr, off: np.abs(off).sum(axis=1)
    )
    assert grid.cell_data_item("on_dev0").sum() == np.sum(grid.plan.owner == 0)
    first = int(grid.get_cells()[0])
    d = grid.neighbor_data_item("dist", first)
    assert len(d) == len(grid.get_neighbors_of(first))

    n_before = len(grid.cell_data_item("on_dev0"))
    grid.refine_completely(first)
    grid.stop_refining()
    n_after = len(grid.cell_data_item("on_dev0"))
    assert n_after == n_before + 7  # recomputed for the new cell set
    grid.remove_cell_data_item("on_dev0")
    with pytest.raises(KeyError):
        grid.cell_data_item("on_dev0")


# -- round-3 API surface ----------------------------------------------

def test_round3_api_surface(mesh8, tmp_path):
    """Every round-3 addition is reachable through the public surface:
    restart-from-file, receiver-dependent transfer predicates, batched
    host writes, staged balancing, fused step loops, RCB, f64 Poisson,
    per-field transfer counters."""
    from dccrg_tpu.models.poisson import PoissonSolver, poisson_fields

    g = make_grid(mesh8, length=(4, 4, 2), max_lvl=1)
    cells = g.get_cells()
    # batched writes + fused steps
    g.set_many(cells, {"rho": cells.astype(np.float32)},
               preserve_ghosts=False)
    g.update_copies_of_remote_neighbors()

    def kernel(cell, nbr, offs, mask, *e):
        return {"rho": cell["rho"]}

    g.run_steps(kernel, ["rho"], ["rho"], 2)
    # transfer predicate + per-field counters
    g.set_transfer_predicate(
        "rho", lambda ids, s, r, h: np.ones(len(ids), dtype=bool))
    assert g.get_number_of_update_send_cells(field="rho") == \
        g.get_number_of_update_send_cells()
    g.set_transfer_predicate("rho", None)
    # staged balance
    g.initialize_balance_load()
    g.continue_balance_load(fields=["rho"])
    ids, vals = g.staged_balance_data("rho")
    g.finish_balance_load()
    # RCB method is a first-class LB method
    g.set_load_balancing_method("rcb")
    g.balance_load()
    # AMR commit + restart from nothing but the file
    g.refine_completely(int(g.get_cells()[0]))
    g.stop_refining()
    g.clear_refined_unrefined_data()
    fn = str(tmp_path / "r3.dc")
    g.save_grid_data(fn)
    g2, _ = Grid.from_file(fn, dict(g.fields), mesh=mesh8)
    np.testing.assert_array_equal(g2.plan.cells, g.plan.cells)
    # f64 Poisson parity mode constructs
    assert poisson_fields(np.float64)["solution"] == np.dtype(np.float64)


def test_parity_accessors(mesh8):
    """The reference's remaining introspection surface: balance-load
    movement, per-peer send lists, neighborhood offsets, pin requests,
    index-based existing-cell lookup."""
    from dccrg_tpu.types import ERROR_CELL

    g = make_grid(mesh8, length=(4, 4, 2), max_lvl=1)
    # balance movement accounting
    for c in g.get_cells()[:8]:
        g.pin(int(c), (g.get_process(int(c)) + 1) % 8)
    assert len(g.get_pin_requests()) == 8
    g.balance_load(use_zoltan=False)
    moved = g.get_cells_added_by_balance_load()
    assert len(moved) >= 8
    np.testing.assert_array_equal(
        moved, g.get_cells_removed_by_balance_load())
    per_dev = sum(len(g.get_cells_added_by_balance_load(d)) for d in range(8))
    assert per_dev == len(moved)
    # per-peer send lists match the counters
    sends = g.get_cells_to_send()
    assert sum(len(v) for v in sends.values()) == \
        g.get_number_of_update_send_cells()
    assert all(p != q for p, q in sends)
    # receive lists are derived from the RECEIVE tables (ghost rows)
    # independently; both sides must describe the same transfers
    recvs = g.get_cells_to_receive()
    assert set(recvs) == set(sends)
    for pq in sends:
        np.testing.assert_array_equal(np.sort(sends[pq]),
                                      np.sort(recvs[pq]))
    # neighborhood offsets
    offs = g.get_neighborhood_of()
    np.testing.assert_array_equal(-offs, g.get_neighborhood_to())
    assert len(offs) == 26
    # refine then look up by indices across levels
    g.refine_completely(1)
    g.stop_refining()
    c = g.get_existing_cell_from_indices((0, 0, 0))
    assert g.mapping.get_refinement_level(c) == 1
    c0 = g.get_existing_cell_from_indices((0, 0, 0),
                                          maximum_refinement_level=0)
    assert c0 == ERROR_CELL  # level-0 cell 1 was replaced by children
    assert g.get_comm_size() == 8
    assert g.get_number_of_cells() == len(g.get_cells())


def test_remote_boundary_cells_have_valid_ghost_data(mesh8):
    """Reference tests/proc_bdy_cells/test1.cpp: on a tiny refined
    grid with a wide (length-2) neighborhood, after balance + halo
    update every remote cell on a process boundary must hold valid
    data on the reading device, and the boundary views must be
    consistent with the neighbor relations."""
    g = (Grid(cell_data={"v": jnp.int32})
         .set_initial_length((3, 1, 1))
         .set_neighborhood_length(2)
         .set_maximum_refinement_level(1)
         .set_load_balancing_method("rcb")
         .initialize(mesh8))
    g.balance_load()
    g.refine_completely(3)
    g.stop_refining()
    g.balance_load()
    cells = g.plan.cells
    g.set("v", cells, cells.astype(np.int32))
    g.update_copies_of_remote_neighbors()

    remote = set(g.remote_cells().ids.tolist())
    # every ghost copy holds its cell's value, on every reader
    host = np.asarray(g.data["v"])
    L = g.plan.L
    for d in range(g.n_dev):
        ghosts = g.plan.ghost_ids[d]
        np.testing.assert_array_equal(host[d, L:L + len(ghosts)],
                                      ghosts.astype(np.int32))
        assert set(ghosts.tolist()) <= remote
    # and every remote neighbor of a local cell is in the remote view
    for cid in cells:
        for nbr, _off in g.get_neighbors_of(int(cid)):
            if nbr and g.get_process(int(nbr)) != g.get_process(int(cid)):
                assert nbr in remote
