"""Test configuration: run all tests on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on 8 virtual CPU devices (the same XLA partitioner runs on
both backends). Must set flags before jax is imported anywhere.

DCCRG_TEST_TPU=1 instead targets the real chip and runs ONLY
tests/test_pallas_kernel.py (the rest skip).
"""

import os

# DCCRG_TEST_TPU=1 runs the suite against the real TPU chip instead of
# the virtual CPU mesh (used for tests/test_pallas_kernel.py).
_USE_TPU = os.environ.get("DCCRG_TEST_TPU", "") == "1"

if not _USE_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
    os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

if not _USE_TPU:
    # The image's axon site hook pre-sets JAX_PLATFORMS=axon; the config
    # update overrides it reliably even if jax was touched earlier.
    jax.config.update("jax_platforms", "cpu")
    # the image may pre-set JAX_ENABLE_X64 before the setdefault above
    jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--dccrg-debug", action="store_true", default=False,
        help="set DCCRG_DEBUG=1 for the whole run: invariant verifiers "
             "at every structure rebuild plus transactional post-commit "
             "validation (the reference's -DDEBUG builds). The CI leg "
             "tests/ci_debug_leg.sh runs a tier-1 marker subset with it.",
    )


def pytest_configure(config):
    if config.getoption("--dccrg-debug"):
        os.environ["DCCRG_DEBUG"] = "1"


@pytest.fixture(autouse=True)
def _tpu_mode_scope(request):
    """DCCRG_TEST_TPU=1 exists to run the Pallas kernel tests on the
    real (single) chip; everything else is written for the 8-device
    virtual CPU mesh and skips rather than failing on mesh setup."""
    if _USE_TPU and not any(k in request.node.nodeid for k in (
            "test_pallas_kernel", "test_poisson_kernel",
            "test_bulk_executor")):
        pytest.skip("CPU-mesh test; run without DCCRG_TEST_TPU")
    yield


@pytest.fixture(scope="session")
def devices():
    import jax

    return jax.devices()


@pytest.fixture
def rng():
    return np.random.default_rng(42)
