"""Test configuration: run all tests on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on 8 virtual CPU devices (the same XLA partitioner runs on
both backends). Must set flags before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

# The image's axon site hook pre-sets JAX_PLATFORMS=axon; the config
# update overrides it reliably even if jax was touched earlier.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    import jax

    return jax.devices()


@pytest.fixture
def rng():
    return np.random.default_rng(42)
