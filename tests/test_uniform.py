"""Uniform (all-level-0) fast-path plan construction vs the generic
builder: same layout, semantically identical gather tables."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu import Grid
from dccrg_tpu import uniform as uniform_mod
from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def build_pair(monkeypatch, **kw):
    """Same grid via fast path and (forced) generic path."""
    fast = make_grid(**kw)
    monkeypatch.setattr(uniform_mod, "is_uniform", lambda cells, n0: False)
    slow = make_grid(**kw)
    return fast, slow


def make_grid(length=(6, 5, 4), periodic=(False, True, False), hood_len=1,
              n_dev=4, max_ref=1, partition="block", user_hood=None):
    g = (
        Grid(cell_data={"v": jnp.float32})
        .set_initial_length(length)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(hood_len)
        .initialize(mesh_of(n_dev), partition=partition)
    )
    if user_hood is not None:
        g.add_neighborhood(42, user_hood)
    return g


def row_sets(g, hid, table="of"):
    """Per-cell sets of (neighbor id, offset) derived from the gather
    tables — the padding-independent content."""
    plan = g.plan
    hood = plan.hoods[hid]
    if table == "of":
        rows, offs, mask = hood.nbr_rows, hood.nbr_offs, hood.nbr_mask
    else:
        rows, offs, mask = hood.to_rows, hood.to_offs, hood.to_mask
    out = {}
    for d in range(plan.n_dev):
        ids = np.concatenate([plan.local_ids[d], plan.ghost_ids[d]])
        for r, cid in enumerate(plan.local_ids[d]):
            entries = []
            for s in range(rows.shape[2]):
                if not mask[d, r, s]:
                    continue
                row = rows[d, r, s]
                nid = ids[row] if row < plan.L else ids[len(plan.local_ids[d]) + row - plan.L]
                entries.append((int(nid), tuple(int(x) for x in offs[d, r, s])))
            out[int(cid)] = sorted(entries)
    return out


CONFIGS = [
    dict(),
    dict(periodic=(True, True, True), length=(4, 4, 4)),
    dict(hood_len=0),
    dict(hood_len=2, length=(5, 5, 5), n_dev=2),
    dict(max_ref=0, partition="morton"),
    dict(n_dev=1),
    dict(user_hood=[[1, 0, 0], [0, -1, 0], [2, 1, 0]], hood_len=2),
]


@pytest.mark.parametrize("kw", CONFIGS)
def test_fast_path_matches_generic(monkeypatch, kw):
    fast, slow = build_pair(monkeypatch, **kw)
    pf, ps = fast.plan, slow.plan
    np.testing.assert_array_equal(pf.cells, ps.cells)
    np.testing.assert_array_equal(pf.owner, ps.owner)
    assert pf.L == ps.L and pf.R == ps.R
    np.testing.assert_array_equal(pf.n_local, ps.n_local)
    np.testing.assert_array_equal(pf.row_of_pos, ps.row_of_pos)
    for d in range(pf.n_dev):
        np.testing.assert_array_equal(pf.local_ids[d], ps.local_ids[d])
        np.testing.assert_array_equal(pf.ghost_ids[d], ps.ghost_ids[d])
    for hid in fast.neighborhoods:
        hf, hs = pf.hoods[hid], ps.hoods[hid]
        assert row_sets(fast, hid, "of") == row_sets(slow, hid, "of")
        assert row_sets(fast, hid, "to") == row_sets(slow, hid, "to")
        np.testing.assert_array_equal(hf.send_rows, hs.send_rows)
        np.testing.assert_array_equal(hf.recv_rows, hs.recv_rows)
        if hid == DEFAULT_NEIGHBORHOOD_ID:
            np.testing.assert_array_equal(hf.n_inner, hs.n_inner)


def test_fast_path_exchange_and_queries(monkeypatch):
    """Halo exchange + lazy query surface on the fast path."""
    g = make_grid(length=(8, 2, 1), max_ref=0, n_dev=4)
    ids = np.asarray(g.plan.cells, dtype=np.uint64)
    g.set("v", ids, ids.astype(np.float32))
    g.update_copies_of_remote_neighbors()
    host = np.asarray(g.data["v"])
    for d in range(4):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host[d, g.plan.L + r] == float(cid)
    # the lazy lists resolve on demand and match the generic engine
    nbrs = g.get_neighbors_of(1)
    assert len(nbrs) > 0


def test_amr_falls_back_to_generic():
    """Refining leaves uniform territory; the rebuilt plan must carry
    the refined structure."""
    g = make_grid(length=(4, 4, 1), max_ref=1, n_dev=2)
    g.refine_completely(1)
    created = g.stop_refining()
    assert len(created) == 8
    assert len(g.plan.cells) == 4 * 4 + 8 - 1


@pytest.mark.parametrize("periodic", [(False, True, False), (True, True, True)])
def test_lazy_single_cell_queries_match_stream(periodic):
    """Single-cell neighbor queries on the fast path answer closed-form
    (without forcing the lazy entry stream) and must equal the
    stream-backed answers entry for entry."""
    if os.environ.get("DCCRG_DEBUG") == "1":
        pytest.skip("DEBUG verifiers force every lazy entry stream by "
                    "design (verify_neighbors recomputes and compares)")
    g = make_grid(length=(5, 4, 3), periodic=periodic, n_dev=2,
                  user_hood=[[1, 0, 0], [0, -1, 0], [1, 1, 1]])
    for hid in (DEFAULT_NEIGHBORHOOD_ID, 42):
        hood = g.plan.hoods[hid]
        assert callable(hood._lists), "fast path should keep lists lazy"
        lazy_of = {int(c): g.get_neighbors_of(c, hid) for c in g.plan.cells}
        lazy_to = {int(c): g.get_neighbors_to(c, hid) for c in g.plan.cells}
        lazy_rof = {int(c): g.get_remote_neighbors_of(c, hid).tolist()
                    for c in g.plan.cells}
        assert callable(hood._lists), "queries must not force the stream"
        hood.lists  # materialize
        for c in g.plan.cells:
            assert lazy_of[int(c)] == g.get_neighbors_of(c, hid), int(c)
            assert lazy_to[int(c)] == g.get_neighbors_to(c, hid), int(c)
            assert lazy_rof[int(c)] == g.get_remote_neighbors_of(c, hid).tolist()


def test_single_device_closed_form_plan():
    """Single-device uniform plans are table-free: nothing dense is
    materialized unless a host introspection path forces it, and the
    closed-form stencil (rolls + synthesized mask) matches a forced
    table build entry for entry."""
    g = make_grid(length=(6, 5, 4), periodic=(True, False, True),
                  n_dev=1, max_ref=1)
    hood = g.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    assert hood.closed_form is not None
    assert callable(hood._nbr_rows), "tables must stay lazy"
    rp = hood.roll_plan(g.plan.L)
    assert rp is not None  # precomputed arithmetically
    # stencil: neighbor sum through the closed-form path
    cells = g.plan.cells
    rng = np.random.default_rng(0)
    vals = rng.random(len(cells)).astype(np.float32)
    g.set("v", cells, vals)

    def kernel(cell, nbr, offs, mask, *e):
        return {"v": jnp.sum(jnp.where(mask, nbr["v"], 0.0), axis=1)
                + 0.5 * cell["v"]}

    g.apply_stencil(kernel, ["v"], ["v"])
    got = g.get("v", cells).copy()
    g.run_steps(kernel, ["v"], ["v"], 2)
    got2 = g.get("v", cells).copy()
    assert callable(hood._nbr_rows), "stencils must not force tables"

    # forced-table reference: materialize + run the table gather
    g.set("v", cells, vals)
    hood.closed_form = None
    _ = hood.nbr_rows  # force materialization
    hood._roll_plan = ()  # disable rolls -> plain table gather
    hood._dev.clear()
    g._program_cache.clear()
    g.apply_stencil(kernel, ["v"], ["v"])
    want = g.get("v", cells).copy()
    g.run_steps(kernel, ["v"], ["v"], 2)
    want2 = g.get("v", cells).copy()
    np.testing.assert_allclose(got, want, rtol=1e-6)
    np.testing.assert_allclose(got2, want2, rtol=1e-6)


def test_closed_form_tiny_periodic_dim():
    """|offset| >= dim on a periodic dimension: every row wraps, the
    fixup set must cover the whole band without emitting aliased
    negative rows (regression for the closed-form band construction)."""
    g = make_grid(length=(5, 1, 5), periodic=(True, True, True),
                  hood_len=2, n_dev=1, max_ref=0)
    hood = g.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    assert hood.closed_form is not None
    rp = hood.roll_plan(g.plan.L)
    assert (rp[1] >= 0).all(), "negative (aliased) fixup rows"
    cells = g.plan.cells
    rng = np.random.default_rng(1)
    vals = rng.random(len(cells)).astype(np.float32)
    g.set("v", cells, vals)

    def kernel(cell, nbr, offs, mask, *e):
        return {"v": jnp.sum(jnp.where(mask, nbr["v"], 0.0), axis=1)}

    g.apply_stencil(kernel, ["v"], ["v"])
    got = g.get("v", cells).copy()
    # forced-table reference
    g.set("v", cells, vals)
    hood.closed_form = None
    _ = hood.nbr_rows
    hood._roll_plan = ()
    hood._dev.clear()
    g._program_cache.clear()
    g.apply_stencil(kernel, ["v"], ["v"])
    np.testing.assert_allclose(got, g.get("v", cells), rtol=1e-6)


class TestMultiDeviceClosedForm:
    """The contiguous-partition closed-form plan (VERDICT r3 item 4):
    no dense [n_dev, L, S] table at build time, identical layout and
    stencil results to the dense path."""

    def _mk(self, monkeypatch, force_tables):
        import jax
        from jax.sharding import Mesh
        from dccrg_tpu.grid import Grid

        if force_tables:
            monkeypatch.setenv("DCCRG_FORCE_TABLES", "1")
        else:
            monkeypatch.delenv("DCCRG_FORCE_TABLES", raising=False)
        return (Grid(cell_data={"v": jnp.float32})
                .set_initial_length((8, 6, 4))
                .set_periodic(True, False, True)
                .set_neighborhood_length(1)
                .initialize(Mesh(np.array(jax.devices()[:8]), ("dev",)),
                            partition="block"))

    def test_closed_form_activates_and_layout_matches(self, monkeypatch):
        from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID

        ga = self._mk(monkeypatch, False)
        gb = self._mk(monkeypatch, True)
        ha = ga.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
        hb = gb.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
        assert ha.closed_form is not None and ha.closed_form.get("multi")
        assert hb.closed_form is None
        assert callable(ha._nbr_rows)  # no dense table materialized
        for d in range(8):
            np.testing.assert_array_equal(ga.plan.local_ids[d],
                                          gb.plan.local_ids[d])
            np.testing.assert_array_equal(ga.plan.ghost_ids[d],
                                          gb.plan.ghost_ids[d])
        np.testing.assert_array_equal(ga.plan.row_of_pos, gb.plan.row_of_pos)
        # the lazily materialized tables agree with the dense build
        np.testing.assert_array_equal(np.asarray(ha.nbr_rows),
                                      np.asarray(hb.nbr_rows))
        np.testing.assert_array_equal(np.asarray(ha.nbr_mask),
                                      np.asarray(hb.nbr_mask))

    def test_stencil_results_match_dense(self, monkeypatch):
        def run(force):
            g = self._mk(monkeypatch, force)
            cells = g.plan.cells
            g.set("v", cells, (cells % np.uint64(13)).astype(np.float32))
            g.update_copies_of_remote_neighbors()

            def kern(cell, nbr, offs, mask, ):
                return {"v": cell["v"] + jnp.sum(
                    jnp.where(mask, nbr["v"], 0.0), axis=1)}

            for _ in range(3):
                g.update_copies_of_remote_neighbors()
                g.apply_stencil(kern, ["v"], ["v"])
            return g.get("v", cells)

        np.testing.assert_allclose(run(False), run(True), rtol=1e-6)

    def test_run_steps_matches_dense(self, monkeypatch):
        def run(force):
            g = self._mk(monkeypatch, force)
            cells = g.plan.cells
            g.set("v", cells, (cells % np.uint64(7)).astype(np.float32))
            g.update_copies_of_remote_neighbors()

            def kern(cell, nbr, offs, mask):
                return {"v": 0.5 * cell["v"] + 0.1 * jnp.sum(
                    jnp.where(mask, nbr["v"], 0.0), axis=1)}

            g.run_steps(kern, ["v"], ["v"], 4)
            return g.get("v", cells)

        np.testing.assert_allclose(run(False), run(True), rtol=1e-6)


def test_closed_form_weighted_contiguous_partition(monkeypatch):
    """Weighted cuts keep owner contiguous in id order, so the
    closed-form multi-device plan must activate and agree with the
    dense build under skewed weights too."""
    import jax
    from jax.sharding import Mesh
    from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID, Grid

    def mk(force):
        if force:
            monkeypatch.setenv("DCCRG_FORCE_TABLES", "1")
        else:
            monkeypatch.delenv("DCCRG_FORCE_TABLES", raising=False)
        g = (Grid(cell_data={"v": jnp.float32})
             .set_initial_length((6, 6, 6))
             .set_periodic(True, True, True)
             .initialize(Mesh(np.array(jax.devices()[:4]), ("dev",)),
                         partition="block"))
        cells = g.plan.cells
        # skewed weights -> uneven but still contiguous cuts
        for c in cells[: len(cells) // 3]:
            g.set_cell_weight(c, 5.0)
        g.set_load_balancing_method("block")
        g.balance_load()
        return g

    ga, gb = mk(False), mk(True)
    ha = ga.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    assert ha.closed_form is not None and ha.closed_form.get("multi")
    assert np.asarray([len(x) for x in ga.plan.local_ids]).std() > 0
    for d in range(4):
        np.testing.assert_array_equal(ga.plan.local_ids[d], gb.plan.local_ids[d])
    hb = gb.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    np.testing.assert_array_equal(np.asarray(ha.nbr_rows),
                                  np.asarray(hb.nbr_rows))
    np.testing.assert_array_equal(np.asarray(ha.nbr_mask),
                                  np.asarray(hb.nbr_mask))
