"""AMR advection tests — the reference advection test's full loop
(solve + adapt + balance) on the general grid path."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dccrg_tpu.models.advection import AdvectionSolver
from dccrg_tpu.models.advection_amr import AmrAdvection


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def test_uniform_matches_dense_solver():
    """max_refinement_level=0: the general-grid gather kernel must
    reproduce the dense fast path step for step (same math,
    solve.hpp:44-266)."""
    n = 16
    amr = AmrAdvection((n, n, 1), max_refinement_level=0, mesh=mesh_of(2))
    dense_mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1), ("x", "y", "z"))
    dense = AdvectionSolver(n=n, nz=1, mesh=dense_mesh)
    dt = 0.4 * amr.max_time_step()
    for _ in range(3):
        amr.step(dt)
        dense.step(dt)
    cells = amr.grid.get_cells()
    got = amr.grid.get("density", cells).astype(np.float64)
    # dense layout is [x, y, z]; cell ids are 1 + x + y*n on a 2-D grid
    want = np.asarray(dense.grid.arrays["rho"])
    idx = (cells - 1).astype(np.int64)
    x, y = idx % n, (idx // n) % n
    # atol covers the boundary cells: the dense path wraps periodically,
    # the general grid has walls — both see ~0 density there
    np.testing.assert_allclose(got, want[x, y, 0], rtol=2e-5, atol=1e-5)


def test_mass_conserved_uniform():
    amr = AmrAdvection((16, 16, 1), max_refinement_level=0, mesh=mesh_of(4))
    m0 = amr.total_mass()
    for _ in range(5):
        amr.step()
    assert amr.total_mass() == pytest.approx(m0, rel=1e-5)


def test_adapt_refines_hump_edge():
    """The relative-difference criterion refines where density varies
    (the hump edge) and leaves the far field coarse (adapter.hpp:47)."""
    amr = AmrAdvection((16, 16, 1), max_refinement_level=1, mesh=mesh_of(4))
    created, removed = amr.adapt()
    assert len(created) > 0
    cells = amr.grid.get_cells()
    lvl = amr.grid.mapping.get_refinement_level(cells)
    assert lvl.max() == 1
    # refined cells sit near the hump edge (distance from (0.25, 0.5))
    centers = amr.grid.geometry.get_center(cells[lvl == 1])
    r = np.sqrt((centers[:, 0] - 0.25) ** 2 + (centers[:, 1] - 0.5) ** 2)
    assert r.min() < 0.2
    # far corner stays coarse
    far = amr.grid.geometry.get_center(cells[lvl == 0])
    assert len(far) > 0


def test_fused_loop_matches_stepwise():
    """run_fused(n) (one device program, exchange+flux+apply inside
    lax.fori_loop) must reproduce n individual step() calls — on a
    refined grid so the fused exchange covers AMR gather tables."""
    a = AmrAdvection((8, 8, 1), max_refinement_level=1, mesh=mesh_of(4))
    b = AmrAdvection((8, 8, 1), max_refinement_level=1, mesh=mesh_of(4))
    a.adapt()
    b.adapt()
    dt = 0.4 * a.max_time_step()
    for _ in range(5):
        a.step(dt)
    b.run_fused(5, dt)
    cells = a.grid.get_cells()
    np.testing.assert_allclose(
        a.grid.get("density", cells), b.grid.get("density", cells),
        rtol=1e-6, atol=1e-7,
    )
    assert a.time == pytest.approx(b.time)


def test_run_fused_segments_match_run_stepwise():
    """run(fused=True) with adaptation events must match fused=False."""
    a = AmrAdvection((8, 8, 1), max_refinement_level=1, mesh=mesh_of(2))
    b = AmrAdvection((8, 8, 1), max_refinement_level=1, mesh=mesh_of(2))
    a.run(6, adapt_n=3, fused=False)
    b.run(6, adapt_n=3, fused=True)
    ca, cb = a.grid.get_cells(), b.grid.get_cells()
    np.testing.assert_array_equal(ca, cb)
    np.testing.assert_allclose(
        a.grid.get("density", ca), b.grid.get("density", cb),
        rtol=1e-5, atol=1e-6,
    )


def test_mass_conserved_across_adaptation():
    """Refinement copies, unrefinement averages — both preserve total
    mass exactly (children have 1/8 the volume)."""
    amr = AmrAdvection((8, 8, 1), max_refinement_level=2, mesh=mesh_of(2))
    m0 = amr.total_mass()
    amr.adapt()
    assert amr.total_mass() == pytest.approx(m0, rel=1e-5)
    for _ in range(3):
        amr.step()
    amr.adapt()
    m_now = amr.total_mass()
    # stepping conserves mass; adaptation conserves mass
    assert m_now == pytest.approx(m0, rel=1e-4)


def test_full_loop_with_balance():
    """The reference main loop: solve + adapt every 2 + balance every 4
    (2d.cpp:321-442); mass conserved, density stays bounded."""
    amr = AmrAdvection((8, 8, 1), max_refinement_level=1, mesh=mesh_of(4))
    m0 = amr.total_mass()
    amr.run(8, adapt_n=2, balance_n=4)
    assert amr.total_mass() == pytest.approx(m0, rel=1e-4)
    cells = amr.grid.get_cells()
    rho = amr.grid.get("density", cells)
    assert rho.min() >= -1e-5
    assert rho.max() <= 0.55


def test_long_loop_deep_refinement():
    """Longer run at max level 2: repeated adapts must never commit a
    structure violating the 2:1 invariant (regression: the unrefine
    check must use the parent's window, not the children's — a finer
    cell 2 child-lengths away blocks unrefinement)."""
    amr = AmrAdvection((12, 12, 1), max_refinement_level=2, mesh=mesh_of(8))
    m0 = amr.total_mass()
    amr.run(12, adapt_n=3, balance_n=6)  # raises StructureError on violation
    assert amr.total_mass() == pytest.approx(m0, rel=1e-4)
    lvl = amr.grid.mapping.get_refinement_level(amr.grid.get_cells())
    assert lvl.max() == 2


def test_device_count_invariance_with_amr():
    """Same physics on 1 vs 8 devices, including through adaptation
    (tests/README:5-6: any process count must agree)."""
    out = []
    for n in (1, 8):
        amr = AmrAdvection((8, 8, 1), max_refinement_level=1, mesh=mesh_of(n))
        dt = 0.4 * amr.max_time_step()
        for i in range(4):
            amr.step(dt)
            if i % 2 == 1:
                amr.adapt()
        cells = amr.grid.get_cells()
        out.append((cells, amr.grid.get("density", cells).astype(np.float64)))
    np.testing.assert_array_equal(out[0][0], out[1][0])
    np.testing.assert_allclose(out[0][1], out[1][1], rtol=1e-5, atol=1e-6)


def test_adapt_epochs_reuse_compiled_programs():
    """Bucketed capacities + shape-keyed program caches: once warmed,
    further adapt epochs with stable buckets must reuse every compiled
    exchange/stencil/step-loop program instead of recompiling (on TPU a
    recompile is tens of seconds per epoch)."""
    amr = AmrAdvection((32, 32, 1), max_refinement_level=1, mesh=mesh_of(4))
    g = amr.grid
    # one full warm cycle: fused steps + one adapt epoch. dt=0 keeps
    # the density static so every later adapt reproduces the same
    # refinement pattern — drift-free, isolating the machinery.
    amr.run_fused(4, dt=0.0)
    amr.adapt()
    amr.run_fused(4, dt=0.0)
    amr.adapt()
    caps_before = dict(g._cap_memo)
    keys_before = set(g._program_cache)
    for _ in range(3):  # three more structure epochs
        amr.run_fused(4, dt=0.0)
        amr.adapt()
    assert dict(g._cap_memo) == caps_before, "capacities flapped"
    new = set(g._program_cache) - keys_before
    assert not new, f"programs recompiled: {[k[0] for k in new]}"
