"""The distributed-coordination layer (dccrg_tpu/coord.py): timeout-
guarded barriers, guarded jax.distributed bring-up, cross-rank trip
consensus, and the cached host-collective programs they ride on.

Everything here runs on the single-controller test mesh — the injected
``barrier_hang`` exercises the REAL watchdog machinery (the sync is
replaced by a sleep inside the watchdog thread, so the timeout path
itself is what trips). The genuinely multi-process versions of these
scenarios run in tests/mp_harness.py.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dccrg_tpu import comm, coord, faults
from dccrg_tpu.grid import Grid

pytestmark = pytest.mark.faultinject


def _mk():
    return (Grid(cell_data={"v": jnp.float32})
            .set_initial_length((4, 4, 4))
            .set_neighborhood_length(1)
            .initialize(partition="block"))


# -- barrier ----------------------------------------------------------

def test_barrier_is_noop_on_single_controller():
    t0 = time.monotonic()
    coord.barrier("nothing-to-sync", timeout=0.05)
    assert time.monotonic() - t0 < 0.05


def test_barrier_timeout_raises_typed_error_within_bound():
    plan = faults.FaultPlan()
    plan.barrier_hang()
    t0 = time.monotonic()
    with plan, pytest.raises(coord.BarrierTimeoutError) as ei:
        coord.barrier("ckpt-commit", timeout=0.3)
    assert time.monotonic() - t0 < 3.0
    assert ei.value.tag == "ckpt-commit"
    assert "ckpt-commit" in str(ei.value)
    assert plan.fired("coord.barrier_hang") == 1


def test_barrier_hang_matches_tag():
    """A hang pinned to one tag must not fire on other barriers."""
    plan = faults.FaultPlan()
    plan.barrier_hang(tag="only-this-one")
    with plan:
        coord.barrier("some-other", timeout=0.2)  # unaffected
        with pytest.raises(coord.BarrierTimeoutError):
            coord.barrier("only-this-one", timeout=0.2)


def test_barrier_survives_slow_but_alive_peer():
    """A finite hang below the timeout models a slow peer: the barrier
    completes instead of raising."""
    plan = faults.FaultPlan()
    plan.barrier_hang(hang_s=0.05)
    with plan:
        coord.barrier("slow-peer", timeout=5.0)


def test_barrier_timeout_env_knob(monkeypatch):
    monkeypatch.setenv("DCCRG_BARRIER_TIMEOUT", "0.2")
    assert coord.barrier_timeout() == 0.2
    plan = faults.FaultPlan()
    plan.barrier_hang()
    with plan, pytest.raises(coord.BarrierTimeoutError) as ei:
        coord.barrier("env-bound")  # no explicit timeout: env applies
    assert ei.value.timeout == 0.2
    monkeypatch.setenv("DCCRG_BARRIER_TIMEOUT", "not-a-number")
    assert coord.barrier_timeout() == coord.DEFAULT_BARRIER_TIMEOUT


def test_injected_transient_barrier_error_propagates():
    """Transient coordination errors (io kind at coord.barrier) are
    raised to the caller — barriers are NOT silently retried (a rank
    re-entering a barrier alone would desynchronize the sequence)."""
    plan = faults.FaultPlan()
    plan.io_error(site="coord.barrier")
    with plan, pytest.raises(faults.InjectedIOError):
        coord.barrier("flaky")


# -- guarded distributed init -----------------------------------------

def test_distributed_init_retries_transient_failures(monkeypatch):
    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    plan = faults.FaultPlan()
    plan.io_error(site="coord.init", times=2)
    with plan:
        coord.distributed_init("127.0.0.1:1234", 2, 0,
                               retries=3, backoff=0.0)
    assert len(calls) == 1  # two injected failures, then success
    assert plan.fired("coord.init") == 2


def test_distributed_init_exhausts_to_typed_error(monkeypatch):
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: (_ for _ in ()).throw(
                            RuntimeError("coordinator unreachable")))
    with pytest.raises(coord.DistributedInitError,
                       match="coordinator unreachable"):
        coord.distributed_init("127.0.0.1:1234", 2, 0,
                               retries=1, backoff=0.0)


# -- trip consensus ---------------------------------------------------

def test_trip_consensus_single_controller_passthrough():
    g = _mk()
    assert not g._multiproc
    assert coord.trip_consensus(g, 0) == 0
    assert coord.trip_consensus(g, 2) == 2


def test_trip_consensus_runs_the_collective_under_a_faked_split():
    """On a multi-process grid the consensus is a real device
    all-reduce (max) with this rank's code on its local device rows —
    on a faked split the result is the local code (no second process
    to disagree), but the compiled path is the one real meshes run."""
    g = _mk()
    g._proc_local_dev = np.array([d < g.n_dev // 2
                                  for d in range(g.n_dev)], dtype=bool)
    assert g._multiproc
    assert coord.trip_consensus(g, 0) == 0
    assert coord.trip_consensus(g, 3) == 3


def test_host_collective_programs_are_cached():
    """The satellite fix for comm._mesh_map: repeated host collectives
    over the same mesh reuse ONE compiled callable (the consensus and
    CRC-gather reductions run every step / every checkpoint)."""
    g = _mk()
    x = np.arange(g.n_dev, dtype=np.int32)
    r1 = comm.host_all_reduce(g.mesh, x, "max")
    n_after_first = len(comm._MESH_PROGRAMS)
    r2 = comm.host_all_reduce(g.mesh, x + 1, "max")
    assert len(comm._MESH_PROGRAMS) == n_after_first
    assert int(r1) == g.n_dev - 1 and int(r2) == g.n_dev
    # distinct ops get distinct programs; repeats of each are cached
    comm.host_all_reduce(g.mesh, x, "sum")
    n_after_sum = len(comm._MESH_PROGRAMS)
    comm.host_all_reduce(g.mesh, x, "sum")
    assert len(comm._MESH_PROGRAMS) == n_after_sum
    g2 = _mk()  # same mesh object -> same cache entries
    comm.host_all_reduce(g2.mesh, x, "sum")
    assert len(comm._MESH_PROGRAMS) == n_after_sum


def test_crc_gather_dtype_survives_x64_off():
    """The two-phase commit ships CRC32s through host_all_gather as
    uint32 ON PURPOSE: with jax_enable_x64 off (JAX's default — the
    library never flips it; only the test harnesses do) 64-bit dtypes
    are silently canonicalized to 32 bits inside the device put, which
    would wrap any CRC >= 2^31 and make healthy ranks look dead at
    commit time. Pin that uint32 rows — including values >= 2^31 —
    round-trip exactly with x64 disabled."""
    g = _mk()
    rows = np.full((g.n_dev, 3), 0, dtype=np.uint32)
    rows[:, 0] = np.uint32(0xFFFFFFFF)   # max CRC32
    rows[:, 1] = np.uint32(0x90000000)   # the sign-bit wrap case
    rows[:, 2] = np.arange(g.n_dev, dtype=np.uint32)
    jax.config.update("jax_enable_x64", False)
    try:
        full = comm.host_all_gather(g.mesh, rows)[0]
    finally:
        jax.config.update("jax_enable_x64", True)
    assert full.dtype == np.uint32
    np.testing.assert_array_equal(full, rows)


def test_host_some_reduce_still_correct_with_sharded_mask():
    """The cache rewrite moved the peer mask from a baked-in closure to
    a sharded argument; results must be unchanged."""
    g = _mk()
    n = g.n_dev
    rng = np.random.default_rng(5)
    x = rng.random((n, 3)).astype(np.float32)
    mask = rng.random((n, n)) < 0.5
    got = comm.host_some_reduce(g.mesh, x, mask)
    want = np.stack([mask[q].astype(np.float32) @ x for q in range(n)])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_runner_fatal_peer_trip_raises_in_sync(tmp_path, monkeypatch):
    """A FATAL consensus code (a peer hit a non-recoverable error)
    makes this rank raise instead of rolling back — the alternative is
    hanging forever in the dead peer's abandoned collectives."""
    from dccrg_tpu import resilience
    from dccrg_tpu.resilience import (ResilienceExhaustedError,
                                      ResilientRunner)

    g = _mk()
    g.set("v", g.plan.cells, np.ones(len(g.plan.cells), np.float32))

    def fake_consensus(grid, code):
        return resilience._TRIP_FATAL if runner.step == 2 else int(code)

    monkeypatch.setattr(coord, "trip_consensus", fake_consensus)
    runner = ResilientRunner(
        g, lambda grid, i: None, str(tmp_path / "f.dc"),
        check_every=100, checkpoint_every=100, backoff=0.0,
        diagnostics_dir=str(tmp_path))
    with pytest.raises(ResilienceExhaustedError, match="peer rank"):
        runner.run(5)
    assert runner.step == 2  # stopped where the peer died


def test_runner_broadcasts_fatal_before_reraising(tmp_path, monkeypatch):
    """A non-recoverable local error still propagates unchanged, but
    only AFTER a fatal trip code was offered to the peers (so they
    unblock and raise too rather than hang in the consensus reduce)."""
    from dccrg_tpu import resilience
    from dccrg_tpu.resilience import ResilientRunner

    g = _mk()
    sent = []
    monkeypatch.setattr(coord, "trip_consensus",
                        lambda grid, code: sent.append(code) or int(code))

    def step_fn(grid, i):
        if i == 1:
            raise ValueError("boom")

    runner = ResilientRunner(
        g, step_fn, str(tmp_path / "b.dc"),
        check_every=100, checkpoint_every=100, backoff=0.0,
        diagnostics_dir=str(tmp_path))
    with pytest.raises(ValueError, match="boom"):
        runner.run(5)
    assert resilience._TRIP_FATAL in sent


def test_runner_rolls_back_on_remote_rank_trip(tmp_path, monkeypatch):
    """Distributed trip consensus in ResilientRunner: a trip reported
    by ANOTHER rank (consensus code > 0 while this rank saw nothing)
    must roll this rank back too — that is what keeps all ranks on the
    same checkpoint instead of deadlocked in a half-entered barrier."""
    from dccrg_tpu.resilience import ResilientRunner

    g = _mk()
    cells = g.plan.cells
    g.set("v", cells, (cells % np.uint64(7)).astype(np.float32))

    def step_fn(grid, i):
        grid.run_steps(lambda c, n, o, m: {"v": c["v"] * np.float32(1.5)},
                       ["v"], ["v"], 1)

    remote_trips = []

    def fake_consensus(grid, code):
        if runner.step == 3 and not remote_trips:
            remote_trips.append(runner.step)
            return 2  # a peer rank tripped; this rank saw code == 0
        return int(code)

    # ResilientRunner.run does `from . import coord` lazily, so
    # patching the coord module itself intercepts its calls
    monkeypatch.setattr(coord, "trip_consensus", fake_consensus)
    runner = ResilientRunner(g, step_fn, str(tmp_path / "c.dc"),
                             check_every=100, checkpoint_every=2,
                             backoff=0.0, diagnostics_dir=str(tmp_path))
    runner.run(5)
    assert remote_trips == [3]
    assert runner.rollbacks == 1
    assert runner.step == 5
    assert runner.trips[0]["fields"].get("remote_rank_trip") == []
    # the rolled-back rank reconverges bitwise with an undisturbed run
    g2 = _mk()
    g2.set("v", cells, (cells % np.uint64(7)).astype(np.float32))
    r2 = ResilientRunner(g2, step_fn, str(tmp_path / "c2.dc"),
                         check_every=100, checkpoint_every=2,
                         backoff=0.0, diagnostics_dir=str(tmp_path))
    r2.run(5)
    assert (np.asarray(g.get("v", cells)).tobytes()
            == np.asarray(g2.get("v", cells)).tobytes())
