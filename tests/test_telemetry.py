"""Telemetry subsystem: registry semantics, span tracing (including
the zero-allocation no-op contract on the step path), JSONL trace
round-trips and cross-rank merging, Prometheus exposition, latency-SLO
fleet admission (deterministic under a fake clock) and the
strictly-best-effort exporter contract (a failing telemetry write can
NEVER trip or roll back the run it observes)."""

import json
import time
import tracemalloc

import numpy as np
import pytest

import jax.numpy as jnp

from dccrg_tpu import Grid, faults, resilience, supervise, telemetry
from dccrg_tpu.fleet import FleetJob, run_solo
from dccrg_tpu.scheduler import FleetScheduler, SLOPolicy

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts with tracing off, an empty ring and a fresh
    registry, and leaves the process the same way (the registry is
    process-global by design)."""
    telemetry.configure(trace=False)
    telemetry.clear_trace()
    telemetry.registry().reset()
    telemetry._METRICS_STATE["last"] = None
    yield
    telemetry.configure(trace=False)
    telemetry.clear_trace()
    telemetry.registry().reset()
    telemetry._METRICS_STATE["last"] = None


# -- registry ---------------------------------------------------------

def test_counters_gauges_histograms():
    telemetry.inc("dccrg_trips_total", kind="numerics")
    telemetry.inc("dccrg_trips_total", kind="numerics")
    telemetry.inc("dccrg_trips_total", kind="corrupt")
    telemetry.set_gauge("dccrg_arena_pool_hits", 7)
    telemetry.observe("dccrg_step_seconds", 0.01)
    telemetry.observe("dccrg_step_seconds", 0.02)
    reg = telemetry.registry()
    assert reg.counter_value("dccrg_trips_total", kind="numerics") == 2
    assert reg.counter_value("dccrg_trips_total", kind="corrupt") == 1
    assert reg.counter_total("dccrg_trips_total") == 3
    h = reg.histogram("dccrg_step_seconds")
    assert h.total == 2 and abs(h.sum_seconds - 0.03) < 1e-9
    assert h.quantile(0.5) >= 0.01


def test_histogram_is_the_one_implementation():
    """Satellite pin: supervise.LatencyHistogram IS the telemetry
    histogram type, with the historical API intact."""
    assert supervise.LatencyHistogram is telemetry.LogHistogram
    h = supervise.LatencyHistogram()
    assert h.BASE == 1e-4 and h.N_BUCKETS == 30
    h.record(0.05)
    assert h.total == 1 and h.max_seconds == 0.05
    assert len(h.buckets()) == 30 and len(h.counts) == 30
    assert h.quantile(0.5) >= 0.05
    assert "p50" in h.summary()


def test_dump_prometheus_exposition():
    telemetry.inc("dccrg_saves_total", kind="keyframe")
    telemetry.observe("dccrg_fleet_quantum_seconds", 0.004, job="a")
    text = telemetry.dump_prometheus()
    assert "# TYPE dccrg_saves_total counter" in text
    assert 'dccrg_saves_total{kind="keyframe"} 1' in text
    assert "# TYPE dccrg_fleet_quantum_seconds histogram" in text
    assert 'le="+Inf"' in text
    assert 'dccrg_fleet_quantum_seconds_count{job="a"} 1' in text
    # bucket counts are cumulative and end at the total
    lines = [ln for ln in text.splitlines()
             if ln.startswith("dccrg_fleet_quantum_seconds_bucket")]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in lines]
    assert counts == sorted(counts) and counts[-1] == 1
    # label values are user strings (job names): escaped, not trusted
    telemetry.inc("dccrg_fleet_trips_total", job='we"ird\\name')
    assert 'job="we\\"ird\\\\name"' in telemetry.dump_prometheus()


# -- span tracer ------------------------------------------------------

def test_span_nesting_depth_and_parent():
    telemetry.configure(trace=True)
    with telemetry.span("outer"):
        with telemetry.span("inner", {"k": 3}):
            pass
    evs = telemetry.events()
    byname = {e["name"]: e for e in evs}
    assert byname["inner"]["depth"] == 1
    assert byname["inner"]["parent"] == "outer"
    assert byname["inner"]["k"] == 3
    assert byname["outer"]["depth"] == 0 and "parent" not in byname["outer"]
    assert all(e["dur"] >= 0 for e in evs)


def test_ambient_tags_scope():
    telemetry.configure(trace=True)
    with telemetry.tags(job="j42"):
        with telemetry.span("ckpt.save"):
            pass
    with telemetry.span("ckpt.save"):
        pass
    evs = [e for e in telemetry.events() if e["name"] == "ckpt.save"]
    assert evs[0]["job"] == "j42" and "job" not in evs[1]


def test_trace_ring_is_bounded_and_drops_are_counted():
    telemetry.configure(trace=True, ring=32)
    for i in range(100):
        with telemetry.span("s"):
            pass
    assert len(telemetry.events()) == 32
    # the 68 evicted events are accounted, not silently forgotten
    assert telemetry.registry().counter_value(
        "dccrg_trace_dropped_total") == 68
    telemetry.configure(ring=telemetry.trace_ring_default())


def test_noop_mode_is_singleton_and_zero_allocation():
    """DCCRG_TRACE off: span() returns ONE shared no-op object — no
    event dict, no ring append, no per-call allocation on the step
    path."""
    assert not telemetry.trace_enabled()
    assert telemetry.span("grid.step") is telemetry.span("fleet.quantum")
    with telemetry.span("warmup"):
        pass
    tracemalloc.start()
    tracemalloc.reset_peak()
    c0, _ = tracemalloc.get_traced_memory()
    for _ in range(5000):
        with telemetry.span("grid.step"):
            pass
    c1, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # a per-call allocation would retain/peak at >= 5000 x object
    # size; the sub-kB residue is tracemalloc bookkeeping noise
    assert c1 - c0 < 512, "no-op spans retained allocations"
    assert peak - c0 < 4096, "no-op spans allocated per call"
    assert telemetry.events() == []


def test_record_span_and_traced_decorator():
    telemetry.configure(trace=True)
    telemetry.record_span("hybrid.classification", 0.125, {"n": 2})

    @telemetry.traced("fn.x", counter="dccrg_fn_x_total")
    def f(a):
        return a + 1

    assert f(1) == 2
    telemetry.configure(trace=False)
    assert f(2) == 3  # untraced call still counts
    evs = telemetry.events()
    assert [e["name"] for e in evs] == ["hybrid.classification", "fn.x"]
    assert evs[0]["dur"] == 0.125
    assert telemetry.registry().counter_value("dccrg_fn_x_total") == 2


# -- JSONL export + merge ---------------------------------------------

def test_jsonl_roundtrip_and_flush_clears_ring(tmp_path):
    telemetry.configure(trace=True)
    with telemetry.span("a", {"job": "x"}):
        pass
    with telemetry.span("b"):
        pass
    p = tmp_path / "trace.jsonl"
    n = telemetry.flush_trace(str(p))
    assert n == 2 and telemetry.events() == []
    evs = telemetry.read_trace(str(p))
    assert [e["name"] for e in evs] == ["a", "b"]
    assert evs[0]["job"] == "x"
    assert all(set(e) >= {"name", "ts", "dur", "rank", "depth"}
               for e in evs)
    # appending a second flush extends the same file
    with telemetry.span("c"):
        pass
    assert telemetry.flush_trace(str(p)) == 1
    assert [e["name"] for e in telemetry.read_trace(str(p))] == \
        ["a", "b", "c"]


def test_merge_traces_orders_across_ranks(tmp_path):
    r0 = tmp_path / "r0.jsonl"
    r1 = tmp_path / "r1.jsonl"
    r0.write_text("".join(json.dumps(e) + "\n" for e in [
        {"name": "grid.step", "ts": 1.0, "dur": 0.5, "rank": 0,
         "depth": 0},
        {"name": "ckpt.save", "ts": 3.0, "dur": 0.2, "rank": 0,
         "depth": 0}]))
    r1.write_text("".join(json.dumps(e) + "\n" for e in [
        {"name": "grid.step", "ts": 2.0, "dur": 0.5, "rank": 1,
         "depth": 0}]) + "{torn line")
    evs = telemetry.merge_traces([str(r0), str(r1)])
    assert [(e["ts"], e["rank"]) for e in evs] == \
        [(1.0, 0), (2.0, 1), (3.0, 0)]
    stats = telemetry.span_stats(evs)
    assert stats["grid.step"]["count"] == 2
    assert abs(stats["grid.step"]["total_s"] - 1.0) < 1e-9


def test_cli_merge_and_summary(tmp_path, capsys):
    from dccrg_tpu.telemetry import _main

    p = tmp_path / "t.jsonl"
    p.write_text(json.dumps({"name": "s", "ts": 1.0, "dur": 0.1,
                             "rank": 0, "depth": 0}) + "\n")
    assert _main(["merge", str(p)]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[0])["name"] == "s"
    assert _main(["summary", str(p)]) == 0
    summ = json.loads(capsys.readouterr().out)
    assert summ["events"] == 1 and summ["ranks"] == [0]
    assert summ["spans"]["s"]["count"] == 1


# -- metrics file export ----------------------------------------------

def test_metrics_file_export(tmp_path, monkeypatch):
    telemetry.inc("dccrg_trips_total", kind="numerics")
    p = tmp_path / "metrics.prom"
    monkeypatch.setenv("DCCRG_METRICS_FILE", str(p))
    assert telemetry.maybe_export_metrics(now=0.0)
    assert "dccrg_trips_total" in p.read_text()
    # inside the min interval: no rewrite
    telemetry.inc("dccrg_trips_total", kind="numerics")
    assert not telemetry.maybe_export_metrics(now=1.0)
    # past it: the fresh value lands
    assert telemetry.maybe_export_metrics(
        now=1.0 + telemetry.metrics_every_default())
    assert 'dccrg_trips_total{kind="numerics"} 2' in p.read_text()


# -- best-effort exporters: fault injection ---------------------------

def test_export_failure_is_swallowed_and_counted(tmp_path):
    telemetry.configure(trace=True)
    with telemetry.span("s"):
        pass
    plan = faults.FaultPlan(seed=0)
    plan.telemetry_io_error(times=1)
    with plan:
        assert telemetry.flush_trace(str(tmp_path / "t.jsonl")) == 0
    assert plan.fired("telemetry.export") == 1
    assert telemetry.registry().counter_value(
        "dccrg_telemetry_export_errors_total") == 1
    # the ring was still cleared: a dead sink must not grow memory
    assert telemetry.events() == []


def _mk_grid(seed=0):
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((8, 8, 4))
         .set_periodic(True, True, False)
         .set_maximum_refinement_level(0)
         .set_neighborhood_length(1)
         .set_load_balancing_method("block")
         .initialize())
    cells = g.plan.cells
    g.set("v", cells, ((cells.astype(np.float64) * (seed + 7) % 31) / 31)
          .astype(np.float32))
    g.update_copies_of_remote_neighbors()
    return g


def _kernel(c, nbr, offs, mask):
    return {"v": jnp.float32(0.5) * c["v"] + jnp.float32(0.125) * jnp.sum(
        jnp.where(mask, nbr["v"], jnp.float32(0)), axis=1)}


def test_exporter_faults_never_trip_a_run(tmp_path, monkeypatch):
    """The satellite pin: EVERY telemetry write failing (trace file
    AND metrics file) must leave the supervised run with zero trips
    and zero rollbacks — telemetry is strictly best-effort."""
    monkeypatch.setenv("DCCRG_TRACE_FILE", str(tmp_path / "t.jsonl"))
    monkeypatch.setenv("DCCRG_METRICS_FILE", str(tmp_path / "m.prom"))
    monkeypatch.setenv("DCCRG_METRICS_EVERY", "0")
    telemetry.configure(trace=True)
    telemetry._METRICS_STATE["last"] = None

    def step_fn(grid, _i):
        grid.run_steps(_kernel, ["v"], ["v"], 1)
        telemetry.flush_trace()

    sup = supervise.SupervisedRunner(
        _mk_grid(), step_fn, str(tmp_path / "ckpt"), check_every=2,
        checkpoint_every=3, backoff=0.0)
    plan = faults.FaultPlan(seed=1)
    plan.telemetry_io_error(times=faults.EVERY)
    with plan:
        sup.run(7)
    assert sup.step == 7
    assert sup.trips == [] and sup.rollbacks == 0
    assert plan.fired("telemetry.export") > 0
    assert telemetry.registry().counter_value(
        "dccrg_telemetry_export_errors_total") > 0
    assert not (tmp_path / "t.jsonl").exists()


# -- instrumented boundaries ------------------------------------------

def test_solo_run_records_distinct_boundary_spans(tmp_path):
    """A single-grid run shows its phases as distinct spans: step
    dispatch, halo exchange (real on the 8-device CPU mesh), AMR
    adapt + plan recommit, checkpoint save/load — and the registry
    carries the matching counters."""
    telemetry.configure(trace=True)
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((8, 8, 4))
         .set_periodic(True, True, False)
         .set_maximum_refinement_level(1)
         .set_neighborhood_length(1)
         .initialize())
    cells = g.plan.cells
    g.set("v", cells, np.linspace(0.0, 1.0, len(cells),
                                  dtype=np.float32))
    g.update_copies_of_remote_neighbors()
    g.run_steps(_kernel, ["v"], ["v"], 2)
    g.refine_completely(cells[0])
    g.stop_refining()
    path = str(tmp_path / "a.dc")
    resilience.save_checkpoint(g, path)
    names = {e["name"] for e in telemetry.events()}
    assert {"grid.step", "grid.exchange", "grid.adapt",
            "grid.recommit", "ckpt.save"} <= names
    reg = telemetry.registry()
    assert reg.counter_value("dccrg_saves_total", kind="keyframe") == 1
    assert "dccrg_saves_total" in telemetry.dump_prometheus()


# -- SLO policy: deterministic under a fake clock ---------------------

def _slo_jobs():
    """Three same-bucket jobs: A outranks B outranks C by priority; C
    alone carries a (tight) completion SLO."""
    a = FleetJob("slo_a", length=(8, 8, 8), n_steps=16, priority=2,
                 seed=1, checkpoint_every=100)
    b = FleetJob("slo_b", length=(8, 8, 8), n_steps=16, priority=1,
                 seed=2, checkpoint_every=100)
    c = FleetJob("slo_c", length=(8, 8, 8), n_steps=16, priority=0,
                 seed=3, checkpoint_every=100, slo_ms=1000.0)
    return a, b, c


def test_slo_policy_ewma_projection_and_slack():
    clk = {"t": 0.0}
    pol = SLOPolicy(quantum=8, alpha=0.5, clock=lambda: clk["t"])
    a, _b, c = _slo_jobs()
    key = c.bucket_key()
    assert pol.quantum_latency(key) is None
    assert pol.projected_completion_s(c) == 0.0  # no data, no reorder
    pol.observe(key, 2.0)
    pol.observe(key, 4.0)
    assert pol.quantum_latency(key) == pytest.approx(3.0)
    # 16 steps at quantum 8 = 2 quanta -> 6 s projected
    assert pol.projected_completion_s(c) == pytest.approx(6.0)
    c.slo_t0 = 0.0
    clk["t"] = 0.25
    # slack = 1.0 - 0.25 - 6.0
    assert pol.slack_s(c) == pytest.approx(-5.25)
    assert pol.slack_s(a) is None  # best-effort job
    # violated SLO sorts into category 0, ahead of any priority
    assert pol.admission_key(c, 99) < pol.admission_key(a, 0)
    # without violation the baseline (-priority, seq) order holds
    clk["t"] = 0.0
    pol.reset_key(key)
    assert pol.admission_key(a, 0) < pol.admission_key(c, 99)


def test_slo_admission_reorders_vs_priority_baseline(tmp_path):
    """THE acceptance pin: identical job mixes, identical capacity
    pressure (2 slots for 3 jobs) — the priority-only baseline admits
    A+B and queues the SLO job C; with a measured quantum-latency
    EWMA projecting C past its deadline, the SLO policy admits C
    FIRST, displacing the lowest-priority best-effort job.
    Deterministic: fake clock, hand-fed latency observations, no
    stepping."""
    # baseline: no latency data -> byte-identical to priority order
    base = FleetScheduler(str(tmp_path / "base"), _slo_jobs(),
                          max_batch=2, quantum=8,
                          slo_policy=SLOPolicy(quantum=8,
                                               clock=lambda: 0.0))
    base._admit_pending()
    assert {j.name: j.status for j in base._by_name.values()} == {
        "slo_a": "running", "slo_b": "running", "slo_c": "queued"}

    # SLO run: the measured EWMA (10 s/quantum) projects C's 2
    # remaining quanta far past its 1 s deadline -> C admits first,
    # then A by priority; B waits
    jobs = _slo_jobs()
    pol = SLOPolicy(quantum=8, clock=lambda: 0.0)
    pol.observe(jobs[2].bucket_key(), 10.0)
    slo = FleetScheduler(str(tmp_path / "slo"), jobs, max_batch=2,
                         quantum=8, slo_policy=pol)
    slo._admit_pending()
    assert {j.name: j.status for j in slo._by_name.values()} == {
        "slo_a": "running", "slo_b": "queued", "slo_c": "running"}


def test_slo_shed_requeues_to_smaller_bucket(tmp_path):
    """A bucket whose measured quantum latency blows its tightest
    admitted SLO sheds its best-effort cohabitants (keyframed +
    requeued) and rebuilds at half capacity with the survivors
    migrated bit-exactly; the fleet then completes with every digest
    still equal to the solo baseline."""
    jobs = [FleetJob(f"shed{i}", length=(8, 8, 8), n_steps=16,
                     priority=i, seed=i, checkpoint_every=4,
                     params=(0.01,),  # stable dt for the 26-nbr kernel
                     slo_ms=(100.0 if i == 3 else None))
            for i in range(4)]
    solo = {j.name: run_solo(FleetJob(
        j.name, length=(8, 8, 8), n_steps=16, seed=j.seed,
        params=(0.01,)))
        for j in jobs}
    pol = SLOPolicy(quantum=8, clock=lambda: 0.0)
    sched = FleetScheduler(str(tmp_path), jobs, max_batch=8,
                           quantum=8, slo_policy=pol)
    sched._admit_pending()
    (batch,) = [b for bs in sched.buckets.values() for b in bs]
    cap0 = batch.capacity
    assert len(batch.jobs) == 4
    # hand-fed latency: 10 s/quantum blows shed3's 100 ms budget
    pol.observe(batch.key, 10.0)
    pre = {j.name: batch.digest(s) for s, j in batch.jobs}
    sched._shed_for_slo(batch)
    shed = [j for j in jobs if j.status == "queued"]
    assert len(shed) == 2 and all(j.slo_ms is None for j in shed)
    assert all(j.requeues == 1 for j in shed)
    (small,) = [b for bs in sched.buckets.values() for b in bs]
    assert small is not batch and small.capacity < cap0
    # survivors migrated bit-exactly; the SLO job survived the shed
    names = {j.name for _s, j in small.jobs}
    assert "shed3" in names
    for s, j in small.jobs:
        assert small.digest(s) == pre[j.name]
    assert telemetry.registry().counter_total(
        "dccrg_fleet_slo_sheds_total") == 2
    # the EWMA reset: the smaller bucket is measured fresh
    assert pol.quantum_latency(batch.key) is None
    # and the whole fleet still converges bitwise to the solo runs
    report = sched.run()
    assert all(r["status"] == "done" for r in report.values())
    assert {n: r["digest"] for n, r in report.items()} == solo
    assert report["shed3"]["slo_ms"] == 100.0
    assert report["shed3"]["slo_met"] is True  # fake clock: 0 elapsed


def test_priority_only_baseline_unchanged_without_slo(tmp_path):
    """No SLO jobs -> the admission pass is the exact priority-FIFO
    baseline and the shed pass never fires, however bad the measured
    latency (nothing to violate)."""
    jobs = [FleetJob(f"pb{i}", length=(8, 8, 8), n_steps=8,
                     priority=i % 3, seed=i, checkpoint_every=100)
            for i in range(5)]
    pol = SLOPolicy(quantum=8, clock=lambda: 0.0)
    pol.observe(jobs[0].bucket_key(), 1e6)
    sched = FleetScheduler(str(tmp_path), jobs, max_batch=3,
                           quantum=8, slo_policy=pol)
    sched._admit_pending()
    running = sorted(j.name for j in jobs if j.status == "running")
    # priorities 2,2 then 1 admit first (FIFO within a priority)
    assert running == ["pb1", "pb2", "pb4"]
    for bs in sched.buckets.values():
        for b in bs:
            assert pol.shed_victims(b.key, b.jobs) == []


# -- the fleet acceptance: trace coverage + exposition ----------------

def test_fleet_trace_covers_step_wall_clock(tmp_path):
    """Acceptance pin: one fleet run with tracing on produces a trace
    whose depth-0 spans account for >= 95% of the measured serving
    wall-clock, with admission / quantum dispatch / checkpoint saves
    visible as distinct (and job-tagged) spans, and dump_prometheus
    exposing the trips/rollbacks/audits/saves counters."""
    telemetry.configure(trace=True,
                        ring=max(telemetry.trace_ring_default(), 1 << 16))
    jobs = [FleetJob(f"cov{i}", length=(12, 12, 12), n_steps=12,
                     priority=i % 2, seed=i, checkpoint_every=4,
                     params=(0.01,))
            for i in range(4)]
    sched = FleetScheduler(str(tmp_path), jobs, quantum=4)
    t0 = time.perf_counter()
    report = sched.run()
    wall = time.perf_counter() - t0
    assert all(r["status"] == "done" for r in report.values())
    evs = telemetry.events()
    names = {e["name"] for e in evs}
    assert {"fleet.admit", "fleet.quantum", "ckpt.save"} <= names
    # per-job checkpoint saves carry the owning job's tag
    assert any(e.get("job", "").startswith("cov")
               for e in evs if e["name"] == "ckpt.save")
    cov = telemetry.root_coverage(evs, wall)
    assert cov >= 0.95, f"spans cover only {cov:.1%} of {wall:.3f}s"
    # the same run exports as a JSONL trace file, one event per span
    trace = tmp_path / "fleet_trace.jsonl"
    n = telemetry.flush_trace(str(trace))
    assert n == len(evs)
    assert len(telemetry.read_trace(str(trace))) == n
    text = telemetry.dump_prometheus()
    for metric in ("dccrg_saves_total",
                   "dccrg_fleet_quantum_seconds",
                   "dccrg_fleet_admissions_total",
                   "dccrg_integrity_checks_total"):
        assert metric in text, metric
    reg = telemetry.registry()
    assert reg.counter_total("dccrg_fleet_admissions_total") == 4
    h = reg.histogram("dccrg_fleet_quantum_seconds", job="cov0")
    assert h is not None and h.total >= 3  # 12 steps / quantum 4


def test_fleet_trip_and_rollback_counters(tmp_path):
    """An injected NaN trip surfaces in the registry: the trips and
    rollbacks counters (the fleet CLI summary's source) count the
    victim's recovery."""
    jobs = [FleetJob(f"ctr{i}", length=(8, 8, 8), n_steps=12, seed=i,
                     params=(0.01,), checkpoint_every=4)
            for i in range(3)]
    plan = faults.FaultPlan(seed=3)
    plan.nan_poison("rho", step=6, job="ctr1")
    sched = FleetScheduler(str(tmp_path), jobs, quantum=4)
    with plan:
        report = sched.run()
    assert all(r["status"] == "done" for r in report.values())
    reg = telemetry.registry()
    assert reg.counter_value("dccrg_fleet_trips_total", job="ctr1",
                             kind="nan") == 1
    assert reg.counter_value("dccrg_fleet_rollbacks_total",
                             job="ctr1") == 1
    assert reg.counter_total("dccrg_fleet_trips_total", job="ctr0") == 0
    assert report["ctr1"]["rollbacks"] == 1
    text = telemetry.dump_prometheus()
    assert "dccrg_fleet_trips_total" in text
    assert "dccrg_fleet_rollbacks_total" in text
