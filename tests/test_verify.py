"""DEBUG verifier checks (reference dccrg.hpp:12454-13036).

Healthy grids — uniform, refined, rebalanced — must pass ``verify_all``;
corrupted derived state must be caught by the matching verifier.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from dccrg_tpu import Grid, VerificationError, verify_all
from dccrg_tpu import verify as V
from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID


@pytest.fixture
def mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("dev",))


def make_grid(mesh, length=(4, 4, 2), max_lvl=0, hood=1, periodic=True):
    return (
        Grid(cell_data={"rho": np.float32})
        .set_initial_length(length)
        .set_maximum_refinement_level(max_lvl)
        .set_periodic(periodic, periodic, periodic)
        .set_neighborhood_length(hood)
        .initialize(mesh)
    )


def test_healthy_uniform_grid_passes(mesh8):
    grid = make_grid(mesh8)
    verify_all(grid)


def test_healthy_after_refine_and_balance(mesh8):
    grid = make_grid(mesh8, max_lvl=2)
    ids = grid.get_cells()
    grid.refine_completely(int(ids[0]))
    grid.stop_refining()
    verify_all(grid)
    grid.balance_load()
    verify_all(grid)


def test_healthy_with_user_neighborhood(mesh8):
    grid = make_grid(mesh8)
    grid.add_neighborhood(7, [[1, 0, 0], [0, 1, 0]])
    verify_all(grid)


def test_pin_verified(mesh8):
    grid = make_grid(mesh8)
    cid = int(grid.get_cells()[0])
    grid.pin(cid, 3)
    grid.balance_load()
    V.pin_requests_succeeded(grid)
    # corrupt: claim the pin went elsewhere
    grid._pins[cid] = 5
    with pytest.raises(VerificationError) as ei:
        V.pin_requests_succeeded(grid)
    # typed error names the offending cell
    assert ei.value.cells == (cid,)
    assert str(cid) in str(ei.value)


def test_corrupt_owner_detected(mesh8):
    grid = make_grid(mesh8)
    grid.plan.owner = grid.plan.owner.copy()
    grid.plan.owner[0] = 99
    with pytest.raises(VerificationError) as ei:
        V.is_consistent(grid)
    assert int(grid.plan.cells[0]) in ei.value.cells


def test_corrupt_neighbor_list_detected(mesh8):
    grid = make_grid(mesh8)
    nl = grid.plan.hoods[DEFAULT_NEIGHBORHOOD_ID].lists
    nl.of_neighbor = nl.of_neighbor.copy()
    nl.of_neighbor[0] = nl.of_neighbor[1]
    with pytest.raises(VerificationError) as ei:
        V.verify_neighbors(grid)
    assert len(ei.value.cells) >= 1


def test_corrupt_send_list_detected(mesh8):
    grid = make_grid(mesh8)
    hp = grid.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    if not np.any(hp.send_rows >= 0):
        pytest.skip("no remote transfers on this mesh")
    # corrupt the (lazily materialized) dense view in place
    hp._send_rows = hp.send_rows.copy()
    p, q, j = np.argwhere(hp._send_rows >= 0)[0]
    hp._send_rows[p, q, j] = -1
    with pytest.raises(VerificationError):
        V.verify_remote_neighbor_info(grid)


def test_corrupt_pad_row_detected(mesh8):
    grid = make_grid(mesh8)
    arr = np.asarray(grid.data["rho"]).copy()
    arr[:, grid.plan.R - 1] = 1.0
    import jax.numpy as jnp

    grid.data["rho"] = jnp.asarray(arr, device=grid._sharding())
    with pytest.raises(VerificationError):
        V.verify_user_data(grid)


def test_debug_env_hook(mesh8, monkeypatch):
    monkeypatch.setenv("DCCRG_DEBUG", "1")
    grid = make_grid(mesh8, max_lvl=1)
    ids = grid.get_cells()
    grid.refine_completely(int(ids[0]))
    grid.stop_refining()  # runs verify_all internally via _build_plan


def test_partition_coverage_detects_double_ownership(mesh8):
    grid = make_grid(mesh8)
    V.verify_partition_coverage(grid)
    # corrupt: device 1 also claims a cell device 0 owns
    stolen = grid.plan.local_ids[0][0]
    grid.plan.local_ids[1] = np.concatenate(
        [grid.plan.local_ids[1], [stolen]]
    )
    with pytest.raises(VerificationError) as ei:
        V.verify_partition_coverage(grid)
    assert ei.value.cells == (int(stolen),)


def test_partition_coverage_detects_dropped_cell(mesh8):
    grid = make_grid(mesh8)
    dropped = grid.plan.local_ids[2][-1]
    grid.plan.local_ids[2] = grid.plan.local_ids[2][:-1]
    with pytest.raises(VerificationError) as ei:
        V.verify_partition_coverage(grid)
    assert int(dropped) in ei.value.cells


def test_refinement_balance_detects_level_jump(mesh8):
    """Plant a >1 level jump: replace one level-1 child with its 8
    level-2 children while a face neighbor stays at level 0 — a valid
    tiling (so load-style checks pass) that violates 2:1."""
    grid = make_grid(mesh8, max_lvl=2)
    grid.refine_completely(1)
    grid.stop_refining()
    V.verify_refinement_balance(grid)
    lvl = grid.mapping.get_refinement_level(grid.plan.cells)
    child = grid.plan.cells[lvl == 1][0]
    grandkids = grid.mapping.get_all_children(np.uint64(child))
    cells = np.sort(np.concatenate([
        grid.plan.cells[grid.plan.cells != child], grandkids
    ]))
    grid.plan.cells = cells  # structure-only corruption
    with pytest.raises(VerificationError) as ei:
        V.verify_refinement_balance(grid)
    assert len(ei.value.cells) >= 2
    assert any(int(k) in ei.value.cells for k in grandkids)


def test_neighbor_symmetry_detects_dropped_edge(mesh8, monkeypatch):
    """The two-engine cross-check: drop one edge from the to-subset
    engine's answer and the symmetry verifier must flag it."""
    grid = make_grid(mesh8)
    V.verify_neighbor_symmetry(grid)
    real = V.find_neighbors_to_subset

    def lossy(mapping, topology, cells, query, offsets):
        qi, src, off = real(mapping, topology, cells, query, offsets)
        return qi[:-1], src[:-1], off[:-1]

    monkeypatch.setattr(V, "find_neighbors_to_subset", lossy)
    with pytest.raises(VerificationError) as ei:
        V.verify_neighbor_symmetry(grid)
    assert len(ei.value.cells) >= 1


def test_verify_all_check_pins_flag(mesh8):
    """A pending (unapplied) pin request is not an invariant break at
    non-balance mutation boundaries."""
    grid = make_grid(mesh8)
    cid = int(grid.get_cells()[0])
    cur = grid.get_process(cid)
    grid.pin(cid, (cur + 1) % 8)
    verify_all(grid, check_pins=False)
    with pytest.raises(VerificationError):
        verify_all(grid)  # strict mode still enforces placement
