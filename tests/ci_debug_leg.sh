#!/bin/sh
# DCCRG_DEBUG CI leg: a short tier-1 marker subset (the mutation-heavy
# fuzz + faultinject suites) with continuous invariant verification
# enabled, so an invariant regression surfaces immediately even though
# the main tier-1 run keeps DEBUG off for speed. Mirrors the
# reference's -DDEBUG CI builds.
#
# Usage: tests/ci_debug_leg.sh [extra pytest args]
set -e
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m "(fuzz or faultinject) and not slow" --dccrg-debug \
    -p no:cacheprovider "$@"
