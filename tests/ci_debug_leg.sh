#!/bin/sh
# DCCRG_DEBUG CI leg: a short tier-1 marker subset (the mutation-heavy
# fuzz + faultinject suites) with continuous invariant verification
# enabled, so an invariant regression surfaces immediately even though
# the main tier-1 run keeps DEBUG off for speed. Mirrors the
# reference's -DDEBUG CI builds.
#
# Also exercises one native-recommit parity test under DEBUG so the
# post-commit verify_all runs against plans built by the native
# in-place table writers + PlanArena (the numpy-only fallback is
# covered by the same test when the native build is unavailable),
# and one incremental-checkpoint chain-integrity test (corrupt/
# truncate/delete each keyframe+delta link position; typed
# DeltaChainError fallback asserted) so the delta data plane runs
# with continuous invariant verification on.
#
# Also runs a small fleet smoke leg: >= 8 concurrent jobs multiplexed
# through one batch with ONE injected NaN trip — the victim must roll
# back alone and every job must finish bitwise equal to its solo run
# (the fleet-isolation fuzz scenario plus the CLI round trip).
#
# Also runs an SDC smoke leg: an 8-job fleet with one silent_flip
# victim (finite corruption, invisible to the finiteness watchdog)
# convicted by the in-program integrity invariants within one
# quantum, plus the quarantine-after-2 path — a repeat-offender
# device lane is taken out of service with its survivors migrated
# bit-exactly (all digests still equal the solo runs).
#
# Also runs a telemetry smoke leg: the strictly-best-effort exporter
# contract (EVERY telemetry write failing must leave the supervised
# run with zero trips/rollbacks), the deterministic latency-SLO
# admission reorder vs the priority-only baseline, and the
# trace-coverage acceptance (a traced fleet run's depth-0 spans
# account for >= 95% of the serving wall-clock).
#
# Also runs a bulk-executor smoke leg: the roll-plan Pallas executor
# (DCCRG_BULK=pallas, interpret mode) against the XLA roll path —
# fixup-row parity on periodic and non-periodic grids plus one fleet
# bucket stepping through the registered bulk kernel — and the
# negative pin that DCCRG_BULK unset compiles the pre-executor
# program.
#
# Also runs a background-recommit leg under DCCRG_DEBUG=1: the
# refine/unrefine/balance parity suite with DCCRG_BG_RECOMMIT on, so
# every step-boundary swap's post-commit verify_all runs against a
# plan built on the worker thread (the swap wraps itself in a
# transaction; --dccrg-debug makes that transaction verify), plus an
# async-save + kill-mid-overlap smoke: a child process is killed
# (os._exit, no cleanup) while an async checkpoint write is in
# flight, and the parent must resume from the last durable save with
# only sweepable temp litter left behind.
#
# Also runs an autopilot smoke leg under DCCRG_DEBUG=1: an opted-in
# fleet run writes its decision journal and every decision replays
# (re-derives) from the journal alone, the explain/replay CLI round
# trips (tampering detected), and the off-by-default negative pin
# holds — no controller, no knob movement, bitwise-solo results.
#
# Also runs a model-zoo smoke leg under DCCRG_DEBUG=1: an MHD 8^3
# run (conservation pinned) plus the MHD-schema GridFuzzer leg, so
# every mutation's post-commit verify_all runs over the multi-field
# schema, and one ghost-split parity case (split vs full outer
# re-pass bitwise, strictly fewer recomputed row slots).
#
# Also runs a warm-start smoke leg under DCCRG_DEBUG=1: a cold serve
# manifests its compile-cache records and a fresh pool serves every
# first dispatch warm with bitwise digests, the full warm-cache fault
# matrix (torn/corrupt/stale/io/mid-prewarm death) degrades typed to
# a cold compile — never a wrong program — and the negative pin holds
# (DCCRG_COMPILE_CACHE unset: no pool, bitwise-identical behavior).
#
# Usage: tests/ci_debug_leg.sh [extra pytest args]
set -e
cd "$(dirname "$0")/.."
env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m "(fuzz or faultinject) and not slow" --dccrg-debug \
    -p no:cacheprovider "$@"
env JAX_PLATFORMS=cpu python -m pytest -q \
    "tests/test_fleet.py::test_fleet_fuzz_isolation_scenario" \
    "tests/test_fleet.py::test_cli_runs_a_job_file" \
    -p no:cacheprovider "$@"
env JAX_PLATFORMS=cpu python -m pytest -q \
    "tests/test_integrity.py::test_silent_flip_detected_within_one_quantum" \
    "tests/test_integrity.py::test_repeat_offender_lane_quarantined_and_migrated" \
    "tests/test_integrity.py::test_fleet_fuzz_flip_scenario" \
    -p no:cacheprovider "$@"
env JAX_PLATFORMS=cpu python -m pytest -q \
    "tests/test_telemetry.py::test_exporter_faults_never_trip_a_run" \
    "tests/test_telemetry.py::test_slo_admission_reorders_vs_priority_baseline" \
    "tests/test_telemetry.py::test_fleet_trace_covers_step_wall_clock" \
    -p no:cacheprovider "$@"
env JAX_PLATFORMS=cpu python -m pytest -q \
    "tests/test_bulk_executor.py::test_bulk_matches_xla_roll_path" \
    "tests/test_bulk_executor.py::test_bulk_negative_pin" \
    "tests/test_bulk_executor.py::test_fleet_bulk_bucket_matches_table_path" \
    -p no:cacheprovider "$@"
env JAX_PLATFORMS=cpu python -m pytest -q \
    "tests/test_autopilot.py::test_autopilot_on_preserves_results" \
    "tests/test_autopilot.py::test_explain_and_replay_cli" \
    "tests/test_autopilot.py::test_off_by_default_negative_pin" \
    --dccrg-debug -p no:cacheprovider "$@"
env JAX_PLATFORMS=cpu python -m pytest -q \
    "tests/test_bgrecommit.py::test_bg_plan_parity_across_refine_unrefine_balance" \
    "tests/test_bgrecommit.py::test_balance_drains_pending_build_first" \
    "tests/test_bgrecommit.py::test_async_preempt_emergency_save_then_resume_bitwise" \
    --dccrg-debug -p no:cacheprovider "$@"
env JAX_PLATFORMS=cpu python - <<'PYEOF'
# async-save kill-mid-overlap smoke: SIGKILL-equivalent death while a
# checkpoint write is overlapped with dispatch; the store must still
# resume from the last durable save, with only sweepable temp litter.
import os, subprocess, sys, tempfile
workdir = tempfile.mkdtemp(prefix="dccrg_kill_overlap_")
child = r'''
import os, sys
import numpy as np, jax.numpy as jnp
from dccrg_tpu import Grid
from dccrg_tpu.supervise import CheckpointStore
os.environ["DCCRG_ASYNC_SAVE"] = "1"
g = (Grid(cell_data={"rho": jnp.float32})
     .set_initial_length((8, 8, 4)).set_periodic(True, True, False)
     .set_load_balancing_method("block").initialize())
cells = g.plan.cells
g.set("rho", cells, (cells.astype(np.float64) % 13).astype(np.float32))
store = CheckpointStore(sys.argv[1], stem="k")
store.save(g, 1); store.drain()          # one durable save
g.set("rho", cells, (cells.astype(np.float64) % 7).astype(np.float32))
store.save(g, 2)                          # in flight...
os._exit(137)                             # ...killed mid-overlap
'''
rc = subprocess.run([sys.executable, "-c", child, workdir],
                    env=dict(os.environ, JAX_PLATFORMS="cpu")).returncode
assert rc == 137, rc
import jax.numpy as jnp
from dccrg_tpu import checkpoint as ckpt, resilience
from dccrg_tpu.supervise import resume_latest
info = resume_latest(workdir, {"rho": jnp.float32}, stem="k",
                     load_balancing_method="block")
assert info is not None and not info.salvaged and info.step >= 1, info
# whatever the kill left behind is recognized stale temp litter, and
# the durable checkpoint the resume used still CRC-verifies
assert resilience.verify_checkpoint(info.path) == []
for p in ckpt.stale_temp_files(workdir):
    os.unlink(p)
left = [n for n in os.listdir(workdir)
        if ".tmp." in n or n.endswith(".mp-tmp")]
assert not left, left
print("kill-mid-overlap smoke OK (resumed step %d)" % info.step)
PYEOF
env JAX_PLATFORMS=cpu python -m pytest -q \
    "tests/test_models.py::test_mhd_conservation" \
    "tests/test_models.py::test_mhd_schema_fuzz_leg" \
    "tests/test_models.py::test_ghost_split_bitwise_and_strictly_fewer_rows" \
    --dccrg-debug -p no:cacheprovider "$@"
env JAX_PLATFORMS=cpu python -m pytest -q \
    "tests/test_warmstart.py::test_cold_run_manifests_and_warm_run_hits" \
    "tests/test_warmstart.py::test_every_warm_fault_site_degrades_typed" \
    "tests/test_warmstart.py::test_negative_pin_no_cache_no_pool" \
    --dccrg-debug -p no:cacheprovider "$@"
exec env JAX_PLATFORMS=cpu python -m pytest -q \
    "tests/test_recommit.py::test_native_numpy_plans_bitwise_identical" \
    "tests/test_checkpoint_integrity.py::test_chain_salvage_falls_back_to_verifying_prefix" \
    --dccrg-debug -p no:cacheprovider "$@"
