"""Particle tests (the reference's tests/particles suite): conservation
while particles advect across cell and device boundaries, ragged
counts, capacity handling."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu.models.particles import ParticleModel


def mesh1(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def drift_x(pos):
    v = jnp.zeros_like(pos)
    return v.at[:, 0].set(1.0)


def test_seeding_and_counts():
    m = ParticleModel(drift_x, length=(4, 1, 1), capacity=4, mesh=mesh1(2))
    placed = m.add_particles([[0.5, 0.5, 0.5], [0.6, 0.5, 0.5], [3.5, 0.5, 0.5], [9.0, 0.5, 0.5]])
    assert placed == 3  # the last one is outside the grid
    np.testing.assert_array_equal(m.counts(), [2, 0, 0, 1])


def test_particles_drift_across_cells_and_devices():
    m = ParticleModel(drift_x, length=(8, 1, 1), capacity=8, mesh=mesh1(4))
    start = np.array([[0.5, 0.5, 0.5], [0.25, 0.4, 0.6], [2.5, 0.5, 0.5]], np.float32)
    m.add_particles(start)
    for _ in range(10):
        m.step(0.5)  # moves at most half a cell per step
    got = m.particles()
    assert len(got) == 3
    # each particle advanced by 5.0 in x
    np.testing.assert_allclose(np.sort(got[:, 0]), np.sort(start[:, 0] + 5.0), atol=1e-5)
    np.testing.assert_allclose(np.sort(got[:, 1]), np.sort(start[:, 1]), atol=1e-6)
    # counts reflect the new cells
    cnt = m.counts()
    assert cnt.sum() == 3
    assert cnt[5] == 2 and cnt[7] == 1


def test_particles_leave_grid():
    m = ParticleModel(drift_x, length=(2, 1, 1), capacity=4, mesh=mesh1(1))
    m.add_particles([[1.5, 0.5, 0.5]])
    for _ in range(3):
        m.step(0.4)
    assert len(m.particles()) == 0  # advected out of the non-periodic grid


def test_capacity_overflow_grows_and_preserves_particles():
    def converge(pos):
        # everything is pulled toward x = 2.25, landing inside cell 3
        v = jnp.zeros_like(pos)
        return v.at[:, 0].set(jnp.sign(2.25 - pos[:, 0]))

    m = ParticleModel(converge, length=(4, 1, 1), capacity=2, mesh=mesh1(1))
    m.add_particles([[0.7, 0.5, 0.5], [1.2, 0.3, 0.5], [2.7, 0.5, 0.5], [3.2, 0.6, 0.5]])
    for _ in range(8):
        m.step(0.4)
    # particles converge on x=2.25, overflowing the capacity-2 buffer:
    # the buffer must have grown (a rolled-back replanning event) and
    # no particle may be lost
    assert m.capacity > 2
    got = m.particles()
    assert len(got) == 4
    assert np.all(np.abs(got[:, 0] - 2.25) < 0.6)


def test_ensure_capacity_grows_buffers():
    m = ParticleModel(drift_x, length=(4, 1, 1), capacity=2, mesh=mesh1(2))
    m.add_particles([[0.2, 0.5, 0.5], [0.6, 0.5, 0.5]])
    m.ensure_capacity(8)
    assert m.capacity == 8
    # data survived
    assert len(m.particles()) == 2
    m.add_particles([[0.3, 0.5, 0.5]] * 5)
    assert m.counts()[0] == 7
    m.step(0.25)
    assert len(m.particles()) == 7


def test_device_invariance(rng):
    pts = np.column_stack(
        [rng.uniform(0, 8, 12), rng.uniform(0, 1, 12), rng.uniform(0, 1, 12)]
    ).astype(np.float32)

    def swirl(pos):
        return jnp.stack(
            [jnp.ones(pos.shape[0]), 0.3 * jnp.sin(pos[:, 0]), jnp.zeros(pos.shape[0])],
            axis=1,
        )

    results = []
    for n in (1, 8):
        m = ParticleModel(swirl, length=(8, 1, 1), capacity=16, mesh=mesh1(n))
        m.add_particles(pts)
        for _ in range(6):
            m.step(0.3)
        got = m.particles()
        results.append(got[np.lexsort(got.T)])
    np.testing.assert_allclose(results[0], results[1], atol=1e-6)


def test_periodic_wrap_preserves_particles():
    m = ParticleModel(
        drift_x, length=(4, 1, 1), capacity=4, mesh=mesh1(2), periodic=(True, False, False)
    )
    m.add_particles([[3.6, 0.5, 0.5]])
    for _ in range(4):
        m.step(0.5)  # crosses the x=4 -> x=0 wrap
    got = m.particles()
    assert len(got) == 1
    np.testing.assert_allclose(got[0, 0], (3.6 + 2.0) % 4.0, atol=1e-5)
