"""Load balancing and checkpoint/restart tests.

Mirrors the reference's tests/load_balancing (incl. the staged
initialize/continue/finish protocol), pinning, weights, and the
tests/restart strategy: run the same simulation twice, once through
save+load, and require identical results (tests/restart/README:10-14).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu.grid import Grid
from dccrg_tpu.models.game_of_life import GameOfLife


def make_grid(length=(4, 4, 1), n_dev=4, max_lvl=0, **kw):
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("dev",))
    g = Grid(cell_data=kw.pop("cell_data", {"v": jnp.float32}))
    g.set_initial_length(length).set_maximum_refinement_level(max_lvl)
    return g.initialize(mesh)


def test_balance_load_preserves_data():
    g = make_grid((8, 1, 1), n_dev=4)
    ids = np.arange(1, 9, dtype=np.uint64)
    g.set("v", ids, ids.astype(np.float32) * 3)
    g.set_cell_weight(1, 10.0)  # skew the partition
    g.balance_load()
    np.testing.assert_allclose(g.get("v", ids), ids * 3.0)
    # heavy cell alone on its device
    dev0 = g.get_process(1)
    others = [g.get_process(int(i)) for i in ids[1:]]
    assert dev0 not in others


def test_staged_protocol():
    g = make_grid((8, 1, 1), n_dev=4)
    with pytest.raises(RuntimeError):
        g.continue_balance_load()
    with pytest.raises(RuntimeError):
        g.finish_balance_load()
    g.initialize_balance_load()
    with pytest.raises(RuntimeError):
        g.initialize_balance_load()
    g.continue_balance_load()
    g.continue_balance_load()  # repeatable (multi-stage transfers)
    g.finish_balance_load()


def test_pinning():
    g = make_grid((8, 1, 1), n_dev=4)
    assert g.pin(5, 2)
    assert not g.pin(5, 9)  # invalid device
    assert not g.pin(99, 0)  # unknown cell
    g.balance_load()
    assert g.get_process(5) == 2
    assert g.unpin(5)
    assert not g.unpin(5)
    g.pin(1, 3)
    g.unpin_all_cells()
    g.balance_load()
    assert g.get_process(1) != 3 or True  # pin gone; partition free


def test_balance_without_zoltan_pins_only():
    g = make_grid((8, 1, 1), n_dev=4)
    before = [g.get_process(int(i)) for i in range(1, 9)]
    g.pin(4, 0)
    g.balance_load(use_zoltan=False)
    after = [g.get_process(int(i)) for i in range(1, 9)]
    assert after[3] == 0
    # everything unpinned stayed put
    for i, (b, a) in enumerate(zip(before, after)):
        if i != 3:
            assert b == a


def test_cell_weights_api():
    g = make_grid((4, 1, 1), n_dev=2)
    assert g.get_cell_weight(1) == 1.0
    assert g.set_cell_weight(1, 5.0)
    assert g.get_cell_weight(1) == 5.0
    assert not g.set_cell_weight(1, -1.0)
    assert not g.set_cell_weight(77, 1.0)


def test_partitioning_options():
    g = make_grid((4, 1, 1), n_dev=2)
    g.set_partitioning_option("LB_METHOD", "hilbert")
    assert g._lb_method == "hilbert"
    g.set_partitioning_option("IMBALANCE_TOL", 1.05)
    assert g.get_partitioning_options()["IMBALANCE_TOL"] == 1.05


def test_amr_then_balance_keeps_data():
    g = make_grid((2, 2, 2), n_dev=8, max_lvl=1)
    cells = g.get_cells()
    g.set("v", cells, np.arange(1, 9, dtype=np.float32))
    g.refine_completely(2)
    g.stop_refining()
    g.assign_children_from_parents()
    g.balance_load()
    kids = g.mapping.get_all_children(np.uint64(2))
    np.testing.assert_allclose(g.get("v", kids), np.full(8, 2.0))
    assert g.get("v", np.uint64(8)) == 8.0


# ---------------------------------------------------------------------
# checkpoint / restart

def test_save_load_roundtrip(tmp_path):
    g = make_grid((4, 3, 2), n_dev=4)
    ids = g.get_cells()
    vals = np.arange(len(ids), dtype=np.float32) * 0.5
    g.set("v", ids, vals)
    fn = str(tmp_path / "grid.dc")
    g.save_grid_data(fn, header=b"hello-header")

    g2 = make_grid((4, 3, 2), n_dev=4)
    header = g2.load_grid_data(fn, header_size=len(b"hello-header"))
    assert header == b"hello-header"
    np.testing.assert_allclose(g2.get("v", ids), vals)


def test_save_load_with_amr(tmp_path):
    g = make_grid((2, 2, 2), n_dev=8, max_lvl=1)
    g.refine_completely(3)
    g.stop_refining()
    ids = g.get_cells()
    g.set("v", ids, np.arange(len(ids), dtype=np.float32))
    fn = str(tmp_path / "amr.dc")
    g.save_grid_data(fn)

    g2 = make_grid((2, 2, 2), n_dev=8, max_lvl=1)
    g2.load_grid_data(fn)
    np.testing.assert_array_equal(g2.get_cells(), ids)
    np.testing.assert_allclose(g2.get("v", ids), np.arange(len(ids), dtype=np.float32))


def test_restart_equivalence(tmp_path):
    """The reference restart test: identical results with and without a
    save/load in the middle (tests/restart/README:10-14)."""
    ref = GameOfLife(mesh=Mesh(np.array(jax.devices()[:4]), ("dev",)))
    blinker = [35, 45, 55]
    ref.set_alive(blinker)
    for _ in range(5):
        ref.step()

    a = GameOfLife(mesh=Mesh(np.array(jax.devices()[:4]), ("dev",)))
    a.set_alive(blinker)
    for _ in range(2):
        a.step()
    fn = str(tmp_path / "gol.dc")
    a.grid.save_grid_data(fn)

    b = GameOfLife(mesh=Mesh(np.array(jax.devices()[:4]), ("dev",)))
    b.grid.load_grid_data(fn)
    for _ in range(3):
        b.step()
    np.testing.assert_array_equal(np.sort(b.alive_cells()), np.sort(ref.alive_cells()))


def test_load_rejects_mismatched_grid(tmp_path):
    g = make_grid((4, 3, 2), n_dev=2)
    fn = str(tmp_path / "g.dc")
    g.save_grid_data(fn)
    other = make_grid((4, 4, 2), n_dev=2)
    with pytest.raises(ValueError):
        other.load_grid_data(fn)
    with pytest.raises(ValueError):
        g.load_grid_data(fn, header_size=5)  # wrong header size -> bad magic

def test_restart_from_file_alone(tmp_path):
    """Reconstruct the whole grid — mapping, geometry, AMR structure,
    data — from nothing but the .dc file (reference load_grid_data,
    dccrg.hpp:1815-2105)."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dev",))
    spec = {"rho": jnp.float32, "vel": ((3,), jnp.float32)}
    g = (Grid(cell_data=spec)
         .set_initial_length((4, 4, 2))
         .set_maximum_refinement_level(2)
         .set_periodic(True, False, False)
         .set_neighborhood_length(1)
         .set_geometry("cartesian", start=(1.0, 2.0, 3.0),
                       level_0_cell_length=(0.5, 0.25, 2.0))
         .initialize(mesh))
    g.refine_completely(1)
    g.refine_completely(7)
    g.stop_refining()
    lvl1 = g.plan.cells[g.mapping.get_refinement_level(g.plan.cells) == 1]
    g.refine_completely(int(lvl1[0]))
    g.stop_refining()
    rng = np.random.default_rng(0)
    cells = g.get_cells()
    g.set("rho", cells, rng.random(len(cells)).astype(np.float32))
    g.set("vel", cells, rng.random((len(cells), 3)).astype(np.float32))
    fn = str(tmp_path / "restart.dc")
    g.save_grid_data(fn, header=b"HDR!")

    g2, header = Grid.from_file(fn, spec, mesh=mesh, header_size=4)
    assert header == b"HDR!"
    assert g2.mapping == g.mapping
    assert g2.topology == g.topology
    assert g2._hood_len == g._hood_len
    assert g2.geometry.to_bytes() == g.geometry.to_bytes()
    np.testing.assert_array_equal(g2.plan.cells, g.plan.cells)
    np.testing.assert_allclose(
        g2.get("rho", cells), g.get("rho", cells), rtol=0, atol=0
    )
    np.testing.assert_allclose(
        g2.get("vel", cells), g.get("vel", cells), rtol=0, atol=0
    )
    # the restarted grid is fully functional
    g2.update_copies_of_remote_neighbors()
    g2.refine_completely(int(g2.plan.cells[-1]))
    g2.stop_refining()


def test_restart_from_file_stretched_geometry(tmp_path):
    mesh = Mesh(np.array(jax.devices()[:2]), ("dev",))
    coords = [np.array([0.0, 1.0, 2.5, 4.5]), np.array([0.0, 2.0, 3.0]),
              np.array([-1.0, 1.0])]
    spec = {"v": jnp.float32}
    g = (Grid(cell_data=spec)
         .set_initial_length((3, 2, 1))
         .set_geometry("stretched", coordinates=coords)
         .initialize(mesh))
    g.set("v", g.get_cells(), np.arange(6, dtype=np.float32))
    fn = str(tmp_path / "s.dc")
    g.save_grid_data(fn)
    g2, _ = Grid.from_file(fn, spec, mesh=mesh)
    assert g2.geometry.to_bytes() == g.geometry.to_bytes()
    np.testing.assert_array_equal(g2.get("v", g2.get_cells()),
                                  g.get("v", g.get_cells()))


def test_streamed_save_load_64cubed(tmp_path, monkeypatch):
    """A >=64^3 multi-field grid roundtrips through the chunked writer
    without materializing the full payload matrix (CHUNK shrunk so the
    streaming actually iterates)."""
    from dccrg_tpu import checkpoint as cp

    monkeypatch.setattr(cp, "CHUNK", 50000)
    mesh = Mesh(np.array(jax.devices()[:8]), ("dev",))
    spec = {"a": jnp.float32, "b": jnp.int32}
    g = (Grid(cell_data=spec)
         .set_initial_length((64, 64, 64))
         .initialize(mesh))
    cells = g.get_cells()
    rng = np.random.default_rng(1)
    g.set_many(cells, {
        "a": rng.random(len(cells)).astype(np.float32),
        "b": rng.integers(0, 1 << 30, len(cells)).astype(np.int32),
    }, preserve_ghosts=False)
    fn = str(tmp_path / "big.dc")
    g.save_grid_data(fn)
    g2, _ = Grid.from_file(fn, spec, mesh=mesh)
    np.testing.assert_array_equal(g2.get("a", cells), g.get("a", cells))
    np.testing.assert_array_equal(g2.get("b", cells), g.get("b", cells))


def test_variable_size_payload_roundtrip(tmp_path):
    """Ragged per-cell payloads via the two-pass count/payload protocol
    (reference tests/particles/cell.hpp:50-84, dccrg.hpp:2108-2123):
    the file stores only `count` rows per cell."""
    mesh = Mesh(np.array(jax.devices()[:2]), ("dev",))
    cap = 5
    spec = {"pos": ((cap, 3), jnp.float32), "count": jnp.int32}
    g = (Grid(cell_data=spec)
         .set_initial_length((2, 2, 1))
         .initialize(mesh))
    cells = g.get_cells()
    rng = np.random.default_rng(2)
    counts = rng.integers(0, cap + 1, len(cells)).astype(np.int32)
    pos = np.zeros((len(cells), cap, 3), np.float32)
    for i, c in enumerate(counts):
        pos[i, :c] = rng.random((c, 3))
    g.set("count", cells, counts)
    g.set("pos", cells, pos)
    fn = str(tmp_path / "var.dc")
    g.save_grid_data(fn, variable={"pos": "count"})
    # the file must be smaller than a fixed-size dump when not full
    fixed_size = len(cells) * (cap * 3 * 4 + 4)
    import os as _os
    assert _os.path.getsize(fn) < fixed_size + 200 or counts.sum() == cap * len(cells)

    g2, _ = Grid.from_file(fn, spec, mesh=mesh, variable={"pos": "count"})
    np.testing.assert_array_equal(g2.get("count", cells), counts)
    got = g2.get("pos", cells)
    for i, c in enumerate(counts):
        np.testing.assert_array_equal(got[i, :c], pos[i, :c])
        assert not got[i, c:].any()  # padding restored as zeros


def test_multi_stage_balance_moves_staged_values():
    """Data captured at continue_balance_load time is what lands at the
    destination — later source mutations must NOT leak through (the
    reference transfers at continue, dccrg.hpp:3932-3964)."""
    g = make_grid((8, 1, 1), n_dev=4, cell_data={"a": jnp.float32,
                                                 "b": jnp.float32})
    cells = g.get_cells()
    g.set("a", cells, np.arange(8, dtype=np.float32))
    g.set("b", cells, 10 + np.arange(8, dtype=np.float32))
    # force moves via pins
    for c in cells:
        g.pin(int(c), (g.get_process(int(c)) + 1) % 4)
    g.initialize_balance_load(use_zoltan=False)
    g.continue_balance_load(fields=["a"])
    ids, vals = g.staged_balance_data("a")
    assert len(ids) == 8 and vals is not None
    # mutate the source AFTER staging: must not affect what arrives
    g.set("a", cells, np.full(8, -99, dtype=np.float32))
    g.set("b", cells, np.full(8, -77, dtype=np.float32))
    g.continue_balance_load(fields=["b"])  # b staged with the new values
    g.finish_balance_load()
    np.testing.assert_array_equal(g.get("a", cells), np.arange(8, dtype=np.float32))
    np.testing.assert_array_equal(g.get("b", cells), np.full(8, -77, np.float32))


def test_multi_stage_balance_with_capacity_growth():
    """The particles flow (tests/particles/cell.hpp:50-84): stage the
    counts, grow the buffer capacity based on them, stage the payload
    — the staged rows land padded to the new capacity."""
    from dccrg_tpu.models.particles import ParticleModel

    mesh = Mesh(np.array(jax.devices()[:4]), ("dev",))
    pm = ParticleModel(lambda p: np.zeros_like(p), length=(4, 1, 1),
                       capacity=2, mesh=mesh)
    g = pm.grid
    # NoGeometry: unit cells, domain [0,4)x[0,1)x[0,1)
    pts = np.array([[0.1, 0.5, 0.5], [0.15, 0.5, 0.5], [2.6, 0.5, 0.5]])
    pm.add_particles(pts)
    cells = g.get_cells()
    for c in cells:
        g.pin(int(c), (g.get_process(int(c)) + 1) % 4)
    g.initialize_balance_load(use_zoltan=False)
    g.continue_balance_load(fields=["count"])
    ids, counts = g.staged_balance_data("count")
    assert counts.sum() == 3
    pm.ensure_capacity(8)  # receiver-driven resize between stages
    g.continue_balance_load(fields=["pos"])
    g.finish_balance_load()
    assert pm.counts().sum() == 3
    got = np.sort(pm.particles(), axis=0)
    np.testing.assert_allclose(got, np.sort(pts, axis=0), atol=1e-6)
