"""Crash-consistent incremental checkpoints: dirty-chunk delta saves
with chain-aware resume, never-orphan retention GC, and fault
injection at every phase (resilience.py delta machinery +
supervise.CheckpointStore.save).

Covers: bitwise keyframe+delta reconstruction, the keyframe-forcing
rules (structural mutation / partition change / ragged fields /
DCCRG_DELTA=0 / DCCRG_KEYFRAME_EVERY), chain-aware rollback and
resume with typed prefix fallback, parent-link corruption, torn delta
writes, the two-phase multi-process delta commit under rank death at
every phase (faked splits; the REAL-process legs live in
tests/mp_harness.py), the fuzzed never-orphan / only-verifying-chain
retention properties, GC fault injection and GC-racing-a-save, stale
delta temp litter, and the chain CLI."""

import os
import shutil

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu import checkpoint as checkpoint_mod
from dccrg_tpu import faults, resilience, supervise
from dccrg_tpu.grid import Grid
from dccrg_tpu.resilience import DeltaChainError
from dccrg_tpu.supervise import CheckpointStore, gc_checkpoints

pytestmark = pytest.mark.deltackpt

# a static-heavy schema: "rho" is the stepped field, "mat"/"tag" never
# change after init — the production shape delta saves exist for
SCHEMA = {"rho": jnp.float32, "mat": ((16,), jnp.float32),
          "tag": jnp.int32}


def _mk_grid(seed=0, n=(4, 4, 2), max_lvl=1, n_dev=None, schema=None):
    devs = jax.devices()
    mesh = Mesh(np.array(devs[: (n_dev or min(2, len(devs)))]), ("dev",))
    g = (Grid(cell_data=schema or SCHEMA)
         .set_initial_length(n)
         .set_periodic(True, True, True)
         .set_maximum_refinement_level(max_lvl)
         .set_neighborhood_length(1)
         .set_load_balancing_method("block")
         .initialize(mesh))
    rng = np.random.default_rng(seed)
    cells = g.plan.cells
    for name, (shape, dtype) in g.fields.items():
        vals = (rng.random((len(cells),) + shape) * 100).astype(dtype)
        g.set(name, cells, vals)
    return g


def _step(g, rng):
    """A 'stepped field' change: rho only, like a step loop."""
    cells = g.plan.cells
    g.set("rho", cells, rng.random(len(cells)).astype(np.float32))


def _full_bytes(g, tmp_path, name="__direct.dc"):
    p = str(tmp_path / name)
    g.save_grid_data(p)
    with open(p, "rb") as f:
        data = f.read()
    os.unlink(p)
    return data


def _materialized_bytes(path, fields):
    out = path + ".chain.test"
    try:
        resilience.materialize_chain(path, out, fields)
        with open(out, "rb") as f:
            return f.read()
    finally:
        if os.path.exists(out):
            os.unlink(out)


# ---------------------------------------------------------------------
# the save policy + bitwise reconstruction
# ---------------------------------------------------------------------

def test_delta_roundtrip_bitwise_and_resume(tmp_path):
    g = _mk_grid()
    rng = np.random.default_rng(1)
    store = CheckpointStore(tmp_path, keyframe_every=8)
    assert store.save(g, 0).endswith(".dc")  # nothing to chain to yet
    for step in (1, 2, 3):
        _step(g, rng)
        p = store.save(g, step)
        assert p.endswith(".dcd"), p
        # reconstruction == a direct full save, bit for bit
        assert _materialized_bytes(p, g.fields) == _full_bytes(g, tmp_path)
    info = supervise.resume_latest(tmp_path, SCHEMA,
                                   load_balancing_method="block")
    assert info.step == 3 and not info.salvaged
    assert len(info.report.chain) == 4  # keyframe + 3 deltas
    cells = g.plan.cells
    for name in SCHEMA:
        np.testing.assert_array_equal(info.grid.get(name, cells),
                                      g.get(name, cells))


def test_keyframe_cadence_and_optout(tmp_path, monkeypatch):
    g = _mk_grid()
    rng = np.random.default_rng(2)
    store = CheckpointStore(tmp_path / "a", keyframe_every=3)
    kinds = []
    for step in range(7):
        _step(g, rng)
        kinds.append(store.save(g, step).endswith(".dcd"))
    # keyframe, d, d, keyframe, d, d, keyframe
    assert kinds == [False, True, True, False, True, True, False]

    monkeypatch.setenv("DCCRG_DELTA", "0")
    store2 = CheckpointStore(tmp_path / "b", keyframe_every=3)
    for step in range(3):
        _step(g, rng)
        assert store2.save(g, step).endswith(".dc")  # opt-out: all full


def test_structural_mutation_forces_keyframe(tmp_path):
    g = _mk_grid()
    rng = np.random.default_rng(3)
    store = CheckpointStore(tmp_path, keyframe_every=50)
    store.save(g, 0)
    _step(g, rng)
    assert store.save(g, 1).endswith(".dcd")
    g.refine_completely(int(g.plan.cells[0]))
    g.stop_refining()
    assert store.save(g, 2).endswith(".dc")  # new structure epoch
    _step(g, rng)
    assert store.save(g, 3).endswith(".dcd")  # chains to the new keyframe
    g.balance_load()  # partition change ends the epoch too
    assert store.save(g, 4).endswith(".dc")


def test_ragged_and_all_dirty_force_keyframe(tmp_path):
    schema = {"rho": jnp.float32, "count": jnp.int32,
              "pos": ((4, 3), jnp.float32)}
    g = _mk_grid(schema=schema)
    cells = g.plan.cells
    g.set("count", cells, np.full(len(cells), 2, np.int32))
    variable = {"pos": "count"}
    store = CheckpointStore(tmp_path, keyframe_every=50)
    store.save(g, 0, variable=variable)
    # a dirty ragged field (or its count) moves the offset table
    g.set("pos", cells, np.zeros((len(cells), 4, 3), np.float32))
    assert store.save(g, 1, variable=variable).endswith(".dc")
    g.set("rho", cells, np.ones(len(cells), np.float32))
    assert store.save(g, 2, variable=variable).endswith(".dcd")
    # every field dirty -> a delta would be a keyframe plus overhead
    for name in schema:
        vals = np.asarray(g.get(name, cells))
        g.set(name, cells, vals)
    assert store.save(g, 3, variable=variable).endswith(".dc")
    # save_delta_checkpoint itself refuses ragged fields loudly
    with pytest.raises(ValueError, match="ragged"):
        resilience.save_delta_checkpoint(
            g, str(tmp_path / "x.dcd"), parent_path=store.path_for(3),
            parent_step=3, step=4, fields=["pos"], variable=variable)


def test_delta_bytes_are_small(tmp_path):
    """The point of the exercise: with static-heavy payloads a delta
    save costs a small fraction of a full one (the bench pins the
    >=10x target on a bigger grid; this is the tier-1 canary)."""
    g = _mk_grid(n=(8, 8, 4), max_lvl=0)
    rng = np.random.default_rng(4)
    store = CheckpointStore(tmp_path, keyframe_every=8)
    kf = store.save(g, 0)
    _step(g, rng)
    dp = store.save(g, 1)
    assert dp.endswith(".dcd")
    # full = 16B pairs + 4B rho + 64B mat + 4B tag per cell;
    # delta = 16B pairs + 4B rho per cell
    assert os.path.getsize(dp) < 0.3 * os.path.getsize(kf)


# ---------------------------------------------------------------------
# chain-aware rollback + typed salvage
# ---------------------------------------------------------------------

def test_runner_rolls_back_to_delta_and_reconverges(tmp_path):
    """A NaN poison lands after a delta save: the rollback target is
    the newest DELTA, restored chain-aware, and the recovered run
    reconverges bitwise with an undisturbed one."""
    def make(run_dir, plan=None):
        g = _mk_grid(seed=7)

        def step_fn(grid, i):
            cells = grid.plan.cells
            vals = np.asarray(grid.get("rho", cells))
            grid.set("rho", cells, (vals * 0.5 + 1.0).astype(np.float32))

        sup = supervise.SupervisedRunner(
            g, step_fn, run_dir, check_every=1, checkpoint_every=2,
            backoff=0.0, keep_last=16, install_signal_handlers=False)
        if plan is None:
            sup.run(6)
        else:
            with plan:
                sup.run(6)
        return g, sup

    ref, _ = make(str(tmp_path / "ref"))
    plan = faults.FaultPlan(seed=5)
    plan.nan_poison("rho", step=5, times=1)
    g, sup = make(str(tmp_path / "run"), plan)
    assert sup.rollbacks >= 1
    # the trip bundle records the rollback target at trip time: the
    # newest periodic save, which was a DELTA (step 4 of cadence 2)
    assert sup.trips[0]["checkpoint"].endswith(".dcd")
    # after the rollback everything is conservatively dirty again, so
    # the post-recovery save is a keyframe
    assert sup.runner.checkpoint_path.endswith(".dc")
    cells = g.plan.cells
    np.testing.assert_array_equal(g.get("rho", cells),
                                  ref.get("rho", cells))


def _plant_chain(tmp_path, n_deltas=3, seed=11, keyframe_every=16):
    g = _mk_grid(seed=seed)
    rng = np.random.default_rng(seed)
    store = CheckpointStore(tmp_path, keyframe_every=keyframe_every)
    paths = [store.save(g, 0)]
    states = [np.asarray(g.get("rho", g.plan.cells))]
    for s in range(1, n_deltas + 1):
        _step(g, rng)
        paths.append(store.save(g, s))
        states.append(np.asarray(g.get("rho", g.plan.cells)))
    return g, store, paths, states


def test_parent_link_corruption_detected(tmp_path):
    """FaultPlan.delta_parent_corrupt lands a wrong parent digest in a
    delta sidecar: chain verification names the link and resume falls
    back to the parent."""
    g = _mk_grid()
    rng = np.random.default_rng(6)
    store = CheckpointStore(tmp_path, keyframe_every=16)
    store.save(g, 0)
    _step(g, rng)
    plan = faults.FaultPlan(seed=1)
    plan.delta_parent_corrupt(times=1)
    with plan:
        p1 = store.save(g, 1)
    assert plan.fired("checkpoint.delta") == 1
    assert p1.endswith(".dcd")
    with pytest.raises(DeltaChainError, match="parent digest"):
        resilience.verify_chain(p1)
    info = supervise.resume_latest(tmp_path, SCHEMA,
                                   load_balancing_method="block")
    assert info.step == 0 and not info.salvaged


def test_parent_replaced_by_different_save_detected(tmp_path):
    """A keyframe OVERWRITTEN by a different save (its own CRCs
    verify!) breaks its deltas' digest links — the chain must refuse
    to mix generations."""
    g, store, paths, _states = _plant_chain(tmp_path, n_deltas=1)
    g2 = _mk_grid(seed=99)  # different data, same shape
    resilience.save_checkpoint(g2, paths[0])  # replace the keyframe
    assert resilience.verify_checkpoint(paths[0]) == []  # self-valid
    with pytest.raises(DeltaChainError, match="parent digest"):
        resilience.verify_chain(paths[1])


def test_torn_delta_write_preserves_chain(tmp_path):
    """An I/O fault mid delta payload stream: the previous chain is
    untouched and still resumable; no litter under the final name."""
    g, store, paths, states = _plant_chain(tmp_path, n_deltas=1)
    before = {p: open(p, "rb").read() for p in paths}
    _step(g, np.random.default_rng(8))
    plan = faults.FaultPlan()
    plan.chunk_io_error(times=faults.EVERY)
    with plan, pytest.raises(OSError):
        store.save(g, 2)
    assert not os.path.exists(store.path_for(2, delta=True))
    for p in paths:
        assert open(p, "rb").read() == before[p]
    assert resilience.verify_chain(paths[-1])
    info = supervise.resume_latest(tmp_path, SCHEMA,
                                   load_balancing_method="block")
    assert info.step == 1


def test_delta_at_rest_corruption_caught_by_chain_verify(tmp_path):
    """A seeded random bit flip after a delta save (FaultPlan's
    at-rest corruption) fails chain verification."""
    g, store, paths, _states = _plant_chain(tmp_path, n_deltas=1)
    _step(g, np.random.default_rng(9))
    plan = faults.FaultPlan(seed=3)
    plan.bit_flip(times=1)
    with plan:
        p2 = store.save(g, 2)
    assert p2.endswith(".dcd") and plan.fired("checkpoint.file") == 1
    with pytest.raises(DeltaChainError):
        resilience.verify_chain(p2)
    info = supervise.resume_latest(tmp_path, SCHEMA,
                                   load_balancing_method="block")
    assert info.step == 1  # the prefix before the flipped delta


# ---------------------------------------------------------------------
# two-phase multi-process delta commit (faked splits; real-process
# versions live in tests/mp_harness.py scenario delta_rank_kill)
# ---------------------------------------------------------------------

def _fake_split(g, local_devs, rank, writes_meta, commits):
    g._proc_local_dev = np.array(
        [d in set(local_devs) for d in range(g.n_dev)], dtype=bool)
    g._ckpt_rank = rank
    g._ckpt_writes_meta = writes_meta
    g._ckpt_commits = commits


def _unfake(g):
    g._proc_local_dev = np.ones(g.n_dev, dtype=bool)
    g._ckpt_rank = None
    for attr in ("_ckpt_writes_meta", "_ckpt_commits"):
        if hasattr(g, attr):
            delattr(g, attr)


def _two_pass_delta(g, path, parent, parent_step, step, fields):
    half = g.n_dev // 2
    for rank in (0, 1):
        _fake_split(g, range(half) if rank == 0 else range(half, g.n_dev),
                    rank, writes_meta=rank == 0, commits=rank == 1)
        resilience.save_delta_checkpoint(
            g, path, parent_path=parent, parent_step=parent_step,
            step=step, fields=fields)
    _unfake(g)


@pytest.fixture(autouse=True)
def _clean_mp_stage():
    yield
    checkpoint_mod._MP_CRC_STAGE.clear()


def test_two_phase_delta_commit_bitwise(tmp_path):
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 faked devices")
    g = _mk_grid(n=(8, 8, 4), max_lvl=0, n_dev=4)
    half = g.n_dev // 2
    kf = str(tmp_path / "mp_00000000.dc")
    for rank in (0, 1):
        _fake_split(g, range(half) if rank == 0 else range(half, g.n_dev),
                    rank, writes_meta=rank == 0, commits=rank == 1)
        resilience.save_checkpoint(g, kf)
    _unfake(g)
    _step(g, np.random.default_rng(10))
    dp = str(tmp_path / "mp_00000001.dcd")
    _two_pass_delta(g, dp, kf, 0, 1, ["rho"])
    rec = resilience.read_sidecar(dp)
    assert rec["slices"], "two-phase delta must carry the slice table"
    assert _materialized_bytes(dp, g.fields) == _full_bytes(g, tmp_path)


@pytest.mark.parametrize("phase", ["meta", "slice", "written", "commit",
                                   "publish"])
def test_delta_rank_death_every_phase_keeps_chain(tmp_path, phase):
    """A rank death at EVERY two-phase delta-commit phase leaves the
    previous keyframe+delta chain bitwise intact and resumable."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 faked devices")
    g = _mk_grid(n=(8, 8, 4), max_lvl=0, n_dev=4)
    half = g.n_dev // 2
    kf = str(tmp_path / "dk_00000000.dc")
    for rank in (0, 1):
        _fake_split(g, range(half) if rank == 0 else range(half, g.n_dev),
                    rank, writes_meta=rank == 0, commits=rank == 1)
        resilience.save_checkpoint(g, kf)
    _unfake(g)
    rng = np.random.default_rng(12)
    _step(g, rng)
    d1 = str(tmp_path / "dk_00000001.dcd")
    _two_pass_delta(g, d1, kf, 0, 1, ["rho"])
    before = {p: open(p, "rb").read() for p in (kf, d1)}
    _step(g, rng)
    d2 = str(tmp_path / "dk_00000002.dcd")
    # the dying rank: rank 0 for prepare-side phases, the committing
    # rank (1) for commit/publish
    dying = 1 if phase in ("commit", "publish") else 0
    plan = faults.FaultPlan()
    plan.rank_death(phase=phase, rank=dying)
    with plan:
        try:
            _two_pass_delta(g, d2, kf, 0, 2, ["rho"])
        except faults.InjectedRankDeath:
            pass
    _unfake(g)
    for p in (kf, d1):
        assert open(p, "rb").read() == before[p], f"{phase} tore {p}"
    assert resilience.verify_chain(d1)
    info = supervise.resume_latest(tmp_path, SCHEMA, stem="dk",
                                   load_balancing_method="block")
    if phase == "publish":
        # death AFTER the rename, before the sidecar: the new delta
        # exists but cannot be interpreted — the old chain answers
        assert os.path.exists(d2)
    assert info is not None and info.step == 1 and not info.salvaged


# ---------------------------------------------------------------------
# chain-aware retention GC
# ---------------------------------------------------------------------

def test_gc_keeps_whole_chain_of_kept_steps(tmp_path):
    _g, store, paths, _states = _plant_chain(tmp_path, n_deltas=3)
    rep = store.gc(keep_last=1, apply=True)
    # keeping step 3 (a delta) forces its whole chain to survive
    assert [s for s, _ in store.list()] == [3, 2, 1, 0]
    assert not rep.dropped


def test_gc_prunes_whole_dead_chains_keyframe_last(tmp_path):
    g, store, paths, _states = _plant_chain(tmp_path, n_deltas=2,
                                            keyframe_every=16)
    # start a second chain so the first can age out
    g.refine_completely(int(g.plan.cells[0]))
    g.stop_refining()
    store.save(g, 3)  # keyframe (new epoch)
    _step(g, np.random.default_rng(13))
    store.save(g, 4)
    rep = store.gc(keep_last=2, apply=False)
    # the whole old chain {0,1,2} is prunable, deltas-first order
    assert [s for s, _ in rep.dropped] == [2, 1, 0]
    rep = store.gc(keep_last=2, apply=True)
    assert [s for s, _ in store.list()] == [4, 3]
    assert resilience.verify_chain(store.path_for(4, delta=True))


def test_gc_fault_mid_prune_never_orphans(tmp_path):
    """An injected I/O error on ANY unlink of the prune: every
    surviving delta still has its full ancestor chain on disk."""
    g, store, paths, _states = _plant_chain(tmp_path, n_deltas=2,
                                            keyframe_every=16)
    g.refine_completely(int(g.plan.cells[0]))
    g.stop_refining()
    store.save(g, 3)
    for kill_at in range(3):
        shutil.rmtree(tmp_path)
        g2, store2, _p, _s = _plant_chain(tmp_path, n_deltas=2,
                                          keyframe_every=16)
        g2.refine_completely(int(g2.plan.cells[0]))
        g2.stop_refining()
        store2.save(g2, 3)
        plan = faults.FaultPlan()
        plan.gc_error(times=1)
        for _skip in range(kill_at):
            plan.rules[0].fired += 1  # advance the rule to unlink k
        plan.rules[0].times = kill_at + 1
        with plan, pytest.raises(faults.InjectedIOError):
            store2.gc(keep_last=1, apply=True)
        # invariant: no delta without its ancestors
        remaining = dict(store2.list())
        for step, path in remaining.items():
            if path.endswith(".dcd"):
                resilience.chain_links(path)  # raises if orphaned


def test_gc_never_drops_only_verifying_chain(tmp_path):
    """Both chains policy-prunable, newest chain corrupt: the verifying
    older chain is rescued WHOLE; nothing verifying -> refuse."""
    g, store, _p, _s = _plant_chain(tmp_path, n_deltas=1,
                                    keyframe_every=16)
    g.refine_completely(int(g.plan.cells[0]))
    g.stop_refining()
    k2 = store.save(g, 2)
    _step(g, np.random.default_rng(14))
    d3 = store.save(g, 3)
    # wreck the NEW chain's keyframe: its deltas can't restore anything
    faults.flip_bit(k2, os.path.getsize(k2) - 5, bit=1)
    rep = store.gc(keep_last=1, apply=True)
    kept = [s for s, _ in store.list()]
    assert 0 in kept and 1 in kept, (kept, rep)
    assert rep.rescued == 1
    # now wreck the old chain too: nothing verifies -> refuse to prune
    faults.flip_bit(store.path_for(0),
                    os.path.getsize(store.path_for(0)) - 5, bit=1)
    rep = gc_checkpoints(str(tmp_path), keep_last=1, apply=True)
    assert rep.refused and not rep.dropped


def test_gc_property_fuzz_never_orphans_never_drops_last(tmp_path):
    """Seeded property fuzz: random chains, random corruption, random
    policy — (a) no surviving delta is ever orphaned, (b) if any chain
    verified before the prune, one still does after, (c) dropped
    chains are dropped whole."""
    for seed in range(6):
        rng = np.random.default_rng(seed)
        d = tmp_path / f"s{seed}"
        g = _mk_grid(seed=seed)
        store = CheckpointStore(d, keyframe_every=int(rng.integers(2, 5)))
        step = 0
        for _ in range(int(rng.integers(4, 9))):
            if rng.random() < 0.3:
                if rng.random() < 0.5:
                    g.refine_completely(int(
                        g.plan.cells[rng.integers(len(g.plan.cells))]))
                    g.stop_refining()
                else:
                    g.balance_load()
            else:
                _step(g, rng)
            store.save(g, step)
            step += 1
        files = dict(store.list())
        # random corruption
        for s, p in files.items():
            if rng.random() < 0.3:
                faults.flip_bit(p, int(rng.integers(
                    0, os.path.getsize(p))), int(rng.integers(0, 8)))

        def survivors_ok(dirpath):
            for _s, p in supervise.list_checkpoints(str(dirpath)):
                if p.endswith(".dcd"):
                    resilience.chain_links(p)  # raises if orphaned

        def any_chain_verifies(dirpath):
            for _s, p in supervise.list_checkpoints(str(dirpath)):
                try:
                    resilience.verify_chain(p)
                    return True
                except resilience.CheckpointCorruptionError:
                    continue
            return False

        had_verifying = any_chain_verifies(d)
        before = set(dict(store.list()).values())
        rep = store.gc(keep_last=int(rng.integers(1, 4)),
                       keep_every=int(rng.choice([0, 2, 3])),
                       apply=True)
        survivors_ok(d)                                   # (a)
        if had_verifying:
            assert any_chain_verifies(d), f"seed {seed}"  # (b)
        # (c) whole chains only: a dropped file's chain-mates are
        # all dropped or all kept — no partial chains among survivors
        after = set(dict(store.list()).values())
        for p in before - after:
            for _s2, p2 in rep.kept:
                if p2 in after and p2.endswith(".dcd"):
                    assert p not in [q for q in
                                     resilience.chain_links(p2)]


def test_gc_racing_a_save_keeps_chain_resumable(tmp_path, monkeypatch):
    """A GC sweep firing INSIDE a delta save's publish window (sidecar
    dropped, rename pending — the worst moment) must not break the
    chain the save is extending: the parent is policy-kept, and the
    directory stays resumable throughout."""
    g, store, paths, _states = _plant_chain(tmp_path, n_deltas=1)
    _step(g, np.random.default_rng(15))
    real_replace = os.replace
    raced = []

    def racing_replace(src, dst):
        if dst.endswith(".dcd") and not raced:
            raced.append(dst)
            gc_checkpoints(str(tmp_path), keep_last=2, apply=True)
            info = supervise.resume_latest(
                tmp_path, SCHEMA, load_balancing_method="block")
            assert info is not None and info.step == 1
        return real_replace(src, dst)

    monkeypatch.setattr(os, "replace", racing_replace)
    p2 = store.save(g, 2)
    monkeypatch.undo()
    assert raced and p2.endswith(".dcd")
    assert resilience.verify_chain(p2)


def test_gc_vouched_chain_skips_byte_verification(tmp_path, monkeypatch):
    """The per-save GC path stays ZERO-READ in the common case: the
    just-saved step vouches for the chain it extended, so dropping an
    aged-out chain never re-reads the kept chain's keyframe bytes
    (the multi-GB I/O delta saves exist to avoid)."""
    g, store, _p, _s = _plant_chain(tmp_path, n_deltas=2,
                                    keyframe_every=16)
    g.refine_completely(int(g.plan.cells[0]))
    g.stop_refining()
    store.save(g, 3)  # new-epoch keyframe: a second chain
    _step(g, np.random.default_rng(21))
    p4 = store.save(g, 4)
    assert p4.endswith(".dcd")
    calls = []
    real = resilience._bad_chunks
    monkeypatch.setattr(
        resilience, "_bad_chunks",
        lambda *a, **k: (calls.append(a[0]), real(*a, **k))[1])
    rep = gc_checkpoints(str(tmp_path), keep_last=2, apply=True,
                         assume_ok=4)
    assert [s for s, _ in rep.dropped] == [2, 1, 0]
    assert not calls, f"vouched kept chain was byte-verified: {calls}"


def test_readonly_store_still_resumes_delta(tmp_path, monkeypatch):
    """A delta in a READ-ONLY checkpoint directory (archived snapshot,
    RO mount) must still load: the materialization scratch falls back
    to the system temp dir instead of failing next to the file."""
    g, store, paths, states = _plant_chain(tmp_path, n_deltas=2)
    ro_dir = os.path.abspath(str(tmp_path))
    real_access = os.access

    def ro_access(p, mode, **kw):
        if mode == os.W_OK and os.path.abspath(str(p)) == ro_dir:
            return False
        return real_access(p, mode, **kw)

    monkeypatch.setattr(os, "access", ro_access)
    scratch = resilience._chain_scratch(paths[-1])
    assert os.path.dirname(os.path.abspath(scratch)) != ro_dir
    os.unlink(scratch)
    grid, _h, rep = resilience.load_checkpoint(
        paths[-1], SCHEMA, load_balancing_method="block")
    monkeypatch.undo()
    assert len(rep.chain) == 3
    cells = g.plan.cells
    np.testing.assert_array_equal(np.asarray(grid.get("rho", cells)),
                                  states[-1])
    assert not [n for n in os.listdir(tmp_path) if ".chain." in n]


# ---------------------------------------------------------------------
# litter, CLI
# ---------------------------------------------------------------------

def test_stale_delta_temp_suffixes_detected(tmp_path):
    """Regression (satellite): an interrupted delta save / chain
    reconstruction leaves only litter the sweeper recognizes."""
    _g, store, paths, _states = _plant_chain(tmp_path, n_deltas=1)
    dead_pid = 999999999
    litter = [
        store.path_for(2, delta=True) + ".mp-tmp",
        store.path_for(2, delta=True) + f".tmp.{dead_pid}",
        paths[-1] + f".chain.{dead_pid}",
    ]
    alive = paths[-1] + f".chain.{os.getpid()}"
    for p in litter + [alive]:
        with open(p, "wb") as f:
            f.write(b"x")
    found = checkpoint_mod.stale_temp_files(str(tmp_path))
    assert sorted(found) == sorted(litter)
    rep = store.gc(keep_last=5, apply=True)
    assert sorted(rep.stale_temps) == sorted(litter)
    for p in litter:
        assert not os.path.exists(p)
    assert os.path.exists(alive)  # its owner (us) is still running
    os.unlink(alive)


def test_cli_chain_and_delta_verify(tmp_path, capsys):
    _g, store, paths, _states = _plant_chain(tmp_path, n_deltas=2)
    assert resilience._main(["verify", paths[-1]]) == 0
    out = capsys.readouterr().out
    assert "chain of 3" in out
    assert resilience._main(["chain", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "keyframe" in out and out.count("delta") >= 2
    # break the middle link: chain prints CORRUPT + BROKEN, verify
    # of the head fails naming the link
    faults.flip_bit(paths[1], os.path.getsize(paths[1]) - 2, bit=0)
    assert resilience._main(["verify", paths[-1]]) == 1
    out = capsys.readouterr().out
    assert "CORRUPT" in out and os.path.basename(paths[1]) in out
    assert resilience._main(["chain", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "CORRUPT" in out and "BROKEN" in out
