"""Zero-stall serving: background AMR recommit + async checkpoint
saves (dccrg_tpu.background).

The pins: background-built plans are BITWISE identical to synchronous
builds across refine/unrefine/balance sequences; a finished plan
installs only at a step boundary (never mid-anything); a transaction
abort while a build is in flight discards it and leaves the live AND
snapshot generations bitwise untouched; a worker crash falls back to
the inline rebuild; ``DCCRG_ASYNC_SAVE=1`` checkpoints are bitwise
identical to synchronous saves with torn-write / preemption / GC-race
fault injection riding the existing FaultPlan sites; resumed runs
reconverge bitwise; and with both env flags unset nothing changes
(the negative pins).
"""

import hashlib
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from test_recommit import make_grid, plan_fingerprint

from dccrg_tpu import Grid, FaultPlan, MutationAbortedError, faults
from dccrg_tpu import checkpoint as checkpoint_mod
from dccrg_tpu import resilience, supervise, telemetry
from dccrg_tpu.supervise import (CheckpointStore, PreemptedError,
                                 SupervisedRunner, resume_latest)
from dccrg_tpu.txn import grid_state_bytes, grid_transaction

pytestmark = pytest.mark.bgrecommit


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("DCCRG_BG_RECOMMIT", raising=False)
    monkeypatch.delenv("DCCRG_ASYNC_SAVE", raising=False)
    telemetry.registry().reset()


def _kernel(cell, nbr, offs, mask, *extra):
    return {"v": cell["v"] + jnp.float32(0.01) * jnp.sum(
        jnp.where(mask, nbr["v"] - cell["v"][:, None], jnp.float32(0)),
        axis=1)}


def _seed(g):
    cells = g.plan.cells
    g.set("v", cells, (cells.astype(np.float64) % 29).astype(np.float32))
    g.update_copies_of_remote_neighbors()


def _step(g, n=1):
    g.run_steps(_kernel, ["v"], ["v"], n)


# -- bitwise plan parity ----------------------------------------------

def _adapt_balance_sequence(bg, monkeypatch, steps_between=0):
    """refine -> recommit -> balance -> unrefine, a fingerprint +
    state digest after every commit (bg mode flushes at a step
    boundary first)."""
    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1" if bg else "0")
    g = make_grid()
    _seed(g)
    out = []

    def flush():
        if steps_between and g.bg_pending():
            _step(g, steps_between)  # serve on the live plan first
        g.bg_install(wait=True)
        out.append(plan_fingerprint(g))

    for c in (1, 2, 3):
        g.refine_completely(c)
    g.stop_refining()
    flush()
    for c in g.plan.cells[:6]:
        g.refine_completely(int(c))
    g.stop_refining()
    flush()
    g.balance_load()
    out.append(plan_fingerprint(g))  # balance installs synchronously
    lvl = g.mapping.get_refinement_level(g.plan.cells)
    deepest = g.plan.cells[lvl == lvl.max()]
    g.unrefine_completely(int(deepest[0]))
    g.stop_refining()
    flush()
    return out


def test_bg_plan_parity_across_refine_unrefine_balance(monkeypatch):
    """THE tentpole pin: plans built on the background worker are
    bitwise identical — layout and every hood table — to synchronous
    builds, across refine/recommit/balance/unrefine epochs."""
    sync = _adapt_balance_sequence(False, monkeypatch)
    bg = _adapt_balance_sequence(True, monkeypatch)
    assert sync == bg


def test_bg_parity_with_serving_between(monkeypatch):
    """Stepping on the live plan while the worker builds changes
    nothing about the PLAN the swap installs."""
    sync = _adapt_balance_sequence(False, monkeypatch)
    bg = _adapt_balance_sequence(True, monkeypatch, steps_between=2)
    assert sync == bg


def test_bg_negative_pin(monkeypatch):
    """Env unset: stop_refining never leaves a pending build — the
    synchronous path, bitwise (trivially: it IS the same code)."""
    monkeypatch.delenv("DCCRG_BG_RECOMMIT", raising=False)
    g = make_grid()
    for c in (1, 2, 3):
        g.refine_completely(c)
    g.stop_refining()
    assert not g.bg_pending()


# -- swap-only-at-boundary --------------------------------------------

def test_swap_only_at_step_boundary(monkeypatch):
    """Between the adapt call and the next step boundary the grid
    serves the PREVIOUS (consistent) epoch — even when the build has
    long finished — and the boundary installs exactly once."""
    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1")
    g = make_grid()
    _seed(g)
    n_before = len(g.plan.cells)
    fp_before = plan_fingerprint(g)
    for c in (1, 2, 3):
        g.refine_completely(c)
    new_cells = g.stop_refining()
    assert g.bg_pending()
    g._bg_build.wait()  # finished, NOT installed
    assert g.bg_pending()
    assert len(g.plan.cells) == n_before  # old epoch still serving
    assert plan_fingerprint(g) == fp_before
    assert not np.isin(new_cells, g.plan.cells).any()
    _step(g)  # the boundary
    assert not g.bg_pending()
    assert len(g.plan.cells) == n_before + len(new_cells) - 3
    assert np.isin(new_cells, g.plan.cells).all()


def test_data_access_to_new_cells_is_a_boundary(monkeypatch):
    """The adapt-then-project pattern stays oblivious to deferral: a
    host data access naming a NEW child right after stop_refining is
    itself a swap boundary — the pending plan installs (blocking) and
    the access proceeds (examples/amr_advection.py runs unmodified
    under DCCRG_BG_RECOMMIT=1)."""
    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1")
    g = make_grid()
    _seed(g)
    for c in (1, 2, 3):
        g.refine_completely(c)
    new_cells = g.stop_refining()
    assert g.bg_pending()
    vals = g.get("v", new_cells[:4])  # needs the new epoch: installs
    assert not g.bg_pending()
    assert np.all(vals == 0.0)  # fresh children zero-initialized
    g.set("v", new_cells, np.ones(len(new_cells), dtype=np.float32))
    assert np.all(g.get("v", new_cells) == 1.0)


def test_fleet_quantum_boundary_polls(monkeypatch):
    """GridBatch.step is a swap point too (the fleet's step
    boundary): a scratch grid with no pending build steps unchanged —
    the poll is a no-op, pinned not to disturb the dispatch."""
    from dccrg_tpu.fleet import FleetJob, GridBatch

    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1")
    job = FleetJob("j0", length=(6, 6, 6), n_steps=4, seed=1,
                   params=(0.03,))
    batch = GridBatch(job, capacity=2)
    slot = batch.admit(job)
    assert not batch.grid.bg_pending()
    batch.step(np.array([2, 0], dtype=np.int32))
    assert batch.digest(slot)


# -- txn aborts + worker crashes --------------------------------------

def test_txn_abort_mid_build_discards_and_restores_bitwise(monkeypatch):
    """An abort while a background build is in flight: the pending
    build is discarded and the grid — live plan, snapshot plan, every
    field byte — is bitwise its pre-transaction self. The restored
    request sets then redo the adaptation to the same bitwise plan a
    synchronous build produces."""
    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1")
    want = _adapt_balance_sequence(False, monkeypatch)[0]
    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1")
    g = make_grid()
    _seed(g)
    for c in (1, 2, 3):
        g.refine_completely(c)
    before = grid_state_bytes(g)
    with pytest.raises(MutationAbortedError):
        with grid_transaction(g, op="outer"):
            g.stop_refining()
            assert g.bg_pending()  # submitted inside the transaction
            raise RuntimeError("abort with the build in flight")
    assert not g.bg_pending()  # discarded, worker joined
    assert grid_state_bytes(g) == before
    # the requests survived the rollback: the retry reconverges to
    # the synchronous build's exact plan
    g.stop_refining()
    g.bg_install(wait=True)
    assert plan_fingerprint(g) == want


def test_worker_crash_falls_back_to_inline(monkeypatch):
    """An injected fault inside the background build (the existing
    hybrid.recommit site) crashes the WORKER, not the run: the swap
    point rebuilds inline and the plan still equals the synchronous
    build bitwise."""
    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1")
    want = _adapt_balance_sequence(False, monkeypatch)[0]
    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1")
    g = make_grid()
    _seed(g)
    for c in (1, 2, 3):
        g.refine_completely(c)
    plan = FaultPlan(seed=3)
    plan.mutation_error(site="hybrid.recommit", phase="tables")
    with plan:
        g.stop_refining()
        g._bg_build.wait()
        assert g._bg_build.error is not None  # the worker crashed
        _step(g)  # boundary: inline fallback rebuild + install
    assert plan.fired("hybrid.recommit") == 1
    assert not g.bg_pending()
    assert plan_fingerprint(g) == want
    reg = telemetry.registry()
    assert reg.counter_total("dccrg_recommit_bg_errors_total") == 1


def test_swap_abort_leaves_live_epoch_bitwise(monkeypatch):
    """A fault during the deferred install (the existing
    grid.restructure site): the swap runs in its own transaction, so
    the step loop keeps its pre-swap epoch bitwise and the failure
    surfaces as MutationAbortedError at the boundary."""
    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1")
    g = make_grid()
    _seed(g)
    for c in (1, 2, 3):
        g.refine_completely(c)
    g.stop_refining()
    g._bg_build.wait()
    before = grid_state_bytes(g)
    plan = FaultPlan(seed=4)
    plan.mutation_error(site="grid.restructure", phase="planned")
    with plan, pytest.raises(MutationAbortedError):
        _step(g)
    assert grid_state_bytes(g) == before
    assert not g.bg_pending()
    _step(g, 2)  # the old epoch still serves


def test_balance_drains_pending_build_first(monkeypatch):
    """A mutation that cannot defer (balance must land staged data on
    the new plan) installs the pending build at its transaction entry
    — never two builds racing one arena."""
    monkeypatch.setenv("DCCRG_BG_RECOMMIT", "1")
    g = make_grid()
    _seed(g)
    for c in (1, 2, 3):
        g.refine_completely(c)
    g.stop_refining()
    assert g.bg_pending()
    g.balance_load()  # entry barrier installs, then rebalances
    assert not g.bg_pending()
    from dccrg_tpu import verify
    verify.verify_all(g, check_pins=False)


# -- async checkpoint saves -------------------------------------------

def _sha(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _mk_uniform(seed=0):
    g = (Grid(cell_data={"rho": jnp.float32, "aux": jnp.float32})
         .set_initial_length((6, 6, 2))
         .set_periodic(True, True, False)
         .set_load_balancing_method("block")
         .initialize())
    cells = g.plan.cells
    g.set("rho", cells, (cells.astype(np.float64) % 17).astype(np.float32))
    g.set("aux", cells, np.ones(len(cells), dtype=np.float32))
    g.update_copies_of_remote_neighbors()
    return g


def _rho_kernel(c, nbr, offs, mask):
    return {"rho": jnp.float32(0.5) * c["rho"] + jnp.float32(0.125)
            * jnp.sum(jnp.where(mask, nbr["rho"], jnp.float32(0)), axis=1)}


def _rho_step(grid, _i):
    grid.run_steps(_rho_kernel, ["rho"], ["rho"], 1)


def test_async_store_saves_bitwise_identical(monkeypatch, tmp_path):
    """Every file a DCCRG_ASYNC_SAVE=1 store publishes — keyframes,
    dirty-field deltas, their CRC sidecars — is bitwise identical to
    the synchronous store's, and the delta chain policy is unchanged
    (the parent link resolves synchronously)."""
    def run(async_on, d):
        monkeypatch.setenv("DCCRG_ASYNC_SAVE", "1" if async_on else "0")
        g = _mk_uniform()
        store = CheckpointStore(str(d), stem="j")
        for i in range(6):
            _rho_step(g, i)
            store.save(g, i + 1)
        store.drain()
        return {n: _sha(os.path.join(str(d), n))
                for n in sorted(os.listdir(str(d)))}

    sync = run(False, tmp_path / "sync")
    asy = run(True, tmp_path / "async")
    assert sync == asy
    assert any(n.endswith(".dcd") for n in sync)  # deltas exercised
    assert telemetry.registry().counter_total(
        "dccrg_ckpt_async_saves_total") == 6


def test_async_torn_write_surfaces_at_drain_and_recovers(monkeypatch,
                                                         tmp_path):
    """Torn-write fault injection through the existing
    checkpoint.write site with retries exhausted: the failure
    surfaces at the next drain barrier, the failed step's file never
    exists under its final name, the chain state resets (next save is
    a keyframe) and resume falls back to the last durable save."""
    monkeypatch.setenv("DCCRG_ASYNC_SAVE", "1")
    g = _mk_uniform()
    store = CheckpointStore(str(tmp_path), stem="j")
    store.save(g, 1)
    store.drain()  # save 1 durable BEFORE the fault plan arms
    plan = FaultPlan(seed=5)
    plan.io_error(times=3)  # all 3 attempts of ONE save
    with plan:
        _rho_step(g, 0)
        path2 = store.save(g, 2)
        with pytest.raises(OSError):
            store.drain()
    assert not os.path.exists(path2)
    assert store._parent is None  # nothing may chain to the failure
    assert g._ckpt_dirty is None  # conservative: next save keyframes
    _rho_step(g, 1)
    path3 = store.save(g, 3)
    store.drain()
    assert path3.endswith(".dc")  # keyframe, not a delta
    info = resume_latest(str(tmp_path), {"rho": jnp.float32,
                                         "aux": jnp.float32},
                         stem="j", load_balancing_method="block")
    assert info is not None and info.step == 3
    assert telemetry.registry().counter_total(
        "dccrg_ckpt_async_errors_total") == 1


def test_async_gc_race_drains_before_pruning(monkeypatch, tmp_path):
    """The GC-race pin: retention GC against a store with a write in
    flight passes the drain barrier first — it can never prune or
    misjudge a half-published save."""
    monkeypatch.setenv("DCCRG_ASYNC_SAVE", "1")
    g = _mk_uniform()
    store = CheckpointStore(str(tmp_path), stem="j")
    for i in range(4):
        _rho_step(g, i)
        store.save(g, i + 1, force_keyframe=True)
    # the 4th save may still be in flight: gc must drain, then keep
    # the newest verifying chain
    rep = store.gc(keep_last=1)
    assert not store.pending()
    kept = [p for _s, p in rep.kept]
    assert store.path_for(4) in kept
    assert resilience.verify_checkpoint(store.path_for(4)) == []


def test_async_runner_trip_rollback_reconverges(monkeypatch, tmp_path):
    """A NaN trip mid-run under DCCRG_ASYNC_SAVE=1: the rollback
    drains the in-flight write first, and the recovered run's final
    bytes equal the synchronous-mode run's exactly."""
    def run(async_on, d):
        monkeypatch.setenv("DCCRG_ASYNC_SAVE", "1" if async_on else "0")
        d.mkdir()
        g = _mk_uniform()
        plan = FaultPlan(seed=6)
        plan.nan_poison("rho", step=7)
        with plan:
            r = resilience.ResilientRunner(
                g, _rho_step, str(d / "c.dc"), checkpoint_every=3,
                check_every=1, backoff=0)
            r.run(12)
        return checkpoint_mod.state_digest(g), r.rollbacks

    sync = run(False, tmp_path / "s")
    asy = run(True, tmp_path / "a")
    assert sync == asy
    assert sync[1] == 1  # the trip actually happened


def test_async_preempt_emergency_save_then_resume_bitwise(monkeypatch,
                                                          tmp_path):
    """Preemption with async saves on: the periodic writer drains,
    the emergency keyframe is synchronous + CRC-verified, and the
    resumed run reconverges bitwise with an uninterrupted
    synchronous-mode run."""
    monkeypatch.setenv("DCCRG_ASYNC_SAVE", "0")
    ref = SupervisedRunner(_mk_uniform(), _rho_step,
                           str(tmp_path / "ref"), check_every=100,
                           checkpoint_every=3, backoff=0.0)
    ref.run(12)
    want = checkpoint_mod.state_digest(ref.grid)

    monkeypatch.setenv("DCCRG_ASYNC_SAVE", "1")
    sup = SupervisedRunner(_mk_uniform(), _rho_step,
                           str(tmp_path / "pre"), check_every=100,
                           checkpoint_every=3, backoff=0.0)
    plan = FaultPlan(seed=7)
    plan.preempt_signal(step=5)
    with plan, pytest.raises(PreemptedError) as ei:
        sup.run(12)
    assert ei.value.clean
    assert resilience.verify_checkpoint(ei.value.checkpoint) == []
    info = resume_latest(str(tmp_path / "pre"),
                         {"rho": jnp.float32, "aux": jnp.float32},
                         load_balancing_method="block")
    assert info is not None and not info.salvaged
    info.grid.update_copies_of_remote_neighbors()
    sup2 = SupervisedRunner(info.grid, _rho_step, str(tmp_path / "pre"),
                            check_every=100, checkpoint_every=3,
                            backoff=0.0, start_step=info.step)
    sup2.run(12)
    assert checkpoint_mod.state_digest(sup2.grid) == want


def test_async_negative_pin(monkeypatch, tmp_path):
    """Env unset: CheckpointStore.save never spawns a writer and
    never defers — the synchronous path byte-for-byte (it IS the same
    code), with no async counters touched."""
    monkeypatch.delenv("DCCRG_ASYNC_SAVE", raising=False)
    g = _mk_uniform()
    store = CheckpointStore(str(tmp_path), stem="j")
    store.save(g, 1)
    assert not store.pending()
    assert telemetry.registry().counter_total(
        "dccrg_ckpt_async_saves_total") == 0
