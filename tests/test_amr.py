"""AMR tests: the reference's tests/refine suite semantics.

Covers refine/unrefine/dont_refine/dont_unrefine requests, induced
(2:1) refinement, conflict resolution, data inheritance (the
tests/advection/adapter.hpp projection protocol), and structural
invariants after every commit.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu.grid import Grid
from dccrg_tpu.neighbors import verify_tiling


def make_grid(length=(4, 4, 4), max_lvl=2, n_dev=8, fields=None, hood=1):
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("dev",))
    return (
        Grid(cell_data=fields or {"v": jnp.float32})
        .set_initial_length(length)
        .set_maximum_refinement_level(max_lvl)
        .set_neighborhood_length(hood)
        .initialize(mesh)
    )


def test_refine_creates_children():
    g = make_grid((2, 2, 2), max_lvl=1)
    assert g.refine_completely(1)
    new = g.stop_refining()
    kids = g.mapping.get_all_children(np.uint64(1))
    np.testing.assert_array_equal(new, np.sort(kids))
    cells = g.get_cells()
    assert len(cells) == 7 + 8
    assert 1 not in cells
    verify_tiling(g.mapping, cells)
    # children on the parent's device
    owners = {g.get_process(int(k)) for k in kids}
    assert len(owners) == 1


def test_refine_request_validation():
    g = make_grid((2, 2, 2), max_lvl=1)
    assert not g.refine_completely(99)  # unknown
    g.refine_completely(1)
    g.stop_refining()
    kid = int(g.mapping.get_all_children(np.uint64(1))[0])
    assert g.mapping.get_refinement_level(np.uint64(kid)) == 1
    assert not g.refine_completely(kid)  # already at max level
    assert not g.unrefine_completely(7)  # level-0 cell
    assert not g.unrefine_completely(12345)


def test_induced_refinement_2to1():
    """Refining twice in a corner forces neighbors to refine (the
    reference's induce_refines, dccrg.hpp:9730-9906)."""
    g = make_grid((4, 4, 4), max_lvl=2)
    g.refine_completely(1)
    g.stop_refining()
    # refine the corner child again: its coarse neighbors must follow
    kid = int(g.mapping.get_all_children(np.uint64(1))[0])
    g.refine_completely(kid)
    new = g.stop_refining()
    assert len(new) > 8  # induced refines happened
    cells = g.get_cells()
    verify_tiling(g.mapping, cells)
    # no neighbor pair differs by more than 1 level: neighbor engine
    # raises StructureError if 2:1 is violated, so building the plan
    # succeeded; double-check explicitly
    from dccrg_tpu.neighbors import build_neighbor_lists, make_neighborhood

    nl = build_neighbor_lists(g.mapping, g.topology, cells, make_neighborhood(1))
    lv = g.mapping.get_refinement_level(cells)
    nbr_lv = g.mapping.get_refinement_level(nl.of_neighbor)
    assert np.all(np.abs(lv[nl.of_source] - nbr_lv) <= 1)


def test_dont_refine_blocks_and_spreads():
    g = make_grid((4, 4, 4), max_lvl=2)
    g.refine_completely(1)
    g.stop_refining()
    kid = int(g.mapping.get_all_children(np.uint64(1))[0])
    # forbid refining a coarse neighbor of cell 1's region: cell 22?
    # choose the +x level-0 neighbor of cell 1: cell 2
    g.dont_refine(2)
    g.refine_completely(kid)
    g.stop_refining()
    # cell 2 must still exist unrefined
    assert 2 in g.get_cells()
    # and the inducing refine was cancelled if it would force cell 2;
    # kid's refinement would force its coarse neighbors (incl. 2's
    # region only if adjacent) — either way the grid stays valid
    verify_tiling(g.mapping, g.get_cells())


def test_unrefine_merges_siblings():
    g = make_grid((2, 2, 2), max_lvl=1)
    g.refine_completely(1)
    g.stop_refining()
    kids = g.mapping.get_all_children(np.uint64(1))
    assert g.unrefine_completely(int(kids[3]))
    g.stop_refining()
    removed = g.get_removed_cells()
    np.testing.assert_array_equal(removed, np.sort(kids))
    assert 1 in g.get_cells()
    assert len(g.get_cells()) == 8
    verify_tiling(g.mapping, g.get_cells())


def test_dont_unrefine_blocks():
    g = make_grid((2, 2, 2), max_lvl=1)
    g.refine_completely(1)
    g.stop_refining()
    kids = g.mapping.get_all_children(np.uint64(1))
    g.dont_unrefine(int(kids[0]))
    g.unrefine_completely(int(kids[3]))
    g.stop_refining()
    assert len(g.get_removed_cells()) == 0
    assert 1 not in g.get_cells()


def test_unrefine_blocked_by_refine():
    g = make_grid((2, 2, 2), max_lvl=2)
    g.refine_completely(1)
    g.stop_refining()
    kids = g.mapping.get_all_children(np.uint64(1))
    g.unrefine_completely(int(kids[0]))
    g.refine_completely(int(kids[0]))  # refine overrides the unrefine
    g.stop_refining()
    assert len(g.get_removed_cells()) == 0


def test_unrefine_blocked_by_fine_neighbor():
    """A sibling group cannot unrefine while a too-fine neighbor exists
    (dccrg.hpp:9935-10124)."""
    g = make_grid((2, 1, 1), max_lvl=2)
    g.refine_completely(1)
    g.refine_completely(2)
    g.stop_refining()
    # refine a child of cell 1 that touches cell 2's children
    kids1 = g.mapping.get_all_children(np.uint64(1))
    g.refine_completely(int(kids1[1]))  # +x child, faces cell 2's kids
    g.stop_refining()
    # now try to unrefine cell 2's children: their parent (2) would be
    # 2 levels away from kids1[1]'s children across the face
    kids2 = g.mapping.get_all_children(np.uint64(2))
    g.unrefine_completely(int(kids2[0]))
    g.stop_refining()
    assert len(g.get_removed_cells()) == 0
    verify_tiling(g.mapping, g.get_cells())


def test_data_inheritance_roundtrip():
    """The adapter.hpp protocol: children inherit the parent's value;
    unrefined parents average their children (adapter.hpp:229-301)."""
    g = make_grid((2, 2, 2), max_lvl=1)
    cells = g.get_cells()
    g.set("v", cells, np.arange(1, 9, dtype=np.float32) * 10)
    g.refine_completely(3)
    new = g.stop_refining()
    g.assign_children_from_parents(fields=["v"])
    np.testing.assert_allclose(g.get("v", new), np.full(8, 30.0))
    g.clear_refined_unrefined_data()

    # perturb children, then unrefine: parent gets the average
    g.set("v", new, np.arange(8, dtype=np.float32))
    g.unrefine_completely(int(new[0]))
    g.stop_refining()
    g.average_parents_from_children(fields=["v"])
    assert g.get("v", np.uint64(3)) == pytest.approx(np.arange(8).mean())
    # other cells kept their data across both restructures
    assert g.get("v", np.uint64(1)) == 10.0
    assert g.get("v", np.uint64(8)) == 80.0


def test_old_data_accessible_until_cleared():
    g = make_grid((2, 2, 2), max_lvl=1)
    g.set("v", np.uint64(5), 55.0)
    g.refine_completely(5)
    g.stop_refining()
    assert g.get_old_data("v", np.uint64(5))[0] == 55.0
    g.clear_refined_unrefined_data()
    with pytest.raises(KeyError):
        g.get_old_data("v", np.uint64(5))


def test_coordinate_variants():
    g = make_grid((4, 4, 4), max_lvl=1)
    g.set_geometry  # default NoGeometry: unit cells at origin
    assert g.refine_completely_at((0.5, 0.5, 0.5))
    new = g.stop_refining()
    assert len(new) == 8
    assert not g.refine_completely_at((-1.0, 0.0, 0.0))


def test_halo_exchange_after_amr():
    """Stencils and halo exchange keep working across structure epochs."""
    g = make_grid((4, 4, 1), max_lvl=1, n_dev=4)
    cells = g.get_cells()
    g.set("v", cells, np.ones(len(cells), dtype=np.float32))
    g.refine_completely(6)
    g.stop_refining()
    g.assign_children_from_parents()
    g.update_copies_of_remote_neighbors()
    # every ghost row holds the owner's value (1.0 for survivors)
    host = np.asarray(g.data["v"])
    for d in range(4):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            owner_dev, owner_row = g._host_rows(np.uint64(cid))
            expect = host[owner_dev[0], owner_row[0]]
            assert host[d, g.plan.L + r] == expect


def test_load_cells():
    g = make_grid((2, 2, 2), max_lvl=1)
    kids = g.mapping.get_all_children(np.uint64(8))
    target = np.sort(np.concatenate([np.arange(1, 8, dtype=np.uint64), kids]))
    g.load_cells(target)
    np.testing.assert_array_equal(g.get_cells(), target)
    with pytest.raises(Exception):
        g.load_cells(np.arange(1, 8, dtype=np.uint64))  # gap
