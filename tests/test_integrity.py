"""Silent-data-corruption (SDC) defense tests.

The acceptance pins of the integrity layer (dccrg_tpu/integrity.py):

- the fingerprint primitive is EXACT and order-independent, and the
  host, device and file-payload computations agree bit-for-bit;
- an injected FINITE bit-flip (invisible to the numerics watchdog by
  construction) is convicted as a CORRUPT trip — by the in-program
  invariants within one quantum, by the shadow-execution audit even
  with the invariants off, and by DMR replica comparison — with only
  the victim rolled back and every job reconverging bitwise to its
  solo digest;
- the NEGATIVE pin: with ``DCCRG_INTEGRITY=0`` and audits off the
  same flip goes undetected and the quantum program is the bitwise
  pre-SDC one (no fingerprint ops at all) — proving the defense, not
  luck, catches it;
- a repeat-offender device lane is quarantined and its survivors
  migrate bit-exactly;
- ``checkpoint.state_digest`` is gather-mode independent and stable
  across extract/insert round trips (the audit comparator assumes a
  mode-dependent digest can never raise a false alarm);
- ``python -m dccrg_tpu.resilience audit`` catches at-rest corruption
  sealed under an intact-looking CRC epoch.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dccrg_tpu import checkpoint as checkpoint_mod
from dccrg_tpu import faults, integrity, resilience
from dccrg_tpu.faults import FaultPlan
from dccrg_tpu.fleet import FleetJob, GridBatch, run_solo, template_grid
from dccrg_tpu.grid import Grid
from dccrg_tpu.scheduler import FleetScheduler

pytestmark = pytest.mark.sdc


# ---------------------------------------------------------------------
# fingerprint primitives
# ---------------------------------------------------------------------

def test_fingerprint_host_device_parity_and_order_independence():
    rng = np.random.default_rng(0)
    x = (rng.random((40, 3)) * 100).astype(np.float32)
    host = integrity.fingerprint_rows(x)
    dev = np.asarray(jax.jit(
        lambda a: integrity.device_fingerprint(a, 40))(jnp.asarray(x)))
    assert host == (int(dev[0]), int(dev[1]))
    # order-independent: any row permutation fingerprints equal
    perm = rng.permutation(40)
    assert integrity.fingerprint_rows(x[perm]) == host
    # sensitive: one flipped bit changes it
    y = x.copy()
    y[7, 1] = faults.flip_values(y[7:8, 1], 23)[0]
    assert integrity.fingerprint_rows(y) != host


def test_fingerprint_row_padding_non_word_dtypes():
    # 3-byte rows pad per ROW to word size, so cell alignment (and
    # with it order independence) survives odd dtypes
    x = np.arange(30, dtype=np.uint8).reshape(10, 3)
    a = integrity.fingerprint_rows(x)
    b = integrity.fingerprint_rows(x[::-1])
    assert a == b
    y = x.copy()
    y[4, 2] ^= 1
    assert integrity.fingerprint_rows(y) != a


def test_second_sum_sees_compensating_changes():
    # +d / -d on two words preserves the linear sum; the nonlinear
    # half-word product must still move (the reason s2 exists)
    x = np.array([[10.0], [20.0], [30.0]], dtype=np.float32)
    u = x.view(np.uint32)
    y = u.copy()
    y[0, 0] += 4096
    y[1, 0] -= 4096
    y = y.view(np.float32)
    s_x = integrity.fingerprint_rows(x)
    s_y = integrity.fingerprint_rows(y)
    assert s_x[0] == s_y[0]  # linear sum compensated
    assert s_x[1] != s_y[1]  # product sum convicts


def test_flip_values_always_finite():
    # includes values past the 1.5*v+1 overflow point (3e38) and the
    # affine-map fixed-point neighborhood, for EVERY exponent bit:
    # the finite guarantee is the fault class's defining contract
    v = np.array([0.0, 1.0, -2.0, -3.5, 3.0e38, -3.4e38, 1e-38],
                 dtype=np.float32)
    for bit in (0, 11, 22, 23, 27, 30):
        f = faults.flip_values(v, bit)
        assert np.isfinite(f).all(), (bit, f)
        assert (f != v).all(), (bit, f)


def test_conserved_registry_respects_periodicity():
    assert integrity.conserved_fields(
        "diffuse", (True, True, True), ("rho",)) == ("rho",)
    assert integrity.conserved_fields(
        "diffuse", (False, False, False), ("rho",)) == ("rho",)
    assert integrity.conserved_fields(
        "advect_x", (True, True, True), ("rho",)) == ("rho",)
    # upwind advection loses mass over a non-wrapping inflow boundary
    assert integrity.conserved_fields(
        "advect_x", (False, True, True), ("rho",)) == ()
    # callable kernels conserve nothing we can assume
    assert integrity.conserved_fields(
        lambda *a: None, (True, True, True), ("rho",)) == ()


# ---------------------------------------------------------------------
# fleet: in-program invariants, audits, DMR, quarantine
# ---------------------------------------------------------------------

def _jobs(count, steps=12, **kw):
    return [FleetJob(f"s{i:02d}", length=(8, 8, 8), n_steps=steps,
                     params=(0.02 + 0.004 * (i % 4),), seed=i,
                     checkpoint_every=4, **kw)
            for i in range(count)]


def _solo(specs):
    return {j.name: run_solo(FleetJob(
        j.name, length=j.length, kernel=j.kernel, n_steps=j.n_steps,
        params=j.params, seed=j.seed)) for j in specs}


def test_silent_flip_detected_within_one_quantum(tmp_path):
    """The flip lands after a dispatch; the post-dispatch fingerprint
    pass convicts it in the SAME quantum — before any checkpoint can
    seal the corrupt bytes — and only the victim replays."""
    specs = _jobs(6)
    solo = _solo(specs)
    plan = FaultPlan(seed=1)
    plan.silent_flip("rho", step=6, job="s03")
    with plan:
        sched = FleetScheduler(tmp_path, _jobs(6), quantum=4)
        report = sched.run()
    assert plan.fired("step.flip") == 1
    assert {n for n, r in report.items() if r["trips"]} == {"s03"}
    assert report["s03"]["sdc_trips"] == 1
    assert all(r["digest"] == solo[n] for n, r in report.items())
    assert sched.suspects[0] == 1


def test_corruption_between_quanta_detected(tmp_path):
    """Manually rotting a slot between dispatches (no FaultPlan, no
    finite violation) trips the entry-fingerprint continuity check at
    the next quantum."""
    specs = _jobs(3, steps=8)
    solo = _solo(specs)
    sched = FleetScheduler(tmp_path, _jobs(3, steps=8), quantum=2)
    # run one tick, corrupt a slot out-of-band, then drain
    sched._admit_pending()
    batch = next(b for bs in sched.buckets.values() for b in bs)
    sched._quantum(batch)
    sched.ticks += 1
    victim_slot, victim = batch.jobs[1]
    cell = int(batch.grid.plan.cells[5])
    batch.flip(victim_slot, "rho", [cell], 23)
    report = sched.run()
    assert report[victim.name]["sdc_trips"] >= 1
    assert {n for n, r in report.items() if r["trips"]} == {victim.name}
    assert all(r["digest"] == solo[n] for n, r in report.items())


def test_negative_pin_integrity_off_flip_undetected(tmp_path,
                                                    monkeypatch):
    """With DCCRG_INTEGRITY=0 and audits off the SAME flip sails
    through: no trips, a silently wrong answer, and the quantum
    program carries no fingerprint stage at all (no program change —
    the defense is the only thing that catches it)."""
    monkeypatch.setenv("DCCRG_INTEGRITY", "0")
    specs = _jobs(4)
    solo = _solo(specs)
    plan = FaultPlan(seed=2)
    plan.silent_flip("rho", step=6, job="s02")
    with plan:
        report = FleetScheduler(tmp_path, _jobs(4), quantum=4).run()
    assert plan.fired("step.flip") == 1
    assert all(r["status"] == "done" for r in report.values())
    assert all(r["trips"] == 0 for r in report.values())
    assert report["s02"]["digest"] != solo["s02"]  # silently wrong
    assert all(report[n]["digest"] == solo[n]
               for n in solo if n != "s02")
    # and the compiled program really has no integrity stage: the
    # batch publishes no invariants and refuses to fingerprint
    batch = GridBatch(specs[0], 4)
    batch.step(np.array([1, 0, 0, 0], dtype=np.int32))
    assert batch.last_inv is None
    with pytest.raises(RuntimeError, match="DCCRG_INTEGRITY"):
        batch.fingerprint_slots()


def test_shadow_audit_detects_with_invariants_off(tmp_path,
                                                  monkeypatch):
    """The sampled shadow re-execution is an independent detector: it
    convicts the flip even with the in-program invariants disabled
    (audits work by bitwise digest comparison, not fingerprints)."""
    monkeypatch.setenv("DCCRG_INTEGRITY", "0")
    specs = _jobs(4)
    solo = _solo(specs)
    # the audit SAMPLES: it convicts corruption that lands in the
    # audited slot's own window. Round-robin starts at slot 0 on tick
    # 0, so a flip in s00's first quantum is exactly what it sees.
    plan = FaultPlan(seed=3)
    plan.silent_flip("rho", step=2, job="s00")
    with plan:
        sched = FleetScheduler(tmp_path, _jobs(4), quantum=2,
                               audit_every=1)
        report = sched.run()
    assert plan.fired("step.flip") == 1
    assert sched.audits > 0
    assert sched.audit_failures >= 1
    assert report["s00"]["sdc_trips"] >= 1
    assert {n for n, r in report.items() if r["trips"]} == {"s00"}
    assert all(r["digest"] == solo[n] for n, r in report.items())


def test_shadow_audit_clean_run_no_false_alarms(tmp_path):
    specs = _jobs(5, steps=10)
    solo = _solo(specs)
    sched = FleetScheduler(tmp_path, _jobs(5, steps=10), quantum=2,
                           audit_every=1)
    report = sched.run()
    assert sched.audits > 0 and sched.audit_failures == 0
    assert all(r["trips"] == 0 for r in report.values())
    assert all(r["digest"] == solo[n] for n, r in report.items())


def test_audit_solo_path_when_batch_is_full(tmp_path):
    """With every slot occupied the audit re-executes through the solo
    Grid.run_steps path instead of a spare slot — and still agrees
    bitwise on a clean run (the fleet parity contract)."""
    specs = _jobs(4, steps=8)
    solo = _solo(specs)
    sched = FleetScheduler(tmp_path, _jobs(4, steps=8), quantum=2,
                           max_batch=4, audit_every=1)
    report = sched.run()
    assert sched.audits > 0 and sched.audit_failures == 0
    assert all(r["digest"] == solo[n] for n, r in report.items())


def test_dmr_redundancy_runs_clean_and_detects_flip(tmp_path):
    """redundancy=2: the replicas digest-compare every quantum. A
    clean run finishes with the solo digest (replication must not
    perturb the primary); a flip on the primary diverges the pair and
    convicts even with the in-program invariants off."""
    solo = _solo(_jobs(2, steps=8))
    report = FleetScheduler(
        tmp_path / "clean", _jobs(2, steps=8, redundancy=2),
        quantum=2).run()
    assert all(r["trips"] == 0 and r["digest"] == solo[n]
               for n, r in report.items())

    os.environ["DCCRG_INTEGRITY"] = "0"
    try:
        plan = FaultPlan(seed=4)
        plan.silent_flip("rho", step=3, job="s00")
        with plan:
            rep2 = FleetScheduler(
                tmp_path / "flip", _jobs(2, steps=8, redundancy=2),
                quantum=2).run()
    finally:
        del os.environ["DCCRG_INTEGRITY"]
    assert plan.fired("step.flip") == 1
    assert rep2["s00"]["sdc_trips"] >= 1
    assert rep2["s01"]["trips"] == 0
    assert all(rep2[n]["digest"] == solo[n] for n in solo)


def test_repeat_offender_lane_quarantined_and_migrated(tmp_path):
    """Two CORRUPT verdicts on one device lane quarantine it: every
    bucket instance rebuilds on the surviving lane with its admitted
    jobs migrated bit-exactly (final digests equal solo), and
    admission never returns to the quarantined lane."""
    dev = jax.devices()[0]
    specs = _jobs(8, steps=16)
    solo = _solo(specs)
    plan = FaultPlan(seed=5)
    plan.silent_flip("rho", step=5, job="s02")
    plan.silent_flip("rho", step=9, job="s04")
    with plan:
        sched = FleetScheduler(
            tmp_path, _jobs(8, steps=16), quantum=4,
            devices=[dev, dev], quarantine_after=2)
        report = sched.run()
    assert plan.fired("step.flip") == 2
    assert sched.quarantined == {0}
    assert sched.suspects[0] == 2
    # the survivors migrated mid-run and still reconverged bitwise
    assert all(r["status"] == "done" for r in report.values())
    assert all(r["digest"] == solo[n] for n, r in report.items())
    assert {n for n, r in report.items() if r["trips"]} == \
        {"s02", "s04"}
    # every live bucket now sits on the surviving lane
    for insts in sched.buckets.values():
        for b in insts:
            assert getattr(b, "lane", 0) == 1


def test_single_lane_cannot_be_quarantined(tmp_path):
    """With one device lane the threshold logs instead of quarantining
    — suspect answers beat failing the whole fleet."""
    plan = FaultPlan(seed=6)
    plan.silent_flip("rho", step=3, job="s00")
    plan.silent_flip("rho", step=7, job="s01")
    with plan:
        sched = FleetScheduler(tmp_path, _jobs(3, steps=12), quantum=4,
                               quarantine_after=2)
        report = sched.run()
    assert sched.quarantined == set()
    assert sched.suspects[0] == 2
    assert all(r["status"] == "done" for r in report.values())


# ---------------------------------------------------------------------
# state_digest determinism (the audit comparator's assumption)
# ---------------------------------------------------------------------

def _digest_under(monkeypatch, job, **env):
    for k in ("DCCRG_ROLL_STENCIL", "DCCRG_FORCE_TABLES"):
        monkeypatch.delenv(k, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    g = template_grid(job)
    job.apply_init(g)
    g.run_steps(job.resolved_kernel(), job.fields_in, job.fields_out,
                job.n_steps,
                extra_args=tuple(jnp.float32(p) for p in job.params))
    return checkpoint_mod.state_digest(g)


def test_state_digest_gather_mode_independent(monkeypatch):
    """roll-decomposed and dense-table gathers must produce the same
    digest for the same simulation — a mode-dependent digest would be
    a false SDC alarm in the audit comparator."""
    job = FleetJob("dig", length=(8, 8, 8), n_steps=6, params=(0.03,),
                   seed=9)
    roll = _digest_under(monkeypatch, job, DCCRG_ROLL_STENCIL="1")
    tables = _digest_under(monkeypatch, job, DCCRG_FORCE_TABLES="1",
                           DCCRG_ROLL_STENCIL="0")
    assert roll == tables


def test_state_digest_extract_insert_round_trip():
    """Slot bytes survive extract -> insert into a DIFFERENT slot (and
    the write_grid path) digest-identically."""
    job = FleetJob("rt", length=(8, 8, 8), n_steps=4, params=(0.03,),
                   seed=11)
    batch = GridBatch(job, 4)
    slot = batch.admit(job)
    batch.step(np.array([4, 0, 0, 0], dtype=np.int32))
    d0 = batch.digest(slot)
    moved = batch.extract(slot)
    batch.insert(2, moved)
    assert batch.digest(2) == d0
    g = batch.write_grid(slot)
    assert checkpoint_mod.state_digest(g) == d0


# ---------------------------------------------------------------------
# the solo runner + the at-rest audit
# ---------------------------------------------------------------------

def _mk_solo_grid(seed=0):
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((8, 8, 4))
         .set_periodic(True, True, True)
         .set_maximum_refinement_level(0)
         .set_neighborhood_length(1)
         .initialize())
    cells = g.plan.cells
    rng = np.random.default_rng(seed)
    g.set("v", cells, (rng.random(len(cells)) * 100).astype(np.float32))
    g.update_copies_of_remote_neighbors()
    return g


def _conserving_step(grid, i):
    grid.run_steps(
        lambda c, n, o, m: {"v": c["v"] + 0.02 * (
            jnp.sum(jnp.where(m, n["v"], 0.0), axis=1)
            - jnp.sum(m, axis=1).astype(c["v"].dtype) * c["v"])},
        ["v"], ["v"], 1)


def test_runner_convicts_silent_flip_and_reconverges(tmp_path):
    g_ref = _mk_solo_grid()
    ref = resilience.ResilientRunner(
        g_ref, _conserving_step, str(tmp_path / "ref.dc"),
        check_every=2, checkpoint_every=4, backoff=0.0,
        conserved_fields=("v",)).run(10)
    assert not ref.trips  # no false alarms across 10 steps
    ref_digest = checkpoint_mod.state_digest(g_ref)

    g = _mk_solo_grid()
    plan = FaultPlan(seed=7)
    plan.silent_flip("v", step=6)
    with plan:
        r = resilience.ResilientRunner(
            g, _conserving_step, str(tmp_path / "x.dc"),
            check_every=2, checkpoint_every=4, backoff=0.0,
            conserved_fields=("v",)).run(10)
    assert plan.fired("step.flip") == 1
    assert r.rollbacks >= 1
    assert "v" in r.trips[0]["fields"]
    assert checkpoint_mod.state_digest(g) == ref_digest


def test_runner_persistent_corruption_raises_integrity_error(tmp_path):
    """A flip that re-lands on every replay (a defective device, not
    a transient upset) exhausts the bounded retries as the typed
    IntegrityError — a ResilienceExhaustedError subclass, so generic
    handlers keep working."""
    g = _mk_solo_grid()
    plan = FaultPlan(seed=8)
    plan.silent_flip("v", step=6, times=10)
    with plan, pytest.raises(integrity.IntegrityError) as ei:
        resilience.ResilientRunner(
            g, _conserving_step, str(tmp_path / "p.dc"),
            check_every=2, checkpoint_every=4, backoff=0.0,
            max_retries=2, conserved_fields=("v",)).run(10)
    assert isinstance(ei.value, resilience.ResilienceExhaustedError)
    assert "v" in ei.value.details


def test_runner_without_conserved_fields_misses_the_flip(tmp_path):
    """The runner-level negative pin: no conserved_fields (or
    integrity off) means the finite flip goes unconvicted."""
    g_ref = _mk_solo_grid()
    resilience.ResilientRunner(
        g_ref, _conserving_step, str(tmp_path / "r.dc"),
        check_every=2, checkpoint_every=4, backoff=0.0).run(10)
    g = _mk_solo_grid()
    plan = FaultPlan(seed=7)
    plan.silent_flip("v", step=6)
    with plan:
        r = resilience.ResilientRunner(
            g, _conserving_step, str(tmp_path / "x.dc"),
            check_every=2, checkpoint_every=4, backoff=0.0).run(10)
    assert not r.trips
    assert checkpoint_mod.state_digest(g) != \
        checkpoint_mod.state_digest(g_ref)


def test_audit_record_written_and_clean(tmp_path):
    g = _mk_solo_grid()
    p = str(tmp_path / "a.dc")
    resilience.save_checkpoint(g, p)
    rec = resilience.read_sidecar(p)
    assert "integrity" in rec and "v" in rec["integrity"]
    rep = resilience.audit_checkpoint(p)
    assert rep is not None and rep["v"][0]
    assert resilience._main(["audit", p]) == 0


def test_audit_catches_sealed_at_rest_corruption(tmp_path, capsys):
    """A payload bit rots AND the chunk CRCs get regenerated (an
    intact-looking CRC epoch). verify passes; only the fingerprint —
    recorded from live device state at save time — convicts."""
    g = _mk_solo_grid()
    p = str(tmp_path / "a.dc")
    resilience.save_checkpoint(g, p)
    rec = resilience.read_sidecar(p)
    with open(p, "r+b") as f:
        f.seek(int(rec["payload_start"]) + 9)
        b = f.read(1)
        f.seek(int(rec["payload_start"]) + 9)
        f.write(bytes([b[0] ^ 8]))
    fresh = resilience._sidecar_record(p)
    fresh["integrity"] = rec["integrity"]
    resilience._write_sidecar_record(resilience.sidecar_path(p), fresh)
    assert resilience.verify_checkpoint(p) == []  # CRCs look intact
    rep = resilience.audit_checkpoint(p)
    assert not rep["v"][0]
    assert resilience._main(["audit", p]) == 1
    assert "SDC" in capsys.readouterr().out


def test_audit_no_record_reports_distinctly(tmp_path, monkeypatch):
    monkeypatch.setenv("DCCRG_INTEGRITY", "0")
    g = _mk_solo_grid()
    p = str(tmp_path / "n.dc")
    resilience.save_checkpoint(g, p)
    assert resilience.audit_checkpoint(p) is None
    assert resilience._main(["audit", p]) == 2


def test_delta_save_records_subset_fingerprint(tmp_path):
    """A dirty-field delta's sidecar fingerprints exactly the fields
    it stores, and audits clean."""
    from dccrg_tpu import supervise

    g = (Grid(cell_data={"v": jnp.float32,
                         "aux": ((4,), jnp.float32)})
         .set_initial_length((6, 6, 4))
         .set_periodic(True, True, True)
         .set_maximum_refinement_level(0)
         .set_neighborhood_length(1)
         .initialize())
    cells = g.plan.cells
    rng = np.random.default_rng(3)
    g.set("v", cells, (rng.random(len(cells)) * 10).astype(np.float32))
    g.set("aux", cells,
          (rng.random((len(cells), 4)) * 10).astype(np.float32))
    g.update_copies_of_remote_neighbors()
    store = supervise.CheckpointStore(tmp_path, stem="d")
    store.save(g, 0)
    _conserving_step(g, 0)
    g._ckpt_dirty = {"v"}
    path = store.save(g, 1)
    assert path.endswith(resilience.DELTA_SUFFIX)
    rec = resilience.read_sidecar(path)
    assert set(rec["integrity"]) == {"v"}
    rep = resilience.audit_checkpoint(path)
    assert rep["v"][0]


def test_fleet_fuzz_flip_scenario():
    """The fuzz oracle's SDC case (tier-1 seed): silent flip on a
    random victim, only-victim-convicted, all digests solo-bitwise."""
    from dccrg_tpu.fuzz import fleet_isolation_case

    out = fleet_isolation_case(1, fault="flip")
    assert out["trips"] >= 1
    assert out["report"][out["victim"]]["sdc_trips"] >= 1
