"""Overlapped fused steps (DCCRG_OVERLAP) must be bit-compatible with
the sequential exchange -> kernel path.

The overlap restructures compile_step_loop's step body: halo sends
launch first, the bulk kernel runs on pre-exchange state (inner rows
read no ghosts, so their results are final), and outer rows are redone
after the scatter — the reference's solve-inner-while-messages-fly
overlap (dccrg.hpp:5046-5413, tests/advection/2d.cpp:327-343) inside
one XLA program.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dccrg_tpu.grid import Grid, DEFAULT_NEIGHBORHOOD_ID


def _mk(monkeypatch, overlap, *, partition="block", force_tables=False,
        refine=False, periodic=(True, True, False)):
    monkeypatch.setenv("DCCRG_OVERLAP", "1" if overlap else "0")
    if force_tables:
        monkeypatch.setenv("DCCRG_FORCE_TABLES", "1")
    else:
        monkeypatch.delenv("DCCRG_FORCE_TABLES", raising=False)
    # 8x8x40 over 8 devices: block slabs 5 cells thick, so the outer
    # fraction (2 boundary planes of 5) stays under the overlap
    # heuristic's half-grid cutoff and the overlap genuinely engages
    g = (
        Grid(cell_data={"v": jnp.float32, "w": jnp.float32})
        .set_initial_length((8, 8, 40))
        .set_periodic(*periodic)
        .set_maximum_refinement_level(2 if refine else 0)
        .set_neighborhood_length(1)
        .initialize(partition=partition)
    )
    if refine:
        for cid in g.local_cells().ids[:6:2]:
            g.refine_completely(int(cid))
        g.stop_refining()
    cells = g.plan.cells
    rng = np.random.default_rng(7)
    g.set("v", cells, rng.random(len(cells)).astype(np.float32))
    g.set("w", cells, rng.random(len(cells)).astype(np.float32))
    g.update_copies_of_remote_neighbors()
    return g


def _engaged(g):
    hood = g.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    return getattr(hood, "_outer_host", None) is not None


def _kern(cell, nbr, offs, mask):
    s = jnp.sum(jnp.where(mask, nbr["v"], 0.0), axis=1)
    return {"v": 0.5 * cell["v"] + 0.125 * s}


def _kern2(cell, nbr, offs, mask):
    # non-power-of-two coefficients: the outer re-pass may fuse/round
    # differently than the bulk pass (FMA contraction differs between
    # the [W, S] and [L, S] layouts), so comparisons for THIS kernel
    # use tight allclose; the power-of-two kernels above stay bitwise
    sv = jnp.sum(jnp.where(mask, nbr["v"], 0.0), axis=1)
    sw = jnp.sum(jnp.where(mask, nbr["w"], 0.0), axis=1)
    return {"v": 0.5 * cell["v"] + 0.125 * sw,
            "w": 0.9 * cell["w"] + 0.05 * sv}


@pytest.mark.parametrize("partition", ["block", "morton", "rcb"])
def test_overlap_matches_sequential(monkeypatch, partition):
    results = []
    for ov in (False, True):
        g = _mk(monkeypatch, ov, partition=partition)
        g.run_steps(_kern, ["v"], ["v"], 5)
        if ov and partition == "block":
            assert _engaged(g), "overlap should engage on thick slabs"
        results.append(g.get("v", g.plan.cells))
    np.testing.assert_array_equal(results[0], results[1])


def test_overlap_matches_with_tables(monkeypatch):
    results = []
    for ov in (False, True):
        g = _mk(monkeypatch, ov, force_tables=True)
        g.run_steps(_kern, ["v"], ["v"], 5)
        if ov:
            assert _engaged(g), "overlap should engage in table mode"
        results.append(g.get("v", g.plan.cells))
    np.testing.assert_array_equal(results[0], results[1])


def test_overlap_matches_on_refined_grid(monkeypatch):
    """Hybrid (split-table) plans: hard rows rerun post-exchange too."""
    results = []
    for ov in (False, True):
        g = _mk(monkeypatch, ov, refine=True)
        g.run_steps(_kern, ["v"], ["v"], 4)
        results.append(g.get("v", g.plan.cells))
    np.testing.assert_array_equal(results[0], results[1])


def test_overlap_multi_field_exchange(monkeypatch):
    """Two exchanged fields, cross-coupled kernel."""
    for what in ("v", "w"):
        results = []
        for ov in (False, True):
            g = _mk(monkeypatch, ov)
            g.run_steps(_kern2, ["v", "w"], ["v", "w"], 4)
            results.append(g.get(what, g.plan.cells))
        np.testing.assert_allclose(results[0], results[1],
                                   rtol=2e-6, atol=2e-6)


def test_overlap_static_field(monkeypatch):
    """A static (non-exchanged) input field keeps its epoch ghosts."""
    def kern(cell, nbr, offs, mask):
        sw = jnp.sum(jnp.where(mask, nbr["w"], 0.0), axis=1)
        return {"v": cell["v"] + 0.015625 * sw * cell["w"]}

    results = []
    for ov in (False, True):
        g = _mk(monkeypatch, ov)
        g.run_steps(kern, ["v", "w"], ["v"], 3)
        results.append(g.get("v", g.plan.cells))
    np.testing.assert_allclose(results[0], results[1],
                               rtol=2e-6, atol=2e-6)


def test_overlap_odd_device_count(monkeypatch):
    """5 devices over 40 z-planes: uneven slabs, per-device outer
    widths differ — the padded outer tables must stay consistent."""
    from jax.sharding import Mesh

    results = []
    for ov in (False, True):
        monkeypatch.setenv("DCCRG_OVERLAP", "1" if ov else "0")
        g = (
            Grid(cell_data={"v": jnp.float32})
            .set_initial_length((8, 8, 40))
            .set_periodic(True, True, False)
            .set_maximum_refinement_level(0)
            .set_neighborhood_length(1)
            .initialize(Mesh(np.array(jax.devices()[:5]), ("dev",)),
                        partition="block")
        )
        cells = g.plan.cells
        rng = np.random.default_rng(11)
        g.set("v", cells, rng.random(len(cells)).astype(np.float32))
        g.update_copies_of_remote_neighbors()
        g.run_steps(_kern, ["v"], ["v"], 4)
        if ov:
            assert _engaged(g)
        results.append(g.get("v", cells))
    np.testing.assert_array_equal(results[0], results[1])


def test_overlap_nonperiodic(monkeypatch):
    results = []
    for ov in (False, True):
        g = _mk(monkeypatch, ov, periodic=(False, False, False))
        g.run_steps(_kern, ["v"], ["v"], 5)
        results.append(g.get("v", g.plan.cells))
    np.testing.assert_array_equal(results[0], results[1])


def test_overlap_honors_transfer_predicates(monkeypatch):
    """Predicate-filtered per-field pair tables feed the overlapped
    sends/scatters exactly as the sequential path's."""
    results = []
    for ov in (False, True):
        g = _mk(monkeypatch, ov)
        # block transfers of cells whose id is 0 mod 3
        g.set_transfer_predicate(
            "v", lambda ids, s, r, h: (ids % np.uint64(3)) != 0)
        g.update_copies_of_remote_neighbors()
        g.run_steps(_kern, ["v"], ["v"], 3)
        if ov:
            assert _engaged(g)
        results.append(g.get("v", g.plan.cells))
    np.testing.assert_array_equal(results[0], results[1])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_overlap_fuzz_random_structures(monkeypatch, seed):
    """Random dims / partition / refinement / steps: overlapped and
    sequential fused loops must agree bitwise (power-of-two kernel)."""
    rng = np.random.default_rng(100 + seed)
    dims = (int(rng.choice([4, 8])), int(rng.choice([4, 8])),
            int(rng.choice([24, 40])))
    part = str(rng.choice(["block", "morton", "rcb"]))
    per = bool(rng.integers(0, 2))
    refine = bool(rng.integers(0, 2))
    steps = int(rng.integers(2, 6))
    results = []
    for ov in (False, True):
        lrng = np.random.default_rng(1000 + seed)  # identical draws per leg
        monkeypatch.setenv("DCCRG_OVERLAP", "1" if ov else "0")
        g = (
            Grid(cell_data={"v": jnp.float32})
            .set_initial_length(dims)
            .set_periodic(per, per, False)
            .set_maximum_refinement_level(1 if refine else 0)
            .set_neighborhood_length(1)
            .initialize(partition=part)
        )
        if refine:
            cells = g.plan.cells
            for cid in cells[lrng.integers(0, len(cells), 3)]:
                g.refine_completely(int(cid))
            g.stop_refining()
        cells = g.plan.cells
        g.set("v", cells, lrng.random(len(cells)).astype(np.float32))
        g.update_copies_of_remote_neighbors()
        g.run_steps(_kern, ["v"], ["v"], steps)
        results.append(g.get("v", cells))
    np.testing.assert_array_equal(results[0], results[1])


def test_overlap_survives_balance(monkeypatch):
    """Partition changes rebuild the outer tables per epoch."""
    results = []
    for ov in (False, True):
        g = _mk(monkeypatch, ov)
        g.run_steps(_kern, ["v"], ["v"], 2)
        g.set_partitioning_option("method", "morton")
        g.balance_load()
        g.update_copies_of_remote_neighbors()
        g.run_steps(_kern, ["v"], ["v"], 2)
        results.append(g.get("v", g.plan.cells))
    np.testing.assert_array_equal(results[0], results[1])
