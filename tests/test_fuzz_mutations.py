"""Invariant-fuzzing chaos harness (dccrg_tpu/fuzz.py).

Tier-1 runs 25 distinct seeds x 40 ops each in the fast config, with
verify_all + numpy-oracle cross-checks after every op, plus
fault-injecting runs that abort mutations mid-flight and assert the
grid is bitwise either fully rolled back or fully committed. Long
runs live under the ``slow`` marker.
"""

import numpy as np
import pytest

from dccrg_tpu.fuzz import FuzzFailure, GridFuzzer
from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID

pytestmark = pytest.mark.fuzz


@pytest.mark.parametrize("seed", range(25))
def test_fuzz_seeded(seed):
    fz = GridFuzzer(seed, ops=40).run()
    assert fz.ops_run == 40


@pytest.mark.parametrize("seed", range(5))
def test_fuzz_fault_injecting(seed):
    """Mutations aborted mid-flight at random fault points must roll
    back bitwise and commit on retry (asserted inside the fuzzer)."""
    fz = GridFuzzer(seed, ops=25, fault_rate=0.6).run()
    assert fz.ops_run == 25


def test_fuzz_deeper_amr_and_devices():
    """A taller octree and a wider mesh in one tier-1 smoke run."""
    fz = GridFuzzer(7, ops=30, length=(4, 4, 4), max_lvl=2, n_dev=4).run()
    assert fz.ops_run == 30


def test_fuzz_is_deterministic():
    """Same seed + config => the identical op trail (the replay
    property every FuzzFailure report relies on)."""
    a = GridFuzzer(11, ops=15).run()
    b = GridFuzzer(11, ops=15).run()
    assert a.log == b.log


def test_planted_invariant_break_is_caught(monkeypatch):
    """A deliberately corrupted neighbor list must surface as a
    FuzzFailure naming the offending cells."""
    fz = GridFuzzer(3, ops=5).run()
    nl = fz.grid.plan.hoods[DEFAULT_NEIGHBORHOOD_ID].lists
    corrupted = nl.of_neighbor.copy()
    corrupted[0] = corrupted[1]
    monkeypatch.setattr(nl, "of_neighbor", corrupted)
    with pytest.raises(FuzzFailure) as ei:
        fz._check(99)
    assert ei.value.cells, "failure must name cells"
    assert ei.value.seed == 3 and ei.value.op_index == 99
    assert "cells" in str(ei.value)


def test_planted_data_corruption_is_caught():
    """A value written behind the oracle's back must trip the sweep."""
    fz = GridFuzzer(4, ops=5).run()
    victim = int(fz.grid.get_cells()[0])
    fz.grid.set("rho", [victim], np.asarray([123.0], dtype=np.float32))
    with pytest.raises(FuzzFailure) as ei:
        fz._check(99)
    assert victim in ei.value.cells


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(8))
def test_fuzz_long_runs(seed):
    fz = GridFuzzer(seed, ops=200, length=(4, 4, 4), max_lvl=2,
                    n_dev=4, fault_rate=0.25).run()
    assert fz.ops_run == 200
