"""Model zoo: multi-field MHD + wide-payload Vlasov workloads, the
per-field ghost-split exchange, and mixed-kernel fleet serving.

The acceptance pins of the zoo contract:

- both new models ride ``Grid.run_steps``, ``ResilientRunner``
  (rollback reconverges bitwise), per-job fleet checkpoints and the
  fuzz oracle with NO changes to those layers' public APIs;
- the per-field ghost-split overlap is bitwise identical to the full
  outer re-pass, recomputes strictly fewer outer row slots when a
  step exchanges a proper field subset (counted), and is opt-out
  (``DCCRG_GHOST_SPLIT=0`` = the pre-split program);
- jobs across >= 3 distinct kernels serve concurrently under one
  scheduler + SLO policy with per-slot fault isolation pinned
  bitwise vs solo runs, and a deadline job can shed a best-effort
  cohabitant from ANOTHER bucket on its lane (parked, resumed
  bitwise).
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from dccrg_tpu import checkpoint, faults, integrity, telemetry
from dccrg_tpu.fleet import (FLEET_KERNELS, FleetJob, _jobs_from_spec,
                             run_solo)
from dccrg_tpu.fuzz import GridFuzzer
from dccrg_tpu.models import available_models
from dccrg_tpu.models.mhd import (GridMHD, MHD_ALL, MHD_BFIELD,
                                  MHD_HYDRO, make_mhd_pass_kernels)
from dccrg_tpu.models.vlasov import (VLASOV_EXCHANGE, VLASOV_FIELDS,
                                     GridVlasov)
from dccrg_tpu.resilience import ResilientRunner
from dccrg_tpu.scheduler import FleetScheduler, SLOPolicy

pytestmark = pytest.mark.models


# -- the registry surface ---------------------------------------------

def test_zoo_registry_surface():
    zoo = {m["name"]: m for m in available_models()}
    assert {"mhd", "vlasov", "diffuse", "advect_x"} <= set(zoo)
    assert set(zoo["mhd"]["fields"]) == set(MHD_ALL)
    assert zoo["mhd"]["ghost_deps"]["bx"] == MHD_BFIELD
    assert zoo["mhd"]["ghost_deps"]["rho"] == MHD_HYDRO
    assert set(zoo["vlasov"]["conserved"]) == {"rho"}
    # registration happened on import: the fleet can name both
    assert "mhd" in FLEET_KERNELS and "vlasov" in FLEET_KERNELS
    assert integrity.conserved_fields(
        "mhd", (True, True, True), MHD_ALL) == MHD_ALL


def test_fleet_job_zoo_defaults():
    """A bare FleetJob naming a zoo kernel inherits its schema,
    field lists and default params from the registered spec."""
    j = FleetJob("z1", kernel="vlasov", length=(6, 6, 6), n_steps=4)
    assert set(j.cell_data) == set(VLASOV_FIELDS)
    assert j.cell_data["f"][0] != ()  # the wide payload
    assert j.fields_out == VLASOV_FIELDS
    j2 = FleetJob("z2", kernel="mhd", length=(6, 6, 6), n_steps=4)
    assert set(j2.cell_data) == set(MHD_ALL)
    # classic kernels keep the classic defaults
    j3 = FleetJob("z3", kernel="diffuse")
    assert set(j3.cell_data) == {"rho"} and j3.params == (0.1,)


# -- physics invariants -----------------------------------------------

def test_mhd_conservation():
    """Mass, momentum, energy and B totals are conserved by the blast
    run under full periodicity (the invariant surface the SDC defense
    registers)."""
    m = GridMHD(n=8)
    before = m.conserved_sums()
    m.run(6, dt=0.01)
    after = m.conserved_sums()
    n_cells = 8 ** 3
    for name in MHD_ALL:
        tol = integrity.sum_tolerance(before[name], n_cells, steps=6)
        assert abs(after[name] - before[name]) <= tol, (
            name, before[name], after[name], tol)
    # and the run actually did something
    assert after != before or m.time > 0


def test_vlasov_mass_conservation():
    v = GridVlasov(n=6, nv=12)
    m0 = v.total_mass()
    v.run(8, dt=0.04)
    m1 = v.total_mass()
    assert abs(m1 - m0) <= integrity.sum_tolerance(m0, 6 ** 3, steps=8)


# -- ResilientRunner: rollback reconverges bitwise --------------------

def _mhd_state(m):
    return b"".join(np.asarray(
        m.grid.get(n, m.grid.plan.cells)).tobytes() for n in MHD_ALL)


def test_mhd_resilient_runner_rollback_bitwise(tmp_path):
    import jax

    from dccrg_tpu.grid import default_mesh

    def mk():
        # single-device mesh: the rollback contract is mesh-agnostic
        # and the 8-field programs compile much faster unsharded
        m = GridMHD(n=6, mesh=default_mesh(jax.devices()[:1]))
        return m, lambda g, i: m.run(1, dt=0.01)

    ref, ref_step = mk()
    ResilientRunner(ref.grid, ref_step, str(tmp_path / "ref.dc"),
                    check_every=1, checkpoint_every=4, backoff=0.0,
                    diagnostics_dir=str(tmp_path)).run(10)

    inj, inj_step = mk()
    plan = faults.FaultPlan(seed=2)
    plan.nan_poison("rho", step=6)
    runner = ResilientRunner(inj.grid, inj_step, str(tmp_path / "i.dc"),
                             check_every=1, checkpoint_every=4,
                             backoff=0.0, diagnostics_dir=str(tmp_path))
    with plan:
        runner.run(10)
    assert runner.rollbacks == 1
    assert _mhd_state(inj) == _mhd_state(ref)


def test_vlasov_resilient_runner_rollback_bitwise(tmp_path):
    import jax

    from dccrg_tpu.grid import default_mesh

    def mk():
        v = GridVlasov(n=6, nv=10, mesh=default_mesh(jax.devices()[:1]))
        return v, lambda g, i: v.run(1, dt=0.04)

    ref, ref_step = mk()
    ResilientRunner(ref.grid, ref_step, str(tmp_path / "ref.dc"),
                    check_every=1, checkpoint_every=3, backoff=0.0,
                    diagnostics_dir=str(tmp_path)).run(8)
    inj, inj_step = mk()
    plan = faults.FaultPlan(seed=4)
    plan.nan_poison("f", step=5)
    runner = ResilientRunner(inj.grid, inj_step, str(tmp_path / "i.dc"),
                             check_every=1, checkpoint_every=3,
                             backoff=0.0, diagnostics_dir=str(tmp_path))
    with plan:
        runner.run(8)
    assert runner.rollbacks == 1
    for n in VLASOV_FIELDS:
        a = np.asarray(inj.grid.get(n, inj.grid.plan.cells))
        b = np.asarray(ref.grid.get(n, ref.grid.plan.cells))
        assert a.tobytes() == b.tobytes(), n


# -- per-field ghost-split overlap ------------------------------------

def _mhd_multidev(monkeypatch, split):
    """8x8x40 block slabs over the 8-device mesh: thick enough that
    the overlap heuristic engages (the test_overlap geometry)."""
    monkeypatch.setenv("DCCRG_OVERLAP", "1")
    monkeypatch.setenv("DCCRG_GHOST_SPLIT", "1" if split else "0")
    return GridMHD(n=8, nz=40)


def test_ghost_split_bitwise_and_strictly_fewer_rows(monkeypatch):
    """THE acceptance pin: split vs full outer re-pass is bitwise
    identical on the MHD model, and a step exchanging a proper field
    subset recomputes STRICTLY fewer outer row slots (counted). The
    split=False leg doubles as the negative pin: the pre-split
    program — full outer tables, full repass field set, no
    gsplit-keyed program anywhere."""
    digests, counts = {}, {}
    for split in (False, True):
        m = _mhd_multidev(monkeypatch, split)
        hydro, bpass = make_mhd_pass_kernels()
        lam = jnp.float32(0.01 * m.n)
        per_pass = []
        for kern, exch in ((hydro, MHD_HYDRO), (bpass, MHD_BFIELD)):
            m.grid.run_steps(kern, MHD_ALL, MHD_ALL, 5,
                             exchange_fields=exch, extra_args=(lam,))
            per_pass.append(dict(m.grid.last_overlap))
        digests[split] = checkpoint.state_digest(m.grid)
        counts[split] = per_pass
        if not split:
            # the negative pin: the opt-out compiled the pre-split
            # program (full repass set, no gsplit program keys)
            assert m.grid.last_overlap["repass_fields"] == MHD_ALL
            for key in m.grid._program_cache:
                assert not any(
                    isinstance(p, tuple) and p and p[0] == "gsplit"
                    for p in key if isinstance(p, tuple)), key
    assert digests[False] == digests[True]
    # split off: the full re-pass recomputes every field at every
    # outer row in both passes
    for ov in counts[False]:
        assert ov["mode"] == "full"
        assert ov["rows_split"] == ov["rows_full"] > 0
    # split on: each pass re-runs only its own subsystem's slots
    hydro_ov, b_ov = counts[True]
    assert hydro_ov["mode"] == "split" and b_ov["mode"] == "split"
    assert set(hydro_ov["repass_fields"]) == set(MHD_HYDRO)
    assert set(b_ov["repass_fields"]) == set(MHD_BFIELD)
    assert 0 < hydro_ov["rows_split"] < hydro_ov["rows_full"]
    assert 0 < b_ov["rows_split"] < b_ov["rows_full"]


def test_ghost_split_vlasov_parity_and_shared_fallback(monkeypatch):
    """Vlasov's declared deps cover every exchanged field at every
    outer row, so the split saves nothing — it must fall back to the
    SHARED pre-split program (mode 'full'), bitwise both ways."""
    digests = {}
    for split in (False, True):
        monkeypatch.setenv("DCCRG_OVERLAP", "1")
        monkeypatch.setenv("DCCRG_GHOST_SPLIT", "1" if split else "0")
        v = GridVlasov(n=8, nz=40, nv=8)
        v.run(4, dt=0.04)
        assert v.grid.last_overlap["mode"] == "full"
        digests[split] = checkpoint.state_digest(v.grid)
    assert digests[False] == digests[True]


def test_vlasov_wide_payload_never_exchanges(monkeypatch):
    """The ragged-Cell_Data contract: the wide [Nv] payload's ghost
    rows keep their stale bytes across stepped exchanges — only the
    moments move."""
    v = GridVlasov(n=8, nz=40, nv=8)
    g = v.grid
    L = g.plan.L

    def ghost_bytes(name):
        host = np.asarray(g.data[name])
        return b"".join(
            host[d, L:L + len(g.plan.ghost_ids[d])].tobytes()
            for d in range(g.n_dev))

    f_before = ghost_bytes("f")
    rho_before = ghost_bytes("rho")
    v.run(4, dt=0.04)
    assert ghost_bytes("f") == f_before          # payload stayed local
    assert ghost_bytes("rho") != rho_before      # moments moved
    # a full exchange DOES move it (the bytes were genuinely stale)
    g.update_copies_of_remote_neighbors(fields=("f",))
    assert ghost_bytes("f") != f_before


# -- Poisson fused-CG split-overlap -----------------------------------

def test_poisson_fused_cg_split_overlap_bitwise(monkeypatch):
    """The fused-CG matvec under the split-overlap treatment (halo
    started, bulk matvec on pre-exchange state, refreshed rows
    redone) converges to the bitwise-identical solution in the same
    iteration count as the sequential pre-split program."""
    from dccrg_tpu.models.poisson import PoissonSolver

    out = {}
    for split in (False, True):
        monkeypatch.setenv("DCCRG_OVERLAP", "1")
        monkeypatch.setenv("DCCRG_GHOST_SPLIT", "1" if split else "0")
        s = PoissonSolver(length=(8, 8, 8), dtype=jnp.float64)
        s.set_rhs_from(
            lambda x, y, z: np.cos(2 * np.pi * x / 8)
            + np.sin(2 * np.pi * y / 8))
        s.solve(rtol=1e-8)
        keys = [k for k in s.grid._program_cache
                if k[0] == "poisson_fused"]
        assert [k[-1] for k in keys] == [split]  # engaged iff split
        out[split] = np.asarray(s.solution())
    assert out[False].tobytes() == out[True].tobytes()


# -- mixed-kernel fleet serving ---------------------------------------

def _zoo_jobs():
    return [FleetJob(f"{k}{i}", kernel=k, length=(6, 6, 6), n_steps=10,
                     seed=17 * i + 3, checkpoint_every=4)
            for k in ("advect_x", "mhd", "vlasov") for i in range(2)]


def _solo_digests(jobs):
    return {j.name: run_solo(FleetJob(
        j.name, kernel=j.kernel, length=j.length, n_steps=j.n_steps,
        seed=j.seed)) for j in jobs}


def test_mixed_kernel_fleet_isolation(tmp_path):
    """THE serving-diversity pin: advection + MHD + Vlasov jobs in
    ONE scheduler run (three distinct buckets), an injected NaN in
    the MHD victim — only the victim trips, and EVERY job's digest is
    bitwise its solo run's."""
    jobs = _zoo_jobs()
    solo = _solo_digests(jobs)
    victim = "mhd1"
    plan = faults.FaultPlan(seed=5)
    plan.nan_poison("rho", step=4, job=victim)
    with plan:
        report = FleetScheduler(str(tmp_path), jobs, quantum=4).run()
    assert plan.fired("step.poison") == 1
    assert len({j.bucket_key() for j in jobs}) == 3
    for j in jobs:
        row = report[j.name]
        assert row["status"] == "done"
        assert row["digest"] == solo[j.name], j.name
        if j.name != victim:
            assert not row["trips"], (j.name, row["trips"])
    assert report[victim]["trips"] >= 1


def test_mixed_kernel_fleet_checkpoint_resume(tmp_path):
    """Per-job fleet checkpoints work on the new schemas out of the
    box — the wide Vlasov field included: a fleet stopped after two
    ticks resumes in a FRESH scheduler over the same dir and every
    job still converges bitwise to its solo run."""
    jobs = [FleetJob(f"r_{k}", kernel=k, length=(6, 6, 6), n_steps=10,
                     seed=23, checkpoint_every=4)
            for k in ("advect_x", "mhd", "vlasov")]
    solo = _solo_digests(jobs)
    FleetScheduler(str(tmp_path), jobs, quantum=2).run(max_ticks=2)
    resumed = [FleetJob(j.name, kernel=j.kernel, length=j.length,
                        n_steps=j.n_steps, seed=j.seed,
                        checkpoint_every=4) for j in jobs]
    report = FleetScheduler(str(tmp_path), resumed, quantum=4,
                            resume=True).run()
    for j in resumed:
        assert report[j.name]["status"] == "done"
        assert report[j.name]["digest"] == solo[j.name], j.name


def test_mixed_kernel_lane_slo_shed(tmp_path):
    """A deadline MHD job whose LANE latency (the advect cohabitant
    bucket dispatches every tick too) projects past its SLO sheds the
    best-effort advect job out of the OTHER bucket: parked with a
    keyframe, resumed after the deadline job finishes, both bitwise
    equal to their solo runs."""
    jobs = [FleetJob("be_adv", kernel="advect_x", length=(6, 6, 6),
                     n_steps=12, seed=1, checkpoint_every=4),
            FleetJob("slo_mhd", kernel="mhd", length=(6, 6, 6),
                     n_steps=12, seed=2, checkpoint_every=4,
                     slo_ms=100.0)]
    solo = _solo_digests(jobs)
    base = telemetry.registry().counter_total(
        "dccrg_fleet_lane_sheds_total")
    pol = SLOPolicy(quantum=4, clock=lambda: 0.0)
    sched = FleetScheduler(str(tmp_path), jobs, quantum=4,
                           slo_policy=pol)
    sched._admit_pending()
    batches = [b for bs in sched.buckets.values() for b in bs]
    assert len(batches) == 2  # two kernels -> two buckets, one lane
    # hand-fed: 20 ms/quantum each; 3 remaining quanta x 40 ms lane
    # latency blows the 100 ms budget, own-bucket 60 ms does not
    for b in batches:
        pol.observe(b.key, 0.02)
    sched._shed_for_lane()
    by_name = {j.name: j for j in jobs}
    assert by_name["be_adv"].status == "parked"
    assert by_name["slo_mhd"].status == "running"
    assert telemetry.registry().counter_total(
        "dccrg_fleet_lane_sheds_total") - base == 1
    report = sched.run()
    for j in jobs:
        assert report[j.name]["status"] == "done"
        assert report[j.name]["digest"] == solo[j.name], j.name
    assert report["slo_mhd"]["slo_met"] is True


def test_lane_shed_negative_pin_without_slo(tmp_path):
    """No SLO jobs -> the lane-shed pass never parks anything,
    whatever the measured latencies (mixed-kernel fleets without
    deadlines keep the exact pre-PR behavior)."""
    jobs = [FleetJob("a", kernel="advect_x", length=(6, 6, 6),
                     n_steps=6, seed=1),
            FleetJob("m", kernel="mhd", length=(6, 6, 6),
                     n_steps=6, seed=2)]
    pol = SLOPolicy(quantum=4, clock=lambda: 0.0)
    sched = FleetScheduler(str(tmp_path), jobs, quantum=4,
                           slo_policy=pol)
    sched._admit_pending()
    for bs in sched.buckets.values():
        for b in bs:
            pol.observe(b.key, 99.0)
    sched._shed_for_lane()
    assert not sched._parked
    assert all(j.status == "running" for j in jobs)


def test_fleet_sdc_fingerprints_cover_wide_field(tmp_path):
    """The integrity layer fingerprints the wide [Nv] float32 field:
    a FINITE silent flip in the Vlasov payload convicts as a CORRUPT
    trip and the victim still converges to its solo digest."""
    jobs = [FleetJob(f"vl{i}", kernel="vlasov", length=(6, 6, 6),
                     n_steps=10, seed=5 + i, checkpoint_every=3)
            for i in range(3)]
    solo = _solo_digests(jobs)
    plan = faults.FaultPlan(seed=9)
    plan.silent_flip("f", step=5, job="vl1")
    with plan:
        report = FleetScheduler(str(tmp_path), jobs, quantum=3).run()
    assert plan.fired("step.flip") == 1
    assert report["vl1"]["sdc_trips"] >= 1
    for j in jobs:
        assert report[j.name]["status"] == "done"
        assert report[j.name]["digest"] == solo[j.name], j.name
        if j.name != "vl1":
            assert not report[j.name]["trips"]


# -- fuzz + CLI surfaces ----------------------------------------------

def test_mhd_schema_fuzz_leg():
    """The MHD-schema GridFuzzer leg: txn/fault mutation sites over
    the 8-field schema, with the multi-field exchange op exercising
    random ``fields=`` subsets against the ghost oracle."""
    fz = GridFuzzer(11, ops=12, schema="mhd", fault_rate=0.3).run()
    assert fz.ops_run == 12
    assert fz.schema == "mhd"


def test_jobs_from_spec_names_zoo_kernels(tmp_path):
    """A CLI job file can name any zoo kernel without spelling out
    its schema; the scheduler serves it to completion."""
    spec = {"jobs": [
        {"name": "jm", "kernel": "mhd", "n": 6, "steps": 4},
        {"name": "jv", "kernel": "vlasov", "n": 6, "steps": 4},
        {"name": "jd", "kernel": "diffuse", "n": 6, "steps": 4},
    ]}
    jobs = _jobs_from_spec(spec)
    assert set(jobs[0].cell_data) == set(MHD_ALL)
    assert "f" in jobs[1].cell_data
    assert jobs[2].params == (0.1,)  # the classic default held
    report = FleetScheduler(str(tmp_path), jobs, quantum=4).run()
    assert all(r["status"] == "done" for r in report.values())
    json.dumps({n: r["digest"] for n, r in report.items()})  # sane
