"""Pallas advection kernel tests.

The kernel always runs: on a TPU it runs natively; on the CPU test
mesh it runs under Pallas's TPU interpret mode
(``pltpu.InterpretParams`` — DMA copies, semaphores and the grid
pipeline are emulated on host), so CI exercises the real kernel body,
not just the pure-numpy mirror of tests/advection/solve.hpp it is
checked against. Interpret mode is slow, so CPU runs use a smaller
grid than the TPU runs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def reference_upwind(rho, x, dt, dx):
    """Numpy mirror of the reference flux math (solve.hpp:44-279) on a
    uniform periodic x/y grid with the rotation field."""
    N = rho.shape[0]
    Z = rho.shape[2]
    out = rho.copy()
    vx = np.broadcast_to((0.5 - x)[None, :, None], rho.shape)
    vy = np.broadcast_to((x - 0.5)[:, None, None], rho.shape)
    for d, v in ((0, vx), (1, vy)):
        vp = np.roll(v, -1, axis=d)
        vm = np.roll(v, 1, axis=d)
        rp = np.roll(rho, -1, axis=d)
        rm = np.roll(rho, 1, axis=d)
        fh = 0.5 * (v + vp)
        fl = 0.5 * (vm + v)
        fh = fh * np.where(fh >= 0, rho, rp)
        fl = fl * np.where(fl >= 0, rm, rho)
        out = out + (fl - fh) * dt / dx
    return out


def on_tpu():
    try:
        return jax.devices()[0].platform not in ("cpu",)
    except Exception:
        return False


# native on TPU; interpreted (smaller grid) on the CPU test mesh
INTERPRET = not on_tpu()


@pytest.mark.parametrize("steps_per_pass", [1, 2, 4, 7])
def test_pallas_matches_reference_math(steps_per_pass):
    from dccrg_tpu.ops.advection_kernel import make_rotation_step

    N = 32 if INTERPRET else 128
    Z = 128
    dx = 1.0 / N
    x = (np.arange(N) + 0.5) * dx
    rho = np.random.default_rng(0).random((N, N, Z)).astype(np.float32)
    dt = np.float32(0.3 * dx)
    vxf = (0.5 - x).astype(np.float32)[None, :]
    vy = (x - 0.5).astype(np.float32)
    vyx = np.concatenate([vy[-8:], vy, vy[:8]])[:, None]
    step = make_rotation_step(
        (N, N, Z), steps_per_pass=steps_per_pass, tile=(8, 128),
        interpret=INTERPRET,
    )
    got = np.asarray(step(jnp.asarray(rho), jnp.asarray(vxf), jnp.asarray(vyx), dt))
    want = rho
    for _ in range(steps_per_pass):
        want = reference_upwind(want, x, dt, dx)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pallas_solver_l2_parity():
    """The fast path must match the general dense path's physics: same
    L2 error vs the analytic rotated hump."""
    from dccrg_tpu.models.advection import PallasRotationAdvection, analytic_density

    n, nz, passes = (64, 128, 16) if not INTERPRET else (32, 128, 4)
    s = PallasRotationAdvection(n=n, nz=nz, steps_per_pass=4, interpret=INTERPRET)
    dt = 0.5 * s.max_time_step()
    for _ in range(passes):
        s.step(dt)
    x = (np.arange(n) + 0.5) / n
    exact = np.asarray(
        analytic_density(x[:, None, None], x[None, :, None], s.time)
    ) * np.ones((1, 1, nz))
    err = float(np.sqrt(np.mean((np.asarray(s.rho, dtype=np.float64) - exact) ** 2)))
    # the coarser interpret config (n=32, 4 passes) is more diffusive
    assert err < (0.05 if INTERPRET else 0.03), err


def test_pallas_bfloat16_storage():
    """The kernel's weakly-typed flux arithmetic keeps bfloat16 state
    narrow end-to-end; the diffusive first-order physics must survive
    the coarser rounding."""
    import jax.numpy as jnp
    from dccrg_tpu.models.advection import PallasRotationAdvection, analytic_density

    n, nz = 32, 128
    s = PallasRotationAdvection(n=n, nz=nz, dtype=jnp.bfloat16,
                                steps_per_pass=4, interpret=INTERPRET)
    assert s.rho.dtype == jnp.bfloat16
    dt = 0.5 * s.max_time_step()
    m0 = float(jnp.sum(s.rho.astype(jnp.float32)))
    for _ in range(4):
        s.step(dt)
    assert s.rho.dtype == jnp.bfloat16  # stayed narrow through steps
    m1 = float(jnp.sum(s.rho.astype(jnp.float32)))
    assert abs(m1 - m0) < 3e-2 * max(m0, 1.0)
    x = (np.arange(n) + 0.5) / n
    exact = np.asarray(
        analytic_density(x[:, None, None], x[None, :, None], s.time)
    ) * np.ones((1, 1, nz))
    err = float(np.sqrt(np.mean((np.asarray(s.rho, dtype=np.float64) - exact) ** 2)))
    assert err < 0.08, err
