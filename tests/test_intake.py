"""Durable streaming-intake tests (dccrg_tpu/intake.py).

Everything here is tier-1: fake clock, in-memory KV, single process.
The exactly-once admission claim under a REAL kill -9 between spool
claim and scheduler add is proven by the ``intake_kill`` scenario in
tests/mp_harness.py (run via tests/ci_mp_leg.sh); this file proves
the same protocol with an in-process injected death, plus the retry/
quarantine envelope, the backpressure gate's hysteresis, tenant
shaping, the journaled graceful shed, and the decision-journal
replay property. The negative pin: a scheduler constructed without
an intake (and without ``DCCRG_INTAKE=1``) has ``sched.intake is
None`` and takes zero new branches.
"""

import json
import os

import pytest

from dccrg_tpu import coord, faults, fleet, intake, telemetry
from dccrg_tpu.autopilot import RULES, Autopilot, read_journal, replay
from dccrg_tpu.fleet import (FleetJob, JobSpecError, UnknownKernelError,
                             job_from_row, run_solo)
from dccrg_tpu.intake import IntakeError, StreamIntake, submit
from dccrg_tpu.scheduler import FleetScheduler

pytestmark = pytest.mark.intake


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Intake knobs out of the env, a fresh telemetry registry, and
    both again on the way out (the registry is process-global)."""
    for var in ("DCCRG_INTAKE", "DCCRG_INTAKE_SPOOL",
                "DCCRG_INTAKE_RETRIES", "DCCRG_INTAKE_BACKOFF_S",
                "DCCRG_INTAKE_BACKOFF_CAP_S", "DCCRG_INTAKE_AGE_S",
                "DCCRG_TENANT_RATE", "DCCRG_TENANT_WEIGHT",
                "DCCRG_TENANT_BURST", "DCCRG_AUTOPILOT",
                "DCCRG_DECISION_FILE"):
        monkeypatch.delenv(var, raising=False)
    telemetry.registry().reset()
    yield
    telemetry.registry().reset()


def _row(name, steps=4, **kw):
    d = {"name": name, "n": 8, "steps": steps,
         "checkpoint_every": 4}
    d.update(kw)
    return d


class _Env:
    """One spool + shared KV + fake clock + N (intake, scheduler)
    pairs — the in-process fleet the admission protocol runs on."""

    def __init__(self, tmp_path, ranks=1, **intake_kw):
        self.spool = str(tmp_path / "spool")
        self.kv = coord.InMemoryKV()
        self.t = [0.0]
        self.pairs = []
        kw = dict(lease_s=1.0, window_s=0.5, poll_s=0.0,
                  backoff_s=0.01, backoff_cap_s=0.05)
        kw.update(intake_kw)
        for r in range(ranks):
            it = StreamIntake(self.spool, kv=self.kv, rank=r,
                              clock=lambda: self.t[0], **kw)
            sched = FleetScheduler(str(tmp_path / f"ck{r}"),
                                   quantum=4, intake=it)
            self.pairs.append((it, sched))

    def submit(self, row, **kw):
        return submit(self.spool, row, **kw)

    def tick(self, dt=0.1):
        self.t[0] += dt


# -- exactly-once admission ------------------------------------------

def test_submit_pump_run_admits_exactly_once(tmp_path):
    """The happy path end to end: a spool record is claimed, added,
    served to completion, finalized (done marker, spool archive,
    journal GC, lease released)."""
    env = _Env(tmp_path)
    it, sched = env.pairs[0]
    env.submit(_row("j1"), tenant="acme")
    assert it.pump()["admitted"] == 1
    env.tick()
    report = sched.run(max_ticks=100)
    assert report["j1"]["status"] == "done"
    env.tick()
    it.pump()  # the finalize pass
    assert it.idle()
    assert env.kv.get("dccrg/intake/done/j1") == "admitted:0"
    assert env.kv.get("dccrg/intake/journal/j1") is None
    assert not it.leases.owned
    assert os.path.exists(os.path.join(env.spool, "admitted",
                                       "j1.json"))
    # the bitwise-solo pin: streaming admission changes WHEN a job
    # runs, never what it computes
    solo = run_solo(FleetJob("j1", length=(8, 8, 8), n_steps=4,
                             checkpoint_every=4))
    assert report["j1"]["digest"] == solo
    assert (telemetry.registry().counter_total(
        "dccrg_intake_admitted_total", tenant="acme") == 1)


def test_duplicate_name_resubmission_deduped_by_done_marker(tmp_path):
    """Re-submitting a finished job under the same name archives the
    duplicate without a second admission."""
    env = _Env(tmp_path)
    it, sched = env.pairs[0]
    env.submit(_row("j1"))
    it.pump()
    sched.run(max_ticks=100)
    env.tick()
    it.pump()
    assert env.kv.get("dccrg/intake/done/j1") is not None
    env.submit(_row("j1"))
    env.tick()
    stats = it.pump()
    assert stats["admitted"] == 0 and it.deduped == 1


def test_same_content_different_name_deduped_by_nonce(tmp_path):
    """The content nonce (CAS ``nonce/`` key) rejects the same spec
    submitted under two names — the retried-submitter double-fire."""
    env = _Env(tmp_path)
    it, sched = env.pairs[0]
    nonce = intake.record_nonce(_row("j1"), "default")
    env.submit(_row("j1"), nonce=nonce)
    env.submit(_row("j2"), nonce=nonce)  # a renamed duplicate
    it.pump()
    assert it.admitted == 1 and it.deduped == 1
    assert "j2" not in sched._by_name


def test_kill_between_claim_and_add_reclaimed_exactly_once(tmp_path):
    """The tentpole protocol in-process: rank 0 dies at the
    ``intake.claim`` site (lease held, journal written, job NOT yet
    added); rank 1 reclaims after lease expiry with the epoch-fenced
    CAS and re-admits from the journal record; the job runs exactly
    once and the decision journal replays clean."""
    env = _Env(tmp_path, ranks=2)
    it0, _s0 = env.pairs[0]
    it1, s1 = env.pairs[1]
    env.submit(_row("j1"))
    plan = faults.FaultPlan()
    plan.intake_death(rank=0)
    with plan:
        with pytest.raises(faults.InjectedRankDeath):
            it0.pump()
    # the half-admitted state a SIGKILL leaves behind
    assert env.kv.get("dccrg/intake/j1") is not None
    assert env.kv.get("dccrg/intake/journal/j1") is not None
    assert "j1" not in s1._by_name
    # before expiry the survivor must NOT steal the admission
    it1.pump()
    assert it1.reclaimed == 0 and "j1" not in s1._by_name
    env.tick(1.5)  # past lease_s=1.0
    stats = it1.pump()
    assert stats["reclaimed"] == 1
    assert "j1" in s1._by_name
    report = s1.run(max_ticks=200)
    assert report["j1"]["status"] == "done"
    env.tick()
    it1.pump()
    assert env.kv.get("dccrg/intake/done/j1") == "admitted:1"
    assert it1.idle() and it1.admitted == 1


def test_reclaim_respects_membership_liveness(tmp_path):
    """An attached membership vetoes reclaim while the claimant is
    merely SUSPECT — only DEAD releases the admission."""
    kv = coord.InMemoryKV()
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    m0 = coord.Membership(0, 2, kv=kv, clock=clock,
                          heartbeat_s=0.25, lease_s=2.0)
    m1 = coord.Membership(1, 2, kv=kv, clock=clock,
                          heartbeat_s=0.25, lease_s=2.0)
    m0.heartbeat(force=True)
    m1.heartbeat(force=True)
    m1.poll(timeout=0.05)  # baseline rank 0's beat at t=0
    spool = str(tmp_path / "spool")
    submit(spool, _row("j1"))
    it0 = StreamIntake(spool, kv=kv, rank=0, clock=clock,
                       membership=m0, lease_s=0.5, poll_s=0.0)
    s0 = FleetScheduler(str(tmp_path / "ck0"), quantum=4, intake=it0)
    it1 = StreamIntake(spool, kv=kv, rank=1, clock=clock,
                       membership=m1, lease_s=0.5, poll_s=0.0)
    FleetScheduler(str(tmp_path / "ck1"), quantum=4, intake=it1)
    plan = faults.FaultPlan()
    plan.intake_death(rank=0)
    with plan:
        with pytest.raises(faults.InjectedRankDeath):
            it0.pump()
    it1.pump()  # observes the orphaned lease (starts its aging)
    t[0] += 0.7  # intake lease expired; rank 0 SUSPECT, not DEAD
    m1.poll(timeout=0.05)
    assert m1.state(0) == coord.Membership.SUSPECT
    it1.pump()
    assert it1.reclaimed == 0
    t[0] += 2.0  # now DEAD
    m1.poll(timeout=0.05)
    assert m1.state(0) == coord.Membership.DEAD
    it1.pump()
    assert it1.reclaimed == 1
    del s0


# -- retry envelope + poison quarantine ------------------------------

def test_torn_spool_record_quarantined_with_reason(tmp_path):
    """A torn sealed frame (submitter died mid-write) retries K times
    under jittered backoff, then moves to ``spool/quarantine/`` with
    a structured reason record — and the stream keeps draining."""
    env = _Env(tmp_path, retries=3)
    it, sched = env.pairs[0]
    plan = faults.FaultPlan()
    plan.spool_torn_write(job="poison")
    with plan:
        env.submit(_row("poison"))
    env.submit(_row("good"))
    for _ in range(12):
        it.pump()
        env.tick(0.1)  # clears every backoff (cap 0.05s)
    assert it.quarantined == 1
    qdir = os.path.join(env.spool, "quarantine")
    assert os.path.exists(os.path.join(qdir, "poison.json"))
    with open(os.path.join(qdir, "poison.reason.json")) as f:
        reason = json.load(f)
    assert reason["name"] == "poison"
    assert reason["attempts"] == 3
    assert reason["error_type"] == "IntakeRetryExhausted"
    # the stream continued behind the poison record
    report = sched.run(max_ticks=100)
    assert report["good"]["status"] == "done"
    assert (telemetry.registry().counter_total(
        "dccrg_intake_quarantined_total") == 1)


def test_transient_io_fault_retries_then_admits(tmp_path):
    """Injected I/O faults under the K budget back off and admit —
    no quarantine, no duplicate."""
    env = _Env(tmp_path, retries=4)
    it, sched = env.pairs[0]
    env.submit(_row("j1"))
    plan = faults.FaultPlan()
    plan.spool_io_error(times=2, job="j1")
    with plan:
        for _ in range(10):
            it.pump()
            env.tick(0.1)
    assert it.quarantined == 0 and it.admitted == 1
    assert (telemetry.registry().counter_total(
        "dccrg_intake_retries_total") == 2)
    assert sched.run(max_ticks=100)["j1"]["status"] == "done"


def test_unknown_kernel_is_typed_poison(tmp_path):
    """A spec naming an unregistered kernel quarantines immediately
    (no retry burn) with the typed ``UnknownKernelError`` reason —
    the satellite contract replacing the raw KeyError."""
    env = _Env(tmp_path)
    it, _sched = env.pairs[0]
    env.submit(_row("bad", kernel="no-such-kernel"))
    it.pump()
    assert it.quarantined == 1
    with open(os.path.join(env.spool, "quarantine",
                           "bad.reason.json")) as f:
        reason = json.load(f)
    assert reason["error_type"] == "UnknownKernelError"
    assert reason["attempts"] == 1
    assert "no-such-kernel" in reason["error"]


def test_malformed_row_is_typed_poison(tmp_path):
    """A structurally hopeless row (no job name) is JobSpecError
    poison at admission time."""
    env = _Env(tmp_path)
    it, _sched = env.pairs[0]
    # bypass submit()'s own validation: land a sealed record whose
    # payload has a job row without a name
    payload = {"job": {"n": 8}, "tenant": "default", "nonce": "x1"}
    sealed = coord.seal_record(json.dumps(payload, sort_keys=True))
    with open(os.path.join(env.spool, "noname.json"), "w") as f:
        f.write(sealed)
    it.pump()
    assert it.quarantined == 1
    with open(os.path.join(env.spool, "quarantine",
                           "noname.reason.json")) as f:
        assert json.load(f)["error_type"] == "JobSpecError"


def test_torn_rename_never_becomes_visible(tmp_path):
    """The other torn half: a submitter dying between temp write and
    rename leaves NO visible record (the atomic-rename contract) —
    nothing admits, nothing quarantines."""
    env = _Env(tmp_path)
    it, _sched = env.pairs[0]
    plan = faults.FaultPlan()
    plan.spool_torn_rename(job="ghost")
    with plan:
        env.submit(_row("ghost"))
    assert not os.path.exists(os.path.join(env.spool, "ghost.json"))
    assert it.pump()["backlog"] == 0


def test_delayed_directory_visibility_heals_next_scan(tmp_path):
    """The delayed-visibility fault hides a fresh entry for one scan;
    the next pump sees and admits it."""
    env = _Env(tmp_path)
    it, _sched = env.pairs[0]
    env.submit(_row("late"))
    plan = faults.FaultPlan()
    plan.spool_delay(rank=0)
    with plan:
        assert it.pump()["admitted"] == 0
    env.tick()
    assert it.pump()["admitted"] == 1


# -- backpressure gate + graceful shed -------------------------------

def _gate_inputs(ratio, age=0.0, **kw):
    d = {"ratio": ratio, "queue_age_s": age, "hi": 1.2, "lo": 0.9,
         "age_bound_s": 30.0}
    d.update(kw)
    return d


def test_gate_rule_hysteresis_band():
    """The pure rule: closes at ratio >= hi, reopens only at
    ratio <= lo — inside the band it holds state (no flap)."""
    rule = RULES["intake.backpressure"]
    assert rule(0, _gate_inputs(1.3)) == 1       # overload: close
    assert rule(1, _gate_inputs(1.0)) is None    # in the band: hold
    assert rule(0, _gate_inputs(1.0)) is None    # in the band: hold
    assert rule(1, _gate_inputs(0.8)) == 0       # calm: reopen
    assert rule(0, _gate_inputs(None, age=45.0)) == 1  # age bound
    assert rule(1, _gate_inputs(None, age=1.0)) == 0
    assert rule(0, _gate_inputs(0.5)) is None


def test_gate_evaluates_once_per_window(tmp_path):
    """<= 1 transition per EWMA window by construction: many pumps
    inside one window evaluate the gate once."""
    env = _Env(tmp_path, window_s=1.0, hi_ratio=1.2, lo_ratio=0.9)
    it, _sched = env.pairs[0]
    it.pump()  # arms the window
    # force an overload verdict, then pump repeatedly INSIDE the
    # window with oscillating EWMAs — the gate must not follow
    for ratio_num in (10.0, 0.1, 10.0, 0.1):
        it.arrival.value = ratio_num
        it.drain.value = 1.0
        env.tick(0.01)
        it.pump()
    assert it.gate_transitions <= 1
    env.tick(1.1)  # a new window: one more evaluation allowed
    it.arrival.value = 10.0
    it.drain.value = 1.0
    it.pump()
    assert it.gate == 1 and it.gate_transitions == 1
    # calm EWMAs + a new window reopen it: exactly 2 transitions
    env.tick(1.1)
    it.arrival.value = 0.1
    it.drain.value = 1.0
    it.pump()
    assert it.gate == 0 and it.gate_transitions == 2


def test_closed_gate_pauses_admission_until_reopen(tmp_path):
    """A closed gate admits nothing (the spool is the durable
    buffer); reopening drains the backlog in arrival order."""
    env = _Env(tmp_path, window_s=0.5)
    it, sched = env.pairs[0]
    it.pump()
    it.arrival.value = 10.0
    it.drain.value = 1.0
    env.tick(0.6)
    it.pump()
    assert it.gate == 1
    env.submit(_row("j1"))
    env.submit(_row("j2"))
    env.tick(0.01)
    assert it.pump()["admitted"] == 0
    assert it.backlog() == 2
    it.arrival.value = 0.1
    env.tick(0.6)
    stats = it.pump()
    assert it.gate == 0 and stats["admitted"] == 2
    report = sched.run(max_ticks=200)
    assert {n: r["status"] for n, r in report.items()} == {
        "j1": "done", "j2": "done"}


def test_saturation_shed_is_journaled_and_resubmittable(tmp_path):
    """Under saturation (backlog / drain > age bound) the newest
    records of the most-backlogged tenant move to ``spool/shed/`` as
    a journaled autopilot decision; shed files re-submit cleanly."""
    ap = Autopilot(quantum=4, clock=lambda: 0.0)
    env = _Env(tmp_path, window_s=0.5, age_bound_s=2.0)
    it, _sched = env.pairs[0]
    it.autopilot = ap
    it.pump()
    it.arrival.value = 10.0
    it.drain.value = 1.0
    env.tick(0.6)
    it.pump()  # closes the gate; nothing waiting yet
    assert it.gate == 1
    for i in range(6):
        env.submit(_row(f"big{i}"), tenant="whale")
    env.submit(_row("small0"), tenant="minnow")
    it.arrival.value = 10.0
    it.drain.value = 1.0  # 7 waiting / 1 per s >> 2 s bound
    env.tick(0.6)
    it.pump()  # still saturated: the journaled shed fires
    assert it.gate == 1 and it.shed > 0
    sdir = os.path.join(env.spool, "shed")
    shed_files = sorted(os.listdir(sdir))
    assert shed_files and all(f.startswith("big") for f in shed_files)
    # minnow's record survived the whale's shed
    assert os.path.exists(os.path.join(env.spool, "small0.json"))
    recs = [r for r in ap.decisions if r["rule"] == "intake.shed"]
    assert len(recs) == 1
    assert recs[0]["inputs"]["tenant"] == "whale"
    assert recs[0]["inputs"]["names"] == sorted(
        f[:-5] for f in shed_files)
    assert replay(list(ap.decisions)) == []
    # shed is graceful: the file is intact and re-submittable
    with open(os.path.join(sdir, shed_files[0])) as f:
        raw = f.read()
    payload = json.loads(coord.unseal_record(raw))
    assert payload["job"]["name"] == shed_files[0][:-5]


# -- tenant shaping ---------------------------------------------------

def test_token_bucket_caps_tenant_rate(tmp_path):
    """A rate-limited tenant admits its burst, then one token per
    1/rate seconds — the rest wait in the spool."""
    env = _Env(tmp_path, rates={"*": 1.0}, burst=2.0)
    it, _sched = env.pairs[0]
    for i in range(5):
        env.submit(_row(f"j{i}"))
    assert it.pump()["admitted"] == 2  # the burst
    env.tick(0.2)
    assert it.pump()["admitted"] == 0  # no token yet
    env.tick(1.0)
    assert it.pump()["admitted"] == 1  # one token refilled
    assert (telemetry.registry().counter_total(
        "dccrg_intake_throttled_total") > 0)


def test_weighted_fairness_orders_tenants(tmp_path):
    """Virtual-time fairness: weight 3 vs 1 admits ~3:1 when both
    tenants have deep backlogs."""
    env = _Env(tmp_path, weights={"gold": 3.0, "*": 1.0},
               max_admit=8)
    it, sched = env.pairs[0]
    for i in range(8):
        env.submit(_row(f"g{i}"), tenant="gold")
        env.submit(_row(f"b{i}"), tenant="bronze")
    it.pump()
    admitted = set(sched._by_name)
    gold = sum(1 for n in admitted if n.startswith("g"))
    bronze = sum(1 for n in admitted if n.startswith("b"))
    assert gold + bronze == 8
    assert gold == 6 and bronze == 2  # 3:1 by virtual time


# -- control-plane + telemetry ---------------------------------------

def test_decisions_replay_divergence_free_end_to_end(tmp_path):
    """Gate flips, a quarantine and a shed all journal through the
    autopilot decision file, and ``replay`` re-derives every one from
    its recorded inputs alone."""
    journal = tmp_path / "decisions.jsonl"
    ap = Autopilot(quantum=4, clock=lambda: 0.0,
                   decision_file=str(journal))
    env = _Env(tmp_path, window_s=0.5, age_bound_s=2.0)
    it, _sched = env.pairs[0]
    it.autopilot = ap
    env.submit(_row("bad", kernel="no-such-kernel"))
    for i in range(5):
        env.submit(_row(f"j{i}"))
    it.pump()  # quarantines "bad", admits the rest
    it.arrival.value = 10.0
    it.drain.value = 0.5
    for i in range(5, 11):
        env.submit(_row(f"j{i}"))
    env.tick(0.6)
    it.pump()  # closes the gate, sheds under saturation
    it.arrival.value = 0.0
    env.tick(0.6)
    it.pump()  # reopens
    rules = [r["rule"] for r in ap.decisions]
    assert "intake.quarantine" in rules
    assert "intake.shed" in rules
    assert rules.count("intake.backpressure") == 2  # close + reopen
    assert replay(read_journal(str(journal))) == []


def test_queue_age_histogram_and_lag_gauge(tmp_path):
    """Telemetry grows per-tenant queue-age observations and the
    intake-lag gauge tracks the backlog."""
    env = _Env(tmp_path)
    it, _sched = env.pairs[0]
    env.submit(_row("j1"), tenant="acme")
    it.pump()
    env.tick(0.5)
    env.submit(_row("j2"), tenant="acme")
    it.pump()
    reg = telemetry.registry()
    h = reg.histogram_total("dccrg_intake_queue_age_seconds",
                            tenant="acme")
    assert h is not None and h.total == 2
    assert reg.counter_total("dccrg_intake_admitted_total",
                             tenant="acme") == 2


# -- wiring + negative pins ------------------------------------------

def test_scheduler_without_intake_is_unchanged(tmp_path):
    """The negative pin: no env knob, no injected intake — the
    scheduler has no front door and a plain run is untouched."""
    jobs = [FleetJob("a", length=(8, 8, 8), n_steps=4,
                     checkpoint_every=4)]
    sched = FleetScheduler(str(tmp_path / "ck"), jobs, quantum=4)
    assert sched.intake is None
    assert sched.run(max_ticks=100)["a"]["status"] == "done"


def test_env_construction_and_missing_spool(tmp_path, monkeypatch):
    """``DCCRG_INTAKE=1`` builds an intake over
    ``DCCRG_INTAKE_SPOOL``; forgetting the spool is a typed error."""
    monkeypatch.setenv("DCCRG_INTAKE", "1")
    with pytest.raises(IntakeError):
        FleetScheduler(str(tmp_path / "ck0"), quantum=4)
    spool = str(tmp_path / "spool")
    monkeypatch.setenv("DCCRG_INTAKE_SPOOL", spool)
    sched = FleetScheduler(str(tmp_path / "ck1"), quantum=4)
    assert isinstance(sched.intake, StreamIntake)
    assert sched.intake.spool == spool
    assert sched.intake.sched is sched


def test_run_loop_pumps_arrivals_to_completion(tmp_path):
    """Jobs landing in the spool BEFORE serving starts drain through
    ``run`` with no manual pumping (the run-loop integration)."""
    env = _Env(tmp_path)
    it, sched = env.pairs[0]
    env.submit(_row("j1"))
    env.submit(_row("j2"))
    report = sched.run(max_ticks=300)
    assert {n: r["status"] for n, r in report.items()} == {
        "j1": "done", "j2": "done"}
    env.tick()
    it.pump()
    assert it.idle()


# -- fleet satellite: job_from_row typed validation ------------------

def test_job_from_row_builds_and_validates():
    job = job_from_row({"name": "x", "n": 8, "steps": 3,
                        "kernel": "diffuse"})
    assert job.name == "x" and job.n_steps == 3
    assert job.length == (8, 8, 8)


def test_job_from_row_typed_errors():
    with pytest.raises(JobSpecError):
        job_from_row("not a dict")
    with pytest.raises(JobSpecError):
        job_from_row({"n": 8})  # no name
    with pytest.raises(JobSpecError):
        job_from_row({"name": "x", "length": "wat"})
    # unknown kernel: lazy by default, typed at validate time
    job = job_from_row({"name": "x", "n": 8, "kernel": "nope"})
    with pytest.raises(UnknownKernelError) as ei:
        job.resolved_kernel()
    assert "nope" in str(ei.value)
    assert isinstance(ei.value, KeyError)  # backcompat
    with pytest.raises(UnknownKernelError):
        job_from_row({"name": "x", "n": 8, "kernel": "nope"},
                     validate_kernel=True)


def test_submit_rejects_unsafe_rows(tmp_path):
    with pytest.raises(JobSpecError):
        submit(str(tmp_path / "s"), {"n": 8})  # no name
    with pytest.raises(JobSpecError):
        submit(str(tmp_path / "s"), {"name": "../escape"})
