"""Roll-plan Pallas bulk executor parity suite (DCCRG_BULK=pallas).

Runs under Pallas TPU interpret mode on the CPU test mesh (the same
discipline as tests/test_pallas_kernel.py), on single-device grids —
the executor's eligibility domain. Pins:

- roll-executor vs XLA roll path: fixup rows BITWISE after one pass
  (the fused scatter epilogue re-runs the reference slot loop with
  exact gathered neighbors), everything to L2/allclose tolerance over
  multi-step runs — across periodic/non-periodic boundaries,
  multi-field kernels and steps_per_pass in {1, 4};
- the negative pin: DCCRG_BULK unset (or =xla) compiles the
  pre-executor XLA program — the bulk path never enters the program
  cache;
- bf16 end-to-end state (Grid(dtype=)): allocate/step/checkpoint
  round-trip/digest dtype pinning/device fingerprints;
- fleet: dtype is part of the bucket key, and a bucket whose kernel
  has a registered bulk twin steps through the batched executor.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dccrg_tpu.grid import DEFAULT_NEIGHBORHOOD_ID, Grid, default_mesh

pytestmark = pytest.mark.pallas


def one_dev_mesh():
    return default_mesh(jax.devices()[:1])


def fixup_rows(grid):
    """All rows whose flat roll is wrong for some slot (the executor's
    scatter-epilogue target set)."""
    hood = grid.plan.hoods[DEFAULT_NEIGHBORHOOD_ID]
    roll = hood.roll_plan(grid.plan.L)
    wr = np.asarray(roll[1])
    return np.unique(wr[wr < grid.plan.L])


def make_diffuse_grid(periodic, mesh=None, dtype=jnp.float32):
    from dccrg_tpu.fleet import seeded_random_init

    g = (Grid(cell_data={"rho": jnp.float32}, dtype=dtype)
         .set_initial_length((16, 16, 16))
         .set_periodic(*periodic)
         .set_maximum_refinement_level(0)
         .set_neighborhood_length(0)
         .initialize(mesh if mesh is not None else one_dev_mesh()))
    seeded_random_init(g, 7)
    g.update_copies_of_remote_neighbors()
    return g


def diffuse_slotwise():
    from dccrg_tpu.fleet import FLEET_BULK_KERNELS

    return FLEET_BULK_KERNELS["diffuse"]


@pytest.mark.parametrize("periodic", [(True, True, True),
                                      (False, False, False)])
@pytest.mark.parametrize("spp", [1, 4])
def test_bulk_matches_xla_roll_path(periodic, spp, monkeypatch):
    """One pass: fixup rows bitwise vs the XLA roll path; multi-step
    (including a remainder pass shorter than steps_per_pass): allclose
    everywhere."""
    kern = diffuse_slotwise()
    dt = jnp.float32(0.05)

    def run(n_steps, bulk):
        if bulk:
            monkeypatch.setenv("DCCRG_BULK", "pallas")
            monkeypatch.setenv("DCCRG_BULK_SPP", str(spp))
        else:
            monkeypatch.delenv("DCCRG_BULK", raising=False)
        g = make_diffuse_grid(periodic)
        g.run_steps(kern, ["rho"], ["rho"], n_steps, extra_args=(dt,))
        return g, np.asarray(g.data["rho"][0][:g.plan.L])

    g_x, rho_x = run(spp, bulk=False)
    g_p, rho_p = run(spp, bulk=True)
    assert any(k[0] == "bulksteploop" for k in g_p._program_cache)
    W = fixup_rows(g_x)
    n0 = 16 ** 3
    if len(W):
        np.testing.assert_array_equal(rho_x[W], rho_p[W])
    np.testing.assert_allclose(rho_p[:n0], rho_x[:n0],
                               rtol=1e-6, atol=1e-6)

    _, rho_x6 = run(spp + 2, bulk=False)  # spp=4: exercises remainder
    _, rho_p6 = run(spp + 2, bulk=True)
    np.testing.assert_allclose(rho_p6[:n0], rho_x6[:n0],
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("spp", [1, 4])
def test_bulk_multi_field_advection(spp, monkeypatch):
    """The north-star workload (3 fields in, 1 out, periodic x/y +
    non-periodic z) through the bulk executor: fixup rows bitwise
    after one pass, L2 parity over a longer run."""
    from dccrg_tpu.models.advection import GridAdvection

    def run(n_steps, bulk):
        if bulk:
            monkeypatch.setenv("DCCRG_BULK", "pallas")
            monkeypatch.setenv("DCCRG_BULK_SPP", str(spp))
        else:
            monkeypatch.delenv("DCCRG_BULK", raising=False)
        s = GridAdvection(n=16, nz=16, mesh=one_dev_mesh())
        dt = 0.5 * s.max_time_step()
        s.run(n_steps, dt)
        return s, np.asarray(s.grid.data["density"][0][:s.grid.plan.L])

    s_x, rho_x = run(spp, bulk=False)
    s_p, rho_p = run(spp, bulk=True)
    W = fixup_rows(s_x.grid)
    assert len(W)  # periodic wraps exist on this configuration
    if spp == 1:
        np.testing.assert_array_equal(rho_x[W], rho_p[W])
    else:
        # the deep pass's epilogue cascade recomputes DILATED sets;
        # XLA CPU contracts mul+add to FMA differently between the
        # full-array and gathered-subset programs for this
        # cancellation-heavy flux, so a few sensitive rows drift by
        # 1 ulp at intermediate sub-steps. The repair itself stays
        # exact: the overwhelming majority of fixup rows are bitwise
        # and the rest are a single float32 ulp off.
        exact = np.count_nonzero(rho_x[W] == rho_p[W]) / len(W)
        assert exact > 0.9, exact
        np.testing.assert_allclose(rho_p[W], rho_x[W],
                                   rtol=2e-6, atol=1e-9)
    n0 = 16 ** 3
    np.testing.assert_allclose(rho_p[:n0], rho_x[:n0],
                               rtol=1e-6, atol=1e-6)

    s_x2, _ = run(6, bulk=False)
    s_p2, _ = run(6, bulk=True)
    assert abs(s_p2.l2_error() - s_x2.l2_error()) < 1e-4


def test_bulk_negative_pin(monkeypatch):
    """DCCRG_BULK unset (and =xla) compiles the pre-executor XLA
    program: the bulk path never enters the program cache — the same
    discipline as DCCRG_INTEGRITY=0."""
    kern = diffuse_slotwise()
    dt = jnp.float32(0.05)
    for mode in (None, "xla"):
        if mode is None:
            monkeypatch.delenv("DCCRG_BULK", raising=False)
        else:
            monkeypatch.setenv("DCCRG_BULK", mode)
        g = make_diffuse_grid((True, True, True))
        g.run_steps(kern, ["rho"], ["rho"], 2, extra_args=(dt,))
        kinds = {k[0] for k in g._program_cache}
        assert "steploop" in kinds and "bulksteploop" not in kinds
    monkeypatch.setenv("DCCRG_BULK", "pallas")
    g = make_diffuse_grid((True, True, True))
    g.run_steps(kern, ["rho"], ["rho"], 2, extra_args=(dt,))
    kinds = {k[0] for k in g._program_cache}
    assert "bulksteploop" in kinds and "steploop" not in kinds


def test_bulk_ineligible_falls_back(monkeypatch):
    """DCCRG_BULK=pallas on an ineligible configuration (multi-device
    mesh) silently falls back to the XLA roll path."""
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    kern = diffuse_slotwise()
    monkeypatch.setenv("DCCRG_BULK", "pallas")
    g = make_diffuse_grid((True, True, True),
                          mesh=default_mesh(jax.devices()[:2]))
    g.run_steps(kern, ["rho"], ["rho"], 2,
                extra_args=(jnp.float32(0.05),))
    kinds = {k[0] for k in g._program_cache}
    assert "steploop" in kinds and "bulksteploop" not in kinds


def test_grid_dtype_bf16_end_to_end(tmp_path, monkeypatch):
    """Grid(dtype=bfloat16): allocate/step/checkpoint/digest/
    fingerprint all stay narrow. Also pins that the executor handles
    bf16 state (the flux arithmetic widens to f32 in-kernel)."""
    from dccrg_tpu import checkpoint as ckpt
    from dccrg_tpu import integrity, resilience
    from dccrg_tpu.models.advection import GridAdvection

    s = GridAdvection(n=16, nz=16, mesh=one_dev_mesh(),
                      dtype=jnp.bfloat16)
    g = s.grid
    assert g.state_dtype == jnp.bfloat16
    for name in ("density", "vx", "vy"):
        assert g.fields[name][1] == jnp.bfloat16
        assert g.data[name].dtype == jnp.bfloat16
    s.run(3, 0.5 * s.max_time_step())
    assert g.data["density"].dtype == jnp.bfloat16

    # digest is dtype-pinned: an f32 grid with the same physics can
    # never alias a bf16 digest
    d16 = ckpt.state_digest(g)
    s32 = GridAdvection(n=16, nz=16, mesh=one_dev_mesh())
    assert ckpt.state_digest(s32.grid) != d16

    # checkpoint round-trip preserves dtype and bytes
    path = str(tmp_path / "bf16.dcc")
    resilience.save_checkpoint(g, path)
    g2 = s.__class__(n=16, nz=16, mesh=one_dev_mesh(),
                     dtype=jnp.bfloat16).grid
    resilience.load_checkpoint_into(g2, path)
    assert g2.data["density"].dtype == jnp.bfloat16
    assert ckpt.state_digest(g2) == d16

    # device fingerprints widen 16-bit state losslessly
    fp = integrity.device_fingerprint(g.data["density"][0],
                                      int(g.plan.n_local[0]))
    assert np.asarray(fp).shape == (2,)

    # and the bulk executor accepts bf16 state
    monkeypatch.setenv("DCCRG_BULK", "pallas")
    sp = GridAdvection(n=16, nz=16, mesh=one_dev_mesh(),
                       dtype=jnp.bfloat16)
    sp.run(3, 0.5 * sp.max_time_step())
    assert sp.grid.data["density"].dtype == jnp.bfloat16
    ref = np.asarray(s.grid.data["density"][0], dtype=np.float32)
    got = np.asarray(sp.grid.data["density"][0], dtype=np.float32)
    np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.02)


def test_fleet_bucket_key_dtype():
    """A bf16 job can never share a compiled program with a float32
    bucket: dtype is part of the bucket key."""
    from dccrg_tpu.fleet import FleetJob

    a = FleetJob("a", length=(16, 16, 16), kernel="diffuse")
    b = FleetJob("b", length=(16, 16, 16), kernel="diffuse",
                 cell_data={"rho": jnp.bfloat16})
    assert a.bucket_key() != b.bucket_key()
    c = FleetJob("c", length=(16, 16, 16), kernel="diffuse")
    assert a.bucket_key() == c.bucket_key()


def test_fleet_bulk_bucket_matches_table_path(monkeypatch):
    """A GridBatch bucket selects the batched bulk executor through
    the fleet bulk-kernel registry under DCCRG_BULK=pallas, and its
    slots match the table-gather program to float re-association."""
    from dccrg_tpu.fleet import FleetJob, GridBatch

    def run(bulk):
        if bulk:
            monkeypatch.setenv("DCCRG_BULK", "pallas")
        else:
            monkeypatch.delenv("DCCRG_BULK", raising=False)
        jobs = [FleetJob(f"j{i}", length=(16, 16, 16), kernel="diffuse",
                         n_steps=4, params=(0.03 + 0.01 * i,), seed=i)
                for i in range(2)]
        batch = GridBatch(jobs[0], capacity=2)
        for j in jobs:
            j.apply_init(batch.grid)
            batch.admit(j)
        batch.step(np.array([4, 4], dtype=np.int32))
        # the solo-path shadow audit keys off this flag: bulk
        # arithmetic is not bitwise-comparable across programs
        assert batch.bulk_active() is bulk
        return [batch.extract(i) for i in range(2)]

    table = run(bulk=False)
    bulk = run(bulk=True)
    for st, sb in zip(table, bulk):
        for name in st:
            np.testing.assert_allclose(
                np.asarray(sb[name], dtype=np.float64),
                np.asarray(st[name], dtype=np.float64),
                rtol=1e-5, atol=1e-6)


def test_overlap_cpu_default_off(monkeypatch):
    """The satellite pin: overlapped fused steps default OFF on the
    CPU backend (measured 0.89x there, PERF.md); DCCRG_OVERLAP=1
    still forces it."""
    monkeypatch.delenv("DCCRG_OVERLAP", raising=False)
    g = make_diffuse_grid((True, True, True))
    assert g._use_overlap() is False
    monkeypatch.setenv("DCCRG_OVERLAP", "1")
    assert g._use_overlap() is True
