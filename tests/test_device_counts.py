"""Device-count sweeps: the reference requires every test to work
with any number of processes (tests/README:5-6, harness runs 1/3/5
ranks). The same invariants must hold on 1/3/5/7-device meshes —
including counts that don't divide the grid."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu.grid import Grid
from dccrg_tpu.models.game_of_life import GameOfLife
from dccrg_tpu.models.advection_amr import AmrAdvection

COUNTS = (1, 3, 5, 7)


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


@pytest.mark.parametrize("n_dev", COUNTS)
def test_game_of_life_oscillator(n_dev):
    """The blinker oscillates identically on any device count
    (examples/simple_game_of_life.cpp:122-158)."""
    gol = GameOfLife(length=(10, 10, 1), mesh=mesh_of(n_dev))
    blinker = [gol.grid.mapping.get_cell_from_indices(
        np.array([x, 5, 0], dtype=np.uint64), 0) for x in (4, 5, 6)]
    gol.set_alive(blinker)
    ref = gol.alive_cells()
    for turn in range(4):
        gol.step()
        alive = gol.alive_cells()
        if turn % 2 == 1:
            np.testing.assert_array_equal(np.sort(alive), np.sort(ref))
        assert len(alive) == 3


@pytest.mark.parametrize("n_dev", COUNTS)
def test_exchange_and_amr(n_dev):
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((5, 3, 2))
         .set_maximum_refinement_level(1)
         .set_periodic(True, False, False)
         .initialize(mesh_of(n_dev)))
    cells = g.plan.cells
    g.set("v", cells, cells.astype(np.float32))
    g.update_copies_of_remote_neighbors()
    host = np.asarray(g.data["v"])
    for d in range(n_dev):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host[d, g.plan.L + r] == float(cid)
    g.refine_completely(1)
    g.stop_refining()
    assert len(g.plan.cells) == 30 + 7
    g.update_copies_of_remote_neighbors()
    g.balance_load()
    np.testing.assert_array_equal(
        np.sort(g.get("v", np.arange(2, 31).astype(np.uint64))),
        np.arange(2, 31, dtype=np.float32),
    )


@pytest.mark.parametrize("n_dev", COUNTS)
def test_amr_advection_conserves_mass(n_dev):
    app = AmrAdvection(length=(8, 8, 1), max_refinement_level=1,
                       mesh=mesh_of(n_dev))
    m0 = app.total_mass()
    app.run(6, adapt_n=3)
    assert abs(app.total_mass() - m0) < 1e-5 * max(m0, 1.0)


@pytest.mark.parametrize("seed", [0, 3, 7, 11])
def test_exchange_topology_fuzz(seed):
    """Halo exchange across odd topologies: thin/tiny dims, every
    partitioner, both exchange phases — exercises the per-peer
    ppermute path and its all_to_all fallback."""
    rng = np.random.default_rng(seed + 777)
    dims = tuple(int(v) for v in rng.choice([1, 2, 3, 5, 9], 3))
    if np.prod(dims) < 4:
        dims = (3, 2, 2)
    periodic = tuple(bool(b) for b in rng.integers(0, 2, 3))
    n_dev = int(rng.choice([2, 3, 5, 7, 8]))
    hood = int(rng.integers(0, 3))
    part = str(rng.choice(["block", "morton", "hilbert", "rcb"]))
    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length(dims).set_periodic(*periodic)
         .set_neighborhood_length(hood)
         .set_load_balancing_method(part)
         .initialize(mesh_of(n_dev)))
    cells = g.plan.cells
    g.set("v", cells, cells.astype(np.float32))
    g.update_copies_of_remote_neighbors()
    host = np.asarray(g.data["v"])
    for d in range(n_dev):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host[d, g.plan.L + r] == float(cid)
    g.set("v", cells, 2 * cells.astype(np.float32))
    g.start_remote_neighbor_copy_updates()
    g.wait_remote_neighbor_copy_updates()
    host = np.asarray(g.data["v"])
    for d in range(n_dev):
        for r, cid in enumerate(g.plan.ghost_ids[d]):
            assert host[d, g.plan.L + r] == 2 * float(cid)
