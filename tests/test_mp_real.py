"""Pytest wrapper over the REAL multi-process smoke harness
(tests/mp_harness.py): each scenario spawns 2 actual OS processes
under ``jax.distributed.initialize`` on the CPU backend.

Marked ``mp`` (run via ``tests/ci_mp_leg.sh`` / ``pytest -m mp``) and
``slow`` so the tier-1 run stays single-process; skips cleanly where
``jax.distributed`` on CPU is unavailable (the harness probes first
and exits 77)."""

import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.mp, pytest.mark.slow]

HARNESS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mp_harness.py")
SCENARIOS = ("save_restore", "psum", "barrier_timeout", "rank_kill",
             "consensus", "sdc_rank", "preempt", "delta_rank_kill",
             "trace_merge", "host_death", "zombie_fence",
             "host_rejoin", "amr_commit", "amr_rank_kill",
             "amr_zombie", "async_save", "async_save_kill",
             "intake_kill", "rejoin_warm")


def _run(scenario, seed=0, timeout=300):
    out = subprocess.run(
        [sys.executable, HARNESS, "--scenario", scenario,
         "--seed", str(seed), "--timeout", str(timeout - 60)],
        capture_output=True, text=True, timeout=timeout)
    if out.returncode == 77:
        pytest.skip("jax.distributed unavailable on CPU here")
    assert out.returncode == 0, (
        f"{scenario} failed (rc {out.returncode}):\n"
        f"{out.stdout[-4000:]}\n{out.stderr[-2000:]}")
    return out.stdout


def _digests(stdout):
    return sorted(line.split(" DIGEST ")[1]
                  for line in stdout.splitlines() if " DIGEST " in line)


@pytest.mark.parametrize("scenario", SCENARIOS)
def test_real_two_process_scenario(scenario):
    _run(scenario)


def test_real_harness_is_seed_deterministic():
    """Two runs with the same seed write the byte-identical checkpoint
    (compared via the DIGEST lines the harness relays — the fuzz.py-
    style determinism contract), and a different seed writes different
    bytes (the digest is not a constant)."""
    a = _digests(_run("save_restore", seed=7))
    b = _digests(_run("save_restore", seed=7))
    c = _digests(_run("save_restore", seed=8))
    assert a and a == b
    assert a != c
