"""Native recommit fast path: bitwise native/numpy parity + arena
atomicity.

The AMR plan re-commit hot loops (batched easy-block lookups, the
in-place far/easy/hard table writers, the stream-reuse position remap)
live in the native engine with pure-numpy fallbacks; these tests pin
that BOTH engines produce bitwise-identical plans — layout and every
hood table — across refine / recommit / unrefine sequences, and that
the PlanArena (pooled table buffers reused across epochs) can never
leak a partially-written build into a rolled-back plan.
"""

import hashlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dccrg_tpu import FaultPlan, Grid, MutationAbortedError, native
from dccrg_tpu.txn import grid_state_bytes

pytestmark = pytest.mark.recommit

needs_native = pytest.mark.skipif(
    native.lib is None, reason="native library failed to build")


def mesh_of(n):
    return Mesh(np.array(jax.devices()[:n]), ("dev",))


def make_grid(length=(6, 5, 4), periodic=(False, True, False), n_dev=4,
              max_ref=2):
    return (
        Grid(cell_data={"v": jnp.float32})
        .set_initial_length(length)
        .set_periodic(*periodic)
        .set_maximum_refinement_level(max_ref)
        .set_neighborhood_length(1)
        .initialize(mesh_of(n_dev))
    )


def adapt_sequence(g):
    """refine -> recommit (reuse epoch) -> unrefine, yielding a plan
    fingerprint after every commit."""
    fps = []
    for c in (1, 2, 3):
        g.refine_completely(c)
    g.stop_refining()
    fps.append(plan_fingerprint(g))
    for c in g.plan.cells[:6]:
        g.refine_completely(int(c))
    g.stop_refining()
    fps.append(plan_fingerprint(g))
    lvl = g.mapping.get_refinement_level(g.plan.cells)
    deepest = g.plan.cells[lvl == lvl.max()]
    g.unrefine_completely(int(deepest[0]))
    g.stop_refining()
    fps.append(plan_fingerprint(g))
    return fps


def plan_fingerprint(g):
    """SHA-256 over the full plan: layout + every hood table, bitwise
    (lazy to-tables and offset tables materialized)."""
    h = hashlib.sha256()
    p = g.plan
    h.update(np.ascontiguousarray(p.cells).tobytes())
    h.update(np.ascontiguousarray(p.owner).tobytes())
    h.update(str((p.L, p.R)).encode())
    h.update(np.ascontiguousarray(p.row_of_pos).tobytes())
    h.update(np.asarray(p.n_local).tobytes())
    for d in range(p.n_dev):
        h.update(np.ascontiguousarray(p.local_ids[d]).tobytes())
        h.update(np.ascontiguousarray(p.ghost_ids[d]).tobytes())
    for hid in sorted(p.hoods):
        hood = p.hoods[hid]
        h.update(np.ascontiguousarray(hood.nbr_rows).tobytes())
        h.update(np.ascontiguousarray(hood.nbr_mask).tobytes())
        h.update(np.ascontiguousarray(hood.nbr_offs).tobytes())
        if hood.scale_rows is not None:
            h.update(np.ascontiguousarray(hood.scale_rows).tobytes())
        for t in (hood.hard_rows, hood.hard_nbr_rows, hood.hard_offs,
                  hood.hard_mask):
            if t is not None:
                h.update(np.ascontiguousarray(t).tobytes())
        for t in hood._to_tables():
            h.update(np.ascontiguousarray(t).tobytes())
        h.update(np.ascontiguousarray(hood.send_rows).tobytes())
        h.update(np.ascontiguousarray(hood.recv_rows).tobytes())
        if hood.n_inner is not None:
            h.update(np.asarray(hood.n_inner).tobytes())
    return h.hexdigest()


CONFIGS = [
    dict(),
    dict(periodic=(True, True, True), length=(4, 4, 4), n_dev=2),
    dict(n_dev=1, length=(5, 4, 4)),
    dict(length=(4, 4, 2), max_ref=3),
]


@needs_native
@pytest.mark.parametrize("kw", CONFIGS)
def test_native_numpy_plans_bitwise_identical(monkeypatch, kw):
    """The same refine/recommit/unrefine sequence with the native lib
    on and forced off must produce bitwise-identical plans: layout and
    every gather/to/hard table."""
    fps_native = adapt_sequence(make_grid(**kw))
    monkeypatch.setattr(native, "lib", None)
    fps_numpy = adapt_sequence(make_grid(**kw))
    assert fps_native == fps_numpy


def test_reuse_and_hint_change_nothing_bitwise():
    """Stream reuse + the stop_refining dirty-set hint are pure
    optimizations: plans must be bitwise identical to a from-scratch
    rebuild with the reuse cache cleared before every commit."""
    def run(kill_reuse):
        g = make_grid()
        fps = []
        for c in (1, 2, 3):
            g.refine_completely(c)
        g.stop_refining()
        fps.append(plan_fingerprint(g))
        for step in range(2):
            if kill_reuse:
                g._hybrid_reuse = {}
            for c in g.plan.cells[6 * step:6 * step + 6]:
                g.refine_completely(int(c))
            g.stop_refining()
            fps.append(plan_fingerprint(g))
        return fps

    assert run(False) == run(True)


def test_balance_then_recommit_matches_fresh_reuse():
    """An owner-only rebuild (balance_load) passes an empty dirty set —
    every stream is reused with only positions/owners remapped; the
    result must be bitwise identical to a cache-cleared rebuild."""
    def run(kill_reuse):
        g = make_grid(n_dev=3)
        for c in (1, 2, 3):
            g.refine_completely(c)
        g.stop_refining()
        if kill_reuse:
            g._hybrid_reuse = {}
        g.balance_load()
        fp1 = plan_fingerprint(g)
        if kill_reuse:
            g._hybrid_reuse = {}
        for c in g.plan.cells[:4]:
            g.refine_completely(int(c))
        g.stop_refining()
        return fp1, plan_fingerprint(g)

    assert run(False) == run(True)


@pytest.mark.faultinject
@pytest.mark.parametrize("phase", ["classified", "cached", "tables"])
def test_arena_rollback_is_bitwise_atomic(phase):
    """A fault at any recommit phase — including after the arena
    tables were written — must roll back to a plan whose tables are
    bitwise identical to the pre-commit state: the arena can never
    hand a protected (rollback-target) buffer to an in-flight build."""
    g = make_grid()
    for c in (1, 2, 3):
        g.refine_completely(c)
    g.stop_refining()
    # one more committed epoch so the arena pool is warm and the next
    # build actually recycles buffers
    for c in g.plan.cells[:4]:
        g.refine_completely(int(c))
    g.stop_refining()

    before_bytes = grid_state_bytes(g)
    before_fp = plan_fingerprint(g)
    before_plan = g.plan

    plan = FaultPlan(seed=3)
    plan.mutation_error(site="hybrid.recommit", times=1, phase=phase)
    for c in g.plan.cells[4:8]:
        g.refine_completely(int(c))
    with plan:
        with pytest.raises(MutationAbortedError):
            g.stop_refining()
    assert plan.fired("hybrid.recommit") == 1
    assert g.plan is before_plan
    assert plan_fingerprint(g) == before_fp
    assert grid_state_bytes(g) == before_bytes

    # the requests survived the rollback: the retry must succeed and
    # match an undisturbed control run bitwise
    g.stop_refining()
    g2 = make_grid()
    for c in (1, 2, 3):
        g2.refine_completely(c)
    g2.stop_refining()
    for c in g2.plan.cells[:4]:
        g2.refine_completely(int(c))
    g2.stop_refining()
    for c in g2.plan.cells[4:8]:
        g2.refine_completely(int(c))
    g2.stop_refining()
    assert plan_fingerprint(g) == plan_fingerprint(g2)


@needs_native
def test_sorted_positions_matches_searchsorted():
    rng = np.random.default_rng(0)
    hay = np.unique(rng.integers(1, 10_000, 500).astype(np.uint64))
    needles = np.unique(rng.choice(hay, 200))
    extra = np.unique(rng.integers(1, 10_000, 50).astype(np.uint64))
    needles = np.unique(np.concatenate([needles, extra]))
    got = native.sorted_positions(hay, needles)
    np.testing.assert_array_equal(got, np.searchsorted(hay, needles))


@needs_native
def test_level_block_batch_matches_numpy_lookup():
    """The batched native lookup and the per-offset numpy path agree
    on (valid, exist) everywhere and on pos wherever the neighbor
    exists (pos is undefined-but-unused elsewhere)."""
    from dccrg_tpu import hybrid as hybrid_mod

    g = make_grid(length=(4, 4, 4), periodic=(True, False, True), n_dev=1)
    for c in (1, 5, 22):
        g.refine_completely(c)
    g.stop_refining()
    cells = g.plan.cells
    mapping, topo = g.mapping, g.topology
    periodic = tuple(topo.is_periodic(d) for d in range(3))
    first = np.uint64(mapping._level_first[1])
    last = np.uint64(mapping._level_first[2])
    a = int(np.searchsorted(cells, first))
    b = int(np.searchsorted(cells, last))
    offs = np.array([[1, 0, 0], [-1, 0, 0], [0, -1, 1], [2, 2, 2]],
                    dtype=np.int64)

    nat = hybrid_mod._LevelBlock(mapping, periodic, cells, 1, a, b)
    nat.precompute(offs)
    ref = hybrid_mod._LevelBlock(mapping, periodic, cells, 1, a, b)
    ref._plat = None  # force the searchsorted fallback
    for o in offs:
        p_n, v_n, e_n = nat.lookup(o)
        p_r, v_r, e_r = ref.lookup(o)
        np.testing.assert_array_equal(v_n, v_r)
        np.testing.assert_array_equal(e_n, e_r)
        np.testing.assert_array_equal(p_n[e_n], p_r[e_r])


@pytest.mark.slow
def test_recommit_192_parity_light():
    """192^3-scale smoke (the ROADMAP scale item): slab refine +
    recommit completes, the arena recycles buffers, and the committed
    structure passes the consistency verifier."""
    import jax.numpy as jnp

    g = (Grid(cell_data={"v": jnp.float32})
         .set_initial_length((192, 192, 192))
         .set_maximum_refinement_level(1)
         .set_neighborhood_length(1)
         .initialize(mesh_of(1)))
    n0 = np.uint64(192) ** 3
    nref = int(n0) // 64
    for c in g.plan.cells[:nref]:
        g.refine_completely(int(c))
    g.stop_refining()
    lvl0 = g.plan.cells[g.plan.cells <= n0]
    for c in lvl0[-nref:]:
        g.refine_completely(int(c))
    g.stop_refining()
    # third epoch: the arena recycles the first epoch's buffers (two
    # generations stay protected: live plan + rollback snapshot)
    lvl1 = g.plan.cells[g.plan.cells > n0]
    for c in lvl1[:8 * 64:8]:
        g.unrefine_completely(int(c))
    g.stop_refining()
    from dccrg_tpu import verify
    verify.is_consistent(g)
    stats = g._plan_arena.stats()
    assert stats["hits"] > 0, stats
