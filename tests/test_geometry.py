"""Geometry unit tests (mirrors reference tests/geometry semantics)."""

import numpy as np
import pytest

from dccrg_tpu import (
    CartesianGeometry,
    GridTopology,
    Mapping,
    NoGeometry,
    StretchedCartesianGeometry,
)
from dccrg_tpu.geometry import geometry_from_bytes


def make(length=(4, 3, 2), max_lvl=0, periodic=(False, False, False)):
    return Mapping(length, max_lvl), GridTopology(periodic)


def test_no_geometry_unit_cells():
    m, t = make((4, 3, 2))
    g = NoGeometry(m, t)
    np.testing.assert_array_equal(g.get_start(), [0, 0, 0])
    np.testing.assert_array_equal(g.get_end(), [4, 3, 2])
    c = np.uint64(1)
    np.testing.assert_allclose(g.get_min(c), [0, 0, 0])
    np.testing.assert_allclose(g.get_max(c), [1, 1, 1])
    np.testing.assert_allclose(g.get_center(c), [0.5, 0.5, 0.5])


def test_cartesian_basic():
    m, t = make((4, 4, 4))
    g = CartesianGeometry(m, t, start=(-1.0, 0.0, 2.0), level_0_cell_length=(0.5, 1.0, 2.0))
    np.testing.assert_allclose(g.get_start(), [-1, 0, 2])
    np.testing.assert_allclose(g.get_end(), [1, 4, 10])
    # cell (0,0,0) is id 1
    np.testing.assert_allclose(g.get_min(np.uint64(1)), [-1, 0, 2])
    np.testing.assert_allclose(g.get_length(np.uint64(1)), [0.5, 1, 2])
    np.testing.assert_allclose(g.get_center(np.uint64(1)), [-0.75, 0.5, 3.0])


def test_cartesian_refined_lengths():
    m, t = make((2, 2, 2), max_lvl=2)
    g = CartesianGeometry(m, t, level_0_cell_length=(4.0, 4.0, 4.0))
    kids = m.get_all_children(np.uint64(1))
    np.testing.assert_allclose(g.get_length(kids[0]), [2, 2, 2])
    grandkids = m.get_all_children(kids[0])
    np.testing.assert_allclose(g.get_length(grandkids[0]), [1, 1, 1])
    # child 0 shares parent's min corner
    np.testing.assert_allclose(g.get_min(kids[0]), g.get_min(np.uint64(1)))
    # child 7 touches parent's max corner
    np.testing.assert_allclose(g.get_max(kids[7]), g.get_max(np.uint64(1)))


def test_get_cell_from_coordinate():
    m, t = make((4, 4, 4))
    g = CartesianGeometry(m, t, start=(0, 0, 0), level_0_cell_length=(1, 1, 1))
    assert g.get_cell(0, (0.5, 0.5, 0.5)) == 1
    assert g.get_cell(0, (3.5, 3.5, 3.5)) == 64
    assert g.get_cell(0, (1.5, 0.5, 0.5)) == 2
    # outside, non-periodic -> error cell
    assert g.get_cell(0, (-0.5, 0.5, 0.5)) == 0


def test_periodic_wrap():
    m, t = make((4, 4, 4), periodic=(True, False, False))
    g = CartesianGeometry(m, t)
    rc = g.get_real_coordinate((-0.5, 1.0, 1.0))
    np.testing.assert_allclose(rc, [3.5, 1.0, 1.0])
    assert g.get_cell(0, (-0.5, 0.5, 0.5)) == 4  # wraps to x index 3
    rc2 = g.get_real_coordinate((0.5, -1.0, 0.5))
    assert np.isnan(rc2[1])


def test_stretched_geometry():
    m, t = make((3, 2, 1))
    coords = [
        np.array([0.0, 1.0, 3.0, 7.0]),
        np.array([-2.0, 0.0, 5.0]),
        np.array([10.0, 20.0]),
    ]
    g = StretchedCartesianGeometry(m, t, coords)
    np.testing.assert_allclose(g.get_start(), [0, -2, 10])
    np.testing.assert_allclose(g.get_end(), [7, 5, 20])
    # cell 2 = level-0 index (1,0,0): x span [1,3]
    np.testing.assert_allclose(g.get_min(np.uint64(2)), [1, -2, 10])
    np.testing.assert_allclose(g.get_length(np.uint64(2)), [2, 2, 10])
    # coordinate lookup in nonuniform spans
    assert g.get_cell(0, (5.0, -1.0, 15.0)) == 3
    assert g.get_cell(0, (0.5, 3.0, 11.0)) == 4


def test_stretched_refined_subdivision():
    m = Mapping((2, 1, 1), maximum_refinement_level=1)
    t = GridTopology()
    coords = [np.array([0.0, 2.0, 6.0]), np.array([0.0, 1.0]), np.array([0.0, 1.0])]
    g = StretchedCartesianGeometry(m, t, coords)
    # children of cell 2 (x span [2,6]) subdivide uniformly: [2,4],[4,6]
    kids = m.get_all_children(np.uint64(2))
    np.testing.assert_allclose(g.get_min(kids[0])[0], 2.0)
    np.testing.assert_allclose(g.get_length(kids[0])[0], 2.0)
    np.testing.assert_allclose(g.get_min(kids[1])[0], 4.0)


def test_stretched_validation():
    m, t = make((2, 1, 1))
    with pytest.raises(ValueError):
        StretchedCartesianGeometry(m, t, [np.array([0.0, 1.0]), np.array([0.0, 1.0]), np.array([0.0, 1.0])])
    with pytest.raises(ValueError):
        StretchedCartesianGeometry(
            m, t, [np.array([0.0, 2.0, 1.0]), np.array([0.0, 1.0]), np.array([0.0, 1.0])]
        )


def test_from_cartesian_clone():
    m, t = make((3, 3, 3))
    cart = CartesianGeometry(m, t, start=(1, 2, 3), level_0_cell_length=(0.5, 0.5, 0.5))
    s = StretchedCartesianGeometry.from_cartesian(cart)
    cells = np.arange(1, 28, dtype=np.uint64)
    np.testing.assert_allclose(s.get_center(cells), cart.get_center(cells))
    np.testing.assert_allclose(s.get_length(cells), cart.get_length(cells))


def test_geometry_file_roundtrip():
    m, t = make((3, 2, 1))
    for g in (
        NoGeometry(m, t),
        CartesianGeometry(m, t, start=(1, 2, 3), level_0_cell_length=(4, 5, 6)),
        StretchedCartesianGeometry(
            m, t, [np.array([0.0, 1.0, 3.0, 7.0]), np.array([-2.0, 0.0, 5.0]), np.array([10.0, 20.0])]
        ),
    ):
        g2 = geometry_from_bytes(g.to_bytes(), m, t)
        assert type(g2) is type(g)
        cells = np.arange(1, 7, dtype=np.uint64)
        np.testing.assert_allclose(g2.get_center(cells), g.get_center(cells))


def test_vectorized_centers_match_scalar():
    m, t = make((4, 4, 4), max_lvl=1)
    g = CartesianGeometry(m, t, start=(-2, -2, -2), level_0_cell_length=(1, 1, 1))
    cells = np.arange(1, int(m.get_last_cell()) + 1, dtype=np.uint64)
    centers = g.get_center(cells)
    for i in (0, 5, 63, 64, 100, len(cells) - 1):
        np.testing.assert_allclose(g.get_center(np.uint64(cells[i])), centers[i])
