"""Resilience layer: numerics watchdog, auto-rollback, OOM fallback
chain, hang-proof device probing — all driven by deterministic fault
injection (dccrg_tpu.faults).

The acceptance pin: a NaN injected at step k must roll the run back to
the last checkpoint and reconverge to the BITWISE-identical final
state of an uninjected run (advection model, CPU backend)."""

import json
import os
import glob

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dccrg_tpu import faults, resilience
from dccrg_tpu.models.advection import GridAdvection
from dccrg_tpu.resilience import (
    NumericsError, ResilienceExhaustedError, ResilientRunner)

pytestmark = pytest.mark.faultinject


def _advection(n=8, nz=4):
    """Small advection solver + a one-step step_fn for the runner."""
    s = GridAdvection(n=n, nz=nz)
    dt = 0.5 * s.max_time_step()

    def step_fn(grid, _i):
        grid.run_steps(s._kernel, ["density", "vx", "vy"], ["density"], 1,
                       extra_args=(jnp.float32(dt),))

    return s, step_fn, dt


# -- watchdog ---------------------------------------------------------

def test_check_finite_and_assert(tmp_path):
    s, _, _ = _advection()
    g = s.grid
    assert resilience.check_finite(g)
    assert resilience.check_finite(g, fields=("density",))
    cells = g.get_cells()
    g.set("density", cells[3:4], np.array([np.inf], np.float32))
    assert not resilience.check_finite(g)
    with pytest.raises(NumericsError) as ei:
        resilience.assert_finite(g, step=7)
    assert "density" in ei.value.details
    np.testing.assert_array_equal(ei.value.details["density"], cells[3:4])
    assert "step 7" in str(ei.value)


def test_find_nonfinite_cells_names_field_and_cells():
    from dccrg_tpu import verify

    s, _, _ = _advection()
    g = s.grid
    cells = g.get_cells()
    g.set("vx", cells[5:7], np.array([np.nan, np.nan], np.float32))
    out = verify.find_nonfinite_cells(g)
    assert list(out) == ["vx"]
    np.testing.assert_array_equal(out["vx"], cells[5:7])


def test_watchdog_env_knob_in_run_steps(monkeypatch):
    """DCCRG_WATCHDOG=N makes plain Grid.run_steps self-check: a
    poisoned field surfaces as NumericsError instead of silently
    stepping garbage."""
    s, _, dt = _advection()
    g = s.grid
    cells = g.get_cells()
    g.set("density", cells[:1], np.array([np.nan], np.float32))
    monkeypatch.setenv("DCCRG_WATCHDOG", "2")
    with pytest.raises(NumericsError):
        g.run_steps(s._kernel, ["density", "vx", "vy"], ["density"], 4,
                    extra_args=(jnp.float32(dt),))


# -- auto-rollback ----------------------------------------------------

def _run(tmp_path, name, n_steps=12, plan=None, **kw):
    s, step_fn, _ = _advection()
    runner = ResilientRunner(
        s.grid, step_fn, str(tmp_path / f"{name}.dc"),
        fields=("density",), check_every=1, checkpoint_every=5,
        backoff=0.0, diagnostics_dir=str(tmp_path), **kw)
    if plan is not None:
        with plan:
            runner.run(n_steps)
    else:
        runner.run(n_steps)
    return runner, np.asarray(s.grid.get("density", s.grid.plan.cells))


def test_nan_rollback_reconverges_bitwise(tmp_path):
    """THE acceptance pin: injected NaN at step 8, checkpoint cadence
    5 -> trip, rollback to step 5, resume; final state bitwise equals
    the uninjected run's."""
    _, ref = _run(tmp_path, "ref")

    plan = faults.FaultPlan(seed=3)
    plan.nan_poison("density", step=8)
    runner, got = _run(tmp_path, "inj", plan=plan)

    assert plan.fired("step.poison") == 1
    assert runner.rollbacks == 1
    assert len(runner.trips) == 1
    assert runner.trips[0]["step"] == 8
    assert runner.trips[0]["rollback_to"] == 5
    assert got.tobytes() == ref.tobytes()


def test_checkpoint_step_checks_before_saving(tmp_path):
    """checkpoint_every NOT a multiple of check_every: a NaN landing
    exactly on a checkpoint step must trip BEFORE the save, so the
    rollback target never captures poisoned state and the run still
    reconverges."""
    s, step_fn, _ = _advection()
    ref_runner = ResilientRunner(
        s.grid, step_fn, str(tmp_path / "r.dc"), fields=("density",),
        check_every=3, checkpoint_every=10, backoff=0.0,
        diagnostics_dir=str(tmp_path))
    ref_runner.run(12)
    ref = np.asarray(s.grid.get("density", s.grid.plan.cells))

    s2, step_fn2, _ = _advection()
    plan = faults.FaultPlan(seed=9)
    plan.nan_poison("density", step=10)  # 10 % 3 != 0: not a check step
    runner = ResilientRunner(
        s2.grid, step_fn2, str(tmp_path / "i.dc"), fields=("density",),
        check_every=3, checkpoint_every=10, backoff=0.0,
        diagnostics_dir=str(tmp_path))
    with plan:
        runner.run(12)
    assert runner.rollbacks == 1
    got = np.asarray(s2.grid.get("density", s2.grid.plan.cells))
    assert got.tobytes() == ref.tobytes()


def test_trip_dumps_diagnostic_bundle(tmp_path):
    plan = faults.FaultPlan(seed=1)
    plan.nan_poison("density", step=3)
    runner, _ = _run(tmp_path, "diag", n_steps=6, plan=plan)
    paths = glob.glob(str(tmp_path / "dccrg_diag_step3_*.json"))
    assert len(paths) == 1
    bundle = json.load(open(paths[0]))
    assert bundle["step"] == 3
    assert bundle["rollback_to"] == 0
    assert bundle["fields"]["density"]  # offending cells are named


def test_persistent_nan_exhausts_retries(tmp_path):
    """A NaN that reappears every replay (poison pinned to the same
    step, every time) trips max_retries rollbacks then surfaces."""
    plan = faults.FaultPlan(seed=2)
    plan.nan_poison("density", step=3, times=8)
    with pytest.raises(ResilienceExhaustedError, match="step 3"):
        _run(tmp_path, "persist", n_steps=6, plan=plan, max_retries=2)
    assert plan.fired("step.poison") == 3  # initial + 2 retries


def test_rollback_refuses_corrupt_checkpoint(tmp_path):
    """If the rollback target itself is corrupt the runner surfaces
    CheckpointCorruptionError rather than resuming from garbage."""
    s, step_fn, _ = _advection()
    ck = str(tmp_path / "cc.dc")
    runner = ResilientRunner(s.grid, step_fn, ck, fields=("density",),
                             check_every=1, checkpoint_every=100,
                             backoff=0.0, diagnostics_dir=str(tmp_path))
    runner.run(2)  # writes the step-0 checkpoint
    faults.flip_bit(ck, os.path.getsize(ck) - 5, 1)
    cells = s.grid.get_cells()
    s.grid.set("density", cells[:1], np.array([np.nan], np.float32))
    with pytest.raises(resilience.CheckpointCorruptionError):
        runner.run(4)


# -- OOM fallback chain -----------------------------------------------

def test_resource_exhausted_falls_back_and_matches(tmp_path):
    """Acceptance pin: simulated RESOURCE_EXHAUSTED on the current
    (dense dispatch) path walks the logged fallback chain; the step
    completes with results equal to the direct slot-wise path."""
    s_ref, _, dt = _advection()
    s_ref.grid.run_steps(s_ref._kernel, ["density", "vx", "vy"],
                         ["density"], 3, extra_args=(jnp.float32(dt),))
    ref = np.asarray(s_ref.grid.get("density", s_ref.grid.plan.cells))

    s, _, _ = _advection()
    plan = faults.FaultPlan()
    plan.resource_exhausted(times=1, mode="current")
    with plan:
        mode = resilience.guarded_step(
            s.grid, s._kernel, ["density", "vx", "vy"], ["density"],
            n_steps=3, extra_args=(jnp.float32(dt),))
    assert mode == "roll"
    assert plan.fired("step.dispatch") == 1
    got = np.asarray(s.grid.get("density", s.grid.plan.cells))
    np.testing.assert_array_equal(got, ref)
    # the downgrade sticks: later guarded dispatches start from the
    # working mode instead of re-trying the one that OOM'd
    assert s.grid._sticky_gather_mode == "roll"
    assert resilience.guarded_step(
        s.grid, s._kernel, ["density", "vx", "vy"], ["density"],
        n_steps=1, extra_args=(jnp.float32(dt),)) == "roll"


def test_forced_env_mode_is_not_retried(monkeypatch):
    """With roll already forced via env, the chain skips the redundant
    'roll' retry and goes current -> tables."""
    monkeypatch.delenv("DCCRG_FORCE_TABLES", raising=False)
    monkeypatch.setenv("DCCRG_ROLL_STENCIL", "1")
    s, _, dt = _advection()
    plan = faults.FaultPlan()
    plan.resource_exhausted(times=1, mode="current")
    with plan:
        mode = resilience.guarded_step(
            s.grid, s._kernel, ["density", "vx", "vy"], ["density"],
            n_steps=1, extra_args=(jnp.float32(dt),))
    assert mode == "tables"
    assert [m for _s, _k, m in
            [(l[0], l[1], l[2].get("mode")) for l in plan.log]] == ["current"]


def test_fallback_reaches_tables_and_matches(tmp_path):
    s_ref, _, dt = _advection()
    s_ref.grid.run_steps(s_ref._kernel, ["density", "vx", "vy"],
                         ["density"], 3, extra_args=(jnp.float32(dt),))
    ref = np.asarray(s_ref.grid.get("density", s_ref.grid.plan.cells))

    s, _, _ = _advection()
    plan = faults.FaultPlan()
    plan.resource_exhausted(times=1, mode="current")
    plan.resource_exhausted(times=1, mode="roll")
    with plan:
        mode = s.grid.run_steps_guarded(
            s._kernel, ["density", "vx", "vy"], ["density"], 3,
            extra_args=(jnp.float32(dt),))
    assert mode == "tables"
    got = np.asarray(s.grid.get("density", s.grid.plan.cells))
    np.testing.assert_array_equal(got, ref)


def test_fallback_chain_exhausted():
    s, _, dt = _advection()
    plan = faults.FaultPlan()
    plan.resource_exhausted(times=faults.EVERY)
    with plan, pytest.raises(ResilienceExhaustedError):
        resilience.guarded_step(
            s.grid, s._kernel, ["density", "vx", "vy"], ["density"],
            n_steps=1, extra_args=(jnp.float32(dt),))


def test_gather_mode_env_restored():
    """The fallback chain restores the caller's gather env vars."""
    s, _, dt = _advection()
    os.environ.pop("DCCRG_FORCE_TABLES", None)
    before = {v: os.environ.get(v)
              for v in ("DCCRG_FORCE_TABLES", "DCCRG_ROLL_STENCIL")}
    plan = faults.FaultPlan()
    plan.resource_exhausted(times=1, mode="current")
    plan.resource_exhausted(times=1, mode="roll")
    with plan:
        s.grid.run_steps_guarded(
            s._kernel, ["density", "vx", "vy"], ["density"], 1,
            extra_args=(jnp.float32(dt),))
    after = {v: os.environ.get(v)
             for v in ("DCCRG_FORCE_TABLES", "DCCRG_ROLL_STENCIL")}
    assert after == before


def test_unrelated_errors_are_not_swallowed():
    """Only RESOURCE_EXHAUSTED walks the chain; anything else
    propagates untouched."""
    s, _, dt = _advection()
    with pytest.raises(KeyError):
        resilience.guarded_step(
            s.grid, s._kernel, ["density", "nope"], ["density"],
            n_steps=1, extra_args=(jnp.float32(dt),))


# -- device probing ---------------------------------------------------

def test_safe_devices_cpu():
    devs = resilience.safe_devices(timeout=120, retries=0, platform="cpu")
    assert len(devs) == len(jax.devices())


def test_safe_devices_hung_probe_times_out_with_backoff():
    plan = faults.FaultPlan()
    plan.probe_hang(times=faults.EVERY)
    with plan, pytest.raises(resilience.DeviceProbeError, match="probe"):
        resilience.safe_devices(timeout=1, retries=2, backoff=0.0,
                                platform="cpu")
    assert plan.fired("device.probe") == 3  # initial + 2 retries


def test_safe_devices_recovers_after_transient_hang():
    plan = faults.FaultPlan()
    plan.probe_hang(times=1)
    with plan:
        devs = resilience.safe_devices(timeout=120, retries=1, backoff=0.0,
                                       platform="cpu")
    assert len(devs) >= 1


def test_runner_survives_failed_adapt(tmp_path):
    """A fault landing INSIDE an AMR commit during the step loop: the
    transaction rolls the grid back to the pre-mutation state, the
    runner treats the MutationAbortedError like a watchdog trip
    (diagnostics + checkpoint rollback + bounded retry), and the replay
    — with the one-shot fault exhausted — commits and completes."""
    s, base_step, _dt = _advection()
    adapt_at = 3
    adapted = []

    def step_fn(grid, i):
        base_step(grid, i)
        if i == adapt_at and not adapted:
            grid.refine_completely(int(grid.get_cells()[0]))
            grid.stop_refining()
            grid.assign_children_from_parents()
            adapted.append(i)

    runner = ResilientRunner(
        s.grid, step_fn, str(tmp_path / "adapt.ckpt"),
        check_every=1, checkpoint_every=2, backoff=0.0)
    plan = faults.FaultPlan(seed=9)
    plan.mutation_error(site="adapt.commit", times=1, phase="resolved")
    with plan:
        runner.run(6)
    assert plan.fired("adapt.commit") == 1
    assert runner.rollbacks == 1
    assert runner.step == 6
    assert adapted  # the replayed adapt committed
    assert runner.trips and "mutation" in runner.trips[0]["fields"]
    from dccrg_tpu import verify

    verify.verify_all(s.grid, check_pins=False)


def test_runner_survives_watchdog_hook_numerics_error(tmp_path, monkeypatch):
    """DCCRG_WATCHDOG fires INSIDE step_fn (run_steps' own self-check
    raises NumericsError mid-step): the runner must recover exactly
    like its own between-steps check — not crash through."""
    s, base_step, _dt = _advection()
    monkeypatch.setenv("DCCRG_WATCHDOG", "1")
    poisoned = []

    def step_fn(grid, i):
        if i == 2 and not poisoned:
            poisoned.append(i)
            grid.set("density", grid.get_cells()[:1],
                     np.array([np.nan], np.float32))
        base_step(grid, i)  # the env hook trips in here

    runner = ResilientRunner(
        s.grid, step_fn, str(tmp_path / "wd.ckpt"),
        check_every=100, checkpoint_every=100, backoff=0.0)
    runner.run(5)
    assert runner.rollbacks == 1
    assert runner.step == 5
    assert runner.trips and "density" in runner.trips[0]["fields"]
    assert resilience.check_finite(s.grid)


def test_runner_recovers_from_transient_oom_trip(tmp_path):
    """A RESOURCE_EXHAUSTED that escapes the step (no guarded_step in
    the loop) is a trip like any other: rollback, bounded retry, and —
    with the one-shot fault exhausted — the replay completes and
    reconverges bitwise. On multi-process meshes this decision rides
    the same trip consensus as mutation/numerics trips."""
    _, ref = _run(tmp_path, "oomref")

    s, base_step, _ = _advection()
    fired = []

    def step_fn(grid, i):
        if i == 4 and not fired:
            fired.append(i)
            raise faults.SimulatedResourceExhausted("transient, step 4")
        base_step(grid, i)

    runner = ResilientRunner(
        s.grid, step_fn, str(tmp_path / "oom.dc"), fields=("density",),
        check_every=1, checkpoint_every=5, backoff=0.0,
        diagnostics_dir=str(tmp_path))
    runner.run(12)
    assert runner.rollbacks == 1
    assert runner.trips[0]["fields"].get("resource_exhausted") == []
    got = np.asarray(s.grid.get("density", s.grid.plan.cells))
    assert got.tobytes() == ref.tobytes()


def test_runner_persistent_oom_exhausts_retries(tmp_path):
    """An OOM that recurs on every replay exhausts the bounded retries
    instead of looping forever."""
    s, _, _ = _advection()

    def step_fn(grid, i):
        raise faults.SimulatedResourceExhausted("every time")

    runner = ResilientRunner(
        s.grid, step_fn, str(tmp_path / "oomx.dc"), fields=("density",),
        check_every=1, checkpoint_every=5, backoff=0.0, max_retries=2,
        diagnostics_dir=str(tmp_path))
    with pytest.raises(ResilienceExhaustedError):
        runner.run(3)


# -- endurance (slow tier) --------------------------------------------

@pytest.mark.slow
def test_endurance_inject_trip_rollback_resume_50_steps(tmp_path):
    """50 steps with a NaN injected every ~7th step: every trip rolls
    back and resumes, and the final state still bitwise-matches the
    uninjected run."""
    _, ref = _run(tmp_path, "ref50", n_steps=50)

    plan = faults.FaultPlan(seed=50)
    poison_steps = list(range(7, 50, 7))
    for k in poison_steps:
        plan.nan_poison("density", step=k)
    runner, got = _run(tmp_path, "inj50", n_steps=50, plan=plan)

    assert plan.fired("step.poison") == len(poison_steps)
    assert runner.rollbacks == len(poison_steps)
    assert got.tobytes() == ref.tobytes()
