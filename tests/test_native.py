"""Native (C++) host runtime vs the NumPy reference implementations.

The native library (dccrg_tpu/native/dccrg_native.cpp) re-implements the
host-side structure code — neighbor-table builder, SFC keys — that the
reference keeps in C++ (dccrg.hpp:4375-4716, :8147-8220). These tests
assert bit-identical results between the two engines on uniform and
refined grids, and that errors carry the same semantics.
"""

import numpy as np
import pytest

from dccrg_tpu import native
from dccrg_tpu.mapping import Mapping
from dccrg_tpu.neighbors import (
    StructureError,
    _find_neighbors_of_numpy,
    make_neighborhood,
)
from dccrg_tpu.partition import hilbert_key, morton_key
from dccrg_tpu.topology import GridTopology

pytestmark = pytest.mark.skipif(
    native.lib is None, reason="native library failed to build"
)


def _refined_cell_set(mapping):
    """Leaf set with one level-0 cell refined (2:1-valid)."""
    level0 = np.arange(1, mapping.length.total_level0_cells + 1, dtype=np.uint64)
    target = level0[0]
    children = mapping.get_all_children(target)
    cells = np.concatenate([level0[level0 != target], children])
    return np.sort(cells)


@pytest.mark.parametrize("hood_len", [0, 1, 2])
@pytest.mark.parametrize("periodic", [(False, False, False), (True, True, True)])
def test_uniform_matches_numpy(hood_len, periodic):
    mapping = Mapping((5, 4, 3), 0)
    topology = GridTopology(periodic)
    cells = np.arange(1, 5 * 4 * 3 + 1, dtype=np.uint64)
    hood = make_neighborhood(hood_len)
    got = native.find_neighbors_of(mapping, topology, cells, cells, hood)
    want = _find_neighbors_of_numpy(mapping, topology, cells, cells, hood)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


@pytest.mark.parametrize("periodic", [(False, False, False), (True, False, True)])
def test_refined_matches_numpy(periodic):
    mapping = Mapping((4, 4, 4), 2)
    topology = GridTopology(periodic)
    # uniform level-1 grid, then one level-1 cell refined to level 2
    # (keeps every neighbor pair within 1 refinement level)
    level0 = np.arange(1, 4 * 4 * 4 + 1, dtype=np.uint64)
    level1 = mapping.get_all_children(level0).ravel()
    one = level1[21]
    cells = np.sort(
        np.concatenate([level1[level1 != one], mapping.get_all_children(one)])
    )
    hood = make_neighborhood(1)
    got = native.find_neighbors_of(mapping, topology, cells, cells, hood)
    want = _find_neighbors_of_numpy(mapping, topology, cells, cells, hood)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


def test_gap_raises_structure_error():
    mapping = Mapping((3, 3, 3), 0)
    topology = GridTopology((False, False, False))
    cells = np.arange(1, 28, dtype=np.uint64)
    broken = cells[cells != 14]  # remove the middle cell
    hood = make_neighborhood(1)
    with pytest.raises(StructureError):
        native.find_neighbors_of(mapping, topology, broken, broken, hood)
    with pytest.raises(StructureError):
        _find_neighbors_of_numpy(mapping, topology, broken, broken, hood)


def test_invalid_query_raises():
    mapping = Mapping((2, 2, 2), 0)
    topology = GridTopology((False, False, False))
    cells = np.arange(1, 9, dtype=np.uint64)
    hood = make_neighborhood(1)
    with pytest.raises(ValueError):
        native.find_neighbors_of(
            mapping, topology, cells, np.array([999], dtype=np.uint64), hood
        )


def test_sfc_keys_match_numpy(monkeypatch):
    mapping = Mapping((8, 8, 8), 1)
    rng = np.random.default_rng(7)
    cells = np.unique(
        rng.integers(1, int(mapping.last_cell), 500, dtype=np.uint64)
    )
    lvl = mapping.get_refinement_level(cells)
    cells = cells[lvl >= 0]
    native_m = morton_key(mapping, cells)
    native_h = hilbert_key(mapping, cells)
    monkeypatch.setattr(native, "lib", None)
    numpy_m = morton_key(mapping, cells)
    numpy_h = hilbert_key(mapping, cells)
    np.testing.assert_array_equal(native_m, numpy_m)
    np.testing.assert_array_equal(native_h, numpy_h)

def test_bulk_mapping_queries_match_numpy():
    # native dispatch engages at >= 4096 ids
    mapping = Mapping((16, 16, 16), 2)
    rng = np.random.default_rng(3)
    cells = rng.integers(0, int(mapping.last_cell) + 1000, 10_000, dtype=np.uint64)
    lvl_native = mapping.get_refinement_level(cells)
    idx_native = mapping.get_indices(cells)
    import dccrg_tpu.native as nat
    saved, nat.lib = nat.lib, None
    try:
        lvl_numpy = mapping.get_refinement_level(cells)
        idx_numpy = mapping.get_indices(cells)
    finally:
        nat.lib = saved
    np.testing.assert_array_equal(lvl_native, lvl_numpy)
    np.testing.assert_array_equal(idx_native, idx_numpy)


@pytest.mark.parametrize("geometry_kind", ["cartesian", "stretched", "none"])
def test_geometry_kernels_match_numpy(geometry_kind):
    """The native geometry kernels (min/len, centers, lengths) must be
    bit-identical to the NumPy fallbacks — same formulas, same
    operation order — across the n=4096 dispatch threshold."""
    from dccrg_tpu.geometry import (
        CartesianGeometry,
        NoGeometry,
        StretchedCartesianGeometry,
        _NATIVE_BATCH,
    )

    mapping = Mapping((4, 3, 2), 3)
    topology = GridTopology((False, True, False))
    if geometry_kind == "cartesian":
        geom = CartesianGeometry(mapping, topology, start=(0.5, -1.0, 2.0),
                                 level_0_cell_length=(0.1, 0.2, 0.3))
    elif geometry_kind == "stretched":
        rng0 = np.random.default_rng(1)
        coords = [np.cumsum(np.abs(rng0.standard_normal(n + 1)) + 0.05)
                  for n in (4, 3, 2)]
        geom = StretchedCartesianGeometry(mapping, topology, coordinates=coords)
    else:
        geom = NoGeometry(mapping, topology)

    rng = np.random.default_rng(0)
    big = rng.integers(1, int(mapping.get_last_cell()) + 1,
                       size=_NATIVE_BATCH + 100).astype(np.uint64)
    # sprinkle invalid ids to cover the NaN rows
    big[::97] = 0

    for method in ("get_length", "get_center", "get_min", "get_max"):
        fn = getattr(geom, method)
        batched = fn(big)
        # per-slice results (below the threshold -> NumPy fallback)
        small = np.concatenate([fn(big[i:i + 1000]) for i in range(0, len(big), 1000)])
        np.testing.assert_array_equal(batched, small, err_msg=method)


def test_cartesian_set_invalidates_length_cache():
    from dccrg_tpu.geometry import CartesianGeometry

    mapping = Mapping((2, 2, 2), 1)
    topology = GridTopology((False, False, False))
    geom = CartesianGeometry(mapping, topology, level_0_cell_length=(1.0, 1.0, 1.0))
    np.testing.assert_array_equal(geom.get_length(np.uint64(1)), [1.0, 1.0, 1.0])
    geom.set((0, 0, 0), (2.0, 2.0, 2.0))
    np.testing.assert_array_equal(geom.get_length(np.uint64(1)), [2.0, 2.0, 2.0])
